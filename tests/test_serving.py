"""Online serving plane (paddle_tpu/serving + the read-only attach mode
in csrc/ps_service.cc and the serve QoS class in ps/rpc.py).

Layers under test, bottom-up: read-only server semantics, the serve-QoS
transport/breaker isolation, replica subscription catch-up
(snapshot → tail → digest-equal vs the primary), bounded staleness
under concurrent pushes, the feed-triggered dense-tower sync, the
frontend's micro-batching / admission control / deadlines, the cached
warm path's staleness bound, and the acceptance scenario: kill the
primary mid-serve (server-side chaos faultpoint), the replica keeps
answering, re-attaches on the promoted epoch, digests converge."""

import threading
import time

import numpy as np
# numpy lazy-loads np.testing, and ITS import runs a subprocess (SVE
# probe). Under the TSAN sweep, a fork once the cluster/lease/shipper
# threads are live deadlocks the child — import it NOW, while this is
# the only thread.
import numpy.testing  # noqa: F401
import pytest

from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import TableConfig

rpc = pytest.importorskip("paddle_tpu.ps.rpc")

pytestmark = pytest.mark.skipif(
    not rpc.rpc_available(), reason="native toolchain unavailable")

from paddle_tpu.ps import ha  # noqa: E402  (needs the native lib)
from paddle_tpu.serving import (CachedLookup, DeadlineExceeded,  # noqa: E402
                                DenseTowerPublisher, DenseTowerSync,
                                FrontendConfig, FreshnessProbe,
                                ReplicaLookup, RequestRejected,
                                ServingFrontend, ServingReplica)


def _acc(dim=4):
    return AccessorConfig(embedx_dim=dim, embedx_threshold=0.0,
                          sgd=SGDRuleConfig(initial_range=0.01))


def _cfg(dim=4):
    return TableConfig(shard_num=4, accessor_config=_acc(dim))


def _push(rng, keys, width):
    push = np.zeros((len(keys), width), np.float32)
    push[:, 1] = 1.0
    push[:, 2:] = rng.normal(0, 0.1, (len(keys), width - 2)).astype(np.float32)
    return push


def _cluster(**kw):
    kw.setdefault("num_shards", 1)
    kw.setdefault("replication", 1)
    kw.setdefault("sync", True)
    return ha.HACluster(**kw)


def _replica(cluster, shard=0, **kw):
    kw.setdefault("hb_interval", 0.05)
    kw.setdefault("hb_ttl", 0.4)
    return ServingReplica(cluster.store, cluster.job_id, shard=shard, **kw)


def _wait_digest_match(cluster, shard, serve_cli, table_id=0, timeout=10.0):
    """Poll until the replica's digest equals the shard primary's;
    returns the matching digest (assertion fail on timeout)."""
    deadline = time.monotonic() + timeout
    while True:
        prim = cluster.primary(shard)
        dg_p = cluster.digests(table_id, shard).get(prim.endpoint)
        dg_r = serve_cli.digest(table_id)[0]
        if dg_p is not None and dg_p == dg_r:
            return dg_r
        assert time.monotonic() < deadline, \
            f"replica digest {dg_r} never converged to primary {dg_p}"
        time.sleep(0.02)


# ---------------------------------------------------------------------------
# read-only attach mode + serve QoS
# ---------------------------------------------------------------------------

def test_read_only_replica_refuses_training_plane():
    with _cluster() as cluster:
        cli = cluster.client()
        cli.create_sparse_table(0, _cfg())
        rng = np.random.default_rng(0)
        keys = np.arange(64, dtype=np.uint64)
        cli.pull_sparse(0, keys)
        with _replica(cluster) as rep:
            serve = rep.client()
            serve.create_sparse_table(0, _cfg())   # bootstrap: allowed
            width = serve._dims(0)[1]
            # training data plane bounces with the read-only error
            from paddle_tpu.core.enforce import PreconditionNotMetError
            with pytest.raises(PreconditionNotMetError, match="READ-ONLY"):
                serve.push_sparse(0, keys[:4], _push(rng, keys[:4], width))
            # insert-on-miss pulls DOWNGRADE: zeros back, no phantom row
            sz0 = serve.size(0)
            out = serve.pull_sparse(
                0, np.asarray([1 << 50], np.uint64), create=True)
            assert serve.size(0) == sz0
            assert np.abs(out).sum() == 0.0
            assert rep.status()["read_only"]


def test_serve_qos_deadline_class_and_breaker_isolation():
    from paddle_tpu.core.flags import flag

    # serve conns resolve their IO deadline AND attempt budget from the
    # serve flag family — live at call time, like every pserver_* flag
    with _cluster() as cluster:
        serve_cli = cluster.client(qos="serve")
        train_cli = cluster.client()
        assert serve_cli._conns[0]._io_flag == "pserver_serve_timeout_ms"
        assert serve_cli._conns[0]._retry_flag == "pserver_serve_max_retry"
        assert int(flag("pserver_serve_max_retry")) == 1  # no retries
        assert train_cli._conns[0]._io_flag == "pserver_timeout_ms"
        assert train_cli._conns[0]._retry_flag == "pserver_max_retry"
        # breakers are per-router-instance AND serve uses its own
        # thresholds: transport failures recorded on the serve router
        # open ITS breaker only — the training client keeps calling
        ep = serve_cli._conns[0].endpoint
        srouter, trouter = serve_cli._router, train_cli._router
        assert srouter.qos == "serve"
        assert srouter.breaker(ep).failures == \
            int(flag("ps_serve_breaker_failures"))
        for _ in range(srouter.breaker(ep).failures):
            srouter.record(ep, ok=False)
        assert srouter.breaker(ep).state == ha.CircuitBreaker.OPEN
        assert trouter.breaker(ep).state == ha.CircuitBreaker.CLOSED
        assert trouter.allow(ep)


# ---------------------------------------------------------------------------
# subscription catch-up + staleness + dense feed
# ---------------------------------------------------------------------------

def test_replica_subscription_catch_up_digest_equal():
    """Late subscriber: the primary already holds rows whose oplog
    entries were consumed long ago — attach must take the snapshot path
    (catalog replay + kSaveAll/kInsertFull + rebase), then the tail,
    ending digest-equal with the primary."""
    with _cluster() as cluster:
        cli = cluster.client()
        cli.create_sparse_table(0, _cfg())
        rng = np.random.default_rng(1)
        keys = rng.integers(0, 1 << 40, 2000).astype(np.uint64)
        cli.pull_sparse(0, keys)
        width = cli._dims(0)[1]
        cli.push_sparse(0, keys, _push(rng, keys, width))
        with _replica(cluster) as rep:
            serve = rep.client()
            serve.create_sparse_table(0, _cfg())
            _wait_digest_match(cluster, 0, serve)
            # tail: a post-attach push flows through the feed (no new
            # snapshot needed) and digests stay equal after drain
            cli.push_sparse(0, keys[:100], _push(rng, keys[:100], width))
            cluster.drain()
            prim = cluster.primary(0)
            assert cluster.digests(0, 0)[prim.endpoint] == \
                serve.digest(0)[0]
            assert rep.status()["applied_seq"] > 0


def test_replica_bounded_staleness_under_concurrent_pushes():
    """Freshness SLO shape: while a writer hammers the table, a marker
    push becomes SERVABLE on the replica within the probe timeout,
    every time (freshness_failures == 0) — the push→servable metric
    SERVING.json gates at p95 ≤ 100 ms."""
    with _cluster() as cluster:
        cli = cluster.client()
        cli.create_sparse_table(0, _cfg())
        rng = np.random.default_rng(2)
        keys = np.arange(512, dtype=np.uint64)
        cli.pull_sparse(0, keys)
        width = cli._dims(0)[1]
        marker_key = np.asarray([1 << 41], np.uint64)
        cli.pull_sparse(0, marker_key)
        with _replica(cluster) as rep:
            serve = rep.client()
            serve.create_sparse_table(0, _cfg())
            _wait_digest_match(cluster, 0, serve)
            stop = threading.Event()

            def writer():
                while not stop.is_set():
                    cli.push_sparse(0, keys, _push(rng, keys, width))

            th = threading.Thread(target=writer)
            th.start()
            try:
                probe = FreshnessProbe(timeout_s=5.0)
                marker = [0.0]

                def write():
                    marker[0] += 1.0
                    mp = np.zeros((1, width), np.float32)
                    # click stat (push layout [slot, show, click, ...]):
                    # additive, so the cumulative value is >= marker the
                    # moment THIS push is applied — and it reads back
                    # directly as pull column 1
                    mp[0, 2] = marker[0]
                    cli.push_sparse(0, marker_key, mp)

                def read():
                    return serve.pull_sparse(0, marker_key,
                                             create=False)[0, 1]

                for _ in range(5):
                    probe.measure(write, read,
                                  lambda v, m=marker: v >= m[0])
            finally:
                stop.set()
                th.join()
            st = probe.stats()
            assert st["failures"] == 0, st
            assert st["p95_ms"] < 5000, st
            # the feed applied entries recently (bounded staleness)
            assert rep.status()["since_last_apply_s"] < 5.0


def test_dense_tower_feed_triggered_sync():
    """The values-only dense delta path: publisher set_dense →
    replicated apply bumps dense_version → replica watcher pulls and
    rebuilds the pytree — no export loop, no byte polling."""
    with _cluster() as cluster:
        cli = cluster.client()
        params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
                  "b": np.zeros(3, np.float32)}
        pub = DenseTowerPublisher(cli, 7, params)
        with _replica(cluster) as rep:
            got = []
            DenseTowerSync(rep, 7, pub.dim, pub.unravel,
                           sink=lambda p: got.append(p))
            pub.publish({"w": params["w"] + 1.0, "b": params["b"] + 2.0})
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if got and np.allclose(np.asarray(got[-1]["b"]), 2.0):
                    break
                time.sleep(0.01)
            assert got, "dense sync never fired"
            np.testing.assert_allclose(np.asarray(got[-1]["w"]),
                                       params["w"] + 1.0)
            np.testing.assert_allclose(np.asarray(got[-1]["b"]), 2.0)
            assert rep.status()["dense_refreshes"] >= 1
            assert rep.status()["sync_errors"] == 0


# ---------------------------------------------------------------------------
# frontend: micro-batching, shedding, deadlines
# ---------------------------------------------------------------------------

class _StubLookup:
    """Deterministic source: value row = [key, key+0.5]; counts calls
    and can inject latency (shedding tests)."""

    def __init__(self, delay_s=0.0):
        self.calls = 0
        self.keys_seen = 0
        self.delay_s = delay_s

    def lookup(self, keys):
        self.calls += 1
        self.keys_seen += len(keys)
        if self.delay_s:
            time.sleep(self.delay_s)
        k = keys.astype(np.float64)
        return np.stack([k, k + 0.5], axis=1).astype(np.float32)


def test_frontend_micro_batches_and_scatters_correctly():
    src = _StubLookup()
    with ServingFrontend(src, config=FrontendConfig(
            max_batch=16, max_delay_us=5000, queue_cap=256)) as fe:
        pending = [(i, fe.submit(np.arange(i * 8, i * 8 + 8,
                                           dtype=np.uint64),
                                 deadline_ms=5000))
                   for i in range(48)]
        for i, p in pending:
            out = p.result(10)
            assert out.shape == (8, 2)
            np.testing.assert_allclose(
                out[:, 0], np.arange(i * 8, i * 8 + 8, dtype=np.float32))
        st = fe.stats()
        assert st["served"] == 48
        # coalescing happened: far fewer lookup calls than requests
        assert src.calls <= 48 // 2, (src.calls, st)
        assert st["avg_batch"] > 1


def test_frontend_infer_receives_stacked_batch():
    src = _StubLookup()

    def infer(emb, dense):
        # [B, S, d] × [B, D] → per-request scalar
        return emb[:, :, 0].sum(axis=1) + dense[:, 0]

    with ServingFrontend(src, infer=infer, config=FrontendConfig(
            max_batch=8, max_delay_us=2000, queue_cap=64)) as fe:
        keys = np.asarray([3, 4], np.uint64)
        out = fe(keys, dense=np.asarray([10.0], np.float32),
                 deadline_ms=5000)
        assert float(out) == 3 + 4 + 10.0


def test_frontend_admission_control_sheds_under_overload():
    src = _StubLookup(delay_s=0.05)
    fe = ServingFrontend(src, config=FrontendConfig(
        max_batch=4, max_delay_us=100, queue_cap=4, retry_after_ms=7.0))
    try:
        accepted, shed = [], 0
        for _ in range(64):
            try:
                accepted.append(fe.submit(np.arange(4, dtype=np.uint64),
                                          deadline_ms=30000))
            except RequestRejected as e:
                shed += 1
                # the configured value is the FLOOR; the quoted hint
                # scales with the measured backlog/drain rate (ISSUE 15
                # satellite — test_serving_fleet pins the derivation)
                assert e.retry_after_ms >= 7.0
        assert shed > 0, "overload never shed"
        assert fe.stats()["shed"] == shed
        # everything ADMITTED completes (bounded queue drains; nothing
        # is silently dropped)
        for p in accepted:
            assert p.result(30).shape == (4, 2)
    finally:
        fe.stop()
    # post-stop submits are refused, queued work was failed loudly
    with pytest.raises(RequestRejected):
        fe.submit(np.arange(4, dtype=np.uint64))


def test_frontend_priority_classes_shed_and_serve_order():
    """Two concurrent priority classes on one frontend: batch-class
    floods fill (and shed) ONLY the batch queue — serve admission stays
    open and sheds independently — and when both classes are queued the
    worker serves every serve-class request before any batch-class one
    (the multi-tenant cloud's serve-plane ordering guarantee)."""

    class _GatedLookup(_StubLookup):
        def __init__(self):
            super().__init__()
            self.gate = threading.Event()

        def lookup(self, keys):
            self.gate.wait(30)
            return super().lookup(keys)

    src = _GatedLookup()
    fe = ServingFrontend(src, config=FrontendConfig(
        max_batch=1, max_delay_us=100, queue_cap=2, retry_after_ms=5.0),
        idle_pop_s=0.005)
    try:
        # occupy the worker so admitted requests stay queued
        plug = fe.submit(np.arange(2, dtype=np.uint64), deadline_ms=60000)
        time.sleep(0.05)

        # batch flood: cap admits 2, the 3rd sheds — as shed_batch
        order = []
        batch_p = []
        for i in range(2):
            p = fe.submit(np.arange(2, dtype=np.uint64),
                          deadline_ms=60000, priority="batch")
            p.add_done_callback(lambda: order.append("batch"))
            batch_p.append(p)
        with pytest.raises(RequestRejected) as ei:
            fe.submit(np.arange(2, dtype=np.uint64),
                      deadline_ms=60000, priority="batch")
        assert ei.value.retry_after_ms >= 5.0
        st = fe.stats()
        assert st["shed_batch"] == 1 and st["shed"] == 0, \
            "batch flood must shed batch-class only"

        # serve admission is still open despite the full batch queue —
        # submitted AFTER batch, they must complete FIRST
        serve_p = []
        for i in range(2):
            p = fe.submit(np.arange(2, dtype=np.uint64),
                          deadline_ms=60000, priority="serve")
            p.add_done_callback(lambda: order.append("serve"))
            serve_p.append(p)
        # serve overload sheds under its own counter
        with pytest.raises(RequestRejected):
            fe.submit(np.arange(2, dtype=np.uint64),
                      deadline_ms=60000, priority="serve")
        st = fe.stats()
        assert st["shed"] == 1 and st["shed_batch"] == 1
        assert st["accepted"] == 3 and st["accepted_batch"] == 2

        src.gate.set()
        for p in serve_p + batch_p:
            p.result(30)
        plug.result(30)
        assert order == ["serve", "serve", "batch", "batch"], order
        assert fe.stats()["served"] == 5
    finally:
        fe.stop()


def test_frontend_deadline_dropped_before_lookup():
    src = _StubLookup(delay_s=0.03)
    with ServingFrontend(src, config=FrontendConfig(
            max_batch=2, max_delay_us=100, queue_cap=64)) as fe:
        # saturate the worker so later submits sit in the queue past
        # their deadline
        slow = [fe.submit(np.arange(2, dtype=np.uint64), deadline_ms=30000)
                for _ in range(6)]
        doomed = fe.submit(np.arange(2, dtype=np.uint64), deadline_ms=1)
        with pytest.raises(DeadlineExceeded):
            doomed.result(30)
        for p in slow:
            p.result(30)
        st = fe.stats()
        assert st["deadline_dropped"] >= 1
        # the doomed request's keys were never looked up
        assert src.keys_seen == 2 * 6


# ---------------------------------------------------------------------------
# warm path: cached lookup over the replica
# ---------------------------------------------------------------------------

def test_cached_lookup_warm_zero_rpc_and_staleness_bound():
    from paddle_tpu.ps.hot_tier import HotEmbeddingTier, HotTierConfig

    with _cluster() as cluster:
        cli = cluster.client()
        cli.create_sparse_table(0, _cfg())
        rng = np.random.default_rng(3)
        keys = np.arange(256, dtype=np.uint64)
        cli.pull_sparse(0, keys)
        width = cli._dims(0)[1]
        cli.push_sparse(0, keys, _push(rng, keys, width))
        with _replica(cluster) as rep:
            serve = rep.client()
            view = rep.serve_view(0, _cfg(), client=serve)
            _wait_digest_match(cluster, 0, serve)
            tier = HotEmbeddingTier(view, HotTierConfig(
                capacity=1 << 10, create_on_miss=False))
            cl = CachedLookup(tier, replica=rep, freshness_budget_s=0.03)
            v0 = cl.lookup(keys)
            assert v0.shape == (len(keys), 1 + 4)
            # WARM: repeated lookups perform zero RPCs of any kind
            serve.reset_op_counts()
            v1 = cl.lookup(keys)
            assert serve.reset_op_counts() == {}
            np.testing.assert_array_equal(v0, v1)
            # idle feed: rows stay resident past the budget (no churn)
            time.sleep(0.05)
            serve.reset_op_counts()
            cl.lookup(keys)
            assert serve.reset_op_counts() == {}
            # a push that ADVANCES the feed makes warm rows refresh
            # once their budget expires — bounded staleness
            cli.push_sparse(0, keys[:16], _push(rng, keys[:16], width))
            cluster.drain()
            time.sleep(0.05)  # budget expiry
            v2 = cl.lookup(keys[:16])
            assert not np.allclose(v1[:16], v2)
            assert cl.refreshes >= 16
            # the refreshed values match the replica's table exactly
            direct = ReplicaLookup(serve, 0).lookup(keys[:16])
            np.testing.assert_array_equal(v2[:, 0], direct[:, 2])


# ---------------------------------------------------------------------------
# acceptance: serve through failover (chaos-gated)
# ---------------------------------------------------------------------------

def test_serve_through_failover_reattach_and_converge():
    """Kill the primary mid-serve via the server-side chaos faultpoint
    (armed kill-shard on the Nth push — deterministic death under
    traffic). The replica must keep answering throughout (stale but
    bounded), re-attach once the coordinator promotes the backup, and
    end digest-identical to the new primary."""
    with _cluster(replication=2) as cluster:
        cli = cluster.client()
        cli.create_sparse_table(0, _cfg())
        rng = np.random.default_rng(4)
        keys = np.arange(400, dtype=np.uint64)
        cli.pull_sparse(0, keys)
        width = cli._dims(0)[1]
        cli.push_sparse(0, keys, _push(rng, keys, width))
        with _replica(cluster) as rep:
            serve = rep.client()
            serve.create_sparse_table(0, _cfg())
            _wait_digest_match(cluster, 0, serve)
            epoch0 = rep.status()["epoch"]
            prim = cluster.primary(0)
            # chaos: the 3rd push from now kills the primary mid-run
            prim.server.arm_fault("kill-shard", cmd=rpc._PUSH_SPARSE,
                                  after=3)
            serve_errors = 0
            promoted = []

            def reader():
                # serve continuously through the death+promotion window
                nonlocal serve_errors
                while not promoted:
                    try:
                        out = serve.pull_sparse(0, keys[:32], create=False)
                        assert out.shape == (32, cli._dims(0)[0])
                    except Exception:  # noqa: BLE001 — counted, asserted 0
                        serve_errors += 1
                    time.sleep(0.005)

            th = threading.Thread(target=reader)
            th.start()
            try:
                # pushes ride the router: the one that hits the armed
                # fault replays against the promoted backup
                for _ in range(6):
                    cli.push_sparse(0, keys[:64],
                                    _push(rng, keys[:64], width))
                    time.sleep(0.02)
                new_prim = cluster.wait_promoted(0, prim.endpoint)
            finally:
                promoted.append(True)
                th.join()
            assert serve_errors == 0, \
                f"{serve_errors} serve reads failed during failover"
            # more traffic through the new primary, then convergence
            cli.push_sparse(0, keys, _push(rng, keys, width))
            deadline = time.monotonic() + 15
            while True:
                dg = cluster.digests(0, 0).get(new_prim)
                if dg is not None and dg == serve.digest(0)[0]:
                    break
                assert time.monotonic() < deadline, "never reconverged"
                time.sleep(0.05)
            st = rep.status()
            assert st["epoch"] > epoch0, st    # re-attached on new epoch
            assert st["epoch_changes"] >= 1, st
