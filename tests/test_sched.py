"""graftsched: deterministic concurrency explorer + protocol harnesses.

Three layers under test:

1. the explorer itself (paddle_tpu/testing/sched.py) on TOY protocols
   with known-good and known-bad interleavings — seed determinism,
   preemption-bounded exhaustion, deadlock / lost-wakeup / lock-order
   detection, shrinking;
2. the core.sync shim contract: zero-interposition pass-throughs when
   no scheduler is installed;
3. the REAL control-plane harnesses (tools/sched/models.py): the
   checkpoint-gate × reshard-cutover × failover three-way, the
   ServingFleet drain-vs-tick race, and the JobCheckpointManager
   writer/stop protocol — including PINNED minimized schedules for the
   two bugs the explorer found (the un-suspended coordinator's torn
   cut; the fleet tick re-admitting a fully-drained member), replayed
   against the fixed code.
"""

import os
import queue
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools", "sched"))

import models  # noqa: E402
from paddle_tpu.core import sync as _sync  # noqa: E402
from paddle_tpu.testing.sched import (  # noqa: E402
    Explorer, Guided, RandomWalk, Scheduler, ScheduleFailure,
    load_lock_order)


# ---------------------------------------------------------------------------
# shim pass-through (production must pay nothing)
# ---------------------------------------------------------------------------

def test_shim_passthrough_returns_raw_primitives():
    assert _sync.current_scheduler() is None
    assert isinstance(_sync.Lock(), type(threading.Lock()))
    assert isinstance(_sync.RLock(), type(threading.RLock()))
    assert isinstance(_sync.Condition(), threading.Condition)
    assert isinstance(_sync.Event(), threading.Event)
    assert isinstance(_sync.Semaphore(2), threading.Semaphore)
    assert isinstance(_sync.Queue(maxsize=3), queue.Queue)
    t = _sync.Thread(target=lambda: None, name="smoke")
    assert isinstance(t, threading.Thread)
    t.start()
    t.join()


# ---------------------------------------------------------------------------
# toy protocols
# ---------------------------------------------------------------------------

def _abba_model(sched):
    a = _sync.Lock(name="a_mu")
    b = _sync.Lock(name="b_mu")

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    sched.spawn(t1, "t1")
    sched.spawn(t2, "t2")


def test_random_walk_finds_abba_deadlock_and_seed_replays():
    ex = Explorer(_abba_model)
    f = ex.explore_random(200, base_seed=7)
    assert f is not None and f.kind == "deadlock"
    assert f.seed is not None
    # the printed seed alone reproduces the identical schedule
    s1 = ex.replay_seed(f.seed)
    s2 = ex.replay_seed(f.seed)
    assert s1.failure is not None and s1.failure.kind == "deadlock"
    assert s1.failure.choices == s2.failure.choices


def test_dfs_finds_abba_deadlock_and_shrinks():
    ex = Explorer(_abba_model)
    f, exhausted = ex.explore_dfs(bound=2)
    assert f is not None and f.kind == "deadlock"
    small = ex.shrink(f)
    assert small.kind == "deadlock"
    assert len(small.choices) <= 3
    # the minimized schedule replays to the same failure
    again = ex.replay_choices(small.choices)
    assert again.failure is not None and again.failure.kind == "deadlock"


def test_dfs_exhausts_clean_protocol():
    def clean(sched):
        mu = _sync.Lock(name="mu")
        box = []

        def worker(i):
            with mu:
                box.append(i)

        for i in range(2):
            sched.spawn(lambda i=i: worker(i), f"w{i}")
        sched.on_finish(lambda: sched.check(
            sorted(box) == [0, 1], "lost increment"))

    ex = Explorer(clean)
    f, exhausted = ex.explore_dfs(bound=2)
    assert f is None
    assert exhausted
    assert ex.schedules_run > 1


def test_lost_wakeup_detected():
    def lossy(sched):
        mu = _sync.Lock(name="mu")
        cv = _sync.Condition(mu, name="cv")
        state = {"ready": False}

        def waiter():
            with mu:
                while not state["ready"]:
                    cv.wait()

        def setter():
            with mu:
                state["ready"] = True
                # BUG: no cv.notify() — a waiter parked before the
                # flag flips never wakes

        sched.spawn(waiter, "waiter")
        sched.spawn(setter, "setter")

    ex = Explorer(lossy)
    f, _ = ex.explore_dfs(bound=2)
    assert f is not None
    assert f.kind == "lost-wakeup"


def test_dynamic_lock_order_leaf_violation():
    decls = ({}, {"leaf_mu"})

    def nests(sched):
        leaf = _sync.Lock(name="leaf_mu")
        other = _sync.Lock(name="other_mu")

        def t():
            with leaf:
                with other:
                    pass

        sched.spawn(t, "t")

    ex = Explorer(nests, order_decls=decls)
    f, _ = ex.explore_dfs(bound=0)
    assert f is not None and f.kind == "lock-order"
    assert "LEAF" in f.message


def test_dynamic_lock_order_inversion():
    decls = ({"outer_mu": {"inner_mu"}, "inner_mu": set()}, set())

    def inverted(sched):
        outer = _sync.Lock(name="outer_mu")
        inner = _sync.Lock(name="inner_mu")

        def t():
            with inner:
                with outer:   # declared outer_mu < inner_mu
                    pass

        sched.spawn(t, "t")

    ex = Explorer(inverted, order_decls=decls)
    f, _ = ex.explore_dfs(bound=0)
    assert f is not None and f.kind == "lock-order"


# ---------------------------------------------------------------------------
# the three-way harness: checkpoint gate × reshard cutover × failover
# ---------------------------------------------------------------------------

_DECLS = load_lock_order(
    [os.path.join(REPO, f) for f in models.DECL_FILES])

#: the bug the explorer found in the PRE-FIX CheckpointGate (no
#: coordinator suspension): the failover promotes mid-capture, the
#: capture re-resolves routing and streams its second table from the
#: UNPAUSED backup — a torn cut. Four choices, shrunk by the explorer.
TORN_CUT_SCHEDULE = ["gate", "gate", "gate", "failover"]


def test_three_way_prefix_bug_found_and_pins():
    # knob OFF reproduces the pre-fix CheckpointGate
    ex = Explorer(models.three_way_model(gate_suspends=False,
                                         with_writer=False),
                  order_decls=_DECLS)
    f, _ = ex.explore_dfs(bound=2, max_schedules=5000)
    assert f is not None and f.kind == "invariant"
    assert "torn cut" in f.message
    small = ex.shrink(f)
    assert len(small.choices) <= len(TORN_CUT_SCHEDULE)
    # the pinned minimized schedule still reproduces it
    pinned = ex.replay_choices(TORN_CUT_SCHEDULE)
    assert pinned.failure is not None
    assert "torn cut" in pinned.failure.message


def test_three_way_naive_suspend_clobbers_routing():
    # suspending with a bare Event (pre-fix resume semantics): a gate
    # overlapping a reshard cutover has the inner resume un-suspend
    # the outer holder — the failover scan publishes a stale doc over
    # the flipped epoch
    ex = Explorer(models.three_way_model(depth_counted=False,
                                         with_writer=False),
                  order_decls=_DECLS)
    f, _ = ex.explore_dfs(bound=2, max_schedules=20000)
    assert f is not None and f.kind == "invariant"
    assert "clobber" in f.message


def test_three_way_fixed_protocol_pb2_exhausts_clean():
    # the acceptance sweep: the FULL preemption-bound-2 schedule space
    # of the fixed protocol, exhausted — not sampled
    ex = Explorer(models.three_way_model(with_writer=False),
                  order_decls=_DECLS)
    f, exhausted = ex.explore_dfs(bound=2, max_schedules=50000)
    assert f is None, f and f.format()
    assert exhausted
    assert ex.schedules_run > 1000

    # pinned bug schedules replay CLEAN against the fixed protocol
    pinned = ex.replay_choices(TORN_CUT_SCHEDULE)
    assert pinned.failure is None


def test_three_way_random_walk_with_writer_clean():
    ex = Explorer(models.three_way_model(), order_decls=_DECLS)
    f = ex.explore_random(400, base_seed=20260807)
    assert f is None, f and f.format()


# ---------------------------------------------------------------------------
# ServingFleet drain vs. watcher-tick harness
# ---------------------------------------------------------------------------

#: the bug the explorer found in ServingFleet.tick(): a drain that ran
#: to COMPLETION while tick was parked inside router.attach left
#: `_draining` empty, the raced re-check saw nothing, and a stopped
#: non-member stayed routed. 34 choices as found (unshrunk — the window
#: needs the whole drain inside it).
FLEET_READMIT_SCHEDULE = (
    ["drain"] * 3 + ["tick"] * 9 + ["drain"] * 10 + ["tick"] * 12)


def test_fleet_drain_tick_pb2_exhausts_clean():
    ex = Explorer(models.fleet_drain_tick_model(), order_decls=_DECLS)
    f, exhausted = ex.explore_dfs(bound=2, max_schedules=20000)
    assert f is None, f and f.format()
    assert exhausted
    # the schedule that broke the pre-fix raced re-check replays clean
    pinned = ex.replay_choices(FLEET_READMIT_SCHEDULE)
    assert pinned.failure is None, pinned.failure


# ---------------------------------------------------------------------------
# cold-tier two-phase compactor harness (csrc/ssd_table.cc miniature)
# ---------------------------------------------------------------------------

#: the bug class the phase-B reconcile exists for: a naive publisher
#: installing the phase-A snapshot verbatim loses the push-path rewrite
#: that landed during the unlocked copy. Five choices, explorer-shrunk.
SSD_STALE_PUBLISH_SCHEDULE = ["bg", "bg", "bg", "bg", "save"]


def test_ssd_compact_naive_publisher_found_and_pins():
    ex = Explorer(models.ssd_compact_model(two_phase=False,
                                           with_shrink=False),
                  order_decls=_DECLS)
    f, _ = ex.explore_dfs(bound=2, max_schedules=5000)
    assert f is not None and f.kind == "invariant"
    # both manifestations of the missing reconcile are legal first finds
    assert any(s in f.message for s in
               ("lost", "BOTH tiers", "resurrected"))
    pinned = ex.replay_choices(SSD_STALE_PUBLISH_SCHEDULE)
    assert pinned.failure is not None
    assert "rewrite lost" in pinned.failure.message


def test_ssd_compact_fixed_pb1_exhausts_clean():
    # pb-1 here for test-suite speed; ci.sh sched runs the pb-2 space
    # (~100k schedules) to exhaustion
    ex = Explorer(models.ssd_compact_model(with_shrink=False),
                  order_decls=_DECLS)
    f, exhausted = ex.explore_dfs(bound=1, max_schedules=10000)
    assert f is None, f and f.format()
    assert exhausted
    # the stale-publish schedule replays CLEAN against phase-B reconcile
    pinned = ex.replay_choices(SSD_STALE_PUBLISH_SCHEDULE)
    assert pinned.failure is None, pinned.failure


def test_ssd_compact_random_walk_with_shrink_clean():
    ex = Explorer(models.ssd_compact_model(), order_decls=_DECLS)
    f = ex.explore_random(300, base_seed=20260807)
    assert f is None, f and f.format()


def test_ssd_csrc_lock_decls_loaded():
    # load_lock_order dispatches to the csrc `//` grammar for .cc files:
    # the compactor's declaration must be in the merged order
    edges, leaves = _DECLS
    assert "bg_mu" in edges.get("disk_mu", set())
    assert "disk_mu" in edges.get("shard_mu", set())
    assert "mem_save_mu" in edges.get("ssd_save_mu", set())
    assert "io_mu" in leaves


# ---------------------------------------------------------------------------
# JobCheckpointManager writer vs. save/stop harness
# ---------------------------------------------------------------------------

def test_ckpt_writer_pb1_exhausts_clean(tmp_path):
    ex = Explorer(models.ckpt_writer_model(root=str(tmp_path)),
                  order_decls=_DECLS)
    f, exhausted = ex.explore_dfs(bound=1, max_schedules=10000)
    assert f is None, f and f.format()
    assert exhausted


# ---------------------------------------------------------------------------
# dynamic lock-order observations vs. static declarations
# ---------------------------------------------------------------------------

def _sched_run():
    """Load tools/sched/run.py under a unique module name: a bare
    `import run` collides with tools/lint/run.py when test_lint.py ran
    first in the same session (both dirs sit on sys.path and
    sys.modules caches whichever `run` won)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "paddle_sched_run", os.path.join(REPO, "tools", "sched", "run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_observed_edges_agree_with_declarations():
    sched_run = _sched_run()
    ex = Explorer(models.three_way_model(), order_decls=_DECLS)
    ex.explore_random(200, base_seed=3)
    assert ex.observed_edges, "harness observed no lock nesting at all"
    violations = sched_run.cross_check(ex.observed_edges, _DECLS)
    assert violations == [], violations


def test_cross_check_catches_leaf_and_inversion():
    sched_run = _sched_run()
    decls = ({"a_mu": {"b_mu"}, "b_mu": set()}, {"leaf_mu"})
    bad = sched_run.cross_check({("leaf_mu", "x_mu"), ("b_mu", "a_mu")},
                                decls)
    assert len(bad) == 2
    assert any("LEAF" in v for v in bad)
    assert any("inverts" in v for v in bad)


def test_load_lock_order_matches_py_locks_grammar():
    edges, leaves = _DECLS
    # ha.py declares both of these (the gate fix added _susp_mu)
    assert "_mu" in edges.get("control_mu", set())
    assert {"_mu", "_step_mu", "_susp_mu"} <= leaves


# ---------------------------------------------------------------------------
# the CLI gate
# ---------------------------------------------------------------------------

def test_sched_cli_gate_fleet_harness(tmp_path):
    import json
    import subprocess
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sched", "run.py"),
         "--harness", "fleet", "--seed", "11", "--json",
         str(tmp_path / "s.json")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    summary = json.loads((tmp_path / "s.json").read_text())
    assert summary["ok"]
    h = summary["harnesses"]["fleet"]
    assert h["dfs"]["exhausted"]
    assert h["random"]["base_seed"] == 11
    # the fleet protocol holds one lock at a time — no nested NAMED
    # pairs to observe — but the cross-checked field must be present
    assert "observed_edges" in h


def test_sched_cli_replay_seed():
    import subprocess
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sched", "run.py"),
         "--replay", "three_way", "--seed", "123456"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "ran clean" in out.stdout


# ---------------------------------------------------------------------------
# declarative reconciler: proposers × serialized actuator × failover
# ---------------------------------------------------------------------------

def test_reconciler_unserialized_actuation_found():
    # knob OFF reproduces the pre-reconciler world: two control loops
    # each diffing observed-vs-desired and actuating directly, no
    # actuator mutex between diff and apply — the second loop admits a
    # transition planned against a topology the first already changed
    ex = Explorer(models.reconciler_model(serialized=False,
                                          with_np_proposer=False),
                  order_decls=_DECLS)
    f, _ = ex.explore_dfs(bound=2, max_schedules=20000)
    assert f is not None and f.kind == "invariant"
    assert "stale transition" in f.message
    small = ex.shrink(f)
    assert small.kind == "invariant"


def test_reconciler_fixed_protocol_pb2_exhausts_clean():
    # the acceptance sweep: one serialized actuator — the whole pb-2
    # schedule space of proposer-write × actuator-diff × lease-expiry
    # interleavings, exhausted, with the dynamic lock-order checker
    # validating reconcile.py/spec.py's declarations
    ex = Explorer(models.reconciler_model(with_np_proposer=False),
                  order_decls=_DECLS)
    f, exhausted = ex.explore_dfs(bound=2, max_schedules=50000)
    assert f is None, f and f.format()
    assert exhausted
    assert ex.schedules_run > 1000


def test_reconciler_random_walk_two_proposers_clean():
    ex = Explorer(models.reconciler_model(), order_decls=_DECLS)
    f = ex.explore_random(400, base_seed=20260807)
    assert f is None, f and f.format()
