"""Multi-HOST sharded embedding serving: two localhost jax.distributed
processes x 4 virtual CPU devices form one global 8-device "ps" mesh and
serve row-sharded cache pull/push across the process boundary — the
DCN-spanning version of the HeterComm serving path (SURVEY §2.4 →TPU:
intra-host hops ride ICI, cross-host hops ride DCN, both inside the same
compiled program). Each rank verifies its addressable shards numerically
match the single-device reference (atol 1e-5).
"""

import textwrap

import pytest

from conftest import launch_two_workers

_WORKER = textwrap.dedent("""
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.ps.embedding_cache import (CacheConfig, cache_pull,
                                               cache_push)
    from paddle_tpu.ps.sharded_cache import (routed_cache_pull,
                                             routed_cache_push,
                                             sharded_cache_pull,
                                             sharded_cache_push)

    # identical host-side state on every rank (same seed)
    Cap, dim, B = 256, 4, 16
    rng = np.random.default_rng(0)
    host = {
        "show": rng.uniform(0, 5, Cap).astype(np.float32),
        "click": rng.uniform(0, 2, Cap).astype(np.float32),
        "embed_w": rng.normal(size=(Cap, 1)).astype(np.float32),
        "embed_state": rng.uniform(0, 1, (Cap, 1)).astype(np.float32),
        "embedx_w": rng.normal(size=(Cap, dim)).astype(np.float32),
        "embedx_state": rng.uniform(0, 1, (Cap, 1)).astype(np.float32),
        "has_embedx": (rng.random(Cap) < 0.5).astype(np.float32),
    }
    rows = rng.integers(0, Cap, B).astype(np.int32)
    grads = rng.normal(size=(B, 1 + dim)).astype(np.float32)
    shows = np.ones(B, np.float32)
    clicks = (rng.random(B) < 0.4).astype(np.float32)
    cfg = CacheConfig(capacity=Cap, embedx_dim=dim, embedx_threshold=1.0)

    mesh = Mesh(np.array(jax.devices()), ("ps",))

    def to_global(a):
        sh = NamedSharding(mesh, P(*(["ps"] + [None] * (a.ndim - 1))))
        return jax.make_array_from_callback(a.shape, sh, lambda i: a[i])

    state_g = {k: to_global(v) for k, v in host.items()}
    rows_g, grads_g, shows_g, clicks_g = (to_global(x) for x in
                                          (rows, grads, shows, clicks))

    pull = jax.jit(shard_map(
        lambda st, r: sharded_cache_pull(st, r, "ps"),
        mesh=mesh, in_specs=(P("ps"), P("ps")), out_specs=P("ps")))
    out = pull(state_g, rows_g)
    ref = np.asarray(cache_pull(
        {k: jnp.asarray(v) for k, v in host.items()}, jnp.asarray(rows)))
    for shard in out.addressable_shards:
        np.testing.assert_allclose(np.asarray(shard.data),
                                   ref[shard.index], atol=1e-6)

    push = jax.jit(shard_map(
        lambda st, r, g, s, c: sharded_cache_push(st, r, g, s, c, cfg, "ps"),
        mesh=mesh, in_specs=(P("ps"),) * 5, out_specs=P("ps")))
    new_g = push(state_g, rows_g, grads_g, shows_g, clicks_g)
    new_ref = cache_push(
        {k: jnp.asarray(v) for k, v in host.items()}, jnp.asarray(rows),
        jnp.asarray(grads), jnp.asarray(shows), jnp.asarray(clicks), cfg)
    for k in new_ref:
        refk = np.asarray(new_ref[k])
        for shard in new_g[k].addressable_shards:
            np.testing.assert_allclose(np.asarray(shard.data),
                                       refk[shard.index], atol=1e-5,
                                       err_msg=k)

    # key-routed all-to-all serving: the split_input_to_shard path, with
    # the inter-host hop riding DCN inside the same compiled program
    pull_r = jax.jit(shard_map(
        lambda st, r: routed_cache_pull(st, r, "ps"),
        mesh=mesh, in_specs=(P("ps"), P("ps")), out_specs=(P("ps"), P()),
        check_vma=False))
    out_r, ov = pull_r(state_g, rows_g)
    assert int(ov) == 0
    for shard in out_r.addressable_shards:
        np.testing.assert_allclose(np.asarray(shard.data),
                                   ref[shard.index], atol=1e-6)
    push_r = jax.jit(shard_map(
        lambda st, r, g, s, c: routed_cache_push(
            st, r, g, s, c, cfg, "ps", 2.0, False),
        mesh=mesh, in_specs=(P("ps"),) * 5, out_specs=(P("ps"), P()),
        check_vma=False))
    new_r, ov = push_r(state_g, rows_g, grads_g, shows_g, clicks_g)
    assert int(ov) == 0
    for k in new_ref:
        refk = np.asarray(new_ref[k])
        for shard in new_r[k].addressable_shards:
            np.testing.assert_allclose(np.asarray(shard.data),
                                       refk[shard.index], atol=1e-5,
                                       err_msg="routed " + k)
    print("WORKER_OK", rank, flush=True)
""")


@pytest.mark.slow
def test_two_process_sharded_cache(tmp_path):
    launch_two_workers(_WORKER, tmp_path)
