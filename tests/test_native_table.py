"""Native C++ sparse-table engine (csrc/sparse_table.cc) vs the Python
shard backend: identical accessor/SGD semantics (SURVEY Appendix A —
ctr_accessor.cc, sparse_sgd_rule.cc, memory_sparse_table.cc behaviors,
rebuilt, not translated)."""

import numpy as np
import pytest

from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.native import native_available
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import MemorySparseTable, TableConfig

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable"
)


def _pair(embed_rule="adagrad", embedx_rule="adagrad", **acc_kw):
    """Same-config native + python tables; initial_range=0 removes init
    randomness so trajectories must match exactly."""
    acc = AccessorConfig(
        embed_sgd_rule=embed_rule,
        embedx_sgd_rule=embedx_rule,
        sgd=SGDRuleConfig(initial_range=0.0),
        **acc_kw,
    )
    tn = MemorySparseTable(TableConfig(shard_num=4, accessor_config=acc, backend="native"))
    tp = MemorySparseTable(TableConfig(shard_num=4, accessor_config=acc, backend="python"))
    assert tn.backend == "native" and tp.backend == "python"
    return tn, tp


def _run_pushes(tables, rng, rounds=4, n=200, key_space=3000):
    push_dim = tables[0].accessor.push_dim
    for _ in range(rounds):
        k = rng.integers(1, key_space, n).astype(np.uint64)
        push = np.zeros((n, push_dim), np.float32)
        push[:, 0] = k % 26
        push[:, 1] = rng.uniform(1, 3, n)
        push[:, 2] = rng.uniform(0, 1, n)
        push[:, 3:] = rng.normal(0, 0.1, (n, push_dim - 3)).astype(np.float32)
        for t in tables:
            t.push_sparse(k, push)


@pytest.mark.parametrize("rule", ["naive", "adagrad", "std_adagrad", "adam"])
def test_pull_push_parity(rule):
    tn, tp = _pair(embed_rule=rule, embedx_rule=rule)
    rng = np.random.default_rng(7)
    keys = rng.integers(1, 3000, 400).astype(np.uint64)
    slots = (keys % 26).astype(np.int32)
    np.testing.assert_allclose(
        tn.pull_sparse(keys, slots), tp.pull_sparse(keys, slots))
    _run_pushes((tn, tp), rng)
    assert tn.size() == tp.size()
    np.testing.assert_allclose(
        tn.pull_sparse(keys, slots, create=False),
        tp.pull_sparse(keys, slots, create=False), atol=1e-5)


def test_missing_key_pull_zero_without_create():
    tn, _ = _pair()
    out = tn.pull_sparse(np.array([42], np.uint64), create=False)
    assert (out == 0).all() and tn.size() == 0


def test_duplicate_keys_merged_before_update():
    tn, tp = _pair()
    k = np.array([5, 5, 9, 5], np.uint64)
    push = np.zeros((4, tn.accessor.push_dim), np.float32)
    push[:, 0] = [1, 1, 2, 1]
    push[:, 1] = 1.0
    push[:, 3] = [0.1, 0.2, 0.3, 0.4]
    tn.push_sparse(k, push)
    tp.push_sparse(k, push)
    q = np.array([5, 9], np.uint64)
    np.testing.assert_allclose(
        tn.pull_sparse(q, create=False), tp.pull_sparse(q, create=False),
        atol=1e-6)
    assert tn.size() == 2


def test_shrink_parity_and_row_recycle():
    tn, tp = _pair()
    rng = np.random.default_rng(3)
    _run_pushes((tn, tp), rng, rounds=3)
    assert tn.shrink() == tp.shrink()
    assert tn.size() == tp.size()
    # recycled rows must come back clean
    _run_pushes((tn, tp), rng, rounds=2)
    keys = rng.integers(1, 3000, 300).astype(np.uint64)
    np.testing.assert_allclose(
        tn.pull_sparse(keys, create=False), tp.pull_sparse(keys, create=False),
        atol=1e-5)


@pytest.mark.parametrize("mode", [0, 1, 2, 3])
def test_save_modes_parity(tmp_path, mode):
    tn, tp = _pair()
    rng = np.random.default_rng(11)
    _run_pushes((tn, tp), rng)
    dn, dp = tmp_path / "native", tmp_path / "python"
    assert tn.save(str(dn), mode) == tp.save(str(dp), mode)
    # round-trip: python-written files load into a native table
    t2 = MemorySparseTable(TableConfig(
        shard_num=4, backend="native",
        accessor_config=AccessorConfig(sgd=SGDRuleConfig(initial_range=0.0))))
    t2.load(str(dp))
    keys = rng.integers(1, 3000, 200).astype(np.uint64)
    got = t2.pull_sparse(keys, create=False)
    want = tp.pull_sparse(keys, create=False)
    if mode in (1, 2):
        # delta/base saves filter rows — loaded table holds a subset;
        # every row it does hold must match
        present = (got != 0).any(axis=1)
        np.testing.assert_allclose(got[present], want[present], atol=1e-5)
    else:
        np.testing.assert_allclose(got, want, atol=1e-5)


def test_sparse_accessor_pull_layout():
    acc = AccessorConfig(sgd=SGDRuleConfig(initial_range=0.0))
    tn = MemorySparseTable(TableConfig(
        shard_num=2, accessor="sparse", accessor_config=acc, backend="native"))
    tp = MemorySparseTable(TableConfig(
        shard_num=2, accessor="sparse", accessor_config=acc, backend="python"))
    assert tn.accessor.pull_dim == 1 + acc.embedx_dim
    rng = np.random.default_rng(5)
    _run_pushes((tn, tp), rng, rounds=2)
    keys = rng.integers(1, 3000, 100).astype(np.uint64)
    np.testing.assert_allclose(
        tn.pull_sparse(keys, create=False), tp.pull_sparse(keys, create=False),
        atol=1e-5)


def test_dedup_u64_matches_np_unique():
    from paddle_tpu.ps.native import dedup_u64

    rng = np.random.default_rng(11)
    for n, hi in [(0, 1), (1, 1), (257, 40), (50_000, 900), (200_000, 1 << 40)]:
        keys = rng.integers(0, hi, size=n).astype(np.uint64)
        got = dedup_u64(keys)
        want = np.unique(keys)
        assert got.shape == want.shape
        np.testing.assert_array_equal(np.sort(got), want)
    # deterministic order across calls
    keys = rng.integers(0, 1000, size=100_000).astype(np.uint64)
    np.testing.assert_array_equal(dedup_u64(keys), dedup_u64(keys))
