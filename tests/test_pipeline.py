"""Pipeline parallel: compiled schedule must match the serial model
(reference pipeline tests compare PP loss to non-PP loss)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.core import mesh as mesh_mod
from paddle_tpu.executor import Trainer
from paddle_tpu.parallel.pipeline import LayerDesc, PipelineLayer, PipelineTrainer


class Block(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc = nn.Linear(d, d)

    def forward(self, x):
        return jax.nn.relu(self.fc(x)) + x


def build(seed, d=8, stages=4):
    pt.seed(seed)
    return PipelineLayer(
        [LayerDesc(Block, d) for _ in range(stages)],
        embed=nn.Linear(4, d),
        head=nn.Linear(d, 3),
    )


def test_pipeline_forward_matches_serial():
    model = build(0)
    mesh = mesh_mod.make_mesh({"dp": 2, "pp": 4})
    x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    y = np.zeros((8,), np.int32)

    serial_out = model(jnp.asarray(x))

    pl = PipelineTrainer(
        model, optimizer.SGD(0.0), nn.functional.cross_entropy, mesh, num_micro=4
    )
    # one zero-lr step just to exercise; then compare loss vs serial loss
    loss = float(pl.train_step(jnp.asarray(x), jnp.asarray(y)))
    serial_loss = float(nn.functional.cross_entropy(serial_out, jnp.asarray(y)))
    np.testing.assert_allclose(loss, serial_loss, rtol=1e-4)


def test_pipeline_training_matches_serial():
    x = np.random.default_rng(1).normal(size=(8, 4)).astype(np.float32)
    y = (np.random.default_rng(2).integers(0, 3, 8)).astype(np.int32)
    mesh = mesh_mod.make_mesh({"dp": 1, "pp": 4, "mp": 2})

    pl = PipelineTrainer(
        build(0), optimizer.SGD(0.2), nn.functional.cross_entropy, mesh, num_micro=4
    )
    serial_model = build(0)
    serial = Trainer(serial_model, optimizer.SGD(0.2), _micro_mean_loss)

    for i in range(6):
        lp = float(pl.train_step(jnp.asarray(x), jnp.asarray(y)))
        ls = float(serial.train_step(jnp.asarray(x), jnp.asarray(y)))
        np.testing.assert_allclose(lp, ls, rtol=1e-3, atol=1e-5)


def _micro_mean_loss(out, y):
    # serial equivalent of mean-over-microbatches of per-micro CE (4 micro)
    losses = [
        nn.functional.cross_entropy(out[i * 2 : (i + 1) * 2], y[i * 2 : (i + 1) * 2])
        for i in range(4)
    ]
    return jnp.mean(jnp.stack(losses))


def test_pipeline_sync_model_roundtrip():
    model = build(3)
    mesh = mesh_mod.make_mesh({"pp": 4, "mp": 2})
    x = np.random.default_rng(3).normal(size=(4, 4)).astype(np.float32)
    y = np.zeros((4,), np.int32)
    pl = PipelineTrainer(
        model, optimizer.SGD(0.1), nn.functional.cross_entropy, mesh, num_micro=2
    )
    pl.train_step(jnp.asarray(x), jnp.asarray(y))
    pl.sync_model()  # params written back without error
    out = model(jnp.asarray(x))
    assert out.shape == (4, 3)
