"""Aux subsystems: hapi Model.fit, auto-checkpoint resume, elastic
manager decisions, local launcher (SURVEY §5 + §2.1 L14)."""

import os
import sys
import textwrap

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.elastic import (ElasticManager, ElasticStatus,
                                            FileStore, MemoryStore)
from paddle_tpu.distributed.launch import JobSpec, launch_local
from paddle_tpu.hapi import Model
from paddle_tpu.io.auto_checkpoint import CheckpointSaver, TrainEpochRange


# -- hapi -------------------------------------------------------------------


def _toy_data(n=64, batch=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8,)).astype(np.float32)
    y = (x @ w > 0).astype(np.int32)
    return [(x[i:i + batch], y[i:i + batch]) for i in range(0, n, batch)]


def test_model_fit_learns(tmp_path):
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = Model(net)
    model.prepare(optimizer.Adam(learning_rate=1e-2), nn.CrossEntropyLoss())
    data = _toy_data()
    hist = model.fit(data, epochs=5, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]

    model.save(str(tmp_path / "m"))
    model2 = Model(net)
    model2.prepare(optimizer.Adam(learning_rate=1e-2), nn.CrossEntropyLoss())
    model2.load(str(tmp_path / "m"))
    x, y = data[0]
    out = model2.predict_batch(x)
    assert out.shape == (16, 2)
    ev = model2.evaluate(data)
    assert ev["eval_loss"] == pytest.approx(hist["loss"][-1], rel=0.5)


# -- auto checkpoint --------------------------------------------------------


def test_checkpoint_saver_gc(tmp_path):
    s = CheckpointSaver(str(tmp_path), max_keep=2)
    for i in range(4):
        s.save({"v": i}, {"epoch": i})
    no, payload, meta = s.get_last()
    assert no == 3 and payload["v"] == 3 and meta["epoch"] == 3
    assert s._ids() == [2, 3]  # older snapshots GC'd


def test_train_epoch_range_resumes(tmp_path):
    state = {"w": 0.0}

    def run(crash_after=None):
        seen = []
        r = TrainEpochRange(5, "job", checkpoint_dir=str(tmp_path))
        r.set_state_getter(lambda: dict(state))
        r.set_state_setter(lambda s: state.update(s))
        for epoch in r:
            state["w"] += 1.0
            seen.append(epoch)
            if crash_after is not None and epoch == crash_after:
                r.save(epoch)
                raise RuntimeError("simulated crash")
        return seen

    with pytest.raises(RuntimeError):
        run(crash_after=2)
    assert state["w"] == 3.0
    state["w"] = -100.0  # clobber; resume must restore from snapshot
    seen = run()
    assert seen == [3, 4]          # epochs 0-2 skipped
    assert state["w"] == 5.0       # restored 3.0 + two more epochs


def test_train_epoch_range_resumes_mid_epoch_steps(tmp_path):
    """A MID-epoch snapshot (save(epoch, step)) must re-enter ITS epoch
    and skip exactly the completed steps — not restart the epoch from
    scratch (the pre-fix behavior re-trained them) and not skip to the
    next epoch (which would silently drop the unfinished tail)."""
    state = {"w": 0.0}
    steps_per_epoch = 4

    def run(crash_at=None):
        trained = []  # (epoch, step) actually trained this run
        r = TrainEpochRange(2, "midjob", checkpoint_dir=str(tmp_path))
        r.set_state_getter(lambda: dict(state))
        r.set_state_setter(lambda s: state.update(s))
        for epoch in r:
            for step, _ in r.steps(range(steps_per_epoch)):
                state["w"] += 1.0
                trained.append((epoch, step))
                if crash_at is not None and (epoch, step) == crash_at:
                    r.save(epoch, step=step + 1)  # steps 0..step done
                    raise RuntimeError("simulated crash")
        return trained

    with pytest.raises(RuntimeError):
        run(crash_at=(1, 1))
    assert state["w"] == 6.0  # epoch 0 (4 steps) + epoch-1 steps 0-1
    state["w"] = -100.0
    trained = run()
    # resume re-enters epoch 1 at step 2: no step replayed, none dropped
    assert trained == [(1, 2), (1, 3)]
    assert state["w"] == 8.0


def test_train_epoch_range_mid_epoch_resume_requires_cursor(tmp_path):
    """A mid-epoch resume whose caller runs a PLAIN inner loop (neither
    r.steps() nor a step_in_epoch read) silently re-trains the
    completed steps — the range must fail loudly at that epoch's end
    instead of corrupting the restored weights."""
    state = {"w": 0.0}
    r = TrainEpochRange(3, "midguard", checkpoint_dir=str(tmp_path))
    r.set_state_getter(lambda: dict(state))
    r.set_state_setter(lambda s: state.update(s))
    r.save(0, step=2)   # mid-epoch snapshot of epoch 0, then "crash"

    r2 = TrainEpochRange(3, "midguard", checkpoint_dir=str(tmp_path))
    r2.set_state_getter(lambda: dict(state))
    r2.set_state_setter(lambda s: state.update(s))
    with pytest.raises(Exception, match="never skipped"):
        for epoch in r2:
            pass   # plain loop: cursor never consumed

    # consuming the cursor (reading step_in_epoch) satisfies the guard
    r3 = TrainEpochRange(3, "midguard", checkpoint_dir=str(tmp_path))
    r3.set_state_getter(lambda: dict(state))
    r3.set_state_setter(lambda s: state.update(s))
    seen = []
    for epoch in r3:
        seen.append((epoch, r3.step_in_epoch))
    assert seen[0] == (0, 2) and [e for e, _ in seen] == [0, 1, 2]


def test_train_epoch_range_cursor_consumed_before_loop(tmp_path):
    """Reading step_in_epoch BEFORE the epoch loop (the documented
    consume-before-the-loop pattern: the caller skips the completed
    steps themselves) must satisfy the skip guard — __iter__ must not
    re-arm it and kill the correct resume at the epoch's end."""
    state = {"w": 0.0}
    r = TrainEpochRange(2, "preloop", checkpoint_dir=str(tmp_path))
    r.set_state_getter(lambda: dict(state))
    r.set_state_setter(lambda s: state.update(s))
    r.save(0, step=2)   # mid-epoch snapshot of epoch 0, then "crash"

    r2 = TrainEpochRange(2, "preloop", checkpoint_dir=str(tmp_path))
    r2.set_state_getter(lambda: dict(state))
    r2.set_state_setter(lambda s: state.update(s))
    assert r2.step_in_epoch == 2   # consumed before the loop starts
    seen = [epoch for epoch in r2]   # must NOT raise "never skipped"
    assert seen == [0, 1]


# -- elastic ----------------------------------------------------------------


def _mk_managers(store, n, np_=None, **kw):
    return [ElasticManager(store, "job", np_ or n, f"host{i}",
                           heartbeat_interval=0.05, heartbeat_ttl=0.3,
                           elastic_timeout=0.3, **kw)
            for i in range(n)]


def test_elastic_healthy_holds():
    store = MemoryStore()
    ms = _mk_managers(store, 2)
    for m in ms:
        m.start()
    try:
        assert ms[0].watch_once() == ElasticStatus.HOLD
        assert ms[0]._match()
    finally:
        for m in ms:
            m.stop()


def test_elastic_node_death_restarts():
    import time
    store = MemoryStore()
    ms = _mk_managers(store, 3, min_np=2, max_np=3)
    for m in ms:
        m.start()
    ms[2].stop()                      # node dies
    time.sleep(0.4)                   # ttl expiry + timeout
    st = ms[0].watch_once()
    time.sleep(0.4)
    st = ms[0].watch_once()
    assert st == ElasticStatus.RESTART
    assert ms[0].adopt_world() == 2   # shrunk world
    for m in ms[:2]:
        m.stop()


def test_elastic_below_min_errors():
    import time
    store = MemoryStore()
    ms = _mk_managers(store, 2, min_np=2, max_np=3)
    ms[0].start()
    ms[1].start()
    ms[1].stop()
    time.sleep(0.4)
    ms[0].watch_once()
    time.sleep(0.4)
    assert ms[0].watch_once() == ElasticStatus.ERROR
    ms[0].stop()


def test_file_store_roundtrip(tmp_path):
    s = FileStore(str(tmp_path))
    s.put("elastic/j/nodes/h0", "x", ttl=100)
    assert s.get("elastic/j/nodes/h0") == "x"
    assert list(s.list_prefix("elastic/j/nodes/")) == ["elastic/j/nodes/h0"]
    s.delete("elastic/j/nodes/h0")
    assert s.get("elastic/j/nodes/h0") is None


def test_tcp_elastic_store_roundtrip_and_lease_expiry():
    """TcpElasticStore (VERDICT r4 #6): the etcd-lease role over the
    cluster TCPStore — master + a second client process-equivalent,
    TTL expiry on read, prefix scans, and the ElasticManager's
    heartbeat/membership loop running over it."""
    import time

    from paddle_tpu.distributed.elastic import (ElasticManager,
                                                TcpElasticStore,
                                                store_from_spec)

    master = TcpElasticStore(is_master=True)
    try:
        client = store_from_spec(f"tcp:127.0.0.1:{master.port}")
        client.put("elastic/j/nodes/h0", "x", ttl=100)
        client.put("elastic/j/nodes/h1", "y", ttl=0.3)
        client.put("other/k", "z")
        # both sides observe the same keys (it IS one store)
        assert master.get("elastic/j/nodes/h0") == "x"
        assert sorted(master.list_prefix("elastic/j/nodes/")) == [
            "elastic/j/nodes/h0", "elastic/j/nodes/h1"]
        time.sleep(0.35)  # h1's lease expires without any sweeper
        assert master.get("elastic/j/nodes/h1") is None
        assert list(master.list_prefix("elastic/j/nodes/")) == [
            "elastic/j/nodes/h0"]
        client.delete("elastic/j/nodes/h0")
        assert master.get("elastic/j/nodes/h0") is None

        # the manager's full heartbeat/membership loop over this store
        ms = _mk_managers(master, 2)
        for m in ms:
            m.start()
        try:
            assert ms[0].watch_once() == ElasticStatus.HOLD
            assert ms[0]._match()
        finally:
            for m in ms:
                m.stop()
        client.close()
    finally:
        master.close()


# -- launcher ---------------------------------------------------------------


def test_launch_local_trainers(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        rank = os.environ["PADDLE_TRAINER_ID"]
        n = os.environ["PADDLE_TRAINERS_NUM"]
        assert os.environ["TRAINING_ROLE"] == "TRAINER"
        print(f"rank {rank}/{n} ok")
        sys.exit(0)
    """))
    rc = launch_local(JobSpec([str(script)], nproc=2,
                              log_dir=str(tmp_path / "logs")), timeout=60)
    assert rc == 0
    logs = sorted(os.listdir(tmp_path / "logs"))
    assert logs == ["trainer_0.log", "trainer_1.log"]
    assert "rank 0/2 ok" in (tmp_path / "logs" / "trainer_0.log").read_text()


def test_launch_propagates_failure(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(3)")
    rc = launch_local(JobSpec([str(script)], nproc=2), timeout=60)
    assert rc == 3


def test_trainer_dump_fields(tmp_path):
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer
    from paddle_tpu.executor import Trainer

    pt.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    tr = Trainer(model, optimizer.SGD(0.1), nn.functional.cross_entropy)
    tr.set_dump_config(str(tmp_path), fields=("loss", "input:0", "label:0"),
                       trainer_id=3)
    rng = np.random.default_rng(0)
    for _ in range(3):
        tr.train_step(rng.normal(size=(8, 4)).astype(np.float32),
                      rng.integers(0, 2, 8))
    tr.set_dump_config(None)  # close
    lines = (tmp_path / "trainer-003.dump").read_text().strip().splitlines()
    assert len(lines) == 9  # 3 steps x 3 fields
    assert lines[0].split("\t")[1] == "loss"
    steps = {int(l.split("\t")[0]) for l in lines}
    assert steps == {1, 2, 3}


def test_print_table_stat():
    import numpy as np

    from paddle_tpu.ps.table import MemorySparseTable, TableConfig

    t = MemorySparseTable(TableConfig(shard_num=4))
    t.pull_sparse(np.arange(1, 101, dtype=np.uint64))
    msg = t.print_table_stat()
    assert "100 features" in msg and "4 shards" in msg
    assert int(t.shard_sizes().sum()) == 100


def test_ps_op_cost_profiling():
    """PS ops feed the CostProfiler aggregator under the reference's
    scope names (cost_timer.h probes: pserver_sparse_select_all in
    MemorySparseTable::PullSparse, memory_sparse_table.cc:419)."""
    import numpy as np

    from paddle_tpu.core.profiler import host_event_stats, reset_host_events
    from paddle_tpu.ps.table import MemorySparseTable, TableConfig

    reset_host_events()
    t = MemorySparseTable(TableConfig(shard_num=2))
    keys = np.arange(1, 100, dtype=np.uint64)
    t.pull_sparse(keys)
    push = np.zeros((99, t.accessor.push_dim), np.float32)
    push[:, 1] = 1.0
    t.push_sparse(keys, push)
    st = host_event_stats()
    assert st["pserver_sparse_select_all"]["count"] == 1
    assert st["pserver_sparse_update_all"]["count"] == 1
    assert st["pserver_sparse_update_all"]["avg_s"] > 0


def test_timeline_merges_worker_traces(tmp_path):
    """tools/timeline.py: per-worker chrome traces merge into one file
    with a named pid lane per worker (the reference timeline tool)."""
    import json
    import sys

    sys.path.insert(0, str(
        __import__("pathlib").Path(__file__).resolve().parents[1] / "tools"))
    from timeline import merge_traces

    from paddle_tpu.core.profiler import (RecordEvent, export_chrome_tracing,
                                          start_timeline, stop_timeline)

    files = []
    for w in range(2):
        start_timeline()
        with RecordEvent(f"work_{w}"):
            pass
        stop_timeline()
        p = tmp_path / f"worker{w}.json"
        export_chrome_tracing(str(p))
        files.append(str(p))

    out = tmp_path / "merged.json"
    n = merge_traces(files, str(out))
    blob = json.loads(out.read_text())
    evs = blob["traceEvents"]
    assert n == len(evs)
    lanes = {e["args"]["name"] for e in evs if e.get("ph") == "M"}
    assert lanes == {"worker0", "worker1"}
    pids = {e["pid"] for e in evs if e.get("ph") == "X"}
    assert pids == {0, 1}
    names = {e["name"] for e in evs if e.get("ph") == "X"}
    assert {"work_0", "work_1"} <= names


def test_hapi_prepare_amp_configs(rng):
    """Model.prepare(amp_configs=...) — the reference hapi's mixed-
    precision knob: 'O1'/'O2'/True/dict enable bf16 contractions in the
    step; None/'O0' keep f32."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu import hapi, nn, optimizer

    pt.seed(0)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = rng.integers(0, 2, 32).astype(np.int32)
    for amp_cfg, expect_bf16 in ((None, False), ("O0", False),
                                 ("O1", True), ({"level": "O2"}, True),
                                 ({"init_loss_scaling": 1024.0}, True)):
        m = hapi.Model(nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                     nn.Linear(16, 2)))
        m.prepare(optimizer.Adam(1e-2), nn.functional.cross_entropy,
                  amp_configs=amp_cfg)
        out = m.train_batch(x, y)
        assert np.isfinite(out["loss"])
        txt = m._train_step.lower(
            m._state, m._opt_state, jax.random.key(0),
            (jnp.asarray(x),), (jnp.asarray(y),)).as_text()
        assert ("bf16" in txt) == expect_bf16, (amp_cfg, expect_bf16)
    # the reference rejects unknown levels; so do we
    m = hapi.Model(nn.Linear(8, 2))
    with pytest.raises(Exception, match="O0/O1/O2"):
        m.prepare(optimizer.Adam(1e-2), nn.functional.cross_entropy,
                  amp_configs="o1")


def test_hapi_o2_master_weights(rng):
    """amp_configs='O2' — pure bf16 parameter storage with f32 master
    weights (paddle.amp.decorate(level='O2') + multi_precision
    optimizer semantics): params live in bf16, masters carry full
    precision, the model trains, and the bf16 params stay exact
    projections of the masters every step."""
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu import hapi, nn, optimizer
    from paddle_tpu.optimizer import MasterWeights

    pt.seed(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = (x @ rng.normal(size=(8, 2)).astype(np.float32)).argmax(-1).astype(
        np.int32)
    m = hapi.Model(nn.Sequential(nn.Linear(8, 32), nn.ReLU(),
                                 nn.Linear(32, 2)))
    m.prepare(optimizer.Adam(5e-3), nn.functional.cross_entropy,
              amp_configs="O2")
    assert isinstance(m._opt, MasterWeights)
    for p in m._state["params"].values():
        assert p.dtype == jnp.bfloat16, p.dtype
    masters = m._opt_state["slots"]["master"]
    for k, mm in masters.items():
        assert mm.dtype == jnp.float32, k
    losses = [m.train_batch(x, y)["loss"] for _ in range(30)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    # params are pure projections of the masters (no drift channel)
    masters = m._opt_state["slots"]["master"]
    for k, p in m._state["params"].items():
        np.testing.assert_array_equal(
            np.asarray(p), np.asarray(masters[k].astype(jnp.bfloat16)), k)


def test_checkpoint_structured_array_roundtrip(tmp_path):
    """Advisor r4 (low): a genuine structured/record array is also
    numpy kind 'V' but is NOT an ml_dtypes scalar — it must take the
    plain savez path and round-trip, not fail at the uint-view."""
    from paddle_tpu.io import checkpoint as ckpt

    rec = np.array([(1, 2.5), (3, 4.5)],
                   dtype=[("k", np.int64), ("v", np.float32)])
    ckpt.save({"rec": rec}, str(tmp_path / "rec"))
    back = ckpt.load(str(tmp_path / "rec"))
    assert back["rec"].dtype == rec.dtype
    np.testing.assert_array_equal(back["rec"], rec)


def test_hapi_o2_checkpoint_roundtrip(rng, tmp_path):
    """O2 bf16 params survive save/load bit-exactly (np.savez degrades
    ml_dtypes arrays to raw void without the serializer's dtype-tagged
    integer view)."""
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu import hapi, nn, optimizer
    from paddle_tpu.io import checkpoint as ckpt

    # serializer-level: bf16 round-trips with dtype intact
    arr = {"w": jnp.asarray(rng.normal(size=(4, 3)), jnp.bfloat16)}
    ckpt.save(arr, str(tmp_path / "bf16"))
    back = ckpt.load(str(tmp_path / "bf16"))
    assert back["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(back["w"]).view(np.uint16),
                                  np.asarray(arr["w"]).view(np.uint16))

    # model-level: O2 save -> load -> training continues
    pt.seed(0)
    x = rng.normal(size=(16, 8)).astype(np.float32)
    y = rng.integers(0, 2, 16).astype(np.int32)
    m = hapi.Model(nn.Linear(8, 2))
    m.prepare(optimizer.Adam(1e-2), nn.functional.cross_entropy,
              amp_configs="O2")
    m.train_batch(x, y)
    m.save(str(tmp_path / "o2"))
    m2 = hapi.Model(nn.Linear(8, 2))
    m2.prepare(optimizer.Adam(1e-2), nn.functional.cross_entropy,
               amp_configs="O2")
    m2.load(str(tmp_path / "o2"))
    for k, v in m2._state["params"].items():
        assert np.asarray(v).dtype == jnp.bfloat16, k
    assert np.isfinite(m2.train_batch(x, y)["loss"])


def test_master_weights_rejects_meta_optimizer():
    """Wrapping order is enforced: MasterWeights(plain) only; a meta
    wrapper inside would half-apply loss scaling."""
    from paddle_tpu import optimizer
    from paddle_tpu.core.enforce import EnforceNotMet
    from paddle_tpu.distributed.meta_optimizers import AMPOptimizer

    with pytest.raises(EnforceNotMet, match="MasterWeights"):
        optimizer.MasterWeights(AMPOptimizer(optimizer.Adam(1e-3)))


def test_amp_optimizer_composes_outside_master_weights(rng):
    """The DOCUMENTED composition — AMPOptimizer(MasterWeights(plain))
    — actually trains: dynamic loss scaling outside, f32 masters
    inside, bf16 params throughout."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import optimizer
    from paddle_tpu.distributed.meta_optimizers import AMPOptimizer

    p32 = {"w": jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)}
    opt = AMPOptimizer(optimizer.MasterWeights(optimizer.Adam(1e-2)))
    state = opt.init(p32)
    params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), p32)
    g = {"w": jnp.full((8, 4), 0.01, jnp.float32)}
    for _ in range(5):
        # grads of the SCALED loss, as the step factory produces them
        sg = jax.tree.map(
            lambda x: x * state["scaler"].loss_scale, g)
        params, state = opt.update(sg, state, params)
    assert params["w"].dtype == jnp.bfloat16
    masters = state["inner"]["slots"]["master"]["w"]
    assert masters.dtype == jnp.float32
    assert np.isfinite(np.asarray(masters)).all()
    # the params moved (updates were not skipped / zeroed by scaling)
    assert not np.array_equal(
        np.asarray(params["w"]).view(np.uint16),
        np.asarray(p32["w"].astype(jnp.bfloat16)).view(np.uint16))


def test_master_weights_matches_f32_trajectory(rng):
    """MasterWeights(Adam) fed the SAME f32 grads reproduces plain f32
    Adam's master trajectory exactly (the wrapper adds no math), while
    exposing bf16 params."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import optimizer

    p32 = {"w": jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)}
    p16 = jax.tree.map(lambda p: p.astype(jnp.bfloat16), p32)
    g = {"w": jnp.asarray(rng.normal(size=(8, 4)) * 0.01, jnp.float32)}
    ref, o2 = optimizer.Adam(1e-2), optimizer.MasterWeights(
        optimizer.Adam(1e-2))
    rs, os_ = ref.init(p32), o2.init(p32)  # masters seeded from f32
    for _ in range(10):
        p32, rs = ref.update(g, rs, p32)
        p16, os_ = o2.update(g, os_, p16)
    np.testing.assert_array_equal(
        np.asarray(os_["slots"]["master"]["w"]), np.asarray(p32["w"]))
    assert p16["w"].dtype == jnp.bfloat16


def test_decorate_o2_composes_with_meta_wrappers(rng):
    """decorate_o2 inserts MasterWeights around the INNERMOST plain
    optimizer: AMPOptimizer(Adam) becomes AMPOptimizer(MasterWeights(
    Adam)); already-decorated chains are left alone (review finding:
    the naive isinstance check dead-ended the documented composition)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import optimizer
    from paddle_tpu.distributed.meta_optimizers import AMPOptimizer
    from paddle_tpu.optimizer import MasterWeights, decorate_o2

    p32 = {"w": jnp.asarray(rng.normal(size=(4, 4)), jnp.float32)}

    # meta wrapper outside: MasterWeights inserted inside
    opt, state, p16 = decorate_o2(AMPOptimizer(optimizer.Adam(1e-2)), p32)
    assert isinstance(opt, AMPOptimizer)
    assert isinstance(opt.inner, MasterWeights)
    assert p16["w"].dtype == jnp.bfloat16
    g = jax.tree.map(lambda x: x * state["scaler"].loss_scale,
                     {"w": jnp.full((4, 4), 0.01, jnp.float32)})
    p16, state = opt.update(g, state, p16)
    assert p16["w"].dtype == jnp.bfloat16

    # already decorated: unchanged, not double-wrapped
    pre = AMPOptimizer(MasterWeights(optimizer.Adam(1e-2)))
    opt2, _, _ = decorate_o2(pre, p32)
    assert opt2 is pre and isinstance(opt2.inner, MasterWeights)
    assert not isinstance(opt2.inner.inner, MasterWeights)
