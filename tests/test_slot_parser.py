"""MultiSlot parser: format compliance + the corruption cases the review
found (short lines must not steal tokens; bad lines must roll back all
slot buffers)."""

import numpy as np
import pytest

from paddle_tpu.ps.native import SlotParser, native_available

SLOTS = [("click", False, True), ("feat", False, True), ("dense", True, True)]


def make_parser():
    return SlotParser(SLOTS)


def test_basic_parse_and_fetch():
    p = make_parser()
    text = "1 1 2 101 102 1 0.5\n1 0 1 103 2 1.5 2.5\n"
    assert p.parse(text) == 2
    assert p.errors == 0
    out = p.fetch()
    np.testing.assert_array_equal(out["click"][0], [1, 0])
    np.testing.assert_array_equal(out["click"][1], [1, 1])
    np.testing.assert_array_equal(out["feat"][0], [101, 102, 103])
    np.testing.assert_array_equal(out["feat"][1], [2, 1])
    np.testing.assert_allclose(out["dense"][0], [0.5, 1.5, 2.5])
    np.testing.assert_array_equal(out["dense"][1], [1, 2])


def test_short_line_does_not_steal_next_line():
    """Line declares 3 ids but has 2 — must fail cleanly, next line intact."""
    p = make_parser()
    text = "1 1 3 10 11\n1 0 1 42 1 2.0\n"
    ok = p.parse(text)
    assert ok == 1
    assert p.errors == 1
    out = p.fetch()
    np.testing.assert_array_equal(out["feat"][0], [42])
    np.testing.assert_array_equal(out["click"][0], [0])


def test_bad_line_rolls_back_all_slots():
    """Garbage mid-line: every slot buffer must be restored."""
    p = make_parser()
    text = "1 1 2 10 xx 0\n1 1 1 5 1 3.0\n"
    ok = p.parse(text)
    assert ok == 1 and p.errors == 1
    out = p.fetch()
    np.testing.assert_array_equal(out["feat"][0], [5])
    np.testing.assert_array_equal(out["feat"][1], [1])
    np.testing.assert_allclose(out["dense"][0], [3.0])


def test_unused_slot_skipped_positionally():
    p = SlotParser([("a", False, True), ("skip", False, False), ("b", False, True)])
    text = "1 7 2 999 998 1 8\n"
    assert p.parse(text) == 1
    out = p.fetch()
    assert "skip" not in out
    np.testing.assert_array_equal(out["a"][0], [7])
    np.testing.assert_array_equal(out["b"][0], [8])


def test_blank_lines_ignored():
    p = make_parser()
    assert p.parse("\n\n1 1 1 5 1 1.0\n\n") == 1
    assert p.errors == 0


def test_no_trailing_newline():
    p = make_parser()
    assert p.parse("1 1 1 5 1 1.0") == 1


def test_multiple_parse_calls_accumulate():
    p = make_parser()
    p.parse("1 1 1 5 1 1.0\n")
    p.parse("1 0 1 6 1 2.0\n")
    out = p.fetch()
    np.testing.assert_array_equal(out["feat"][0], [5, 6])


def test_native_is_available():
    assert native_available()  # g++ is baked into this image
