"""Multi-process distributed backend: two localhost processes × 4
virtual CPU devices each join one jax.distributed job (the DCN bootstrap
replacing c_gen_nccl_id's TCP exchange, SURVEY §2.4 →TPU) and run
(a) eager host collectives (ProcessGroup role) and (b) ONE compiled
psum over the global 8-device mesh — the reference's
test_dist_base-style localhost-subprocess harness.
"""

import textwrap

import pytest

from conftest import launch_two_workers

_WORKER = textwrap.dedent("""
    # (a) eager host collectives
    got = C.all_reduce(np.asarray([1.0 + rank, 10.0]), op="sum")
    assert got.tolist() == [sum(1.0 + r for r in range(world)), 10.0 * world], got
    b = C.broadcast(np.asarray([rank * 7.0]), src=1)
    assert b.tolist() == [7.0], b
    gathered = C.all_gather(np.asarray([float(rank)]))
    assert [g.tolist() for g in gathered] == [[0.0], [1.0]]
    C.barrier()

    # (b) compiled psum over the GLOBAL 8-device mesh
    import jax.numpy as jnp
    from jax import lax, shard_map
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(world * 4), ("dp",))
    local = np.full((4, 2), float(rank + 1), np.float32)  # 4 local shards
    garr = multihost_utils.host_local_array_to_global_array(
        local, mesh, P("dp"))
    out = jax.jit(shard_map(lambda x: lax.psum(x, "dp"), mesh=mesh,
                            in_specs=P("dp"), out_specs=P()))(garr)
    total = float(np.asarray(out.addressable_data(0))[0, 0])
    # sum over 8 shards: 4 shards of 1.0 + 4 shards of 2.0 = 12
    assert total == 12.0, total
    print("WORKER_OK", rank, flush=True)
""")


@pytest.mark.slow
def test_two_process_jax_distributed(tmp_path):
    launch_two_workers(_WORKER, tmp_path)
