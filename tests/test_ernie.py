"""Ernie flagship model: serial vs sharded parity on the virtual 8-device
mesh (the reference validates TP/PP numerics by comparing distributed
losses against single-process runs — test_dist_base.py pattern)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.core import mesh as mesh_mod
from paddle_tpu.models.ernie import (Ernie, ErnieConfig, parallel_cross_entropy,
                                     partition_spec)

CFG = ErnieConfig(vocab_size=32, hidden_size=16, num_heads=4, ffn_size=32,
                  num_layers=2, max_seq_len=64)


def _specs(state, cfg, mesh):
    # mirror the exact pytree type (get_state returns OrderedDicts) and
    # drop axes the mesh doesn't have
    def spec(path, a):
        p = partition_spec(path[-1].key, a, cfg)
        return P(*[ax if ax in mesh.shape else None for ax in p])

    return jax.tree_util.tree_map_with_path(spec, state)


def _serial_loss(model, state, ids, labels):
    out, _ = nn.functional_call(model, state, ids, training=False)
    ce = nn.functional.cross_entropy(out, labels, reduction="none")
    return jnp.mean(ce)


def _sharded_loss(model, cfg, mesh, state, ids, labels):
    specs = _specs(state, cfg, mesh)

    def f(st, ids, labels):
        out, _ = nn.functional_call(model, st, ids, training=False)
        ce = parallel_cross_entropy(out, labels, cfg.vocab_size, cfg.mp_axis)
        local = jnp.mean(ce)
        batch_axes = tuple(a for a in ("dp", "cp") if a in mesh.shape)
        denom = int(np.prod([mesh.shape[a] for a in batch_axes]))
        return jax.lax.psum(local / denom, batch_axes)

    data_axes = [a for a in ("dp", "cp") if a in mesh.shape]
    ids_spec = P(data_axes[0] if "dp" in mesh.shape else None,
                 "cp" if "cp" in mesh.shape else None)
    return shard_map(f, mesh=mesh, in_specs=(specs, ids_spec, ids_spec),
                     out_specs=P())(state, ids, labels)


def _data(cfg, batch=4, seq=8):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)
    return jnp.asarray(ids), jnp.asarray(labels)


def test_serial_forward_shapes():
    pt.seed(0)
    model = Ernie(CFG)
    ids, labels = _data(CFG)
    logits = model(ids)
    assert logits.shape == (4, 8, CFG.vocab_size)
    loss = model.loss(ids, labels)
    assert np.isfinite(float(loss))


def test_tp_matches_serial():
    pt.seed(0)
    model = Ernie(CFG)
    state = nn.get_state(model)
    ids, labels = _data(CFG)
    serial = _serial_loss(model, state, ids, labels)
    mesh = mesh_mod.make_mesh({"dp": 2, "mp": 4})
    sharded = _sharded_loss(model, CFG, mesh, state, ids, labels)
    np.testing.assert_allclose(float(sharded), float(serial), rtol=1e-4)


def test_cp_matches_serial():
    pt.seed(1)
    model = Ernie(CFG)
    state = nn.get_state(model)
    ids, labels = _data(CFG)
    serial = _serial_loss(model, state, ids, labels)
    mesh = mesh_mod.make_mesh({"dp": 2, "cp": 4})
    sharded = _sharded_loss(model, CFG, mesh, state, ids, labels)
    np.testing.assert_allclose(float(sharded), float(serial), rtol=1e-4)


def test_causal_cp_matches_serial():
    cfg = dataclasses.replace(CFG, causal=True)
    pt.seed(2)
    model = Ernie(cfg)
    state = nn.get_state(model)
    ids, labels = _data(cfg)
    serial = _serial_loss(model, state, ids, labels)
    mesh = mesh_mod.make_mesh({"cp": 8})
    sharded = _sharded_loss(model, cfg, mesh, state, ids, labels)
    np.testing.assert_allclose(float(sharded), float(serial), rtol=1e-4)


def test_moe_ep_matches_serial():
    cfg = dataclasses.replace(CFG, num_experts=4, ep_axis="dp")
    pt.seed(3)
    model = Ernie(cfg)
    state = nn.get_state(model)
    ids, labels = _data(cfg, batch=8)
    serial = _serial_loss(model, state, ids, labels)
    mesh = mesh_mod.make_mesh({"dp": 2, "mp": 4})
    sharded = _sharded_loss(model, cfg, mesh, state, ids, labels)
    # token grid differs between serial (one dispatch over all tokens) and
    # ep (per-dp-shard dispatch): capacity truncation can drop different
    # tokens, so compare loosely
    np.testing.assert_allclose(float(sharded), float(serial), rtol=0.05)


def test_tp_grads_match_serial():
    """TP+DP gradients vs jax.grad of the serial model — written in the
    sanctioned explicit-reduction pattern (the hybrid trainer's): jax
    0.4.x shard_map cannot be trusted to transpose psums through this
    model (this test failed at PR-2 baseline with the rep-tracking
    form), so the loss psum and the PCE reductions are pinned-VJP
    (``pinned_vjp=True``), the shard_map runs ``check_vma=False``, and
    each param's grad is explicitly psum'd over every mesh axis it is
    NOT sharded on."""
    from paddle_tpu.ops import collectives as coll

    pt.seed(4)
    model = Ernie(CFG)
    state = nn.get_state(model)
    ids, labels = _data(CFG)
    gs = jax.grad(lambda st: _serial_loss(model, st, ids, labels))(state)
    mesh = mesh_mod.make_mesh({"dp": 2, "mp": 4})
    specs = _specs(state, CFG, mesh)

    def f(st, ids, labels):
        def loss(st):
            out, _ = nn.functional_call(model, st, ids, training=False)
            ce = parallel_cross_entropy(out, labels, CFG.vocab_size, "mp",
                                        pinned_vjp=True)
            return coll.psum_replicated(jnp.mean(ce) / 2, ("dp",))

        grads = jax.grad(loss)(st)
        return coll.spec_reduced_grads(grads, specs, dict(mesh.shape))

    gd = shard_map(f, mesh=mesh, in_specs=(specs, P("dp", None), P("dp", None)),
                   out_specs=specs, check_vma=False)(state, ids, labels)
    for name, g in gs["params"].items():
        np.testing.assert_allclose(np.asarray(gd["params"][name]),
                                   np.asarray(g), rtol=2e-3, atol=1e-5,
                                   err_msg=name)
