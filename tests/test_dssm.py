"""DSSM two-tower recall (models/dssm.py): in-batch-negatives training
through the GPUPS pass path learns a query↔doc pairing structure, and
retrieval ranks the true doc above batch negatives."""

import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.models.ctr import _masked_pull
from paddle_tpu.models.dssm import DSSM, make_dssm_train_step
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
from paddle_tpu.ps.table import MemorySparseTable, TableConfig

SQ, SD, DIM = 2, 2, 8
N_PAIRS = 48  # latent topics: query topic t pairs with doc topic t


def _synth(rng, n):
    """Query slots drawn from topic-t query vocab; the paired doc's
    slots from topic-t doc vocab — towers must embed both sides of a
    topic near each other."""
    topic = rng.integers(0, N_PAIRS, size=n).astype(np.uint64)
    q = (topic[:, None] * np.uint64(4)
         + rng.integers(0, 4, size=(n, SQ)).astype(np.uint64) + np.uint64(1))
    d = (topic[:, None] * np.uint64(4)
         + rng.integers(0, 4, size=(n, SD)).astype(np.uint64) + np.uint64(1)
         + (np.uint64(1) << np.uint64(32)))  # doc slot-space tag
    keys = np.concatenate([q, d], axis=1)
    dense = np.zeros((n, 1), np.float32)
    labels = np.ones(n, np.int32)
    return keys, dense, labels


def test_dssm_learns_pairing_and_ranks_true_doc():
    pt.seed(0)
    rng = np.random.default_rng(0)
    cache_cfg = CacheConfig(capacity=2048, embedx_dim=DIM,
                            embedx_threshold=0.0)
    # embedx_threshold=0 on the TABLE accessor too: DSSM's objective is
    # purely bilinear in the embx vectors — lazily-created all-zero embx
    # would put both towers at an exact saddle (zero gradients)
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(
            embedx_dim=DIM, embedx_threshold=0.0)))
    cache = HbmEmbeddingCache(table, cache_cfg)

    keys, dense, labels = _synth(rng, 2048)
    cache.begin_pass(keys.reshape(-1))
    model = DSSM(SQ, SD, DIM)
    opt = optimizer.Adam(learning_rate=3e-3)
    params = {"params": dict(model.named_parameters()), "buffers": {}}
    opt_state = opt.init(params)
    step = make_dssm_train_step(model, opt, cache_cfg,
                                temperature=0.2, donate=False)

    B = 128
    losses = []
    for epoch in range(40):
        for i in range(0, len(keys), B):
            rows = jnp.asarray(
                cache.lookup(keys[i:i + B].reshape(-1)).reshape(B, SQ + SD))
            params, opt_state, cache.state, loss = step(
                params, opt_state, cache.state, rows,
                jnp.asarray(dense[i:i + B]), jnp.asarray(labels[i:i + B]))
            losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    # retrieval check: within held-out batches, the true doc must rank
    # top-1 among the in-batch candidates far above the 1/B chance rate
    keys2, dense2, _ = _synth(rng, 512)
    hits = total = 0
    for i in range(0, len(keys2), B):
        k = keys2[i:i + B]
        rows = jnp.asarray(cache.lookup(k.reshape(-1)).reshape(B, SQ + SD))
        emb = _masked_pull(cache.state, rows.reshape(-1)).reshape(
            B, SQ + SD, -1)
        (q, d), _ = nn.functional_call(model, params, emb,
                                       jnp.asarray(dense2[i:i + B]),
                                       training=False)
        sim = np.asarray(q @ d.T)
        hits += int((sim.argmax(axis=1) == np.arange(B)).sum())
        total += B
    top1 = hits / total
    assert top1 > 0.25, top1  # chance = 1/128 ≈ 0.008


def test_padded_examples_are_not_fake_negatives():
    """The padding contract: a tail batch's padded rows must not act as
    in-batch negatives — real rows' losses are identical whether the
    batch carries padding or not."""
    import jax

    pt.seed(0)
    rng = np.random.default_rng(3)
    model = DSSM(SQ, SD, DIM)
    params = {"params": dict(model.named_parameters()), "buffers": {}}
    B, Breal = 8, 5
    emb = jnp.asarray(rng.normal(scale=0.1, size=(B, SQ + SD, 1 + DIM)),
                      jnp.float32)
    dense = jnp.zeros((B, 1), jnp.float32)
    w = jnp.asarray((np.arange(B) < Breal).astype(np.float32))
    out_full, _ = nn.functional_call(model, params, emb, dense,
                                     training=False)
    per_masked = DSSM.loss_vec(out_full, None, 0.2, weights=w)
    out_real, _ = nn.functional_call(model, params, emb[:Breal], dense[:Breal],
                                     training=False)
    per_real = DSSM.loss_vec(out_real, None, 0.2)
    np.testing.assert_allclose(np.asarray(per_masked)[:Breal],
                               np.asarray(per_real), rtol=1e-5)
    assert np.isfinite(np.asarray(per_masked)).all()


def test_dssm_tower_export(tmp_path):
    """export_dssm_towers: query and doc towers export as separate
    portable programs (ANN-index build + online query, the module's
    promised serving split); loaded towers reproduce the in-process
    normalized vectors and their dot ranks the true pairing."""
    import jax

    from paddle_tpu.io.inference import load_inference_model
    from paddle_tpu.models.dssm import export_dssm_towers
    from paddle_tpu.nn.layer import functional_call

    pt.seed(0)
    rng = np.random.default_rng(0)
    cache_cfg = CacheConfig(capacity=2048, embedx_dim=DIM,
                            embedx_threshold=0.0)
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(
            embedx_dim=DIM, embedx_threshold=0.0)))
    cache = HbmEmbeddingCache(table, cache_cfg, device_map=True)
    keys, dense, labels = _synth(rng, 256)
    cache.begin_pass(keys.reshape(-1))
    # non-trivial table values so towers output distinct vectors
    cache.state["embedx_w"] = jnp.asarray(
        rng.normal(size=cache.state["embedx_w"].shape).astype(np.float32))

    model = DSSM(SQ, SD, DIM)
    # _synth's key scheme: every query slot lives in hi=0 key space,
    # every doc slot in hi=1 (the doc slot-space tag)
    export_dssm_towers(str(tmp_path), model, cache,
                       query_slot_ids=np.zeros(SQ, np.uint32),
                       doc_slot_ids=np.ones(SD, np.uint32))
    q_pred = load_inference_model(str(tmp_path / "query"))
    d_pred = load_inference_model(str(tmp_path / "doc"))

    B = 16
    lo = (keys[:B] & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    q_vec = np.asarray(q_pred(jnp.asarray(lo[:, :SQ])))
    d_vec = np.asarray(d_pred(jnp.asarray(lo[:, SQ:])))
    assert q_vec.shape == d_vec.shape == (B, 16)
    np.testing.assert_allclose(np.linalg.norm(q_vec, axis=1), 1.0,
                               atol=1e-3)

    # in-process reference through the full model
    rows = jnp.asarray(cache.lookup(keys[:B].reshape(-1)).reshape(
        B, SQ + SD))
    from paddle_tpu.ps.embedding_cache import cache_pull
    emb = cache_pull(cache.state, rows.reshape(-1)).reshape(B, SQ + SD, -1)
    (q_ref, d_ref), _ = functional_call(
        model, {"params": dict(model.named_parameters()), "buffers": {}},
        emb, jnp.asarray(dense[:B]), training=False)
    np.testing.assert_allclose(q_vec, np.asarray(q_ref), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(d_vec, np.asarray(d_ref), rtol=1e-5,
                               atol=1e-5)

    # params-only refresh (the online query-tower update): mutate the
    # tables, overwrite values — the programs are untouched and a fresh
    # predictor serves moved vectors
    import os

    prog = tmp_path / "query" / "model.stablehlo"
    before = prog.read_bytes()
    cache.state["embed_w"] = cache.state["embed_w"] * 2.0
    export_dssm_towers(str(tmp_path), model, cache,
                       query_slot_ids=np.zeros(SQ, np.uint32),
                       doc_slot_ids=np.ones(SD, np.uint32),
                       refresh_only=True)
    assert prog.read_bytes() == before
    q2 = np.asarray(load_inference_model(str(tmp_path / "query"))(
        jnp.asarray(lo[:, :SQ])))
    assert not np.allclose(q2, q_vec)
    np.testing.assert_allclose(np.linalg.norm(q2, axis=1), 1.0, atol=1e-3)
