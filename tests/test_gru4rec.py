"""GRU4Rec session recall (models/gru4rec.py) + the nn.GRU/LSTM layers
it rides on. Synthetic signal: sessions walk within an item cluster
and the next item comes from the same cluster — after training the
session vector must rank the true next item above in-batch negatives."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.models.gru4rec import (GRU4Rec, item_keys,
                                       make_gru4rec_train_step)
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import MemorySparseTable, TableConfig

N_ITEMS, N_CLUSTERS, T = 32, 4, 5


def _sessions(rng, n):
    cluster = rng.integers(0, N_CLUSTERS, n)
    lo = cluster * (N_ITEMS // N_CLUSTERS)
    span = N_ITEMS // N_CLUSTERS
    seq = lo[:, None] + rng.integers(0, span, (n, T))
    lengths = rng.integers(2, T + 1, n)
    target = lo + rng.integers(0, span, n)
    return seq.astype(np.uint64), lengths, target.astype(np.uint64), cluster


def test_gru_masking_and_shapes(rng):
    pt.seed(0)
    gru = nn.GRU(4, 8, num_layers=2)
    x = jnp.asarray(rng.normal(size=(3, 6, 4)).astype(np.float32))
    lengths = jnp.asarray([6, 2, 4])
    out, h = gru(x, lengths)
    assert out.shape == (3, 6, 8) and h.shape == (2, 3, 8)
    o = np.asarray(out)
    assert (o[1, 2:] == 0).all() and (o[2, 4:] == 0).all()
    # final state = last REAL step's output
    np.testing.assert_allclose(np.asarray(h)[1][1], o[1, 1], rtol=1e-6)

    lstm = nn.LSTM(4, 8)
    o2, (h2, c2) = lstm(x, lengths)
    assert o2.shape == (3, 6, 8) and h2.shape == c2.shape == (1, 3, 8)
    assert (np.asarray(o2)[1, 2:] == 0).all()


def test_gru4rec_learns_session_recall(rng):
    pt.seed(0)
    dim = 8
    sgd = SGDRuleConfig(learning_rate=0.1)
    acc = AccessorConfig(embedx_dim=dim, embedx_threshold=0.0, sgd=sgd)
    table = MemorySparseTable(TableConfig(shard_num=2,
                                          accessor_config=acc))
    cache_cfg = CacheConfig(capacity=1 << 8, embedx_dim=dim,
                            embedx_threshold=0.0, sgd=sgd)
    cache = HbmEmbeddingCache(table, cache_cfg, device_map=True)
    cache.begin_pass(item_keys(np.arange(N_ITEMS)))
    cache.state["embedx_w"] = jnp.asarray(
        rng.normal(scale=0.1,
                   size=cache.state["embedx_w"].shape).astype(np.float32))

    model = GRU4Rec(embedx_dim=dim, hidden=16, out_dim=8)
    opt = optimizer.Adam(5e-3)
    params = {"params": dict(model.named_parameters()), "buffers": {}}
    opt_state = opt.init(params)
    step = make_gru4rec_train_step(model, opt, cache_cfg, donate=False)

    C = cache_cfg.capacity
    losses = []
    for it in range(120):
        seq, lengths, target, _ = _sessions(rng, 32)
        rows_seq = cache.lookup(item_keys(seq.reshape(-1))).reshape(
            seq.shape).astype(np.int32)
        # positions past length use the sentinel (padding contract)
        pad = np.arange(T)[None, :] >= lengths[:, None]
        rows_seq = np.where(pad, C, rows_seq)
        rows_tgt = cache.lookup(item_keys(target)).astype(np.int32)
        params, opt_state, cache.state, loss = step(
            params, opt_state, cache.state, jnp.asarray(rows_seq),
            jnp.asarray(rows_tgt), jnp.asarray(lengths))
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.7, (
        np.mean(losses[:10]), np.mean(losses[-10:]))

    # retrieval: the true next item ranks above most in-batch negatives
    seq, lengths, target, cluster = _sessions(rng, 64)
    rows_seq = cache.lookup(item_keys(seq.reshape(-1))).reshape(
        seq.shape).astype(np.int32)
    pad = np.arange(T)[None, :] >= lengths[:, None]
    rows_seq = np.where(pad, C, rows_seq)
    rows_tgt = cache.lookup(item_keys(target)).astype(np.int32)
    from paddle_tpu.ps.embedding_cache import cache_pull

    emb_seq = cache_pull(cache.state, jnp.asarray(rows_seq.reshape(-1))
                         ).reshape(64, T, -1)
    emb_tgt = cache_pull(cache.state, jnp.asarray(rows_tgt))
    (u, v), _ = nn.functional_call(model, params, emb_seq, emb_tgt,
                                   jnp.asarray(lengths), training=False)
    scores = np.asarray(u @ v.T)                 # [B, B]
    # in-batch negatives include ~B/N_CLUSTERS same-cluster items that
    # are equally valid nexts, capping rank-of-target metrics — the
    # learnable signal is the CLUSTER: same-cluster targets must score
    # above cross-cluster ones (AUC over the score matrix)
    same = cluster[:, None] == cluster[None, :]
    pos, neg = scores[same], scores[~same]
    auc = float(np.mean(pos[:, None] > neg[None, :]))
    assert auc > 0.85, auc                        # random = 0.5
    # and the true target still beats clear majority of CROSS-cluster
    # negatives per example
    ranks_cross = ((scores > np.diag(scores)[:, None]) & ~same).sum(1)
    assert float(np.mean(ranks_cross)) < 3.0, ranks_cross.mean()


def test_gru4rec_tower_exports(rng, tmp_path):
    """export_gru4rec_towers: the session tower (keys+lengths →
    normalized session vector, GRU scan inside a batch-polymorphic
    portable program) and the item tower (keys → normalized vectors)
    match the in-process forward; padding past lengths and out-of-pass
    ids hit the sentinel; refresh_only swaps values without touching
    the programs."""
    from paddle_tpu.io.inference import load_inference_model
    from paddle_tpu.models.gru4rec import export_gru4rec_towers
    from paddle_tpu.ps.embedding_cache import cache_pull

    pt.seed(0)
    dim = 8
    acc = AccessorConfig(embedx_dim=dim, embedx_threshold=0.0,
                         sgd=SGDRuleConfig(initial_range=0.0))
    table = MemorySparseTable(TableConfig(shard_num=2, accessor_config=acc))
    cache_cfg = CacheConfig(capacity=1 << 8, embedx_dim=dim,
                            embedx_threshold=0.0)
    cache = HbmEmbeddingCache(table, cache_cfg, device_map=True)
    cache.begin_pass(item_keys(np.arange(N_ITEMS)))
    cache.state["embedx_w"] = jnp.asarray(
        rng.normal(scale=0.1,
                   size=cache.state["embedx_w"].shape).astype(np.float32))
    cache.state["embed_w"] = jnp.asarray(
        rng.normal(scale=0.1,
                   size=cache.state["embed_w"].shape).astype(np.float32))

    model = GRU4Rec(embedx_dim=dim, hidden=16, out_dim=8)
    export_gru4rec_towers(str(tmp_path), model, cache, max_len=T)
    sess = load_inference_model(str(tmp_path / "session"))
    item = load_inference_model(str(tmp_path / "item"))

    seq, lengths, target, _ = _sessions(rng, 8)
    C = cache_cfg.capacity
    # serving feeds RAW lo32 ids; pad positions use an out-of-pass id
    lo = seq.astype(np.uint32)
    pad = np.arange(T)[None, :] >= lengths[:, None]
    lo = np.where(pad, np.uint32(0xFFFFFF), lo)
    u = np.asarray(sess(jnp.asarray(lo), jnp.asarray(lengths, jnp.int32)))
    v = np.asarray(item(jnp.asarray(target[:, None].astype(np.uint32))))
    assert u.shape == (8, 8) and v.shape == (8, 8)
    np.testing.assert_allclose(np.linalg.norm(u, axis=1), 1.0, atol=1e-3)
    np.testing.assert_allclose(np.linalg.norm(v, axis=1), 1.0, atol=1e-3)

    # in-process oracle through the training forward
    rows_seq = cache.lookup(item_keys(seq.reshape(-1))).reshape(
        seq.shape).astype(np.int32)
    rows_seq = np.where(pad, C, rows_seq)
    rows_tgt = cache.lookup(item_keys(target)).astype(np.int32)
    emb_seq = cache_pull(cache.state, jnp.asarray(rows_seq.reshape(-1))
                         ).reshape(8, T, -1)
    emb_tgt = cache_pull(cache.state, jnp.asarray(rows_tgt))
    (u_ref, v_ref), _ = nn.functional_call(
        model, {"params": dict(model.named_parameters()), "buffers": {}},
        emb_seq, emb_tgt, jnp.asarray(lengths), training=False)
    np.testing.assert_allclose(u, np.asarray(u_ref), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(v, np.asarray(v_ref), rtol=1e-5, atol=1e-5)

    # refresh_only: tables move, programs byte-identical, vectors move
    prog = tmp_path / "session" / "model.stablehlo"
    before = prog.read_bytes()
    cache.state["embedx_w"] = cache.state["embedx_w"] * 2.0
    export_gru4rec_towers(str(tmp_path), model, cache, max_len=T,
                          refresh_only=True)
    assert prog.read_bytes() == before
    u2 = np.asarray(load_inference_model(str(tmp_path / "session"))(
        jnp.asarray(lo), jnp.asarray(lengths, jnp.int32)))
    assert not np.allclose(u2, u)
