"""io/fs tests — LocalFS behavior + HDFSClient shell contract via a fake
``hadoop`` binary (the reference tests HDFSClient the same way:
fleet/utils/fs.py tests stub the hadoop shell)."""

import os
import stat

import pytest

from paddle_tpu.core.enforce import ExecuteError
from paddle_tpu.io.fs import FS, HDFSClient, LocalFS


@pytest.fixture
def lfs():
    return LocalFS()


def test_local_roundtrip(lfs, tmp_path):
    d = tmp_path / "a" / "b"
    lfs.mkdirs(str(d))
    assert lfs.is_dir(str(d))
    f = d / "x.txt"
    lfs.touch(str(f))
    assert lfs.is_file(str(f))
    dirs, files = lfs.ls_dir(str(d.parent))
    assert dirs == ["b"] and files == []
    dirs, files = lfs.ls_dir(str(d))
    assert files == ["x.txt"]
    lfs.mv(str(f), str(d / "y.txt"))
    assert lfs.is_exist(str(d / "y.txt")) and not lfs.is_exist(str(f))
    lfs.delete(str(d))
    assert not lfs.is_exist(str(d))


def test_local_mv_refuses_overwrite(lfs, tmp_path):
    a, b = tmp_path / "a", tmp_path / "b"
    a.write_text("1")
    b.write_text("2")
    with pytest.raises(ExecuteError):
        lfs.mv(str(a), str(b))
    lfs.mv(str(a), str(b), overwrite=True)
    assert b.read_text() == "1"


def test_local_upload_download(lfs, tmp_path):
    src = tmp_path / "src.txt"
    src.write_text("data")
    lfs.upload(str(src), str(tmp_path / "store" / "src.txt"))
    lfs.download(str(tmp_path / "store" / "src.txt"), str(tmp_path / "back.txt"))
    assert (tmp_path / "back.txt").read_text() == "data"


FAKE_HADOOP = """#!/bin/bash
# fake `hadoop fs` over a local root for contract tests
shift  # drop "fs"
ROOT="$FAKE_HDFS_ROOT"
cmd="$1"; shift
case "$cmd" in
  -mkdir) [ "$1" = "-p" ] && shift; mkdir -p "$ROOT/$1";;
  -test)
    flag="$1"; p="$ROOT/$2"
    case "$flag" in
      -e) [ -e "$p" ] ;;
      -d) [ -d "$p" ] ;;
    esac
    exit $? ;;
  -touchz) : > "$ROOT/$1";;
  -rm) [ "$1" = "-r" ] && shift; [ "$1" = "-f" ] && shift; rm -rf "$ROOT/$1";;
  -mv) mv "$ROOT/$1" "$ROOT/$2";;
  -put) [ "$1" = "-f" ] && shift; cp -r "$1" "$ROOT/$2";;
  -get) cp -r "$ROOT/$1" "$2";;
  -ls)
    p="$ROOT/$1"
    [ -e "$p" ] || exit 1
    for e in "$p"/*; do
      [ -e "$e" ] || continue
      if [ -d "$e" ]; then perm="drwxr-xr-x"; else perm="-rw-r--r--"; fi
      echo "$perm 1 u g 0 2026-01-01 00:00 $1/$(basename "$e")"
    done ;;
  *) echo "unknown $cmd" >&2; exit 2;;
esac
"""


@pytest.fixture
def hdfs(tmp_path):
    bin_path = tmp_path / "hadoop"
    bin_path.write_text(FAKE_HADOOP)
    bin_path.chmod(bin_path.stat().st_mode | stat.S_IEXEC)
    root = tmp_path / "hdfs_root"
    root.mkdir()
    os.environ["FAKE_HDFS_ROOT"] = str(root)
    client = HDFSClient(hadoop_bin=str(bin_path), retry_times=1,
                        time_out_ms=10_000, sleep_inter_ms=10)
    assert client.available()
    return client


def test_hdfs_contract(hdfs, tmp_path):
    hdfs.mkdirs("models/day1")
    assert hdfs.is_exist("models/day1") and hdfs.is_dir("models/day1")
    hdfs.touch("models/day1/donefile")
    assert hdfs.is_file("models/day1/donefile")
    dirs, files = hdfs.ls_dir("models")
    assert dirs == ["day1"]
    dirs, files = hdfs.ls_dir("models/day1")
    assert files == ["donefile"]
    local = tmp_path / "local.txt"
    local.write_text("table data")
    hdfs.upload(str(local), "models/day1/part-0")
    back = tmp_path / "back.txt"
    hdfs.download("models/day1/part-0", str(back))
    assert back.read_text() == "table data"
    hdfs.mv("models/day1", "models/day2")
    assert hdfs.is_exist("models/day2") and not hdfs.is_exist("models/day1")
    hdfs.delete("models")
    assert not hdfs.is_exist("models")


def test_hdfs_unavailable_binary():
    client = HDFSClient(hadoop_bin="/nonexistent/hadoop", retry_times=1,
                        sleep_inter_ms=1)
    assert not client.available()
    with pytest.raises(ExecuteError):
        client.mkdirs("x")
