"""VGG + MobileNet families (reference: paddle/vision/models/vgg.py,
mobilenetv1.py, mobilenetv2.py): shape contracts, jit-ability, and a
small training sanity check."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.models import (
    MobileNetV1,
    MobileNetV2,
    mobilenet_v1,
    mobilenet_v2,
    vgg11,
    vgg16,
)


def _forward(model, hw=32, n=2):
    state = nn.get_state(model)
    x = jnp.zeros((n, 3, hw, hw), jnp.float32)

    @jax.jit
    def fwd(state, x):
        out, _ = nn.functional_call(model, state, x, training=False)
        return out

    return fwd(state, x)


def test_vgg11_shapes():
    pt.seed(0)
    # classifier head expects the canonical 224 input (7x7 after 5 pools)
    out = _forward(vgg11(num_classes=10), hw=224, n=1)
    assert out.shape == (1, 10)


def test_vgg16_bn_shapes():
    pt.seed(0)
    out = _forward(vgg16(batch_norm=True, num_classes=7), hw=224, n=1)
    assert out.shape == (1, 7)


def test_vgg_headless():
    pt.seed(0)
    out = _forward(vgg11(num_classes=0, with_pool=False), hw=64)
    assert out.shape == (2, 512, 2, 2)


def test_mobilenet_v1_shapes_and_scale():
    pt.seed(0)
    assert _forward(mobilenet_v1(num_classes=10), hw=64).shape == (2, 10)
    m = MobileNetV1(scale=0.5, num_classes=5)
    assert _forward(m, hw=64).shape == (2, 5)
    # width multiplier halves channel counts
    assert m.fc.weight.shape[0] == 512


def test_mobilenet_v2_shapes():
    pt.seed(0)
    assert _forward(mobilenet_v2(num_classes=10), hw=64).shape == (2, 10)
    assert _forward(MobileNetV2(scale=0.75, num_classes=4), hw=64).shape == (2, 4)


def test_mobilenet_v2_residual_structure():
    m = MobileNetV2()
    blocks = [b for b in m.features
              if b.__class__.__name__ == "_InvertedResidual"]
    assert len(blocks) == 17  # sum of n in the settings table
    assert sum(b.use_res for b in blocks) == 10  # stride-1 same-ch blocks


def test_mobilenet_trains():
    pt.seed(0)
    from paddle_tpu.executor import Trainer

    model = MobileNetV1(scale=0.25, num_classes=4)
    tr = Trainer(model, optimizer.Adam(2e-3), nn.functional.cross_entropy)
    rng = np.random.default_rng(0)
    first = last = None
    for _ in range(12):
        y = rng.integers(0, 4, 16)
        x = rng.normal(0, 0.2, (16, 3, 32, 32)).astype(np.float32)
        x[np.arange(16), 0, 0, 0] += y  # class-dependent pixel
        loss = float(tr.train_step(x, y))
        first = first if first is not None else loss
        last = loss
    assert last < first, (first, last)


# -- round-2 additions: alexnet / googlenet / squeezenet / densenet /
# shufflenetv2 (reference paddle/vision/models parity) -----------------


@pytest.mark.slow
@pytest.mark.parametrize("ctor,hw", [
    # alexnet's 6x6 adaptive pool needs the canonical 224 input
    (lambda: pt.models.alexnet(num_classes=10), 224),
    (lambda: pt.models.squeezenet1_1(num_classes=10), 96),
    (lambda: pt.models.shufflenet_v2_x0_25(num_classes=10), 64),
])
def test_new_families_forward_shapes(ctor, hw):
    pt.seed(0)
    out = _forward(ctor(), hw=hw, n=2)
    assert out.shape == (2, 10)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
def test_googlenet_main_and_aux():
    pt.seed(0)
    m = pt.models.googlenet(num_classes=10, with_aux=True)
    state = nn.get_state(m)
    # aux heads adaptive-pool to 4x4: input 128 -> 8x8 at the aux taps
    # (divisible; 96 -> 6x6 is not)
    x = jnp.zeros((1, 3, 128, 128), jnp.float32)

    @jax.jit
    def fwd(state, x):
        (out, a1, a2), _ = nn.functional_call(m, state, x, training=True,
                                              rng=jax.random.key(0))
        return out, a1, a2

    out, a1, a2 = fwd(state, x)
    assert out.shape == a1.shape == a2.shape == (1, 10)


@pytest.mark.slow
def test_densenet121_forward():
    pt.seed(0)
    out = _forward(pt.models.densenet121(num_classes=10), hw=64, n=1)
    assert out.shape == (1, 10)
    assert np.isfinite(np.asarray(out)).all()


def test_new_optimizers_learn():
    """Adadelta/Adamax step a tiny regression problem downhill."""
    pt.seed(0)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64, 4)).astype(np.float32))
    w_true = jnp.asarray(rng.normal(size=(4, 1)).astype(np.float32))
    y = x @ w_true
    # Adadelta starts slowly by design (update magnitude bootstraps from
    # the accumulated-update estimate) — give it more steps
    for opt, steps, gate in ((optimizer.Adadelta(learning_rate=1.0), 300, 0.7),
                             (optimizer.Adamax(learning_rate=0.1), 60, 0.5)):
        model = nn.Linear(4, 1)
        from paddle_tpu.executor import Trainer

        tr = Trainer(model, opt, nn.functional.mse_loss)
        first = float(tr.train_step(x, y))
        for _ in range(steps):
            last = float(tr.train_step(x, y))
        assert last < first * gate, (type(opt).__name__, first, last)
