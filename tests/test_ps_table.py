"""PS table stack: sgd rules, accessor lifecycle, sparse/dense/geo/aux
tables (reference: distributed/test/ sparse_sgd_rule_test.cc,
ctr_accessor_test.cc, memory_sparse_table_test.cc, dense_table_test.cc,
barrier_table_test.cc)."""

import numpy as np
import pytest

from paddle_tpu.ps.accessor import AccessorConfig, CtrCommonAccessor, SparseAccessor
from paddle_tpu.ps.sgd_rule import SGDRuleConfig, make_sgd_rule
from paddle_tpu.ps.table import (
    BarrierTable,
    GlobalStepTable,
    MemoryDenseTable,
    MemorySparseGeoTable,
    MemorySparseTable,
    TableConfig,
)


# -- sgd rules ------------------------------------------------------------


def test_naive_rule_update_and_bounds():
    rule = make_sgd_rule("naive", 4, SGDRuleConfig(learning_rate=1.0, weight_bounds=(-1, 1)))
    w = np.zeros((2, 4), np.float32)
    st = np.zeros((2, 0), np.float32)
    rule.update(w, st, np.full((2, 4), 0.5, np.float32), np.ones(2, np.float32))
    np.testing.assert_allclose(w, -0.5)
    rule.update(w, st, np.full((2, 4), 5.0, np.float32), np.ones(2, np.float32))
    np.testing.assert_allclose(w, -1.0)  # clipped


def test_adagrad_rule_shared_g2sum():
    cfg = SGDRuleConfig(learning_rate=0.1, initial_g2sum=3.0)
    rule = make_sgd_rule("adagrad", 2, cfg)
    w = np.zeros((1, 2), np.float32)
    st = np.zeros((1, 1), np.float32)
    g = np.asarray([[1.0, 2.0]], np.float32)
    scale = np.asarray([2.0], np.float32)
    rule.update(w, st, g, scale)
    scaled = g / 2.0
    expect_w = -0.1 * scaled * np.sqrt(3.0 / 3.0)
    np.testing.assert_allclose(w, expect_w, rtol=1e-6)
    np.testing.assert_allclose(st[0, 0], np.mean(scaled**2), rtol=1e-6)


def test_std_adagrad_per_dim_state():
    rule = make_sgd_rule("std_adagrad", 3)
    assert rule.state_dim == 3


def test_adam_rule_converges():
    rule = make_sgd_rule("adam", 4, SGDRuleConfig(learning_rate=0.05))
    rng = np.random.default_rng(0)
    w, st = rule.init_value(1, rng)
    target = np.asarray([[1.0, -1.0, 0.5, 2.0]], np.float32)
    for _ in range(500):
        g = w - target
        rule.update(w, st, g, np.ones(1, np.float32))
    np.testing.assert_allclose(w, target, atol=0.05)


# -- accessor -------------------------------------------------------------


def make_push(n, dim, show=1.0, click=0.0, g=0.1, slot=3):
    push = np.zeros((n, 4 + dim), np.float32)
    push[:, 0] = slot
    push[:, 1] = show
    push[:, 2] = click
    push[:, 3] = g
    push[:, 4:] = g
    return push


def test_ctr_accessor_push_updates_stats_and_lazy_embedx():
    cfg = AccessorConfig(embedx_dim=4, embedx_threshold=5.0)
    table = MemorySparseTable(TableConfig(shard_num=2, accessor_config=cfg))
    keys = np.asarray([11, 22, 33], np.uint64)
    vals = table.pull_sparse(keys)
    assert vals.shape == (3, table.accessor.pull_dim)
    # fresh rows: zero show/click, embedx absent
    np.testing.assert_allclose(vals[:, 0], 0.0)
    np.testing.assert_allclose(vals[:, 3:], 0.0)

    # below embedx threshold: one click-less push
    table.push_sparse(keys, make_push(3, 4, show=1.0))
    v1 = table.pull_sparse(keys)
    np.testing.assert_allclose(v1[:, 0], 1.0)  # show accumulated
    np.testing.assert_allclose(v1[:, 3:], 0.0)  # embedx still lazy

    # heavy clicks push score over threshold -> embedx materializes
    table.push_sparse(keys, make_push(3, 4, show=10.0, click=10.0))
    v2 = table.pull_sparse(keys)
    assert np.abs(v2[:, 3:]).sum() > 0


def test_sparse_accessor_pull_drops_stats():
    acc = SparseAccessor(AccessorConfig(embedx_dim=4))
    assert acc.pull_dim == 5  # embed_w + embedx


def test_insert_on_miss_and_no_create_lookup():
    table = MemorySparseTable(TableConfig(shard_num=4))
    keys = np.asarray([7, 8], np.uint64)
    table.pull_sparse(keys, create=True)
    assert table.size() == 2
    table.pull_sparse(np.asarray([9], np.uint64), create=False)
    assert table.size() == 2  # no-create lookup doesn't insert


def test_push_merges_duplicate_keys():
    table = MemorySparseTable(TableConfig(shard_num=2))
    keys = np.asarray([5, 5, 5], np.uint64)
    table.push_sparse(keys, make_push(3, 8, show=1.0))
    v = table.pull_sparse(np.asarray([5], np.uint64))
    np.testing.assert_allclose(v[0, 0], 3.0)  # shows summed across dups


def test_save_load_roundtrip(tmp_path):
    cfg = AccessorConfig(embedx_dim=4, embedx_threshold=0.5)
    table = MemorySparseTable(TableConfig(shard_num=4, accessor_config=cfg))
    keys = np.asarray([101, 202, 303, 404], np.uint64)
    table.pull_sparse(keys)
    table.push_sparse(keys, make_push(4, 4, show=5.0, click=3.0))
    before = table.pull_sparse(keys)
    n = table.save(str(tmp_path / "model"), mode=0)
    assert n == 4

    table2 = MemorySparseTable(TableConfig(shard_num=4, accessor_config=cfg))
    loaded = table2.load(str(tmp_path / "model"))
    assert loaded == 4
    after = table2.pull_sparse(keys)
    np.testing.assert_allclose(after, before, rtol=1e-5)


def test_save_load_gzip_converter(tmp_path):
    """The DataConverter role (reference accessor.h:42/95/141,
    afs_warpper.h:123): save pipes shard files through a named
    converter; load reads the converter from meta.json. Round-trip is
    value-exact and the files really are gzip."""
    import gzip
    import os

    cfg = AccessorConfig(embedx_dim=4, embedx_threshold=0.5)
    table = MemorySparseTable(TableConfig(shard_num=4, accessor_config=cfg))
    keys = np.asarray([101, 202, 303, 404], np.uint64)
    table.pull_sparse(keys)
    table.push_sparse(keys, make_push(4, 4, show=5.0, click=3.0))
    before = table.pull_sparse(keys)
    n = table.save(str(tmp_path / "gz"), mode=0, converter="gzip")
    assert n == 4
    part = tmp_path / "gz" / "part-00000.shard.gz"
    assert os.path.exists(part)
    with gzip.open(part, "rt") as f:
        f.read()  # decodes as real gzip text

    table2 = MemorySparseTable(TableConfig(shard_num=4, accessor_config=cfg))
    assert table2.load(str(tmp_path / "gz")) == 4
    np.testing.assert_allclose(table2.pull_sparse(keys), before, rtol=1e-5)

    # config-level default (TableConfig.converter) applies without an arg
    t3 = MemorySparseTable(TableConfig(shard_num=2, accessor_config=cfg,
                                       converter="gzip"))
    t3.pull_sparse(keys)
    t3.push_sparse(keys, make_push(4, 4, show=2.0))
    t3.save(str(tmp_path / "gz2"))
    assert os.path.exists(tmp_path / "gz2" / "part-00000.shard.gz")


def test_save_mode_delta_filters(tmp_path):
    cfg = AccessorConfig(embedx_dim=2, base_threshold=5.0, delta_threshold=1.0)
    table = MemorySparseTable(TableConfig(shard_num=2, accessor_config=cfg))
    hot = np.asarray([1], np.uint64)
    cold = np.asarray([2], np.uint64)
    table.push_sparse(hot, make_push(1, 2, show=20.0, click=10.0))
    table.push_sparse(cold, make_push(1, 2, show=0.1, click=0.0))
    n = table.save(str(tmp_path / "delta"), mode=1)
    assert n == 1  # only the hot feature passes the delta filter


def test_shrink_deletes_stale():
    cfg = AccessorConfig(
        embedx_dim=2, delete_threshold=0.5, show_click_decay_rate=0.1,
        delete_after_unseen_days=2,
    )
    table = MemorySparseTable(TableConfig(shard_num=2, accessor_config=cfg))
    keys = np.asarray([1, 2, 3], np.uint64)
    table.push_sparse(keys, make_push(3, 2, show=0.5))
    # aggressive decay: one shrink round kills low-score features
    deleted = table.shrink()
    assert deleted == 3
    assert table.size() == 0


# -- dense/geo/aux tables -------------------------------------------------


def test_dense_table_adam():
    t = MemoryDenseTable(4, optimizer="adam", lr=0.1)
    target = np.asarray([1.0, 2.0, -1.0, 0.5], np.float32)
    for _ in range(300):
        t.push_dense(t.pull_dense() - target)
    np.testing.assert_allclose(t.pull_dense(), target, atol=0.05)


def test_geo_table_accumulates_and_drains():
    t = MemorySparseGeoTable(4)
    keys = np.asarray([1, 2], np.uint64)
    t.push_delta(keys, np.ones((2, 4), np.float32))
    t.push_delta(np.asarray([1], np.uint64), np.ones((1, 4), np.float32) * 3)
    k, d = t.pull_geo()
    got = {int(kk): dd for kk, dd in zip(k, d)}
    np.testing.assert_allclose(got[1], 2.0)  # (1+3)/2 pushes
    np.testing.assert_allclose(got[2], 1.0)
    k2, _ = t.pull_geo()
    assert len(k2) == 0  # drained


def test_barrier_and_global_step():
    import threading

    b = BarrierTable(2)
    done = []

    def worker():
        b.barrier(timeout=5)
        done.append(1)

    th = threading.Thread(target=worker)
    th.start()
    b.barrier(timeout=5)
    th.join()
    assert len(done) == 1

    lrs = []
    g = GlobalStepTable(decay_fn=lambda s: lrs.append(s))
    g.push_step(5)
    g.push_step(3)
    assert g.step == 8 and lrs == [5, 8]


def test_accessor_defaults_match_reference_constants():
    """The CtrAccessor lifecycle defaults are parity-critical (SURVEY
    Appendix A; reference CtrAccessorParameter defaults in
    distributed/ps.proto / the_one_ps table config): pin them so a
    refactor cannot silently drift the training semantics."""
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig

    a = AccessorConfig()
    assert a.nonclk_coeff == pytest.approx(0.1)
    assert a.click_coeff == pytest.approx(1.0)
    assert a.base_threshold == pytest.approx(1.5)
    assert a.delta_threshold == pytest.approx(0.25)
    assert a.delta_keep_days == pytest.approx(16.0)
    assert a.show_click_decay_rate == pytest.approx(0.98)
    assert a.delete_threshold == pytest.approx(0.8)
    assert a.delete_after_unseen_days == pytest.approx(30.0)
    assert a.embedx_dim == 8
    assert a.embedx_threshold == pytest.approx(10.0)
    assert a.embed_sgd_rule == "adagrad" and a.embedx_sgd_rule == "adagrad"

    s = SGDRuleConfig()
    assert s.learning_rate == pytest.approx(0.05)
    assert s.initial_g2sum == pytest.approx(3.0)
    assert s.initial_range == pytest.approx(1e-4)
    assert tuple(s.weight_bounds) == (-10.0, 10.0)
