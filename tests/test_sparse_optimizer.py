"""Pallas fused CTR row kernel (ops/sparse_optimizer.py) vs the jnp
path — parity of the optimizer.cuh.h / sparse_sgd_rule.cc math across
the whole rule family (interpret mode on the CPU mesh, same discipline
as the flash-attention tests) — plus device-vs-host-table parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.sparse_optimizer import (ctr_sparse_rows,
                                             rule_state_dim)
from paddle_tpu.ps.embedding_cache import CacheConfig, cache_push
from paddle_tpu.ps.sgd_rule import SGDRuleConfig

RULES = ["naive", "adagrad", "std_adagrad", "adam"]


def _state(rng, C, dim, embed_rule="adagrad", embedx_rule="adagrad"):
    es = rule_state_dim(embed_rule, 1)
    xs = rule_state_dim(embedx_rule, dim)
    st = {
        "show": jnp.asarray(rng.uniform(0, 5, C).astype(np.float32)),
        "click": jnp.asarray(rng.uniform(0, 2, C).astype(np.float32)),
        "embed_w": jnp.asarray(rng.normal(size=(C, 1)).astype(np.float32)),
        "embed_state": jnp.asarray(rng.uniform(0, 1, (C, es)).astype(np.float32)),
        "embedx_w": jnp.asarray(rng.normal(size=(C, dim)).astype(np.float32)),
        "embedx_state": jnp.asarray(rng.uniform(0, 1, (C, xs)).astype(np.float32)),
        "has_embedx": jnp.asarray((rng.random(C) < 0.5).astype(np.float32)),
    }
    if embed_rule == "adam" and es:
        st["embed_state"] = st["embed_state"].at[:, -2:].set(0.9)
    if embedx_rule == "adam" and xs:
        st["embedx_state"] = st["embedx_state"].at[:, -2:].set(0.9)
    return st


@pytest.mark.parametrize("create_applies_grad", [True, False])
@pytest.mark.parametrize("embed_rule,embedx_rule",
                         [(r, r) for r in RULES] + [("adagrad", "adam"),
                                                    ("naive", "std_adagrad")])
def test_pallas_push_matches_jnp(rng, embed_rule, embedx_rule,
                                 create_applies_grad):
    C, dim, n = 512, 4, 300
    state = _state(rng, C, dim, embed_rule, embedx_rule)
    rows = jnp.asarray(rng.integers(0, C, n), jnp.int32)
    grads = jnp.asarray(rng.normal(size=(n, 1 + dim)).astype(np.float32))
    shows = jnp.ones((n,), jnp.float32)
    clicks = jnp.asarray((rng.random(n) < 0.4).astype(np.float32))

    kw = dict(capacity=C, embedx_dim=dim, embedx_threshold=3.0,
              embed_rule=embed_rule, embedx_rule=embedx_rule,
              create_applies_grad=create_applies_grad)
    # pin the merge_grad-shaped path: "auto" would resolve to the dense
    # push on TPU backends, which never calls the Pallas kernel — these
    # tests exist to cover ctr_sparse_rows
    cfg_j = CacheConfig(pallas_update=False, push_mode="sparse", **kw)
    cfg_p = CacheConfig(pallas_update=True, push_mode="sparse", **kw)
    a = jax.jit(lambda st: cache_push(st, rows, grads, shows, clicks, cfg_j))(state)
    b = jax.jit(lambda st: cache_push(st, rows, grads, shows, clicks, cfg_p))(state)
    for k in a:
        np.testing.assert_allclose(np.asarray(b[k]), np.asarray(a[k]),
                                   rtol=1e-6, atol=1e-7, err_msg=k)
    # lifecycle flags are exact
    np.testing.assert_array_equal(np.asarray(b["has_embedx"]),
                                  np.asarray(a["has_embedx"]))


@pytest.mark.parametrize("rule", RULES)
def test_cache_push_matches_host_table(rng, rule):
    """Device cache push == host MemorySparseTable push for the same
    merged records, for every rule (the parity-critical A.2 math)."""
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.embedding_cache import HbmEmbeddingCache
    from paddle_tpu.ps.table import MemorySparseTable, TableConfig

    dim = 4
    acc = AccessorConfig(embedx_dim=dim, embedx_threshold=0.0,
                         embed_sgd_rule=rule, embedx_sgd_rule=rule,
                         sgd=SGDRuleConfig(initial_range=0.0))
    mirror = MemorySparseTable(TableConfig(shard_num=2, accessor_config=acc))
    backing = MemorySparseTable(TableConfig(shard_num=2, accessor_config=acc))
    cache = HbmEmbeddingCache(backing, CacheConfig(
        capacity=256, embedx_dim=dim, embedx_threshold=0.0,
        embed_rule=rule, embedx_rule=rule))

    keys = np.arange(1, 101, dtype=np.uint64)
    cache.begin_pass(keys)
    for it in range(3):
        bkeys = rng.integers(1, 101, size=64).astype(np.uint64)
        push = np.zeros((64, 4 + dim), np.float32)
        push[:, 1] = 1.0
        push[:, 2] = (rng.random(64) < 0.4).astype(np.float32)
        push[:, 3:] = rng.normal(size=(64, 1 + dim)).astype(np.float32)
        mirror.push_sparse(bkeys, push)

        rows = jnp.asarray(cache.lookup(bkeys), jnp.int32)
        cache.state = cache_push(
            cache.state, rows, jnp.asarray(push[:, 3:]),
            jnp.asarray(push[:, 1]), jnp.asarray(push[:, 2]), cache.config)
    cache.end_pass()

    np.testing.assert_allclose(
        backing.pull_sparse(keys, create=False),
        mirror.pull_sparse(keys, create=False), rtol=1e-5, atol=1e-6)


def test_pallas_push_unaligned_n(rng):
    # n not a multiple of the kernel block exercises the padded tail —
    # drive the kernel directly with block=64 over n=300
    C, dim, n = 256, 8, 300
    state = _state(rng, C, dim)
    srows = jnp.asarray(rng.integers(0, C, n), jnp.int32)
    gathered = tuple(state[k][srows] for k in
                     ("show", "click", "embed_w", "embed_state",
                      "embedx_w", "embedx_state", "has_embedx"))
    dshow = jnp.ones((n,), jnp.float32)
    dclick = jnp.asarray((rng.random(n) < 0.3).astype(np.float32))
    ge = jnp.asarray(rng.normal(size=(n, 1)).astype(np.float32))
    gx = jnp.asarray(rng.normal(size=(n, dim)).astype(np.float32))
    kw = dict(embed_rule="adagrad", embedx_rule="adagrad",
              lr=0.05, initial_g2sum=3.0, weight_bounds=(-10.0, 10.0),
              beta1=0.9, beta2=0.999, eps=1e-8,
              nonclk_coeff=0.1, click_coeff=1.0, embedx_threshold=0.0)
    small = ctr_sparse_rows(gathered, dshow, dclick, ge, gx, block=64, **kw)
    full = ctr_sparse_rows(gathered, dshow, dclick, ge, gx, block=1024, **kw)
    for a, b in zip(small, full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_pallas_push_in_cache_small(rng):
    C, dim, n = 256, 8, 129
    state = _state(rng, C, dim)
    rows = jnp.asarray(rng.integers(0, C, n), jnp.int32)
    grads = jnp.asarray(rng.normal(size=(n, 1 + dim)).astype(np.float32))
    shows = jnp.ones((n,), jnp.float32)
    clicks = jnp.zeros((n,), jnp.float32)
    cfg = CacheConfig(capacity=C, embedx_dim=dim, embedx_threshold=0.0,
                      pallas_update=True, push_mode="sparse")
    cfg_ref = CacheConfig(capacity=C, embedx_dim=dim, embedx_threshold=0.0,
                          pallas_update=False, push_mode="sparse")
    b = jax.jit(lambda st: cache_push(st, rows, grads, shows, clicks, cfg))(state)
    a = jax.jit(lambda st: cache_push(st, rows, grads, shows, clicks, cfg_ref))(state)
    for k in a:
        np.testing.assert_allclose(np.asarray(b[k]), np.asarray(a[k]),
                                   rtol=1e-6, atol=1e-7, err_msg=k)


@pytest.mark.parametrize("create_applies_grad", [True, False])
@pytest.mark.parametrize("embed_rule,embedx_rule",
                         [(r, r) for r in RULES] + [("adagrad", "adam"),
                                                    ("naive", "std_adagrad")])
def test_dense_push_matches_sparse(rng, embed_rule, embedx_rule,
                                   create_applies_grad):
    """push_mode="dense" (scatter-add + masked full-table update — the
    TPU hot path) == push_mode="sparse" (the merge_grad shape) up to f32
    re-association of duplicate-row sums, including: heavy duplicates,
    the capacity sentinel, zero-show masked padding rows (must stay
    bit-untouched), and untouched rows (must stay bit-untouched)."""
    C, dim, n = 512, 4, 600
    state = _state(rng, C, dim, embed_rule, embedx_rule)
    # heavy duplication (rows drawn from 64) + sentinel padding tail
    rows = rng.integers(0, 64, n).astype(np.int32)
    rows[-40:] = C  # missing-key / padding sentinel
    # row 100 appears ONLY at masked positions: both paths must still
    # apply the rule at zero delta (Adam decays m/v) — batch presence,
    # not show, decides "touched"
    rows[10:20] = 100
    rows = jnp.asarray(rows)
    grads = rng.normal(size=(n, 1 + dim)).astype(np.float32)
    shows = np.ones((n,), np.float32)
    # a masked (weight=0) position ships zero show AND zero grad
    shows[10:20] = 0.0
    grads[10:20] = 0.0
    clicks = (rng.random(n) < 0.4).astype(np.float32) * shows
    grads, shows, clicks = map(jnp.asarray, (grads, shows, clicks))

    kw = dict(capacity=C, embedx_dim=dim, embedx_threshold=3.0,
              embed_rule=embed_rule, embedx_rule=embedx_rule,
              create_applies_grad=create_applies_grad,
              pallas_update=False)
    cfg_s = CacheConfig(push_mode="sparse", **kw)
    cfg_d = CacheConfig(push_mode="dense", **kw)
    a = jax.jit(lambda st: cache_push(st, rows, grads, shows, clicks, cfg_s))(state)
    b = jax.jit(lambda st: cache_push(st, rows, grads, shows, clicks, cfg_d))(state)
    for k in a:
        np.testing.assert_allclose(np.asarray(b[k]), np.asarray(a[k]),
                                   rtol=2e-5, atol=1e-6, err_msg=k)
    np.testing.assert_array_equal(np.asarray(b["has_embedx"]),
                                  np.asarray(a["has_embedx"]))
    # rows absent from the batch are bit-identical in the dense path
    touched = np.zeros(C, bool)
    r_np = np.asarray(rows)
    touched[r_np[r_np < C]] = True
    for k in a:
        np.testing.assert_array_equal(
            np.asarray(b[k])[~touched[: C]],
            np.asarray(state[k])[~touched[: C]], err_msg=f"untouched {k}")
