"""Pallas fused CTR AdaGrad row kernel (ops/sparse_optimizer.py) vs the
jnp path — parity of the optimizer.cuh.h math (interpret mode on the CPU
mesh, same discipline as the flash-attention tests)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ps.embedding_cache import CacheConfig, cache_push
from paddle_tpu.ps.sgd_rule import SGDRuleConfig


def _state(rng, C, dim):
    return {
        "show": jnp.asarray(rng.uniform(0, 5, C).astype(np.float32)),
        "click": jnp.asarray(rng.uniform(0, 2, C).astype(np.float32)),
        "embed_w": jnp.asarray(rng.normal(size=(C, 1)).astype(np.float32)),
        "embed_g2sum": jnp.asarray(rng.uniform(0, 1, (C, 1)).astype(np.float32)),
        "embedx_w": jnp.asarray(rng.normal(size=(C, dim)).astype(np.float32)),
        "embedx_g2sum": jnp.asarray(rng.uniform(0, 1, (C, 1)).astype(np.float32)),
        "has_embedx": jnp.asarray((rng.random(C) < 0.5).astype(np.float32)),
    }


def test_pallas_push_matches_jnp(rng):
    C, dim, n = 512, 4, 300
    state = _state(rng, C, dim)
    rows = jnp.asarray(rng.integers(0, C, n), jnp.int32)
    grads = jnp.asarray(rng.normal(size=(n, 1 + dim)).astype(np.float32))
    shows = jnp.ones((n,), jnp.float32)
    clicks = jnp.asarray((rng.random(n) < 0.4).astype(np.float32))

    cfg_j = CacheConfig(capacity=C, embedx_dim=dim, embedx_threshold=3.0,
                        pallas_update=False)
    cfg_p = CacheConfig(capacity=C, embedx_dim=dim, embedx_threshold=3.0,
                        pallas_update=True)
    a = jax.jit(lambda st: cache_push(st, rows, grads, shows, clicks, cfg_j))(state)
    b = jax.jit(lambda st: cache_push(st, rows, grads, shows, clicks, cfg_p))(state)
    for k in a:
        np.testing.assert_allclose(np.asarray(b[k]), np.asarray(a[k]),
                                   rtol=1e-6, atol=1e-7, err_msg=k)
    # lifecycle flags are exact
    np.testing.assert_array_equal(np.asarray(b["has_embedx"]),
                                  np.asarray(a["has_embedx"]))


def test_pallas_push_unaligned_n(rng):
    # n not a multiple of the kernel block exercises the padded tail —
    # cache_push uses the kernel default, so shrink n below it is not
    # enough; drive the kernel directly with block=64 over n=300
    from paddle_tpu.ops.sparse_optimizer import ctr_adagrad_rows

    C, dim, n = 256, 8, 300
    state = _state(rng, C, dim)
    srows = jnp.asarray(rng.integers(0, C, n), jnp.int32)
    gathered = tuple(state[k][srows] for k in
                     ("show", "click", "embed_w", "embed_g2sum",
                      "embedx_w", "embedx_g2sum", "has_embedx"))
    dshow = jnp.ones((n,), jnp.float32)
    dclick = jnp.asarray((rng.random(n) < 0.3).astype(np.float32))
    ge = jnp.asarray(rng.normal(size=(n, 1)).astype(np.float32))
    gx = jnp.asarray(rng.normal(size=(n, dim)).astype(np.float32))
    kw = dict(lr=0.05, initial_g2sum=3.0, weight_bounds=(-10.0, 10.0),
              nonclk_coeff=0.1, click_coeff=1.0, embedx_threshold=0.0)
    small = ctr_adagrad_rows(gathered, dshow, dclick, ge, gx, block=64, **kw)
    full = ctr_adagrad_rows(gathered, dshow, dclick, ge, gx, block=1024, **kw)
    for a, b in zip(small, full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_pallas_push_in_cache_small(rng):
    C, dim, n = 256, 8, 129
    state = _state(rng, C, dim)
    rows = jnp.asarray(rng.integers(0, C, n), jnp.int32)
    grads = jnp.asarray(rng.normal(size=(n, 1 + dim)).astype(np.float32))
    shows = jnp.ones((n,), jnp.float32)
    clicks = jnp.zeros((n,), jnp.float32)
    cfg = CacheConfig(capacity=C, embedx_dim=dim, embedx_threshold=0.0,
                      pallas_update=True)
    cfg_ref = CacheConfig(capacity=C, embedx_dim=dim, embedx_threshold=0.0,
                          pallas_update=False)
    b = jax.jit(lambda st: cache_push(st, rows, grads, shows, clicks, cfg))(state)
    a = jax.jit(lambda st: cache_push(st, rows, grads, shows, clicks, cfg_ref))(state)
    for k in a:
        np.testing.assert_allclose(np.asarray(b[k]), np.asarray(a[k]),
                                   rtol=1e-6, atol=1e-7, err_msg=k)
