"""Heter pipeline trainer (HeterPipelineTrainer/HeterSectionWorker
parity: framework/heter_pipeline_trainer.cc, heter_section_worker.cc):
CPU sections feed device sections through bounded channels."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.parallel.heter_pipeline import HeterPipelineTrainer, SectionConfig


def test_single_thread_sections_preserve_order():
    tr = HeterPipelineTrainer([
        SectionConfig(lambda x: x * 2),
        SectionConfig(lambda x: x + 1),
    ])
    out = tr.run(range(20))
    assert out == [x * 2 + 1 for x in range(20)]


def test_multi_thread_section_processes_all():
    seen = []
    lock = threading.Lock()

    def slow_double(x):
        time.sleep(0.001)
        with lock:
            seen.append(x)
        return x * 2

    tr = HeterPipelineTrainer([SectionConfig(slow_double, num_threads=4)])
    out = tr.run(range(50))
    assert sorted(out) == [x * 2 for x in range(50)]
    assert sorted(seen) == list(range(50))


def test_sections_overlap_in_time():
    """Pipelining: two 10ms sections over 8 items must beat serial."""
    def slow(x):
        time.sleep(0.01)
        return x

    tr = HeterPipelineTrainer([SectionConfig(slow), SectionConfig(slow)])
    t0 = time.monotonic()
    tr.run(range(8))
    elapsed = time.monotonic() - t0
    assert elapsed < 8 * 0.02 * 0.9, elapsed  # overlapped, not serial


def test_error_propagates_without_hanging():
    def boom(x):
        if x == 3:
            raise ValueError("section exploded")
        return x

    tr = HeterPipelineTrainer([SectionConfig(boom)], channel_capacity=2)
    with pytest.raises(ValueError, match="section exploded"):
        tr.run(range(100))


def test_heter_ctr_training_cpu_pull_tpu_train_cpu_push():
    """The HeterPS workload shape: CPU section pulls embeddings from the
    host table, device section runs the jitted dense step, CPU tail
    pushes gradients back (heter_section_worker's cpu->gpu->cpu
    program split)."""
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.table import MemorySparseTable, TableConfig

    pt.seed(0)
    table = MemorySparseTable(TableConfig(shard_num=4,
                                          accessor_config=AccessorConfig(embedx_dim=4)))
    dense = nn.Linear(5 * 5, 1)  # 5 slots x (1+4) dims
    state = nn.get_state(dense)
    opt = optimizer.SGD(0.1)
    opt_state = opt.init(state["params"])
    lock = threading.Lock()
    losses = []

    @jax.jit
    def device_step(params, emb, label):
        def f(p, e):
            out, _ = nn.functional_call(dense, {"params": p, "buffers": {}},
                                        e.reshape(e.shape[0], -1))
            return jnp.mean((out[:, 0] - label) ** 2)
        loss, (gp, ge) = jax.value_and_grad(f, argnums=(0, 1))(params, emb)
        return loss, gp, ge

    def cpu_pull(batch):
        keys, label = batch
        pulled = table.pull_sparse(keys.ravel())
        emb = pulled[:, 2:].reshape(keys.shape[0], 5, 5)
        return keys, jnp.asarray(emb), jnp.asarray(label)

    def tpu_train(item):
        nonlocal opt_state
        keys, emb, label = item
        with lock:  # device section is single-threaded here; lock for clarity
            loss, gp, ge = device_step(state["params"], emb, label)
            new_params, opt_state = opt.update(gp, opt_state, state["params"])
            state["params"] = new_params
            losses.append(float(loss))
        return keys, np.asarray(ge)

    def cpu_push(item):
        keys, ge = item
        n = keys.size
        push = np.zeros((n, 8), np.float32)
        push[:, 1] = 1.0
        push[:, 3:] = ge.reshape(n, 5)[:, :5]
        table.push_sparse(keys.ravel(), push)
        return keys.shape[0]

    rng = np.random.default_rng(0)
    batches = []
    for _ in range(12):
        keys = rng.integers(1, 500, (16, 5)).astype(np.uint64)
        label = (keys.sum(axis=1) % 2).astype(np.float32)
        batches.append((keys, label))

    tr = HeterPipelineTrainer([
        SectionConfig(cpu_pull, place="cpu"),
        SectionConfig(tpu_train, place="tpu"),
        SectionConfig(cpu_push, place="cpu"),
    ])
    out = tr.run(batches)
    assert out == [16] * 12
    assert table.size() > 0
    assert len(losses) == 12 and losses[-1] < losses[0]
