"""Live elastic resharding (ps/reshard.py + the csrc kRetain/
kErrWrongShard fence + RpcPsClient misroute replay).

Fast tier: plans, retain/filtered-digest semantics, the ownership
bounce (typed, breaker-cold), client topology replay through a real
grow and shrink, refusals, the injectable-clock backoff+jitter
satellite, checkpoint-concurrent-with-reshard gate nesting, and the
hot tier keeping its resident set across a cutover.

Slow tier (ci.sh reshard gate / full): THE acceptance e2e — grow 2→4
and shrink back to 2 mid-CtrStreamTrainer (sync replication) with an
armed kill-shard faultpoint during one migration; zero lost/doubled
rows by content digests, final pulled rows + dense params bit-identical
to an unresharded oracle, trainer never observes an error.
"""

import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    not __import__("paddle_tpu.ps.rpc", fromlist=["rpc_available"]
                   ).rpc_available(),
    reason="native PS service unavailable")

from paddle_tpu.core.enforce import (PreconditionNotMetError,  # noqa: E402
                                     WrongShardError)
from paddle_tpu.ps import ha, rpc  # noqa: E402
from paddle_tpu.ps.reshard import (Migration, ReshardController,  # noqa: E402
                                   ReshardError, plan_grow, plan_shrink)
from paddle_tpu.ps.table import TableConfig  # noqa: E402

MASK = 0xFFFFFFFFFFFFFFFF


def _cfg(table_id=0, **kw):
    return TableConfig(table_id=table_id, shard_num=4, accessor="ctr",
                       **kw)


def _seed_rows(cli, n=400, dim=8):
    keys = np.arange(1, n + 1, dtype=np.uint64)
    cli.pull_sparse(0, keys)
    push = np.zeros((n, 4 + dim), np.float32)
    push[:, 1] = 1.0
    push[:, 3:] = 0.01
    cli.push_sparse(0, keys, push)
    return keys


# ---------------------------------------------------------------------------
# plans
# ---------------------------------------------------------------------------

def test_plan_grow_splits_single_source():
    p = plan_grow(2, 2)
    assert (p.old_n, p.new_n) == (2, 4)
    assert p.migrations == (Migration(0, 2, 4, 2), Migration(1, 3, 4, 3))
    # factor 3: every new shard still has exactly one source (d % S)
    p3 = plan_grow(2, 3)
    assert all(m.src == m.dst % 2 for m in p3.migrations)
    assert len(p3.migrations) == 4


def test_plan_shrink_halves_only():
    p = plan_shrink(4, 2)
    assert p.migrations == (Migration(2, 0, 4, 2), Migration(3, 1, 4, 3))
    with pytest.raises(PreconditionNotMetError):
        plan_shrink(8, 4)  # chain halvings instead
    with pytest.raises(PreconditionNotMetError):
        plan_shrink(3, 2)


# ---------------------------------------------------------------------------
# kRetain / filtered digest / ownership fence (single server)
# ---------------------------------------------------------------------------

def test_retain_filtered_digest_and_fence():
    with rpc.NativePsServer() as s:
        cli = rpc.RpcPsClient([f"127.0.0.1:{s.port}"])
        try:
            cli.create_sparse_table(0, _cfg())
            keys = _seed_rows(cli, 100)
            assert cli.ownership(0) == (0, 0)
            d_all = cli.digest_at(0, 0)
            d_even = cli.digest_at(0, 0, 2, 0)
            d_odd = cli.digest_at(0, 0, 2, 1)
            # digests are wrapping SUMS of row hashes: any partition
            # of the key space adds back to the whole
            assert (d_even + d_odd) & MASK == d_all
            erased = cli.retain(0, 2, 0)
            assert erased == 50
            assert cli.ownership(0) == (2, 0)
            assert cli.size(0) == 50
            assert cli.digest_at(0, 0) == d_even
            # non-owned key: whole frame bounces, typed, nothing applied
            with pytest.raises(WrongShardError):
                cli.pull_sparse(0, np.array([3], np.uint64))
            assert cli.size(0) == 50
            cli.pull_sparse(0, np.array([4], np.uint64))  # owned: fine
            # fence-out (-1): retiring shard answers everything with
            # the bounce but keeps its rows
            assert cli.retain(0, 2, -1) == 0
            with pytest.raises(WrongShardError):
                cli.pull_sparse(0, np.array([4], np.uint64))
            assert cli.size(0) == 50
        finally:
            cli.close()
            s.stop()


def test_wrong_shard_bounce_rejects_frame_whole():
    # one bad key poisons the whole frame BEFORE any apply: the push's
    # good keys must not land (the exactly-once replay contract)
    with rpc.NativePsServer() as s:
        cli = rpc.RpcPsClient([f"127.0.0.1:{s.port}"])
        try:
            cli.create_sparse_table(0, _cfg())
            keys = _seed_rows(cli, 10)
            cli.retain(0, 2, 0)
            d0 = cli.digest_at(0, 0)
            mixed = np.array([2, 4, 5], np.uint64)  # 5 is non-owned
            push = np.zeros((3, 12), np.float32)
            push[:, 1] = 1.0
            with pytest.raises(WrongShardError):
                cli.push_sparse(0, mixed, push)
            assert cli.digest_at(0, 0) == d0  # keys 2/4 unchanged too
        finally:
            cli.close()
            s.stop()


def test_wrong_shard_is_not_a_transport_error():
    # the server ANSWERED: breaker stays cold, no failover wait
    with ha.HACluster(num_shards=1, replication=1, sync=False) as c:
        cli = c.client()
        cli.create_sparse_table(0, _cfg())
        _seed_rows(cli, 10)
        ep = c.primary(0).endpoint
        c.primary(0).server._lib  # touch to keep handle alive
        # fence the shard by hand; the router-less replay cannot kick
        # in for a 1-shard router whose routing never changes, so the
        # bounce surfaces after the hop budget — but the breaker must
        # stay CLOSED throughout (server-side rejection, not death)
        conn = rpc.make_conn(ep)
        try:
            conn.check(rpc._RETAIN, n=2, aux=0, retries=0)
        finally:
            conn.close()
        with pytest.raises(WrongShardError):
            cli.pull_sparse(0, np.array([3], np.uint64), create=False)
        assert cli._router.breaker(ep).state == ha.CircuitBreaker.CLOSED


# ---------------------------------------------------------------------------
# live grow / shrink with a stale client (the misroute replay)
# ---------------------------------------------------------------------------

def test_grow_and_shrink_preserve_rows_and_reroute_clients():
    with ha.HACluster(num_shards=2, replication=2, sync=True) as c:
        cli = c.client()
        cli.create_sparse_table(0, _cfg())
        keys = _seed_rows(cli)
        rows = cli.size(0)
        d_before = sum(cli.digest(0)) & MASK
        ctrl = ReshardController(c)
        rec = ctrl.grow(2)
        assert rec["to_shards"] == 4 and c.num_shards == 4
        assert rec["rows_moved"] > 0
        # the STALE client's next ops bounce, re-resolve, and replay —
        # and the client's topology follows the routing table
        pulled4 = cli.pull_sparse(0, keys, create=False)
        assert cli.num_servers == 4
        assert cli.size(0) == rows
        assert (sum(cli.digest(0)) & MASK) == d_before
        # ownership landed everywhere (backups converge via the tap)
        c.drain()
        for s in range(4):
            assert cli.ownership(s) == (4, s)
        # push through the new topology, then shrink back
        push = np.zeros((len(keys), 12), np.float32)
        push[:, 1] = 1.0
        push[:, 3:] = 0.25
        cli.push_sparse(0, keys, push)
        c.drain()
        d4 = sum(cli.digest(0)) & MASK
        rec2 = ctrl.shrink(2)
        assert rec2["to_shards"] == 2 and c.num_shards == 2
        pulled2 = cli.pull_sparse(0, keys, create=False)
        assert cli.num_servers == 2
        assert cli.size(0) == rows
        assert (sum(cli.digest(0)) & MASK) == d4
        # the rows themselves moved bit-exactly through both flips
        np.testing.assert_array_equal(
            pulled2, cli.pull_sparse(0, keys, create=False))
        assert pulled4.shape == pulled2.shape
        assert len(ctrl.events) == 2
        assert [e["direction"] for e in ctrl.events] == ["grow", "shrink"]
        # the journal is mirrored into the elastic store
        assert len(c.store.list_prefix(f"ps/{c.job_id}/reshard/")) == 2


def test_grow_refuses_dense_geo_tables():
    with ha.HACluster(num_shards=2, replication=1, sync=False) as c:
        cli = c.client()
        cli.create_sparse_table(0, _cfg())
        cli.create_dense_table(1, 16, optimizer="sgd", lr=0.1)
        ctrl = ReshardController(c)
        with pytest.raises(ReshardError):
            ctrl.grow(2)
        assert c.num_shards == 2  # nothing moved


# ---------------------------------------------------------------------------
# satellite: backoff + jitter on the client re-resolve path
# ---------------------------------------------------------------------------

def test_wait_for_primary_backoff_and_jitter_injectable_clock():
    from paddle_tpu.distributed.elastic import MemoryStore

    store = MemoryStore()
    ha.RoutingTable(store, "j").publish(0, [{"primary": "a:1",
                                             "backups": []}])

    def run(seed):
        t = [0.0]
        sleeps = []

        def clock():
            return t[0]

        def sleep(dt):
            sleeps.append(dt)
            t[0] += dt

        r = ha.HARouter(store, "j", poll_s=0.02, failover_timeout_s=1.0,
                        clock=clock, sleep=sleep, jitter_seed=seed)
        assert r.wait_for_primary(0, bad_endpoint="a:1") is None
        return sleeps

    s1 = run(7)
    s2 = run(7)
    s3 = run(8)
    # deterministic under a pinned seed; different seeds decohere
    assert s1 == s2
    assert s1 != s3
    # exponential envelope with jitter in [0.5, 1.5): consecutive
    # UN-jittered waits double (0.02, 0.04, ... capped 0.25), so each
    # jittered sleep stays inside its slot's band
    base = 0.02
    for dt in s1[:-1]:  # last sleep is deadline-clipped
        assert 0.5 * base <= dt <= 1.5 * base
        base = min(base * 2, 0.25)
    # and the deadline is honored on the fake clock
    assert sum(s1) <= 1.0 + 1e-9
    # failover() still rides the same path (advancing fake clock)
    t3 = [0.0]

    def adv(dt):
        t3[0] += dt

    t2 = ha.HARouter(store, "j", poll_s=0.02, failover_timeout_s=0.05,
                     clock=lambda: t3[0], sleep=adv)
    assert t2.failover(5, "a:1") is None  # no such shard → timeout


# ---------------------------------------------------------------------------
# satellite: reshard concurrent with a job-checkpoint save (gate nesting)
# ---------------------------------------------------------------------------

def test_checkpoint_save_concurrent_with_reshard(tmp_path):
    from paddle_tpu.io.job_checkpoint import JobCheckpointManager
    from paddle_tpu.ps.rpc import RemoteSparseTable
    from paddle_tpu.ps.table import MemorySparseTable

    with ha.HACluster(num_shards=2, replication=2, sync=True) as c:
        cli = c.client()
        cfg = _cfg()
        cli.create_sparse_table(0, cfg)
        keys = _seed_rows(cli)
        view = RemoteSparseTable(cli, 0, cfg)
        mgr = JobCheckpointManager(str(tmp_path), gate=c.checkpoint_gate())
        mgr.register_sparse("ctr", view)
        ctrl = ReshardController(c)
        errs = []

        def scale():
            try:
                ctrl.grow(2)
                ctrl.shrink(2)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        th = threading.Thread(target=scale, name="test-reshard")
        th.start()
        # hammer consistent cuts WHILE the reshard runs: the depth-
        # counted pauses nest and control_mu keeps capture/cutover
        # atomic w.r.t. each other — no deadlock, no half-migrated cut
        saves = 0
        try:
            while th.is_alive():
                mgr.save(step=saves, blocking=True)
                saves += 1
        finally:
            th.join()  # never tear the cluster down under the scaler
        assert not errs, errs
        mgr.save(step=saves, blocking=True)
        mgr.stop()
        assert saves >= 1
        # every published cut restores digest-consistent (restore_sparse
        # re-verifies the captured digest against the restored table)
        restored = JobCheckpointManager(str(tmp_path)).load_latest()
        fresh = MemorySparseTable(cfg)
        assert restored.restore_sparse("ctr", fresh) == len(keys)


# ---------------------------------------------------------------------------
# hot tier: resident set survives the cutover (no drop)
# ---------------------------------------------------------------------------

def _stream_data(n, S, D, seed=0):
    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc

    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        ids = rng.integers(0, 48, S)
        dense = rng.normal(size=D)
        label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
        lines.append(" ".join([f"1 {v}" for v in ids]
                              + [f"1 {v:.4f}" for v in dense]
                              + [f"1 {label}"]))
    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1)
              for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1)
                for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])
    ds = InMemoryDataset(slots, seed=0)
    ds.load_from_lines(lines)
    return ds


def _hot_trainer(cli, S=3, D=2):
    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps.communicator import SyncCommunicator
    from paddle_tpu.ps.hot_tier import HotTierConfig
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer

    comm = SyncCommunicator(cli)
    comm.start()
    pt.seed(0)
    tr = CtrStreamTrainer(
        DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=8,
                         dnn_hidden=(8,))),
        optimizer.Adam(1e-2), None, communicator=comm, table_id=0,
        embedx_dim=8, hot_tier=HotTierConfig(capacity=1 << 11),
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
    return tr, comm


def test_hot_tier_keeps_resident_set_across_reshard():
    import jax

    S, D = 3, 2

    def run(reshard):
        with ha.HACluster(num_shards=2, replication=1, sync=True) as c:
            cli = c.client()
            cli.create_sparse_table(0, _cfg())
            tr, comm = _hot_trainer(cli, S, D)
            tr.train_from_dataset(_stream_data(512, S, D, seed=0),
                                  batch_size=128)
            occ = tr.hot_tier.stats()["occupancy"]
            assert occ > 0
            if reshard:
                ReshardController(c).grow(2)
                tr.on_reshard()  # flush-dirty, KEEP residency, re-route
                st = tr.hot_tier.stats()
                assert st["occupancy"] == occ  # nothing dropped
                assert st["reshards"] == 1
                assert cli.num_servers == 4
            out = tr.train_from_dataset(_stream_data(512, S, D, seed=1),
                                        batch_size=128)
            if reshard:
                # warm steady state continued across the flip: the
                # second epoch's working set was already resident
                assert tr.hot_tier.stats()["occupancy"] >= occ
            tr.hot_tier.flush()
            comm.barrier()
            probe = np.unique(
                (np.arange(0, 48, dtype=np.uint64)[None, :]
                 + (np.arange(S, dtype=np.uint64)[:, None]
                    << np.uint64(32))).reshape(-1))
            pulled = cli.pull_sparse(0, probe, create=False)
            params = jax.tree_util.tree_map(np.asarray, tr.params)
            comm.stop()
            return pulled, params, out

    pulled_r, params_r, _ = run(reshard=True)
    pulled_o, params_o, _ = run(reshard=False)
    # bit-parity: the reshard (and its extra flush) must not change
    # what the model learned or what the rows hold on the pull surface
    np.testing.assert_array_equal(pulled_r, pulled_o)
    for (ka, va), (kb, vb) in zip(
            sorted(jax_flatten(params_r)), sorted(jax_flatten(params_o))):
        assert ka == kb
        np.testing.assert_array_equal(va, vb)


def jax_flatten(tree):
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(k), np.asarray(v)) for k, v in flat]


# ---------------------------------------------------------------------------
# THE acceptance e2e (slow): reshard under load + kill-shard chaos
# ---------------------------------------------------------------------------

def _stream_trainer(cli, cluster, S=3, D=2):
    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps.communicator import SyncCommunicator
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer

    comm = SyncCommunicator(cli)
    # sync replication, made AIRTIGHT per batch (the PR 4 e2e pattern):
    # nothing is acked-but-unshipped when the chaos kill fires
    base_send = comm.send_sparse

    def send_and_drain(table_id, keys, values):
        base_send(table_id, keys, values)
        cluster.drain()

    comm.send_sparse = send_and_drain
    comm.start()
    pt.seed(0)
    tr = CtrStreamTrainer(
        DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=8,
                         dnn_hidden=(8,))),
        optimizer.Adam(1e-2), None, communicator=comm, table_id=0,
        embedx_dim=8,
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
    return tr, comm


@pytest.mark.slow
def test_reshard_under_load_chaos_e2e():
    """Grow 2→4 and shrink back to 2 while a CtrStreamTrainer streams
    (sync replication), with a kill-shard faultpoint armed on a source
    primary so it dies MID-MIGRATION (first kSaveAll of the bootstrap
    snapshot): the coordinator promotes its backup, the promoted
    primary re-attaches the migration lease, and the reshard completes.
    Zero lost/doubled rows (content digests), final pulled rows AND
    dense params bit-identical to an unresharded oracle, the trainer
    never observes an error."""
    import jax

    S, D = 3, 2
    EPOCHS = 6

    def run(reshard: bool, kill: bool):
        with ha.HACluster(num_shards=2, replication=2, sync=True) as c:
            cli = c.client()
            cli.create_sparse_table(0, _cfg())
            tr, comm = _stream_trainer(cli, c, S, D)
            ctrl = ReshardController(c) if reshard else None
            errs = []

            def op(fn):
                def run_op():
                    try:
                        fn()
                    except BaseException as e:  # noqa: BLE001
                        errs.append(e)
                t = threading.Thread(target=run_op, name="test-scaler")
                t.start()
                return t

            th = None
            steps = 0
            for e in range(EPOCHS):
                if reshard and e == 1:
                    if kill:
                        # die on the FIRST bootstrap snapshot read of
                        # shard 0's primary — mid-migration, under load
                        c.primary(0).server.arm_fault(
                            "kill-shard", cmd=rpc._SAVE_ALL, after=1)
                    th = op(lambda: ctrl.grow(2))
                if reshard and e == 3:
                    th.join()
                    assert not errs, errs
                    assert c.num_shards == 4
                    th = op(lambda: ctrl.shrink(2))
                out = tr.train_from_dataset(
                    _stream_data(768, S, D, seed=e), batch_size=128)
                steps += out["steps"]
            if th is not None:
                th.join()
                assert not errs, errs
            comm.barrier()
            c.drain()
            if reshard:
                assert c.num_shards == 2
                assert [ev["direction"] for ev in ctrl.events] == \
                    ["grow", "shrink"]
                if kill:
                    assert c.coordinator.promotions >= 1
            probe = np.unique(
                (np.arange(0, 48, dtype=np.uint64)[None, :]
                 + (np.arange(S, dtype=np.uint64)[:, None]
                    << np.uint64(32))).reshape(-1))
            pulled = cli.pull_sparse(0, probe, create=False)
            digest = sum(cli.digest(0)) & MASK
            rows = cli.size(0)
            params = jax.tree_util.tree_map(np.asarray, tr.params)
            comm.stop()
            return pulled, params, digest, rows, steps

    p_chaos, w_chaos, d_chaos, n_chaos, s1 = run(reshard=True, kill=True)
    p_ok, w_ok, d_ok, n_ok, s2 = run(reshard=False, kill=False)
    assert s1 == s2  # identical batch sequences — the comparison is fair
    assert n_chaos == n_ok          # zero lost or doubled rows...
    assert d_chaos == d_ok          # ...bit-exactly (content digests)
    np.testing.assert_array_equal(p_chaos, p_ok)
    for (ka, va), (kb, vb) in zip(sorted(jax_flatten(w_chaos)),
                                  sorted(jax_flatten(w_ok))):
        assert ka == kb
        np.testing.assert_array_equal(va, vb)


# ---------------------------------------------------------------------------
# review-hardening regressions
# ---------------------------------------------------------------------------

def test_ownership_rides_the_snapshot_attach():
    """A backup attached AFTER a reshard must receive the key-ownership
    predicate with its snapshot — rows alone are not the replicated
    state: a later promotion of an ownership-less replacement would
    silently accept stale-topology traffic instead of bouncing it."""
    with ha.HACluster(num_shards=2, replication=2, sync=True) as c:
        cli = c.client()
        cli.create_sparse_table(0, _cfg())
        _seed_rows(cli)
        ReshardController(c).grow(2)
        c.drain()
        # kill shard 0's BACKUP, restart a fresh replica on its port:
        # the endpoint stays in the routing doc, the primary's shipper
        # drops the dead conn and re-runs the snapshot attach (catalog
        # + ownership + rows + rebase) when the port answers again
        backup = c.backups(0)[0]
        ep = backup.endpoint
        backup.kill()
        fresh = c.restart_replica(0, ep)
        # traffic makes the shipper NOTICE the restart (an idle shipper
        # with a fully-acked cursor never touches the dead conn): each
        # push fails the ship → drop → re-attach → snapshot the fresh
        # server, ownership included
        push = np.zeros((1, 12), np.float32)
        push[:, 1] = 1.0
        deadline = time.monotonic() + 10.0
        while True:
            cli.push_sparse(0, np.array([4], np.uint64), push)  # class 0
            seq = c.primary(0).server.oplog_seq()
            rm = c.primary(0).rm
            lg = rm.lag() if rm is not None else {"acked": {}}
            if lg["acked"].get(ep, -1) >= seq and fresh.server.applied_seq:
                break
            assert time.monotonic() < deadline, "fresh backup never synced"
            time.sleep(0.05)
        conn = rpc.make_conn(ep)
        try:
            _, resp = conn.check(rpc._RETAIN, n=0)
            own = np.frombuffer(resp, np.int64)
        finally:
            conn.close()
        assert (int(own[0]), int(own[1])) == (4, 0)
        assert not fresh.server.stopped


def test_migrate_lag_excluded_from_replication_gauges():
    """A reshard bootstrap target's cursor trails by the whole history
    mid-copy — exporting it as ps_replication_lag_entries would fire
    the replication_lag up-rule and make the autoscaler chase its own
    bootstrap (positive feedback)."""
    import json as _json

    from paddle_tpu.ps.ha import observer_key

    with ha.HACluster(num_shards=1, replication=2, sync=True) as c:
        cli = c.client()
        cli.create_sparse_table(0, _cfg())
        _seed_rows(cli, 50)
        c.drain()
        target = rpc.NativePsServer()
        tep = f"127.0.0.1:{target.port}"
        try:
            c.store.put(observer_key(c.job_id, 0, tep),
                        _json.dumps({"mode": "migrate"}), ttl=5.0)
            deadline = time.monotonic() + 10.0
            while True:
                rm = c.primary(0).rm
                if rm is not None and tep in rm.lag()["acked"]:
                    break
                assert time.monotonic() < deadline, "migrate never attached"
                time.sleep(0.05)
            rm.export_metrics()
            # the real backup gets a lag gauge; the migrate target must
            # NOT (and the normal backup's is the only one bound)
            assert tep not in rm._lag_gauges
            assert any(ep != tep for ep in rm._lag_gauges)
        finally:
            c.store.delete(observer_key(c.job_id, 0, tep))
            target.stop()
            target.close()


def test_coordinator_suspend_blocks_scans_under_the_lock():
    """suspend() must gate the scan UNDER _step_mu: a scan that passed
    the unlocked check just before suspend() could publish a stale
    routing doc over a reshard cutover's flip."""
    from paddle_tpu.distributed.elastic import MemoryStore

    store = MemoryStore()
    routing = ha.RoutingTable(store, "sus")
    with rpc.NativePsServer() as backup:
        bep = f"127.0.0.1:{backup.port}"
        routing.publish(0, [{"primary": "10.0.0.1:1", "backups": [bep],
                             "replicas": ["10.0.0.1:1", bep]}])
        # only the backup heartbeats: the primary is promotable-dead
        store.put(f"ps/sus/hb/{bep}", "{}", ttl=30.0)
        coord = ha.FailoverCoordinator(store, "sus", grace_s=0.0)
        coord._missing_since["10.0.0.1:1"] = -1e9  # grace long expired
        coord.suspend()
        assert coord.step() == 0                 # gated: no promotion,
        assert routing.read()[1][0]["primary"] == "10.0.0.1:1"  # no write
        coord.resume_scans()
        assert coord.step() == 1                 # released: promotes
        assert routing.read()[1][0]["primary"] == bep


def test_ssd_remote_digest_and_readonly_ownership_read(tmp_path):
    """Edge regressions: (a) RemoteSparseTable.digest() on an
    SSD-backed remote table takes the plain kDigest path (the filtered
    form is RAM-only, and SSD tables cannot reshard anyway); (b) the
    kRetain n=0 ownership READ stays open on a read-only serving
    replica (the apply keeps bouncing)."""
    from paddle_tpu.ps.rpc import RemoteSparseTable

    with rpc.NativePsServer() as s:
        cli = rpc.RpcPsClient([f"127.0.0.1:{s.port}"])
        try:
            cfg = TableConfig(table_id=0, shard_num=2, accessor="ctr",
                              storage="ssd", ssd_path=str(tmp_path))
            cli.create_sparse_table(0, cfg)
            _seed_rows(cli, 40)
            view = RemoteSparseTable(cli, 0, cfg)
            assert view.digest() == cli.digest(0)  # plain path, works
            # (b) read-only replica: ownership read open, apply bounced
            s.set_read_only(True)
            assert cli.ownership(0) == (0, 0)
            with pytest.raises(PreconditionNotMetError):
                cli.retain(0, 2, 0)
            s.set_read_only(False)
        finally:
            cli.close()
            s.stop()


def test_load_cold_replays_across_reshard():
    """load_cold (the bulk build path) self-heals through a topology
    flip like the other keyed ops: bounced chunks re-resolve and
    replay; rows already landed are not re-sent blind (exactly-once
    per key via whole-frame rejection)."""
    with ha.HACluster(num_shards=2, replication=1, sync=False) as c:
        cli = c.client()
        cli.create_sparse_table(0, _cfg())
        _seed_rows(cli, 50)
        ReshardController(c).grow(2)
        # STALE client (still 2 conns): a bulk load must succeed via
        # bounce → re-resolve → replay
        assert cli.num_servers == 2
        full_dim = cli._dims(0)[2]
        keys = np.arange(1000, 1200, dtype=np.uint64)
        vals = np.zeros((len(keys), full_dim), np.float32)
        vals[:, 5] = 0.5
        assert cli.load_cold(0, keys, vals) == len(keys)
        assert cli.num_servers == 4
        assert cli.size(0) == 50 + len(keys)
        got, found = cli.export_full(0, keys)
        assert found.all() and np.allclose(got[:, 5], 0.5)
