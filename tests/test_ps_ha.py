"""PS high availability: replication, failure detection, failover,
and the deterministic fault-injection harness (ps/ha.py + the
kReplicate/kEpoch/kDigest wire commands in csrc/ps_service.cc).

Layers under test, bottom-up: the faultpoint registry and circuit
breaker (pure python), the oplog/epoch wire protocol (two in-process
servers), the full HACluster control loop (heartbeats → coordinator →
promotion → client failover → rejoin), and the e2e acceptance runs —
CtrStreamTrainer surviving a kill-shard faultpoint mid-run with
sync-replication bit-identity against a fault-free oracle, plus a true
multiprocess variant (SIGKILL'd server process, FileStore leases)."""

import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.faultpoints import (FaultInjected, arm_faultpoint,
                                       disarm_faultpoints, faultpoint)
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import MemorySparseTable, TableConfig, row_digest

rpc = pytest.importorskip("paddle_tpu.ps.rpc")

pytestmark = pytest.mark.skipif(
    not rpc.rpc_available(), reason="native toolchain unavailable")

from paddle_tpu.ps import ha  # noqa: E402  (needs the native lib)


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    yield
    disarm_faultpoints()


def _acc():
    return AccessorConfig(sgd=SGDRuleConfig(initial_range=0.0))


def _cfg():
    return TableConfig(shard_num=4, accessor_config=_acc())


def _push(rng, keys, width=12):
    push = np.zeros((len(keys), width), np.float32)
    push[:, 0] = (keys % 8).astype(np.float32)
    push[:, 1] = 1.0
    push[:, 3:] = rng.normal(0, 0.1, (len(keys), width - 3)).astype(np.float32)
    return push


# ---------------------------------------------------------------------------
# faultpoint registry
# ---------------------------------------------------------------------------

def test_faultpoint_unarmed_is_noop():
    assert faultpoint("nowhere") is None


def test_faultpoint_schedule_after_every_count():
    spec = arm_faultpoint("site", "corrupt-epoch", after=3, every=2, count=2,
                          param=99)
    fired = [i for i in range(10) if faultpoint("site") is not None]
    # hits 1..10: threshold at 3, then every 2 → 3,5 (count caps at 2)
    assert fired == [2, 4]
    assert spec.fired == 2


def test_faultpoint_drop_frame_raises_transport_error():
    arm_faultpoint("site", "drop-frame")
    with pytest.raises(FaultInjected):
        faultpoint("site")
    assert faultpoint("site") is None  # count=0 means unlimited? no: fired
    # unlimited count keeps firing on every hit
    arm_faultpoint("site", "drop-frame", every=1)
    for _ in range(3):
        with pytest.raises(FaultInjected):
            faultpoint("site")


def test_faultpoint_flag_arming(monkeypatch):
    """FLAGS_ps_faultpoints parses site=action[:k=v]* and arms lazily on
    the FIRST faultpoint() probe (the env-driven chaos path)."""
    import paddle_tpu as pt
    from paddle_tpu.ps import faultpoints as fp

    monkeypatch.setattr(fp, "_flag_loaded", False)
    pt.set_flags({"ps_faultpoints":
                  "rpc.call=delay-ms:ms=1:after=2;other=drop-frame"})
    try:
        t0 = time.perf_counter()
        assert faultpoint("rpc.call") is None      # hit 1 < after
        faultpoint("rpc.call")                     # hit 2 → 1ms delay
        assert time.perf_counter() - t0 >= 0.001
        with pytest.raises(FaultInjected):
            faultpoint("other")
    finally:
        pt.set_flags({"ps_faultpoints": ""})
        disarm_faultpoints()


def test_faultpoint_cmd_filter_and_kill_callback():
    killed = []
    arm_faultpoint("site", "kill-shard", cmd=4)
    assert faultpoint("site", cmd=3, kill=lambda: killed.append(1)) is None
    assert faultpoint("site", cmd=4, kill=lambda: killed.append(1)) is not None
    assert killed == [1]


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

def test_breaker_open_half_open_close():
    t = [0.0]
    b = ha.CircuitBreaker(failures=3, cooldown_s=5.0, clock=lambda: t[0])
    assert b.state == b.CLOSED and b.allow()
    for _ in range(3):
        b.record(ok=False)
    assert b.state == b.OPEN
    assert not b.allow()          # open: fail fast
    t[0] = 4.9
    assert not b.allow()          # cooldown not elapsed
    t[0] = 5.1
    assert b.allow()              # the ONE half-open probe
    assert b.state == b.HALF_OPEN
    assert not b.allow()          # second caller blocked while probing
    b.record(ok=True)
    assert b.state == b.CLOSED and b.allow()


def test_breaker_half_open_failure_reopens():
    t = [0.0]
    b = ha.CircuitBreaker(failures=1, cooldown_s=1.0, clock=lambda: t[0])
    b.record(ok=False)
    assert b.state == b.OPEN
    t[0] = 1.5
    assert b.allow()
    b.record(ok=False)            # probe failed → re-open, cooldown resets
    assert b.state == b.OPEN
    t[0] = 2.0
    assert not b.allow()
    t[0] = 2.6
    assert b.allow()


# ---------------------------------------------------------------------------
# oplog / epoch wire protocol (two bare servers)
# ---------------------------------------------------------------------------

@pytest.fixture
def pair():
    prim = rpc.NativePsServer(n_trainers=1)
    back = rpc.NativePsServer(n_trainers=1)
    prim.set_replication(True)
    cp = rpc.RpcPsClient([f"127.0.0.1:{prim.port}"])
    cb = rpc.RpcPsClient([f"127.0.0.1:{back.port}"])
    yield prim, back, cp, cb
    cp.close()
    cb.close()
    prim.close()
    back.close()


def _ship_all(prim, back_conn, epoch=0):
    while True:
        seq, frame = prim.oplog_next(timeout_ms=50)
        if seq < 0:
            return
        st = rpc.send_replicate(back_conn, frame, seq, epoch)
        assert st == seq, (st, seq)


def test_oplog_orders_and_replays_mutations(pair):
    prim, back, cp, cb = pair
    cp.create_sparse_table(0, _cfg())
    cb.create_sparse_table(0, _cfg())
    rng = np.random.default_rng(0)
    keys = rng.integers(1, 3000, 200).astype(np.uint64)
    cp.pull_sparse(0, keys)                   # create=True → replicated
    for _ in range(3):
        cp.push_sparse(0, keys, _push(rng, keys))
    # seqs are strictly increasing and frames decode to the issued ops
    import struct
    seen = []
    bconn = rpc.make_conn(f"127.0.0.1:{back.port}")
    try:
        last = 0
        while True:
            seq, frame = prim.oplog_next(timeout_ms=50)
            if seq < 0:
                break
            assert seq == last + 1, "oplog seq must be gapless"
            last = seq
            _, cmd, tid, _, _ = struct.unpack_from("<QIIqi", frame, 0)
            seen.append(cmd)
            assert rpc.send_replicate(bconn, frame, seq, 0) == seq
        # create (tapped, applied idempotently), pull-create, 3 pushes
        assert seen == [rpc._CREATE_SPARSE, rpc._PULL_SPARSE,
                        rpc._PUSH_SPARSE, rpc._PUSH_SPARSE, rpc._PUSH_SPARSE]
        assert cp.digest(0) == cb.digest(0)
        np.testing.assert_array_equal(cp.pull_sparse(0, keys, create=False),
                                      cb.pull_sparse(0, keys, create=False))
    finally:
        bconn.close()


def test_epoch_fencing_rejects_stale_primary(pair):
    prim, back, cp, cb = pair
    cp.create_sparse_table(0, _cfg())
    rng = np.random.default_rng(1)
    keys = np.arange(1, 50, dtype=np.uint64)
    cp.push_sparse(0, keys, _push(rng, keys))
    bconn = rpc.make_conn(f"127.0.0.1:{back.port}")
    try:
        back.set_epoch(7)  # the backup has been promoted at epoch 7
        seq, frame = prim.oplog_next(timeout_ms=100)
        assert seq >= 1
        # stale stream (epoch < 7) is fenced, nothing applied
        assert rpc.send_replicate(bconn, frame, seq, epoch=3) == -5
        # current-epoch stream applies
        assert rpc.send_replicate(bconn, frame, seq, epoch=7) == seq
        # duplicate replay after reconnect acks idempotently
        assert rpc.send_replicate(bconn, frame, seq, epoch=7) == seq
        # a seq that skips ahead reports the gap (backup needs a snapshot)
        assert rpc.send_replicate(bconn, frame, seq + 5, epoch=7) == -6
    finally:
        bconn.close()


def test_corrupt_epoch_faultpoint_exercises_fence(pair):
    prim, back, cp, cb = pair
    cp.create_sparse_table(0, _cfg())
    back.set_epoch(2)
    bconn = rpc.make_conn(f"127.0.0.1:{back.port}")
    try:
        seq, frame = prim.oplog_next(timeout_ms=100)
        arm_faultpoint("repl.ship", "corrupt-epoch", param=0)
        assert rpc.send_replicate(bconn, frame, seq, epoch=2) == -5
        disarm_faultpoints("repl.ship")
        assert rpc.send_replicate(bconn, frame, seq, epoch=2) == seq
    finally:
        bconn.close()


def test_replicate_accepts_seq_beyond_32_bits(pair):
    """The oplog seq rides ReqHeader.n and is NOT an element count — a
    long-lived shard's lifetime mutation count exceeds the 2^32 frame
    bound, and kReplicate must keep flowing there."""
    prim, back, cp, cb = pair
    cp.create_sparse_table(0, _cfg())
    cb.create_sparse_table(0, _cfg())
    rng = np.random.default_rng(0)
    keys = np.arange(1, 30, dtype=np.uint64)
    cp.push_sparse(0, keys, _push(rng, keys))
    bconn = rpc.make_conn(f"127.0.0.1:{back.port}")
    try:
        big = (1 << 33) + 7
        back.set_epoch(0)
        # rebase the backup as if it had applied big-1 entries already
        bconn.check(rpc._REPL_STATE, n=big - 1)
        frames = []
        while True:
            seq, frame = prim.oplog_next(timeout_ms=50)
            if seq < 0:
                break
            frames.append(frame)
        assert rpc.send_replicate(bconn, frames[-1], big, epoch=0) == big
        assert back.applied_seq == big
    finally:
        bconn.close()


def test_replicate_acks_frames_the_primary_also_rejected(pair):
    """A malformed mutating frame (tapped before the primary's payload
    validation rejected it) must ACK on the backup instead of wedging
    replication — state changed on neither side."""
    import struct

    prim, back, cp, cb = pair
    cp.create_sparse_table(0, _cfg())
    cb.create_sparse_table(0, _cfg())
    bconn = rpc.make_conn(f"127.0.0.1:{back.port}")
    try:
        # hand-build a kPushSparse frame whose payload is the wrong size
        # (header layout incl. the obs trace-context field — ps/ha.py
        # _HDR mirrors csrc ReqHeader)
        bad_payload = b"\x00" * 24
        inner = struct.pack("<QIIqiQQ", len(bad_payload), rpc._PUSH_SPARSE,
                            0, 5, 0, 0, 0) + bad_payload
        assert rpc.send_replicate(bconn, inner, 1, epoch=0) == 1
        assert back.applied_seq == 1  # advanced despite the rejection
        # and the stream keeps flowing afterwards
        rng = np.random.default_rng(0)
        keys = np.arange(1, 20, dtype=np.uint64)
        cp.push_sparse(0, keys, _push(rng, keys))
        _ship_all(prim, bconn)
        assert cp.digest(0) == cb.digest(0)
    finally:
        bconn.close()


def test_global_step_replicates_and_reads_stay_ungated(pair):
    prim, back, cp, cb = pair
    bconn = rpc.make_conn(f"127.0.0.1:{back.port}")
    try:
        prim.pause_mutations(True)
        # an n=0 read is NOT gated (the snapshot path reads it from a
        # paused primary) ...
        assert cp.global_step(0) == 0
        prim.pause_mutations(False)
        # ... but increments are, and they replicate
        assert cp.global_step(5) == 5
        _ship_all(prim, bconn)
        assert cb.global_step(0) == 5
    finally:
        bconn.close()


def test_foreign_seq_cursor_forces_snapshot_rebase():
    """A backup whose applied_seq was numbered by a DIFFERENT primary
    (promotion chain) must be re-synced via snapshot, not silently
    skipped by cursor comparison against the new primary's seqs."""
    store = ha.MemoryStore()
    routing = ha.RoutingTable(store, "foreign")
    prim = rpc.NativePsServer(n_trainers=1)
    back = rpc.NativePsServer(n_trainers=1)
    pep, bep = f"127.0.0.1:{prim.port}", f"127.0.0.1:{back.port}"
    routing.publish(0, [{"primary": pep, "backups": [bep],
                         "replicas": [pep, bep]}])
    cp = rpc.RpcPsClient([pep])
    cb = rpc.RpcPsClient([bep])
    rm = None
    try:
        prim.set_replication(True)
        cb.create_sparse_table(0, _cfg())
        # the backup claims a cursor far beyond the fresh primary's ring
        bconn = rpc.make_conn(bep)
        bconn.check(rpc._REPL_STATE, n=100_000)
        bconn.close()
        cp.create_sparse_table(0, _cfg())
        rng = np.random.default_rng(0)
        keys = rng.integers(1, 2000, 150).astype(np.uint64)
        cp.push_sparse(0, keys, _push(rng, keys))
        rm = ha.ReplicationManager(prim, pep, 0, routing).start()
        deadline = time.monotonic() + 20
        while cp.digest(0) != cb.digest(0):
            assert time.monotonic() < deadline, \
                (rm.lag(), cp.digest(0), cb.digest(0))
            time.sleep(0.02)
    finally:
        if rm is not None:
            rm.stop()
        cp.close()
        cb.close()
        prim.close()
        back.close()


def test_application_errors_do_not_trip_breaker_or_failover():
    """Server-side rejections (missing table, bad sizes) are NOT
    transport deaths: they pass through _shard_op untouched, never
    record a breaker failure, and never wait on the failover timeout."""
    from paddle_tpu.core.enforce import NotFoundError

    with ha.HACluster(num_shards=1, replication=2, sync=False) as c:
        cli = c.client(failures=2, cooldown_s=60.0, failover_timeout_s=5.0)
        cli.create_sparse_table(0, _cfg())
        ep = c.primary(0).endpoint
        keys = np.arange(1, 10, dtype=np.uint64)
        t0 = time.perf_counter()
        for _ in range(4):  # > breaker threshold
            with pytest.raises(NotFoundError):
                cli.pull_sparse(42, keys)  # table never created
        # fast (no failover waits) and the healthy endpoint stays CLOSED
        assert time.perf_counter() - t0 < 2.0
        assert cli._router.breaker(ep).state == ha.CircuitBreaker.CLOSED
        cli.pull_sparse(0, keys)  # still healthy


def test_shard_op_app_error_releases_half_open_probe():
    """A server-side rejection during a HALF_OPEN probe proves the
    transport is ALIVE — it must release the probe (record success),
    not leak it and lock the healthy endpoint out forever."""
    server = rpc.NativePsServer(n_trainers=1)
    ep = f"127.0.0.1:{server.port}"

    class StubRouter:
        def __init__(self):
            self.b = ha.CircuitBreaker(failures=1, cooldown_s=0.01)

        def routing(self):
            return 0, [ep]

        def allow(self, endpoint):
            return self.b.allow()

        def record(self, endpoint, ok):
            self.b.record(ok)

        def failover(self, shard, bad):
            return None

    router = StubRouter()
    cli = rpc.RpcPsClient([ep], router=router)
    try:
        router.b.record(ok=False)  # force OPEN
        assert router.b.state == ha.CircuitBreaker.OPEN
        time.sleep(0.02)  # past cooldown → next allow() is THE probe
        from paddle_tpu.core.enforce import NotFoundError
        with pytest.raises(NotFoundError):
            cli.digest(99)  # reaches the server; rejected kErrNoTable
        # the probe released and the server answered → breaker CLOSED
        assert router.b.state == ha.CircuitBreaker.CLOSED
        cli.create_sparse_table(0, _cfg())  # endpoint fully usable
    finally:
        cli.close()
        server.close()


def test_communicator_stays_failed_after_first_error_surfaces():
    """Once the background push thread dies, the FIRST barrier raises
    the original error and every later join with queued work raises
    again (a dead thread can never drain) instead of hanging."""
    from paddle_tpu.core.enforce import PreconditionNotMetError
    from paddle_tpu.ps.communicator import AsyncCommunicator

    class DoomedClient:
        def push_sparse(self, table_id, keys, values):
            raise PsTransportError("server gone")

        def pull_sparse(self, table_id, keys, create=True):
            return np.zeros((len(keys), 1), np.float32)

    from paddle_tpu.core.enforce import PsTransportError

    comm = AsyncCommunicator(DoomedClient())
    comm.start()
    keys = np.arange(3, dtype=np.uint64)
    comm.send_sparse(0, keys, np.zeros((3, 4), np.float32))
    with pytest.raises(PsTransportError):
        comm.barrier()
    comm.send_sparse(0, keys, np.zeros((3, 4), np.float32))
    t0 = time.perf_counter()
    with pytest.raises(PreconditionNotMetError):
        comm.barrier()  # raises again, promptly — no infinite spin
    assert time.perf_counter() - t0 < 15.0
    with pytest.raises(PreconditionNotMetError):
        comm.stop()


def test_server_fault_drop_frame_and_delay(pair):
    prim, _, cp, _ = pair
    cp.create_sparse_table(0, _cfg())
    keys = np.arange(1, 20, dtype=np.uint64)
    # drop-frame: the next matching request's connection dies without a
    # response; the client transport reconnects and retries through
    prim.arm_fault("drop-frame", cmd=rpc._PULL_SPARSE, after=1)
    out = cp.pull_sparse(0, keys, create=False)
    assert out.shape[0] == len(keys)
    # delay-ms: armed latency is observable
    prim.arm_fault("delay-ms", cmd=rpc._PULL_SPARSE, after=1, param=120)
    t0 = time.perf_counter()
    cp.pull_sparse(0, keys, create=False)
    assert time.perf_counter() - t0 >= 0.1


# ---------------------------------------------------------------------------
# HACluster: replication + failover + rejoin
# ---------------------------------------------------------------------------

@pytest.fixture
def cluster():
    with ha.HACluster(num_shards=2, replication=2, sync=True) as c:
        yield c


def test_sync_replication_bit_identical_at_barrier(cluster):
    cli = cluster.client()
    cli.create_sparse_table(0, _cfg())
    rng = np.random.default_rng(0)
    keys = rng.integers(1, 5000, 500).astype(np.uint64)
    cli.pull_sparse(0, keys)
    cli.push_sparse(0, keys, _push(rng, keys))
    cluster.drain()
    for shard in range(2):
        dg = cluster.digests(0, shard)
        assert len(dg) == 2 and len(set(dg.values())) == 1, dg


def test_failover_reroutes_pulls_and_pushes(cluster):
    cli = cluster.client()
    cli.create_sparse_table(0, _cfg())
    rng = np.random.default_rng(0)
    keys = rng.integers(1, 5000, 300).astype(np.uint64)
    cli.pull_sparse(0, keys)
    cli.push_sparse(0, keys, _push(rng, keys))
    cluster.drain()
    before = cli.pull_sparse(0, keys, create=False)
    dead = cluster.kill_primary(0)
    # the next pull fails over to the promoted backup and sees the
    # replicated state bit-identically
    after = cli.pull_sparse(0, keys, create=False)
    np.testing.assert_array_equal(before, after)
    assert cluster.wait_promoted(0, dead) != dead
    # pushes keep training through the new primary
    cli.push_sparse(0, keys, _push(rng, keys))
    cluster.drain()
    assert np.abs(cli.pull_sparse(0, keys, create=False) - before).sum() > 0


def test_barrier_rides_through_promotion(cluster):
    """The satellite bugfix: barrier runs retries=0, so one racing a
    primary→backup promotion must re-resolve the routing table and
    arrive on the promoted server instead of raising dead-server."""
    cli = cluster.client()
    cli.create_sparse_table(0, _cfg())
    dead = cluster.kill_primary(0)
    cli.barrier()  # must NOT raise: re-resolves to the promoted backup
    assert cluster.wait_promoted(0, dead) != dead


def test_in_flight_async_pull_replays_across_failover(cluster):
    from paddle_tpu.ps.communicator import AsyncCommunicator

    cli = cluster.client()
    cli.create_sparse_table(0, _cfg())
    rng = np.random.default_rng(0)
    keys = rng.integers(1, 4000, 256).astype(np.uint64)
    cli.pull_sparse(0, keys)
    cli.push_sparse(0, keys, _push(rng, keys))
    cluster.drain()
    want = cli.pull_sparse(0, keys, create=False)
    comm = AsyncCommunicator(cli)
    comm.start()
    try:
        # kill the shard-0 primary ON the next pull command it sees:
        # the prefetch pull is in flight when the server dies under it
        cluster.primary(0).server.arm_fault(
            "kill-shard", cmd=rpc._PULL_SPARSE, after=1)
        fut = comm.pull_sparse_async(0, keys, create=False)
        got = fut.result(timeout=30)  # drains/replays via failover
        np.testing.assert_array_equal(got, want)
    finally:
        comm.stop()


def test_rejoin_snapshot_and_tail_catch_up(cluster):
    cli = cluster.client()
    cli.create_sparse_table(0, _cfg())
    cli.create_dense_table(1, dim=16, optimizer="adam", lr=0.05)
    rng = np.random.default_rng(0)
    keys = rng.integers(1, 5000, 400).astype(np.uint64)
    cli.pull_sparse(0, keys)
    cli.push_sparse(0, keys, _push(rng, keys))
    cli.push_dense(1, np.ones(16, np.float32))
    cluster.drain()
    dead = cluster.kill_primary(0)
    new_prim = cluster.wait_promoted(0, dead)
    # keep training while the replica is down (its ring entry is gone —
    # rejoin MUST go through catalog replay + snapshot, not the tail)
    for _ in range(3):
        cli.push_sparse(0, keys, _push(rng, keys))
        cli.push_dense(1, np.ones(16, np.float32))
    cluster.restart_replica(0, dead)
    deadline = time.monotonic() + 15
    while True:
        _, shards = cluster.routing.read()
        if dead in shards[0]["backups"]:
            break
        assert time.monotonic() < deadline, shards
        time.sleep(0.05)
    cli.push_sparse(0, keys, _push(rng, keys))  # tail traffic post-rejoin
    cluster.drain()
    dg = cluster.digests(0, 0)
    assert len(dg) == 2 and len(set(dg.values())) == 1, dg
    # dense state (values + adam moments + step) caught up bit-identically
    # (each shard-0 replica holds the first 16/2 = 8 dims of the split)
    a = rpc.RpcPsClient([new_prim])
    b = rpc.RpcPsClient([dead])
    a._dense_dims[1] = b._dense_dims[1] = 8
    try:
        np.testing.assert_array_equal(a.pull_dense(1), b.pull_dense(1))
    finally:
        a.close()
        b.close()


def test_oplog_overflow_falls_back_to_snapshot():
    """A backup that attaches after the bounded ring dropped entries
    must come up via the full snapshot, not a corrupt tail."""
    store = ha.MemoryStore()
    routing = ha.RoutingTable(store, "ovf")
    prim = rpc.NativePsServer(n_trainers=1)
    back = rpc.NativePsServer(n_trainers=1)
    pep, bep = f"127.0.0.1:{prim.port}", f"127.0.0.1:{back.port}"
    routing.publish(0, [{"primary": pep, "backups": [bep],
                         "replicas": [pep, bep]}])
    cp = rpc.RpcPsClient([pep])
    cb = rpc.RpcPsClient([bep])
    rm = None
    try:
        prim.set_replication(True, cap_entries=8)  # tiny ring
        cp.create_sparse_table(0, _cfg())
        rng = np.random.default_rng(0)
        keys = rng.integers(1, 3000, 200).astype(np.uint64)
        for _ in range(30):  # >> ring capacity before any shipping
            cp.push_sparse(0, keys, _push(rng, keys))
        assert prim.oplog_dropped() > 0
        rm = ha.ReplicationManager(prim, pep, 0, routing,
                                   oplog_cap=8).start()
        deadline = time.monotonic() + 20
        while True:
            lg = rm.lag()
            if lg["acked"].get(bep, -1) >= lg["seq"] and lg["pending"] == 0:
                break
            assert time.monotonic() < deadline, lg
            time.sleep(0.01)
        assert cp.digest(0) == cb.digest(0)
    finally:
        if rm is not None:
            rm.stop()
        cp.close()
        cb.close()
        prim.close()
        back.close()


def test_breaker_opens_after_repeated_failures_without_promotion():
    """Replication factor 1: nothing to promote — after N consecutive
    transport failures the endpoint's breaker opens and subsequent
    calls fail FAST instead of paying timeout*retries each."""
    import paddle_tpu as pt

    old = pt.get_flags(["pserver_connect_timeout_ms", "pserver_timeout_ms",
                        "pserver_max_retry", "pserver_retry_backoff_ms"])
    pt.set_flags({"pserver_connect_timeout_ms": 200,
                  "pserver_timeout_ms": 300,
                  "pserver_max_retry": 1,
                  "pserver_retry_backoff_ms": 10})
    try:
        with ha.HACluster(num_shards=1, replication=1, sync=False) as c:
            cli = c.client(failures=2, cooldown_s=60.0,
                           failover_timeout_s=0.2)
            cli.create_sparse_table(0, _cfg())
            keys = np.arange(1, 20, dtype=np.uint64)
            cli.pull_sparse(0, keys)
            ep = c.primary(0).endpoint
            c.kill_primary(0)
            from paddle_tpu.core.enforce import PreconditionNotMetError
            for _ in range(2):
                with pytest.raises(PreconditionNotMetError):
                    cli.pull_sparse(0, keys, create=False)
            assert cli._router.breaker(ep).state == ha.CircuitBreaker.OPEN
            t0 = time.perf_counter()
            with pytest.raises(PreconditionNotMetError):
                cli.pull_sparse(0, keys, create=False)
            # fail-fast path: no connect/call timeout was paid, only the
            # (short) failover wait for a promotion that can't happen
            assert time.perf_counter() - t0 < 1.0
    finally:
        pt.set_flags(old)


# ---------------------------------------------------------------------------
# e2e: CtrStreamTrainer survives kill-shard; sync mode is bit-identical
# ---------------------------------------------------------------------------

def _make_stream_data(n=384, S=3, D=2, seed=0):
    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc

    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        ids = rng.integers(0, 48, S)
        dense = rng.normal(size=D)
        label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
        lines.append(" ".join([f"1 {v}" for v in ids]
                              + [f"1 {v:.4f}" for v in dense]
                              + [f"1 {label}"]))
    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1) for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1) for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])
    ds = InMemoryDataset(slots, seed=0)
    ds.load_from_lines(lines)
    return ds


def _run_stream_trainer(cli, cluster=None, kill_after_pushes=None):
    """One deterministic CtrStreamTrainer run against ``cli``'s table 0.
    With ``kill_after_pushes``, the shard-0 primary is armed to die on
    its Nth push — mid-run, under traffic. ``cluster`` (sync mode)
    drains after every batch so every acked op is on the backup before
    the next lands: the kill point then loses NOTHING and the run is
    bit-identical to a fault-free one."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps.communicator import SyncCommunicator
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer

    S, D = 3, 2
    ds = _make_stream_data(S=S, D=D)
    cli.create_sparse_table(0, _cfg())
    if kill_after_pushes is not None:
        cluster.primary(0).server.arm_fault(
            "kill-shard", cmd=rpc._PUSH_SPARSE, after=kill_after_pushes)

    comm = SyncCommunicator(cli)
    if cluster is not None:
        base_send = comm.send_sparse

        def send_and_drain(table_id, keys, values):
            base_send(table_id, keys, values)
            cluster.drain()  # sync replication: nothing acked-but-unshipped

        comm.send_sparse = send_and_drain
    comm.start()
    pt.seed(0)
    tr = CtrStreamTrainer(
        DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=8,
                         dnn_hidden=(8,))),
        optimizer.Adam(1e-2), None, communicator=comm, table_id=0,
        embedx_dim=8,
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
    out = tr.train_from_dataset(ds, batch_size=128)
    comm.stop()
    assert np.isfinite(out["loss"])
    probe = np.unique(
        (np.arange(0, 48, dtype=np.uint64)[None, :]
         + (np.arange(S, dtype=np.uint64)[:, None] << np.uint64(32)))
        .reshape(-1))
    return out, cli.pull_sparse(0, probe, create=False)


def test_stream_trainer_survives_kill_shard_bit_identical():
    """THE acceptance run: kill a PS shard mid-CtrStreamTrainer via the
    armed kill-shard faultpoint; training completes through failover,
    and with sync replication the final pulled params are BIT-identical
    to a fault-free run."""
    with ha.HACluster(num_shards=2, replication=2, sync=True) as oracle:
        cli = oracle.client()
        _, params_ok = _run_stream_trainer(cli, cluster=oracle)

    with ha.HACluster(num_shards=2, replication=2, sync=True) as chaotic:
        cli = chaotic.client()
        t0 = time.perf_counter()
        out, params_chaos = _run_stream_trainer(cli, cluster=chaotic,
                                                kill_after_pushes=2)
        dt = time.perf_counter() - t0
        # the primary really died and a backup really took over
        assert chaotic.coordinator.promotions >= 1
        assert chaotic.servers[0][0].server.stopped
    assert out["steps"] == 3.0  # 384 rows / 128, drop_last
    np.testing.assert_array_equal(params_chaos, params_ok)
    assert np.isfinite(dt)


_HA_SERVER_SCRIPT = """
import sys, time
from paddle_tpu.distributed.elastic import FileStore
from paddle_tpu.ps.ha import HAServer
store = FileStore(sys.argv[1])
s = HAServer(store, sys.argv[2], int(sys.argv[3]), n_trainers=1,
             hb_interval=0.1, hb_ttl=0.6)
s.start()
print("READY", s.endpoint, flush=True)
while not s.server.stopped:
    time.sleep(0.1)
print("DEAD", flush=True)
"""


def test_multiprocess_failover_kill_minus_nine(tmp_path):
    """True multiprocess e2e: 2 replicas of one shard in separate
    PROCESSES over a FileStore; the primary is SIGKILL'd mid-traffic
    (nothing graceful anywhere), the parent's coordinator promotes the
    backup, and the client's pulls keep answering from the replicated
    state. Bit-identity is asserted for everything drained BEFORE the
    kill (drain_remote — the wire-level sync barrier)."""
    from paddle_tpu.distributed.elastic import FileStore

    store_dir = str(tmp_path / "store")
    store = FileStore(store_dir)
    procs = []
    eps = []
    try:
        for _ in range(2):
            p = subprocess.Popen(
                [sys.executable, "-c", _HA_SERVER_SCRIPT, store_dir, "mp", "0"],
                stdout=subprocess.PIPE, text=True, cwd="/root/repo")
            line = p.stdout.readline().strip()
            assert line.startswith("READY"), line
            procs.append(p)
            eps.append(line.split()[1])
        routing = ha.RoutingTable(store, "mp")
        routing.publish(0, [{"primary": eps[0], "backups": [eps[1]],
                             "replicas": eps}])
        coord = ha.FailoverCoordinator(store, "mp", grace_s=0.2,
                                       poll_s=0.05).start()
        try:
            cli = rpc.RpcPsClient([eps[0]],
                                  router=ha.HARouter(store, "mp"))
            cli.create_sparse_table(0, _cfg())
            rng = np.random.default_rng(0)
            keys = rng.integers(1, 4000, 300).astype(np.uint64)
            cli.pull_sparse(0, keys)
            cli.push_sparse(0, keys, _push(rng, keys))
            ha.drain_remote(eps[0], [eps[1]])
            want = cli.pull_sparse(0, keys, create=False)
            procs[0].kill()  # SIGKILL: no cleanup, lease expires by TTL
            got = cli.pull_sparse(0, keys, create=False)  # fails over
            np.testing.assert_array_equal(got, want)
            assert routing.read()[1][0]["primary"] == eps[1]
            # and the job keeps training on the survivor
            cli.push_sparse(0, keys, _push(rng, keys))
            assert np.abs(cli.pull_sparse(0, keys, create=False)
                          - want).sum() > 0
            cli.close()
        finally:
            coord.stop()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_digest_matches_local_oracle():
    """kDigest over the wire == MemorySparseTable.digest() == the
    python row_digest mirror for identical content — the probe the
    replica-consistency checks stand on."""
    server = rpc.NativePsServer(n_trainers=1)
    cli = rpc.RpcPsClient([f"127.0.0.1:{server.port}"])
    try:
        cli.create_sparse_table(0, _cfg())
        local = MemorySparseTable(_cfg())
        rng = np.random.default_rng(3)
        keys = np.unique(rng.integers(1, 2000, 300).astype(np.uint64))
        slots = (keys % 8).astype(np.int32)
        push = _push(rng, keys)
        push[:, 0] = slots
        cli.pull_sparse(0, keys, slots=slots)
        cli.push_sparse(0, keys, push)
        local.pull_sparse(keys, slots=slots)
        local.push_sparse(keys, push)
        (remote_digest,) = cli.digest(0)
        assert remote_digest == local.digest()
        vals, found = local.export_full(keys)
        assert found.all()
        assert remote_digest == row_digest(keys, vals)
    finally:
        cli.close()
        server.close()


def test_self_conn_lazy_connect_outside_lock(monkeypatch):
    """Regression (py_locks blocking-under-lock): ReplicationManager._self
    builds its TCP conn OUTSIDE _mu (double-checked swap); racing callers get
    ONE shared conn and the loser's stray is closed."""
    import threading as _threading

    class FakeConn:
        def __init__(self):
            self.closed = False

        def close(self):
            self.closed = True

    built = []

    def fake_make_conn(endpoint):
        c = FakeConn()
        built.append(c)
        barrier.wait(timeout=5)     # both racers connect concurrently
        return c

    monkeypatch.setattr(ha, "make_conn", fake_make_conn)
    srv = ha.ReplicationManager.__new__(ha.ReplicationManager)
    srv._mu = _threading.Lock()
    srv._self_conn = None
    srv.endpoint = "127.0.0.1:0"
    barrier = _threading.Barrier(2)
    got = []
    ts = [_threading.Thread(target=lambda: got.append(srv._self()),
                            name=f"self-conn-racer-{i}") for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
    assert len(got) == 2 and got[0] is got[1]
    assert len(built) == 2
    winner = got[0]
    strays = [c for c in built if c is not winner]
    assert len(strays) == 1 and strays[0].closed
    assert not winner.closed
    # subsequent calls reuse the cached conn without connecting again
    assert srv._self() is winner and len(built) == 2
