"""DIN (models/din.py): attention over variable-length behavior slots
through the GPUPS pass path — learns a behavior-match signal sum-pooling
can't express cleanly, and provably ignores padding positions."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.metrics.auc import AUC
from paddle_tpu.models.ctr import _masked_pull
from paddle_tpu.models.din import DIN, make_ctr_attention_train_step
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
from paddle_tpu.ps.table import MemorySparseTable, TableConfig

G, TB, D, DIM = 1, 6, 2, 8  # target cols, behavior cols, dense, emb dim
VOCAB = 64


def _synth(rng, n):
    """Target item + a variable-length behavior history; the label
    depends on how many REAL history items are 'clicky' (id%5==0) —
    learnable per-item structure that must flow through the attention
    pooling, where only the mask keeps padding out of the count. (Pure
    target∈history identity matching is deliberately NOT the gate: at
    test scale that is a research-grade embedding-identity problem, not
    a framework property.)"""
    target = rng.integers(1, VOCAB, size=(n, G)).astype(np.uint64)
    lens = rng.integers(1, TB + 1, size=n)
    behav = rng.integers(1, VOCAB, size=(n, TB)).astype(np.uint64)
    # target and behaviors SHARE the item embedding space (DIN's
    # shared item embedding) — same feasign for the same item
    keys = np.concatenate([target, behav], axis=1)
    pad_mask = np.arange(TB)[None, :] < lens[:, None]
    clicky = ((behav % np.uint64(5) == 0) & pad_mask).sum(axis=1)
    dense = rng.normal(size=(n, D)).astype(np.float32)
    labels = ((clicky + dense[:, 0]
               + rng.normal(scale=0.5, size=n)) > 1.3).astype(np.int32)
    return keys, pad_mask, dense, labels


def test_din_learns_match_signal_and_ignores_padding():
    pt.seed(0)
    rng = np.random.default_rng(0)
    cache_cfg = CacheConfig(capacity=1024, embedx_dim=DIM,
                            embedx_threshold=0.0)
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=DIM)))
    cache = HbmEmbeddingCache(table, cache_cfg)

    keys, pad_mask, dense, labels = _synth(rng, 2048)
    cache.begin_pass(keys.reshape(-1))
    C = cache_cfg.capacity

    def rows_of(k, mask):
        r = cache.lookup(k.reshape(-1)).reshape(k.shape).astype(np.int32)
        full = np.concatenate(
            [np.ones((len(k), G), bool), mask], axis=1)
        return np.where(full, r, C)  # padding → sentinel

    model = DIN(G, TB, D, DIM)
    opt = optimizer.Adam(learning_rate=1e-2)
    params = {"params": dict(model.named_parameters()), "buffers": {}}
    opt_state = opt.init(params)
    step = make_ctr_attention_train_step(model, opt, cache_cfg,
                                         donate=False)

    B = 256
    for epoch in range(12):
        for i in range(0, len(keys), B):
            rows = jnp.asarray(rows_of(keys[i:i + B], pad_mask[i:i + B]))
            params, opt_state, cache.state, loss = step(
                params, opt_state, cache.state, rows,
                jnp.asarray(dense[i:i + B]), jnp.asarray(labels[i:i + B]))
    assert np.isfinite(float(loss))

    m = AUC()
    for i in range(0, len(keys), B):
        rows = jnp.asarray(rows_of(keys[i:i + B], pad_mask[i:i + B]))
        # sentinel-safe pull (raw eager cache_pull would FILL NaN for
        # out-of-bounds sentinel rows — the step uses the masked pull)
        emb = _masked_pull(cache.state, rows.reshape(-1)).reshape(
            rows.shape[0], G + TB, -1)
        real = (rows < C).astype(jnp.float32)
        out, _ = nn.functional_call(model, params, emb, real,
                                    jnp.asarray(dense[i:i + B]),
                                    training=False)
        m.update(np.asarray(nn.functional.sigmoid(out)), labels[i:i + B])
    auc = m.accumulate()
    assert auc > 0.8, auc

    # padding invariance: corrupt the PADDED positions' embeddings with
    # garbage — outputs must not change (the mask, not zero-embeddings,
    # is what excludes padding)
    i = 0
    rows = jnp.asarray(rows_of(keys[i:i + B], pad_mask[i:i + B]))
    emb = np.array(_masked_pull(cache.state, rows.reshape(-1)).reshape(
        B, G + TB, -1))
    real = np.asarray(rows) < C
    out1, _ = nn.functional_call(model, params, jnp.asarray(emb),
                                 jnp.asarray(real.astype(np.float32)),
                                 jnp.asarray(dense[:B]), training=False)
    emb2 = emb.copy()
    emb2[~real] = 777.0  # garbage in every padded position
    out2, _ = nn.functional_call(model, params, jnp.asarray(emb2),
                                 jnp.asarray(real.astype(np.float32)),
                                 jnp.asarray(dense[:B]), training=False)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))

    cache.end_pass()
    assert table.size() >= len(np.unique(keys))
