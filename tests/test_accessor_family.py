"""Accessor-family completion (VERDICT r4 missing #4): the double-
precision CTR accessor (ctr_double_accessor.h:27), the comm-merge /
tensor accessor roles (tensor_accessor.h), and selection from
TableConfig / YAML — with save-format round-trips and the precision
behavior that motivates the double layout."""

import numpy as np
import pytest

from paddle_tpu.ps.accessor import (AccessorConfig, CommMergeAccessor,
                                    CtrCommonAccessor, CtrDoubleAccessor,
                                    TensorAccessor, make_accessor)
from paddle_tpu.ps.table import MemorySparseTable, TableConfig


def _push(n, dim, show=1.0, click=0.0, g=0.0, slot=3):
    push = np.zeros((n, 4 + dim), np.float32)
    push[:, 0] = slot
    push[:, 1] = show
    push[:, 2] = click
    push[:, 3] = g
    push[:, 4:] = g
    return push


def _cfg(**kw):
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig

    kw.setdefault("embedx_dim", 4)
    kw.setdefault("sgd", SGDRuleConfig(initial_range=0.0))
    return AccessorConfig(**kw)


class TestCtrDouble:
    def test_registry_and_python_backend(self):
        acc = make_accessor("ctr_double", _cfg())
        assert isinstance(acc, CtrDoubleAccessor)
        assert make_accessor("DownpourCtrDoubleAccessor", _cfg()).__class__ \
            is CtrDoubleAccessor
        t = MemorySparseTable(TableConfig(shard_num=2, accessor="ctr_double",
                                          accessor_config=_cfg()))
        # no native engine id for ctr_double: python backend serves it
        assert t.backend == "python"

    def test_show_accumulates_past_float32_saturation(self):
        """The reason this accessor exists: at show = 2^24 a float32
        accumulator stops absorbing +1.0 (1.6777216e7 + 1 == 1.6777216e7
        in f32); the double layout keeps counting."""
        key = np.asarray([7], np.uint64)
        sat = float(2 ** 24)

        def run(accessor_name):
            t = MemorySparseTable(TableConfig(
                shard_num=1, accessor=accessor_name, accessor_config=_cfg()))
            t.pull_sparse(key)
            t.push_sparse(key, _push(1, 4, show=sat))
            for _ in range(50):
                t.push_sparse(key, _push(1, 4, show=1.0))
            return float(t.pull_sparse(key, create=False)[0, 0])

        assert run("ctr_double") == sat + 50.0
        assert run("ctr") == sat  # f32 freezes — the bug being fixed

    def test_math_parity_with_ctr_in_f32_range(self):
        """Inside the float32-exact range the double accessor follows
        the common accessor's A.1/A.3 math identically (same SGD rules,
        same lifecycle) — only the accumulator dtype differs."""
        rng = np.random.default_rng(3)
        keys = np.arange(1, 40, dtype=np.uint64)
        pushes = [
            _push(len(keys), 4, show=2.0, click=1.0,
                  g=rng.normal(0, 0.1)) for _ in range(5)
        ]

        def run(name):
            t = MemorySparseTable(TableConfig(
                shard_num=2, accessor=name,
                accessor_config=_cfg(embedx_threshold=2.0)))
            t.pull_sparse(keys)
            for p in pushes:
                t.push_sparse(keys, p)
            return t.pull_sparse(keys, create=False)

        np.testing.assert_allclose(run("ctr_double"), run("ctr"),
                                   rtol=1e-6, atol=1e-7)

    def test_save_format_and_roundtrip(self, tmp_path):
        """Distinct text format (ParseToString field order: unseen delta
        show click embed_w g2sum slot [embedx_g2sum embedx_w...]) with no
        explicit has_embedx flag; round-trips through save/load, and a
        plain ctr table refuses the file."""
        cfg = _cfg(embedx_threshold=1.0)
        t = MemorySparseTable(TableConfig(shard_num=2, accessor="ctr_double",
                                          accessor_config=cfg))
        keys = np.asarray([11, 22, 33], np.uint64)
        t.pull_sparse(keys, slots=np.full(3, 3, np.int32))  # slot set at create
        t.push_sparse(keys, _push(3, 4, show=5.0, click=2.0, g=0.1))
        before = t.pull_sparse(keys, create=False)
        assert t.save(str(tmp_path / "dbl"), mode=0) == 3

        # field order check on the raw line
        with open(tmp_path / "dbl" / "part-00000.shard") as f:
            line = f.readline().split()
        # key unseen delta show click embed_w g2sum slot + 1+4 embedx tail
        assert len(line) == 8 + 5
        assert float(line[3]) == 5.0      # show in position 3
        assert int(line[7]) == 3          # slot at position 7

        t2 = MemorySparseTable(TableConfig(shard_num=2, accessor="ctr_double",
                                           accessor_config=cfg))
        assert t2.load(str(tmp_path / "dbl")) == 3
        np.testing.assert_allclose(t2.pull_sparse(keys, create=False), before,
                                   rtol=1e-6)

        plain = MemorySparseTable(TableConfig(shard_num=2, accessor="ctr",
                                              accessor_config=cfg))
        with pytest.raises(Exception, match="cannot load"):
            plain.load(str(tmp_path / "dbl"))

    def test_save_modes_filter(self, tmp_path):
        cfg = _cfg(base_threshold=5.0, delta_threshold=1.0)
        t = MemorySparseTable(TableConfig(shard_num=2, accessor="ctr_double",
                                          accessor_config=cfg))
        hot = np.asarray([1], np.uint64)
        cold = np.asarray([2], np.uint64)
        t.push_sparse(hot, _push(1, 4, show=20.0, click=10.0))
        t.push_sparse(cold, _push(1, 4, show=0.1))
        assert t.save(str(tmp_path / "m0"), mode=0) == 2
        assert t.save(str(tmp_path / "m1"), mode=1) == 1  # delta filter

    def test_gzip_converter_composes(self, tmp_path):
        cfg = _cfg()
        t = MemorySparseTable(TableConfig(shard_num=2, accessor="ctr_double",
                                          accessor_config=cfg,
                                          converter="gzip"))
        keys = np.asarray([5, 6], np.uint64)
        t.pull_sparse(keys)
        t.push_sparse(keys, _push(2, 4, show=3.0))
        before = t.pull_sparse(keys, create=False)
        t.save(str(tmp_path / "z"))
        import os

        assert os.path.exists(tmp_path / "z" / "part-00000.shard.gz")
        t2 = MemorySparseTable(TableConfig(shard_num=2, accessor="ctr_double",
                                           accessor_config=cfg))
        assert t2.load(str(tmp_path / "z")) == 2
        np.testing.assert_allclose(t2.pull_sparse(keys, create=False), before,
                                   rtol=1e-6)


class TestCommMergeAndTensor:
    def test_merge_sums_and_lifecycle_constants(self):
        acc = make_accessor("comm_merge", AccessorConfig(embedx_dim=6))
        assert isinstance(acc, CommMergeAccessor)
        assert acc.select_dim == 6 and acc.update_dim == 6
        a = np.arange(6, dtype=np.float32)
        b = np.ones(6, np.float32)
        out = acc.merge(a, b)
        np.testing.assert_allclose(out, np.arange(6) + 1)
        assert out is a  # in-place, Eigen u_mat += o_mat semantics
        assert acc.shrink(a) is False
        assert acc.save_filter(a, 0) is True

    def test_tensor_accessor_is_selectable_alias(self):
        acc = make_accessor("tensor", AccessorConfig(embedx_dim=3))
        assert isinstance(acc, TensorAccessor)
        assert isinstance(acc, CommMergeAccessor)
        assert make_accessor("TensorAccessor").__class__ is TensorAccessor


class TestSelection:
    def test_yaml_accessor_class(self):
        from paddle_tpu.ps.config import load_ps_config

        cfg = {
            "runner": {"sync_mode": "async", "thread_num": 4,
                       "accessor_class": "ctr_double"},
            "hyper_parameters": {"sparse_inputs_slots": 9,
                                 "sparse_feature_dim": 5,
                                 "optimizer": {"class": "adam",
                                               "learning_rate": 0.001}},
        }
        job = load_ps_config(cfg)
        assert job.table.accessor == "ctr_double"
        t = MemorySparseTable(job.table)
        assert isinstance(t.accessor, CtrDoubleAccessor)

    def test_yaml_table_parameters_override_and_converter(self):
        from paddle_tpu.ps.config import load_ps_config

        cfg = {
            "runner": {"sync_mode": "async"},
            "table_parameters": {"accessor_class": "SparseAccessor",
                                 "converter": "gzip"},
            "hyper_parameters": {"sparse_feature_dim": 5},
        }
        job = load_ps_config(cfg)
        assert job.table.accessor == "SparseAccessor"
        assert job.table.converter == "gzip"

    def test_unknown_accessor_fails_fast(self):
        from paddle_tpu.ps.config import load_ps_config

        with pytest.raises(KeyError, match="unknown accessor"):
            load_ps_config({
                "runner": {"accessor_class": "nope"},
                "hyper_parameters": {"sparse_feature_dim": 5},
            })

    def test_non_feature_accessor_rejected_at_config_time(self):
        """comm_merge/tensor are communicator/dense roles — selecting
        one for the sparse table must fail AT CONFIG TIME with a clear
        message, not as an AttributeError inside table construction."""
        from paddle_tpu.ps.config import load_ps_config

        with pytest.raises(Exception, match="not a sparse feature"):
            load_ps_config({
                "runner": {"accessor_class": "comm_merge"},
                "hyper_parameters": {"sparse_feature_dim": 5},
            })

    def test_ctr_double_requires_single_state_rules(self):
        from paddle_tpu.ps.sgd_rule import SGDRuleConfig

        with pytest.raises(KeyError, match="single-state"):
            make_accessor("ctr_double", AccessorConfig(
                embedx_dim=4, embedx_sgd_rule="adam",
                sgd=SGDRuleConfig()))
