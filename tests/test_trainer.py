import os

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.data import DataLoader, TensorDataset
from paddle_tpu.executor import Trainer
from paddle_tpu.metrics import Accuracy
from paddle_tpu.models import LeNet


def make_blobs(n=256, dim=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 4, (classes, dim)).astype(np.float32)
    labels = rng.integers(0, classes, n)
    x = centers[labels] + rng.normal(0, 0.5, (n, dim)).astype(np.float32)
    return x.astype(np.float32), labels.astype(np.int32)


def test_mlp_trains_on_blobs():
    pt.seed(0)
    x, y = make_blobs()
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
    trainer = Trainer(model, optimizer.Adam(1e-2), nn.functional.cross_entropy)
    loader = DataLoader(TensorDataset(x, y), batch_size=64, shuffle=True, seed=0)
    first_loss = None
    for epoch in range(12):
        for xb, yb in loader:
            loss = trainer.train_step(jnp.asarray(xb), jnp.asarray(yb))
            if first_loss is None:
                first_loss = loss
    assert loss < first_loss * 0.3, (first_loss, loss)
    metric = Accuracy()
    metric.update(trainer.predict(jnp.asarray(x)), y)
    assert metric.accumulate() > 0.9


def test_lenet_forward_and_one_step():
    pt.seed(0)
    model = LeNet(num_classes=10)
    x = np.random.default_rng(0).normal(size=(8, 1, 28, 28)).astype(np.float32)
    y = np.arange(8, dtype=np.int32) % 10
    out = model(jnp.asarray(x))
    assert out.shape == (8, 10)
    trainer = Trainer(model, optimizer.SGD(0.01), nn.functional.cross_entropy)
    l1 = trainer.train_step(jnp.asarray(x), jnp.asarray(y))
    l2 = trainer.train_step(jnp.asarray(x), jnp.asarray(y))
    assert np.isfinite(l1) and np.isfinite(l2)


def test_checkpoint_roundtrip(tmp_path):
    pt.seed(0)
    from paddle_tpu.io import load_checkpoint, save_checkpoint

    model = nn.Linear(4, 2)
    trainer = Trainer(model, optimizer.Adam(1e-2), nn.functional.mse_loss)
    x = np.ones((4, 4), np.float32)
    y = np.zeros((4, 2), np.float32)
    trainer.train_step(jnp.asarray(x), jnp.asarray(y))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, trainer.state_dict(), step=trainer.global_step)
    snap = load_checkpoint(path)
    assert snap["step"] == 1
    model2 = nn.Linear(4, 2)
    model2.set_state_dict(snap["model"])
    np.testing.assert_allclose(
        np.asarray(model2.state_dict()["weight"]),
        np.asarray(trainer.state_dict()["weight"]),
    )


def test_auc_metric_matches_sklearn_style():
    from paddle_tpu.metrics import AUC

    rng = np.random.default_rng(0)
    labels = rng.integers(0, 2, 2000)
    # informative predictions
    preds = np.clip(labels * 0.4 + rng.uniform(0, 0.6, 2000), 0, 1)
    m = AUC()
    m.update(preds, labels)
    val = m.accumulate()
    # exact pairwise AUC for comparison
    pos = preds[labels == 1]
    neg = preds[labels == 0]
    exact = (
        (pos[:, None] > neg[None, :]).sum() + 0.5 * (pos[:, None] == neg[None, :]).sum()
    ) / (len(pos) * len(neg))
    assert abs(val - exact) < 0.005, (val, exact)


def test_auc_distributed_merge():
    from paddle_tpu.metrics import AUC

    rng = np.random.default_rng(1)
    labels = rng.integers(0, 2, 1000)
    preds = np.clip(labels * 0.3 + rng.uniform(0, 0.7, 1000), 0, 1)
    whole = AUC()
    whole.update(preds, labels)
    w1, w2 = AUC(), AUC()
    w1.update(preds[:500], labels[:500])
    w2.update(preds[500:], labels[500:])
    w1.merge(w2.buckets)
    assert abs(whole.accumulate() - w1.accumulate()) < 1e-12


def test_trainer_amp_trains_and_is_bf16_in_trace(rng):
    """Trainer(amp=True): the step body traces under auto_cast — bf16
    contractions appear in the compiled program regardless of where the
    first call happens, and training still converges."""
    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer
    from paddle_tpu.executor import Trainer, make_train_step

    pt.seed(0)
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))
    tr = Trainer(model, optimizer.Adam(5e-3),
                 nn.functional.cross_entropy, amp=True)
    centers = rng.normal(size=(2, 8)).astype(np.float32) * 2
    x = np.concatenate([centers[y] + 0.3 * rng.normal(size=(64, 8))
                        for y in (0, 1)]).astype(np.float32)
    y = np.repeat(np.arange(2), 64).astype(np.int32)
    losses = [float(tr.train_step(x, y)) for _ in range(40)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5

    # the amp mode is a property of the step, not of the call site
    step = make_train_step(model, optimizer.Adam(5e-3),
                           nn.functional.cross_entropy, donate=False,
                           amp=True)
    import jax
    state = {"params": dict(model.named_parameters()), "buffers": {}}
    opt_state = optimizer.Adam(5e-3).init(state["params"])
    lowered = step.lower(state, opt_state, jax.random.key(0), (x,), (y,))
    assert "bf16" in lowered.as_text()
    step_f32 = make_train_step(model, optimizer.Adam(5e-3),
                               nn.functional.cross_entropy, donate=False)
    lowered32 = step_f32.lower(state, opt_state, jax.random.key(0),
                               (x,), (y,))
    assert "bf16" not in lowered32.as_text()


def test_trainer_amp_o2_master_weights():
    """Trainer(amp="O2"): bf16 parameter storage + f32 masters (the
    hapi amp_configs="O2" semantics on the low-level Trainer)."""
    import jax.numpy as jnp

    from paddle_tpu.optimizer import MasterWeights

    pt.seed(0)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = (x.sum(-1) > 0).astype(np.int32)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    tr = Trainer(model, optimizer.Adam(5e-3), nn.functional.cross_entropy,
                 amp="O2")
    assert isinstance(tr.optimizer, MasterWeights)
    for p in tr.state["params"].values():
        assert p.dtype == jnp.bfloat16
    losses = [float(tr.train_step((x,), (y,))) for _ in range(20)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    masters = tr.opt_state["slots"]["master"]
    for k, p in tr.state["params"].items():
        np.testing.assert_array_equal(
            np.asarray(p), np.asarray(masters[k].astype(jnp.bfloat16)), k)
