"""Concurrent access to the native table engines: pull/push/save/shrink
(/spill/compact for the SSD tier) racing from many threads must not
crash, deadlock, or corrupt rows. ctypes releases the GIL during native
calls, so these threads genuinely overlap inside the C++ engine — the
in-process analogue of the reference's brpc_service_*_sgd_test.cc
hammering a live server."""

import threading

import numpy as np
import pytest

from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.native import native_available
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import (MemorySparseTable, SsdSparseTable,
                                 TableConfig)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native library unavailable")


def _cfg():
    return TableConfig(shard_num=8, accessor_config=AccessorConfig(
        embedx_dim=4, embedx_threshold=0.0,
        sgd=SGDRuleConfig(initial_range=0.0)))


def _hammer(table, ops, n_threads=6, iters=30):
    """Run mixed ops from n_threads concurrently; re-raise any error."""
    errors = []
    barrier = threading.Barrier(n_threads)

    def worker(tid):
        rng = np.random.default_rng(tid)
        try:
            barrier.wait(timeout=30)
            for it in range(iters):
                ops[(tid + it) % len(ops)](rng)
        except Exception as e:  # noqa: BLE001 — reported to the main thread
            errors.append(e)

    ts = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
        assert not t.is_alive(), "worker deadlocked"
    if errors:
        raise errors[0]


def _mixed_ops(table, key_hi=5000):
    pd = table.accessor.push_dim

    def do_push(rng):
        keys = rng.integers(1, key_hi, 256).astype(np.uint64)
        push = np.zeros((256, pd), np.float32)
        push[:, 0] = (keys % 8).astype(np.float32)
        push[:, 1] = 1.0
        push[:, 3:] = rng.normal(0, 0.1, (256, pd - 3)).astype(np.float32)
        table.push_sparse(keys, push)

    def do_pull(rng):
        keys = rng.integers(1, key_hi, 256).astype(np.uint64)
        out = table.pull_sparse(keys, create=False)
        assert np.isfinite(out).all()

    def do_export(rng):
        keys = rng.integers(1, key_hi, 128).astype(np.uint64)
        vals, _ = table.export_full(keys)
        assert np.isfinite(vals).all()

    def do_save(rng):
        k, v = table._native.save_items(mode=0)
        assert len(k) == len(v)
        assert np.isfinite(v).all()

    return [do_push, do_pull, do_export, do_save]


def test_memory_table_concurrent_mixed_ops():
    table = MemorySparseTable(_cfg())
    _hammer(table, _mixed_ops(table))
    assert table.size() > 0
    # post-race integrity: every row still pulls finite values
    keys = np.arange(1, 5000, dtype=np.uint64)
    assert np.isfinite(table.pull_sparse(keys, create=False)).all()


def test_ssd_table_concurrent_mixed_ops_with_tiering(tmp_path):
    table = SsdSparseTable(str(tmp_path / "t"), _cfg())
    ops = _mixed_ops(table)

    def do_spill(rng):
        table.spill(hot_budget=int(rng.integers(0, 2000)))

    def do_shrink_like(rng):  # stats+compact exercise the disk paths
        table.stats()
        table.compact()

    _hammer(table, ops + [do_spill, do_shrink_like])
    assert table.size() > 0
    keys = np.arange(1, 5000, dtype=np.uint64)
    assert np.isfinite(table.pull_sparse(keys, create=False)).all()
    st = table.stats()
    assert st["hot_rows"] + st["cold_rows"] == table.size()


def test_rpc_server_concurrent_clients():
    """Several client connections hammer one in-process server
    concurrently (each connection gets its own handler thread in C++)."""
    import paddle_tpu.ps.rpc as rpc

    server = rpc.NativePsServer(n_trainers=1)
    clients = [rpc.RpcPsClient([f"127.0.0.1:{server.port}"])
               for _ in range(4)]
    cfg = _cfg()
    clients[0].create_sparse_table(0, cfg)
    for c in clients[1:]:
        c.create_sparse_table(0, cfg)  # idempotent re-create

    errors = []

    def worker(ci):
        rng = np.random.default_rng(ci)
        cli = clients[ci]
        try:
            for it in range(20):
                keys = rng.integers(1, 3000, 128).astype(np.uint64)
                push = np.zeros((128, 4 + 4), np.float32)
                push[:, 1] = 1.0
                push[:, 3:] = rng.normal(0, 0.1, (128, 5)).astype(np.float32)
                cli.push_sparse(0, keys, push)
                out = cli.pull_sparse(0, keys, create=False)
                assert np.isfinite(out).all()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
        assert not t.is_alive(), "client thread hung"
    assert not errors, errors[0]
    assert clients[0].size(0) > 0
    for c in clients:
        c.close()
    server.stop()
