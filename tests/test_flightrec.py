"""Crash flight recorder (ISSUE 10): bundle structure + atomic publish,
rate limiting + GC, the module hook surface, every wired trigger site
(breaker open, faultpoint, trainer/serving exception, SIGTERM), and THE
slow e2e: kill-shard mid-CtrStreamTrainer → watchdog failover/breaker
alerts + a postmortem bundle whose merged trace carries the failing
(replayed) request spans and whose metric timeline shows the recovery."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.obs import flightrec, registry, slo, timeseries, trace
from paddle_tpu.obs.flightrec import FlightRecorder
from paddle_tpu.ps import ha, rpc
from paddle_tpu.ps.faultpoints import arm_faultpoint, disarm_faultpoints
from paddle_tpu.ps.table import TableConfig

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(autouse=True)
def _clean_hooks():
    yield
    flightrec.uninstall()
    disarm_faultpoints()
    trace.stop_tracing()
    trace.drain_spans()


def _cfg(tid=0):
    return TableConfig(table_id=tid, shard_num=4, accessor="ctr")


# -- bundle mechanics -------------------------------------------------------

def test_trigger_dumps_parseable_atomic_bundle(tmp_path):
    ring = timeseries.MetricRing()
    reg = registry.Registry()
    reg.counter("c").inc(3)
    ring.append(reg.snapshot(), t=1.0)
    wd = slo.SloWatchdog(ring)
    rec = FlightRecorder(str(tmp_path), ring=ring, watchdog=wd,
                         min_interval_s=0.0)
    rec.note("transport_error", shard=0, endpoint="127.0.0.1:1")
    trace.start_tracing(sample=1.0)
    with trace.span("incident_step"):
        pass
    path = rec.trigger("unit_test", detail="x")
    assert path is not None and os.path.isdir(path)
    # nothing unpublished left behind (atomic-publish contract)
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]
    man = json.load(open(os.path.join(path, "manifest.json")))
    assert man["reason"] == "unit_test" and man["info"]["detail"] == "x"
    assert man["process"]["pid"] == os.getpid()
    assert set(man["files"]) == {"trace.json", "timeline.json",
                                 "alerts.json", "events.json"}
    tr = json.load(open(os.path.join(path, "trace.json")))
    names = {e.get("name") for e in tr["traceEvents"]}
    assert "incident_step" in names                 # the span tail
    assert "EVENT transport_error" in names         # noted events
    # the span tail was PEEKED, not drained — a later export still owns it
    assert any(s.name == "incident_step" for s in trace.peek_spans())
    tl = json.load(open(os.path.join(path, "timeline.json")))
    assert tl["records"][0]["t"] == 1.0
    ev = json.load(open(os.path.join(path, "events.json")))["events"]
    assert ev[0]["kind"] == "transport_error"


def test_rate_limit_gc_and_restart_numbering(tmp_path):
    rec = FlightRecorder(str(tmp_path), min_interval_s=3600.0, keep=2)
    p1 = rec.trigger("first")
    assert p1 is not None
    assert rec.trigger("suppressed") is None        # inside the interval
    assert rec.suppressed == 1
    rec2 = FlightRecorder(str(tmp_path), min_interval_s=0.0, keep=2)
    p2 = rec2.trigger("second")
    p3 = rec2.trigger("third")
    # a restarted recorder numbers past the survivors, never clobbers
    assert [os.path.basename(p) for p in (p1, p2, p3)] == [
        "postmortem_1", "postmortem_2", "postmortem_3"]
    assert [os.path.basename(b) for b in rec2.bundles()] == [
        "postmortem_2", "postmortem_3"]             # keep=2 GC'd the first


def test_module_hooks_and_dump_on_policy(tmp_path):
    # no recorder installed: notify is a no-op returning None
    assert flightrec.notify("breaker_open", endpoint="x") is None
    rec = flightrec.install(FlightRecorder(str(tmp_path), min_interval_s=0.0,
                                           dump_on={"faultpoint"}))
    assert flightrec.installed() is rec
    assert flightrec.notify("slo_alert", rule="r") is None  # note-only kind
    assert len(rec.events()) == 1
    path = flightrec.notify("faultpoint", site="s", action="delay-ms")
    assert path is not None and os.path.isdir(path)
    flightrec.uninstall()
    assert flightrec.notify("faultpoint") is None


def test_trigger_never_raises(tmp_path, monkeypatch):
    rec = FlightRecorder(str(tmp_path), min_interval_s=0.0)
    monkeypatch.setattr(rec, "_dump",
                        lambda *a, **k: (_ for _ in ()).throw(OSError("disk")))
    assert rec.trigger("boom") is None
    assert rec.dump_errors == 1 and "disk" in rec.last_error


# -- wired trigger sites ----------------------------------------------------

def test_breaker_open_counts_and_triggers(tmp_path):
    rec = flightrec.install(FlightRecorder(str(tmp_path), min_interval_s=0.0))
    before = {tuple(sorted(s["labels"].items())): s["value"]
              for s in registry.snapshot()["metrics"]
              .get("ps_breaker_open", {}).get("series", [])}
    b = ha.CircuitBreaker(failures=2, cooldown_s=60.0, name="ep-test:1")
    b.record(False)
    assert not rec.bundles()                        # not open yet
    b.record(False)                                 # transition → OPEN
    assert b.state == ha.CircuitBreaker.OPEN and b.opens == 1
    b.record(False)                                 # already open: no re-fire
    assert b.opens == 1
    kinds = [e["kind"] for e in rec.events()]
    assert kinds.count("breaker_open") == 1
    assert len(rec.bundles()) == 1                  # default dump_on kind
    after = {tuple(sorted(s["labels"].items())): s["value"]
             for s in registry.snapshot()["metrics"]
             ["ps_breaker_open"]["series"]}
    key = (("endpoint", "ep-test:1"),)
    assert after[key] == before.get(key, 0) + 1


def test_faultpoint_fire_counts_and_notifies(tmp_path):
    from paddle_tpu.ps.faultpoints import faultpoint

    rec = flightrec.install(FlightRecorder(str(tmp_path), min_interval_s=0.0))
    arm_faultpoint("fr.site", "delay-ms", ms=0, after=2)
    faultpoint("fr.site")                           # hit 1: below after
    assert not rec.events()
    faultpoint("fr.site")                           # hit 2: fires
    ev = rec.events()
    assert ev and ev[0]["kind"] == "faultpoint" and \
        ev[0]["site"] == "fr.site" and ev[0]["action"] == "delay-ms"
    assert rec.bundles()                            # default dump_on kind
    series = {tuple(sorted(s["labels"].items())): s["value"]
              for s in registry.snapshot()["metrics"]
              ["ps_faultpoints_fired"]["series"]}
    assert series[(("site", "fr.site"),)] >= 1


def test_trainer_exception_notifies_and_reraises(tmp_path, monkeypatch):
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer
    from paddle_tpu.ps.table import MemorySparseTable

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from test_ps_ha import _make_stream_data

    rec = flightrec.install(FlightRecorder(str(tmp_path), min_interval_s=0.0))
    S, D = 3, 2
    tr = CtrStreamTrainer(
        DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=8,
                         dnn_hidden=(8,))),
        optimizer.Adam(1e-2), MemorySparseTable(_cfg()),
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")

    def boom(*a, **k):
        raise RuntimeError("poisoned batch")

    monkeypatch.setattr(tr, "_step", boom)
    with pytest.raises(RuntimeError, match="poisoned batch"):
        tr.train_from_dataset(_make_stream_data(n=128, S=S, D=D),
                              batch_size=64)
    ev = [e for e in rec.events() if e["kind"] == "trainer_exception"]
    assert ev and "poisoned batch" in ev[0]["error"]
    assert rec.bundles()


def test_serving_exception_notifies(tmp_path):
    from paddle_tpu.serving.frontend import FrontendConfig, ServingFrontend

    class BadLookup:
        def lookup(self, keys):
            raise RuntimeError("replica gone")

    rec = flightrec.install(FlightRecorder(str(tmp_path), min_interval_s=0.0))
    with ServingFrontend(BadLookup(),
                         config=FrontendConfig(max_delay_us=0)) as fe:
        with pytest.raises(RuntimeError, match="replica gone"):
            fe(np.arange(4, dtype=np.uint64), deadline_ms=2000)
    ev = [e for e in rec.events() if e["kind"] == "serving_exception"]
    assert ev and "replica gone" in ev[0]["error"]
    assert rec.bundles()


_SIGTERM_SCRIPT = """
import os, signal, sys, time
sys.path.insert(0, {repo!r})
from paddle_tpu.obs import flightrec
rec = flightrec.install(flightrec.FlightRecorder(sys.argv[1],
                                                 min_interval_s=0.0))
assert flightrec.install_signal_handler()
print("READY", flush=True)
os.kill(os.getpid(), signal.SIGTERM)
time.sleep(10)   # never reached: the chained default disposition kills us
"""


def test_sigterm_dumps_bundle_then_terminates(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-c", _SIGTERM_SCRIPT.format(repo=REPO),
         str(tmp_path)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert "READY" in proc.stdout
    assert proc.returncode != 0                     # terminated by SIGTERM
    bundle = os.path.join(tmp_path, "postmortem_1")
    assert os.path.isdir(bundle)
    man = json.load(open(os.path.join(bundle, "manifest.json")))
    assert man["reason"] == "sigterm"
    assert man["info"]["signal"] == 15


# -- THE e2e acceptance (slow): kill-shard under the full always-on layer --

@pytest.mark.slow
def test_e2e_kill_shard_alerts_and_postmortem_bundle(tmp_path):
    """ISSUE 10 acceptance: kill-shard faultpoint mid-CtrStreamTrainer
    → the watchdog raises breaker/failover alerts, the flight recorder
    publishes an atomic bundle whose merged trace contains the failing
    (replayed) request spans and whose metric timeline shows the
    recovery."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from test_ps_ha import _run_stream_trainer

    with ha.HACluster(num_shards=2, replication=2, sync=True) as cluster:
        cli = cluster.client()
        ring = timeseries.MetricRing(capacity=4096)
        sampler = timeseries.JobCollector(client=cli, period_s=0.05,
                                          ring=ring)
        sampler.add_probe(cluster.obs_probe)
        wd = slo.SloWatchdog(ring, [
            slo.SloRule("breaker_open", "ps_breaker_open",
                        kind="threshold", field="delta", agg="rate",
                        threshold=0.0, windows=((30.0, 1.0),)),
            slo.SloRule("failover_promotion", "ha_promotions",
                        kind="threshold", field="delta", agg="rate",
                        threshold=0.0, windows=((30.0, 1.0),)),
        ])
        wd.attach(sampler)
        rec = flightrec.install(FlightRecorder(
            str(tmp_path), ring=ring, watchdog=wd, client=cli,
            min_interval_s=0.0))
        trace.start_tracing(sample=1.0, ring=1 << 17)
        sampler.start()
        try:
            out, _ = _run_stream_trainer(cli, cluster=cluster,
                                         kill_after_pushes=2)
        finally:
            sampler.stop()
            trace.stop_tracing()
        assert cluster.coordinator.promotions >= 1
        assert out["steps"] == 3.0
        t_promo = next(e["t"] for e in rec.events()
                       if e["kind"] == "failover_promotion")
        sampler.tick()                  # final deterministic tick
        wd.evaluate()
        # -- alerts: the failover fired; breaker may or may not have
        # OPENED (3 consecutive failures vs promotion latency), but the
        # promotion alert is deterministic
        fired = {a["rule"] for a in wd.alerts()}
        assert "failover_promotion" in fired, (fired, wd.alerts())

        # -- a bundle was AUTO-dumped by a failure trigger mid-run
        auto = [json.load(open(os.path.join(b, "manifest.json")))
                for b in rec.bundles()]
        assert any(m["reason"] in ("failover_promotion", "breaker_open",
                                   "faultpoint") for m in auto), auto

        # -- the postmortem view at quiesce: merged trace has the
        # failing (replayed) request spans; the timeline shows recovery
        final = rec.trigger("e2e_postmortem")
        assert final is not None
        tr = json.load(open(os.path.join(final, "trace.json")))
        retried = [e for e in tr["traceEvents"]
                   if e.get("ph") == "X" and e.get("args", {}).get("retried")]
        assert retried, "no replayed request span in the merged trace"
        instants = {e["name"] for e in tr["traceEvents"]
                    if e.get("ph") == "i"}
        assert "ALERT failover_promotion" in instants
        assert "EVENT failover_promotion" in instants
        tl = json.load(open(os.path.join(final, "timeline.json")))["records"]
        steps_after = sum(
            s.get("delta", 0)
            for r in tl if r["t"] > t_promo
            for s in r["metrics"].get("trainer_step_time_s", {}).get(
                "series", [])
            if "count" in s
            for s in [{"delta": s["count"]}])
        assert steps_after > 0, "metric timeline shows no post-promotion steps"
        # replication-lag probe fed the job history (the acked-cursor gap)
        lag_curve = [r for r in tl
                     if "ps_replication_lag_entries" in r["metrics"]]
        assert lag_curve, "obs_probe never exported replication lag"
