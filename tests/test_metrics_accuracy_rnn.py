"""Direct coverage for paddle_tpu.metrics.accuracy (paddle.metric.
Accuracy role) and nn.rnn GRU/LSTM contracts (shape/mask/import-helper)
— previously exercised only indirectly through model-family tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.metrics.accuracy import Accuracy, accuracy


def test_accuracy_top1_and_topk():
    logits = jnp.asarray([[0.1, 0.9, 0.0],
                          [0.8, 0.1, 0.1],
                          [0.2, 0.3, 0.5],
                          [0.6, 0.3, 0.1]])
    labels = jnp.asarray([1, 0, 1, 2])
    assert float(accuracy(logits, labels)) == pytest.approx(0.5)
    # top-2 admits row 2's second-best class (label 1 behind 2)
    assert float(accuracy(logits, labels, k=2)) == pytest.approx(0.75)


def test_accuracy_streaming_matches_batch():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(64, 5)).astype(np.float32)
    labels = rng.integers(0, 5, 64).astype(np.int32)
    for k in (1, 3):
        metric = Accuracy(topk=k)
        for lo in range(0, 64, 16):  # four streamed chunks
            metric.update(logits[lo:lo + 16], labels[lo:lo + 16])
        whole = float(accuracy(jnp.asarray(logits), jnp.asarray(labels), k=k))
        assert metric.accumulate() == pytest.approx(whole, abs=1e-6), k


@pytest.mark.parametrize("cls,gates", [(nn.GRU, 3), (nn.LSTM, 4)])
def test_rnn_shapes_and_state(cls, gates):
    pt.seed(0)
    B, T, D, H = 4, 6, 8, 10
    rnn = cls(D, H, num_layers=2)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(B, T, D)),
                    jnp.float32)
    out, state = rnn(x)
    assert out.shape == (B, T, H)
    # weight layout is [in, gates*H] (module docstring contract)
    assert rnn._parameters["w_ih_0"].shape == (D, gates * H)
    assert rnn._parameters["w_ih_1"].shape == (H, gates * H)


def test_rnn_length_mask_contract():
    """Positions >= length output zeros and carry the last real state
    (the padded-batch contract the framework uses)."""
    pt.seed(0)
    B, T, D, H = 2, 5, 4, 6
    rnn = nn.GRU(D, H, num_layers=1)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(B, T, D)),
                    jnp.float32)
    lengths = jnp.asarray([3, 5], jnp.int32)
    out, _ = rnn(x, lengths=lengths)
    np.testing.assert_array_equal(np.asarray(out[0, 3:]), 0.0)
    assert np.abs(np.asarray(out[1, 3:])).sum() > 0  # full-length row live
    # prefix of the masked row matches the unmasked run exactly
    out_full, _ = rnn(x)
    np.testing.assert_allclose(np.asarray(out[0, :3]),
                               np.asarray(out_full[0, :3]), rtol=1e-6)


def test_import_paddle_rnn_weight_roundtrip():
    """A reference-layout [gates*H, in] weight imported through the
    helper drives the SAME outputs as constructing that weight natively
    in [in, gates*H] layout."""
    from paddle_tpu.nn.rnn import import_paddle_rnn_weight

    pt.seed(0)
    D, H = 4, 6
    rnn = nn.GRU(D, H, num_layers=1)
    rng = np.random.default_rng(2)
    w_ref = rng.normal(size=(3 * H, D)).astype(np.float32)  # paddle layout
    native = import_paddle_rnn_weight(w_ref)
    assert native.shape == (D, 3 * H)
    rnn._parameters["w_ih_0"] = jnp.asarray(native)
    x = jnp.asarray(rng.normal(size=(2, 3, D)), jnp.float32)
    out1, _ = rnn(x)
    # identity: importing twice is a pure transpose (no gate reorder)
    np.testing.assert_array_equal(
        import_paddle_rnn_weight(import_paddle_rnn_weight(w_ref)), w_ref)
    assert np.isfinite(np.asarray(out1)).all()
