"""CI gate for the v2 convergence anchor (tools/make_anchor_v2.py):
the stream path (per-batch host-table pull/push) and the pass path
(per-day HBM working set, in-graph fused push) must produce AUC curves
within epsilon ON IDENTICAL DATA over an SSD-backed population — the
reference's expectation that GPUPS training converges like the CPU
table path (test_dist_fleet_base.py:311 harness role).

Runs the same harness as the full-scale anchor at reduced scale.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

from paddle_tpu.ps import rpc  # noqa: E402  (native toolchain probe)

pytestmark = pytest.mark.skipif(
    not rpc.rpc_available(), reason="native toolchain unavailable (SSD tier)")


@pytest.mark.slow
def test_stream_and_pass_paths_auc_parity(tmp_path):
    from make_anchor_v2 import run_anchor

    out = run_anchor(pop=260_000, days=2, steps_per_day=40, batch=256,
                     eval_every=10, dnn=(64, 64), hot=4000, fresh=500,
                     base_dir=str(tmp_path))
    gates = out["gates"]
    assert gates["parity_ok"], gates
    # both paths actually learned (not trivially-equal flat curves)
    assert out["paths"]["stream"]["final_auc"] > 0.58, out["paths"]["stream"]
    assert out["paths"]["pass"]["final_auc"] > 0.58, out["paths"]["pass"]
    # the SSD population really backs the run: cold features got promoted
    # (table size counts resident + cold rows at full population scale)
    assert out["paths"]["stream"]["table_features"] >= 260_000 // 26 * 26
