"""Async device prefetcher (data/prefetcher.py) — the DataFeed
double-buffering role (data_feed.h channels / MiniBatchGpuPack)."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.data.prefetcher import DevicePrefetcher, device_prefetch


def test_order_and_completeness():
    src = (np.full((2,), i) for i in range(20))
    got = [int(x[0]) for x in DevicePrefetcher(src, depth=3)]
    assert got == list(range(20))


def test_transform_applied_and_overlap():
    slow_transformed = []

    def slow_transform(x):
        time.sleep(0.02)
        slow_transformed.append(x)
        return x * 2

    pf = DevicePrefetcher(iter(range(10)), depth=4, transform=slow_transform)
    time.sleep(0.15)  # producer should have run ahead ~depth items
    assert len(slow_transformed) >= 4
    assert list(pf) == [2 * i for i in range(10)]


def test_device_prefetch_moves_leaves():
    batches = [(np.ones((2, 3), np.float32), {"y": np.zeros(2, np.int32)})
               for _ in range(3)]
    out = list(device_prefetch(iter(batches), depth=2))
    assert len(out) == 3
    x, d = out[0]
    assert isinstance(x, jnp.ndarray) and isinstance(d["y"], jnp.ndarray)


def test_producer_exception_propagates():
    def src():
        yield 1
        raise ValueError("boom")

    it = DevicePrefetcher(src(), depth=2)
    assert next(it) == 1
    with pytest.raises(ValueError, match="boom"):
        list(it)


def test_close_stops_producer():
    produced = []

    def src():
        for i in range(10_000):
            produced.append(i)
            yield i

    pf = DevicePrefetcher(src(), depth=2)
    next(pf)
    pf.close()
    time.sleep(0.1)
    n = len(produced)
    time.sleep(0.2)
    assert len(produced) == n  # producer stopped
