"""ResNet family: forward shapes and a few training steps (ladder rung 2)."""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.models.resnet import resnet18, resnet50


def test_resnet50_forward_shape():
    pt.seed(0)
    m = resnet50(num_classes=10)
    x = jnp.zeros((2, 3, 64, 64), jnp.float32)
    out = m(x)
    assert out.shape == (2, 10)
    n_params = sum(int(np.prod(p.shape)) for p in m.parameters())
    # ~25.6M at 1000 classes; with 10-class fc head: ~23.5M
    assert 20_000_000 < n_params < 30_000_000


def test_resnet18_trains():
    pt.seed(1)
    m = resnet18(num_classes=4)
    opt = optimizer.Momentum(learning_rate=0.05)
    step = pt.make_train_step(m, opt, nn.CrossEntropyLoss())
    state = nn.get_state(m)
    opt_state = opt.init(state["params"])
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 3, 32, 32)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 4, size=8).astype(np.int32))
    key = jax.random.key(0)
    first = last = None
    for _ in range(5):
        state, opt_state, loss = step(state, opt_state, key, (x,), (y,))
        first = first if first is not None else float(loss)
        last = float(loss)
    assert last < first
