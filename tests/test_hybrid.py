"""HybridParallelTrainer: dp×pp×cp×mp single-step parity vs serial and
multi-step convergence on the 8-device virtual mesh."""

import pytest
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.core import mesh as mesh_mod
from paddle_tpu.models.ernie import Ernie, ErnieConfig
from paddle_tpu.parallel.hybrid import HybridParallelTrainer

CFG = ErnieConfig(vocab_size=32, hidden_size=16, num_heads=4, ffn_size=32,
                  num_layers=2, max_seq_len=64)


def _data(cfg, batch, seq, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)
    return jnp.asarray(ids), jnp.asarray(labels)


def _serial_loss_from_trainer(trainer, cfg, ids, labels):
    """Assemble a serial Ernie from the trainer's stacked params and
    compute the plain loss (parity oracle)."""
    params = jax.device_get(trainer.params)
    serial = Ernie(cfg)
    pp = serial_blocks = cfg.num_layers
    stages = params["stages"]
    n_stages = next(iter(stages["params"].values())).shape[0]
    bps = cfg.num_layers // n_stages
    state = {"params": {}, "buffers": {}}
    for group in ("params", "buffers"):
        for name, arr in stages[group].items():
            # stage-local name "blocks.b.rest" → serial "blocks.{s*bps+b}.rest"
            parts = name.split(".")
            for s in range(n_stages):
                i = s * bps + int(parts[1])
                state[group][".".join(["blocks", str(i)] + parts[2:])] = arr[s]
        for name, arr in params["aux"]["embed"][group].items():
            state[group]["embed." + name] = arr
        for name, arr in params["aux"]["head"][group].items():
            state[group]["head." + name] = arr
    out, _ = nn.functional_call(serial, state, ids, training=False)
    ce = nn.functional.cross_entropy(out, labels, reduction="none")
    return float(jnp.mean(ce))


def test_hybrid_first_loss_matches_serial():
    pt.seed(0)
    mesh = mesh_mod.make_mesh({"dp": 1, "pp": 2, "cp": 2, "mp": 2})
    trainer = HybridParallelTrainer(CFG, mesh, optimizer.SGD(learning_rate=0.1),
                                    num_micro=2)
    ids, labels = _data(CFG, batch=4, seq=8)
    serial = _serial_loss_from_trainer(trainer, trainer.cfg, ids, labels)
    loss = float(trainer.train_step(ids, labels))
    np.testing.assert_allclose(loss, serial, rtol=1e-4)


def test_hybrid_loss_decreases():
    pt.seed(1)
    mesh = mesh_mod.make_mesh({"dp": 2, "pp": 2, "cp": 1, "mp": 2})
    trainer = HybridParallelTrainer(CFG, mesh, optimizer.Adam(learning_rate=1e-2),
                                    num_micro=2)
    ids, labels = _data(CFG, batch=8, seq=8)
    first = float(trainer.train_step(ids, labels))
    for _ in range(10):
        last = float(trainer.train_step(ids, labels))
    assert last < first, (first, last)


def test_hybrid_moe_runs():
    cfg = dataclasses.replace(CFG, num_experts=4)
    pt.seed(2)
    mesh = mesh_mod.make_mesh({"dp": 2, "pp": 2, "cp": 1, "mp": 2})
    trainer = HybridParallelTrainer(cfg, mesh, optimizer.SGD(learning_rate=0.1),
                                    num_micro=2)
    ids, labels = _data(cfg, batch=8, seq=8)
    first = float(trainer.train_step(ids, labels))
    assert np.isfinite(first)
    for _ in range(5):
        last = float(trainer.train_step(ids, labels))
    assert last < first, (first, last)


@pytest.mark.slow
def test_hybrid_realistic_width_converges():
    """Hybrid step at non-toy width (hidden 128, 4 layers, vocab 512,
    seq 128 over cp=2) on the full 8-device dp×pp×cp×mp mesh: several
    steps must reduce loss — exercises sharding-constraint edges the
    tiny shapes cannot (head dims, ffn splits, vocab partitions all
    > 1 element per shard)."""
    cfg = ErnieConfig(vocab_size=512, hidden_size=128, num_heads=4,
                      ffn_size=256, num_layers=4, max_seq_len=128)
    mesh = mesh_mod.make_mesh({"dp": 1, "pp": 2, "cp": 2, "mp": 2})
    tr = HybridParallelTrainer(cfg, mesh, optimizer.Adam(3e-3), num_micro=2)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(4, 128)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    losses = [float(tr.train_step(ids, labels)) for _ in range(8)]
    assert losses[-1] < losses[0] - 0.1, losses


def test_hybrid_save_load_resume(tmp_path):
    """Checkpoint mid-training and resume in a fresh trainer: the next
    steps follow the same trajectory (params + opt state + rng + step
    counter all restored; global-shape params make the snapshot mesh-
    layout-independent)."""
    mesh = mesh_mod.make_mesh({"dp": 2, "pp": 2, "cp": 1, "mp": 2})
    rng = np.random.default_rng(0)
    ids = rng.integers(0, CFG.vocab_size, size=(8, 8)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    pt.seed(0)
    a = HybridParallelTrainer(CFG, mesh, optimizer.Adam(1e-3), num_micro=2)
    for _ in range(3):
        a.train_step(ids, labels)
    a.save(str(tmp_path / "snap"))
    la = [float(a.train_step(ids, labels)) for _ in range(3)]

    pt.seed(0)
    b = HybridParallelTrainer(CFG, mesh, optimizer.Adam(1e-3), num_micro=2)
    b.load(str(tmp_path / "snap"))
    assert b.global_step == 3
    lb = [float(b.train_step(ids, labels)) for _ in range(3)]
    np.testing.assert_allclose(lb, la, rtol=1e-5)


def test_hybrid_sharding_axis_shards_opt_state():
    """dp×pp×cp×mp×sh: optimizer slots are device-sharded over the "sh"
    axis (ZeRO/sharding_optimizer role) while params stay global; one
    step runs and every sharded slot leaf holds 1/sh of the rows."""
    pt.seed(0)
    mesh = mesh_mod.make_mesh({"dp": 1, "pp": 2, "cp": 1, "mp": 2, "sh": 2})
    tr = HybridParallelTrainer(CFG, mesh, optimizer.Adam(1e-2), num_micro=2)
    def axes_of(spec):
        out = []
        for e in tuple(spec):
            out.extend(e if isinstance(e, tuple) else [e])
        return out

    sharded = [
        leaf for leaf in jax.tree_util.tree_leaves(tr.opt_state["slots"])
        if "sh" in axes_of(leaf.sharding.spec)
    ]
    assert sharded, "no slot leaf is sharded over sh"
    for leaf in sharded:
        local = leaf.addressable_shards[0].data.size
        assert local * 2 <= leaf.size, (local, leaf.size)
    ids, labels = _data(CFG, batch=4, seq=8)
    loss = tr.train_step(ids, labels)
    assert np.isfinite(float(loss))
    # the sh constraint survives the compiled update (donated buffers)
    post = [
        leaf for leaf in jax.tree_util.tree_leaves(tr.opt_state["slots"])
        if "sh" in axes_of(leaf.sharding.spec)
    ]
    assert len(post) == len(sharded), (len(post), len(sharded))


@pytest.mark.slow
def test_hybrid_sharding_matches_unsharded_and_restores(tmp_path):
    """The sh axis is an inner data-parallel group: dp1×sh2 follows the
    same trajectory as dp2 unsharded (sharding changes memory layout,
    not math — sharding_optimizer parity), and a snapshot taken from the
    sharded trainer restores into an UNSHARDED trainer (different shard
    factorization) and continues identically."""
    rng = np.random.default_rng(0)
    ids = rng.integers(0, CFG.vocab_size, size=(8, 8)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    pt.seed(0)
    mesh_sh = mesh_mod.make_mesh({"dp": 1, "pp": 2, "cp": 1, "mp": 2, "sh": 2})
    a = HybridParallelTrainer(CFG, mesh_sh, optimizer.Adam(1e-2), num_micro=2)
    pt.seed(0)
    mesh_dp = mesh_mod.make_mesh({"dp": 2, "pp": 2, "cp": 1, "mp": 2})
    b = HybridParallelTrainer(CFG, mesh_dp, optimizer.Adam(1e-2), num_micro=2)

    for i in range(3):
        la, lb = a.train_step(ids, labels), b.train_step(ids, labels)
        np.testing.assert_allclose(float(la), float(lb), rtol=2e-5,
                                   err_msg=f"step {i}")

    a.save(str(tmp_path / "snap"))
    la = [float(a.train_step(ids, labels)) for _ in range(2)]
    pt.seed(1)  # different init — must be fully overwritten by load
    c = HybridParallelTrainer(CFG, mesh_dp, optimizer.Adam(1e-2), num_micro=2)
    c.load(str(tmp_path / "snap"))
    assert c.global_step == a.global_step - 2
    lc = [float(c.train_step(ids, labels)) for _ in range(2)]
    np.testing.assert_allclose(lc, la, rtol=2e-5)


@pytest.mark.slow
def test_hybrid_grads_match_serial():
    """The serial-gradient oracle that caught PR 3's fix targets: under
    jax 0.4.x the un-pinned psums (hybrid loss, pipe masked psum, the
    standalone parallel_cross_entropy) plus the rep-tracker's confusion
    over the no-op pcast shim produced grads that were ×mp on aux
    params and ZERO on the head — while every loss-only test passed.
    One SGD(lr=1) step must now reproduce jax.grad of the equivalent
    serial model to fp32 roundoff on every parameter."""
    pt.seed(0)
    mesh = mesh_mod.make_mesh({"dp": 2, "pp": 2, "cp": 1, "mp": 2})
    tr = HybridParallelTrainer(CFG, mesh, optimizer.SGD(1.0), num_micro=2)
    ids, labels = _data(CFG, batch=8, seq=8)

    params = jax.device_get(tr.params)
    serial = Ernie(CFG)
    n_stages = next(iter(params["stages"]["params"].values())).shape[0]
    bps = CFG.num_layers // n_stages
    state = {"params": {}, "buffers": {}}
    for group in ("params", "buffers"):
        for name, arr in params["stages"][group].items():
            parts = name.split(".")
            for s in range(n_stages):
                i = s * bps + int(parts[1])
                state[group][".".join(["blocks", str(i)] + parts[2:])] = arr[s]
        for name, arr in params["aux"]["embed"][group].items():
            state[group]["embed." + name] = arr
        for name, arr in params["aux"]["head"][group].items():
            state[group]["head." + name] = arr

    def loss_fn(p):
        out, _ = nn.functional_call(
            serial, {"params": p, "buffers": state["buffers"]}, ids,
            training=False)
        ce = nn.functional.cross_entropy(out, labels, reduction="none")
        return jnp.mean(ce)

    gs = jax.grad(loss_fn)(state["params"])
    tr.train_step(ids, labels)          # SGD lr=1: delta == gradient
    p1 = jax.device_get(tr.params)

    for name, arr in params["stages"]["params"].items():
        g = np.asarray(arr) - np.asarray(p1["stages"]["params"][name])
        rest = name.split(".", 2)[2]
        b = int(name.split(".")[1])
        for s in range(n_stages):
            np.testing.assert_allclose(
                g[s], np.asarray(gs[f"blocks.{s * bps + b}.{rest}"]),
                atol=5e-6, err_msg=f"stage{s}.{name}")
    for an in ("embed", "head"):
        for pn, arr in params["aux"][an]["params"].items():
            g = np.asarray(arr) - np.asarray(p1["aux"][an]["params"][pn])
            np.testing.assert_allclose(g, np.asarray(gs[f"{an}.{pn}"]),
                                       atol=5e-6, err_msg=f"{an}.{pn}")
