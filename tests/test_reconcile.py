"""The declarative control plane (ps/spec.py + ps/reconcile.py) and
its policy simulator (ps/simulate.py).

Fast tier: ClusterSpec document semantics, the pure transition planner
(ordering, grow/shrink arithmetic, unreachable surfacing), SpecStore
single-writer discipline, the reconciler against duck-typed fakes
(convergence, abort/backoff, stall detection + flight-recorder
bundles, the autoscaler-as-proposer and rollout-guard-as-proposer
paths), the ``reconcile_stall`` SLO rule, and the simulator replaying
both committed traces — including the acceptance case where a
hysteresis inversion is CAUGHT as oscillation before it ships.

Slow tier (ci.sh reconcile gate / full): the compound-transition chaos
e2e — canary open + grow 2→4 proposed as ONE spec update with a
kill-shard faultpoint armed mid-bootstrap, content digests and dense
params bit-identical to a sequential direct-primitive oracle.
"""

import json
import os
import threading
import types

import numpy as np
import pytest

from paddle_tpu.core.enforce import PreconditionNotMetError
from paddle_tpu.obs import flightrec
from paddle_tpu.obs import slo
from paddle_tpu.obs.registry import Registry
from paddle_tpu.obs.timeseries import MetricRing
from paddle_tpu.ps.reconcile import Reconciler
from paddle_tpu.ps.simulate import (SimClock, SimCluster, SimController,
                                    diurnal_wave_profile,
                                    flash_crowd_profile, simulate)
from paddle_tpu.ps.spec import (ClusterSpec, SpecStore, plan_transitions,
                                spec_delta)
from paddle_tpu.ps.autoscale import AutoscaleConfig, Autoscaler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MASK = 0xFFFFFFFFFFFFFFFF

try:
    from paddle_tpu.ps.rpc import rpc_available
    _HAVE_RPC = rpc_available()
except Exception:  # pragma: no cover - import guard only
    _HAVE_RPC = False
needs_rpc = pytest.mark.skipif(not _HAVE_RPC,
                               reason="native PS service unavailable")


@pytest.fixture(autouse=True)
def _clean_flightrec():
    yield
    flightrec.uninstall()


# ---------------------------------------------------------------------------
# ClusterSpec: the document
# ---------------------------------------------------------------------------

def test_spec_roundtrip_and_copy_isolation():
    s = ClusterSpec(version=3, shards=4, replication=2, model_version=7,
                    canary={"version": 8, "fraction": 0.25},
                    placements={"0": "collective"}, trainer_np=16,
                    origin="gameday")
    s2 = ClusterSpec.from_json(s.to_json())
    assert s2 == s
    c = s.copy()
    c.placements["1"] = "ps"
    assert "1" not in s.placements  # dict fields are deep-copied


def test_spec_validate_rejects_bad_documents():
    with pytest.raises(PreconditionNotMetError):
        ClusterSpec(shards=0).validate()
    with pytest.raises(PreconditionNotMetError):
        ClusterSpec(canary={"version": 2, "fraction": 1.5}).validate()
    with pytest.raises(PreconditionNotMetError):
        ClusterSpec(canary={"fraction": 0.25}).validate()  # no version
    with pytest.raises(PreconditionNotMetError):
        ClusterSpec(placements={"0": "gpu"}).validate()
    with pytest.raises(PreconditionNotMetError):
        ClusterSpec(trainer_np=0).validate()
    ClusterSpec(shards=8, canary={"version": 2, "fraction": 0.5},
                placements={"0": "ps"}, trainer_np=4).validate()


def test_spec_delta_skips_version_and_origin():
    a = ClusterSpec(version=1, shards=2, origin="operator")
    b = ClusterSpec(version=9, shards=4, origin="autoscaler")
    d = spec_delta(a, b)
    assert d == {"shards": {"from": 2, "to": 4}}
    assert spec_delta(a, a.copy()) == {}


# ---------------------------------------------------------------------------
# plan_transitions: the pure diff
# ---------------------------------------------------------------------------

def _obs(shards=2, stable=None, canary=None, placements=None,
         trainer_np=None):
    return {"shards": shards, "stable_version": stable, "canary": canary,
            "placements": placements or {}, "trainer_np": trainer_np}


def test_plan_grow_is_one_factor_step():
    steps = plan_transitions(ClusterSpec(shards=8), _obs(shards=2))
    assert [s.kind for s in steps] == ["reshard_grow"]
    assert steps[0].detail == {"factor": 4, "from": 2, "to": 8}


def test_plan_shrink_chains_halvings():
    steps = plan_transitions(ClusterSpec(shards=2), _obs(shards=8))
    assert [s.kind for s in steps] == ["reshard_shrink", "reshard_shrink"]
    assert [s.detail["to"] for s in steps] == [4, 2]


def test_plan_unreachable_is_surfaced_not_dropped():
    up = plan_transitions(ClusterSpec(shards=3), _obs(shards=2))
    assert [s.kind for s in up] == ["unreachable"]
    down = plan_transitions(ClusterSpec(shards=4), _obs(shards=6))
    assert [s.kind for s in down] == ["unreachable"]
    assert down[0].detail == {"field": "shards", "from": 6, "to": 4}


def test_plan_canary_moves_precede_the_reshard():
    spec = ClusterSpec(shards=4, model_version=1,
                       canary={"version": 2, "fraction": 0.25})
    steps = plan_transitions(spec, _obs(shards=2, stable=1))
    assert [s.kind for s in steps] == ["canary_open", "reshard_grow"]


def test_plan_canary_clear_promotes_or_rolls_back():
    obs = _obs(stable=1, canary={"version": 2, "fraction": 0.25})
    promote = plan_transitions(ClusterSpec(shards=2, model_version=2), obs)
    assert [s.kind for s in promote] == ["canary_promote"]
    rollback = plan_transitions(ClusterSpec(shards=2, model_version=1), obs)
    assert [s.kind for s in rollback] == ["canary_rollback"]


def test_plan_canary_retarget_is_rollback_then_open():
    spec = ClusterSpec(shards=2, model_version=1,
                       canary={"version": 3, "fraction": 0.5})
    steps = plan_transitions(
        spec, _obs(stable=1, canary={"version": 2, "fraction": 0.25}))
    assert [s.kind for s in steps] == ["canary_rollback", "canary_open"]
    assert steps[1].detail == {"version": 3, "fraction": 0.5}


def test_plan_canary_open_skipped_when_already_stable():
    # a promote raced the proposal: the canary version already IS the
    # fleet-wide stable — nothing to open
    spec = ClusterSpec(shards=2, model_version=2,
                       canary={"version": 2, "fraction": 0.25})
    assert plan_transitions(spec, _obs(stable=2)) == []


def test_plan_placement_and_trainer_lever():
    spec = ClusterSpec(shards=2, placements={"0": "collective", "1": "ps"},
                       trainer_np=8)
    steps = plan_transitions(spec, _obs(trainer_np=4))
    # observed placement defaults to "ps": only table 0 moves
    assert [(s.kind, s.detail.get("table")) for s in steps] == \
        [("placement", "0"), ("trainer_np", None)]
    assert steps[1].detail == {"np": 8}


# ---------------------------------------------------------------------------
# SpecStore: single-writer versioning
# ---------------------------------------------------------------------------

def test_spec_store_initialize_refuses_clobber():
    cluster = SimCluster(2, job_id="specstore-a")
    st = SpecStore(cluster.store, cluster.job_id)
    st.initialize(ClusterSpec(version=0, shards=2))
    with pytest.raises(PreconditionNotMetError):
        st.initialize(ClusterSpec(version=0, shards=4))


def test_spec_store_propose_dedups_and_journals():
    cluster = SimCluster(2, job_id="specstore-b")
    st = SpecStore(cluster.store, cluster.job_id)
    st.initialize(ClusterSpec(version=0, shards=2))
    seen = []
    st.subscribe(seen.append)

    def noop(s):
        s.shards = 2
    assert st.propose("autoscaler", noop).version == 0  # no-op: no bump
    assert st.log() == [] and seen == []

    def grow(s):
        s.shards = 4
    new = st.propose("autoscaler", grow)
    assert new.version == 1 and new.origin == "autoscaler"
    assert [s.version for s in seen] == [1]
    log = st.log()
    assert len(log) == 1
    assert log[0]["delta"] == {"shards": {"from": 2, "to": 4}}
    # re-asserting the same target every poll does not churn versions
    assert st.propose("autoscaler", grow).version == 1
    assert len(st.log()) == 1


# ---------------------------------------------------------------------------
# Reconciler against duck-typed fakes
# ---------------------------------------------------------------------------

def _sim_rig(job_id, shards=2, **kw):
    clock = SimClock()
    cluster = SimCluster(shards, job_id=job_id)
    ctrl = SimController(cluster, clock)
    rec = Reconciler(cluster, ctrl, clock=clock.now,
                     sleep=lambda s: clock.advance(s), **kw)
    rec.capture()
    return clock, cluster, ctrl, rec


def test_capture_is_idempotent_version_zero():
    _, cluster, _, rec = _sim_rig("cap-a")
    spec = rec.capture()
    assert spec.version == 0 and spec.shards == 2
    assert spec.origin == "capture"
    rec.propose_shards(4)
    assert rec.capture().version == 1  # never clobbers the live doc


def test_reconcile_grow_converges_in_one_pass():
    _, cluster, ctrl, rec = _sim_rig("grow-a")
    spec = rec.propose_shards(8, origin="operator")
    assert spec.version == 1
    assert not rec.converged()
    assert rec.step(now=0.0) == 1           # ONE factor-4 grow
    assert cluster.num_shards == 8
    assert rec.converged() and rec.stalled_ticks() == 0
    kinds = [e["kind"] for e in rec.events]
    assert kinds.count("transition") == 1
    tr = next(e for e in rec.events if e["kind"] == "transition")
    assert tr["transition"] == "reshard_grow"
    assert tr["spec_version"] == 1
    assert tr["info"]["to_shards"] == 8
    # journal mirrored to the elastic store
    assert cluster.store.get("ps/grow-a/reconcile/1") is not None


def test_reconcile_shrink_chain_verified_per_step():
    _, cluster, ctrl, rec = _sim_rig("shrink-a", shards=8)
    rec.propose_shards(2)
    assert rec.step(now=0.0) == 2           # two halvings, one pass
    assert cluster.num_shards == 2
    assert [op["to_shards"] for op in ctrl.ops] == [4, 2]


def test_reconcile_trainer_np_lever():
    _, cluster, _, rec = _sim_rig(
        "np-a", elastic_job_id="np-a-job", trainer_np_fn=lambda n: 2 * n)
    rec.propose_shards(4)                   # trainer_np rides the shards
    assert rec.step(now=0.0) == 2
    assert rec.observe()["trainer_np"] == 8
    kinds = [e["transition"] for e in rec.events
             if e["kind"] == "transition"]
    assert kinds == ["reshard_grow", "trainer_np"]


def test_autoscaler_proposes_and_reconciler_actuates():
    clock, cluster, ctrl, rec = _sim_rig("as-a")
    cfg = AutoscaleConfig(min_shards=1, max_shards=8, cooldown_up_s=30.0)
    scaler = Autoscaler(ctrl, config=cfg, clock=clock.now, proposer=rec)
    scaler.notify_fire(types.SimpleNamespace(rule="step_time_p95"))
    assert scaler.step(now=0.0) == "up"
    # the decision only WROTE desired state — nothing actuated yet
    assert cluster.num_shards == 2
    spec = rec.spec_store.read()
    assert (spec.version, spec.shards, spec.origin) == (1, 4, "autoscaler")
    ev = [e for e in scaler.events if e["kind"] == "scale_proposed"]
    assert len(ev) == 1 and ev[0]["spec_version"] == 1
    assert rec.step(now=0.0) == 1
    assert cluster.num_shards == 4
    # hysteresis paces the DECISION: cooldown starts at proposal time
    assert scaler.step(now=1.0) is None
    assert scaler.step(now=31.0) == "up"
    assert rec.spec_store.read().shards == 8


class _FailController:
    """grow/shrink raise until ``healed`` — the abort/stall rigs."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.healed = False
        self.calls = 0

    def grow(self, factor, replication=None):
        self.calls += 1
        if not self.healed:
            raise RuntimeError("cutover refused (injected)")
        self.cluster._n *= int(factor)
        return {"to_shards": self.cluster._n, "cutover_pause_ms": 1.0}

    def shrink(self, divisor=2):
        raise RuntimeError("cutover refused (injected)")


def test_abort_journals_dumps_bundle_and_backs_off(tmp_path):
    fr = flightrec.install(
        flightrec.FlightRecorder(str(tmp_path), min_interval_s=0.0))
    cluster = SimCluster(2, job_id="abort-a")
    ctrl = _FailController(cluster)
    rec = Reconciler(cluster, ctrl, abort_backoff_s=5.0)
    rec.capture()
    rec.propose_shards(4)
    assert rec.step(now=0.0) == 0
    assert rec.aborts() == 1
    ab = [e for e in rec.events if e["kind"] == "spec_abort"]
    assert len(ab) == 1 and "cutover refused" in ab[0]["error"]
    assert ab[0]["transition"] == "reshard_grow"
    # the postmortem bundle carries the observed-vs-desired spec diff
    mans = [json.load(open(os.path.join(b, "manifest.json")))
            for b in fr.bundles()]
    man = next(m for m in mans if m["reason"] == "spec_abort")
    assert man["info"]["spec_diff"]["shards"] == {"from": 2, "to": 4}
    # cooldown: no re-actuation attempt inside the backoff window
    assert rec.step(now=1.0) == 0 and ctrl.calls == 1
    assert rec.step(now=6.0) == 0 and ctrl.calls == 2
    assert rec.aborts() == 2
    # heal the primitive: the same spec converges, stall state clears
    ctrl.healed = True
    assert rec.step(now=12.0) == 1
    assert cluster.num_shards == 4 and rec.converged()
    assert rec.stalled_ticks() == 0


def test_stall_detection_dumps_once_per_episode(tmp_path):
    fr = flightrec.install(
        flightrec.FlightRecorder(str(tmp_path), min_interval_s=0.0))
    cluster = SimCluster(2, job_id="stall-a")
    ctrl = _FailController(cluster)
    rec = Reconciler(cluster, ctrl, stall_ticks=3, abort_backoff_s=0.0)
    rec.capture()
    rec.propose_shards(4)
    for i in range(8):
        assert rec.step(now=float(i)) == 0
    assert rec.stalled_ticks() == 8
    stalls = [e for e in rec.events if e["kind"] == "reconcile_stall"]
    assert len(stalls) == 1                 # once per episode, not per tick
    assert stalls[0]["ticks"] == 4 and stalls[0]["pending"] == \
        ["reshard_grow"]
    mans = [json.load(open(os.path.join(b, "manifest.json")))
            for b in fr.bundles()]
    stall_mans = [m for m in mans if m["reason"] == "reconcile_stall"]
    assert len(stall_mans) == 1
    assert stall_mans[0]["info"]["spec_diff"]["shards"]["to"] == 4
    # a completed transition ends the episode and re-arms the dump
    ctrl.healed = True
    assert rec.step(now=9.0) == 1
    assert rec.stalled_ticks() == 0


def test_unreachable_spec_aborts_with_the_reason():
    _, cluster, _, rec = _sim_rig("unreach-a")
    rec.propose_shards(3)                   # 2 -> 3: no primitive reaches it
    assert rec.step(now=0.0) == 0
    assert rec.aborts() == 1
    ab = [e for e in rec.events if e["kind"] == "spec_abort"]
    assert "unreachable" in ab[0]["error"]
    assert cluster.num_shards == 2


def test_reconcile_stall_slo_rule_fires():
    reg = Registry()
    reg.gauge("reconcile_stall_ticks", job="slo-a").set(12.0)
    ring = MetricRing()
    ring.append(reg.snapshot(), t=100.0)
    rules = [r for r in slo.default_rules() if r.name == "reconcile_stall"]
    assert len(rules) == 1
    wd = slo.SloWatchdog(ring, rules)
    assert [a.rule for a in wd.evaluate(now=100.0)] == ["reconcile_stall"]


# ---------------------------------------------------------------------------
# rollout guard as proposer (serving plane under spec control)
# ---------------------------------------------------------------------------

def _serving_rig(rec_factory, job_id):
    """4-member router-protocol fleet over real frontends (the gameday
    stubs), a RolloutManager, and a Reconciler wired as its proposer."""
    import random as _random

    from paddle_tpu.serving import (DenseModel, FrontendConfig,
                                    RolloutConfig, RolloutManager,
                                    RouterConfig, ServingFrontend,
                                    ServingRouter)

    class _Lookup:
        def lookup(self, keys):
            k = keys.astype(np.float64)
            return np.stack([k, k + 0.5], axis=1).astype(np.float32)

    class _Member:
        def __init__(self, name, flat):
            self.endpoint = name
            self.lookup = _Lookup()
            self.frontend = ServingFrontend(
                self.lookup, config=FrontendConfig(
                    max_batch=8, max_delay_us=100, queue_cap=256),
                replica_label=name)
            self.model = DenseModel(lambda f: f, flat.copy(), version=1,
                                    sink=lambda p: None)

        @property
        def healthy(self):
            return not self.frontend.stopped

        def stop(self):
            self.frontend.stop()

    flat1 = np.arange(16, dtype=np.float32)
    flat2 = flat1 + 2.0
    members = [_Member(f"m{i}", flat1) for i in range(4)]
    router = ServingRouter(RouterConfig(), rng=_random.Random(0))
    for m in members:
        router.attach(m)
    rollout = RolloutManager(lambda: members, router,
                             RolloutConfig(canary_members=1))
    v1 = rollout.register_baseline(flat1)
    for m in members:
        m.model.set(v1, flat1)
    rec = rec_factory(rollout, lambda v: {2: flat2}[v])
    rollout.set_proposer(rec)
    return members, router, rollout, rec


def test_rollout_guard_rolls_back_through_the_spec():
    cluster = SimCluster(2, job_id="guard-a")
    members, router, rollout, rec = _serving_rig(
        lambda ro, src: Reconciler(cluster, None, rollout=ro,
                                   model_source=src), "guard-a")
    try:
        rec.capture()
        rec.propose_canary(2, 0.25)
        assert rec.step(now=0.0) == 1
        assert rollout.canary_open() == 2
        # SLO guard fires: the guard PROPOSES (clears spec.canary) —
        # the canary stays open until the actuator runs the rollback
        rollout._on_alert(types.SimpleNamespace(rule="serving_p99"))
        assert rec.spec_store.read().canary is None
        assert rollout.canary_open() == 2
        rb = [e for e in rec.events if e["kind"] == "rollback_proposed"]
        assert rb and rb[0]["reason"] == "slo_alert:serving_p99"
        assert rec.step(now=1.0) == 1
        assert rollout.canary_open() is None
        assert all(v == 1 for v, _ in rollout.fleet_versions().values())
    finally:
        for m in members:
            m.stop()
        router.stop()


def test_spec_promote_flips_the_fleet():
    cluster = SimCluster(2, job_id="promote-a")
    members, router, rollout, rec = _serving_rig(
        lambda ro, src: Reconciler(cluster, None, rollout=ro,
                                   model_source=src), "promote-a")
    try:
        rec.capture()
        rec.propose_canary(2, 0.25)
        assert rec.step(now=0.0) == 1
        rec.propose_promote()
        assert rec.step(now=1.0) == 1
        assert rollout.canary_open() is None
        assert rollout.stable_version() == 2
        assert all(v == 2 for v, _ in rollout.fleet_versions().values())
        assert rec.converged()
    finally:
        for m in members:
            m.stop()
        router.stop()


# ---------------------------------------------------------------------------
# the policy simulator: committed traces, 1000-shard scale
# ---------------------------------------------------------------------------

STOCK = dict(min_shards=256, max_shards=1024)


def test_sim_diurnal_wave_stock_policy_is_stable():
    """RESHARD.json's measured diurnal wave at 1000-shard scale: the
    stock hysteresis tracks the wave without flapping, inside the
    acceptance wall budget."""
    res = simulate(AutoscaleConfig(**STOCK),
                   diurnal_wave_profile(os.path.join(REPO, "RESHARD.json"),
                                        base_shards=512))
    assert res.wall_s < 60.0
    assert res.scale_events, "the wave must move the fleet"
    assert res.max_shards_seen() == 1024            # rode the peak...
    assert res.final_shards < 1024                  # ...and came back down
    assert res.oscillations(window_s=15.0) == 0     # no flapping
    assert all(t["shards"] >= 256 for t in res.timeline)
    assert res.spec_version >= 1


def test_sim_hysteresis_inversion_caught_as_oscillation():
    """The acceptance misconfiguration: cooldowns/hold collapsed to
    zero (hysteresis inverted away) flaps the fleet on the SAME trace
    the stock policy rides cleanly — the simulator catches the policy
    bug before it ships."""
    profile = lambda: diurnal_wave_profile(  # noqa: E731
        os.path.join(REPO, "RESHARD.json"), base_shards=256)
    stock = simulate(AutoscaleConfig(**STOCK), profile(),
                     fire_after_ticks=1, clear_after_ticks=1)
    broken = simulate(
        AutoscaleConfig(cooldown_up_s=0.0, cooldown_down_s=0.0,
                        clear_hold_s=0.0, **STOCK),
        profile(), fire_after_ticks=1, clear_after_ticks=1)
    assert stock.oscillations(window_s=15.0) == 0
    assert broken.oscillations(window_s=15.0) >= 5
    assert len(broken.scale_events) > len(stock.scale_events)


def test_sim_flash_crowd_scales_up_and_recovers():
    res = simulate(AutoscaleConfig(**STOCK),
                   flash_crowd_profile(os.path.join(REPO,
                                                    "RECSYS_E2E.json"),
                                       base_shards=256))
    assert res.wall_s < 60.0
    assert res.max_shards_seen() > 256              # the spike moved it
    assert res.oscillations(window_s=15.0) == 0
    assert res.final_shards >= 256
    # every actuation the simulator ran came through the spec
    assert res.spec_version >= len(res.scale_events)


# ---------------------------------------------------------------------------
# THE acceptance e2e (slow): ONE compound spec update under chaos
# ---------------------------------------------------------------------------

def _table_cfg():
    from paddle_tpu.ps.table import TableConfig
    return TableConfig(table_id=0, shard_num=4, accessor="ctr")


def _stream_data(n, S, D, seed=0):
    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc

    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        ids = rng.integers(0, 48, S)
        dense = rng.normal(size=D)
        label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
        lines.append(" ".join([f"1 {v}" for v in ids]
                              + [f"1 {v:.4f}" for v in dense]
                              + [f"1 {label}"]))
    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1)
              for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1)
                for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])
    ds = InMemoryDataset(slots, seed=0)
    ds.load_from_lines(lines)
    return ds


def _stream_trainer(cli, cluster, S=3, D=2):
    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps.communicator import SyncCommunicator
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer

    comm = SyncCommunicator(cli)
    # sync replication made AIRTIGHT per batch: nothing is
    # acked-but-unshipped when the chaos kill fires
    base_send = comm.send_sparse

    def send_and_drain(table_id, keys, values):
        base_send(table_id, keys, values)
        cluster.drain()

    comm.send_sparse = send_and_drain
    comm.start()
    pt.seed(0)
    tr = CtrStreamTrainer(
        DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=8,
                         dnn_hidden=(8,))),
        optimizer.Adam(1e-2), None, communicator=comm, table_id=0,
        embedx_dim=8,
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
    return tr, comm


def _jax_flatten(tree):
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(k), np.asarray(v)) for k, v in flat]


@needs_rpc
@pytest.mark.slow
def test_compound_transition_chaos_e2e():
    """Canary open (v2 at 0.25) + grow 2→4 proposed as ONE spec update
    while a CtrStreamTrainer streams (sync replication) and a
    kill-shard faultpoint fires mid-bootstrap: the reconciler sequences
    canary-before-reshard at the SAME spec version, the coordinator's
    promotion repairs observed state under the in-flight transition,
    and the result is bit-identical to a sequential direct-primitive
    oracle — rows, content digest, pulled probe, dense params."""
    import jax  # noqa: F401 - fail fast if params can't be compared

    from paddle_tpu.ps import ha, rpc
    from paddle_tpu.ps.reshard import ReshardController

    S, D = 3, 2
    EPOCHS = 4
    BLOCKS = 64

    def run(compound: bool):
        with ha.HACluster(num_shards=2, replication=2, sync=True) as c:
            members = router = rec = comm = None
            try:
                cli = c.client()
                cli.create_sparse_table(0, _table_cfg())
                ctrl = ReshardController(c)
                if compound:
                    members, router, rollout, rec = _serving_rig(
                        lambda ro, src: Reconciler(
                            c, ctrl, rollout=ro, model_source=src,
                            poll_s=0.02).start(), "compound")
                else:
                    members, router, rollout, _ = _serving_rig(
                        lambda ro, src: None, "oracle")
                tr, comm = _stream_trainer(cli, c, S, D)
                steps = 0
                for e in range(EPOCHS):
                    if e == 1:
                        if compound:
                            # die on the FIRST bootstrap snapshot read
                            # of shard 0's primary — mid-transition
                            c.primary(0).server.arm_fault(
                                "kill-shard", cmd=rpc._SAVE_ALL, after=1)

                            def mut(s):
                                s.canary = {"version": 2,
                                            "fraction": 0.25}
                                s.shards = 4
                            spec = rec.propose("e2e", mut)
                            assert spec.version == 1
                        else:
                            # the sequential oracle: same moves, direct
                            # primitives, no reconciler, no chaos
                            rollout.begin_canary(np.arange(
                                16, dtype=np.float32) + 2.0,
                                fraction=0.25)
                            ctrl.grow(2)
                    out = tr.train_from_dataset(
                        _stream_data(768, S, D, seed=e), batch_size=128)
                    steps += out["steps"]
                if compound:
                    assert rec.wait_converged(120.0), list(rec.events)
                    # compound ordering: canary opened BEFORE the grow,
                    # both under the same spec version
                    trans = [e for e in rec.events
                             if e["kind"] == "transition"]
                    kinds = [t["transition"] for t in trans]
                    assert kinds.index("canary_open") < \
                        kinds.index("reshard_grow")
                    assert {t["spec_version"] for t in trans} == {1}
                    # the kill landed mid-transition and was repaired
                    assert c.coordinator.promotions >= 1
                    assert any(e["kind"] == "observed_repair"
                               for e in rec.events)
                comm.barrier()
                c.drain()
                assert len(c.routing.read()[1]) == 4
                assert rollout.canary_open() == 2
                # exact split: request routing against band arithmetic
                expect = sum(router.in_canary_band(b, 0.25)
                             for b in range(BLOCKS))
                for b in range(BLOCKS):
                    router.submit(
                        np.arange(b << 6, (b << 6) + 8, dtype=np.uint64),
                        deadline_ms=5000).result(10)
                counts = router.stats()["version_counts"]
                assert counts.get("2", 0) == expect, (counts, expect)
                probe = np.unique(
                    (np.arange(0, 48, dtype=np.uint64)[None, :]
                     + (np.arange(S, dtype=np.uint64)[:, None]
                        << np.uint64(32))).reshape(-1))
                pulled = cli.pull_sparse(0, probe, create=False)
                digest = sum(cli.digest(0)) & MASK
                rows = cli.size(0)
                params = jax.tree_util.tree_map(np.asarray, tr.params)
                return pulled, params, digest, rows, steps
            finally:
                if rec is not None:
                    rec.stop()
                if comm is not None:
                    comm.stop()
                if members is not None:
                    for m in members:
                        m.stop()
                if router is not None:
                    router.stop()

    p_c, w_c, d_c, n_c, s1 = run(compound=True)
    p_o, w_o, d_o, n_o, s2 = run(compound=False)
    assert s1 == s2                 # identical batch sequences
    assert n_c == n_o               # zero lost or doubled rows...
    assert d_c == d_o               # ...bit-exactly (content digests)
    np.testing.assert_array_equal(p_c, p_o)
    for (ka, va), (kb, vb) in zip(sorted(_jax_flatten(w_c)),
                                  sorted(_jax_flatten(w_o))):
        assert ka == kb
        np.testing.assert_array_equal(va, vb)
