"""Runtime wire-contract drift guard (tier-1).

Reuses the graftlint wire_contract pass's two extractors as a library
and pins the csrc↔python mirror in plain pytest, so protocol drift
fails `pytest tests/` even for someone who never runs `ci.sh lint`.
The lint pass is the commit-time gate; this is the belt to its braces
(and the static complement of the PR 4 runtime digest machinery).
"""

import os
import struct
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(REPO, "tools", "lint"))

import wire_contract as wc  # noqa: E402

CSRC = os.path.join(REPO, "paddle_tpu", "csrc", "ps_service.cc")


@pytest.fixture(scope="module")
def cs():
    return wc.extract_csrc(CSRC)


@pytest.fixture(scope="module")
def py():
    return wc.extract_python(REPO)


def test_every_csrc_cmd_id_mirrored(cs, py):
    assert cs.cmds, "extractor found no Cmd enum"
    for name, (val, _line) in cs.cmds.items():
        spec = wc.CONTRACT.get(name)
        assert spec is not None, f"csrc cmd {name} not in CONTRACT"
        assert spec.id == val, f"{name}: contract {spec.id} != csrc {val}"
        if spec.py is not None:
            mod, const = spec.py
            got = py.consts[mod].get(const)
            assert got is not None, f"python mirror {const} missing"
            assert got[0] == val, f"{const} = {got[0]} != csrc {name} = {val}"
    # and nothing in the contract has silently left the enum
    assert set(wc.CONTRACT) == set(cs.cmds)


def test_error_codes_mirrored(cs, py):
    assert set(wc.ERR_CONTRACT) == set(cs.errs)
    for name, (val, mirror) in wc.ERR_CONTRACT.items():
        assert cs.errs[name][0] == val
        if mirror is None:
            continue
        kind, nm = mirror
        if kind == "ha":
            assert py.consts["ha"][nm][0] == val, \
                f"ha.{nm} != csrc {name} = {val}"
        else:
            got = py.raises.get(val)
            assert got is not None, \
                f"_ServerConn.check maps nothing for status {val} ({name})"
            assert got[0] == nm, \
                f"status {val}: raises {got[0]}, contract wants {nm}"


def test_req_header_layout_and_size(cs, py):
    fields = cs.structs["ReqHeader"]
    fmt = wc.struct_format(fields)
    assert py.hdr_format is not None
    assert py.hdr_format.replace(" ", "") == fmt, \
        f"ha._HDR {py.hdr_format!r} != csrc ReqHeader {fmt!r}"
    size = struct.calcsize(fmt)
    assert py.req_header_bytes == size, \
        f"rpc._REQ_HEADER_BYTES {py.req_header_bytes} != packed {size}"
    # the fixed trace-context field is exactly the obs plane's constant
    assert py.wire_context_bytes == 16
    assert size == 28 + py.wire_context_bytes


def test_obs_span_layout(cs, py):
    fmt = wc.struct_format(cs.structs["ObsSpan"])
    assert py.span_format is not None
    assert py.span_format.replace(" ", "") == fmt
    assert struct.calcsize(fmt) == 64  # the csrc static_assert's twin


def test_classification_tables_match_contract(cs):
    # the full cross-validation (tap/gate/keyed/create + the
    # untapped-mutation rule) — identical to the lint gate
    diags = wc.check(REPO)
    assert diags == [], [str(d) for d in diags]


def test_python_mirrors_agree_with_runtime_modules():
    # the extractor reads source; make sure source == imported runtime
    # (a conditional re-definition would fool a static extractor)
    from paddle_tpu.obs import trace
    from paddle_tpu.ps import graph_client, ha, rpc
    py = wc.extract_python(REPO)
    for key, mod in (("rpc", rpc), ("graph", graph_client), ("ha", ha)):
        for const, (val, _ln) in py.consts[key].items():
            runtime = getattr(mod, const, None)
            if isinstance(runtime, int):
                assert runtime == val, f"{key}.{const}: {runtime} != {val}"
    assert trace.WIRE_CONTEXT_BYTES == py.wire_context_bytes
    assert ha._HDR.format.lstrip("<") == py.hdr_format.lstrip("<")
    assert ha._HDR.size == py.req_header_bytes


def test_push_wire_flags_mirrored(cs, py):
    """The quantized push-payload aux bits (PushWireFlag) are pinned in
    both languages — the aux word rides the tapped replication frames,
    so a drifted flag silently corrupts every replaying backup."""
    assert cs.flags, "extractor found no PushWireFlag enum"
    assert set(wc.FLAG_CONTRACT) == set(cs.flags)
    for name, (val, (mod, const)) in wc.FLAG_CONTRACT.items():
        assert cs.flags[name][0] == val, \
            f"{name}: contract {val} != csrc {cs.flags[name][0]}"
        got = py.consts[mod].get(const)
        assert got is not None, f"python mirror {const} missing"
        assert got[0] == val, f"{const} = {got[0]} != csrc {name} = {val}"


def test_push_wire_flag_drift_detected(tmp_path):
    """Perturbation pin: a drifted flag value in a csrc copy trips
    wire-flag-drift (the extractor really reads the enum, the check
    really compares it)."""
    src = open(CSRC, encoding="utf-8").read()
    bad = src.replace("kPushWireI8 = 2,", "kPushWireI8 = 4,")
    assert bad != src
    perturbed = wc.extract_csrc(_write_tmp(tmp_path, bad))
    assert perturbed.flags["kPushWireI8"][0] == 4
    # and the runtime constants agree with the real enum
    from paddle_tpu.ps import rpc
    assert rpc._PUSH_WIRE_F16 == wc.FLAG_CONTRACT["kPushWireF16"][0]
    assert rpc._PUSH_WIRE_I8 == wc.FLAG_CONTRACT["kPushWireI8"][0]
    assert rpc._PUSH_WIRE_BLOCK_SHIFT == \
        wc.FLAG_CONTRACT["kPushWireBlockShift"][0]


def _write_tmp(tmp_path, content):
    p = tmp_path / "ps_service.cc"
    p.write_text(content, encoding="utf-8")
    return str(p)
