"""Unified observability plane (ISSUE 8): registry semantics, trace
propagation through the RPC wire into the C++ shard and back, failover
replay marking, job-wide aggregation, and the timeline merge."""

import json
import os
import sys
import threading

import numpy as np
import pytest

from paddle_tpu.core.flags import get_flags, set_flags
from paddle_tpu.obs import aggregate, registry, trace
from paddle_tpu.obs.registry import CounterGroup, Registry
from paddle_tpu.ps import ha, rpc
from paddle_tpu.ps.table import TableConfig

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _cfg(tid=0):
    return TableConfig(table_id=tid, shard_num=4, accessor="ctr")


@pytest.fixture(autouse=True)
def _tracing_off_after():
    yield
    trace.stop_tracing()
    trace.drain_spans()


# -- registry ---------------------------------------------------------------

def test_counter_gauge_histogram_basics():
    reg = Registry()
    c = reg.counter("reqs", table="0")
    c.inc()
    c.inc(4)
    assert c.value == 5
    g = reg.gauge("density", table="0")
    g.set(1.0)
    g.set(0.5)
    assert g.value == 0.5
    assert 0.5 < g.ewma < 1.0  # EWMA lags the last write
    h = reg.histogram("lat", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    hs = h.hist()
    assert hs["count"] == 3 and hs["buckets"] == [1, 1, 1]
    snap = reg.snapshot()
    assert snap["metrics"]["reqs"]["series"][0]["value"] == 5
    assert snap["metrics"]["reqs"]["series"][0]["labels"] == {"table": "0"}
    assert snap["process"]["pid"] == os.getpid()


def test_same_labels_same_handle_distinct_labels_distinct():
    reg = Registry()
    a = reg.counter("fam", table="0")
    b = reg.counter("fam", table="0")
    c = reg.counter("fam", table="1")
    assert a is b and a is not c
    with pytest.raises(ValueError):
        reg.gauge("fam")  # kind mismatch on an existing family


def test_label_cardinality_bounded():
    reg = Registry()
    handles = [reg.counter("fam", max_series=4, k=str(i))
               for i in range(10)]
    for h in handles:
        h.inc()
    snap = reg.snapshot()["metrics"]["fam"]
    assert snap["dropped_series"] == 6
    series = {tuple(sorted(s["labels"].items())): s["value"]
              for s in snap["series"]}
    # 4 admitted label-sets + ONE shared overflow series holding the rest
    assert series[(("overflow", "true"),)] == 6
    assert len(series) == 5


def test_overflow_emits_per_family_drop_counter():
    """ISSUE 19 satellite: cardinality overflow is itself a metric —
    ``obs_dropped_series{family=...}`` counts drops PER FAMILY so the
    watchdog can alert on the one family that is churning labels
    (snapshot()'s per-family ``dropped_series`` number requires a human
    to diff; the counter is alertable)."""
    reg = Registry()
    for i in range(7):
        reg.counter("noisy", max_series=2, k=str(i)).inc()
    for i in range(4):
        reg.counter("chatty", max_series=2, k=str(i)).inc()
    snap = reg.snapshot()["metrics"]
    drops = {s["labels"]["family"]: s["value"]
             for s in snap["obs_dropped_series"]["series"]}
    assert drops == {"noisy": 5, "chatty": 2}
    # the drop family can NEVER recurse into itself (it is bounded and
    # exempt): overflow IT and the registry stays standing
    for i in range(300):
        reg.counter("f" + str(i), max_series=1, k=str(i))
    assert reg.snapshot()["metrics"]["obs_dropped_series"] is not None
    reg.reset()
    assert "obs_dropped_series" not in reg.snapshot()["metrics"]


def test_disabled_mode_null_handles():
    was = get_flags(["obs_metrics"])["obs_metrics"]
    set_flags({"obs_metrics": False})
    try:
        reg = Registry()
        c = reg.counter("fam")
        c.inc(100)
        assert c.value == 0
        assert reg.snapshot()["metrics"] == {}
        # all creations share the one null handle — zero per-site cost
        assert reg.gauge("g") is reg.histogram("h")
    finally:
        set_flags({"obs_metrics": was})


def test_counter_thread_consistency():
    reg = Registry()
    c = reg.counter("fam")
    h = reg.histogram("lat", buckets=(0.5,))

    def work():
        for _ in range(10000):
            c.inc()
            h.observe(0.1)

    ts = [threading.Thread(target=work) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value == 80000
    assert h.hist()["count"] == 80000


def test_counter_group_mirrors_registry():
    reg = Registry()
    g = CounterGroup("fam", ("hits", "misses"), registry=reg, tier="1")
    g["hits"] += 3
    g["misses"] += 1
    assert g["hits"] == 3 and dict(g.items())["misses"] == 1
    series = {s["labels"]["key"]: s["value"]
              for s in reg.snapshot()["metrics"]["fam"]["series"]}
    assert series == {"hits": 3, "misses": 1}
    # a LOWER write resets only the local window (monotonic registry)
    g["hits"] = 0
    assert g["hits"] == 0
    series = {s["labels"]["key"]: s["value"]
              for s in reg.snapshot()["metrics"]["fam"]["series"]}
    assert series["hits"] == 3


def test_merge_snapshots_sums_counters_and_lists_processes():
    reg1, reg2 = Registry(), Registry()
    reg1.set_role("a")
    reg2.set_role("b")
    reg1.counter("fam", t="0").inc(2)
    reg2.counter("fam", t="0").inc(3)
    reg2.counter("fam", t="1").inc(7)
    reg1.histogram("lat", buckets=(1.0,)).observe(0.5)
    reg2.histogram("lat", buckets=(1.0,)).observe(2.0)
    job = aggregate.merge_snapshots([reg1.snapshot(), reg2.snapshot()])
    assert [p["role"] for p in job["processes"]] == ["a", "b"]
    series = {tuple(sorted(s["labels"].items())): s["value"]
              for s in job["metrics"]["fam"]["series"]}
    assert series[(("t", "0"),)] == 5 and series[(("t", "1"),)] == 7
    lat = job["metrics"]["lat"]["series"][0]
    assert lat["count"] == 2 and lat["buckets"] == [1, 1]


# -- trace core -------------------------------------------------------------

def test_span_nesting_ids_and_wire_context():
    trace.start_tracing(sample=1.0)
    assert trace.wire_context() == (0, 0)  # no open span yet
    with trace.span("root") as root:
        rid = trace.wire_context()
        assert rid == (root.trace_id, root.span_id)
        with trace.span("child") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
            assert child.span_id != root.span_id
            assert trace.wire_context()[1] == child.span_id
        assert trace.wire_context()[1] == root.span_id
    trace.stop_tracing()
    spans = trace.drain_spans()
    assert [s.name for s in spans] == ["child", "root"]  # close order
    assert len({s.span_id for s in spans}) == 2


def test_tracing_off_is_zero_context():
    assert not trace.tracing_enabled()
    with trace.span("x") as s:
        assert s is None
        assert trace.wire_context() == (0, 0)
    assert trace.drain_spans() == []
    trace.start_tracing(sample=0.0)  # on but unsampled
    with trace.span("x") as s:
        assert s is None and trace.wire_context() == (0, 0)


def test_wire_struct_contract():
    # the fixed header: 28 legacy bytes + the 16-byte context field —
    # csrc ReqHeader, ha._HDR and the obs structs must agree byte-wise
    assert trace.WIRE_CONTEXT_BYTES == 16
    assert ha._HDR.size == 28 + trace.WIRE_CONTEXT_BYTES
    assert trace.SERVER_SPAN_STRUCT.size == 64
    assert trace.SERVER_WIRE_STRUCT.size == 48


def test_span_ring_bounded():
    trace.start_tracing(sample=1.0, ring=8)
    for i in range(20):
        with trace.span(f"s{i}"):
            pass
    assert len(trace.drain_spans()) == 8
    assert trace.dropped_spans() == 12


# -- RPC e2e ----------------------------------------------------------------

needs_rpc = pytest.mark.skipif(not rpc.rpc_available(),
                               reason="native toolchain unavailable")


@pytest.fixture()
def cluster2():
    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    client = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers])
    client.create_sparse_table(0, _cfg())
    try:
        yield servers, client
    finally:
        client.stop_servers()
        client.close()
        for s in servers:
            s.close()


@needs_rpc
def test_trace_context_reaches_server_and_links(cluster2):
    servers, client = cluster2
    keys = np.arange(1, 101, dtype=np.uint64)
    client.pull_sparse(0, keys)  # untraced warm-up
    for s in range(2):
        aggregate.fetch_server_obs(client, s, drain=True)

    trace.start_tracing(sample=1.0)
    with trace.span("step"):
        client.pull_sparse(0, keys)
        client.push_sparse(0, keys, np.ones((100, 12), np.float32))
    trace.stop_tracing()
    spans = {s.name: s for s in trace.drain_spans()}
    pull = spans["pserver_client_pull_sparse"]
    assert pull.attrs["rpc"] and pull.attrs["tx_bytes"] > 0 \
        and pull.attrs["rx_bytes"] > 0

    srv = []
    for s in range(2):
        _, sp = aggregate.fetch_server_obs(client, s, drain=True)
        srv.extend(sp)
    pull_srv = [s for s in srv if s["cmd"] == rpc._PULL_SPARSE]
    # both shards served a slice of THE SAME client span (fan-out), so
    # both server spans carry its id — no orphans, no duplicates beyond
    # the genuine per-shard fan-out
    assert len(pull_srv) == 2
    assert {s["span_id"] for s in pull_srv} == {pull.span_id}
    assert all(s["trace_id"] == pull.trace_id for s in srv)
    assert all(s["dur_us"] >= 0 and s["req_bytes"] > 0 for s in srv)


@needs_rpc
def test_untraced_requests_record_no_server_spans(cluster2):
    servers, client = cluster2
    for s in range(2):
        aggregate.fetch_server_obs(client, s, drain=True)
    client.pull_sparse(0, np.arange(1, 50, dtype=np.uint64))
    for s in range(2):
        _, spans = aggregate.fetch_server_obs(client, s, drain=True)
        assert spans == []  # wire counters still accumulate
    snap, _ = aggregate.fetch_server_obs(client, 0)
    series = snap["metrics"]["ps_server_wire_bytes"]["series"]
    assert any(r["value"] > 0 for r in series)


@needs_rpc
def test_server_wire_accounting_rows_and_directions(cluster2):
    servers, client = cluster2
    for s in range(2):
        aggregate.fetch_server_obs(client, s, drain=True)  # note: spans only
    def rows_by_dir(snap):
        # per-shard series (the shard label keeps shards' cumulative
        # counters from aliasing in the time-series ring) sum per dir
        out: dict = {}
        for r in snap["metrics"]["ps_server_wire_rows"]["series"]:
            d = r["labels"]["dir"]
            out[d] = out.get(d, 0) + r["value"]
        return out

    base_rows = rows_by_dir(aggregate.job_snapshot(client))
    keys = np.arange(1, 201, dtype=np.uint64)
    client.pull_sparse(0, keys)
    client.push_sparse(0, keys, np.ones((200, 12), np.float32))
    job = aggregate.job_snapshot(client)
    rows = rows_by_dir(job)
    assert rows["out"] - base_rows.get("out", 0) == 200   # pulled
    assert rows["in"] - base_rows.get("in", 0) == 200     # pushed
    # client-side view exists too, with density gauges in (0, 1]
    dens = job["metrics"]["ps_client_density"]["series"]
    assert any(0 < r["value"] <= 1.0 for r in dens)
    assert len(job["processes"]) >= 3


@needs_rpc
def test_op_counts_shim_exact_and_independent(cluster2):
    servers, client = cluster2
    client.reset_op_counts()
    keys = np.arange(1, 10, dtype=np.uint64)
    client.pull_sparse(0, keys)
    client.pull_sparse(0, keys)
    client.push_sparse(0, keys, np.ones((9, 12), np.float32))
    assert client.op_counts == {"pull_sparse": 2, "push_sparse": 1}
    assert client.reset_op_counts() == {"pull_sparse": 2,
                                        "push_sparse": 1}
    assert client.reset_op_counts() == {}
    # a second client's window is its own (distinct registry label)
    other = rpc.RpcPsClient([client._conns[0].endpoint])
    try:
        other._sparse_dims[0] = client._sparse_dims[0]
        other.pull_sparse(0, keys)
        assert other.op_counts == {"pull_sparse": 1}
        assert client.op_counts == {}
    finally:
        other.close()


@needs_rpc
def test_failover_replay_marks_span_retried_no_duplicate_ids():
    """PR 4 failover + tracing: the replayed pull keeps ITS span id
    (marked retried) and exactly one server span exists for it — on
    the promoted replacement."""
    sA = rpc.NativePsServer(n_trainers=1)
    sB = rpc.NativePsServer(n_trainers=1)
    epA, epB = f"127.0.0.1:{sA.port}", f"127.0.0.1:{sB.port}"

    class StubRouter:
        def routing(self):
            return 0, [epB]

        def allow(self, endpoint):
            return True

        def record(self, endpoint, ok):
            pass

        def failover(self, shard, bad):
            return epB

    flags_was = get_flags(["pserver_max_retry", "pserver_timeout_ms"])
    set_flags({"pserver_max_retry": 1, "pserver_timeout_ms": 2000})
    cli = rpc.RpcPsClient([epA], router=StubRouter())
    cliB = rpc.RpcPsClient([epB])
    try:
        cli.create_sparse_table(0, _cfg())
        cliB.create_sparse_table(0, _cfg())
        keys = np.arange(1, 50, dtype=np.uint64)
        cli.pull_sparse(0, keys)
        sA.stop()  # kill the primary under the client

        trace.start_tracing(sample=1.0)
        with trace.span("step"):
            cli.pull_sparse(0, keys)  # dies on A → replays on B
        trace.stop_tracing()
        spans = trace.drain_spans()
        pulls = [s for s in spans
                 if s.name == "pserver_client_pull_sparse"]
        assert len(pulls) == 1  # ONE logical span, not one per attempt
        assert pulls[0].attrs.get("retried") is True
        assert len({s.span_id for s in spans}) == len(spans)

        _, srv = aggregate.fetch_server_obs(cliB, 0, drain=True)
        served = [s for s in srv if s["span_id"] == pulls[0].span_id]
        assert len(served) == 1  # exactly one server span — no orphans
        assert served[0]["cmd"] == rpc._PULL_SPARSE
    finally:
        set_flags(flags_was)
        cli.close()
        cliB.stop_servers()
        cliB.close()
        sA.close()
        sB.close()


@needs_rpc
def test_registry_consistent_under_concurrent_communicator_workers():
    """Concurrent push/pull workers (HalfAsync queue drain + async
    prefetch pulls) against live shards: the registry's per-table row
    counters land EXACTLY (distinct keys per send, so client-side
    dedup-merge can't collapse rows)."""
    from paddle_tpu.ps.communicator import HalfAsyncCommunicator

    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    client = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers])
    tid = 7  # a fresh table id → fresh per-table registry series
    try:
        client.create_sparse_table(tid, _cfg(tid))
        comm = HalfAsyncCommunicator(client)
        comm.start()
        rows_h = client._tbl_obs[tid]["push_rows"]
        pull_h = client._tbl_obs[tid]["pull_rows"]
        base_push, base_pull = rows_h.value, pull_h.value

        N_SENDS, N_KEYS = 40, 32

        def sender(worker):
            for i in range(N_SENDS):
                lo = (worker * N_SENDS + i) * N_KEYS + 1
                keys = np.arange(lo, lo + N_KEYS, dtype=np.uint64)
                comm.send_sparse(tid, keys,
                                 np.ones((N_KEYS, 12), np.float32))
                comm.pull_sparse_async(tid, keys).result()

        ts = [threading.Thread(target=sender, args=(w,)) for w in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        comm.barrier()
        comm.stop()
        total = 4 * N_SENDS * N_KEYS
        assert rows_h.value - base_push == total
        assert pull_h.value - base_pull == total
    finally:
        client.stop_servers()
        client.close()
        for s in servers:
            s.close()


# -- chrome export + timeline merge ----------------------------------------

def test_flow_events_in_chrome_export(tmp_path):
    trace.start_tracing(sample=1.0)
    with trace.span("op") as s:
        s.add_bytes(tx=10, rx=20)
    trace.stop_tracing()
    path = trace.export_chrome_trace(str(tmp_path / "t.json"),
                                     process_name="trainer")
    blob = json.load(open(path))
    assert blob["clockSyncUs"] > 0
    evs = blob["traceEvents"]
    assert any(e.get("ph") == "s" and e.get("cat") == "rpc_flow"
               for e in evs)
    xs = [e for e in evs if e.get("ph") == "X"]
    assert xs[0]["args"]["tx_bytes"] == 10
    # raw perf-counter ts: the blob anchor is what wall-aligns them
    assert xs[0]["ts"] < 1e14


def test_server_spans_to_chrome_flow_finish():
    spans = [{"trace_id": 1, "span_id": 42, "cmd": 3, "table_id": 0,
              "ts_us": 1000, "dur_us": 50, "gate_us": 10,
              "req_bytes": 64, "resp_bytes": 256}]
    evs = aggregate.server_spans_to_chrome(spans, pid=0,
                                           process_name="shard0")
    fl = [e for e in evs if e.get("ph") == "f"]
    assert len(fl) == 1 and fl[0]["id"] == 42
    x = [e for e in evs if e.get("ph") == "X" and e["name"] != "gate_wait"]
    assert x[0]["args"]["resp_bytes"] == 256
    assert any(e["name"] == "gate_wait" for e in evs)


def test_timeline_merge_aligns_clocks_and_deconflicts_pids(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import timeline

    # worker A booted "late": small raw ts, large anchor; worker B
    # early: big raw ts, small anchor. On raw clocks A sorts first;
    # wall-aligned, B's event happened first. Both files use pid 0.
    a = {"traceEvents": [{"name": "a", "ph": "X", "ts": 10.0, "dur": 1,
                          "pid": 0, "tid": 0}],
         "clockSyncUs": 2_000_000.0}
    b = {"traceEvents": [{"name": "b", "ph": "X", "ts": 500_000.0,
                          "dur": 1, "pid": 0, "tid": 0}],
         "clockSyncUs": 1_000_000.0}
    pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    json.dump(a, open(pa, "w"))
    json.dump(b, open(pb, "w"))
    out = str(tmp_path / "m.json")
    timeline.merge_traces([pa, pb], out)
    evs = json.load(open(out))["traceEvents"]
    xa = next(e for e in evs if e["name"] == "a")
    xb = next(e for e in evs if e["name"] == "b")
    assert xa["pid"] != xb["pid"]  # same original pid, distinct lanes
    assert xb["ts"] < xa["ts"]     # wall order, not raw-clock order
    assert min(xa["ts"], xb["ts"]) == 0.0  # re-zeroed axis
    names = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert names == {"a", "b"}


def test_timeline_merge_preserves_multi_pid_files(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import timeline

    blob = {"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": 0,
         "args": {"name": "trainer"}},
        {"name": "t", "ph": "X", "ts": 1.0, "dur": 1, "pid": 0, "tid": 0},
        {"name": "process_name", "ph": "M", "pid": 1,
         "args": {"name": "shard"}},
        {"name": "s", "ph": "X", "ts": 2.0, "dur": 1, "pid": 1, "tid": 0},
    ], "clockSyncUs": 0.0}
    p = str(tmp_path / "multi.json")
    json.dump(blob, open(p, "w"))
    out = str(tmp_path / "m.json")
    timeline.merge_traces([p], out)
    evs = json.load(open(out))["traceEvents"]
    t = next(e for e in evs if e["name"] == "t")
    s = next(e for e in evs if e["name"] == "s")
    assert t["pid"] != s["pid"]  # the file's internal lanes survive


def test_unsampled_root_suppresses_child_sampling():
    """Children INHERIT an unsampled root's decision: no re-roll, no
    orphan root spans, no wire context — even if the sample rate rises
    mid-scope (regression: children used to roll independently)."""
    trace.start_tracing(sample=0.0)
    with trace.span("root") as r:
        assert r is None
        trace._sample_rate = 1.0  # a child re-roll would now sample
        with trace.span("child") as c:
            assert c is None
            assert trace.wire_context() == (0, 0)
    trace.stop_tracing()
    assert trace.drain_spans() == []
    # and a FRESH root after the unsampled scope samples normally
    trace.start_tracing(sample=1.0)
    with trace.span("root2") as r2:
        assert r2 is not None
    trace.stop_tracing()
    assert [s.name for s in trace.drain_spans()] == ["root2"]


def test_merge_histogram_bounds_conflict_marked_not_corrupted():
    """Same family, different bucket ladders across processes: the
    merge keeps the first ladder internally consistent
    (sum(buckets) == count) and marks the conflict instead of adding
    count/sum it cannot bucket."""
    r1, r2 = Registry(), Registry()
    r1.histogram("lat", buckets=(1.0,)).observe(0.5)
    r2.histogram("lat", buckets=(2.0, 4.0)).observe(0.5)
    job = aggregate.merge_snapshots([r1.snapshot(), r2.snapshot()])
    s = job["metrics"]["lat"]["series"][0]
    assert s["bounds_conflict"] is True
    assert s["count"] == 1 and sum(s["buckets"]) == s["count"]


@needs_rpc
def test_disabled_metrics_skip_wire_accounting_entirely():
    """FLAGS_obs_metrics=0 at client build: NO per-table handles bind,
    so the accounting blocks (incl. their density count_nonzero scans)
    short-circuit — while the op_counts accessor stays exact (its
    CounterGroup local mirror is flag-independent)."""
    was = get_flags(["obs_metrics"])["obs_metrics"]
    set_flags({"obs_metrics": False})
    server = client = None
    try:
        server = rpc.NativePsServer(n_trainers=1)
        client = rpc.RpcPsClient([f"127.0.0.1:{server.port}"])
        client.create_sparse_table(0, _cfg())
        assert client._tbl_obs == {}  # nothing bound, nothing scanned
        keys = np.arange(1, 10, dtype=np.uint64)
        client.pull_sparse(0, keys)
        client.push_sparse(0, keys, np.ones((9, 12), np.float32))
        assert client.op_counts == {"pull_sparse": 1, "push_sparse": 1}
    finally:
        set_flags({"obs_metrics": was})
        if client is not None:
            client.stop_servers()
            client.close()
        if server is not None:
            server.close()
