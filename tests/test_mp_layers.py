"""TP layer numerics: sharded layers under shard_map must match the
serial computation (reference parity tests compare mp vs single)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from jax import shard_map

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.core import mesh as mesh_mod
from paddle_tpu.parallel import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)

MP = 4


@pytest.fixture(scope="module")
def mesh():
    return mesh_mod.make_mesh({"dp": 2, "mp": MP})


def test_vocab_parallel_embedding_matches_serial(mesh):
    pt.seed(0)
    vocab, dim = 16, 8
    full_weight = np.random.default_rng(0).normal(size=(vocab, dim)).astype(np.float32)
    ids = np.array([[0, 5, 11, 15], [3, 2, 9, 1]], dtype=np.int32)

    layer = VocabParallelEmbedding(vocab, dim, mp_size=MP)
    serial = jnp.take(jnp.asarray(full_weight), jnp.asarray(ids), axis=0)

    def f(w_shard, ids):
        layer._parameters["weight"] = w_shard
        return layer(ids)

    out = shard_map(
        f, mesh=mesh, in_specs=(P("mp", None), P(None, None)), out_specs=P(None, None, None)
    )(jnp.asarray(full_weight), jnp.asarray(ids))
    # out replicated; psum over mp gave full rows
    np.testing.assert_allclose(np.asarray(out), np.asarray(serial), rtol=1e-5)


def test_col_row_parallel_matches_serial(mesh):
    rng = np.random.default_rng(1)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    w1 = rng.normal(size=(8, 16)).astype(np.float32)
    b1 = rng.normal(size=(16,)).astype(np.float32)
    w2 = rng.normal(size=(16, 8)).astype(np.float32)
    b2 = rng.normal(size=(8,)).astype(np.float32)

    serial = np.maximum(x @ w1 + b1, 0) @ w2 + b2

    col = ColumnParallelLinear(8, 16, mp_size=MP, gather_output=False)
    row = RowParallelLinear(16, 8, mp_size=MP, input_is_parallel=True)

    def f(w1s, b1s, w2s, b2s, x):
        col._parameters["weight"], col._parameters["bias"] = w1s, b1s
        row._parameters["weight"], row._parameters["bias"] = w2s, b2s
        h = jnp.maximum(col(x), 0)
        return row(h)

    out = shard_map(
        f,
        mesh=mesh,
        in_specs=(P(None, "mp"), P("mp"), P("mp", None), P(None), P(None, None)),
        out_specs=P(None, None),
    )(jnp.asarray(w1), jnp.asarray(b1), jnp.asarray(w2), jnp.asarray(b2), jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), serial, rtol=1e-4, atol=1e-4)


def test_parallel_cross_entropy_matches_serial(mesh):
    rng = np.random.default_rng(2)
    logits = rng.normal(size=(6, 16)).astype(np.float32)
    labels = rng.integers(0, 16, size=(6,)).astype(np.int32)

    serial = nn.functional.cross_entropy(
        jnp.asarray(logits), jnp.asarray(labels), reduction="none"
    )

    pce = ParallelCrossEntropy(mp_size=MP)

    def f(logits_shard, labels):
        return pce(logits_shard, labels)

    out = shard_map(
        f, mesh=mesh, in_specs=(P(None, "mp"), P(None)), out_specs=P(None)
    )(jnp.asarray(logits), jnp.asarray(labels))
    np.testing.assert_allclose(np.asarray(out), np.asarray(serial), rtol=1e-5, atol=1e-5)


def test_mp_size_1_degrades_to_serial():
    pt.seed(0)
    emb = VocabParallelEmbedding(8, 4, mp_size=1)
    out = emb(jnp.asarray([1, 2]))
    assert out.shape == (2, 4)
    col = ColumnParallelLinear(4, 6, mp_size=1)
    assert col(jnp.ones((2, 4))).shape == (2, 6)


def test_parallel_cross_entropy_grad_matches_serial(mesh):
    """Backward parity (a fwd-only test missed a missing pmax VJP)."""
    rng = np.random.default_rng(3)
    logits = rng.normal(size=(6, 16)).astype(np.float32)
    labels = rng.integers(0, 16, size=(6,)).astype(np.int32)
    pce = ParallelCrossEntropy(mp_size=MP)

    serial_grad = jax.grad(
        lambda lg: nn.functional.cross_entropy(lg, jnp.asarray(labels), reduction="none").sum()
    )(jnp.asarray(logits))

    def loss_fn(lg, lb):
        return pce(lg, lb).sum()

    grad = shard_map(
        jax.grad(loss_fn), mesh=mesh, in_specs=(P(None, "mp"), P(None)), out_specs=P(None, "mp")
    )(jnp.asarray(logits), jnp.asarray(labels))
    np.testing.assert_allclose(np.asarray(grad), np.asarray(serial_grad), rtol=1e-4, atol=1e-5)


def test_vocab_parallel_padded_non_divisible():
    """Non-divisible vocab pads up (Megatron-style): gather over mp still
    returns each real id's row exactly once."""
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as pt
    from paddle_tpu.core import mesh as mesh_mod
    from paddle_tpu.parallel.mp_layers import VocabParallelEmbedding

    vocab, dim, mp = 13, 8, 4  # 13 % 4 != 0 → padded to 16
    mesh = mesh_mod.make_mesh({"dp": 2, "mp": mp})
    pt.seed(0)
    layers = [VocabParallelEmbedding(vocab, dim, mp_size=mp, mp_rank=r)
              for r in range(mp)]
    assert layers[0].per_part == 4
    import numpy as np

    full = np.concatenate([np.asarray(l.weight) for l in layers])[:vocab]
    stacked = jnp.stack([l.weight for l in layers])  # [mp, per, dim]

    ids = jnp.asarray(np.arange(vocab, dtype=np.int32))

    def fwd(w_local, ids):
        layers[0].weight = w_local[0]
        return layers[0](ids)

    out = jax.jit(shard_map(
        fwd, mesh=mesh, in_specs=(P("mp"), P()), out_specs=P(),
        check_vma=False))(stacked, ids)
    np.testing.assert_allclose(np.asarray(out), full, rtol=1e-6)
