"""Fused Pallas hot-tier kernels (ops/hot_kernels.py): bit-parity of
the Pallas(interpret) kernels against the jnp reference formulations —
probe+gather vs ``dynamic_map_lookup`` + ``cache_pull``, scatter+apply
vs ``cache_push_sparse`` — across the rule family (adagrad, std_adagrad,
adam, naive), unaligned n, banked maps, duplicate/sentinel rows and
post-mutation map states. Tier-level parity (eviction churn, checkpoint
/restore, the RPC-only oracle) rides tests/test_hot_tier.py."""

import numpy as np
import pytest

import paddle_tpu  # noqa: F401 — jax compat shims
import jax
import jax.numpy as jnp

from paddle_tpu.ops.hot_kernels import (hot_probe, hot_probe_gather,
                                        hot_scatter_apply,
                                        resolve_hot_kernels)
from paddle_tpu.ops.sparse_optimizer import rule_state_dim
from paddle_tpu.ps.device_hash import (DynamicDeviceKeyMap,
                                       dynamic_map_lookup, split_keys)
from paddle_tpu.ps.embedding_cache import (CacheConfig, cache_pull,
                                           cache_push_sparse)
from paddle_tpu.ps.sgd_rule import SGDRuleConfig


def _banked_map(C, banks, keys, rng):
    """Map + per-bank row allocation (the tier's placement contract:
    a key's row lives inside its bank's contiguous row block)."""
    m = DynamicDeviceKeyMap(C, banks=banks)
    Cb = C // banks
    bk = m.bank_of(keys)
    rows = np.zeros(len(keys), np.int32)
    nxt = [0] * banks
    for i, b in enumerate(bk):
        rows[i] = b * Cb + nxt[b]
        nxt[b] += 1
    m.insert(keys, rows)
    return m, rows


def _tier_state(C, xd, rng, es=1, xs=1):
    return {
        "show": jnp.asarray(np.abs(rng.normal(size=C)).astype(np.float32)),
        "click": jnp.asarray(np.abs(rng.normal(size=C)).astype(np.float32)),
        "embed_w": jnp.asarray(rng.normal(size=(C, 1)).astype(np.float32)),
        "embed_state": jnp.asarray(
            np.abs(rng.normal(size=(C, es))).astype(np.float32)),
        "embedx_w": jnp.asarray(rng.normal(size=(C, xd)).astype(np.float32)),
        "embedx_state": jnp.asarray(
            np.abs(rng.normal(size=(C, xs))).astype(np.float32)),
        "has_embedx": jnp.asarray((rng.random(C) > 0.5).astype(np.float32)),
    }


@pytest.mark.parametrize("banks", [1, 4])
def test_probe_gather_matches_jnp_reference(banks):
    """Fused probe+gather ≡ dynamic_map_lookup + cache_pull, bitwise —
    unaligned n (not a block multiple), missing keys pulling zeros."""
    rng = np.random.default_rng(0)
    C, xd = 256, 8
    keys = np.unique(rng.integers(1, 2**63, 300).astype(np.uint64))[:120]
    m, rows = _banked_map(C, banks, keys, rng)
    state = _tier_state(C, xd, rng)
    # 157 probes = resident + absent, NOT a multiple of the 64 block
    probe = np.concatenate([keys,
                            rng.integers(1, 2**63, 37).astype(np.uint64)])
    hi, lo = split_keys(probe)
    hi, lo = jnp.asarray(hi), jnp.asarray(lo)
    ms = m.device_state()
    ref_rows = dynamic_map_lookup(ms, hi, lo, m.probe_buckets, banks)
    ref_pull = cache_pull(state, jnp.where(ref_rows >= 0, ref_rows, C))
    krows, kpull = hot_probe_gather(ms, hi, lo, state,
                                    probe_buckets=m.probe_buckets,
                                    banks=banks, block=64, interpret=True)
    np.testing.assert_array_equal(np.asarray(krows), np.asarray(ref_rows))
    np.testing.assert_array_equal(np.asarray(kpull), np.asarray(ref_pull))
    # the resident keys actually resolved (not a trivially-all-miss run)
    assert (np.asarray(krows)[:len(keys)] == rows).all()
    assert (np.asarray(krows)[len(keys):] == -1).all()

    prows = hot_probe(ms, hi, lo, probe_buckets=m.probe_buckets,
                      banks=banks, block=64, interpret=True)
    np.testing.assert_array_equal(np.asarray(prows), np.asarray(ref_rows))


@pytest.mark.parametrize("banks", [1, 4])
def test_probe_gather_after_mutation_and_rebuild(banks):
    """Evict/insert churn (incremental device patches) and a grow
    rebuild (full re-upload, new probe seed) — the kernel probes the
    SAME device state the jnp path does, so parity must survive both."""
    rng = np.random.default_rng(1)
    C, xd = 256, 4
    keys = np.unique(rng.integers(1, 2**63, 300).astype(np.uint64))[:96]
    m, rows = _banked_map(C, banks, keys, rng)
    state = _tier_state(C, xd, rng)
    hi, lo = split_keys(keys)
    hi, lo = jnp.asarray(hi), jnp.asarray(lo)

    def check():
        ms = m.device_state()
        ref = dynamic_map_lookup(ms, hi, lo, m.probe_buckets, banks)
        got, _ = hot_probe_gather(ms, hi, lo, state,
                                  probe_buckets=m.probe_buckets,
                                  banks=banks, interpret=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
        np.testing.assert_array_equal(np.asarray(ref), m.lookup_host(keys))

    check()
    m.remove(keys[::3])          # tombstones → incremental patches
    check()
    m._rebuild(grow=True)        # reseed + grow → full re-upload
    check()


@pytest.mark.parametrize("rule", ["adagrad", "std_adagrad", "adam", "naive"])
def test_scatter_apply_matches_jnp_reference(rule):
    """Fused scatter+apply ≡ cache_push_sparse (jnp rule path), bitwise:
    the full rule family, duplicate rows (merge association pinned by
    the shared unique/segment-sum prologue), sentinel rows dropped,
    unaligned n."""
    rng = np.random.default_rng(2)
    C, xd, n = 128, 8, 101  # prime n — no alignment luck
    cfg = CacheConfig(capacity=C, embedx_dim=xd, embed_rule=rule,
                      embedx_rule=rule, sgd=SGDRuleConfig(),
                      pallas_update=False, push_mode="sparse")
    es, xs = rule_state_dim(rule, 1), rule_state_dim(rule, xd)
    state = _tier_state(C, xd, rng, es=es, xs=xs)
    if rule == "adam":
        # beta-power columns must be in (0, 1) like real rows
        st = np.array(state["embedx_state"])
        st[:, 2 * xd:] = 0.9
        state["embedx_state"] = jnp.asarray(st)
        est = np.array(state["embed_state"])
        est[:, 2:] = 0.9
        state["embed_state"] = jnp.asarray(est)
    rows = np.concatenate([rng.integers(0, C, n - 16),
                           rng.integers(0, C, 8),  # duplicates likely
                           np.full(8, C)])         # sentinel → dropped
    rows = jnp.asarray(rows.astype(np.int32))
    grads = jnp.asarray(rng.normal(size=(n, 1 + xd)).astype(np.float32))
    shows = jnp.ones(n, jnp.float32)
    clicks = jnp.asarray((rng.random(n) > 0.7).astype(np.float32))
    ref = cache_push_sparse(state, rows, grads, shows, clicks, cfg)
    got = hot_scatter_apply(state, rows, grads, shows, clicks, cfg,
                            interpret=True)
    for k in ref:
        np.testing.assert_array_equal(np.asarray(got[k]), np.asarray(ref[k]),
                                      err_msg=f"{rule}: column {k}")
    # the update actually landed somewhere (not a trivially-equal no-op)
    assert not np.array_equal(np.asarray(got["embed_w"]),
                              np.asarray(state["embed_w"]))


def test_scatter_apply_under_jit_and_donation():
    """The kernel composes into a jitted step with the tier-state
    donation the trainer uses."""
    rng = np.random.default_rng(3)
    C, xd, n = 64, 4, 32
    cfg = CacheConfig(capacity=C, embedx_dim=xd, push_mode="sparse",
                      pallas_update=False)
    state = _tier_state(C, xd, rng)
    rows = jnp.asarray(rng.integers(0, C, n).astype(np.int32))
    grads = jnp.asarray(rng.normal(size=(n, 1 + xd)).astype(np.float32))
    shows = jnp.ones(n, jnp.float32)
    clicks = jnp.zeros(n, jnp.float32)
    ref = cache_push_sparse(state, rows, grads, shows, clicks, cfg)

    @jax.jit
    def step(st):
        return hot_scatter_apply(st, rows, grads, shows, clicks, cfg,
                                 interpret=True)

    got = step(state)
    for k in ref:
        np.testing.assert_array_equal(np.asarray(got[k]), np.asarray(ref[k]))


def test_bank_membership_stable_across_rebuilds():
    """bank_of is a FIXED hash: reseed and grow rebuilds relocate
    buckets but never move a key between banks (the tier's row blocks
    depend on it)."""
    rng = np.random.default_rng(4)
    m = DynamicDeviceKeyMap(256, banks=8)
    keys = np.unique(rng.integers(1, 2**63, 300).astype(np.uint64))[:128]
    before = m.bank_of(keys)
    m.insert(keys, np.arange(len(keys), dtype=np.int32))
    m._rebuild(grow=False)   # reseed
    m._rebuild(grow=True)    # grow
    np.testing.assert_array_equal(m.bank_of(keys), before)
    np.testing.assert_array_equal(m.lookup_host(keys),
                                  np.arange(len(keys), dtype=np.int32))
    # banked probe never resolves a key through another bank's region:
    # the in-graph lookup agrees with the host mirror on every key
    hi, lo = split_keys(keys)
    got = np.asarray(dynamic_map_lookup(m.device_state(), jnp.asarray(hi),
                                        jnp.asarray(lo), m.probe_buckets,
                                        m.banks))
    np.testing.assert_array_equal(got, m.lookup_host(keys))


def test_resolve_hot_kernels():
    assert resolve_hot_kernels("pallas") is True
    assert resolve_hot_kernels("jnp") is False
    # "auto" follows the backend (CPU CI → jnp)
    expect = jax.default_backend() == "tpu"
    assert resolve_hot_kernels("auto") is expect
    with pytest.raises(Exception, match="kernels"):
        resolve_hot_kernels("cuda")
