"""Device-resident cuckoo key→row map (ps/device_hash.py + csrc/cuckoo.cc)
— the GPU HashTable::get analogue (heter_ps/hashtable_inl.h) probed
in-graph; and the key-fed CTR step that fuses the probe into the program.
"""

import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import optimizer
from paddle_tpu.models.ctr import (CtrConfig, DeepFM, make_ctr_train_step,
                                   make_ctr_train_step_from_keys)
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.device_hash import DeviceKeyMap, split_keys
from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
from paddle_tpu.ps.table import MemorySparseTable, TableConfig


def test_device_map_exact_and_missing(rng):
    keys = np.unique(rng.integers(1, 1 << 62, size=5000, dtype=np.uint64))
    rows = rng.permutation(len(keys)).astype(np.int32)
    m = DeviceKeyMap(keys, rows)

    batch = keys[rng.integers(0, len(keys), size=2000)]
    got = np.asarray(m.lookup(*[jnp.asarray(a) for a in split_keys(batch)]))
    want = rows[np.searchsorted(keys, batch)]
    np.testing.assert_array_equal(got, want)

    miss = rng.integers(1 << 62, 1 << 63, size=500, dtype=np.uint64)
    got = np.asarray(m.lookup(*[jnp.asarray(a) for a in split_keys(miss)]))
    assert (got == -1).all()


def test_device_map_low_bit_keys(rng):
    # hi half all zeros (plain small ids) must still disambiguate
    keys = np.unique(rng.integers(1, 1 << 30, size=4096, dtype=np.uint64))
    rows = np.arange(len(keys), dtype=np.int32)
    m = DeviceKeyMap(keys, rows)
    got = np.asarray(m.lookup(*[jnp.asarray(a) for a in split_keys(keys)]))
    np.testing.assert_array_equal(got, rows)


def test_key_fed_step_matches_row_fed(rng):
    """The in-graph lookup step produces the identical trajectory to the
    host-lookup step (same rows → same math)."""
    S, dim = 6, 4
    ccfg = CtrConfig(num_sparse_slots=S, num_dense=3, embedx_dim=dim,
                     dnn_hidden=(16,))
    cache_cfg = CacheConfig(capacity=1 << 11, embedx_dim=dim,
                            embedx_threshold=0.0)
    n_keys, batch = 200, 16
    # slot-tagged keys: hi = column slot id
    lo = rng.integers(0, 1 << 20, size=(n_keys, S)).astype(np.uint64)
    pool = lo + (np.arange(S, dtype=np.uint64) << np.uint64(32))

    def build():
        pt.seed(0)
        table = MemorySparseTable(TableConfig(
            shard_num=4, accessor_config=AccessorConfig(embedx_dim=dim)))
        cache = HbmEmbeddingCache(table, cache_cfg, device_map=True)
        cache.begin_pass(pool.reshape(-1))
        model = DeepFM(ccfg)
        opt = optimizer.Adam(learning_rate=1e-3)
        params = {"params": dict(model.named_parameters()), "buffers": {}}
        return table, cache, model, opt, params, opt.init(params)

    idx = rng.integers(0, n_keys, size=(3, batch))
    dense = rng.normal(size=(3, batch, 3)).astype(np.float32)
    labels = (rng.random((3, batch)) < 0.4).astype(np.int32)

    # row-fed reference
    table1, cache1, model1, opt1, params1, opt_state1 = build()
    step1 = make_ctr_train_step(model1, opt1, cache_cfg, donate=False)
    for t in range(3):
        keys = pool[idx[t]]
        rows = jnp.asarray(cache1.lookup(keys.reshape(-1)).reshape(keys.shape))
        params1, opt_state1, cache1.state, loss1 = step1(
            params1, opt_state1, cache1.state, rows,
            jnp.asarray(dense[t]), jnp.asarray(labels[t]))

    # key-fed
    table2, cache2, model2, opt2, params2, opt_state2 = build()
    step2 = make_ctr_train_step_from_keys(model2, opt2, cache_cfg,
                                          slot_ids=np.arange(S), donate=False)
    for t in range(3):
        lo32 = (pool[idx[t]] & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        params2, opt_state2, cache2.state, loss2 = step2(
            params2, opt_state2, cache2.state, cache2.device_map.state,
            jnp.asarray(lo32), jnp.asarray(dense[t]), jnp.asarray(labels[t]))

    np.testing.assert_array_equal(np.asarray(loss1), np.asarray(loss2))
    for k in cache1.state:
        np.testing.assert_array_equal(
            np.asarray(cache1.state[k]), np.asarray(cache2.state[k]),
            err_msg=f"cache[{k}]")


def test_wide_key_step_matches_slot_tagged(rng):
    """slot_ids=None variant (explicit hi halves) gives the identical
    trajectory when fed the same keys."""
    S, dim = 4, 4
    ccfg = CtrConfig(num_sparse_slots=S, num_dense=2, embedx_dim=dim,
                     dnn_hidden=(8,))
    cache_cfg = CacheConfig(capacity=1 << 10, embedx_dim=dim,
                            embedx_threshold=0.0)
    lo = rng.integers(0, 1 << 20, size=(100, S)).astype(np.uint64)
    pool = lo + (np.arange(S, dtype=np.uint64) << np.uint64(32))

    def build():
        pt.seed(0)
        table = MemorySparseTable(TableConfig(
            shard_num=4, accessor_config=AccessorConfig(embedx_dim=dim)))
        cache = HbmEmbeddingCache(table, cache_cfg, device_map=True)
        cache.begin_pass(pool.reshape(-1))
        model = DeepFM(ccfg)
        opt = optimizer.Adam(learning_rate=1e-3)
        params = {"params": dict(model.named_parameters()), "buffers": {}}
        return cache, model, opt, params, opt.init(params)

    idx = rng.integers(0, 100, size=16)
    keys = pool[idx]
    dense = rng.normal(size=(16, 2)).astype(np.float32)
    labels = (rng.random(16) < 0.4).astype(np.int32)
    lo32 = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi32 = (keys >> np.uint64(32)).astype(np.uint32)

    c1, m1, o1, p1, s1 = build()
    step1 = make_ctr_train_step_from_keys(m1, o1, cache_cfg,
                                          slot_ids=np.arange(S), donate=False)
    _, _, st1, loss1 = step1(p1, s1, c1.state, c1.device_map.state,
                             jnp.asarray(lo32), jnp.asarray(dense),
                             jnp.asarray(labels))

    c2, m2, o2, p2, s2 = build()
    step2 = make_ctr_train_step_from_keys(m2, o2, cache_cfg, slot_ids=None,
                                          donate=False)
    _, _, st2, loss2 = step2(p2, s2, c2.state, c2.device_map.state,
                             jnp.asarray(hi32), jnp.asarray(lo32),
                             jnp.asarray(dense), jnp.asarray(labels))
    np.testing.assert_array_equal(np.asarray(loss1), np.asarray(loss2))
    for k in st1:
        np.testing.assert_array_equal(np.asarray(st1[k]), np.asarray(st2[k]))
