"""Continuous telemetry plane (ISSUE 10): delta-compressed time-series
ring, sampler/collector, OpenMetrics exporter (rendering + strict
parse, label escaping), SLO watchdog multi-window burn-rate semantics,
and the obs/aggregate histogram bounds_conflict path."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from paddle_tpu.obs import aggregate, exporter, registry, slo, timeseries
from paddle_tpu.obs import flightrec
from paddle_tpu.obs.registry import Registry
from paddle_tpu.obs.timeseries import MetricRing, Sampler, quantile_from_hist


@pytest.fixture(autouse=True)
def _no_recorder_leak():
    yield
    flightrec.uninstall()


# -- quantile helper --------------------------------------------------------

def test_quantile_from_hist_interpolates():
    bounds = (0.1, 1.0, 10.0)
    #          ≤0.1  ≤1  ≤10  +inf
    buckets = [0,    10, 0,   0]
    # all mass inside (0.1, 1.0]: linear interpolation inside the bucket
    assert quantile_from_hist(bounds, buckets, 0.5) == pytest.approx(0.55)
    assert quantile_from_hist(bounds, buckets, 1.0) == pytest.approx(1.0)
    # +inf bucket clamps to the largest finite bound
    assert quantile_from_hist(bounds, [0, 0, 0, 5], 0.99) == 10.0
    assert quantile_from_hist(bounds, [0, 0, 0, 0], 0.5) == 0.0


# -- MetricRing -------------------------------------------------------------

def _snap_with(reg):
    return reg.snapshot()


def test_ring_counter_rates_gauge_last_hist_deltas():
    reg = Registry()
    c = reg.counter("reqs", table="0")
    g = reg.gauge("dens")
    h = reg.histogram("lat", buckets=(0.1, 1.0))
    ring = MetricRing()
    c.inc(10)
    g.set(0.5)
    h.observe(0.05)
    ring.append(_snap_with(reg), t=100.0)
    c.inc(6)
    g.set(0.25)
    h.observe(5.0)
    ring.append(_snap_with(reg), t=102.0)
    # counter → rate (delta / dt); first tick has no rate basis
    assert ring.series("reqs", "rate") == [(100.0, 0.0), (102.0, 3.0)]
    assert ring.series("reqs", "delta") == [(100.0, 10.0), (102.0, 6.0)]
    # gauge → last value
    assert ring.series("dens", "value") == [(100.0, 0.5), (102.0, 0.25)]
    # histogram → per-tick bucket deltas
    recs = ring.records()
    assert recs[1]["metrics"]["lat"]["series"][0]["buckets"] == [0, 0, 1]
    assert recs[1]["metrics"]["lat"]["series"][0]["count"] == 1


def test_ring_counter_restart_rebases_not_negative():
    ring = MetricRing()
    mk = lambda v: {"metrics": {"c": {"type": "counter", "series": [
        {"labels": {}, "value": v}]}}}
    ring.append(mk(100), t=10.0)
    ring.append(mk(3), t=11.0)   # process restarted: 3 < 100
    deltas = [v for _, v in ring.series("c", "delta")]
    assert deltas == [100.0, 3.0]  # re-based, no negative spike


def test_ring_bounded_capacity_and_window_queries():
    ring = MetricRing(capacity=4)
    reg = Registry()
    h = reg.histogram("lat", buckets=(0.1, 1.0))
    for i in range(8):
        h.observe(0.05 if i < 6 else 5.0)
        ring.append(_snap_with(reg), t=float(i))
    assert len(ring) == 4  # oldest ticks dropped
    # window over the surviving ticks: 2 bad of 4
    bad, count = ring.bad_fraction("lat", 1.0, window_s=10.0, now=7.0)
    assert count == 4 and bad == pytest.approx(0.5)
    # windowed quantile input sums bucket deltas
    bounds, acc, _ = ring.window_hist("lat", 10.0, now=7.0)
    assert sum(acc) == 4 and acc[-1] == 2


def test_ring_label_subset_match_and_reduce():
    ring = MetricRing()
    snap = {"metrics": {"wire": {"type": "counter", "series": [
        {"labels": {"table": "0", "dir": "in"}, "value": 10},
        {"labels": {"table": "1", "dir": "in"}, "value": 30},
        {"labels": {"table": "0", "dir": "out"}, "value": 5}]}}}
    ring.append(snap, t=1.0)
    assert ring.series("wire", "delta", labels={"dir": "in"}) == [(1.0, 40.0)]
    assert ring.series("wire", "delta", labels={"table": "0", "dir": "out"}
                       ) == [(1.0, 5.0)]


def test_ring_histogram_bounds_conflict_marked_not_corrupted():
    ring = MetricRing()
    mk = lambda bounds: {"metrics": {"lat": {"type": "histogram", "series": [
        {"labels": {}, "count": 3, "sum": 1.0, "bounds": list(bounds),
         "buckets": [1] * (len(bounds) + 1)}]}}}
    ring.append(mk((0.1, 1.0)), t=1.0)
    ring.append(mk((0.5, 2.0)), t=2.0)   # different ladder, same family
    recs = ring.records()
    assert recs[1]["metrics"]["lat"]["series"][0] == {
        "labels": {}, "bounds_conflict": True}
    # the family ladder stays the FIRST one
    assert ring.bounds("lat") == (0.1, 1.0)


# -- Sampler ----------------------------------------------------------------

def test_sampler_tick_probes_listeners_and_errors():
    reg = Registry()
    c = reg.counter("x")
    probed, seen = [], []
    s = Sampler(period_s=99.0, snapshot_fn=reg.snapshot, name="t-sampler")
    s.add_probe(lambda: probed.append(1))
    s.on_sample(lambda t: seen.append(t))
    c.inc(2)
    rec = s.tick(t=50.0)
    assert rec["t"] == 50.0 and probed == [1] and seen == [50.0]
    assert s.ticks == 1 and s.errors == 0

    # a failing snapshot costs one tick, not the sampler
    def boom():
        raise RuntimeError("shard died")

    bad = Sampler(period_s=99.0, snapshot_fn=boom)
    assert bad.tick() is None
    assert bad.errors == 1 and "shard died" in bad.last_error
    # a failing listener is counted but the tick still landed
    s2 = Sampler(period_s=99.0, snapshot_fn=reg.snapshot)
    s2.on_sample(lambda t: (_ for _ in ()).throw(RuntimeError("l")))
    assert s2.tick(t=1.0) is not None
    assert s2.errors == 1 and s2.ticks == 1


def test_sampler_thread_named_and_stops():
    reg = Registry()
    s = Sampler(period_s=0.01, snapshot_fn=reg.snapshot, name="obs-sampler")
    s.start()
    try:
        names = [t.name for t in threading.enumerate()]
        assert "obs-sampler" in names  # anonymous-thread rule's point
        deadline = 100
        while s.ticks == 0 and deadline:
            deadline -= 1
            threading.Event().wait(0.01)
        assert s.ticks > 0
    finally:
        s.stop()
    assert all(t.name != "obs-sampler" for t in threading.enumerate())


# -- SLO watchdog -----------------------------------------------------------

def _burn_ring(good_then_bad, t0=0.0, dt=1.0):
    """Ring with one 2-bucket histogram: 'g' ticks observe 0.05 (good),
    'b' ticks 5.0 (bad vs threshold 1.0)."""
    reg = Registry()
    h = reg.histogram("lat", buckets=(0.1, 1.0))
    ring = MetricRing()
    t = t0
    for ch in good_then_bad:
        h.observe(0.05 if ch == "g" else 5.0)
        ring.append(reg.snapshot(), t=t)
        t += dt
    return ring, t - dt


def test_watchdog_multiwindow_fires_and_clears():
    ring, now = _burn_ring("gggggggggg")
    rule = slo.SloRule("lat_p", "lat", threshold=1.0, budget=0.25,
                       windows=((8.0, 1.0), (3.0, 1.0)))
    wd = slo.SloWatchdog(ring, [rule])
    assert wd.evaluate(now=now) == []          # healthy: nothing fires
    ring2, now2 = _burn_ring("gggggbbbbb")
    wd2 = slo.SloWatchdog(ring2, [rule])
    fired = wd2.evaluate(now=now2)
    assert [a.rule for a in fired] == ["lat_p"]
    assert wd2.active() == ["lat_p"]
    # active rule does not re-fire while burning
    assert wd2.evaluate(now=now2) == []
    assert len(wd2.alerts()) == 1
    # recovery: short window clears first; once ALL windows are below
    # budget*factor the alert clears and the rule re-arms
    reg_alert = wd2.alerts()[0]
    assert reg_alert["cleared_t"] is None
    ring3, now3 = _burn_ring("gbbgggggggggggggg")
    wd3 = slo.SloWatchdog(ring3, [rule])
    assert wd3.evaluate(now=now3) == [] and wd3.active() == []


def test_watchdog_short_window_gates_stale_burn():
    # bad ticks exist in the LONG window but the last 3 ticks are clean:
    # the short window refuses → no fire (the fast-clear half of the
    # multi-window pair)
    ring, now = _burn_ring("bbbbbggg")
    rule = slo.SloRule("lat_p", "lat", threshold=1.0, budget=0.25,
                       windows=((8.0, 1.0), (2.5, 1.0)))
    wd = slo.SloWatchdog(ring, [rule])
    assert wd.evaluate(now=now) == []


def test_watchdog_threshold_rules_value_rate_age():
    ring = MetricRing()
    snap = lambda v: {"metrics": {"lag": {"type": "gauge", "series": [
        {"labels": {}, "value": v}]}}}
    for i, v in enumerate([10, 20, 5000]):
        ring.append(snap(v), t=float(i))
    wd = slo.SloWatchdog(ring, [slo.SloRule(
        "lag", "lag", kind="threshold", agg="max", threshold=1000,
        windows=((10.0, 1.0),))])
    assert [a.rule for a in wd.evaluate(now=2.0)] == ["lag"]

    # rate: counter deltas > 0 in the window (the breaker-open shape)
    ring2 = MetricRing()
    csnap = lambda v: {"metrics": {"opens": {"type": "counter", "series": [
        {"labels": {}, "value": v}]}}}
    ring2.append(csnap(0), t=0.0)
    ring2.append(csnap(0), t=1.0)
    wd2 = slo.SloWatchdog(ring2, [slo.SloRule(
        "opens", "opens", kind="threshold", field="delta", agg="rate",
        threshold=0.0, windows=((10.0, 1.0),))])
    assert wd2.evaluate(now=1.0) == []
    ring2.append(csnap(2), t=2.0)
    assert [a.rule for a in wd2.evaluate(now=2.0)] == ["opens"]

    # age: now - wall-timestamp gauge (checkpoint staleness shape)
    ring3 = MetricRing()
    gsnap = lambda v: {"metrics": {"ckpt": {"type": "gauge", "series": [
        {"labels": {}, "value": v}]}}}
    ring3.append(gsnap(1000.0), t=1001.0)
    wd3 = slo.SloWatchdog(ring3, [slo.SloRule(
        "stale", "ckpt", kind="threshold", agg="age", threshold=600,
        windows=((10.0, 1.0),))])
    assert wd3.evaluate(now=1001.0) == []           # age 1 s
    ring3.append(gsnap(1000.0), t=1700.0)
    assert [a.rule for a in wd3.evaluate(now=1700.0)] == ["stale"]


def test_watchdog_alerts_are_metrics_and_log_bounded():
    reg_before = registry.snapshot()["metrics"].get("slo_alerts")
    ring, now = _burn_ring("ggbbbb")
    rule = slo.SloRule("m_rule", "lat", threshold=1.0, budget=0.25,
                       windows=((6.0, 1.0),))
    wd = slo.SloWatchdog(ring, [rule], log_cap=2)
    wd.evaluate(now=now)
    snap = registry.snapshot()["metrics"]
    series = {tuple(sorted(s["labels"].items())): s["value"]
              for s in snap["slo_alerts"]["series"]}
    assert series[(("rule", "m_rule"),)] >= 1
    active = {tuple(sorted(s["labels"].items())): s["value"]
              for s in snap["slo_alert_active"]["series"]}
    assert active[(("rule", "m_rule"),)] == 1.0
    # bounded log
    for i in range(5):
        wd._log.append(slo.Alert(f"r{i}", "lat", 0.0, 1.0, "burn_rate", {}))
    assert len(wd.alerts()) == 2
    with pytest.raises(ValueError):
        wd.add_rule(rule)  # duplicate name


def test_watchdog_alert_notifies_flightrec(tmp_path):
    rec = flightrec.install(flightrec.FlightRecorder(
        str(tmp_path), dump_on=set(), min_interval_s=0.0))
    ring, now = _burn_ring("bbbb")
    wd = slo.SloWatchdog(ring, [slo.SloRule(
        "fr_rule", "lat", threshold=1.0, budget=0.25,
        windows=((6.0, 1.0),))])
    wd.evaluate(now=now)
    kinds = [e["kind"] for e in rec.events()]
    assert "slo_alert" in kinds


def test_default_rules_cover_the_issue_slos():
    rules = {r.name for r in slo.default_rules()}
    assert {"step_time_p95", "serving_p99", "freshness_p95",
            "breaker_open", "failover_promotion", "replication_lag",
            "checkpoint_staleness"} <= rules


# -- obs/aggregate bounds_conflict (direct coverage satellite) --------------

def _hist_snap(bounds, buckets, count, total):
    return {"process": {"role": "p"},
            "metrics": {"lat": {"type": "histogram", "dropped_series": 0,
                                "series": [{"labels": {"k": "v"},
                                            "count": count, "sum": total,
                                            "bounds": list(bounds),
                                            "buckets": list(buckets)}]}}}


def test_aggregate_bounds_conflict_keeps_first_ladder_intact():
    a = _hist_snap((0.1, 1.0), [1, 2, 3], 6, 9.0)
    b = _hist_snap((0.5, 2.0), [4, 4, 4], 12, 20.0)
    merged = aggregate.merge_snapshots([a, b])
    s = merged["metrics"]["lat"]["series"][0]
    # first ladder's data intact, conflict marked, count == sum(buckets)
    assert s["bounds"] == [0.1, 1.0]
    assert s["buckets"] == [1, 2, 3]
    assert s["count"] == 6 and s["sum"] == 9.0
    assert s["bounds_conflict"] is True
    assert sum(s["buckets"]) == s["count"]
    # same-ladder merge still sums (the conflict is per label-set)
    c = _hist_snap((0.1, 1.0), [1, 0, 0], 1, 0.05)
    ok = aggregate.merge_snapshots([a, c])["metrics"]["lat"]["series"][0]
    assert ok["buckets"] == [2, 2, 3] and ok["count"] == 7
    assert "bounds_conflict" not in ok


def test_openmetrics_skips_conflicted_series():
    merged = aggregate.merge_snapshots([
        _hist_snap((0.1, 1.0), [1, 2, 3], 6, 9.0),
        _hist_snap((0.5, 2.0), [4, 4, 4], 12, 20.0)])
    text = exporter.to_openmetrics(merged)
    # a known-corrupt percentile must not reach a scraper as data
    assert "lat_bucket" not in text
    exporter.parse_openmetrics(text)  # still well-formed


# -- OpenMetrics rendering + strict parse (escaping satellite) --------------

def test_openmetrics_label_escaping_round_trip():
    reg = Registry()
    nasty = 'back\\slash "quoted" new\nline'
    reg.counter("evil", path=nasty).inc(3)
    text = exporter.to_openmetrics(reg.snapshot())
    # escaped on the wire: no raw newline inside the sample line
    sample = [ln for ln in text.splitlines() if ln.startswith("evil_total")]
    assert len(sample) == 1
    assert '\\\\' in sample[0] and '\\"' in sample[0] and '\\n' in sample[0]
    fams = exporter.parse_openmetrics(text)
    (_, labels, value), = fams["evil"]["samples"]
    assert labels["path"] == nasty and value == 3.0


def test_openmetrics_histogram_cumulative_and_counter_total():
    reg = Registry()
    h = reg.histogram("lat_s", buckets=(0.1, 1.0), table="0")
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    reg.counter("reqs_total").inc(2)   # *_total family keeps ONE suffix
    text = exporter.to_openmetrics(reg.snapshot())
    fams = exporter.parse_openmetrics(text)
    buckets = [(lbl["le"], v) for n, lbl, v in fams["lat_s"]["samples"]
               if n == "lat_s_bucket"]
    assert buckets == [("0.1", 1.0), ("1", 2.0), ("+Inf", 3.0)]
    assert ("reqs_total", {}, 2.0) in fams["reqs"]["samples"]
    assert "reqs_total_total" not in text
    assert text.endswith("# EOF\n")


def test_openmetrics_parser_rejects_malformations():
    with pytest.raises(ValueError, match="EOF"):
        exporter.parse_openmetrics('# TYPE x counter\nx_total 1\n')
    with pytest.raises(ValueError, match="TYPE"):
        exporter.parse_openmetrics('x_total 1\n# EOF\n')
    with pytest.raises(ValueError, match="belong"):
        exporter.parse_openmetrics(
            '# TYPE x counter\ny_total 1\n# EOF\n')
    with pytest.raises(ValueError, match="escape"):
        exporter.parse_openmetrics(
            '# TYPE x counter\nx_total{a="\\q"} 1\n# EOF\n')
    with pytest.raises(ValueError, match="cumulative"):
        exporter.parse_openmetrics(
            '# TYPE h histogram\nh_bucket{le="1"} 5\n'
            'h_bucket{le="+Inf"} 3\n# EOF\n')
    with pytest.raises(ValueError, match="count"):
        exporter.parse_openmetrics(
            '# TYPE h histogram\nh_bucket{le="+Inf"} 3\nh_count 4\n# EOF\n')
    with pytest.raises(ValueError, match="value"):
        exporter.parse_openmetrics('# TYPE x gauge\nx nope\n# EOF\n')


# -- HTTP exporter ----------------------------------------------------------

def test_exporter_endpoints_and_read_only():
    reg = Registry()
    reg.counter("scraped").inc(7)
    ring = MetricRing()
    ring.append(reg.snapshot(), t=1.0)
    alerts = [{"rule": "r", "t": 1.0}]
    with exporter.ObsExporter(reg.snapshot, ring=ring,
                              alerts_fn=lambda: alerts) as exp:
        with urllib.request.urlopen(f"{exp.url}/metrics", timeout=10) as r:
            body = r.read().decode()
            assert r.headers["Content-Type"] == exporter.CONTENT_TYPE
        fams = exporter.parse_openmetrics(body)
        assert ("scraped_total", {}, 7.0) in fams["scraped"]["samples"]
        with urllib.request.urlopen(f"{exp.url}/history.json",
                                    timeout=10) as r:
            hist = json.load(r)
        assert hist["records"][0]["t"] == 1.0
        with urllib.request.urlopen(f"{exp.url}/alerts.json",
                                    timeout=10) as r:
            assert json.load(r)["alerts"] == alerts
        with urllib.request.urlopen(f"{exp.url}/healthz", timeout=10) as r:
            assert json.load(r)["ok"] is True
        # read-only: POST is 405, unknown path 404
        req = urllib.request.Request(f"{exp.url}/metrics", data=b"x",
                                     method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 405
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{exp.url}/nope", timeout=10)
        assert ei.value.code == 404
    # stopped: the port no longer answers
    with pytest.raises(Exception):
        urllib.request.urlopen(f"{exp.url}/healthz", timeout=0.5)


# -- job collector over a real shard pair (RPC fan-out leg) -----------------

def test_job_collector_merges_shards_and_tolerates_death():
    from paddle_tpu.ps import rpc
    from paddle_tpu.ps.table import TableConfig

    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    client = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers])
    try:
        client.create_sparse_table(
            0, TableConfig(table_id=0, shard_num=4, accessor="ctr"))
        import numpy as np

        keys = np.arange(64, dtype=np.uint64)
        client.pull_sparse(0, keys, create=True)
        coll = timeseries.JobCollector(client=client, period_s=99.0)
        rec = coll.tick(t=1.0)
        assert rec is not None and coll.shard_errors == 0
        merged = coll.latest()
        roles = {p.get("role") for p in merged["processes"]}
        assert {"ps_shard_0", "ps_shard_1"} <= roles
        assert len(merged["processes"]) >= 3  # + this process
        wire = merged["metrics"]["ps_server_wire_bytes"]["series"]
        assert any(s["value"] > 0 for s in wire)
        # kill one shard: the next tick still lands, error counted
        servers[0].stop()
        rec2 = coll.tick(t=2.0)
        assert rec2 is not None
        assert coll.shard_errors >= 1
        assert coll.ticks == 2 and coll.errors == 0
    finally:
        client.close()
        for s in servers:
            s.stop()
            s.close()


# -- timeline.py sloAlerts instant events (satellite) -----------------------

def test_timeline_renders_slo_alerts_as_instants(tmp_path):
    import os
    import sys

    REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import timeline

    # one span lane on a wall anchor + the watchdog's alert log: the
    # alert must land as a GLOBAL instant event, wall-aligned with the
    # span (anchor + raw ts == alert wall seconds * 1e6)
    blob = {"traceEvents": [
                {"name": "step", "ph": "X", "ts": 500_000.0, "dur": 100,
                 "pid": 0, "tid": 0}],
            "clockSyncUs": 1_000_000.0,
            "sloAlerts": [{"rule": "step_time_p95", "t": 1.5,
                           "threshold": 0.1, "cleared_t": 2.0}]}
    p = str(tmp_path / "lane.json")
    json.dump(blob, open(p, "w"))
    out = str(tmp_path / "merged.json")
    timeline.merge_traces([p], out)
    evs = json.load(open(out))["traceEvents"]
    step = next(e for e in evs if e["name"] == "step")
    alert = next(e for e in evs if e["name"] == "ALERT step_time_p95")
    clear = next(e for e in evs if e["name"] == "CLEAR step_time_p95")
    assert alert["ph"] == "i" and alert["s"] == "g"
    assert alert["args"]["threshold"] == 0.1
    # the span's wall time is anchor+ts = 1.5 s — the alert fired at
    # that same instant, so after merge+re-zero they coincide
    assert alert["ts"] == pytest.approx(step["ts"])
    assert clear["ts"] == pytest.approx(step["ts"] + 0.5e6)
    assert alert["pid"] == step["pid"]


# ---------------------------------------------------------------------------
# push subscriptions: on_fire / on_clear (the ps/autoscale.py input)
# ---------------------------------------------------------------------------

def test_watchdog_on_fire_and_on_clear_transitions_only():
    rule = slo.SloRule("lat_p", "lat", threshold=1.0, budget=0.25,
                       windows=((8.0, 1.0), (3.0, 1.0)))
    ring, now = _burn_ring("gggggbbbbb")
    wd = slo.SloWatchdog(ring, [rule])
    fired, cleared = [], []
    wd.on_fire(lambda a: fired.append(a.rule))
    wd.on_clear(lambda a: cleared.append((a.rule, a.cleared_t)))
    wd.evaluate(now=now)
    assert fired == ["lat_p"] and cleared == []
    # still burning: ACTIVE, not a transition — no re-notify spam
    wd.evaluate(now=now)
    assert fired == ["lat_p"]
    # recover on the same ring: new good ticks clear every window
    reg = Registry()
    h = reg.histogram("lat", buckets=(0.1, 1.0))
    t = now + 1
    for _ in range(12):
        h.observe(0.05)
        ring.append(reg.snapshot(), t=t)
        t += 1.0
    wd.evaluate(now=t - 1)
    assert cleared and cleared[0][0] == "lat_p"
    assert cleared[0][1] is not None           # the original alert,
    assert wd.active() == []                   # cleared_t stamped
    # healthy steady state: neither hook re-fires
    wd.evaluate(now=t - 1)
    assert len(fired) == 1 and len(cleared) == 1


def test_watchdog_subscriber_errors_counted_not_fatal():
    rule = slo.SloRule("lat_p", "lat", threshold=1.0, budget=0.25,
                       windows=((3.0, 1.0),))
    ring, now = _burn_ring("bbbb")
    wd = slo.SloWatchdog(ring, [rule])
    seen = []

    def broken(alert):
        raise RuntimeError("subscriber bug")

    wd.on_fire(broken)
    wd.on_fire(lambda a: seen.append(a.rule))  # later subscribers run
    fired = wd.evaluate(now=now)
    assert [a.rule for a in fired] == ["lat_p"]
    assert wd.subscriber_errors == 1
    assert seen == ["lat_p"]


def test_watchdog_on_fire_not_called_while_healthy():
    rule = slo.SloRule("lat_p", "lat", threshold=1.0, budget=0.25,
                       windows=((8.0, 1.0),))
    ring, now = _burn_ring("gggggggg")
    wd = slo.SloWatchdog(ring, [rule])
    called = []
    wd.on_fire(lambda a: called.append(a))
    wd.on_clear(lambda a: called.append(a))
    assert wd.evaluate(now=now) == []
    assert called == []                        # no fire, and no clear
    #                                           for a never-fired rule
