"""Multi-HOST hybrid training: the FULL HybridParallelTrainer step
(pipeline scan over pp, TP collectives over mp, dp grad sync) runs over
a dp×pp×cp×mp mesh spanning two jax.distributed processes — pp stages
live on different hosts, so the pipeline's ppermute and the grad psum
ride the cross-process link inside one compiled program."""

import textwrap

import pytest

from conftest import launch_two_workers

_WORKER = textwrap.dedent("""
    import jax.numpy as jnp
    from jax.sharding import Mesh

    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models.ernie import ErnieConfig
    from paddle_tpu.parallel.hybrid import HybridParallelTrainer

    # pp OUTERMOST over the process-major device order: stage 0 on
    # process 0, stage 1 on process 1 — the pipeline hop crosses hosts
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 1, 2),
                ("pp", "dp", "cp", "mp"))
    pt.seed(0)
    cfg = ErnieConfig(vocab_size=64, hidden_size=16, num_heads=4,
                      ffn_size=32, num_layers=2, max_seq_len=64)
    tr = HybridParallelTrainer(cfg, mesh, optimizer.Adam(1e-2), num_micro=2)
    assert tr._multihost

    rngh = np.random.default_rng(0)
    ids = rngh.integers(0, cfg.vocab_size, size=(8, 8)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    losses = [float(tr.train_step(ids, labels)) for _ in range(4)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses
    print("LOSSES", " ".join(f"{l:.6f}" for l in losses), flush=True)

    # checkpoint across hosts: sharded leaves gather, process 0 writes,
    # everyone restores and the resumed trajectory matches exactly
    import os
    from jax.experimental import multihost_utils

    snap = os.path.join(os.path.dirname(os.path.abspath(__file__)), "snap")
    tr.save(snap)
    multihost_utils.sync_global_devices("snap_written")
    pt.seed(1)  # different init — load must overwrite everything
    tr2 = HybridParallelTrainer(cfg, mesh, optimizer.Adam(1e-2), num_micro=2)
    tr2.load(snap)
    la = float(tr.train_step(ids, labels))
    lb = float(tr2.train_step(ids, labels))
    assert abs(la - lb) < 1e-6, (la, lb)
    print("WORKER_OK", rank, flush=True)
""")


@pytest.mark.slow
def test_two_process_hybrid_trainer(tmp_path):
    outs = launch_two_workers(_WORKER, tmp_path)
    # both processes observed the identical replicated loss trajectory
    l0 = [l for l in outs[0].splitlines() if l.startswith("LOSSES")]
    l1 = [l for l in outs[1].splitlines() if l.startswith("LOSSES")]
    assert l0 and l0 == l1, (l0, l1)
