"""PS transport robustness: timeouts, bounded retry, reconnect and
failover when servers die mid-training.

Reference counterpart: the brpc client's FLAGS_pserver_* deadline/retry
family (brpc_ps_client.cc:24-45) and the elastic manager's expectation
that a dead pserver surfaces as a clean, bounded error rather than a
hang (fleet/elastic/manager.py).
"""

import os
import socket
import subprocess
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import TableConfig

rpc = pytest.importorskip("paddle_tpu.ps.rpc")

pytestmark = pytest.mark.skipif(
    not rpc.rpc_available(), reason="native toolchain unavailable")

_SERVER_SCRIPT = """
import sys
import time
from paddle_tpu.ps.rpc import NativePsServer
s = NativePsServer(port=int(sys.argv[1]), n_trainers=1)
print("READY", s.port, flush=True)
time.sleep(3600)
"""


def _acc():
    return AccessorConfig(sgd=SGDRuleConfig(initial_range=0.0))


def _spawn_server(port=0):
    p = subprocess.Popen([sys.executable, "-c", _SERVER_SCRIPT, str(port)],
                         stdout=subprocess.PIPE, text=True, cwd=_REPO_ROOT)
    line = p.stdout.readline().strip()
    assert line.startswith("READY"), line
    return p, int(line.split()[1])


@pytest.fixture
def fast_flags():
    """Short deadlines so failure paths stay test-sized; restored after."""
    saved = pt.get_flags(["pserver_connect_timeout_ms", "pserver_timeout_ms",
                          "pserver_max_retry", "pserver_retry_backoff_ms",
                          "pserver_long_call_timeout_ms",
                          "pserver_barrier_timeout_ms"])
    pt.set_flags({"pserver_connect_timeout_ms": 1000,
                  "pserver_timeout_ms": 800,
                  "pserver_max_retry": 2,
                  "pserver_retry_backoff_ms": 20,
                  "pserver_long_call_timeout_ms": 1500,
                  "pserver_barrier_timeout_ms": 2000})
    yield
    pt.set_flags(saved)


def test_kill_server_mid_training_raises_bounded(fast_flags):
    """SIGKILL a live server mid-training: the next call fails with a
    clean PreconditionNotMetError naming the endpoint, within the
    retry×timeout budget — never a hang, never a wedged trainer."""
    proc, port = _spawn_server()
    try:
        cli = rpc.RpcPsClient([f"127.0.0.1:{port}"])
        cli.create_sparse_table(0, TableConfig(shard_num=4,
                                               accessor_config=_acc()))
        keys = np.arange(1, 64, dtype=np.uint64)
        assert (cli.pull_sparse(0, keys) == 0).all()  # training under way

        proc.kill()
        proc.wait()
        t0 = time.monotonic()
        with pytest.raises(Exception, match="unreachable|refused|reset"):
            cli.pull_sparse(0, keys)
        elapsed = time.monotonic() - t0
        # 2 attempts × (≤1s connect) + backoff — well under the 30s the
        # old transport would hang for (forever, on a half-open peer)
        assert elapsed < 10, elapsed
        cli.close()
    finally:
        if proc.poll() is None:
            proc.kill()


def test_unresponsive_server_call_times_out(fast_flags):
    """A server that accepts but never answers (wedged host) trips the
    per-call IO deadline instead of blocking the trainer forever."""
    lst = socket.socket()
    lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lst.bind(("127.0.0.1", 0))
    lst.listen(4)
    port = lst.getsockname()[1]
    accepted = []
    import threading

    def sink():
        try:
            while True:
                c, _ = lst.accept()
                accepted.append(c)  # read nothing, answer nothing
        except OSError:
            pass

    th = threading.Thread(target=sink, daemon=True)
    th.start()
    try:
        cli = rpc.RpcPsClient([f"127.0.0.1:{port}"])
        t0 = time.monotonic()
        with pytest.raises(Exception, match="unreachable|timed out"):
            cli.create_sparse_table(0, TableConfig(shard_num=4,
                                                   accessor_config=_acc()))
        elapsed = time.monotonic() - t0
        assert elapsed < 10, elapsed  # 2 × 0.8s deadline + backoff
        cli.close()
    finally:
        lst.close()
        for c in accepted:
            c.close()


def test_barrier_deadline_is_finite(fast_flags):
    """A barrier against a world that never completes (peer died before
    arriving) trips the generous-but-finite barrier deadline instead of
    wedging the trainer forever."""
    lib = rpc._rpc_lib()
    h = lib.pss_create(0, 2)  # 2-trainer barrier; only 1 will arrive
    port = int(lib.pss_port(h))
    try:
        cli = rpc.RpcPsClient([f"127.0.0.1:{port}"])
        t0 = time.monotonic()
        with pytest.raises(Exception, match="unreachable|timed out"):
            cli.barrier()
        assert 1.0 < time.monotonic() - t0 < 10
        cli.close()
    finally:
        lib.pss_destroy(h)


def test_barrier_timeout_cancels_arrival(fast_flags):
    """A trainer whose barrier timed out must NOT leave a phantom
    arrival: the server cancels the count when the waiter's connection
    drops, so the next generation still requires every live trainer."""
    import threading

    lib = rpc._rpc_lib()
    h = lib.pss_create(0, 2)
    port = int(lib.pss_port(h))
    try:
        a = rpc.RpcPsClient([f"127.0.0.1:{port}"])
        with pytest.raises(Exception, match="unreachable|timed out"):
            a.barrier()  # arrives alone, times out, disconnects
        a.close()
        time.sleep(0.3)  # let the server notice the hangup and cancel

        b = rpc.RpcPsClient([f"127.0.0.1:{port}"])
        c = rpc.RpcPsClient([f"127.0.0.1:{port}"])
        released = []

        def arrive(cli, tag):
            cli.barrier()
            released.append(tag)

        tb = threading.Thread(target=arrive, args=(b, "b"), daemon=True)
        tb.start()
        time.sleep(0.7)
        # with a phantom arrival counted, b alone would have released
        assert released == [], "barrier released with a phantom arrival"
        tc = threading.Thread(target=arrive, args=(c, "c"), daemon=True)
        tc.start()
        tb.join(5)
        tc.join(5)
        assert sorted(released) == ["b", "c"]
        b.close()
        c.close()
    finally:
        lib.pss_destroy(h)


def test_bulk_load_survives_server_crash_and_replay(fast_flags, tmp_path):
    """The 1e9-path crash story: SIGKILL a server mid-bulk-load, restart
    it on the same SSD directories (cold-tier log replay), re-issue the
    failed chunk (client retries are at-least-once — duplicate appends
    are benign: the index keeps the newest record, compaction reclaims
    the garbage) and finish the load; every row is present with the
    right values and compact() shrinks the log back."""
    import paddle_tpu.ps.rpc as _rpc
    from paddle_tpu.ps.accessor import AccessorConfig

    proc, port = _spawn_server()
    cli = None
    acc = AccessorConfig(embedx_dim=4, embedx_threshold=0.0,
                         sgd=SGDRuleConfig(initial_range=0.0))
    cfg = TableConfig(shard_num=4, accessor_config=acc, storage="ssd",
                      ssd_path=str(tmp_path / "tiers"))
    # keep fast_flags' tight 1.5 s long-call deadline (it's what makes
    # the at-least-once duplicate scenario reproducible) but give the
    # calls more retry headroom: on a loaded 1-core CI host the SSD
    # replay/chunk commands can blow that deadline a few times in a row,
    # and 2 attempts turned this test flaky under the full suite
    pt.set_flags({"pserver_max_retry": 6})
    try:
        cli = _rpc.RpcPsClient([f"127.0.0.1:{port}"])
        cli.create_sparse_table(0, cfg)
        full_dim = cli._dims(0)[2]
        rng = np.random.default_rng(7)
        n = 30_000
        keys = np.arange(1, n + 1, dtype=np.uint64)
        vals = np.zeros((n, full_dim), np.float32)
        vals[:, 3] = 1.0
        vals[:, 5] = rng.normal(0, 0.01, n).astype(np.float32)

        half = n // 2
        assert cli.load_cold(0, keys[:half], vals[:half]) == half
        proc.kill()
        proc.wait()
        with pytest.raises(Exception, match="unreachable"):
            cli.load_cold(0, keys[half:], vals[half:])

        # restart on the SAME directories: the cold log replays
        proc, port2 = _spawn_server(port)
        assert port2 == port
        cli.create_sparse_table(0, cfg)
        st = cli.table_stats(0)
        assert st["cold_rows"] == half  # replayed, nothing lost
        # at-least-once retry: re-issue the whole failed chunk PLUS an
        # overlap of already-loaded rows (a retried frame the server
        # had actually applied before dying)
        overlap = keys[half - 1000 : half]
        assert cli.load_cold(0, np.concatenate([overlap, keys[half:]]),
                             np.concatenate([vals[half - 1000 : half],
                                             vals[half:]])) == n - half + 1000
        # at-least-once means a client-side timeout can leave an EARLIER
        # attempt still applying server-side after the retry succeeded
        # (fast_flags' 1.5 s long-call deadline makes this reproducible
        # on the 1-core host) — counts are eventually consistent, so
        # poll to quiescence before asserting
        deadline = time.monotonic() + 15
        while True:
            st = cli.table_stats(0)
            if st["cold_rows"] == n or time.monotonic() > deadline:
                break
            time.sleep(0.2)
        assert st["cold_rows"] == n  # duplicates shadowed, not counted
        sample = rng.choice(keys, 500, replace=False)
        got, found = cli.export_full(0, sample)
        assert found.all()
        np.testing.assert_allclose(got, vals[sample.astype(np.int64) - 1],
                                   atol=1e-6)
        disk_before = cli.table_stats(0)["disk_bytes"]
        cli.compact(0)
        st2 = cli.table_stats(0)
        assert st2["disk_bytes"] <= disk_before  # garbage reclaimed
        # export_full PROMOTED the sampled rows to the hot tier (the
        # documented tier protocol) — the invariant is total rows, not
        # cold rows
        assert st2["hot_rows"] + st2["cold_rows"] == n
        assert st2["hot_rows"] == len(sample)
    finally:
        if cli is not None:
            cli.close()
        if proc.poll() is None:
            proc.kill()


def test_failover_to_restarted_server(fast_flags):
    """Stretch goal: kill a server, restart it on the same port, and the
    SAME client object recovers via reconnect — re-create the table,
    reload the checkpoint, keep training (the elastic resume loop)."""
    proc, port = _spawn_server()
    cli = None
    try:
        cfg = TableConfig(shard_num=4, accessor_config=_acc())
        cli = rpc.RpcPsClient([f"127.0.0.1:{port}"])
        cli.create_sparse_table(0, cfg)
        keys = np.arange(1, 128, dtype=np.uint64)
        push = np.zeros((len(keys), 12), np.float32)
        push[:, 1] = 1.0
        push[:, 3:] = 0.25
        cli.pull_sparse(0, keys)
        cli.push_sparse(0, keys, push)
        before = cli.pull_sparse(0, keys, create=False)

        import tempfile

        with tempfile.TemporaryDirectory() as ckpt:
            cli.save(0, ckpt)

            proc.kill()
            proc.wait()
            with pytest.raises(Exception, match="unreachable"):
                cli.pull_sparse(0, keys, create=False)

            proc, port2 = _spawn_server(port)  # same endpoint comes back
            assert port2 == port
            # the client's retry loop reconnects transparently; state is
            # restored from the checkpoint (auto-checkpoint resume role)
            cli.create_sparse_table(0, cfg)
            cli.load(0, ckpt)
        after = cli.pull_sparse(0, keys, create=False)
        np.testing.assert_allclose(after, before, atol=1e-6)
        # and training continues
        cli.push_sparse(0, keys, push)
        assert cli.size(0) == len(keys)
    finally:
        if cli is not None:
            cli.close()
        if proc.poll() is None:
            proc.kill()
