"""Serving fleet (ISSUE 15): router balancing/hedging/reroute, fleet
membership + drain + warm handoff, autoscaler lever, and the versioned
dense-tower rollout lifecycle.

Layers, bottom-up: the ServingRouter's bounded-load consistent-hash
affinity, P2C, hedge-with-dedupe and failure-reroute semantics (stub
members — deterministic under injected rng/clock); the frontend's
drain-rate-derived retry-after (satellite 1); fleet join/drain/crash
over REAL replicas with the TTL-lease watch; warm handoff vs a cold
join (the miss-storm comparison SERVING_FLEET.json curves); the PR 11
Autoscaler driving replica count; canary/promote/rollback with exact
split counting and digest-pinned rollback (satellite 3); per-replica
metric labels + fleet SLO rules + the router-process /metrics view
(satellite 4)."""

import random
import threading
import time
import urllib.request

import numpy as np
# eager: numpy.testing's lazy import forks (SVE probe) — deadlocks the
# sanitizer sweeps once cluster threads are live (test_serving.py note)
import numpy.testing  # noqa: F401
import pytest

from paddle_tpu.io.fs import crc32c
from paddle_tpu.obs import registry as obs_registry
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import TableConfig

rpc = pytest.importorskip("paddle_tpu.ps.rpc")

pytestmark = pytest.mark.skipif(
    not rpc.rpc_available(), reason="native toolchain unavailable")

from paddle_tpu.distributed import elastic  # noqa: E402
from paddle_tpu.ps import ha  # noqa: E402
from paddle_tpu.ps.autoscale import AutoscaleConfig, Autoscaler  # noqa: E402
from paddle_tpu.ps.hot_tier import (HotEmbeddingTier,  # noqa: E402
                                    HotTierConfig)
from paddle_tpu.serving import (CachedLookup, DenseModel,  # noqa: E402
                                FleetConfig, FleetMember, FrontendConfig,
                                RequestRejected, RolloutConfig,
                                RolloutManager, RouterConfig, RoutedRequest,
                                ServingFleet, ServingFrontend,
                                ServingReplica, ServingRouter)
from paddle_tpu.serving.router import _splitmix64  # noqa: E402


# ---------------------------------------------------------------------------
# stub plumbing (router-only tests: no cluster, no RPC)
# ---------------------------------------------------------------------------

class _StubLookup:
    def __init__(self, delay_s=0.0, tag=0.0):
        self.delay_s = delay_s
        self.tag = tag
        self.calls = 0

    def lookup(self, keys):
        self.calls += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        k = keys.astype(np.float64)
        return np.stack([k, k + self.tag], axis=1).astype(np.float32)


class _FakeReplicaHandle:
    """Replica-shaped stub for FleetMember lifecycle tests."""

    class _Srv:
        stopped = False

    def __init__(self, endpoint):
        self.endpoint = endpoint
        self.server = self._Srv()

    def status(self):
        return {"endpoint": self.endpoint}

    def close(self):
        self.server.stopped = True

    def kill(self):
        self.server.stopped = True


class _StubMember:
    """Router-protocol member over a real frontend + stub lookup."""

    def __init__(self, name, delay_s=0.0, tag=0.5, model=None, **fe_kw):
        self.endpoint = name
        self.lookup = _StubLookup(delay_s, tag)
        fe_kw.setdefault("max_batch", 8)
        fe_kw.setdefault("max_delay_us", 100)
        fe_kw.setdefault("queue_cap", 256)
        self.frontend = ServingFrontend(self.lookup,
                                        config=FrontendConfig(**fe_kw),
                                        replica_label=name)
        self.model = model

    @property
    def healthy(self):
        return not self.frontend.stopped

    def stop(self):
        self.frontend.stop()


def _router(**kw):
    kw.setdefault("rng", random.Random(0))
    cfg = kw.pop("config", None) or RouterConfig()
    return ServingRouter(cfg, **kw)


def _keys_for_block(block, shift=6, n=8):
    base = block << shift
    return np.arange(base, base + n, dtype=np.uint64)


# ---------------------------------------------------------------------------
# router: affinity, bounded load, P2C, hedging, reroute
# ---------------------------------------------------------------------------

def test_ch_affinity_same_block_same_member_blocks_spread():
    members = [_StubMember(f"m{i}") for i in range(3)]
    with _router() as r:
        for m in members:
            r.attach(m)
        try:
            # same block → same member, every time (CachedLookup
            # residency is per-member; affinity IS the warm hit rate)
            picks = set()
            for _ in range(8):
                rr = r.submit(_keys_for_block(5), deadline_ms=5000)
                rr.result(10)
                picks.add(rr.tried[0])
            assert len(picks) == 1
            # distinct blocks cover the whole fleet
            eps = set()
            for b in range(48):
                rr = r.submit(_keys_for_block(b), deadline_ms=5000)
                rr.result(10)
                eps.add(rr.tried[0])
            assert eps == {m.endpoint for m in members}
            st = r.stats()
            assert st["sparse_ch"] == 8 + 48
            assert st["errors"] == 0 and st["reroutes"] == 0
        finally:
            for m in members:
                m.stop()


def test_bounded_load_diverts_overloaded_member():
    members = [_StubMember(f"m{i}") for i in range(3)]
    with _router() as r:
        for m in members:
            r.attach(m)
        try:
            rr = r.submit(_keys_for_block(5), deadline_ms=5000)
            rr.result(10)
            home = rr.tried[0]
            # saturate the home member's in-flight ledger: the CH walk
            # must skip past it to the NEXT ring choice
            with r._mu:
                r._members[home].inflight = 100
            rr2 = r.submit(_keys_for_block(5), deadline_ms=5000)
            rr2.result(10)
            assert rr2.tried[0] != home
            with r._mu:
                r._members[home].inflight = 0
            rr3 = r.submit(_keys_for_block(5), deadline_ms=5000)
            rr3.result(10)
            assert rr3.tried[0] == home     # load gone → affinity back
        finally:
            for m in members:
                m.stop()


def test_p2c_dense_prefers_shallower_queue():
    # m0's worker is wedged on a slow batch with a backlog queued; P2C
    # (seeded rng) must steer non-affinity traffic to m1
    m0 = _StubMember("m0", delay_s=0.2, max_batch=1, max_delay_us=10)
    m1 = _StubMember("m1")
    with _router() as r:
        r.attach(m0)
        r.attach(m1)
        try:
            backlog = [m0.frontend.submit(_keys_for_block(1),
                                          deadline_ms=30000)
                       for _ in range(8)]
            picks = []
            for _ in range(12):
                rr = r.submit(_keys_for_block(2), deadline_ms=30000,
                              affinity=False)
                rr.result(30)
                picks.append(rr.tried[0])
            assert picks.count("m1") > picks.count("m0"), picks
            assert r.stats()["dense_p2c"] == 12
            for p in backlog:
                p.result(30)
        finally:
            m0.stop()
            m1.stop()


def test_hedge_fires_after_budget_dedupes_and_meters():
    slow = _StubMember("slow", delay_s=0.4, tag=100.0)
    fast = _StubMember("fast", tag=0.5)
    cfg = RouterConfig(hedge_default_ms=20.0, hedge_min_samples=1 << 30)
    with _router(config=cfg) as r:
        r.attach(slow)
        r.attach(fast)
        try:
            # find a block whose first choice is the slow member
            block = next(b for b in range(64) if r._pick(
                RoutedRequest(r, None, None, 1e4, b, "-")).endpoint
                == "slow")
            t0 = time.perf_counter()
            rr = r.submit(_keys_for_block(block), deadline_ms=10000)
            out = rr.result(10)
            dt = time.perf_counter() - t0
            # the hedge (fast member) answered: its tag, well under the
            # slow member's 400 ms
            assert np.allclose(out[:, 1] - out[:, 0], 0.5)
            assert dt < 0.35, dt
            assert rr.tried == ["slow", "fast"]
            st = r.stats()
            assert st["hedges"] == 1 and st["hedge_wins"] == 1
            # the loser completes later and is deduped, not delivered
            deadline = time.monotonic() + 5
            while r.stats()["hedge_lost"] < 1:
                assert time.monotonic() < deadline, r.stats()
                time.sleep(0.02)
            assert st["errors"] == 0
        finally:
            slow.stop()
            fast.stop()


def test_failure_reroutes_and_ejects_dead_member():
    members = [_StubMember(f"m{i}") for i in range(3)]
    with _router() as r:
        for m in members:
            r.attach(m)
        try:
            rr = r.submit(_keys_for_block(7), deadline_ms=5000)
            rr.result(10)
            home = rr.tried[0]
            # SIGKILL-shaped: the frontend dies; queued+new submits fail
            next(m for m in members if m.endpoint == home).stop()
            for _ in range(4):
                out = r.submit(_keys_for_block(7),
                               deadline_ms=5000).result(10)
                assert out.shape == (8, 2)
            st = r.stats()
            assert st["reroutes"] >= 1
            assert st["errors"] == 0
            assert home not in r.endpoints()      # ejected on failure
            # no members at all → immediate, honest rejection
            for m in members:
                m.stop()
            for ep in [m.endpoint for m in members]:
                r.remove(ep)
            with pytest.raises(RequestRejected, match="no live"):
                r.submit(_keys_for_block(1), deadline_ms=1000)
        finally:
            for m in members:
                m.stop()


def test_ring_hash_is_process_stable():
    # ring placement must not ride PYTHONHASHSEED (a salted hash routes
    # the same block to different members in different processes) —
    # golden values pin the cross-process contract
    from paddle_tpu.serving.router import _stable_str_hash

    assert _stable_str_hash("127.0.0.1:7001") == 17876159239217230246
    assert _stable_str_hash("127.0.0.1:7002") == 15823385287752048255
    assert _stable_str_hash("") == _stable_str_hash("")


def test_failure_with_hedge_outstanding_waits_for_sibling():
    """A failed sub must not finalize the request while its hedge is
    still in flight — the hedge may (and here does) deliver the
    answer."""

    class _FailingLookup:
        def lookup(self, keys):
            time.sleep(0.1)
            raise RuntimeError("replica storage gone")

    bad = _StubMember("bad")
    bad.lookup = None  # replaced below via frontend
    bad = _StubMember.__new__(_StubMember)
    bad.endpoint = "bad"
    bad.lookup = _FailingLookup()
    bad.frontend = ServingFrontend(bad.lookup, config=FrontendConfig(
        max_batch=8, max_delay_us=100, queue_cap=64))
    bad.model = None
    slow_ok = _StubMember("slow-ok", delay_s=0.3, tag=0.5)
    cfg = RouterConfig(hedge_default_ms=20.0, hedge_min_samples=1 << 30,
                       max_attempts=2)
    with _router(config=cfg) as r:
        r.attach(bad)
        r.attach(slow_ok)
        try:
            block = next(b for b in range(64) if r._pick(
                RoutedRequest(r, None, None, 1e4, b, "-")).endpoint
                == "bad")
            rr = r.submit(_keys_for_block(block), deadline_ms=10000)
            # timeline: hedge to slow-ok at ~20 ms; bad FAILS at
            # ~100 ms (no attempts left, but the hedge is outstanding);
            # slow-ok delivers at ~300 ms — the caller must get it
            out = rr.result(10)
            assert np.allclose(out[:, 1] - out[:, 0], 0.5)
            assert r.stats()["errors"] == 0
        finally:
            bad.frontend.stop()
            slow_ok.stop()


def test_drain_marker_blocks_watcher_readmission():
    """tick() must not re-admit a healthy, leased member that drain()
    deliberately ejected (the drain-vs-watcher race)."""
    store = elastic.MemoryStore()
    sm = _StubMember("dr1")
    member = FleetMember(_FakeReplicaHandle("dr1"), sm.lookup, sm.frontend)
    router = _router()
    fleet = ServingFleet(store, "dr-job", lambda: member, router)
    try:
        with fleet._mu:
            fleet._members["dr1"] = member
            fleet._join_order.append("dr1")
        router.attach(member)
        store.put("ps/dr-job/obs/0/dr1", "{}", ttl=30.0)  # leased
        router.eject("dr1")
        fleet.tick()
        # healthy + leased + unrouted ⇒ the watcher re-admits (the
        # transient-error heal path)
        assert "dr1" in router.endpoints()
        router.eject("dr1")
        with fleet._mu:
            fleet._draining.add("dr1")
        fleet.tick()
        assert "dr1" not in router.endpoints()   # drain owns the eject
    finally:
        sm.stop()
        fleet.stop()
        router.stop()


def test_lease_miss_grace_before_eviction():
    """Crash-removal SIGKILLs, so one stale lease read must not execute
    a healthy member: eviction needs ``FleetConfig.evict_misses``
    CONSECUTIVE misses, a hit resets the count, and a member whose
    process is verifiably dead skips the grace entirely."""
    store = elastic.MemoryStore()
    sm = _StubMember("ev1")
    member = FleetMember(_FakeReplicaHandle("ev1"), sm.lookup, sm.frontend)
    router = _router()
    fleet = ServingFleet(store, "ev-job", lambda: member, router)
    try:
        with fleet._mu:
            fleet._members["ev1"] = member
            fleet._join_order.append("ev1")
        router.attach(member)
        lease = "ps/ev-job/obs/0/ev1"
        store.put(lease, "{}", ttl=30.0)
        # miss 1 (transient): retained, still routed, nothing killed
        store.delete(lease)
        fleet.tick()
        assert fleet.member("ev1") is member
        assert member.healthy and "ev1" in router.endpoints()
        assert fleet.counters["crashes_removed"] == 0
        # a hit RESETS the consecutive count…
        store.put(lease, "{}", ttl=30.0)
        fleet.tick()
        # …so the next single miss is again only miss 1
        store.delete(lease)
        fleet.tick()
        assert fleet.member("ev1") is member and member.healthy
        # miss 2 consecutive: evicted for real (removed + crashed)
        fleet.tick()
        assert fleet.member("ev1") is None
        assert not member.healthy
        assert fleet.counters["crashes_removed"] == 1
        assert "ev1" not in router.endpoints(live_only=False)
        # a DEAD member gets no grace: first miss removes it
        sm2 = _StubMember("ev2")
        member2 = FleetMember(_FakeReplicaHandle("ev2"), sm2.lookup,
                              sm2.frontend)
        with fleet._mu:
            fleet._members["ev2"] = member2
            fleet._join_order.append("ev2")
        member2.replica.kill()           # proc verifiably gone
        fleet.tick()
        assert fleet.member("ev2") is None
        assert fleet.counters["crashes_removed"] == 2
        sm2.stop()
    finally:
        sm.stop()
        fleet.stop()
        router.stop()


# ---------------------------------------------------------------------------
# satellite 1: retry-after from measured drain rate
# ---------------------------------------------------------------------------

def test_retry_after_derived_from_drain_rate():
    idle = _StubMember("idle")
    slow = _StubMember("busy", delay_s=0.02, max_batch=1, max_delay_us=10,
                       queue_cap=64)
    try:
        # idle: no backlog → the config floor
        assert idle.frontend.retry_after_hint_ms() == \
            idle.frontend.config.retry_after_ms
        # measure a drain rate (a few served batches), then pile a
        # backlog: the quoted backoff must scale with backlog/rate
        for _ in range(4):
            slow.frontend.submit(_keys_for_block(0),
                                 deadline_ms=30000).result(30)
        backlog = [slow.frontend.submit(_keys_for_block(0),
                                        deadline_ms=30000)
                   for _ in range(40)]
        hint = slow.frontend.retry_after_hint_ms()
        assert hint > idle.frontend.retry_after_hint_ms()
        assert hint > 100.0, hint       # 40 queued at ~50/s ≈ 800 ms
        assert hint <= slow.frontend.config.retry_after_max_ms
        # a shed request carries the measured hint, not the constant
        shed_hint = None
        try:
            for _ in range(80):
                backlog.append(slow.frontend.submit(
                    _keys_for_block(0), deadline_ms=30000))
        except RequestRejected as e:
            shed_hint = e.retry_after_ms
        assert shed_hint is not None and shed_hint > 100.0, shed_hint
        for p in backlog:
            p.result(60)
    finally:
        idle.stop()
        slow.stop()


# ---------------------------------------------------------------------------
# real-cluster plumbing
# ---------------------------------------------------------------------------

def _acc(dim=4):
    return AccessorConfig(embedx_dim=dim, embedx_threshold=0.0,
                          sgd=SGDRuleConfig(initial_range=0.01))


def _cfg(dim=4):
    return TableConfig(shard_num=4, accessor_config=_acc(dim))


def _push(rng, keys, width):
    push = np.zeros((len(keys), width), np.float32)
    push[:, 1] = 1.0
    push[:, 2:] = rng.normal(0, 0.1, (len(keys), width - 2)).astype(
        np.float32)
    return push


def _cluster(**kw):
    kw.setdefault("num_shards", 1)
    kw.setdefault("replication", 1)
    kw.setdefault("sync", True)
    return ha.HACluster(**kw)


def _wait_caught_up(cluster, serve_cli, table_id=0, timeout=15.0):
    deadline = time.monotonic() + timeout
    while True:
        prim = cluster.primary(0)
        dg_p = cluster.digests(table_id, 0).get(prim.endpoint)
        dg_r = serve_cli.digest(table_id)[0]
        if dg_p is not None and dg_p == dg_r:
            return
        assert time.monotonic() < deadline, "replica never caught up"
        time.sleep(0.02)


def _member_factory(cluster, table_cfg, capacity=1 << 11, model_flat=None,
                    unravel=None):
    """Real fleet member: replica (fast lease), caught-up serve view,
    read-only tier + CachedLookup, frontend labeled by endpoint."""

    def build():
        rep = ServingReplica(cluster.store, cluster.job_id, shard=0,
                             hb_interval=0.05, hb_ttl=0.4)
        serve = rep.client()
        view = rep.serve_view(0, table_cfg, client=serve)
        _wait_caught_up(cluster, serve)
        tier = HotEmbeddingTier(view, HotTierConfig(
            capacity=capacity, create_on_miss=False))
        cl = CachedLookup(tier, replica=rep, freshness_budget_s=30.0)
        model = None
        if model_flat is not None:
            model = DenseModel(unravel or (lambda f: f), model_flat)
        fe = ServingFrontend(cl, config=FrontendConfig(
            max_batch=16, max_delay_us=200, queue_cap=512,
            default_deadline_ms=5000.0), replica_label=rep.endpoint)
        return FleetMember(rep, cl, fe, model=model)

    return build


def _preload(cli, keys, rng):
    cli.create_sparse_table(0, _cfg())
    cli.pull_sparse(0, keys)
    width = cli._dims(0)[1]
    cli.push_sparse(0, keys, _push(rng, keys, width))
    return width


# ---------------------------------------------------------------------------
# fleet: join / drain / crash-by-lease / warm handoff
# ---------------------------------------------------------------------------

def test_fleet_join_drain_and_crash_lease_removal():
    with _cluster() as cluster:
        cli = cluster.client()
        rng = np.random.default_rng(0)
        keys = np.arange(512, dtype=np.uint64)
        _preload(cli, keys, rng)
        router = _router()
        fleet = ServingFleet(cluster.store, cluster.job_id,
                             _member_factory(cluster, _cfg()), router,
                             config=FleetConfig(poll_s=0.05))
        try:
            m1, m2 = fleet.add(2, warm=False)
            assert fleet.size() == 2
            assert set(router.endpoints()) == {m1.endpoint, m2.endpoint}
            # traffic lands across the fleet, zero errors
            for b in range(8):
                out = router.submit(keys[b * 64:b * 64 + 8],
                                    deadline_ms=5000).result(10)
                assert out.shape == (8, 5)
            # draining restart: eject → finish in-flight → lease gone
            assert fleet.drain(m1.endpoint)
            assert fleet.size() == 1
            assert m1.endpoint not in router.endpoints()
            assert m1.endpoint not in fleet._leased_endpoints()
            # requests keep flowing through the survivor
            out = router.submit(keys[:8], deadline_ms=5000).result(10)
            assert out.shape == (8, 5)
            # crash: lease expires by TTL; the watch removes the member
            m2.crash()
            deadline = time.monotonic() + 10
            while fleet.members(live_only=False):
                fleet.tick()
                assert time.monotonic() < deadline, "crash never expired"
                time.sleep(0.05)
            assert m2.endpoint not in router.endpoints(live_only=False)
            assert fleet.counters["crashes_removed"] == 1
            # the fleet recovers by joining a fresh member
            fleet.add(1, warm=False)
            out = router.submit(keys[:8], deadline_ms=5000).result(10)
            assert out.shape == (8, 5)
        finally:
            fleet.stop()
            router.stop()


def test_warm_handoff_beats_cold_join():
    with _cluster() as cluster:
        cli = cluster.client()
        rng = np.random.default_rng(1)
        keys = np.arange(1024, dtype=np.uint64)
        _preload(cli, keys, rng)
        router = _router()
        fleet = ServingFleet(cluster.store, cluster.job_id,
                             _member_factory(cluster, _cfg()), router,
                             config=FleetConfig(poll_s=0.05,
                                                warm_chunk=256))
        try:
            (seed,) = fleet.add(1, warm=False)
            # season the peer: its resident set IS the working set
            for lo in range(0, len(keys), 64):
                seed.lookup.lookup(keys[lo:lo + 64])
            occ = seed.lookup.tier.stats()["occupancy"]
            assert occ >= len(keys)
            # WARM join: the peer's manifest is bulk-admitted
            (warm,) = fleet.add(1, warm=True)
            handoff = fleet.events[-1]["handoff"]
            assert handoff is not None and handoff["rows"] >= len(keys)
            warm_miss0 = warm.lookup.tier.counters["misses"]
            for lo in range(0, len(keys), 64):
                warm.lookup.lookup(keys[lo:lo + 64])
            warm_misses = warm.lookup.tier.counters["misses"] - warm_miss0
            # COLD join: every row is a serving-path miss
            (cold,) = fleet.add(1, warm=False)
            cold_miss0 = cold.lookup.tier.counters["misses"]
            for lo in range(0, len(keys), 64):
                cold.lookup.lookup(keys[lo:lo + 64])
            cold_misses = cold.lookup.tier.counters["misses"] - cold_miss0
            assert warm_misses == 0, warm_misses
            assert cold_misses >= len(keys)
            assert warm_misses < cold_misses
            # the handoff rows were stamped fresh: values match the
            # cold-join (feed-converged) reads bit-for-bit
            np.testing.assert_array_equal(warm.lookup.lookup(keys[:64]),
                                          cold.lookup.lookup(keys[:64]))
        finally:
            fleet.stop()
            router.stop()


# ---------------------------------------------------------------------------
# autoscaler lever: PR 11 hysteresis, replica count as the actuator
# ---------------------------------------------------------------------------

class _Alert:
    def __init__(self, rule):
        self.rule = rule


def test_autoscaler_drives_replica_count():
    store = elastic.MemoryStore()

    def stub_factory():
        name = f"as-m{next(_SEQ)}"
        sm = _StubMember(name)
        rep = _FakeReplicaHandle(name)
        member = FleetMember(rep, sm.lookup, sm.frontend)
        return member

    router = _router()
    fleet = ServingFleet(store, "as-job", stub_factory, router,
                         config=FleetConfig(min_replicas=2,
                                            max_replicas=8))
    t = [0.0]
    scaler = Autoscaler(fleet.controller(), config=AutoscaleConfig(
        min_shards=2, max_shards=8,
        up_rules=("fleet_serving_p99", "serving_p99"),
        cooldown_up_s=5.0, cooldown_down_s=10.0, clear_hold_s=4.0),
        clock=lambda: t[0])
    try:
        fleet.add(2, warm=False)
        assert scaler.step() is None                  # quiet
        scaler.notify_fire(_Alert("fleet_serving_p99"))
        assert scaler.step() == "up" and fleet.size() == 4
        assert scaler.events[-1]["kind"] == "scale"
        t[0] = 2.0
        assert scaler.step() is None                  # up-cooldown holds
        scaler.notify_clear(_Alert("fleet_serving_p99"))
        t[0] = 4.0
        assert scaler.step() is None                  # quiet-hold not met
        t[0] = 20.0
        assert scaler.step() == "down" and fleet.size() == 2
        # journal landed in the serving namespace of the elastic store
        assert store.list_prefix("ps/as-job/serving/scale/")
    finally:
        fleet.stop()
        router.stop()


_SEQ = iter(range(1, 1 << 20))


# ---------------------------------------------------------------------------
# satellite 3: dense-version lifecycle (canary → promote → rollback)
# ---------------------------------------------------------------------------

def _model_member(name, dim=16):
    holder = {}
    flat = np.arange(dim, dtype=np.float32)
    model = DenseModel(lambda f: f, flat, version=1,
                       sink=lambda p: holder.__setitem__("p", p))
    m = _StubMember(name, model=model)
    m.holder = holder
    return m


def test_canary_split_exact_counted_per_version():
    members = [_model_member(f"c{i}") for i in range(4)]
    with _router() as r:
        for m in members:
            r.attach(m)
        try:
            mgr = RolloutManager(lambda: members, r,
                                 RolloutConfig(canary_members=1))
            v1 = mgr.register_baseline(np.arange(16, dtype=np.float32))
            v2 = mgr.begin_canary(np.arange(16, dtype=np.float32) + 1.0,
                                  fraction=0.3)
            canary_eps = {ep for ep in r.stats()["canary"]["endpoints"]}
            assert len(canary_eps) == 1
            blocks = list(range(400))
            expect_canary = sum(r.in_canary_band(b, 0.3) for b in blocks)
            assert 0 < expect_canary < len(blocks)   # a real split
            for b in blocks:
                rr = r.submit(_keys_for_block(b), deadline_ms=5000)
                rr.result(10)
                # the routed member matches the band side, exactly
                assert (rr.tried[0] in canary_eps) == \
                    r.in_canary_band(b, 0.3)
            counts = r.stats()["version_counts"]
            assert counts == {str(v2): expect_canary,
                              str(v1): len(blocks) - expect_canary}
        finally:
            for m in members:
                m.stop()


def test_promote_flips_fleet_rollback_digest_identical():
    members = [_model_member(f"p{i}") for i in range(3)]
    with _router() as r:
        for m in members:
            r.attach(m)
        try:
            mgr = RolloutManager(lambda: members, r)
            flat1 = np.arange(16, dtype=np.float32)
            flat2 = flat1 + 2.0
            dg1 = crc32c(np.ascontiguousarray(flat1).tobytes())
            dg2 = crc32c(np.ascontiguousarray(flat2).tobytes())
            v1 = mgr.register_baseline(flat1)
            for m in members:
                m.model.set(v1, flat1)
            v2 = mgr.begin_canary(flat2, fraction=0.34)
            vers = mgr.fleet_versions()
            assert sorted(v for v, _ in vers.values()) == [v1, v1, v2]
            # promotion flips EVERY member to v2
            assert mgr.promote() == v2
            assert set(mgr.fleet_versions().values()) == {(v2, dg2)}
            assert mgr.canary_open() is None
            # the promoted params actually reached the live sinks
            for m in members:
                np.testing.assert_array_equal(m.holder["p"], flat2)
            # one-epoch rollback: v1 restored BIT-identical everywhere,
            # digest-pinned at load time
            assert mgr.rollback() == v1
            assert set(mgr.fleet_versions().values()) == {(v1, dg1)}
            for m in members:
                np.testing.assert_array_equal(m.holder["p"], flat1)
            assert mgr.version_digest(v1) == dg1
        finally:
            for m in members:
                m.stop()


def test_canary_requires_registered_baseline():
    from paddle_tpu.core.enforce import PreconditionNotMetError

    members = [_model_member(f"nb{i}") for i in range(2)]
    with _router() as r:
        for m in members:
            r.attach(m)
        try:
            mgr = RolloutManager(lambda: members, r)
            # no baseline: the rollback target would be unpinned — the
            # canary must refuse up front, not KeyError at rollback
            # time (possibly on the watchdog's auto-rollback thread)
            with pytest.raises(PreconditionNotMetError,
                               match="register_baseline"):
                mgr.begin_canary(np.ones(8, np.float32))
            v1 = mgr.register_baseline(np.zeros(8, np.float32))
            for m in members:
                m.model.set(v1, np.zeros(8, np.float32))
            mgr.begin_canary(np.ones(8, np.float32))
            # assignments are already consistent mid-canary: a fleet
            # tick heals nothing (the set-before-load ordering)
            assert mgr.assert_assignments() == 0
        finally:
            for m in members:
                m.stop()


def test_version_store_never_evicts_live_baseline():
    """keep_versions churn must not evict the CURRENT version: a
    baseline plus keep_versions aborted canary cycles used to pop the
    rollback target and KeyError on the watchdog's auto-rollback."""
    members = [_model_member(f"ev{i}") for i in range(2)]
    with _router() as r:
        for m in members:
            r.attach(m)
        try:
            mgr = RolloutManager(lambda: members, r)
            flat1 = np.zeros(8, np.float32)
            v1 = mgr.register_baseline(flat1)
            dg1 = mgr.version_digest(v1)
            for m in members:
                m.model.set(v1, flat1)
            for cycle in range(mgr.config.keep_versions + 2):
                mgr.begin_canary(np.full(8, cycle + 1.0, np.float32))
                mgr.rollback(reason="aborted")     # was KeyError here
            assert mgr.current == v1
            assert mgr.version_digest(v1) == dg1
            assert set(mgr.fleet_versions().values()) == {(v1, dg1)}
        finally:
            for m in members:
                m.stop()


def test_auto_rollback_on_fired_alert():
    members = [_model_member(f"g{i}") for i in range(2)]
    with _router() as r:
        for m in members:
            r.attach(m)
        try:
            mgr = RolloutManager(lambda: members, r)
            v1 = mgr.register_baseline(np.zeros(8, np.float32))
            for m in members:
                m.model.set(v1, np.zeros(8, np.float32))
            mgr.begin_canary(np.ones(8, np.float32))
            assert mgr.canary_open() is not None
            # a non-guard rule does nothing
            mgr._on_alert(_Alert("checkpoint_staleness"))
            assert mgr.canary_open() is not None
            # a guard rule rolls the canary back
            mgr._on_alert(_Alert("fleet_serving_p99"))
            assert mgr.canary_open() is None
            assert mgr.current == v1
            assert set(v for v, _ in mgr.fleet_versions().values()) == {v1}
            assert mgr.events[-1]["reason"] == \
                "slo_alert:fleet_serving_p99"
        finally:
            for m in members:
                m.stop()


def test_reattached_replica_rejoins_at_correct_version():
    """PR 7 epoch fence: kill the primary, the replica re-attaches on
    the promoted epoch (its dense table re-synced by the new primary's
    snapshot may have rewritten the live tower); the fleet tick's
    assignment heal re-pins the member to the ASSIGNED version,
    digest-checked."""
    with _cluster(replication=2) as cluster:
        cli = cluster.client()
        rng = np.random.default_rng(3)
        keys = np.arange(256, dtype=np.uint64)
        _preload(cli, keys, rng)
        flat1 = np.arange(8, dtype=np.float32)
        flat2 = flat1 + 5.0
        router = _router()
        fleet = ServingFleet(cluster.store, cluster.job_id,
                             _member_factory(cluster, _cfg(),
                                             model_flat=flat1), router,
                             config=FleetConfig(poll_s=0.05))
        try:
            m1, m2 = fleet.add(2, warm=False)
            mgr = RolloutManager(lambda: fleet.members(), router)
            fleet.rollout = mgr
            mgr.register_baseline(flat1)
            mgr.begin_canary(flat2, fraction=0.5)
            v2 = mgr.promote()
            dg2 = mgr.version_digest(v2)
            assert set(mgr.fleet_versions().values()) == {(v2, dg2)}
            # kill the primary mid-fleet; both replicas must survive the
            # promotion and re-attach on the new epoch
            prim = cluster.primary(0)
            epochs0 = {m.endpoint: m.replica.status()["epoch"]
                       for m in (m1, m2)}
            prim.server.arm_fault("kill-shard", cmd=rpc._PUSH_SPARSE,
                                  after=2)
            width = cli._dims(0)[1]
            for _ in range(4):
                cli.push_sparse(0, keys[:32], _push(rng, keys[:32], width))
                time.sleep(0.02)
            cluster.wait_promoted(0, prim.endpoint)
            deadline = time.monotonic() + 15
            for m in (m1, m2):
                while m.replica.status()["epoch"] <= \
                        epochs0[m.endpoint]:
                    assert time.monotonic() < deadline, \
                        "replica never re-attached on the new epoch"
                    time.sleep(0.05)
            # the re-attach rewrote one member's live tower (the dense
            # snapshot carries the FEED's values, not the rollout's)
            m1.model.set(1, flat1)
            assert mgr.fleet_versions()[m1.endpoint][0] != v2
            healed = fleet.tick()["healed"]
            assert healed == 1
            # back at the assigned version, digest-identical, fleet-wide
            assert set(mgr.fleet_versions().values()) == {(v2, dg2)}
            # and the fleet still serves through the promoted feed
            out = router.submit(keys[:8], deadline_ms=5000).result(10)
            assert out.shape == (8, 5)
        finally:
            fleet.stop()
            router.stop()


# ---------------------------------------------------------------------------
# satellite 4: per-replica labels, fleet SLO rules, router /metrics view
# ---------------------------------------------------------------------------

def test_per_replica_labels_and_fleet_slo_rules():
    from paddle_tpu.obs import slo

    m = _StubMember("127.0.0.1:9999")
    try:
        m.frontend.submit(_keys_for_block(0), deadline_ms=5000).result(10)
        snap = obs_registry.REGISTRY.snapshot()
        lat = snap["metrics"]["serving_latency_s"]["series"]
        assert any(s["labels"].get("replica") == "127.0.0.1:9999"
                   and s["labels"].get("recorder") == "frontend_request"
                   for s in lat)
        adm = snap["metrics"]["serving_frontend_events"]["series"]
        assert any(s["labels"].get("replica") == "127.0.0.1:9999"
                   for s in adm)
    finally:
        m.stop()
    rules = {r.name: r for r in slo.default_rules()}
    assert "fleet_serving_p99" in rules and "fleet_hedge_rate" in rules
    assert rules["fleet_serving_p99"].labels == \
        {"recorder": "router_request"}
    assert rules["fleet_hedge_rate"].family == "serving_hedges"


def test_router_process_metrics_carries_fleet_view():
    from paddle_tpu.obs.exporter import ObsExporter, parse_openmetrics

    members = [_StubMember(f"127.0.0.1:{7000 + i}") for i in range(2)]
    with _router() as r:
        for mm in members:
            r.attach(mm)
        exp = ObsExporter(lambda: obs_registry.REGISTRY.snapshot()).start()
        try:
            for b in range(32):
                r.submit(_keys_for_block(b), deadline_ms=5000).result(10)
            with urllib.request.urlopen(exp.url + "/metrics",
                                        timeout=10) as resp:
                text = resp.read().decode()
            fams = parse_openmetrics(text)
            # the fleet view: size gauge, router events, per-replica
            # latency series — one scrape of the ROUTER process
            assert "serving_fleet_size" in fams
            assert "serving_router_events" in fams
            lat = [lbl for n, lbl, v in
                   fams["serving_latency_s"]["samples"]
                   if lbl.get("recorder") == "router_member"]
            assert {lbl["replica"] for lbl in lat} >= \
                {m.endpoint for m in members}
        finally:
            exp.stop()
            for mm in members:
                mm.stop()


# ---------------------------------------------------------------------------
# fleet member protocol sanity over stub handles
# ---------------------------------------------------------------------------

def test_fleet_member_lifecycle_with_stub_handles():
    sm = _StubMember("h1")
    rep = _FakeReplicaHandle("h1")
    member = FleetMember(rep, sm.lookup, sm.frontend)
    assert member.healthy
    assert member.resident_keys().size == 0     # non-cached lookup
    member.stop()
    assert not member.healthy and rep.server.stopped
