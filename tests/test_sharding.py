"""Group-sharded (ZeRO) stages on the virtual 8-device CPU mesh —
parity targets: fleet/meta_parallel/sharding/sharding_stage{2,3}.py and
the static sharding_optimizer stages."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.parallel.sharding import (
    ShardingStage1,
    ShardingStage2,
    ShardingStage3,
    group_sharded_parallel,
)


def _mesh():
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, ("dp", "sharding"))


class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.l1 = nn.Linear(16, 64)
        self.l2 = nn.Linear(64, 4)

    def forward(self, x):
        return self.l2(nn.functional.relu(self.l1(x)))


def _data(rng, n=64):
    y = rng.integers(0, 4, n)
    x = rng.normal(0, 0.2, (n, 16)).astype(np.float32)
    x[np.arange(n), y] += 2.0
    return x, y


@pytest.mark.parametrize("stage_cls", [ShardingStage1, ShardingStage2, ShardingStage3])
def test_stage_trains(stage_cls):
    pt.seed(0)
    model = _MLP()
    wrapper = stage_cls(model, optimizer.Adam(5e-3))
    tr = wrapper.trainer(nn.functional.cross_entropy, _mesh())
    rng = np.random.default_rng(0)
    first = last = None
    for _ in range(30):
        x, y = _data(rng)
        loss = float(tr.train_step(x, y))
        first = first if first is not None else loss
        last = loss
    assert last < first * 0.5, (first, last)


def test_stage3_params_actually_sharded():
    pt.seed(0)
    model = _MLP()
    tr = ShardingStage3(model, optimizer.Adam(1e-3)).trainer(
        nn.functional.cross_entropy, _mesh())
    # l1 weight [16, 64]: largest dim 64 divisible by sharding=4
    w = tr.state["params"]["l1.weight"]
    assert "sharding" in str(w.sharding.spec), w.sharding
    # stage-1/2 params stay replicated
    pt.seed(0)
    tr1 = ShardingStage1(_MLP(), optimizer.Adam(1e-3)).trainer(
        nn.functional.cross_entropy, _mesh())
    w1 = tr1.state["params"]["l1.weight"]
    assert w1.sharding.spec == jax.sharding.PartitionSpec()


def test_opt_state_sharded_from_stage1():
    pt.seed(0)
    tr = ShardingStage1(_MLP(), optimizer.Adam(1e-3)).trainer(
        nn.functional.cross_entropy, _mesh())
    leaves = [x for x in jax.tree_util.tree_leaves(tr.opt_state)
              if hasattr(x, "sharding") and getattr(x, "ndim", 0) > 0
              and x.shape and max(x.shape) % 4 == 0 and max(x.shape) >= 4]
    assert leaves and any("sharding" in str(x.sharding.spec) for x in leaves)


def test_group_sharded_parallel_levels():
    m = _MLP()
    opt = optimizer.Adam(1e-3)
    assert group_sharded_parallel(m, opt, "os").stage == 1
    assert group_sharded_parallel(m, opt, "os_g").stage == 2
    assert group_sharded_parallel(m, opt, "p_g_os").stage == 3
    with pytest.raises(Exception):
        group_sharded_parallel(m, opt, "bogus")


def test_stages_match_single_device_trajectory():
    """Sharded training must be numerically equivalent to unsharded
    (the reference's dist/single parity checks in test_dist_base)."""
    rng = np.random.default_rng(3)
    batches = [_data(rng) for _ in range(5)]

    def run(stage):
        pt.seed(7)
        model = _MLP()
        tr = (ShardingStage2(model, optimizer.Adam(1e-3)).trainer(
            nn.functional.cross_entropy, _mesh()) if stage else None)
        if tr is None:
            from paddle_tpu.executor import Trainer
            t = Trainer(model, optimizer.Adam(1e-3), nn.functional.cross_entropy)
            return [float(t.train_step(x, y)) for x, y in batches]
        return [float(tr.train_step(x, y)) for x, y in batches]

    np.testing.assert_allclose(run(True), run(False), rtol=2e-4, atol=2e-5)


def test_stage2_hlo_contains_reduce_scatter():
    """ZeRO-2's defining comm pattern (sharding_stage2.py:43): grads are
    reduce-scattered (not all-reduced full-size) and updated params
    all-gathered. Stage 1 (GSPMD) shows the all-reduce pattern instead."""
    pt.seed(0)
    mesh = _mesh()
    x = jnp.zeros((16, 16)); y = jnp.zeros((16,), jnp.int32)

    def hlo(stage):
        tr = group_sharded_parallel(_MLP(), optimizer.Adam(1e-3), 
                                    {1: "os", 2: "os_g"}[stage]).trainer(
            nn.functional.cross_entropy, mesh)
        return tr._step.lower(tr.state, tr.opt_state, jax.random.key(0),
                              (x,), (y,)).compile().as_text()

    t2 = hlo(2)
    assert t2.count("reduce-scatter") >= 2, "stage-2 grads must reduce-scatter"
    assert t2.count("all-gather") >= 2, "stage-2 params must all-gather"
    t1 = hlo(1)
    # stage 2 must be strictly more reduce-scatter-shaped than stage 1's
    # GSPMD program (don't pin stage 1 to exactly zero — XLA may learn
    # the reassociation on its own someday)
    assert t2.count("reduce-scatter") > t1.count("reduce-scatter")
