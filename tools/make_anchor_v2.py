"""Convergence anchor v2: SSD-backed multi-day stream with an AUC-parity
gate between the two training paths.

VERDICT r2 #5 ("an anchor that means something"): the v1 anchor was a
toy (in-RAM table, 120 steps, 6.8 s). v2 runs the BASELINE.md rung-3/4
workload at capacity scale:

- **population**: 10M+ features cold-loaded into the SSD tier
  (csrc/ssd_table.cc) before any training — day batches promote
  disk→RAM on access, the trillion-feature architecture in miniature;
- **multi-day stream** with feature drift: every day draws mostly from
  a hot Zipf window plus a fresh slice of the cold population;
- **two paths, identical data**: the stream path (the_one_ps role —
  every batch pulls/pushes the host table through the CTR accessor) and
  the pass path (GPUPS role — per-day HBM working set, in-graph lookup
  + fused batch-scaled push) train on byte-identical batch sequences
  from identically-seeded tables (initial_range=0 so insertion order
  cannot skew init);
- **AUC-parity gate**: the two paths' AUC-vs-step curves must agree
  within epsilon at every eval point and tighter at the end — the
  reference's expectation that GPUPS training converges like the CPU
  table path (test_dist_fleet_base.py:311 harness role);
- **plateau check**: the curve must flatten (late improvement below a
  threshold) so the anchor captures converged AUC, not a rising slope.

Importable: ``run_anchor(...)`` returns the result dict (the slow-tier
CI test runs it at reduced scale and asserts the gates); ``__main__``
runs full scale and writes ANCHOR.json (v2 schema).
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _latent(keys: np.ndarray) -> np.ndarray:
    """Deterministic per-feasign latent logit weight (splitmix-style
    hash → uniform → centered), stateless so a 10M-key population needs
    no stored ground-truth table."""
    k = np.asarray(keys, np.uint64)
    h = (k ^ (k >> np.uint64(33))) * np.uint64(0xFF51AFD7ED558CCD)
    h = (h ^ (h >> np.uint64(33))) * np.uint64(0xC4CEB9FE1A85EC53)
    h ^= h >> np.uint64(33)
    u = (h >> np.uint64(11)).astype(np.float64) / float(1 << 53)
    return ((u - 0.5) * 1.4).astype(np.float32)


def run_anchor(pop=10_000_000, days=6, steps_per_day=150, batch=512,
               eval_every=25, base_dir=None, dnn=(400, 400, 400),
               hot=50_000, fresh=5_000, parity_eps=0.02,
               parity_final_eps=0.012, plateau_eps=0.01):
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer
    from paddle_tpu.metrics.auc import AUC
    from paddle_tpu.models.ctr import (CtrConfig, DeepFM,
                                       make_ctr_train_step_from_keys)
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig
    from paddle_tpu.ps.table import SsdSparseTable, TableConfig

    cfg = CtrConfig(num_sparse_slots=26, num_dense=13, embedx_dim=8,
                    dnn_hidden=tuple(dnn))
    S, dim = cfg.num_sparse_slots, cfg.embedx_dim
    pop_per_slot = pop // S
    # scale the hot window / daily fresh slice into the population so
    # reduced-scale runs (CI smoke) keep the same day structure
    hot = min(hot, max(2, pop_per_slot // 2))
    fresh = max(1, min(fresh, (pop_per_slot - hot) // max(days, 1)))
    base = base_dir or tempfile.mkdtemp(prefix="anchor_v2_")
    cleanup = base_dir is None
    rng = np.random.default_rng(0)
    dense_w = rng.normal(0, 0.3, size=cfg.num_dense).astype(np.float32)
    slot_hi = np.arange(S, dtype=np.uint64) << np.uint64(32)
    zipf_p = 1.0 / np.arange(1, hot + 1) ** 1.05
    zipf_p /= zipf_p.sum()

    def sample(n, day, day_rng):
        ids = day_rng.choice(hot, size=(n, S), p=zipf_p).astype(np.uint64)
        # fresh window clamped INSIDE the population: at tiny scales the
        # per-day stride can run past it (then later days reuse the tail)
        lo = min(hot + day * fresh, pop_per_slot - 1)
        is_fresh = day_rng.random((n, S)) < 0.15
        fresh_ids = day_rng.integers(
            lo, min(lo + fresh, pop_per_slot), size=(n, S)).astype(np.uint64)
        ids = np.where(is_fresh, fresh_ids, ids) + np.uint64(1)
        keys = ids + slot_hi[None, :]
        dense = day_rng.normal(size=(n, cfg.num_dense)).astype(np.float32)
        logit = _latent(keys).sum(axis=1) + dense @ dense_w
        labels = (day_rng.random(n) <
                  1.0 / (1.0 + np.exp(-(logit - 0.3)))).astype(np.int32)
        return keys, dense, labels

    def make_table(name):
        return SsdSparseTable(
            os.path.join(base, name),
            TableConfig(shard_num=16, accessor_config=AccessorConfig(
                embedx_dim=dim, embedx_threshold=0.0,
                sgd=SGDRuleConfig(initial_range=0.0))))

    # ---- cold population: pop features on disk before any training ----
    t0 = time.perf_counter()
    tables = {"stream": make_table("stream"), "pass": make_table("pass")}
    chunk = 1 << 20
    for s in range(S):
        for lo in range(0, pop_per_slot, chunk):
            n = min(chunk, pop_per_slot - lo)
            keys = (np.arange(lo + 1, lo + 1 + n, dtype=np.uint64)
                    + slot_hi[s])
            vals = np.zeros((n, tables["stream"].full_dim), np.float32)
            vals[:, 3] = 10.0  # seen-before show (survives shrink decay)
            for t in tables.values():
                t.load_cold(keys, vals)
    load_s = time.perf_counter() - t0

    # ---- identical data for both paths --------------------------------
    day_batches = []
    for d in range(days):
        day_rng = np.random.default_rng(2000 + d)
        day_batches.append([sample(batch, d, day_rng)
                            for _ in range(steps_per_day)])
    eval_rng = np.random.default_rng(999)
    ek, ed, el = sample(4096, 0, eval_rng)
    slot_ids32 = np.tile(np.arange(S, dtype=np.int32), batch)

    def build_model():
        pt.seed(0)
        model = DeepFM(cfg)
        opt = optimizer.Adam(learning_rate=1e-3)
        params = {"params": dict(model.named_parameters()), "buffers": {}}
        return model, opt, params, opt.init(params)

    def infer_fn(model):
        @jax.jit
        def infer(params, emb, dense_x):
            out, _ = nn.functional_call(model, params, emb, dense_x,
                                        training=False)
            return jax.nn.sigmoid(out)

        return infer

    def auc_of(probs):
        m = AUC()
        m.update(np.asarray(probs), el)
        return float(m.accumulate())

    results = {}

    # ---- path 1: stream (per-batch host-table pull/push) --------------
    table = tables["stream"]
    model, opt, params, opt_state = build_model()
    infer = infer_fn(model)

    def loss_fn(params, emb, dense_x, labels):
        out, _ = nn.functional_call(model, params, emb, dense_x,
                                    training=True)
        return nn.functional.binary_cross_entropy_with_logits(
            out, labels.astype(jnp.float32)), out

    @jax.jit
    def train_step(params, opt_state, emb, dense_x, labels):
        (loss, _), (grads, emb_grad) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(params, emb, dense_x,
                                                   labels)
        new_params, new_opt = opt.update(grads, opt_state, params)
        return new_params, new_opt, loss, emb_grad

    def pull_emb(t, flat, create):
        pulled = t.pull_sparse(flat, slots=slot_ids32[:len(flat)],
                               create=create)
        return pulled[:, 2:].reshape(-1, S, 1 + dim)

    curve = []
    elapsed = 0.0
    gstep = 0
    for d in range(days):
        for keys, dense, labels in day_batches[d]:
            flat = keys.reshape(-1)
            ts = time.perf_counter()
            emb = pull_emb(table, flat, True)
            params, opt_state, loss, emb_grad = train_step(
                params, opt_state, jnp.asarray(emb), jnp.asarray(dense),
                jnp.asarray(labels))
            g = np.asarray(emb_grad).reshape(-1, 1 + dim)
            push = np.empty((len(flat), 4 + dim), np.float32)
            push[:, 0] = slot_ids32
            push[:, 1] = 1.0
            push[:, 2] = np.repeat(labels, S)
            push[:, 3:] = g
            table.push_sparse(flat, push)
            elapsed += time.perf_counter() - ts
            gstep += 1
            if gstep % eval_every == 0 or gstep == 1:
                probs = infer(params, jnp.asarray(
                    pull_emb(table, ek.reshape(-1), False)), jnp.asarray(ed))
                curve.append([gstep, round(elapsed, 2),
                              round(auc_of(probs), 4)])
    results["stream"] = {
        "auc_curve": curve,
        "samples_per_sec": round(batch * gstep / elapsed, 1),
        "final_auc": curve[-1][2],
        "table_features": tables["stream"].size(),
    }

    # ---- path 2: pass (per-day HBM working set, in-graph push) --------
    table = tables["pass"]
    model, opt, params, opt_state = build_model()
    infer = infer_fn(model)
    cache_cfg = CacheConfig(capacity=1 << 21, embedx_dim=dim,
                            embedx_threshold=0.0,
                            sgd=SGDRuleConfig(initial_range=0.0))
    cache = HbmEmbeddingCache(table, cache_cfg, device_map=True)
    step = make_ctr_train_step_from_keys(model, opt, cache_cfg,
                                         slot_ids=np.arange(S))
    curve = []
    elapsed = 0.0
    gstep = 0
    for d in range(days):
        day_keys = np.concatenate(
            [b[0].reshape(-1) for b in day_batches[d]] + [ek.reshape(-1)])
        ts = time.perf_counter()
        cache.begin_pass(day_keys)
        ms = cache.device_map.state
        elapsed += time.perf_counter() - ts
        for keys, dense, labels in day_batches[d]:
            lo32 = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            ts = time.perf_counter()
            params, opt_state, cache.state, loss = step(
                params, opt_state, cache.state, ms, jnp.asarray(lo32),
                jnp.asarray(dense), jnp.asarray(labels))
            elapsed += time.perf_counter() - ts
            gstep += 1
            if gstep % eval_every == 0 or gstep == 1:
                rows = cache.lookup(ek.reshape(-1))
                from paddle_tpu.ps.embedding_cache import cache_pull

                emb = np.asarray(cache_pull(
                    cache.state, jnp.asarray(rows))).reshape(-1, S, 1 + dim)
                probs = infer(params, jnp.asarray(emb), jnp.asarray(ed))
                curve.append([gstep, round(elapsed, 2),
                              round(auc_of(probs), 4)])
        ts = time.perf_counter()
        cache.end_pass()
        elapsed += time.perf_counter() - ts
    results["pass"] = {
        "auc_curve": curve,
        "samples_per_sec": round(batch * gstep / elapsed, 1),
        "final_auc": curve[-1][2],
        "table_features": tables["pass"].size(),
    }

    # ---- gates ---------------------------------------------------------
    sa = results["stream"]["auc_curve"]
    pa = results["pass"]["auc_curve"]
    assert len(sa) == len(pa)
    # ignore the pre-learning head, but never empty the comparison set
    warm = min(max(1, len(sa) // 5), len(sa) - 1)
    gaps = [abs(a[2] - b[2]) for a, b in zip(sa[warm:], pa[warm:])]
    final_gap = abs(results["stream"]["final_auc"]
                    - results["pass"]["final_auc"])
    # plateau: AUC gained over the LAST QUARTER of the curve
    tail = [p[2] for p in sa[-3:]]
    plateau_gain = max(tail) - sa[3 * len(sa) // 4][2]
    gates = {
        "parity_max_gap": round(max(gaps), 4),
        "parity_final_gap": round(final_gap, 4),
        "plateau_late_gain": round(plateau_gain, 4),
        "parity_ok": bool(max(gaps) <= parity_eps
                          and final_gap <= parity_final_eps),
        "plateau_ok": bool(plateau_gain <= plateau_eps
                           and results["stream"]["final_auc"] > 0.6),
    }

    out = {
        "version": 2,
        "task": "deepfm_criteo_synthetic_ssd_multiday",
        "population": pop,
        "days": days,
        "steps_per_day": steps_per_day,
        "batch": batch,
        "ssd_cold_load_sec": round(load_s, 1),
        "paths": results,
        "gates": gates,
        "config": {"slots": S, "dense": cfg.num_dense, "embedx_dim": dim,
                   "dnn": list(dnn), "hot_window": hot,
                   "fresh_per_day": fresh,
                   "optimizer": "Adam 1e-3 dense + CTR AdaGrad sparse"},
    }
    for t in tables.values():
        t.close()
    if cleanup:
        shutil.rmtree(base, ignore_errors=True)
    return out


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    out = run_anchor(
        pop=int(os.environ.get("ANCHOR_POP", 10_000_000)),
        days=int(os.environ.get("ANCHOR_DAYS", 6)),
        steps_per_day=int(os.environ.get("ANCHOR_STEPS_PER_DAY", 150)),
        batch=int(os.environ.get("ANCHOR_BATCH", 512)),
        eval_every=int(os.environ.get("ANCHOR_EVAL_EVERY", 25)),
    )
    path = os.environ.get("ANCHOR_OUT") or os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "ANCHOR.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"final_auc_stream": out["paths"]["stream"]["final_auc"],
                      "final_auc_pass": out["paths"]["pass"]["final_auc"],
                      "gates": out["gates"]}))


if __name__ == "__main__":
    main()
