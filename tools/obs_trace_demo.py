"""Cross-process trace demo: ONE merged chrome trace for a sampled
CtrStreamTrainer step over a real 2-shard NativePsServer cluster
(ISSUE 8 acceptance artifact — committed as OBS_TRACE.json).

What the artifact shows (load it in chrome://tracing or perfetto):

- a ``trainer`` lane with the sampled ``ctr_stream_step`` root spans
  and their ``pserver_client_pull_sparse`` / push children (wire bytes
  in args);
- one lane per PS shard with the server-side spans the shards recorded
  against the SAME trace ids (service time, gate wait, request and
  response bytes in args);
- FLOW ARROWS from each trainer-side pull/push span to the exact
  shard-side span that served it — the client span's id rode the RPC
  frame header's fixed trace-context field and the server recorded its
  span under it, so the two halves bind by id with no clock guesswork.

The merge itself goes through tools/timeline.py (clockSyncUs
alignment + pid de-conflict), i.e. this demo also exercises the
multi-worker merge path end to end.

Standalone: prints exactly ONE JSON line (driver contract) and writes
OBS_TRACE.json (env OBS_TRACE_OUT overrides). Env knobs: OTD_BATCHES,
OTD_BATCH, OTD_SLOTS, OTD_NID.
"""

import json
import os
import sys
import tempfile


def run(out_path: str) -> dict:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.obs import aggregate, registry, trace
    from paddle_tpu.ps import rpc
    from paddle_tpu.ps.communicator import SyncCommunicator
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer
    from paddle_tpu.ps.table import TableConfig

    sys.path.insert(0, os.path.join(repo, "tools"))
    import timeline

    from obs_overhead_bench import _make_dataset  # one shared generator

    S = int(os.environ.get("OTD_SLOTS", 8))
    D = 4
    batch = int(os.environ.get("OTD_BATCH", 256))
    n_batches = int(os.environ.get("OTD_BATCHES", 8))
    nid = int(os.environ.get("OTD_NID", 1000))
    ds = _make_dataset(S, D, batch, n_batches, nid=nid)

    registry.set_process_role("trainer")
    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    client = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers])
    try:
        client.create_sparse_table(
            0, TableConfig(table_id=0, shard_num=4, accessor="ctr"))
        comm = SyncCommunicator(client)  # pulls/pushes inline → traced
        comm.start()
        cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=8,
                        dnn_hidden=(64, 64))
        trainer = CtrStreamTrainer(
            DeepFM(cfg), optimizer.Adam(1e-3), None,
            sparse_slots=[f"s{i}" for i in range(S)],
            dense_slots=[f"d{i}" for i in range(D)], label_slot="label",
            communicator=comm, table_id=0, embedx_dim=8)
        # warm one epoch UNSAMPLED (compile + row creation), then the
        # sampled epoch the artifact shows
        trainer.train_from_dataset(ds, batch_size=batch)
        for s in range(client.num_servers):
            aggregate.fetch_server_obs(client, s, drain=True)  # discard
        trace.start_tracing(sample=1.0)
        result = trainer.train_from_dataset(ds, batch_size=batch)
        trace.stop_tracing()
        comm.stop()

        tmp = tempfile.mkdtemp(prefix="obs_trace_")
        trainer_file = os.path.join(tmp, "trainer.json")
        trace.export_chrome_trace(trainer_file, pid=0,
                                  process_name="trainer")
        lanes = [trainer_file]
        shard_spans = 0
        snaps = [registry.snapshot()]
        for s in range(client.num_servers):
            snap, spans = aggregate.fetch_server_obs(client, s, drain=True)
            snaps.append(snap)
            shard_spans += len(spans)
            evs = aggregate.server_spans_to_chrome(
                spans, pid=0, process_name=f"ps_shard_{s}")
            lane = os.path.join(tmp, f"ps_shard_{s}.json")
            with open(lane, "w") as f:
                # server span ts are wall-epoch µs already → anchor 0
                json.dump({"traceEvents": evs, "clockSyncUs": 0.0}, f)
            lanes.append(lane)
        n_events = timeline.merge_traces(lanes, out_path)

        # -- acceptance self-check on the committed artifact -------------
        with open(out_path) as f:
            merged = json.load(f)["traceEvents"]
        flows_s = {e["id"] for e in merged if e.get("ph") == "s"}
        flows_f = {e["id"] for e in merged if e.get("ph") == "f"}
        linked = flows_s & flows_f
        client_pulls = [e for e in merged
                        if e.get("name") == "pserver_client_pull_sparse"]
        server_pulls = [e for e in merged
                        if e.get("name") == "ps_server_pull_sparse"]
        assert linked, "no client span flow-linked to a server span"
        assert client_pulls and server_pulls, "missing pull spans"
        assert all("tx_bytes" in e["args"] for e in client_pulls)
        assert all(e["args"]["req_bytes"] > 0 for e in server_pulls)
        job = aggregate.merge_snapshots(snaps)
        wire = job["metrics"]["ps_server_wire_bytes"]["series"]
        return {
            "metric": "obs_trace_demo",
            "out": out_path,
            "events": n_events,
            "steps": int(result["steps"]),
            "client_pull_spans": len(client_pulls),
            "server_pull_spans": len(server_pulls),
            "flow_links": len(linked),
            "shard_spans": shard_spans,
            "job_processes": len(job["processes"]),
            "server_wire_bytes": {f"{r['labels']['table']}/"
                                  f"{r['labels']['dir']}": r["value"]
                                  for r in wire},
        }
    finally:
        client.stop_servers()
        client.close()
        for s in servers:
            s.close()


def main() -> int:
    out = os.environ.get("OBS_TRACE_OUT", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "OBS_TRACE.json"))
    try:
        rec = run(out)
    except Exception as e:  # one-JSON-line driver contract
        rec = {"metric": "obs_trace_demo", "error": f"{type(e).__name__}: {e}"}
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
