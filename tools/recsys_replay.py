"""Million-user recsys replay (ISSUE 18 acceptance → RECSYS_E2E.json).

Drives the FULL retrieval→ranking serve path end to end, the way the
paper's serving story actually runs: a training HA cluster keeps
learning (CtrStreamTrainer over the half-async communicator) while a
**multi-host** serving fleet — every member its own OS process
(serving.member_host), reachable only by endpoint — answers an
open-loop replay through one :class:`PipelineFrontend`:

- **retrieval**: per request, ``fanout`` candidate sub-requests routed
  over the fleet (bounded-load CH affinity, p95-budget hedging, failure
  reroute — every recovery inheriting the MEASURED remaining budget),
  finalized at the early top-K cut;
- **ranking**: top-K + history keys from MANY concurrent requests
  coalesced into ONE pow2-padded CachedLookup gather and ONE stacked
  jitted GRU4Rec infer (models.make_gru4rec_ranker), scattered back.

Traffic is an **open-loop** replay (arrivals scheduled on the wall
clock whether or not earlier requests finished) over a Zipf-skewed
user/item population (``RRB_USERS`` users, default one million — user
ids drawn Zipf so a head of hyperactive sessions dominates, candidate
items drawn Zipf so the hot tier sees a real popularity skew), shaped
as three phases:

1. **diurnal ramp** — rate climbs a half-sine from ``RRB_BASE_QPS`` to
   ``RRB_PEAK_QPS``; mid-ramp one member is SIGKILLed (chaos). Gate:
   ZERO user-visible errors — the early cut + reroute carry the loss.
2. **flash crowd** — ``RRB_SPIKE_X`` × peak for ``RRB_SPIKE_S`` s. The
   ``recsys_e2e_p99`` burn-rate rule (obs/slo.py recsys_rules) fires
   and the PR 11 Autoscaler GROWS the fleet — spawning new member
   *processes* mid-storm; the journal records the decision.
3. **recovery tail** — back to peak with the grown fleet, then a
   canary→promote→rollback chunk (RolloutManager pushing dense
   versions OVER THE WIRE to every member process).

Throughout, a freshness prober measures push→servable fleet-wide
(marker stat pushed on the TRAINING client, polled through each
member's serve path) WHILE the trainer streams — the
``freshness_under_training`` SLO's p95.

Standalone: prints exactly ONE JSON line (driver contract). Knobs:
RRB_USERS (1e6), RRB_KEYS (20000), RRB_MEMBERS (2), RRB_DIM (8),
RRB_HIST (6), RRB_FANOUT (2), RRB_FAN_WIDTH (8), RRB_TOPK (8),
RRB_BASE_QPS (15), RRB_PEAK_QPS (60), RRB_SPIKE_X (3), RRB_RAMP_S
(10), RRB_SPIKE_S (6), RRB_TAIL_S (6), RRB_DEADLINE_MS (4000),
RRB_SLO_MS (120 — the autoscale trigger, deliberately far inside the
request deadline: the rule pages on tail degradation long before users
see errors), RRB_DELAY_US (4000 coalesce window), RRB_TRAIN_BATCH
(128), RRB_CANARY (400), RRB_SCALE_WAIT_S (45). Shared-host note: the
1-core CI box moves p99 2-3× under ambient load; the ci.sh gate
asserts the invariants (zero errors, grow journaled, coalesce > 1,
freshness bounded) and retries once — the committed RECSYS_E2E.json is
a quiet-host run.
"""

import json
import os
import queue
import sys
import threading
import time

METRIC = "recsys_e2e_qps"


def _log(msg: str) -> None:
    """Progress to stderr (stdout carries exactly ONE JSON line)."""
    if os.environ.get("RRB_VERBOSE", "1") == "1":
        print(f"[recsys_replay] {msg}", file=sys.stderr, flush=True)


def run() -> dict:
    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import random as _random
    import shutil
    import tempfile

    import jax

    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.data.dataset import QueueDataset, SlotDesc
    from paddle_tpu.distributed import elastic
    from paddle_tpu.io.fs import crc32c
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.models.gru4rec import GRU4Rec, make_gru4rec_ranker
    from paddle_tpu.obs import slo, timeseries
    from paddle_tpu.ps import (AccessorConfig, SGDRuleConfig, TableConfig,
                               ha)
    from paddle_tpu.ps.autoscale import AutoscaleConfig, Autoscaler
    from paddle_tpu.ps.communicator import HalfAsyncCommunicator
    from paddle_tpu.ps.hot_tier import HotEmbeddingTier, HotTierConfig
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer
    from paddle_tpu.serving import (CachedLookup, FleetConfig,
                                    FreshnessProbe, PipelineConfig,
                                    PipelineFrontend, RolloutManager,
                                    RouterConfig, ServingFleet,
                                    ServingReplica, ServingRouter,
                                    spawn_member)

    n_users = int(float(os.environ.get("RRB_USERS", 1_000_000)))
    n_keys = int(float(os.environ.get("RRB_KEYS", 20_000)))
    n_members = int(os.environ.get("RRB_MEMBERS", 2))
    xd = int(os.environ.get("RRB_DIM", 8))
    H = int(os.environ.get("RRB_HIST", 6))
    fanout = int(os.environ.get("RRB_FANOUT", 2))
    fan_width = int(os.environ.get("RRB_FAN_WIDTH", 8))
    topk = int(os.environ.get("RRB_TOPK", 8))
    base_qps = float(os.environ.get("RRB_BASE_QPS", 15))
    peak_qps = float(os.environ.get("RRB_PEAK_QPS", 60))
    spike_x = float(os.environ.get("RRB_SPIKE_X", 3.0))
    ramp_s = float(os.environ.get("RRB_RAMP_S", 10))
    spike_s = float(os.environ.get("RRB_SPIKE_S", 6))
    tail_s = float(os.environ.get("RRB_TAIL_S", 6))
    deadline_ms = float(os.environ.get("RRB_DEADLINE_MS", 4000))
    slo_ms = float(os.environ.get("RRB_SLO_MS", 120))
    delay_us = int(os.environ.get("RRB_DELAY_US", 4000))
    train_batch = int(os.environ.get("RRB_TRAIN_BATCH", 128))
    n_canary = int(float(os.environ.get("RRB_CANARY", 400)))
    scale_wait_s = float(os.environ.get("RRB_SCALE_WAIT_S", 45))
    dense_len = 64

    S, D = 8, 4                       # trainer slots (the CTR family)
    cfg = TableConfig(shard_num=4, accessor_config=AccessorConfig(
        embedx_dim=xd, embedx_threshold=0.0,
        sgd=SGDRuleConfig(initial_range=0.01)))
    cap = 1 << int(np.ceil(np.log2(max(n_keys * 1.8, 1 << 12))))
    base = tempfile.mkdtemp(prefix="recsys_replay_")
    store_dir = os.path.join(base, "store")
    os.makedirs(store_dir, exist_ok=True)
    rng = np.random.default_rng(0)

    with ha.HACluster(num_shards=1, replication=1,
                      store=elastic.FileStore(store_dir),
                      sync=False) as cluster:
        train_cli = cluster.client()
        train_cli.create_sparse_table(0, cfg)
        keys = np.arange(n_keys, dtype=np.uint64)
        width = None
        t0 = time.perf_counter()
        for lo in range(0, n_keys, 1 << 15):
            kc = keys[lo:lo + (1 << 15)]
            train_cli.pull_sparse(0, kc)
            if width is None:
                width = train_cli._dims(0)[1]
            push = np.zeros((len(kc), width), np.float32)
            push[:, 1] = 1.0
            push[:, 3:] = 0.01 * rng.standard_normal(
                (len(kc), width - 3)).astype(np.float32)
            train_cli.push_sparse(0, kc, push)
        preload_s = time.perf_counter() - t0
        _log(f"preloaded {n_keys} keys in {preload_s:.1f}s")

        # -- parent-side ranking stack: own read replica + hot tier ----
        rep = ServingReplica(cluster.store, cluster.job_id, shard=0,
                             hb_interval=0.05, hb_ttl=10.0)
        serve = rep.client()
        view = rep.serve_view(0, cfg, client=serve)
        prim = cluster.primary(0)
        deadline = time.perf_counter() + 60
        delay = 0.005
        while cluster.digests(0, 0).get(prim.endpoint) != \
                serve.digest(0)[0]:
            if time.perf_counter() > deadline:
                raise TimeoutError("rank replica never caught up")
            time.sleep(delay)
            delay = min(delay * 2, 0.1)
        tier = HotEmbeddingTier(view, HotTierConfig(capacity=cap,
                                                    create_on_miss=False))
        lookup = CachedLookup(tier, replica=rep, freshness_budget_s=30.0)

        pt.seed(0)
        gru = GRU4Rec(embedx_dim=xd, hidden=16, out_dim=16)
        ranker = make_gru4rec_ranker(gru)
        rank_max_batch = 32
        sw = lookup.lookup(keys[:1]).shape[1]   # serve row: show ++ embedx
        # compile-prime every pow2 bucket (ranker AND the fused gather):
        # replay traffic must never compile
        Bp = 1
        while Bp <= rank_max_batch:
            ranker(np.zeros((Bp, H, sw), np.float32),
                   np.full(Bp, H, np.int32),
                   np.zeros((Bp, topk, sw), np.float32))
            lookup.lookup(keys[:min(Bp * (H + topk), n_keys)])
            Bp <<= 1

        # -- fleet of member PROCESSES + router + rollout ---------------
        def make_member():
            return spawn_member(f"file:{store_dir}", cluster.job_id,
                                embedx_dim=xd, shard_num=4, capacity=cap,
                                dense_len=dense_len, max_batch=64,
                                max_delay_us=1000,
                                # the staleness budget IS the servable-
                                # freshness knob this bench measures:
                                # cached rows revalidate against the
                                # child's oplog-fed replica table within
                                # this bound, so probe p95 ≈ budget +
                                # replication lag (the default 30 s
                                # budget would defeat a 5 s probe; much
                                # below ~2 s the hot-row revalidation
                                # churn eats the flash-crowd headroom on
                                # a small host)
                                freshness_budget_s=2.0,
                                default_deadline_ms=deadline_ms,
                                prime_pow2_max=fan_width,
                                # file-store leases on an oversubscribed
                                # host: a parent-side jit compile can
                                # starve a child's heartbeat thread for
                                # seconds, and an expired lease gets the
                                # member SIGKILLed by the watcher — keep
                                # the TTL far above any compile pause
                                # (chaos detection rides proc.poll(),
                                # not the lease, so kills still register
                                # immediately)
                                hb_ttl=10.0)

        # hedge floor above the members' coalesce window (the fleet
        # bench's measured rule: hedging below it duplicates healthy
        # requests); hedges/reroutes inherit remaining budget (ISSUE 18)
        router = ServingRouter(RouterConfig(block_shift=6,
                                            hedge_default_ms=25.0,
                                            hedge_floor_ms=10.0),
                               rng=_random.Random(0))
        fleet = ServingFleet(cluster.store, cluster.job_id, make_member,
                             router,
                             config=FleetConfig(poll_s=0.25,
                                                warm_handoff=False,
                                                min_replicas=1,
                                                max_replicas=6)).start()
        rollout = RolloutManager(lambda: fleet.members(), router)
        fleet.rollout = rollout
        rngp = np.random.default_rng(7)
        flat_v1 = 0.1 * rngp.standard_normal(dense_len).astype(np.float32)
        flat_v2 = flat_v1 + np.float32(0.01)
        rollout.register_baseline(flat_v1)
        _log(f"spawning {n_members} member processes")
        fleet.add(n_members)
        _log(f"fleet up: {[m.endpoint for m in fleet.members()]}")

        pipe = PipelineFrontend(
            router, lookup, ranker=ranker,
            config=PipelineConfig(default_deadline_ms=deadline_ms,
                                  retrieval_frac=0.5, fanout=fanout,
                                  fan_width=fan_width,
                                  early_cut_frac=0.5, topk=topk,
                                  rank_max_batch=rank_max_batch,
                                  rank_max_delay_us=delay_us,
                                  queue_cap=8192),
            idle_pop_s=0.005, name="recsys")

        # control plane (ring → watchdog → autoscaler) starts AFTER the
        # warm pass — warm-phase compile stragglers would otherwise sit
        # in the SLO windows and fire a phantom scale-up at t=0
        ring = sampler = wd = scaler = None

        def _start_control_plane():
            nonlocal ring, sampler, wd, scaler
            ring = timeseries.MetricRing(capacity=8192)
            sampler = timeseries.Sampler(period_s=0.25, ring=ring).start()
            wd = slo.SloWatchdog(ring)
            for rule in slo.recsys_rules(e2e_p99_s=slo_ms / 1e3,
                                         freshness_training_p95_s=5.0,
                                         long_s=6.0, short_s=2.0):
                wd.add_rule(rule)
            wd.attach(sampler)
            scaler = Autoscaler(
                fleet.controller(), watchdog=wd, ring=ring,
                config=AutoscaleConfig(
                    min_shards=1, max_shards=6, factor=2,
                    up_rules=("recsys_e2e_p99",),
                    # down-scale suppressed for the bench window: the
                    # run measures GROW under a flash crowd, not decay
                    cooldown_up_s=30.0, cooldown_down_s=3600.0,
                    clear_hold_s=3600.0),
                poll_s=0.25).start()

        # -- streaming trainer (the freshness-under-training load) ------
        slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1)
                  for i in range(S)]
                 + [SlotDesc(f"d{i}", is_float=True, max_len=1)
                    for i in range(D)]
                 + [SlotDesc("label", is_float=True, max_len=1)])
        comm_cli = cluster.client()
        comm_cli.create_sparse_table(0, cfg)
        comm = HalfAsyncCommunicator(comm_cli)
        comm.start()
        trainer = CtrStreamTrainer(
            DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D,
                             embedx_dim=xd, dnn_hidden=(32, 32))),
            optimizer.Adam(1e-3), None, embedx_dim=xd,
            sparse_slots=[f"s{i}" for i in range(S)],
            dense_slots=[f"d{i}" for i in range(D)],
            label_slot="label", communicator=comm, table_id=0)
        hot_ids = rng.choice(n_keys, 2000, replace=False)
        trng = np.random.default_rng(11)

        def _stream_lines():
            lines = []
            for _ in range(train_batch):
                ids = trng.choice(hot_ids, S)
                dense = trng.normal(size=D)
                label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
                parts = [f"1 {v}" for v in ids]
                parts += [f"1 {v:.4f}" for v in dense]
                parts.append(f"1 {label}")
                lines.append(" ".join(parts))
            return lines

        stop_train = threading.Event()
        train_rounds = [0]

        def _train_round():
            path = os.path.join(base, f"stream_{train_rounds[0] % 2}.txt")
            with open(path, "w") as f:
                f.write("\n".join(_stream_lines()))
            ds = QueueDataset(slots)
            ds.set_filelist([path])
            trainer.train_from_dataset(ds, batch_size=train_batch,
                                       drop_last=False)
            train_rounds[0] += 1

        def _train_loop():
            while not stop_train.is_set():
                _train_round()
                # cadence gap: lets the oplog drain so joining members'
                # digest catch-up can land between rounds
                stop_train.wait(0.25)

        _train_round()                 # compile the step OFF the clock

        # -- fleet-wide freshness prober (runs WHILE training) ----------
        # The serve row is [embed_w, embedx…] — show/click stats are
        # pruned from the servable view, so the single-replica bench's
        # click-marker idiom cannot work through a member frontend.
        # Instead each probe pushes embed_g = -1 with show = 1: the
        # AdaGrad embed rule makes embed_w STRICTLY INCREASE on every
        # write, and the primary's post-push pull (synchronous RPC) is
        # exact ground truth — a member is "fresh" once its served
        # embed_w catches up to that truth (monotonicity makes the
        # predicate exact even with many writes outstanding).
        probe_cli = cluster.client()
        probe_cli.create_sparse_table(0, cfg)
        marker_key = np.asarray([np.uint64(1) << np.uint64(41)], np.uint64)
        probe_cli.pull_sparse(0, marker_key)
        stop_probe = threading.Event()
        truth = [0.0]                  # primary embed_w after last write
        fresh_dts: list = []
        fresh_fail = [0]
        probe_skips = [0]
        probes: dict = {}

        def _write_marker():
            mp = np.zeros((1, width), np.float32)
            mp[0, 1] = 1.0            # show: scales the embed update
            mp[0, 3] = -1.0           # embed_g < 0 ⇒ embed_w goes UP
            probe_cli.push_sparse(0, marker_key, mp)
            # train pull layout: show, click, embed_w, embedx…
            truth[0] = float(probe_cli.pull_sparse(0, marker_key)[0, 2])

        def _probe_loop():
            idx = 0
            while not stop_probe.is_set():
                members = fleet.members()
                if not members:
                    stop_probe.wait(0.2)
                    continue
                m = members[idx % len(members)]
                idx += 1
                pr = probes.get(m.endpoint)
                if pr is None:
                    pr = FreshnessProbe(timeout_s=5.0, poll_s=0.002,
                                        replica=m.endpoint)
                    probes[m.endpoint] = pr
                pk = np.full(fan_width, marker_key[0], np.uint64)

                def _read(m=m, pk=pk):
                    rows = m.frontend.submit(
                        pk, deadline_ms=1500.0).result(3.0)
                    return float(rows[0, 0])   # serve col 0 = embed_w

                try:
                    dt = pr.measure(_write_marker, _read,
                                    lambda v: v >= truth[0] - 1e-7)
                    if dt is None:
                        fresh_fail[0] += 1
                    else:
                        fresh_dts.append(dt)
                except Exception:  # noqa: BLE001 — member died mid-probe
                    probe_skips[0] += 1
                stop_probe.wait(0.3)

        # -- Zipf + diurnal/flash-crowd open-loop generator -------------
        MIX1, MIX2 = np.uint64(2654435761), np.uint64(0x9E3779B9)

        def gen_phase(duration, rate_fn, seed):
            g = np.random.default_rng(seed)
            ts, t = [], 0.0
            while t < duration:
                t += 1.0 / max(rate_fn(t), 1.0)
                ts.append(t)
            n = len(ts)
            users = ((g.zipf(1.2, n) - 1) % n_users).astype(np.uint64)
            cand = ((g.zipf(1.3, (n, fanout * fan_width)) - 1)
                    % n_keys).astype(np.uint64)
            hist = ((users[:, None] * MIX1
                     + np.arange(H, dtype=np.uint64)[None, :] * MIX2)
                    % np.uint64(n_keys)).astype(np.uint64)
            uv = g.standard_normal((n, xd)).astype(np.float32)
            return np.asarray(ts), users, hist, cand, uv

        def replay(phase, collectors=8, mid_hook=None):
            ts, _users, hist, cand, uv = phase
            n = len(ts)
            out_q: "queue.Queue" = queue.Queue(maxsize=n + 1)
            errors = [0]

            def collect():
                while True:
                    pr = out_q.get()
                    if pr is None:
                        return
                    try:
                        pr.result(deadline_ms / 1e3 + 10)
                    except Exception:  # noqa: BLE001 — counted
                        errors[0] += 1

            cts = [threading.Thread(target=collect, daemon=True,
                                    name=f"rrb-collect-{i}")
                   for i in range(collectors)]
            for c in cts:
                c.start()
            shed = late = 0
            start = time.perf_counter()
            for i in range(n):
                target = start + ts[i]
                now = time.perf_counter()
                if target > now:
                    time.sleep(target - now)
                elif now - target > 0.05:
                    late += 1
                if mid_hook is not None and i == n // 2:
                    mid_hook()
                try:
                    out_q.put(pipe.submit(uv[i], hist[i], cand[i]))
                except Exception:  # noqa: BLE001 — shed at admission
                    shed += 1
                    errors[0] += 1
            for _ in cts:
                out_q.put(None)
            for c in cts:
                c.join()
            wall = time.perf_counter() - start
            return {"requests": n, "wall_s": wall, "errors": errors[0],
                    "shed": shed, "late": late}

        def arm(phase, mid_hook=None):
            s0 = pipe.stats()
            h0 = router.counters["hedges"]
            r0 = router.counters["reroutes"]
            pipe.e2e_latency.reset()
            import gc

            gc.collect()
            rep_ = replay(phase, mid_hook=mid_hook)
            s1 = pipe.stats()
            d = {k: int(s1[k] - s0[k])
                 for k in ("served", "errors", "early_cuts",
                           "stragglers_abandoned", "fan_failures",
                           "rank_batches", "coalesced",
                           "rank_deadline_dropped", "deadline_misses",
                           "shed")}
            out = {"requests": rep_["requests"],
                   "achieved_qps": round(
                       (rep_["requests"] - rep_["errors"])
                       / rep_["wall_s"], 1),
                   "wall_s": round(rep_["wall_s"], 2),
                   "e2e_ms": pipe.e2e_latency.percentiles(),
                   "errors": rep_["errors"],
                   "late_arrivals": rep_["late"], **d,
                   "hedges": int(router.counters["hedges"] - h0),
                   "reroutes": int(router.counters["reroutes"] - r0)}
            if d["rank_batches"]:
                out["coalesce_factor"] = round(
                    d["coalesced"] / d["rank_batches"], 3)
            out["within_deadline"] = (
                rep_["errors"] == 0
                and out["e2e_ms"]["p99_ms"] <= deadline_ms)
            return out

        out: dict = {"metric": METRIC, "unit": "qps"}
        train_thread = threading.Thread(target=_train_loop, daemon=True,
                                        name="rrb-trainer")
        probe_thread = threading.Thread(target=_probe_loop, daemon=True,
                                        name="rrb-freshness")
        try:
            # warm pass: child tiers page in the hot head, every code
            # path compiles — then the measured phases start clean
            warm = gen_phase(max(200 / base_qps, 2.0),
                             lambda t: base_qps, seed=1)
            _log("warm pass")
            replay(warm)
            _log(f"warm done; fleet size {fleet.size()}")
            pipe.reset_stats()
            router.latency.reset()
            _start_control_plane()

            train_thread.start()
            probe_thread.start()

            # -- phase 1: diurnal ramp, chaos kill at the midpoint ------
            victim = fleet.members()[-1]
            pre_n = fleet.size()

            def _kill():
                victim.crash()

            _log("phase 1: diurnal ramp (chaos kill mid-ramp)")
            ramp = arm(gen_phase(
                ramp_s,
                lambda t: base_qps + (peak_qps - base_qps)
                * float(np.sin(0.5 * np.pi * min(t / ramp_s, 1.0))),
                seed=2), mid_hook=_kill)
            ramp["killed"] = victim.endpoint
            ramp["members_before"] = pre_n

            # -- phase 2: flash crowd (the autoscale trigger) -----------
            _log(f"ramp: {json.dumps(ramp)}")
            _log("phase 2: flash crowd")
            spike = arm(gen_phase(spike_s,
                                  lambda t: peak_qps * spike_x, seed=3))

            # the grow decision may land while the spike is still
            # draining — wait for the journal (member spawn is a full
            # process bring-up, seconds on this box)
            deadline2 = time.perf_counter() + scale_wait_s
            delay = 0.1
            while not any(e.get("kind") == "scale"
                          and e.get("direction") == "up"
                          for e in scaler.events):
                if time.perf_counter() > deadline2:
                    break
                time.sleep(delay)
                delay = min(delay * 1.5, 1.0)
            scale_events = [e for e in scaler.events
                            if e.get("kind", "").startswith("scale")]

            # -- phase 3: recovery tail on the grown fleet --------------
            _log(f"spike: {json.dumps(spike)}")
            _log(f"scale events: {len(scale_events)}; fleet {fleet.size()}")
            _log("phase 3: recovery tail")
            tail = arm(gen_phase(tail_s, lambda t: peak_qps, seed=4))

            stop_probe.set()
            probe_thread.join(timeout=15)
            stop_train.set()
            train_thread.join(timeout=60)

            dts = sorted(fresh_dts)
            fresh = {
                "probes": len(dts) + fresh_fail[0],
                "failures": fresh_fail[0],
                "skipped_member_death": probe_skips[0],
                "p50_s": round(dts[len(dts) // 2], 4) if dts else None,
                "p95_s": round(dts[min(int(len(dts) * 0.95),
                                       len(dts) - 1)], 4) if dts else None,
                "train_rounds": train_rounds[0],
                "per_member": {ep: p.stats() for ep, p in probes.items()},
            }

            # -- phase 4: canary → promote → rollback over the wire -----
            # a canary needs one band + one stable member; if the flash
            # crowd never tripped the autoscaler (fleet still at 1 after
            # the chaos kill) an operator would add capacity before a
            # rollout — do the same so the rollout phase measures the
            # rollout, not the scaler
            if fleet.size() < 2:
                _log(f"canary: topping fleet up from {fleet.size()} to 2")
                fleet.add(2 - fleet.size())
            dg_v1 = crc32c(np.ascontiguousarray(flat_v1).tobytes())
            v1 = rollout.current
            v2 = rollout.begin_canary(flat_v2, fraction=0.2)
            c0 = dict(router.stats()["version_counts"])
            _log("phase 4: canary rollout")
            rep5 = replay(gen_phase(n_canary / peak_qps,
                                    lambda t: peak_qps, seed=5))
            counts = {k: v - c0.get(k, 0)
                      for k, v in router.stats()["version_counts"].items()}
            rollout.promote()
            promoted = set(rollout.fleet_versions().values())
            rollout.rollback(reason="bench")
            back = rollout.fleet_versions()
            out["canary"] = {
                "errors": rep5["errors"],
                "version_counts": counts,
                "both_versions_served": counts.get(str(v1), 0) > 0
                and counts.get(str(v2), 0) > 0,
                "promoted_all": promoted == {(v2, rollout.version_digest(
                    v2))},
                "rollback_digest_ok": set(back.values()) == {(v1, dg_v1)},
                "members": len(back),
            }

            out["ramp"] = ramp
            out["spike"] = spike
            out["tail"] = tail
            total_req = sum(p["requests"] for p in (ramp, spike, tail))
            total_err = sum(p["errors"] for p in (ramp, spike, tail))
            total_wall = sum(p["wall_s"] for p in (ramp, spike, tail))
            out["value"] = round((total_req - total_err) / total_wall, 1)
            out["errors_total"] = total_err
            out["freshness_under_training"] = fresh
            out["autoscale"] = {
                "journal": scale_events[:8],
                "grew": any(e.get("kind") == "scale"
                            and e.get("direction") == "up"
                            for e in scale_events),
                "members_after": fleet.size(),
            }
            out["members"] = {
                m.endpoint: {"pid": m.replica.status().get("pid"),
                             "multi_host": bool(
                                 m.replica.status().get("multi_host"))}
                for m in fleet.members()}
            out["pipeline"] = {
                k: v for k, v in pipe.stats().items()
                if k not in ("e2e_ms",)}
            out["router"] = {k: v for k, v in router.stats().items()
                             if k not in ("members", "request")}
            out["population"] = {"users": n_users, "items": n_keys}
            out["profile"] = {
                "fanout": fanout, "fan_width": fan_width, "topk": topk,
                "hist_len": H, "deadline_ms": deadline_ms,
                "slo_ms": slo_ms, "coalesce_us": delay_us,
                "base_qps": base_qps, "peak_qps": peak_qps,
                "spike_x": spike_x, "train_batch": train_batch,
                "preload_s": round(preload_s, 2)}
            out["platform"] = jax.devices()[0].platform
            out["host_cores"] = os.cpu_count()
            return out
        finally:
            stop_probe.set()
            stop_train.set()
            if train_thread.is_alive():
                train_thread.join(timeout=60)
            if probe_thread.is_alive():
                probe_thread.join(timeout=15)
            pipe.stop()
            if scaler is not None:
                scaler.stop()
            if sampler is not None:
                sampler.stop()
            comm.stop()
            fleet.stop()
            router.stop()
            rep.close()
            shutil.rmtree(base, ignore_errors=True)


def main() -> None:
    try:
        rec = run()
    except Exception as e:  # noqa: BLE001 — one-JSON-line contract
        import traceback

        traceback.print_exc(file=sys.stderr)
        rec = {"metric": METRIC, "value": 0.0,
               "error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
