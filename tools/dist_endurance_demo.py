"""Distributed endurance: the multi-day lifecycle at multi-server scale.

DIST_SCALE.json proved the 0.67e9-row build/save/restore composition;
this artifact stresses what that run only touched (3 passes): the
SUSTAINED loop — pass → train → flush → spill → (periodic) shrink +
delta save — over a 4-server SSD-sharded population for many rounds,
watching the trajectories that reveal slow leaks:

  - per-pass build/step/flush rates (drift = accumulating cost),
  - per-server RSS (index/arena leaks),
  - cold-tier disk bytes (the shrink sweep REWRITES kept rows into the
    log; without compaction the logs grow unboundedly — sst_shrink's
    maybe_compact is the mechanism under test),
  - table row counts (shrink's decay/delete lifecycle at scale).

Emits one JSON line (committed as DIST_ENDURANCE.json). Knobs:
DE_SERVERS (4), DE_POP (100M), DE_PASSES (30), DE_PASS_KEYS (400k),
DE_SHRINK_EVERY (10), DE_DIR. Single-core host: run ALONE.
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dist_scale_demo import _du, _rss_bytes, spawn_servers  # noqa: E402


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.ps.rpc as rpc
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM, make_ctr_train_step
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
    from paddle_tpu.ps.rpc import RemoteSparseTable
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig
    from paddle_tpu.ps.table import TableConfig

    n_servers = int(os.environ.get("DE_SERVERS", 4))
    pop = int(float(os.environ.get("DE_POP", 100_000_000)))
    n_passes = int(os.environ.get("DE_PASSES", 30))
    pass_keys = int(os.environ.get("DE_PASS_KEYS", 400_000))
    shrink_every = int(os.environ.get("DE_SHRINK_EVERY", 10))
    dim = 4
    base = os.environ.get("DE_DIR") or tempfile.mkdtemp(prefix="dist_end_")
    cleanup = "DE_DIR" not in os.environ
    os.makedirs(base, exist_ok=True)

    pt.seed(0)
    rng = np.random.default_rng(0)
    acc = AccessorConfig(embedx_dim=dim, embedx_threshold=0.0,
                         # survivable lifecycle at this cadence: gentle
                         # decay, delete only long-unseen rows
                         show_click_decay_rate=0.98,
                         delete_threshold=0.05,
                         delete_after_unseen_days=8.0,
                         sgd=SGDRuleConfig(initial_range=0.0))

    out = {"n_servers": n_servers, "population": pop, "passes": n_passes,
           "pass_keys": pass_keys, "shrink_every": shrink_every,
           "host_cores": os.cpu_count()}
    procs, cli = [], None
    try:
        procs, ports = spawn_servers(n_servers)
        cli = rpc.RpcPsClient([f"127.0.0.1:{p}" for p in ports])
        cfg = TableConfig(shard_num=8, accessor_config=acc, storage="ssd",
                          ssd_path=os.path.join(base, "tiers"))
        cli.create_sparse_table(0, cfg)
        full_dim = cli._dims(0)[2]

        t0 = time.perf_counter()
        chunk = 4_000_000
        for lo in range(0, pop, chunk):
            n = min(chunk, pop - lo)
            keys = np.arange(lo + 1, lo + 1 + n, dtype=np.uint64)
            vals = np.zeros((n, full_dim), np.float32)
            vals[:, 0] = keys % 26
            vals[:, 3] = 1.0
            vals[:, 5] = 0.01 * rng.standard_normal(n).astype(np.float32)
            vals[:, 7] = 1.0
            vals[:, 8:8 + dim] = 0.01 * rng.standard_normal(
                (n, dim)).astype(np.float32)
            assert cli.load_cold(0, keys, vals) == n
        out["build"] = {"rows": pop,
                        "seconds": round(time.perf_counter() - t0, 1)}

        remote = RemoteSparseTable(cli, 0, cfg)
        hot_pool = max(pop // 50, pass_keys)
        cap = 1 << int(np.ceil(np.log2(max(pass_keys * 1.25, 1 << 18))))
        cache = HbmEmbeddingCache(remote, CacheConfig(
            capacity=cap, embedx_dim=dim, embedx_threshold=0.0))
        ccfg = CtrConfig(num_sparse_slots=8, num_dense=4, embedx_dim=dim,
                         dnn_hidden=(64, 64))
        model = DeepFM(ccfg)
        opt = optimizer.Adam(1e-3)
        params = {"params": dict(model.named_parameters()), "buffers": {}}
        ostate = opt.init(params)
        step = make_ctr_train_step(model, opt, cache.config)

        rounds = []
        ckpt_dir = os.path.join(base, "delta_ckpts")
        os.makedirs(ckpt_dir, exist_ok=True)
        for pno in range(n_passes):
            hot = rng.integers(1, hot_pool + 1,
                               size=int(pass_keys * 0.9)).astype(np.uint64)
            tail = rng.integers(1, pop + 1,
                                size=pass_keys - len(hot)).astype(np.uint64)
            pk = np.concatenate([hot, tail]).reshape(-1, 8)
            t0 = time.perf_counter()
            n_uniq = cache.begin_pass(pk.reshape(-1))
            build_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            for _ in range(10):
                b = rng.integers(0, pk.shape[0], size=512)
                rows = cache.lookup(pk[b].reshape(-1)).reshape(512, 8)
                dense = rng.standard_normal((512, 4)).astype(np.float32)
                lab = (pk[b, 0] % 2).astype(np.int32)
                params, ostate, cache.state, loss = step(
                    params, ostate, cache.state, rows, dense, lab)
            jax.block_until_ready(loss)
            steps_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            cache.end_pass()
            flush_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            spilled = cli.spill(0, hot_budget=hot_pool)
            spill_s = time.perf_counter() - t0

            rec = {"pass": pno, "uniq": int(n_uniq),
                   "build_s": round(build_s, 2),
                   "steps_s": round(steps_s, 2),
                   "flush_s": round(flush_s, 2),
                   "spill_s": round(spill_s, 2), "spilled": int(spilled),
                   "loss": round(float(loss), 4)}
            if (pno + 1) % shrink_every == 0:
                # the daily boundary: decay + delete sweep over BOTH
                # tiers, then a delta save of the changed keep-set
                t0 = time.perf_counter()
                erased = cli.shrink(0)
                rec["shrink_s"] = round(time.perf_counter() - t0, 1)
                rec["shrink_erased"] = int(erased)
                t0 = time.perf_counter()
                saved = cli.save_local(
                    0, os.path.join(ckpt_dir, f"d{pno}"), mode=1,
                    converter="raw")
                rec["delta_save_s"] = round(time.perf_counter() - t0, 1)
                rec["delta_rows"] = int(saved)
            st = cli.table_stats(0)
            rec["stats"] = st
            rec["server_rss"] = [_rss_bytes(p.pid) for p in procs]
            rec["client_rss"] = _rss_bytes()
            rounds.append(rec)
        out["rounds"] = rounds

        first, last = rounds[0], rounds[-1]
        d0 = first["stats"]["disk_bytes"]
        d1 = last["stats"]["disk_bytes"]
        r0 = sum(first["server_rss"])
        r1 = sum(last["server_rss"])
        out["trajectories"] = {
            "disk_bytes_first_to_last": [d0, d1],
            "disk_growth_frac": round((d1 - d0) / max(d0, 1), 4),
            "server_rss_first_to_last": [r0, r1],
            "rss_growth_frac": round((r1 - r0) / max(r0, 1), 4),
            "build_s_first_to_last": [first["build_s"], last["build_s"]],
            "flush_s_first_to_last": [first["flush_s"], last["flush_s"]],
        }
        # gates: bounded growth — a leak shows up as monotone unbounded
        # RSS or disk (shrink rewrites + compaction must hold disk near
        # the live-row footprint; allow slack for hot-tier promotion and
        # log garbage between compactions)
        out["ok"] = bool(out["trajectories"]["disk_growth_frac"] < 0.5
                         and out["trajectories"]["rss_growth_frac"] < 0.5)
    finally:
        try:
            if cli is not None:
                cli.stop_servers()
                cli.close()
        except Exception:
            pass
        for p in procs:
            if p.poll() is None:
                p.kill()
        if cleanup:
            shutil.rmtree(base, ignore_errors=True)
    print(json.dumps(out))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — artifact must be one JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"ok": False,
                          "error": f"{type(e).__name__}: {e}"[:300]}))
        sys.exit(0)
