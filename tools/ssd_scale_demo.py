"""SSD-tier capacity demonstration: a feature population far larger
than the hot budget, trained pass-by-pass through the full tier stack
(disk cold tier -> RAM hot tier -> HBM pass cache -> flush back ->
spill), with timings. The mechanism behind the reference's
trillion-feature scale claim (README.md:31-34) on one host: population
size is bounded by DISK, the hot tier by a configured budget, the HBM
working set by the pass.

Emits one JSON line (committed as SSD_SCALE.json by the round driver or
by hand). Env knobs: SSD_DEMO_POP (population), SSD_DEMO_HOT (hot
budget), SSD_DEMO_PASSES, SSD_DEMO_PASS_KEYS, SSD_DEMO_DIR.
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _rss_bytes() -> int:
    """Host resident set — the FeasignIndex/cold-index memory profile
    the 100M-row run exists to measure (VERDICT r4 #5)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


def main() -> None:
    import jax

    if os.environ.get("SSD_DEMO_CPU", "1") == "1":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.table import SsdSparseTable, TableConfig

    pop = int(os.environ.get("SSD_DEMO_POP", 20_000_000))
    # hot budget BELOW passes x working-set so the coldest-first spill
    # actually evicts (with 4 x ~199k-unique passes, ~790k promoted rows
    # squeeze into 400k)
    hot_budget = int(os.environ.get("SSD_DEMO_HOT", 400_000))
    n_passes = int(os.environ.get("SSD_DEMO_PASSES", 4))
    pass_keys = int(os.environ.get("SSD_DEMO_PASS_KEYS", 200_000))
    base = os.environ.get("SSD_DEMO_DIR") or tempfile.mkdtemp(prefix="ssd_demo_")
    cleanup = "SSD_DEMO_DIR" not in os.environ

    pt.seed(0)
    rng = np.random.default_rng(0)
    dim = 8
    acc = AccessorConfig(embedx_dim=dim, embedx_threshold=0.0)
    table = SsdSparseTable(os.path.join(base, "tbl"),
                           TableConfig(shard_num=16, accessor_config=acc))
    try:
        _run(table, pop, hot_budget, n_passes, pass_keys, rng, dim)
    finally:
        table.close()
        if cleanup:
            shutil.rmtree(base, ignore_errors=True)


def _run(table, pop, hot_budget, n_passes, pass_keys, rng, dim) -> None:
    import jax
    import numpy as np

    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM, make_ctr_train_step
    from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache

    # cold-load the population in chunks (bulk model load at scale);
    # per-chunk rates expose load-time degradation (index growth)
    chunk = 1_000_000
    rss_start = _rss_bytes()
    t0 = time.perf_counter()
    fd = table.full_dim
    chunk_rates = []
    for lo in range(0, pop, chunk):
        n = min(chunk, pop - lo)
        keys = np.arange(lo + 1, lo + 1 + n, dtype=np.uint64)
        vals = np.zeros((n, fd), np.float32)
        vals[:, 3] = 1.0  # show
        vals[:, 5] = 0.01 * rng.standard_normal(n).astype(np.float32)
        tc = time.perf_counter()
        table.load_cold(keys, vals)
        chunk_rates.append(n / (time.perf_counter() - tc))
    load_s = time.perf_counter() - t0
    st0 = table.stats()
    rss_after_load = _rss_bytes()

    cfg = CtrConfig(num_sparse_slots=8, num_dense=4, embedx_dim=dim,
                    dnn_hidden=(64, 64))
    # HBM pass-cache capacity tracks the pass working set (x1.25 slack,
    # min 2^18) — a fixed 2^18 cap rejected the 400k-key XL passes
    cap = 1 << int(np.ceil(np.log2(max(pass_keys * 1.25, 1 << 18))))
    cache = HbmEmbeddingCache(table, CacheConfig(
        capacity=cap, embedx_dim=dim, embedx_threshold=0.0))
    model = DeepFM(cfg)
    opt = optimizer.Adam(1e-3)
    params = {"params": dict(model.named_parameters()), "buffers": {}}
    ostate = opt.init(params)
    step = make_ctr_train_step(model, opt, cache.config)

    passes = []
    for p in range(n_passes):
        keys = rng.integers(1, pop + 1,
                            size=(pass_keys // 8, 8)).astype(np.uint64)
        t0 = time.perf_counter()
        n_uniq = cache.begin_pass(keys.reshape(-1))
        build_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        steps = 20
        for it in range(steps):
            b = rng.integers(0, keys.shape[0], size=512)
            rows = cache.lookup(keys[b].reshape(-1)).reshape(512, 8)
            dense = rng.standard_normal((512, 4)).astype(np.float32)
            lab = (keys[b, 0] % 2).astype(np.int32)
            params, ostate, cache.state, loss = step(
                params, ostate, cache.state, rows, dense, lab)
        jax.block_until_ready(loss)
        steps_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        cache.end_pass()
        flush_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        spilled = table.spill(hot_budget)
        spill_s = time.perf_counter() - t0
        st = table.stats()
        passes.append({"uniq": int(n_uniq), "build_s": round(build_s, 2),
                       "steps_s": round(steps_s, 2),
                       "flush_s": round(flush_s, 2),
                       "spill_s": round(spill_s, 2), "spilled": int(spilled),
                       "hot_rows": st["hot_rows"]})

    st = table.stats()
    out = {
        "population": pop,
        "hot_budget": hot_budget,
        "disk_bytes_after_load": st0["disk_bytes"],
        "cold_load_s": round(load_s, 2),
        "cold_load_rows_per_s": round(pop / load_s),
        # first vs last chunk: does the cold index degrade with size?
        "load_rate_first_chunk": round(chunk_rates[0]),
        "load_rate_last_chunk": round(chunk_rates[-1]),
        "passes": passes,
        "final": {"hot_rows": st["hot_rows"], "cold_rows": st["cold_rows"],
                  "disk_bytes": st["disk_bytes"]},
        "hot_fraction": round(st["hot_rows"] / max(pop, 1), 6),
        # FeasignIndex / cold-index host memory (VmRSS deltas)
        "rss_start_bytes": rss_start,
        "rss_after_load_bytes": rss_after_load,
        "rss_final_bytes": _rss_bytes(),
        "index_bytes_per_row": round(
            (rss_after_load - rss_start) / max(pop, 1), 2),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — artifact must be one JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}))
        sys.exit(0)
