"""Closed-loop elastic reshard demo: the cluster breathes with traffic
(ISSUE 11 acceptance; the committed RESHARD.json artifact).

A diurnal traffic wave hits a live 2-shard PS job and the control loop
runs END TO END with nobody's hand on the wheel:

1. a 2-shard HACluster (sync replication ×2) + SyncCommunicator DeepFM
   stream trainer, preloaded with a RESHARD_ROWS-row table so the
   migration copies real bulk; an obs Sampler feeds a MetricRing and a
   SloWatchdog (step-time burn-rate rule calibrated from the warm p95,
   the slo_demo discipline);
2. an :class:`~paddle_tpu.ps.autoscale.Autoscaler` subscribes to the
   watchdog (``on_fire``/``on_clear``) and drives a
   :class:`~paddle_tpu.ps.reshard.ReshardController` from its own
   worker thread;
3. the WAVE arrives (a ``delay-ms`` faultpoint on every client pull —
   the injectable stand-in for peak traffic): the step-time SLO fires
   → the autoscaler grows 2 → 4 LIVE (snapshot+tail bootstrap, ms-scale
   cutover gate) while the trainer keeps streaming;
4. the wave passes (faultpoint disarmed): the alert clears, the
   quiet-hold and cooldown pass, and the autoscaler shrinks 4 → 2 —
   the full breath, journaled;
5. the artifact records the step-time p95 and shard-count curves, the
   alert timeline, the scale-event journal (autoscaler decisions +
   controller operations + the trainer-np target published through the
   elastic store), and the cutover economics: gate-hold pause p50/p95
   vs the full-copy bootstrap time — the pause must be a small
   fraction of the copy (the whole point of snapshot+tail+fence over
   stop-the-world).

Standalone: prints exactly ONE JSON line (driver contract) and writes
RESHARD.json (env RESHARD_OUT overrides). Env knobs: RESHARD_ROWS,
RESHARD_SLOTS, RESHARD_BATCH, RESHARD_STEPS, RESHARD_MAX_EPOCHS,
RESHARD_PERIOD.
"""

import json
import os
import sys
import time

METRIC = "reshard_demo"


def _pctile(xs, q):
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


def run(out_path: str) -> dict:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import jax
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.obs import slo, timeseries
    from paddle_tpu.ps import ha, rpc
    from paddle_tpu.ps.autoscale import AutoscaleConfig, Autoscaler
    from paddle_tpu.ps.communicator import SyncCommunicator
    from paddle_tpu.ps.faultpoints import arm_faultpoint, disarm_faultpoints
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer
    from paddle_tpu.ps.reshard import ReshardController
    from paddle_tpu.ps.table import TableConfig
    from paddle_tpu.distributed import elastic as el

    sys.path.insert(0, os.path.join(repo, "tools"))
    from obs_overhead_bench import _make_dataset

    S = int(os.environ.get("RESHARD_SLOTS", 6))
    D = 4
    rows = int(os.environ.get("RESHARD_ROWS", 150000))
    batch = int(os.environ.get("RESHARD_BATCH", 256))
    steps = int(os.environ.get("RESHARD_STEPS", 6))
    max_epochs = int(os.environ.get("RESHARD_MAX_EPOCHS", 40))
    period = float(os.environ.get("RESHARD_PERIOD", 0.1))
    ds = _make_dataset(S, D, batch, steps, nid=2000)

    sampler = scaler = None
    cluster = ha.HACluster(num_shards=2, replication=2, sync=True,
                           job_id="reshard-demo")
    try:
        client = cluster.client()
        client.create_sparse_table(
            0, TableConfig(table_id=0, shard_num=8, accessor="ctr"))
        # preload the bulk the migration must move: the bootstrap copy
        # scales with this, the cutover gate hold must NOT
        bulk = np.arange(1, rows + 1, dtype=np.uint64)
        for lo in range(0, rows, 1 << 15):
            client.pull_sparse(0, bulk[lo:lo + (1 << 15)])
        cluster.drain()
        comm = SyncCommunicator(client)
        comm.start()
        pt.seed(0)
        trainer = CtrStreamTrainer(
            DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=8,
                             dnn_hidden=(32, 32))),
            optimizer.Adam(1e-3), None,
            sparse_slots=[f"s{i}" for i in range(S)],
            dense_slots=[f"d{i}" for i in range(D)], label_slot="label",
            communicator=comm, table_id=0, embedx_dim=8)

        # -- control plane -----------------------------------------------
        ring = timeseries.MetricRing(capacity=4096)
        sampler = timeseries.Sampler(period_s=period, ring=ring).start()
        wd = slo.SloWatchdog(ring)
        wd.attach(sampler)
        ctrl = ReshardController(cluster)
        scaler = Autoscaler(
            ctrl, watchdog=wd, ring=ring,
            config=AutoscaleConfig(
                min_shards=2, max_shards=4, factor=2,
                up_rules=("step_time_p95",),
                cooldown_up_s=3.0, cooldown_down_s=3.0, clear_hold_s=1.5,
                trainer_np=lambda shards: shards,
                elastic_job_id="reshard-demo"),
            poll_s=0.2).start()

        # -- warm + calibrate --------------------------------------------
        warm_ms = []
        for _ in range(3):
            t0 = time.perf_counter()
            r = trainer.train_from_dataset(ds, batch_size=batch)
            comm.barrier()
            warm_ms.append((time.perf_counter() - t0) / r["steps"] * 1e3)
        time.sleep(2.5 * period)
        threshold_s = max(4.0 * min(warm_ms) / 1e3, 0.02)
        wd.add_rule(slo.SloRule(
            "step_time_p95", "trainer_step_time_s",
            threshold=threshold_s, budget=0.2,
            windows=((40 * period, 1.0), (10 * period, 1.0))))

        # -- the wave arrives --------------------------------------------
        delay_ms = max(100, int(threshold_s * 1e3 * 2))
        wave_t0 = time.time()  # graftlint: ignore[time-time] — artifact wall timestamps
        arm_faultpoint("rpc.call", "delay-ms", cmd=rpc._PULL_SPARSE,
                       ms=delay_ms, every=1)
        up_epochs = 0
        try:
            for _ in range(max_epochs):
                trainer.train_from_dataset(ds, batch_size=batch)
                comm.barrier()
                up_epochs += 1
                if any(e["kind"] == "scale" and e["direction"] == "up"
                       for e in scaler.events):
                    break
        finally:
            disarm_faultpoints()   # the wave passes
        scaled_up = [e for e in scaler.events if e["kind"] == "scale"
                     and e["direction"] == "up"]
        assert scaled_up, (
            f"autoscaler never scaled up after {up_epochs} wave epochs "
            f"(alerts: {wd.alerts()}, journal: {list(scaler.events)})")
        assert cluster.num_shards == 4, cluster.num_shards
        alerts = [a for a in wd.alerts() if a["rule"] == "step_time_p95"]
        assert alerts and alerts[0]["t"] >= 0

        # -- recovery: alert clears, cluster exhales ---------------------
        # wall-clock bounded, not epoch bounded: the exhale waits out
        # REAL hysteresis time (quiet-hold + down-cooldown), and calm
        # epochs are tens of ms each
        down_epochs = 0
        calm_deadline = time.perf_counter() + max(
            30.0, 10 * (scaler.config.clear_hold_s
                        + scaler.config.cooldown_down_s))
        while time.perf_counter() < calm_deadline:
            trainer.train_from_dataset(ds, batch_size=batch)
            comm.barrier()
            down_epochs += 1
            if any(e["kind"] == "scale" and e["direction"] == "down"
                   for e in scaler.events):
                break
        scaled_down = [e for e in scaler.events if e["kind"] == "scale"
                       and e["direction"] == "down"]
        assert scaled_down, (
            f"autoscaler never scaled back down after {down_epochs} "
            f"calm epochs (active: {wd.active()}, "
            f"journal: {list(scaler.events)})")
        assert cluster.num_shards == 2
        cleared = "step_time_p95" not in wd.active()
        wave_t1 = time.time()  # graftlint: ignore[time-time] — artifact wall timestamps

        # -- trainer-np lever: the target rode the elastic store ---------
        mgr = el.ElasticManager(cluster.store, "reshard-demo", np=2,
                                host="demo", min_np=1, max_np=16)
        trainer_np_target = mgr.desired_np()

        # -- cutover economics -------------------------------------------
        pauses = list(ctrl.pause_ms)
        boots = list(ctrl.bootstrap_s)
        pause_p95_ms = _pctile(pauses, 0.95)
        copy_min_ms = min(boots) * 1e3 if boots else 0.0
        # THE point of snapshot+tail+fence: the writers-blocked window
        # is a small fraction of the time a stop-the-world copy of the
        # same rows takes (the bootstrap measures exactly that copy)
        assert pause_p95_ms < copy_min_ms / 2, (pauses, boots)

        t_base = ring.records()[0]["t"] if len(ring) else 0.0

        def curve(pairs, scale=1.0, nd=3):
            return [[round(t - t_base, 3), round(v * scale, nd)]
                    for t, v in pairs]

        rec_out = {
            "metric": METRIC,
            "platform": jax.devices()[0].platform,
            "out": out_path,
            "rows": rows,
            "period_s": period,
            "warm_ms_per_step": round(min(warm_ms), 2),
            "threshold_ms": round(threshold_s * 1e3, 2),
            "delay_ms": delay_ms,
            "wave_epochs": up_epochs,
            "calm_epochs": down_epochs,
            "wave_span_s": round(wave_t1 - wave_t0, 2),
            "alert": alerts[0],
            "alert_cleared": cleared,
            "scaled_up": scaled_up[0],
            "scaled_down": scaled_down[0],
            "shards_final": cluster.num_shards,
            "trainer_np_target": trainer_np_target,
            "cutover_pause_ms": {
                "all": [round(p, 2) for p in pauses],
                "p50": round(_pctile(pauses, 0.5), 2),
                "p95": round(pause_p95_ms, 2),
            },
            "bootstrap_copy_s": [round(b, 3) for b in boots],
            "gate_hold_over_copy": round(
                pause_p95_ms / max(copy_min_ms, 1e-9), 4),
            "scale_journal": list(scaler.events),
            "reshard_journal": list(ctrl.events),
            "curves": {
                "step_time_p95_ms": curve(
                    ring.series("trainer_step_time_s", "p95"), 1e3),
                "shard_count": curve(
                    ring.series("ps_shard_count", "value", reduce="last")),
                "slo_alert_active": curve(
                    ring.series("slo_alert_active", "value",
                                labels={"rule": "step_time_p95"},
                                reduce="last")),
            },
        }
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(rec_out, f, indent=1, sort_keys=True)
        comm.stop()
        return rec_out
    finally:
        if scaler is not None:
            scaler.stop()
        if sampler is not None:
            sampler.stop()
        cluster.stop()


def main() -> int:
    out = os.environ.get("RESHARD_OUT", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "RESHARD.json"))
    try:
        rec = run(out)
        rec = {k: v for k, v in rec.items()
               if k not in ("curves", "scale_journal", "reshard_journal")}
    except Exception as e:  # one-JSON-line driver contract
        rec = {"metric": METRIC, "error": f"{type(e).__name__}: {e}"}
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
