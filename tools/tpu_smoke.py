"""Real-TPU smoke: run the compiled hot paths once on hardware and
record timings (VERDICT r1 weak #4: the Pallas flash kernel and the
compiled hybrid/cache paths had only ever executed on the CPU mesh).

Legs:
1. Pallas flash attention fwd+bwd vs the einsum reference (correctness
   on hardware + timing at a realistic shape).
2. One compiled CTR cache step (in-graph cuckoo lookup + pull + DeepFM
   fwd/bwd + batch-scaled AdaGrad push) — the bench inner loop.
3. One compiled transformer train step at realistic hidden size, with
   an MFU estimate from the analytic FLOP count.
4. The fused sparse-rule Pallas kernel (naive/AdaGrad/StdAdaGrad/Adam)
   compiled on hardware vs interpret mode.
5. The pooled multi-valued-slot CTR step (sum-pool + gradient fan-out).

Writes TPU_SMOKE.json (committed per round). Tolerates a stuck chip:
a watchdog emits {"ok": false, ...} instead of hanging the caller.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

# SMOKE_OUT overrides the artifact path (CI's light-mode validation
# must not clobber the canonical real-TPU artifact at the repo root).
# Without an override, the destination is picked AFTER the backend
# resolves: hardware runs land in TPU_SMOKE.json, anything else in
# TPU_SMOKE_CPU.json — the canonical file only ever records silicon
# attempts, failures included (VERDICT r4 weak #2).
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(payload, platform=None) -> None:
    out = os.environ.get("SMOKE_OUT")
    if not out:
        name = ("TPU_SMOKE.json" if platform not in ("cpu",)
                else "TPU_SMOKE_CPU.json")
        out = os.path.join(_ROOT, name)
    with open(out, "w") as f:
        json.dump(payload, f, indent=1)
    try:
        print(json.dumps(payload)[:400])
    except OSError:
        pass  # closed stdout (e.g. piped to head) must not unwind into
        # the top-level handler and clobber the artifact just written


def _timed(fn, *args, iters=20):
    """paddle_tpu.core.profiler.timed — the shared fetch-synced
    measurement (block_until_ready lies on the axon relay; see
    fetch_sync's docstring). Thin seam kept so main() reads the same
    before/after the helper moved into the package."""
    from paddle_tpu.core.profiler import timed

    return timed(fn, *args, iters=iters)


def _run_leg(result, name, body):
    """Run one smoke leg; a failing leg (unmeasurable op, compile error)
    records its error under its own key instead of aborting the run —
    the artifact keeps every completed leg (the module contract:
    tolerate a stuck/slow chip, don't lose evidence)."""
    try:
        result["legs"][name] = body()
    except Exception as e:  # noqa: BLE001 — per-leg evidence capture
        import traceback

        traceback.print_exc(file=sys.stderr)
        result["legs"][name] = {"error": f"{type(e).__name__}: {e}"[:300]}
        result["ok"] = False


def main() -> None:
    import threading

    import jax

    # SMOKE_PLATFORM=cpu: force a backend in-process (env vars alone
    # cannot override the boot-registered axon platform)
    if os.environ.get("SMOKE_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["SMOKE_PLATFORM"])

    got = {}

    def init():
        try:
            got["devs"] = jax.devices()
        except Exception as e:  # noqa: BLE001
            got["err"] = str(e)

    t = threading.Thread(target=init, daemon=True, name="tpu-smoke-init")
    t.start()
    t.join(float(os.environ.get("SMOKE_INIT_TIMEOUT", 180)))
    if "devs" not in got:
        _write({"ok": False, "error": got.get("err", "backend init hung")},
               platform=os.environ.get("SMOKE_PLATFORM"))
        sys.stdout.flush()
        os._exit(0)

    import jax.numpy as jnp

    dev = got["devs"][0]
    # SMOKE_LIGHT=1: tiny shapes / few iters — validates the script
    # end-to-end on a CPU host without burning minutes; the real-TPU
    # artifact runs with the full shapes
    light = os.environ.get("SMOKE_LIGHT") == "1"
    iters = 3 if light else 20
    result = {"ok": True, "platform": dev.platform, "light": light,
              "device": str(dev.device_kind), "legs": {}}
    rng = np.random.default_rng(0)

    # --- leg 1: Pallas flash attention fwd/bwd vs einsum reference ------
    from paddle_tpu.ops.flash_attention import flash_attention

    B, H, L, D = (1, 2, 256, 64) if light else (4, 8, 1024, 128)
    q, k, v = (jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
               for _ in range(3))

    def ref_attn(q, k, v):
        s = jnp.einsum("blhd,bmhd->bhlm", q, k) / np.sqrt(D)
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask, s, -1e30)
        return jnp.einsum("bhlm,bmhd->blhd", jax.nn.softmax(s, axis=-1), v)

    flash_loss = jax.jit(jax.value_and_grad(
        lambda q: jnp.sum(flash_attention(q, k, v, causal=True))))
    ref_loss = jax.jit(jax.value_and_grad(
        lambda q: jnp.sum(ref_attn(q, k, v))))

    def leg_flash():
        t_flash, (lf, gf) = _timed(flash_loss, q, iters=min(iters, 10))
        t_ref, (lr, grf) = _timed(ref_loss, q, iters=min(iters, 10))
        max_err = float(jnp.max(jnp.abs(gf - grf)) /
                        (jnp.max(jnp.abs(grf)) + 1e-9))
        return {
            "shape": [B, L, H, D], "fwd_bwd_ms": round(t_flash * 1e3, 3),
            "einsum_ref_ms": round(t_ref * 1e3, 3),
            "speedup_vs_einsum": round(t_ref / t_flash, 2),
            "grad_rel_err": round(max_err, 6),
            "grads_match": bool(max_err < 2e-2),
        }

    _run_leg(result, "flash_attention", leg_flash)

    # --- leg 2: CTR cache step (bench inner loop) -----------------------
    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM, make_ctr_train_step_from_keys
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
    from paddle_tpu.ps.table import MemorySparseTable, TableConfig

    pt.seed(0)
    batch, pass_keys = (256, 1 << 14) if light else (4096, 1 << 18)
    ccfg = CtrConfig(num_sparse_slots=26, num_dense=13, embedx_dim=8,
                     dnn_hidden=(64,) if light else (400, 400, 400))
    cache_cfg = CacheConfig(capacity=1 << 15 if light else 1 << 19,
                            embedx_dim=8, embedx_threshold=0.0)
    table = MemorySparseTable(TableConfig(
        shard_num=16, accessor_config=AccessorConfig(embedx_dim=8)))
    cache = HbmEmbeddingCache(table, cache_cfg, device_map=True)
    pool = rng.integers(0, pass_keys // 26 + 1, size=(pass_keys, 26)).astype(np.uint64)
    pool += np.arange(26, dtype=np.uint64) << np.uint64(32)
    cache.begin_pass(pool.reshape(-1))
    model = DeepFM(ccfg)
    opt = optimizer.Adam(learning_rate=1e-3)
    params = {"params": dict(model.named_parameters()), "buffers": {}}
    opt_state = opt.init(params)
    step = make_ctr_train_step_from_keys(model, opt, cache_cfg,
                                         slot_ids=np.arange(26), donate=False)
    idx = rng.integers(0, pass_keys, size=batch)
    lo32 = jnp.asarray((pool[idx] & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    dense = jnp.asarray(rng.normal(size=(batch, 13)), jnp.float32)
    labels = jnp.asarray((rng.random(batch) < 0.3).astype(np.int32))
    ms = cache.device_map.state

    def ctr_once(lo32, dense, labels):
        return step(params, opt_state, cache.state, ms, lo32, dense, labels)[3]

    def leg_ctr():
        t_ctr, _ = _timed(jax.jit(ctr_once), lo32, dense, labels, iters=iters)
        return {
            "batch": batch, "step_ms": round(t_ctr * 1e3, 3),
            "device_samples_per_sec": round(batch / t_ctr, 0),
        }

    _run_leg(result, "ctr_cache_step", leg_ctr)

    # --- leg 2b: slab-scan CTR step (BENCH_SLAB path: N packed steps
    # per dispatch; isolates how much of the per-step wall time was
    # dispatch overhead vs device compute) ------------------------------
    from paddle_tpu.models.ctr import (make_ctr_train_step_slab,
                                       make_random_packs)

    slab_n = 8
    # amp mirror of bench.py: factory-level amp (bf16 dense tower on the
    # MXU, state/push math stays f32) — not a call-site auto_cast, which
    # only works if the first trace happens inside the context
    step_sl = make_ctr_train_step_slab(model, opt, cache_cfg,
                                       slot_ids=np.arange(26),
                                       batch_size=batch, num_dense=13,
                                       slab=slab_n, donate=False, amp=True)
    packs_d = jnp.asarray(np.stack(
        make_random_packs(rng, pool, batch, 13, slab_n)))

    def slab_once(packs_d):
        return step_sl(params, opt_state, cache.state, ms, packs_d)[3]

    def leg_slab():
        t_slab, _ = _timed(jax.jit(slab_once), packs_d,
                           iters=max(2, iters // slab_n))
        return {
            "batch": batch, "slab": slab_n, "amp": True,
            "dispatch_ms": round(t_slab * 1e3, 3),
            "per_step_ms": round(t_slab / slab_n * 1e3, 3),
            "device_samples_per_sec": round(batch * slab_n / t_slab, 0),
        }

    _run_leg(result, "ctr_slab_step", leg_slab)

    # --- leg 2c: push formulations head-to-head (the round-3 redesign:
    # dense scatter-add + masked full-table update vs the merge_grad-
    # shaped sort/gather/scatter path, both compiled on hardware) -------
    import dataclasses as _dc

    from paddle_tpu.ps.embedding_cache import cache_push

    rows_c = jnp.asarray(
        rng.integers(0, cache_cfg.capacity, size=batch * 26), jnp.int32)
    grads_c = jnp.asarray(rng.normal(size=(batch * 26, 9)), jnp.float32)
    shows_c = jnp.ones((batch * 26,), jnp.float32)
    clicks_c = jnp.asarray(
        (rng.random(batch * 26) < 0.3).astype(np.float32))
    def leg_push_modes():
        leg2c = {}
        for mode in ("dense", "sparse"):
            mcfg = _dc.replace(cache_cfg, push_mode=mode)
            t_push, _ = _timed(
                jax.jit(lambda st, r, g, s, c, _m=mcfg: cache_push(
                    st, r, g, s, c, _m)),
                cache.state, rows_c, grads_c, shows_c, clicks_c, iters=iters)
            leg2c[mode] = round(t_push * 1e3, 3)
        return {"rows": batch * 26, "capacity": cache_cfg.capacity, **leg2c}

    _run_leg(result, "cache_push_modes_ms", leg_push_modes)

    # --- leg 3: transformer step at realistic hidden + MFU --------------
    from paddle_tpu import nn
    from paddle_tpu.executor import Trainer
    from paddle_tpu.models.ernie import Ernie, ErnieConfig

    pt.seed(0)
    if light:
        ecfg = ErnieConfig(vocab_size=1024, hidden_size=128, num_heads=4,
                           ffn_size=256, num_layers=2, max_seq_len=128)
        B2, L2 = 2, 128
    else:
        ecfg = ErnieConfig(vocab_size=32768, hidden_size=1024, num_heads=16,
                           ffn_size=4096, num_layers=8, max_seq_len=512)
        B2, L2 = 8, 512
    emodel = Ernie(ecfg)

    def lm_loss(out, labels):
        return nn.functional.cross_entropy(
            out.reshape(-1, out.shape[-1]), labels.reshape(-1))

    tr = Trainer(emodel, optimizer.Adam(1e-4), lm_loss, amp=True)
    ids = jnp.asarray(rng.integers(0, ecfg.vocab_size, size=(B2, L2)), jnp.int32)
    lbl = jnp.asarray(rng.integers(0, ecfg.vocab_size, size=(B2, L2)), jnp.int32)

    def leg_transformer():
        # amp is a property of the Trainer's step (amp=True above), not
        # of this call site
        t_step, _ = _timed(lambda a, b: tr.train_step(a, b), ids, lbl,
                           iters=min(iters, 10))
        # analytic FLOPs: 6 * params * tokens (fwd+bwd) + attention term
        n_params = sum(int(np.prod(p.shape))
                       for p in dict(emodel.named_parameters()).values())
        tokens = B2 * L2
        attn_flops = 12 * ecfg.num_layers * B2 * L2 * L2 * ecfg.hidden_size
        flops = 6 * n_params * tokens + attn_flops
        # bf16 peak of the serving chip (v5e 197 TFLOP/s)
        peak = float(os.environ.get("SMOKE_PEAK_TFLOPS", 197e12))
        return {
            "config": {"hidden": ecfg.hidden_size, "layers": ecfg.num_layers,
                       "seq": L2, "batch": B2},
            "amp": True,
            "step_ms": round(t_step * 1e3, 2),
            "params_millions": round(n_params / 1e6, 1),
            "tokens_per_sec": round(tokens / t_step, 0),
            "mfu_pct_of_peak": round(100 * flops / t_step / peak, 2),
        }

    _run_leg(result, "transformer_step", leg_transformer)

    # --- leg 4: fused sparse-rule Pallas kernel (all four rules) --------
    # First hardware execution of ops/sparse_optimizer.py compiled (not
    # interpret): parity vs the jnp path + timing at batch-merge scale.
    from paddle_tpu.ops.sparse_optimizer import (ctr_sparse_rows,
                                                 rule_state_dim)

    def leg_rules():
        leg4 = {}
        n_rows, dim4 = (1 << 12 if light else 1 << 17), 8
        for rule in ("naive", "adagrad", "std_adagrad", "adam"):
            es, xs = rule_state_dim(rule, 1), rule_state_dim(rule, dim4)
            gathered = (
                jnp.asarray(rng.uniform(0, 5, n_rows), jnp.float32),
                jnp.asarray(rng.uniform(0, 2, n_rows), jnp.float32),
                jnp.asarray(rng.normal(size=(n_rows, 1)), jnp.float32),
                jnp.asarray(rng.uniform(0, 1, (n_rows, es)), jnp.float32),
                jnp.asarray(rng.normal(size=(n_rows, dim4)), jnp.float32),
                jnp.asarray(rng.uniform(0, 1, (n_rows, xs)), jnp.float32),
                jnp.asarray((rng.random(n_rows) < 0.5).astype(np.float32)),
            )
            dshow = jnp.ones((n_rows,), jnp.float32)
            dclick = jnp.asarray((rng.random(n_rows) < 0.3).astype(np.float32))
            ge = jnp.asarray(rng.normal(size=(n_rows, 1)), jnp.float32)
            gx = jnp.asarray(rng.normal(size=(n_rows, dim4)), jnp.float32)
            kw = dict(embed_rule=rule, embedx_rule=rule, lr=0.05,
                      initial_g2sum=3.0, weight_bounds=(-10.0, 10.0),
                      beta1=0.9, beta2=0.999, eps=1e-8, nonclk_coeff=0.1,
                      click_coeff=1.0, embedx_threshold=0.0)
            # light mode runs on CPU where non-interpret pallas is N/A
            kern = jax.jit(lambda g: ctr_sparse_rows(
                g, dshow, dclick, ge, gx, interpret=True if light else False,
                **kw))
            t_k, out_k = _timed(kern, gathered, iters=iters)
            out_ref = ctr_sparse_rows(gathered, dshow, dclick, ge, gx,
                                      interpret=True, **kw)
            err = max(float(jnp.max(jnp.abs(a - b)))
                      for a, b in zip(out_k, out_ref)
                      if a.size)  # naive rule: zero-width state columns
            leg4[rule] = {"rows": n_rows, "kernel_ms": round(t_k * 1e3, 3),
                          "max_abs_err_vs_interpret": round(err, 7),
                          "match": bool(err < 1e-4)}
        return leg4

    _run_leg(result, "sparse_rule_kernel", leg_rules)

    # --- leg 5: pooled multi-valued-slot CTR step -----------------------
    from paddle_tpu.models.ctr import make_ctr_pooled_train_step

    seg = np.repeat(np.arange(8), [8, 4, 4, 2, 2, 2, 2, 2])  # T=26 cols
    pcfg = CtrConfig(num_sparse_slots=8, num_dense=13, embedx_dim=8,
                     dnn_hidden=(64,) if light else (400, 400, 400))
    pmodel = DeepFM(pcfg)
    pparams = {"params": dict(pmodel.named_parameters()), "buffers": {}}
    popt_state = opt.init(pparams)
    pstep = make_ctr_pooled_train_step(pmodel, opt, cache_cfg, seg,
                                       donate=False)
    rows_p = jnp.asarray(
        rng.integers(0, cache_cfg.capacity, size=(batch, len(seg))), jnp.int32)

    def pooled_once(rows_p, dense, labels):
        return pstep(pparams, popt_state, cache.state, rows_p, dense,
                     labels)[3]

    def leg_pooled():
        t_pool, _ = _timed(jax.jit(pooled_once), rows_p, dense, labels,
                           iters=iters)
        return {
            "batch": batch, "key_columns": int(len(seg)),
            "step_ms": round(t_pool * 1e3, 3),
            "device_samples_per_sec": round(batch / t_pool, 0),
        }

    _run_leg(result, "pooled_ctr_step", leg_pooled)

    result["timestamp"] = time.strftime("%Y-%m-%d %H:%M:%S")
    _write(result, platform=dev.platform)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc(file=sys.stderr)
        _write({"ok": False, "error": f"{type(e).__name__}: {e}"[:300]},
               platform=os.environ.get("SMOKE_PLATFORM"))
