"""Serving-fleet bench (ISSUE 15 acceptance → SERVING_FLEET.json).

Drives the REAL fleet end to end — an HA training cluster, N≥3
:class:`ServingReplica` members (each: oplog-subscribed replica +
read-only hot tier + micro-batching frontend) behind a
:class:`ServingRouter` with bounded-load CH affinity and hedging, a
:class:`ServingFleet` lease watcher, and a :class:`RolloutManager` —
under an **open-loop** traffic replay (arrivals scheduled on the wall
clock at a target rate, submitted whether or not earlier requests
finished — the load shape that actually exposes tail collapse; a
closed loop self-throttles around it). Phases:

0. **single-member reference** — the SAME open-loop driver against a
   ONE-member fleet at the steady rate: the apples-to-apples p99
   baseline for the "fleet p99 within 2× of single-replica" prong.
   The committed SERVING.json p99 is a closed-loop number from a
   different host generation (2 cores then, 1 now — MEASURED.md rule:
   cross-record ratios are not comparable, same-box re-measurement
   is), so the fleet tax must be measured against a same-box,
   same-driver single member.
1. **steady** — warm replay at ``SFB_RATE_QPS`` (default 1.15× the
   committed SERVING.json qps): the LATENCY arm — zero errors, hedge
   rate bounded, p99 compared against arm 0.
2. **saturation** — replay at ``SFB_SAT_QPS`` (default 2.6× the
   committed baseline): the CAPACITY arm — open-loop arrivals near the
   fleet's ceiling, queues form, batches grow, and the achieved rate
   IS the aggregate throughput (read the steady arm for tails). With
   ``SFB_SINGLE=1`` the bench also re-measures the single-replica
   CLOSED-loop ceiling on this host via tools/serving_bench.run() so
   the committed artifact carries every baseline the acceptance names.
2. **kill-replica chaos** — mid-replay, one member dies SIGKILL-style
   (frontend dead, lease left to expire); the router reroutes its
   traffic and the lease watch removes it. Gate: ZERO request errors.
3. **draining restart** — a member is drained (eject → finish
   in-flight → graceful detach) and a fresh one joins WARM mid-replay.
   Gate: ZERO request errors.
4. **join miss curves** — a warm-handoff join vs a cold join, each
   serving the same replayed chunk; per-chunk tier-miss curves. Gate:
   warm misses < cold misses (the handoff kills the cold-miss storm).
5. **canary → promote → rollback** — a traffic chunk under a canary
   band (split counted per version and checked against the
   deterministic band predicate), promote to N+1 fleet-wide, then roll
   back; gate: version N restored digest-identical on EVERY member.

Standalone: prints exactly ONE JSON line (driver contract). Env knobs:
SFB_KEYS (population, 20k), SFB_REPLICAS (3), SFB_BATCH (64),
SFB_RATE_QPS (0 = derive from SERVING.json), SFB_STEADY (steady-phase
requests, 4000), SFB_CHUNK (chaos/join/canary chunk, 1500), SFB_DIM
(embedx, 8), SFB_DELAY_US (coalesce window, 2000). Shared-host note:
ambient load on the 2-core CI box moves p99 2-3×; the ci.sh gate
asserts the error/ordering invariants (zero errors, warm<cold, hedge
bound) and retries once — the committed SERVING_FLEET.json is a
quiet-host run that also meets the throughput/latency acceptance.
"""

import json
import os
import queue
import sys
import threading
import time

METRIC = "serving_fleet_agg_qps"


def run() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import random as _random

    from paddle_tpu.io.fs import crc32c
    from paddle_tpu.ps import ha
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.hot_tier import HotEmbeddingTier, HotTierConfig
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig
    from paddle_tpu.ps.table import TableConfig
    from paddle_tpu.serving import (CachedLookup, DenseModel, FleetConfig,
                                    FleetMember, FrontendConfig,
                                    RolloutManager, RouterConfig,
                                    ServingFleet, ServingFrontend,
                                    ServingReplica, ServingRouter)

    S, D = 8, 4
    xd = int(os.environ.get("SFB_DIM", 8))
    n_keys = int(float(os.environ.get("SFB_KEYS", 20_000)))
    n_replicas = int(os.environ.get("SFB_REPLICAS", 3))
    max_batch = int(os.environ.get("SFB_BATCH", 64))
    n_steady = int(float(os.environ.get("SFB_STEADY", 4000)))
    n_chunk = int(float(os.environ.get("SFB_CHUNK", 1500)))
    delay_us = int(os.environ.get("SFB_DELAY_US", 4000))
    rate_env = float(os.environ.get("SFB_RATE_QPS", 0))
    sat_env = float(os.environ.get("SFB_SAT_QPS", 0))
    with_single = os.environ.get("SFB_SINGLE", "0") == "1"

    block_shift = 6
    blocks = n_keys >> block_shift

    # single-replica baseline (the committed SERVING.json)
    base_qps, base_p99 = 0.0, 0.0
    sj = os.path.join(repo, "SERVING.json")
    if os.path.exists(sj):
        with open(sj) as f:
            rec = json.load(f)
        base_qps = float(rec.get("warm", {}).get("qps", 0.0))
        base_p99 = float(rec.get("warm", {}).get("request_ms", {})
                         .get("p99_ms", 0.0))
    rate_qps = rate_env if rate_env > 0 else max(1.15 * base_qps, 1000.0)
    sat_qps = sat_env if sat_env > 0 else max(2.6 * base_qps, 2000.0)

    # optional same-box single-replica re-measurement (committed-run
    # mode): the SERVING.json record may predate a host change, so the
    # capacity comparison re-baselines on THIS machine
    single_same_box = None
    if with_single:
        import tools.serving_bench as _sb

        saved = {k: os.environ.get(k) for k in ("SB_REQUESTS", "SB_PROBES")}
        os.environ["SB_REQUESTS"] = os.environ.get("SFB_SINGLE_REQS",
                                                   "2000")
        os.environ["SB_PROBES"] = "5"
        try:
            srec = _sb.run()
            single_same_box = {
                "qps": srec["warm"]["qps"],
                "p99_ms": srec["warm"]["request_ms"]["p99_ms"],
                "via": "tools/serving_bench.run() on this host",
            }
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    rng = np.random.default_rng(0)
    cfg = TableConfig(shard_num=8, accessor_config=AccessorConfig(
        embedx_dim=xd, embedx_threshold=0.0,
        sgd=SGDRuleConfig(initial_range=0.01)))

    with ha.HACluster(num_shards=1, replication=1, sync=False) as cluster:
        train_cli = cluster.client()
        train_cli.create_sparse_table(0, cfg)
        keys = np.arange(n_keys, dtype=np.uint64)
        width = None
        t0 = time.perf_counter()
        for lo in range(0, n_keys, 1 << 15):
            kc = keys[lo:lo + (1 << 15)]
            train_cli.pull_sparse(0, kc)
            if width is None:
                width = train_cli._dims(0)[1]
            push = np.zeros((len(kc), width), np.float32)
            push[:, 1] = 1.0
            push[:, 3:] = 0.01 * rng.standard_normal(
                (len(kc), width - 3)).astype(np.float32)
            train_cli.push_sparse(0, kc, push)
        preload_s = time.perf_counter() - t0

        # one shared jitted MLP head; per-member params holders
        x_dim = S * (1 + xd) + D
        flat_dim = x_dim * 16 + 16 + 16 + 1
        rngp = np.random.default_rng(7)
        flat_v1 = 0.1 * rngp.standard_normal(flat_dim).astype(np.float32)
        flat_v2 = flat_v1 + np.float32(0.01)

        def unravel(flat):
            i = 0
            w1 = flat[i:i + x_dim * 16].reshape(x_dim, 16); i += x_dim * 16
            b1 = flat[i:i + 16]; i += 16
            w2 = flat[i:i + 16].reshape(16, 1); i += 16
            b2 = flat[i:i + 1]
            return {"w1": w1, "b1": b1, "w2": w2, "b2": b2}

        def _mlp(p, emb, dense):
            x = jnp.concatenate([emb.reshape(emb.shape[0], -1), dense],
                                axis=1)
            h = jnp.tanh(x @ p["w1"] + p["b1"])
            return (h @ p["w2"] + p["b2"]).reshape(-1)

        infer_jit = jax.jit(_mlp)

        def make_member():
            rep = ServingReplica(cluster.store, cluster.job_id, shard=0,
                                 hb_interval=0.05, hb_ttl=0.4)
            serve = rep.client()
            view = rep.serve_view(0, cfg, client=serve)
            prim = cluster.primary(0)
            deadline = time.perf_counter() + 60
            while True:
                dg = cluster.digests(0, 0).get(prim.endpoint)
                if dg is not None and dg == serve.digest(0)[0]:
                    break
                if time.perf_counter() > deadline:
                    raise TimeoutError("replica never caught up")
                time.sleep(0.02)
            tier = HotEmbeddingTier(view, HotTierConfig(
                capacity=1 << int(np.ceil(np.log2(n_keys * 1.8))),
                create_on_miss=False))
            lookup = CachedLookup(tier, replica=rep,
                                  freshness_budget_s=30.0)
            holder = {}
            model = DenseModel(
                unravel, flat_v1,
                sink=lambda p: holder.__setitem__(
                    "p", jax.device_put(p)))

            def infer(emb, dense):
                B = emb.shape[0]
                Bp = 1 << (max(B, 1) - 1).bit_length()
                if Bp != B:
                    emb = np.concatenate(
                        [emb, np.zeros((Bp - B,) + emb.shape[1:],
                                       emb.dtype)])
                    dense = np.concatenate(
                        [dense, np.zeros((Bp - B, dense.shape[1]),
                                         dense.dtype)])
                return np.asarray(infer_jit(holder["p"], emb, dense))[:B]

            fe = ServingFrontend(lookup, infer=infer,
                                 config=FrontendConfig(
                                     max_batch=max_batch,
                                     max_delay_us=delay_us,
                                     queue_cap=4096,
                                     default_deadline_ms=2000.0),
                                 replica_label=rep.endpoint)
            # compile every pow-2 bucket NOW (both jits): warm traffic
            # must never compile
            Bp = 1
            while Bp <= max_batch:
                infer(np.zeros((Bp, S, 1 + xd), np.float32),
                      np.zeros((Bp, D), np.float32))
                lookup.lookup(keys[: Bp * S])
                Bp <<= 1
            tier.drop()   # compile priming polluted residency: restart cold
            return FleetMember(rep, lookup, fe, model=model)

        # hedge floor 10 ms: on a batching frontend the coalesce window
        # IS most of the latency — hedging below it duplicates healthy
        # requests (measured: p95-budget hedging at a 4 ms window ran a
        # 13% hedge rate, all losers)
        router = ServingRouter(RouterConfig(block_shift=block_shift,
                                            hedge_default_ms=25.0,
                                            hedge_floor_ms=10.0),
                               rng=_random.Random(0))
        fleet = ServingFleet(cluster.store, cluster.job_id, make_member,
                             router,
                             config=FleetConfig(poll_s=0.05,
                                                warm_chunk=4096,
                                                max_replicas=16)).start()
        rollout = RolloutManager(lambda: fleet.members(), router)
        fleet.rollout = rollout
        rollout.register_baseline(flat_v1)

        # -- open-loop replay machinery ---------------------------------
        def gen_requests(n, rblocks=None, seed=1):
            g = np.random.default_rng(seed)
            bs = g.integers(0, blocks, n) if rblocks is None else \
                g.choice(rblocks, n)
            reqs = []
            for b in bs:
                base = int(b) << block_shift
                ks = (base + g.integers(0, 1 << block_shift, S)).astype(
                    np.uint64)
                reqs.append((int(b), ks,
                             g.standard_normal(D).astype(np.float32)))
            return reqs

        def gen_cover_requests(seed=2):
            """One request per (block, key-octet): tiles EVERY key of
            every block exactly once — the priming pass that makes the
            steady arm a genuinely warm measurement (random draws leave
            ~3/4 of each block cold and the arm measures miss RPCs, not
            routing)."""
            g = np.random.default_rng(seed)
            reqs = []
            per = (1 << block_shift) // S
            for b in range(blocks):
                base = b << block_shift
                perm = g.permutation(1 << block_shift)
                for j in range(per):
                    ks = (base + perm[j * S:(j + 1) * S]).astype(np.uint64)
                    reqs.append((b, ks,
                                 g.standard_normal(D).astype(np.float32)))
            g.shuffle(reqs)
            return reqs

        def replay(reqs, rate, collectors=8, deadline_ms=2000.0,
                   mid_hook=None):
            """Open loop: submit at `rate`, collect concurrently.
            Returns (wall_s, errors, shed, n_late)."""
            out_q: "queue.Queue" = queue.Queue(maxsize=len(reqs) + 1)
            errors = [0]
            done = threading.Event()

            def collect():
                while True:
                    rr = out_q.get()
                    if rr is None:
                        return
                    try:
                        rr.result(30)
                    except Exception:  # noqa: BLE001 — counted
                        errors[0] += 1

            cts = [threading.Thread(target=collect, daemon=True,
                                    name=f"sfb-collect-{i}")
                   for i in range(collectors)]
            for c in cts:
                c.start()
            shed = 0
            late = 0
            start = time.perf_counter()
            for i, (b, ks, dn) in enumerate(reqs):
                target = start + i / rate
                now = time.perf_counter()
                if target > now:
                    time.sleep(target - now)
                elif now - target > 0.05:
                    late += 1
                if mid_hook is not None and i == len(reqs) // 3:
                    mid_hook()
                try:
                    out_q.put(router.submit(ks, dense=dn,
                                            deadline_ms=deadline_ms))
                except Exception:  # noqa: BLE001 — shed at the router
                    shed += 1
                    errors[0] += 1
            submit_wall = time.perf_counter() - start
            for _ in cts:
                out_q.put(None)
            for c in cts:
                c.join()
            done.set()
            wall = time.perf_counter() - start
            return {"submit_wall_s": submit_wall, "wall_s": wall,
                    "errors": errors[0], "shed": shed, "late": late}

        out: dict = {"metric": METRIC, "unit": "qps"}
        try:
            # -- phase 0: one member, primed, same driver — the
            # same-box single-member open-loop reference ---------------
            fleet.add(1, warm=False)
            replay(gen_cover_requests(seed=2), rate=rate_qps,
                   deadline_ms=10000.0)

            # -- phase 1: steady (latency arm) + saturation (capacity
            # arm) open loops ------------------------------------------
            import gc

            def arm(n, rate):
                for m in fleet.members():
                    m.frontend.reset_stats()
                router.latency.reset()
                h0 = router.counters["hedges"]
                r0 = router.counters["reroutes"]
                routed0 = router.counters["routed"]
                gc.collect()
                gc.disable()
                try:
                    rep = replay(gen_requests(n, seed=3), rate=rate)
                finally:
                    gc.enable()
                lat = router.latency.percentiles()
                routed = router.counters["routed"] - routed0
                return {
                    "requests": n, "target_qps": round(rate, 1),
                    "achieved_qps": round(
                        (n - rep["errors"]) / rep["wall_s"], 1),
                    "request_ms": lat,
                    "errors": rep["errors"], "shed": rep["shed"],
                    "late_arrivals": rep["late"],
                    "hedges": router.counters["hedges"] - h0,
                    "reroutes": router.counters["reroutes"] - r0,
                    "hedge_rate": round(
                        (router.counters["hedges"] - h0)
                        / max(routed, 1), 4),
                    "per_member_batch": {
                        m.endpoint: m.frontend.stats().get("avg_batch", 0)
                        for m in fleet.members()},
                }

            single_arm = arm(max(n_steady // 2, 500), rate_qps)
            out["single_member_open_loop"] = single_arm

            # -- grow to the fleet: joiners warm-handoff from the
            # seasoned member, then a cover pass settles the CH
            # assignment's residual shares ----------------------------
            fleet.add(n_replicas - 1, warm=True)
            replay(gen_cover_requests(seed=2), rate=rate_qps,
                   deadline_ms=10000.0)

            steady = arm(n_steady, rate_qps)
            if os.environ.get("SFB_QUICK", "0") == "1":
                # tuning mode: steady arm only, skip the rest
                out["steady"] = steady
                out["value"] = steady["achieved_qps"]
                return out
            saturation = arm(n_steady, sat_qps)
            out["steady"] = steady
            out["saturation"] = saturation
            out["value"] = saturation["achieved_qps"]
            rst = router.stats()
            single_p99 = single_arm["request_ms"]["p99_ms"]
            out["vs_single_replica"] = {
                # committed-record prong: both arms clear the whole
                # committed single-replica record's throughput
                "committed_qps": base_qps, "committed_p99_ms": base_p99,
                "steady_qps_ratio": round(
                    steady["achieved_qps"] / base_qps, 3)
                if base_qps else None,
                "capacity_qps_ratio": round(
                    saturation["achieved_qps"] / base_qps, 3)
                if base_qps else None,
                # same-box p99 prong: fleet tail vs the one-member
                # same-driver arm at the same rate (arm 0) — the 2×
                # budget the acceptance names, measured without a host
                # generation change underneath it
                "single_open_loop_p99_ms": single_p99,
                "fleet_p99_over_single": round(
                    steady["request_ms"]["p99_ms"] / single_p99, 3)
                if single_p99 else None,
                # same-box closed-loop ceiling (SFB_SINGLE=1)
                "single_same_box_closed_loop": single_same_box,
                "capacity_vs_same_box": round(
                    saturation["achieved_qps"] / single_same_box["qps"],
                    3) if single_same_box else None,
            }

            # -- phase 2: kill-replica chaos ---------------------------
            victim = fleet.members()[-1]
            pre_n = fleet.size()
            rep2 = replay(gen_requests(n_chunk, seed=4), rate=rate_qps,
                          mid_hook=victim.crash)
            deadline = time.perf_counter() + 10
            while any(m.endpoint == victim.endpoint
                      for m in fleet.members(live_only=False)):
                if time.perf_counter() > deadline:
                    raise TimeoutError("crashed member never expired")
                time.sleep(0.05)
            rst2 = router.stats()
            out["chaos_kill"] = {
                "requests": n_chunk, "errors": rep2["errors"],
                "killed": victim.endpoint,
                "members_before": pre_n, "members_after": fleet.size(),
                "reroutes": rst2["reroutes"] - rst["reroutes"],
                "hedges": rst2["hedges"] - rst["hedges"],
            }

            # -- phase 3: warm rejoin + draining restart ---------------
            (warm_m,) = fleet.add(1, warm=True)
            handoff = fleet.events[-1].get("handoff")
            warm_curve = []
            miss0 = warm_m.lookup.tier.counters["misses"]
            for part in range(4):
                replay(gen_requests(n_chunk // 4, seed=10 + part),
                       rate=rate_qps)
                warm_curve.append(
                    int(warm_m.lookup.tier.counters["misses"] - miss0))
            oldest = fleet.members()[0]
            drain_clean = []

            def _drain_restart():
                drain_clean.append(fleet.drain(oldest.endpoint))
                fleet.add(1, warm=True)

            rep3 = replay(gen_requests(n_chunk, seed=5), rate=rate_qps,
                          mid_hook=_drain_restart)
            out["drain_restart"] = {
                "requests": n_chunk, "errors": rep3["errors"],
                "drained": oldest.endpoint,
                "drain_clean": bool(drain_clean and drain_clean[0]),
                "members": fleet.size(),
            }

            # -- phase 4: cold join (the comparison arm) ---------------
            (cold_m,) = fleet.add(1, warm=False)
            cold_curve = []
            miss0 = cold_m.lookup.tier.counters["misses"]
            for part in range(4):
                replay(gen_requests(n_chunk // 4, seed=20 + part),
                       rate=rate_qps)
                cold_curve.append(
                    int(cold_m.lookup.tier.counters["misses"] - miss0))
            out["join"] = {
                "warm": {"handoff": handoff, "miss_curve": warm_curve,
                         "misses": warm_curve[-1]},
                "cold": {"miss_curve": cold_curve,
                         "misses": cold_curve[-1]},
                "warm_lt_cold": warm_curve[-1] < cold_curve[-1],
            }

            # -- phase 5: canary → promote → rollback ------------------
            dg_v1 = crc32c(np.ascontiguousarray(flat_v1).tobytes())
            v1 = rollout.current
            v2 = rollout.begin_canary(flat_v2, fraction=0.2)
            canary_reqs = gen_requests(n_chunk, seed=6)
            expect = sum(router.in_canary_band(b, 0.2)
                         for b, _, _ in canary_reqs)
            rep5 = replay(canary_reqs, rate=rate_qps)
            counts = dict(router.stats()["version_counts"])
            rollout.promote()
            promoted = set(rollout.fleet_versions().values())
            rollout.rollback(reason="bench")
            back = rollout.fleet_versions()
            out["canary"] = {
                "errors": rep5["errors"],
                "version_counts": counts,
                "expected_canary": expect,
                "split_exact": counts.get(str(v2)) == expect,
                "promoted_all": promoted == {(v2, rollout.version_digest(
                    v2))},
                "rollback_versions": sorted(set(back.values())),
                "rollback_digest_ok": set(back.values()) ==
                {(v1, dg_v1)},
            }
            out["fleet_events"] = dict(fleet.counters)
            out["router"] = {k: v for k, v in router.stats().items()
                             if k not in ("members", "request")}
            out["population"] = n_keys
            out["replicas"] = n_replicas
            out["batch"] = max_batch
            out["coalesce_us"] = delay_us
            out["preload_s"] = round(preload_s, 2)
            out["platform"] = jax.devices()[0].platform
            out["host_cores"] = os.cpu_count()
            return out
        finally:
            fleet.stop()
            router.stop()


def main() -> None:
    try:
        rec = run()
    except Exception as e:  # noqa: BLE001 — one-JSON-line contract
        import traceback

        traceback.print_exc(file=sys.stderr)
        rec = {"metric": METRIC, "value": 0.0,
               "error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
