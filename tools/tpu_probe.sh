#!/bin/bash
# TPU recovery probe (VERDICT r2 item #1).
#
# The axon relay's grant leg has been wedged since 2026-07-29 ~21:38 UTC:
# any backend init hangs indefinitely. This loop probes init under a
# subprocess timeout every 15 min; the moment the backend comes up it
# captures the round's hardware evidence (bench.py + tools/tpu_smoke.py)
# and drops a RECOVERED.flag marker for the build session to commit.
# It deliberately does NOT git-commit itself (index-lock races with the
# interactive session).
# External `timeout` on a grant-holding process is what wedges the
# relay (MEASURED.md 2026-07-31): bench self-bounds via BENCH_DEADLINE
# (clean self-exit with a diagnostic JSON); the `timeout -k 60 3600`
# wrappers are a last-resort backstop far above any plausible runtime.
cd /root/repo || exit 1
LOG=tools/probe.log
while true; do
  ts=$(date -u +%FT%TZ)
  if timeout 90 python -c "
import jax
d = jax.devices()
assert d and d[0].platform not in ('cpu',), d
print('devices:', d)
" >>"$LOG" 2>&1; then
    echo "$ts RECOVERED — capturing evidence" >>"$LOG"
    BENCH_INIT_TIMEOUT=300 BENCH_DEADLINE=900 timeout -k 60 3600 python bench.py >BENCH_RECOVERY.json 2>>"$LOG"
    # slab sweep: how much of the wall time was dispatch (BENCH_DECOMP
    # term 4) — one line per slab setting
    for SLAB in 1 16 32; do
      BENCH_SLAB=$SLAB BENCH_INIT_TIMEOUT=300 BENCH_DEADLINE=600 \
        timeout -k 60 3600 python bench.py >>BENCH_SLAB_SWEEP.jsonl 2>>"$LOG"
    done
    # batch sweep: per-sample overheads fall with batch; wire grows
    for BATCH in 8192 16384; do
      BENCH_BATCH=$BATCH BENCH_INIT_TIMEOUT=300 BENCH_DEADLINE=600 \
        timeout -k 60 3600 python bench.py >>BENCH_BATCH_SWEEP.jsonl 2>>"$LOG"
    done
    # NOTE: tpu_smoke.py and tpu_decomp.py write their artifacts
    # (TPU_SMOKE.json / DECOMP.json) INTERNALLY; redirecting stdout onto
    # the same file would interleave the truncated stdout echo with the
    # real dump and corrupt it — stdout goes to the log instead
    timeout -k 60 3600 python tools/tpu_smoke.py >>"$LOG" 2>&1
    # composed-term re-verification (VERDICT #1: tpu_decomp ties each
    # BENCH_DECOMP model term to a measured-on-chip number)
    timeout -k 60 3600 python tools/tpu_decomp.py >>"$LOG" 2>&1
    echo "$ts evidence captured" >>"$LOG"
    touch RECOVERED.flag
    exit 0
  else
    echo "$ts probe: backend init hung/failed (>90s)" >>"$LOG"
  fi
  sleep 300
done
