"""PS high-availability chaos demo: measured failover + replication cost.

Drives the full HA control loop (ps/ha.py) end to end and emits one
JSON line for the bench trajectory:

- **recovery time** — N trials of: replicated cluster under live
  CtrStream-style traffic, kill-shard the primary via the armed
  faultpoint, time from the kill to the first successful client call
  answered by the promoted backup (lease expiry + grace + promotion +
  client re-route). Reported as p50/p95 ms.
- **steady-state replication overhead** — the CtrStreamTrainer
  microbench run against a replication-factor-1 cluster vs an async
  replication-factor-2 cluster (same data, same seeds, steady-state
  pass timed after a warm-up pass); overhead % = throughput loss from
  the oplog tap + shipper + backup apply sharing the host.

Env knobs: CHAOS_TRIALS (default 5), CHAOS_ROWS (dataset rows),
CHAOS_BATCH, CHAOS_OUT (also write JSON to this path), CHAOS_CPU=0 to
keep the ambient jax platform. Exits 0 with an "error" field on
failure (one-JSON-line driver contract).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pct(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def _make_dataset(rows, S, D, seed=0):
    import numpy as np

    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc

    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(rows):
        ids = rng.integers(0, 96, S)
        dense = rng.normal(size=D)
        label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
        lines.append(" ".join([f"1 {v}" for v in ids]
                              + [f"1 {v:.4f}" for v in dense]
                              + [f"1 {label}"]))
    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1) for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1) for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])
    ds = InMemoryDataset(slots, seed=0)
    ds.load_from_lines(lines)
    return ds


class _StreamBench:
    """One CtrStreamTrainer kept alive across passes so A/B configs can
    be measured INTERLEAVED (pass-paired ambient load — on a small host
    the load noise otherwise dwarfs the shipping cost this measures)."""

    def __init__(self, cluster, ds, S, D, batch):
        import paddle_tpu as pt
        from paddle_tpu import optimizer
        from paddle_tpu.models.ctr import CtrConfig, DeepFM
        from paddle_tpu.ps.communicator import SyncCommunicator
        from paddle_tpu.ps.ps_trainer import CtrStreamTrainer
        from paddle_tpu.ps.table import TableConfig
        from paddle_tpu.ps.accessor import AccessorConfig
        from paddle_tpu.ps.sgd_rule import SGDRuleConfig

        self.ds, self.batch = ds, batch
        cli = cluster.client()
        cli.create_sparse_table(0, TableConfig(
            shard_num=4, accessor_config=AccessorConfig(
                sgd=SGDRuleConfig(initial_range=0.0))))
        self.comm = SyncCommunicator(cli)
        self.comm.start()
        pt.seed(0)
        self.tr = CtrStreamTrainer(
            DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=8,
                             dnn_hidden=(16,))),
            optimizer.Adam(1e-2), None, communicator=self.comm, table_id=0,
            embedx_dim=8,
            sparse_slots=[f"s{i}" for i in range(S)],
            dense_slots=[f"d{i}" for i in range(D)], label_slot="label")

    def run_pass(self) -> float:
        import numpy as np

        out = self.tr.train_from_dataset(self.ds, batch_size=self.batch)
        assert np.isfinite(out["loss"])
        return out["samples_per_sec"]

    def close(self) -> None:
        self.comm.stop()


def _recovery_trial(rpc, ha, cfg, rng):
    """One kill→recover measurement; returns milliseconds."""
    import numpy as np

    with ha.HACluster(num_shards=1, replication=2, sync=False,
                      hb_interval=0.05, hb_ttl=0.4, grace_s=0.1) as cluster:
        cli = cluster.client()
        cli.create_sparse_table(0, cfg)
        keys = rng.integers(1, 50_000, 2048).astype(np.uint64)
        push = np.zeros((len(keys), 12), np.float32)
        push[:, 1] = 1.0
        push[:, 3:] = rng.normal(0, 0.1, (len(keys), 9)).astype(np.float32)
        cli.pull_sparse(0, keys)
        for _ in range(5):
            cli.push_sparse(0, keys, push)
        # die on the NEXT pull the primary sees (armed faultpoint)
        cluster.primary(0).server.arm_fault(
            "kill-shard", cmd=rpc._PULL_SPARSE, after=1)
        t0 = time.perf_counter()
        out = cli.pull_sparse(0, keys, create=False)  # rides the failover
        dt = (time.perf_counter() - t0) * 1000.0
        assert out.shape == (len(keys), cli._dims(0)[0])
        assert cluster.coordinator.promotions >= 1
        return dt


def main() -> None:
    out = {"bench": "chaos_ps"}
    path = os.environ.get("CHAOS_OUT")
    try:
        import jax

        if os.environ.get("CHAOS_CPU", "1") == "1":
            jax.config.update("jax_platforms", "cpu")
        import numpy as np

        from paddle_tpu.ps import ha, rpc
        from paddle_tpu.ps.accessor import AccessorConfig
        from paddle_tpu.ps.sgd_rule import SGDRuleConfig
        from paddle_tpu.ps.table import TableConfig

        out["platform"] = jax.devices()[0].platform

        trials = int(os.environ.get("CHAOS_TRIALS", 5))
        rows = int(os.environ.get("CHAOS_ROWS", 512))
        batch = int(os.environ.get("CHAOS_BATCH", 128))
        cfg = TableConfig(shard_num=4, accessor_config=AccessorConfig(
            sgd=SGDRuleConfig(initial_range=0.0)))
        rng = np.random.default_rng(0)

        # -- recovery time distribution --------------------------------
        times = sorted(_recovery_trial(rpc, ha, cfg, rng)
                       for _ in range(trials))
        out["recovery_trials"] = trials
        out["recovery_ms_p50"] = round(_pct(times, 0.50), 1)
        out["recovery_ms_p95"] = round(_pct(times, 0.95), 1)
        out["recovery_ms_all"] = [round(t, 1) for t in times]

        # -- steady-state async replication overhead -------------------
        # interleaved A/B: the plain and replicated trainers alternate
        # passes (best-of over rounds), so ambient load hits both
        S, D = 3, 2
        rounds = int(os.environ.get("CHAOS_AB_ROUNDS", 5))
        ds = _make_dataset(rows, S, D)
        with ha.HACluster(num_shards=1, replication=1, sync=False) as base, \
                ha.HACluster(num_shards=1, replication=2, sync=False) as repl:
            a = _StreamBench(base, ds, S, D, batch)
            b = _StreamBench(repl, ds, S, D, batch)
            a.run_pass()  # compile warm-up, both configs
            b.run_pass()
            rate_plain = rate_repl = 0.0
            for r in range(rounds):
                # alternate the slot order: an A/A control shows ~10%
                # systematic bias toward whichever config runs first in
                # a round — alternating + best-of cancels it
                first, second = (a, b) if r % 2 == 0 else (b, a)
                r1, r2 = first.run_pass(), second.run_pass()
                ra, rb = (r1, r2) if r % 2 == 0 else (r2, r1)
                rate_plain = max(rate_plain, ra)
                rate_repl = max(rate_repl, rb)
            a.close()
            b.close()
            repl.drain()  # async mode still drains clean at exit
        out["stream_samples_per_sec_plain"] = round(rate_plain, 1)
        out["stream_samples_per_sec_replicated"] = round(rate_repl, 1)
        out["repl_overhead_pct"] = round(
            max(0.0, (1.0 - rate_repl / max(rate_plain, 1e-9)) * 100.0), 2)
    except Exception as e:  # noqa: BLE001 — one-JSON-line contract
        out["error"] = f"{type(e).__name__}: {e}"
    line = json.dumps(out)
    print(line)
    if path:
        with open(path, "w") as f:
            f.write(line + "\n")
    sys.exit(0)


if __name__ == "__main__":
    main()
