"""DeepFM/Criteo convergence anchor on the CPU MemorySparseTable path.

BASELINE.md's first measured-baseline task (SURVEY §6): run the
the_one_ps-style CPU-table configuration — every batch pulls from and
pushes to the host sparse table (MemorySparseTable, CTR accessor +
AdaGrad rules; memory_sparse_table.cc pull/push semantics), with only
the dense fwd/bwd jitted — and record samples/sec plus the AUC-vs-step
curve as the comparison anchor future rounds must match or beat.
Harness shape follows the reference's fleet CTR tests
(test_dist_fleet_base.py:311 / dist_fleet_ctr.py): synthetic
Criteo-shaped stream, bucketed AUC metric.

Synthetic task: each feasign carries a latent logit weight; the label is
Bernoulli(sigmoid(sum of latent weights + dense effect)) — learnable,
with a known AUC ceiling. Deterministic (seed 0).

Writes ANCHOR.json. Runs on CPU only (never touches the TPU chip).
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main() -> None:
    import jax  # noqa: E402

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp  # noqa: E402

    import paddle_tpu as pt  # noqa: E402
    from paddle_tpu import nn, optimizer  # noqa: E402
    from paddle_tpu.metrics.auc import AUC  # noqa: E402
    from paddle_tpu.models.ctr import CtrConfig, DeepFM  # noqa: E402
    from paddle_tpu.ps.accessor import AccessorConfig  # noqa: E402
    from paddle_tpu.ps.table import MemorySparseTable, TableConfig  # noqa: E402

    steps = int(os.environ.get("ANCHOR_STEPS", 120))
    batch = int(os.environ.get("ANCHOR_BATCH", 512))
    eval_every = int(os.environ.get("ANCHOR_EVAL_EVERY", 10))
    vocab_per_slot = 4096

    cfg = CtrConfig(num_sparse_slots=26, num_dense=13, embedx_dim=8,
                    dnn_hidden=(400, 400, 400))
    S, dim = cfg.num_sparse_slots, cfg.embedx_dim

    pt.seed(0)
    rng = np.random.default_rng(0)

    # ground truth: per-feasign latent logit weights, Zipf-ish popularity
    latent = rng.normal(0, 0.35, size=(S, vocab_per_slot)).astype(np.float32)
    dense_w = rng.normal(0, 0.3, size=cfg.num_dense).astype(np.float32)
    zipf_p = 1.0 / np.arange(1, vocab_per_slot + 1) ** 1.1
    zipf_p /= zipf_p.sum()

    def sample(n):
        ids = rng.choice(vocab_per_slot, size=(n, S), p=zipf_p)
        keys = ids.astype(np.uint64) + (np.arange(S, dtype=np.uint64) << np.uint64(32))
        dense = rng.normal(size=(n, cfg.num_dense)).astype(np.float32)
        logit = latent[np.arange(S)[None, :], ids].sum(axis=1) + dense @ dense_w
        labels = (rng.random(n) < 1.0 / (1.0 + np.exp(-(logit - 1.0)))).astype(np.int32)
        return keys, dense, labels

    table = MemorySparseTable(TableConfig(
        shard_num=16,
        accessor_config=AccessorConfig(embedx_dim=dim, embedx_threshold=0.0)))
    slot_ids = np.tile(np.arange(S, dtype=np.int32), batch)

    model = DeepFM(cfg)
    opt = optimizer.Adam(learning_rate=1e-3)
    params = {"params": dict(model.named_parameters()), "buffers": {}}
    opt_state = opt.init(params)

    def loss_fn(params, emb, dense_x, labels):
        out, _ = nn.functional_call(model, params, emb, dense_x, training=True)
        loss = nn.functional.binary_cross_entropy_with_logits(
            out, labels.astype(jnp.float32))
        return loss, out

    @jax.jit
    def train_step(params, opt_state, emb, dense_x, labels):
        (loss, logits), (grads, emb_grad) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(params, emb, dense_x,
                                                   labels)
        new_params, new_opt = opt.update(grads, opt_state, params)
        return new_params, new_opt, loss, emb_grad, jax.nn.sigmoid(logits)

    @jax.jit
    def infer(params, emb, dense_x):
        out, _ = nn.functional_call(model, params, emb, dense_x,
                                    training=False)
        return jax.nn.sigmoid(out)

    def pull_emb(keys_flat, create):
        pulled = table.pull_sparse(
            keys_flat, slots=slot_ids[:len(keys_flat)], create=create)
        # CTR pull layout: show, click, embed_w, embedx_w[dim]
        return pulled[:, 2:].reshape(-1, S, 1 + dim)

    eval_keys, eval_dense, eval_labels = sample(4096)

    def eval_auc():
        m = AUC()
        emb = pull_emb(eval_keys.reshape(-1), create=False)
        probs = np.asarray(infer(params, jnp.asarray(emb),
                                 jnp.asarray(eval_dense)))
        m.update(probs, eval_labels)
        return float(m.accumulate())

    curve = []
    t0 = time.perf_counter()
    train_time = 0.0
    for step_i in range(steps):
        keys, dense, labels = sample(batch)
        flat = keys.reshape(-1)
        ts = time.perf_counter()
        emb = pull_emb(flat, create=True)
        params, opt_state, loss, emb_grad, probs = train_step(
            params, opt_state, jnp.asarray(emb), jnp.asarray(dense),
            jnp.asarray(labels))
        g = np.asarray(emb_grad).reshape(-1, 1 + dim)
        push = np.empty((len(flat), 4 + dim), np.float32)
        push[:, 0] = slot_ids
        push[:, 1] = 1.0                                # show
        push[:, 2] = np.repeat(labels, S)               # click
        push[:, 3:] = g                                 # embed_g, embedx_g
        table.push_sparse(flat, push)
        train_time += time.perf_counter() - ts
        if (step_i + 1) % eval_every == 0 or step_i == 0:
            auc = eval_auc()
            curve.append([step_i + 1, round(auc, 4)])
            print(f"step {step_i+1}: loss {float(loss):.4f} auc {auc:.4f}",
                  file=sys.stderr, flush=True)

    # secondary measurement: the SAME workload through the GPUPS-style
    # fused cache path (in-graph lookup+pull+push) — the speed ratio the
    # HBM-cache architecture buys even on CPU
    from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
    from paddle_tpu.models.ctr import make_ctr_train_step_from_keys

    pt.seed(0)
    table2 = MemorySparseTable(TableConfig(
        shard_num=16,
        accessor_config=AccessorConfig(embedx_dim=dim, embedx_threshold=0.0)))
    cache_cfg = CacheConfig(capacity=1 << 18, embedx_dim=dim,
                            embedx_threshold=0.0)
    cache = HbmEmbeddingCache(table2, cache_cfg, device_map=True)
    model2 = DeepFM(cfg)
    params2 = {"params": dict(model2.named_parameters()), "buffers": {}}
    opt_state2 = opt.init(params2)
    step2 = make_ctr_train_step_from_keys(model2, opt, cache_cfg,
                                          slot_ids=np.arange(S))
    # pass working set = the full key space (every slot × vocab id)
    all_keys = (np.tile(np.arange(vocab_per_slot, dtype=np.uint64), S)
                + np.repeat(np.arange(S, dtype=np.uint64), vocab_per_slot)
                * np.uint64(1 << 32))
    cache.begin_pass(all_keys)
    ms = cache.device_map.state
    cache_steps = min(steps, 40)
    # warm up (compile) outside the timer — the table-path loop amortizes
    # its compile over `steps`, so give the cache leg the same footing
    wk, wd, wl = sample(batch)
    wlo = (wk & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    params2, opt_state2, cache.state, l0 = step2(
        params2, opt_state2, cache.state, ms, jnp.asarray(wlo),
        jnp.asarray(wd), jnp.asarray(wl))
    jax.block_until_ready(l0)
    t1 = time.perf_counter()
    loss2 = None
    done = 0
    for i in range(cache_steps):
        keys, dense, labels = sample(batch)
        lo32 = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        try:
            params2, opt_state2, cache.state, loss2 = step2(
                params2, opt_state2, cache.state, ms, jnp.asarray(lo32),
                jnp.asarray(dense), jnp.asarray(labels))
            done += 1
        except Exception as e:  # noqa: BLE001 — secondary metric only
            print(f"cache-path leg stopped at step {i}: {e}",
                  file=sys.stderr)
            break
    if loss2 is not None:
        jax.block_until_ready(loss2)
    cache_dt = time.perf_counter() - t1
    cache_sps = round(batch * done / cache_dt, 1) if done else None
    cache.discard_pass()

    out = {
        "task": "deepfm_criteo_synthetic_cpu_table_path",
        "mode": "the_one_ps CPU MemorySparseTable pull/push per batch",
        "samples_per_sec": round(batch * steps / train_time, 1),
        "cache_path_samples_per_sec": cache_sps,
        "steps": steps,
        "batch": batch,
        "final_auc": curve[-1][1],
        "auc_curve": curve,
        "table_features": table.size(),
        "config": {"slots": S, "dense": cfg.num_dense, "embedx_dim": dim,
                   "dnn": list(cfg.dnn_hidden), "vocab_per_slot": vocab_per_slot,
                   "optimizer": "Adam 1e-3 dense + CTR AdaGrad sparse"},
        "wall_clock_sec": round(time.perf_counter() - t0, 1),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "ANCHOR.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"anchor": out["final_auc"],
                      "samples_per_sec": out["samples_per_sec"]}))


if __name__ == "__main__":
    main()
