"""SLO pipeline demo: the DeepFM stream under the always-on sampler,
with one injected degradation — the committed OBS_TIMESERIES.json
artifact (ISSUE 10 satellite: the headline perf trajectory as durable
CURVES, not a single number).

What one run produces:

1. a 2-shard RPC PS cluster + SyncCommunicator DeepFM stream trainer,
   with a :class:`~paddle_tpu.obs.timeseries.JobCollector` thread
   sampling trainer + both shards and a
   :class:`~paddle_tpu.obs.slo.SloWatchdog` attached to its ticks;
2. a WARM phase that calibrates the step-time SLO threshold from the
   observed p95 (platform-independent: the artifact is meaningful on
   any box);
3. a DEGRADED phase: a ``delay-ms`` faultpoint armed on the client
   ``rpc.call`` site (every pull pays the delay) until the watchdog's
   multi-window burn-rate rule FIRES — the alert dumps a flight-
   recorder bundle (``dump_on={"slo_alert"}``);
4. a RECOVERY phase (faultpoint disarmed) until the alert CLEARS;
5. the artifact: step-time p95 / step-rate / per-table wire-density
   and wire-byte curves, the alert record, the bundle's self-check
   (alert inside the degraded window, merged trace parses, spans
   present), an OpenMetrics scrape of the live exporter validated by
   the strict parser, and a tools/timeline.py merge showing the alert
   as an instant event against the span lanes.

Standalone: prints exactly ONE JSON line (driver contract) and writes
OBS_TIMESERIES.json (env SLO_OUT overrides). Env knobs: SLO_SLOTS,
SLO_BATCH, SLO_STEPS, SLO_MAX_EPOCHS, SLO_PERIOD.
"""

import json
import os
import sys
import tempfile
import time
import urllib.request

METRIC = "slo_timeseries_demo"


def run(out_path: str) -> dict:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import jax

    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.obs import exporter as om
    from paddle_tpu.obs import flightrec, registry, slo, timeseries, trace
    from paddle_tpu.ps import rpc
    from paddle_tpu.ps.communicator import SyncCommunicator
    from paddle_tpu.ps.faultpoints import arm_faultpoint, disarm_faultpoints
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer
    from paddle_tpu.ps.table import TableConfig

    sys.path.insert(0, os.path.join(repo, "tools"))
    import timeline

    from obs_overhead_bench import _make_dataset  # one shared generator

    S = int(os.environ.get("SLO_SLOTS", 8))
    D = 4
    batch = int(os.environ.get("SLO_BATCH", 256))
    steps = int(os.environ.get("SLO_STEPS", 6))
    max_epochs = int(os.environ.get("SLO_MAX_EPOCHS", 12))
    period = float(os.environ.get("SLO_PERIOD", 0.1))
    ds = _make_dataset(S, D, batch, steps, nid=1000)

    registry.set_process_role("trainer")
    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    client = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers])
    sampler = exp = None
    try:
        client.create_sparse_table(
            0, TableConfig(table_id=0, shard_num=4, accessor="ctr"))
        comm = SyncCommunicator(client)
        comm.start()
        pt.seed(0)
        trainer = CtrStreamTrainer(
            DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=8,
                             dnn_hidden=(64, 64))),
            optimizer.Adam(1e-3), None,
            sparse_slots=[f"s{i}" for i in range(S)],
            dense_slots=[f"d{i}" for i in range(D)], label_slot="label",
            communicator=comm, table_id=0, embedx_dim=8)

        # -- the always-on layer -----------------------------------------
        ring = timeseries.MetricRing(capacity=2048)
        sampler = timeseries.JobCollector(client=client, period_s=period,
                                          ring=ring).start()
        wd = slo.SloWatchdog(ring)
        wd.attach(sampler)
        bundle_dir = tempfile.mkdtemp(prefix="slo_demo_flightrec_")
        rec = flightrec.install(flightrec.FlightRecorder(
            bundle_dir, ring=ring, watchdog=wd, client=client,
            dump_on={"slo_alert"}, min_interval_s=0.0))
        exp = om.ObsExporter(sampler.latest, ring=ring,
                             alerts_fn=wd.alerts).start()

        # -- warm phase: compile + calibrate the objective ---------------
        warm_ms = []
        for _ in range(3):
            t0 = time.perf_counter()
            r = trainer.train_from_dataset(ds, batch_size=batch)
            comm.barrier()
            warm_ms.append((time.perf_counter() - t0) / r["steps"] * 1e3)
        time.sleep(2.5 * period)   # let the sampler see the warm tail
        # calibrate from the FASTEST warm epoch (the ring's p95 curve
        # still carries the first epoch's compile step — multi-hundred
        # ms — which would inflate the objective past any injectable
        # delay); 4× the steady-state step is a tight-but-honest SLO
        threshold_s = max(4.0 * min(warm_ms) / 1e3, 0.02)
        wd.add_rule(slo.SloRule(
            "step_time_p95", "trainer_step_time_s",
            threshold=threshold_s, budget=0.2,
            windows=((40 * period, 1.0), (10 * period, 1.0))))

        # -- degraded phase: delay every pull until the rule fires -------
        delay_ms = max(100, int(threshold_s * 1e3 * 2))
        # sample=1.0: every degraded step records a span, so the bundle
        # the alert dumps deterministically contains the slow steps (a
        # fractional sample can dump before any root happened to be
        # sampled — the gate asserts spans > 0)
        trace.start_tracing(sample=1.0)
        degrade_t0 = trace.wall_s()
        arm_faultpoint("rpc.call", "delay-ms", cmd=rpc._PULL_SPARSE,
                       ms=delay_ms, every=1)
        degraded_epochs = 0
        try:
            for _ in range(max_epochs):
                trainer.train_from_dataset(ds, batch_size=batch)
                comm.barrier()
                degraded_epochs += 1
                if any(a["rule"] == "step_time_p95" and a["cleared_t"] is None
                       for a in wd.alerts()):
                    break
        finally:
            disarm_faultpoints()
        degrade_t1 = trace.wall_s()
        alerts_fired = [a for a in wd.alerts()
                        if a["rule"] == "step_time_p95"]
        assert alerts_fired, (
            f"watchdog never fired after {degraded_epochs} degraded epochs "
            f"(threshold {threshold_s * 1e3:.1f} ms, delay {delay_ms} ms)")
        alert = alerts_fired[0]
        assert degrade_t0 <= alert["t"] <= degrade_t1 + period, alert

        # -- recovery phase: the alert must CLEAR ------------------------
        recovery_epochs = 0
        for _ in range(max_epochs):
            trainer.train_from_dataset(ds, batch_size=batch)
            comm.barrier()
            recovery_epochs += 1
            if "step_time_p95" not in wd.active():
                break
        time.sleep(2.5 * period)
        trace.stop_tracing()
        cleared = "step_time_p95" not in wd.active()

        # -- bundle self-check -------------------------------------------
        bundles = rec.bundles()
        assert bundles, "alert did not dump a flight-recorder bundle"
        with open(os.path.join(bundles[0], "manifest.json")) as f:
            manifest = json.load(f)
        with open(os.path.join(bundles[0], "trace.json")) as f:
            btrace = json.load(f)
        with open(os.path.join(bundles[0], "alerts.json")) as f:
            balerts = json.load(f)["alerts"]
        in_window = [a for a in balerts
                     if a["rule"] == "step_time_p95"
                     and degrade_t0 <= a["t"] <= degrade_t1 + period]
        assert in_window, (balerts, degrade_t0, degrade_t1)
        alert_instants = [e for e in btrace["traceEvents"]
                          if e.get("ph") == "i"
                          and e["name"].startswith("ALERT")]
        assert alert_instants, "bundle trace has no alert instant event"

        # -- exporter scrape, validated as well-formed OpenMetrics -------
        with urllib.request.urlopen(f"{exp.url}/metrics", timeout=10) as r:
            text = r.read().decode()
        fams = om.parse_openmetrics(text)
        assert "trainer_step_time_s" in fams and "slo_alerts" in fams, \
            sorted(fams)

        # -- timeline merge: alert instants against the span lanes -------
        tmp = tempfile.mkdtemp(prefix="slo_demo_tl_")
        lane = os.path.join(tmp, "trainer.json")
        trace.export_chrome_trace(lane, pid=0, process_name="trainer")
        with open(lane) as f:
            blob = json.load(f)
        blob["sloAlerts"] = wd.alerts()
        with open(lane, "w") as f:
            json.dump(blob, f)
        merged_path = os.path.join(tmp, "merged.json")
        n_events = timeline.merge_traces([lane], merged_path)
        with open(merged_path) as f:
            merged = json.load(f)["traceEvents"]
        tl_alerts = [e for e in merged if e.get("cat") == "slo_alert"]
        assert any(e["name"] == "ALERT step_time_p95" for e in tl_alerts)

        # -- the committed curves ----------------------------------------
        t_base = ring.records()[0]["t"] if len(ring) else 0.0

        def curve(pairs, scale=1.0, nd=3):
            return [[round(t - t_base, 3), round(v * scale, nd)]
                    for t, v in pairs]

        density = {}
        byte_rate = {}
        for d in ("push", "pull"):
            density[d] = curve(ring.series(
                "ps_client_density", "value", labels={"dir": d},
                reduce="mean"), nd=4)
            byte_rate[d] = curve(ring.series(
                "ps_server_wire_bytes", "rate", labels={"dir": "in" if
                                                        d == "push"
                                                        else "out"}), nd=0)
        rec_out = {
            "metric": METRIC,
            "platform": jax.devices()[0].platform,
            "out": out_path,
            "period_s": period,
            "ticks": sampler.ticks,
            "tick_errors": sampler.errors,
            "warm_ms_per_step": round(min(warm_ms), 2),
            "threshold_ms": round(threshold_s * 1e3, 2),
            "delay_ms": delay_ms,
            "degraded_epochs": degraded_epochs,
            "recovery_epochs": recovery_epochs,
            "alert": alert,
            "alert_cleared": cleared,
            "bundle": {
                "path": bundles[0],
                "reason": manifest["reason"],
                "spans": manifest["spans"],
                "alerts": manifest["alerts"],
                "alert_in_degraded_window": bool(in_window),
                "alert_instants_in_trace": len(alert_instants),
            },
            "openmetrics_ok": True,
            "openmetrics_families": len(fams),
            "timeline_events": n_events,
            "timeline_alert_instants": len(tl_alerts),
            "curves": {
                "step_time_p95_ms": curve(
                    ring.series("trainer_step_time_s", "p95"), 1e3),
                "step_rate_per_s": curve(
                    ring.series("trainer_step_time_s", "count")),
                "wire_density": density,
                "server_wire_bytes_per_tick": byte_rate,
            },
        }
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(rec_out, f, indent=1, sort_keys=True)
        comm.stop()
        return rec_out
    finally:
        from paddle_tpu.obs import flightrec as _fr

        _fr.uninstall()
        if exp is not None:
            exp.stop()
        if sampler is not None:
            sampler.stop()
        client.stop_servers()
        client.close()
        for s in servers:
            s.close()


def main() -> int:
    out = os.environ.get("SLO_OUT", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "OBS_TIMESERIES.json"))
    try:
        rec = run(out)
        rec = {k: v for k, v in rec.items() if k != "curves"}  # short line
    except Exception as e:  # one-JSON-line driver contract
        rec = {"metric": METRIC, "error": f"{type(e).__name__}: {e}"}
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
