"""Online-serving-plane bench (ROADMAP item 2 rung; ISSUE 7 acceptance).

Drives the real plane end to end — an HA training cluster
(NativePsServer + ReplicationManager), a :class:`ServingReplica`
subscribed to the oplog change feed, the dense-tower values-only sync,
and a :class:`ServingFrontend` micro-batching requests over the warm
``CachedLookup`` path — and measures the two SLOs SERVING.json gates:

- **warm latency**: lookup+infer request latency (submit → delivered)
  with the working set resident in the hot tier — zero RPCs of any
  kind per warm request, counted, not assumed. Target: p99 in
  single-digit ms at the bench batch size.
- **freshness**: push→servable — a marker stat pushed on the TRAINING
  client, polled until visible through the SERVING path — measured
  under concurrent writer traffic. Target: p95 ≤ 100 ms with
  ``freshness_failures == 0``, vs the ≈1.38 s p95 arrival→export loop
  in the committed ONLINE.json (quoted as the baseline column).

Standalone: prints exactly ONE JSON line (driver contract). Importable:
``run()`` returns the record. Env knobs: SB_KEYS (warm population,
default 20k), SB_BATCH (frontend max_batch, 64), SB_REQUESTS (warm
requests measured, 2000), SB_CONCURRENCY (closed-loop submitters, 8),
SB_PROBES (freshness probes, 25), SB_DIM (embedx dim, 8). Shared-host
note: ambient load on a 2-core CI box moves the p99 by 2-3x between
runs — the CI gate thresholds carry headroom for that; the committed
SERVING.json is a quiet-host run.
"""

import json
import os
import sys
import threading
import time

METRIC = "serving_warm_p99_ms"


def run() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from paddle_tpu.ps import ha
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.hot_tier import HotEmbeddingTier, HotTierConfig
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig
    from paddle_tpu.ps.table import TableConfig
    from paddle_tpu.serving import (CachedLookup, DenseTowerPublisher,
                                    DenseTowerSync, FreshnessProbe,
                                    FrontendConfig, ReplicaLookup,
                                    ServingFrontend, ServingReplica)

    S, D = 8, 4                       # sparse slots per request / dense feats
    xd = int(os.environ.get("SB_DIM", 8))
    n_keys = int(float(os.environ.get("SB_KEYS", 20_000)))
    max_batch = int(os.environ.get("SB_BATCH", 64))
    n_requests = int(float(os.environ.get("SB_REQUESTS", 2000)))
    concurrency = int(os.environ.get("SB_CONCURRENCY", 8))
    n_probes = int(os.environ.get("SB_PROBES", 25))

    rng = np.random.default_rng(0)
    cfg = TableConfig(shard_num=8, accessor_config=AccessorConfig(
        embedx_dim=xd, embedx_threshold=0.0,
        sgd=SGDRuleConfig(initial_range=0.01)))

    with ha.HACluster(num_shards=1, replication=1, sync=False) as cluster:
        train_cli = cluster.client()
        train_cli.create_sparse_table(0, cfg)
        keys = np.arange(n_keys, dtype=np.uint64)
        width = None

        # preload: create + one push so embedx is initialized (the warm
        # population a serving frontend would carry)
        t0 = time.perf_counter()
        chunk = 1 << 15
        for lo in range(0, n_keys, chunk):
            kc = keys[lo:lo + chunk]
            train_cli.pull_sparse(0, kc)
            if width is None:
                width = train_cli._dims(0)[1]
            push = np.zeros((len(kc), width), np.float32)
            push[:, 1] = 1.0
            push[:, 3:] = 0.01 * rng.standard_normal(
                (len(kc), width - 3)).astype(np.float32)
            train_cli.push_sparse(0, kc, push)
        preload_s = time.perf_counter() - t0

        # dense tower: tiny MLP head over [B, S*(1+xd)] emb ++ [B, D]
        x_dim = S * (1 + xd) + D
        params = {"w1": 0.1 * rng.standard_normal((x_dim, 16)).astype(
                      np.float32),
                  "b1": np.zeros(16, np.float32),
                  "w2": 0.1 * rng.standard_normal((16, 1)).astype(np.float32),
                  "b2": np.zeros(1, np.float32)}
        pub = DenseTowerPublisher(train_cli, 7, params)
        pub.publish(params)

        rep = ServingReplica(cluster.store, cluster.job_id, shard=0)
        frontend = None
        try:
            serve_cli = rep.client()
            view = rep.serve_view(0, cfg, client=serve_cli)

            # subscription catch-up: poll until digest-equal (the
            # snapshot path for a late joiner, then the live tail)
            t0 = time.perf_counter()
            prim = cluster.primary(0)
            deadline = t0 + 60
            while True:
                dg = cluster.digests(0, 0).get(prim.endpoint)
                if dg is not None and dg == serve_cli.digest(0)[0]:
                    break
                if time.perf_counter() > deadline:
                    raise TimeoutError("replica never converged to primary")
                time.sleep(0.02)
            catch_up_s = time.perf_counter() - t0

            # feed-triggered dense sync into the jitted infer's params
            live = {"params": jax.device_put(params)}

            def _mlp(p, emb, dense):
                x = jnp.concatenate(
                    [emb.reshape(emb.shape[0], -1), dense], axis=1)
                h = jnp.tanh(x @ p["w1"] + p["b1"])
                return (h @ p["w2"] + p["b2"]).reshape(-1)

            infer_jit = jax.jit(_mlp)

            def infer(emb, dense):
                # micro-batches arrive at whatever size coalesced —
                # pad rows up to the next power of two so XLA compiles
                # a handful of bucket shapes once, not every size (an
                # unpadded jit recompiles per new B: ~200 ms outliers
                # that swamp the p99 this bench exists to measure)
                B = emb.shape[0]
                Bp = 1 << (max(B, 1) - 1).bit_length()
                if Bp != B:
                    emb = np.concatenate(
                        [emb, np.zeros((Bp - B,) + emb.shape[1:],
                                       emb.dtype)])
                    dense = np.concatenate(
                        [dense, np.zeros((Bp - B, dense.shape[1]),
                                         dense.dtype)])
                return np.asarray(
                    infer_jit(live["params"], emb, dense))[:B]

            sync = DenseTowerSync(
                rep, 7, pub.dim, pub.unravel,
                sink=lambda p: live.__setitem__(
                    "params", jax.device_put(p)))

            lookup = CachedLookup(
                HotEmbeddingTier(view, HotTierConfig(
                    capacity=1 << int(np.ceil(np.log2(n_keys * 2))),
                    create_on_miss=False)),
                replica=rep, freshness_budget_s=0.05)
            frontend = ServingFrontend(
                lookup, infer=infer,
                config=FrontendConfig(max_batch=max_batch,
                                      max_delay_us=200, queue_cap=4096,
                                      default_deadline_ms=1000.0))

            # -- phase 1: warm lookup+infer latency (idle feed) --------
            n_prime = min(max(4 * concurrency, 128), n_requests)
            req_keys = rng.integers(0, n_keys,
                                    (n_requests + n_prime, S)).astype(
                np.uint64)
            req_dense = rng.standard_normal(
                (n_requests + n_prime, D)).astype(np.float32)
            # admit the working set + compile every bucket shape once
            # (both jits: the frontend's infer and the CachedLookup
            # gather — each pads to pow-2 buckets, so warm traffic
            # never compiles)
            frontend(req_keys[0], dense=req_dense[0], timeout=60)
            lookup.lookup(keys)
            Bp = 1
            while Bp <= max_batch:
                infer(np.zeros((Bp, S, 1 + xd), np.float32),
                      np.zeros((Bp, D), np.float32))
                lookup.lookup(keys[: Bp * S])
                Bp <<= 1
            nxt = [1]
            mu = threading.Lock()

            def submitter(limit):
                while True:
                    with mu:
                        i = nxt[0]
                        if i >= limit:
                            return
                        nxt[0] += 1
                    frontend.submit(req_keys[i], dense=req_dense[i]) \
                            .result(30)

            def drive(limit):
                threads = [threading.Thread(target=submitter,
                                            args=(limit,),
                                            name=f"sb-submit-{ti}")
                           for ti in range(concurrency)]
                t0 = time.perf_counter()
                for th in threads:
                    th.start()
                for th in threads:
                    th.join()
                return time.perf_counter() - t0

            # priming burst: the first concurrent rounds pay one-time
            # costs no steady-state request ever sees again (thread
            # stack page-ins, allocator growth, XLA thread-pool spin-up)
            drive(n_prime)
            frontend.reset_stats()
            serve_cli.reset_op_counts()
            # a CPython GC pause mid-batch lands straight in the p99 —
            # collect now, hold GC for the bounded measurement window
            # (the same knob a production serving process would tune)
            import gc
            gc.collect()
            gc.disable()
            try:
                warm_wall = drive(n_prime + n_requests)
            finally:
                gc.enable()
            warm_rpc_ops = serve_cli.reset_op_counts()
            st = frontend.stats()

            # -- phase 2: push→servable freshness under writer load ----
            marker_key = np.asarray([np.uint64(1) << np.uint64(41)],
                                    np.uint64)
            train_cli.pull_sparse(0, marker_key)
            direct = ReplicaLookup(serve_cli, 0)
            hot = keys[:4096]
            stop = threading.Event()

            def writer():
                w = np.zeros((len(hot), width), np.float32)
                while not stop.is_set():
                    w[:, 1] = 1.0
                    w[:, 3:] = 0.01 * rng.standard_normal(
                        (len(hot), width - 3)).astype(np.float32)
                    train_cli.push_sparse(0, hot, w)

            probe = FreshnessProbe(timeout_s=5.0)
            marker = [0.0]

            def write():
                marker[0] += 1.0
                mp = np.zeros((1, width), np.float32)
                mp[0, 2] = marker[0]   # click stat: additive, pull col 1
                train_cli.push_sparse(0, marker_key, mp)

            wth = threading.Thread(target=writer, name="sb-writer")
            wth.start()
            try:
                for _ in range(n_probes):
                    probe.measure(write,
                                  lambda: direct.lookup(marker_key)[0, 1],
                                  lambda v, m=marker: v >= m[0])
            finally:
                stop.set()
                wth.join()
            fresh = probe.stats()

            # dense feed really drove the tower at least once
            pub.publish({k: v + 1.0 for k, v in params.items()})
            deadline = time.perf_counter() + 10
            while sync.syncs < 2 and time.perf_counter() < deadline:
                time.sleep(0.01)

            baseline = {}
            online_path = os.path.join(repo, "ONLINE.json")
            if os.path.exists(online_path):
                with open(online_path) as f:
                    oj = json.load(f)
                baseline = {
                    "export_loop_p50_s": oj.get("latency_p50_s"),
                    "export_loop_p95_s": oj.get("latency_p95_s"),
                }
                if fresh["p95_ms"] > 0 and baseline["export_loop_p95_s"]:
                    baseline["freshness_speedup_p95"] = round(
                        baseline["export_loop_p95_s"] * 1e3
                        / fresh["p95_ms"], 1)

            out = {
                "metric": METRIC,
                "value": st["request"]["p99_ms"],
                "unit": "ms",
                "warm": {
                    "request_ms": st["request"],
                    "serve_batch_ms": st["serve_batch"],
                    "requests": st["served"],
                    "qps": round(st["served"] / warm_wall, 1),
                    "avg_batch": st.get("avg_batch", 1.0),
                    "deadline_misses": st["deadline_misses"],
                    "shed": st["shed"],
                    # THE zero-RPC claim: warm requests touched neither
                    # the training PS (by construction — the client only
                    # knows the replica) nor the replica itself
                    "rpc_ops_during_warm": dict(warm_rpc_ops),
                    "rpc_per_request": round(
                        sum(warm_rpc_ops.values()) / max(st["served"], 1),
                        4),
                },
                "freshness": fresh,
                "freshness_failures": fresh["failures"],
                "catch_up_s": round(catch_up_s, 3),
                "dense_syncs": sync.syncs,
                "replica": rep.status(),
                "vs_online_export_loop": baseline,
                "population": n_keys,
                "batch": max_batch,
                "concurrency": concurrency,
                "preload_s": round(preload_s, 2),
                "platform": jax.devices()[0].platform,
                "host_cores": os.cpu_count(),
            }
            return out
        finally:
            if frontend is not None:
                frontend.stop()
            rep.close()


def main() -> None:
    try:
        rec = run()
    except Exception as e:  # noqa: BLE001 — one-JSON-line contract
        import traceback

        traceback.print_exc(file=sys.stderr)
        rec = {"metric": METRIC, "value": 0.0,
               "error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
