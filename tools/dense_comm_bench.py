"""Dense-DP comm micro-bench: the compression degradation ladder.

Measures one MLP train step on a pure-dp mesh over every rung of the
comm ladder — fused+int8 → fused+bf16 → fused fp32 → unfused per-tensor
baseline — emitting step time AND the compiled program's collective
bytes/step (tools/hlo_bytes.py, post-optimization HLO: what this
backend actually puts on the wire; note XLA CPU float-normalization
legalizes bf16 collectives to f32, so the bf16 rung only narrows on
TPU-class backends — the int8 rung narrows everywhere).

The headline ``value`` is the step time of the FIRST rung that builds
and runs (the degradation-ladder contract: a novel compile failure in a
quantized path costs a rung, not the number); every rung's result (or
error) is recorded under ``ladder``.

Standalone: prints exactly ONE JSON line (driver contract). Importable:
``run()`` returns the record — bench.py embeds it in its single
emission under ``dense_comm``. Env knobs: DCB_BATCH, DCB_STEPS,
DCB_WARMUP, DCB_HIDDEN, DCB_LAYERS, DCB_BUCKET_MB, DCB_BLOCK.
"""

import json
import os
import sys
import time

METRIC = "dense_dp_comm_step_ms"


def run() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import hlo_bytes

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed.comm_fusion import CommFusionConfig
    from paddle_tpu.parallel import SpmdTrainer
    from jax.sharding import Mesh

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        return {"metric": METRIC, "value": 0.0,
                "error": f"need >=2 devices for a dp mesh, have {n}"}
    mesh = Mesh(np.array(devs), ("dp",))

    batch = int(os.environ.get("DCB_BATCH", 1024))
    steps = int(os.environ.get("DCB_STEPS", 15))
    warmup = max(1, int(os.environ.get("DCB_WARMUP", 3)))
    hidden = int(os.environ.get("DCB_HIDDEN", 256))
    layers = int(os.environ.get("DCB_LAYERS", 3))
    bucket_mb = float(os.environ.get("DCB_BUCKET_MB", 4.0))
    block = int(os.environ.get("DCB_BLOCK", 256))

    def fresh():
        pt.seed(0)
        mods = [nn.Linear(32, hidden), nn.ReLU()]
        for _ in range(layers - 1):
            mods += [nn.Linear(hidden, hidden), nn.ReLU()]
        mods += [nn.Linear(hidden, 8)]
        return nn.Sequential(*mods)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 32)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 8, batch).astype(np.int32))

    rungs = [
        ("fused+int8", CommFusionConfig(bucket_mb=bucket_mb, quant="int8",
                                        block_size=block)),
        ("fused+bf16", CommFusionConfig(bucket_mb=bucket_mb, quant="bf16")),
        ("fused+fp32", CommFusionConfig(bucket_mb=bucket_mb)),
        ("unfused", CommFusionConfig(fuse=False)),
    ]
    ladder, errors = [], []
    headline = None
    for name, comm in rungs:
        try:
            tr = SpmdTrainer(fresh(), optimizer.SGD(0.1),
                             nn.functional.cross_entropy, mesh, comm=comm)
            compiled = tr._step.lower(
                tr.state, tr.opt_state, jax.random.key(0), (x,), (y,)
            ).compile()
            rep = hlo_bytes.report_compiled(compiled, num_devices=n)
            grad = hlo_bytes.grad_collectives(rep)
            wire = sum(c["wire_bytes"] for c in grad)
            for _ in range(warmup):
                loss = tr.train_step(x, y)
            float(loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = tr.train_step(x, y)
            float(loss)
            dt = (time.perf_counter() - t0) / steps
            rung = {"mode": name, "step_ms": round(dt * 1e3, 3),
                    "collective_wire_bytes_per_step": int(wire),
                    "n_grad_collectives": len(grad),
                    "dtypes": sorted({c["dtype"] for c in grad})}
            ladder.append(rung)
            if headline is None:
                headline = rung
        except Exception as e:  # noqa: BLE001 — degrade, don't die
            msg = f"{name}: {type(e).__name__}: {e}"[:160]
            errors.append(msg)
            ladder.append({"mode": name, "error": msg})
    if headline is None:
        return {"metric": METRIC, "value": 0.0, "error": "; ".join(errors),
                "platform": devs[0].platform, "devices": n}
    out = {"metric": METRIC, "value": headline["step_ms"], "unit": "ms",
           "mode": headline["mode"],
           "collective_wire_bytes_per_step":
               headline["collective_wire_bytes_per_step"],
           "n_grad_collectives": headline["n_grad_collectives"],
           "platform": devs[0].platform, "devices": n, "ladder": ladder}
    if errors:
        out["degraded_from"] = errors
    return out


def main() -> None:
    try:
        rec = run()
    except Exception as e:  # noqa: BLE001 — one-JSON-line contract
        import traceback

        traceback.print_exc(file=sys.stderr)
        rec = {"metric": METRIC, "value": 0.0,
               "error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
