"""Game-day chaos schedule for the declarative control plane (ISSUE 20
acceptance; the committed GAMEDAY.json artifact).

A game day is a TIMED sequence of spec perturbations and armed
faultpoints run against a live cluster — the fire-drill discipline:
every transition is driven by writing desired state (never by calling
primitives), and the drill passes only when the reconciler's journal
closes the loop on every step. The stock schedule:

1. bring up a 2-shard HACluster (sync ×2) + ReshardController +
   a 4-member serving fleet under a RolloutManager, all behind ONE
   :class:`~paddle_tpu.ps.reconcile.Reconciler`; seed the PS table and
   record the content digest; start background pull traffic;
2. **grow-under-fire**: arm a kill-shard faultpoint on the shard-0
   primary (fires mid-bootstrap, during the grow's snapshot save),
   then propose ``shards: 4`` — the coordinator promotes the backup
   WHILE the reconciler's transition is in flight, and the transition
   still converges (the observed-repair event lands in the journal);
3. **canary open** via spec (version 2 at an exact fraction) — the
   router split is counted request-by-request and must match the band
   arithmetic exactly;
4. **canary rollback** via spec (clear the canary) — the fleet returns
   to the baseline version, digest-pinned;
5. **shrink back** to 2 shards via spec;
6. final: the table content digest is bit-identical to the seed, the
   background traffic saw zero errors, and every schedule step
   converged within its deadline.

Standalone: prints exactly ONE JSON line (driver contract) and writes
GAMEDAY.json (env GAMEDAY_OUT overrides). Env knobs: GAMEDAY_ROWS,
GAMEDAY_BLOCKS.
"""

import json
import os
import random
import sys
import time

METRIC = "gameday"


def run(out_path: str) -> dict:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import numpy as np

    from paddle_tpu.io.fs import crc32c
    from paddle_tpu.ps import ha, rpc
    from paddle_tpu.ps.reconcile import Reconciler
    from paddle_tpu.ps.reshard import ReshardController
    from paddle_tpu.ps.table import TableConfig
    from paddle_tpu.serving import (DenseModel, FrontendConfig,
                                    RolloutConfig, RolloutManager,
                                    RouterConfig, ServingFrontend,
                                    ServingRouter)
    from paddle_tpu.core import sync as _sync

    rows = int(os.environ.get("GAMEDAY_ROWS", 20000))
    blocks = int(os.environ.get("GAMEDAY_BLOCKS", 200))
    dim = 16

    # -- serving-side stubs (router-protocol members over real
    # frontends; the rollout lifecycle needs real model slots) ---------
    class _Lookup:
        def lookup(self, keys):
            k = keys.astype(np.float64)
            return np.stack([k, k + 0.5], axis=1).astype(np.float32)

    class _Member:
        def __init__(self, name, flat):
            self.endpoint = name
            self.lookup = _Lookup()
            self.frontend = ServingFrontend(
                self.lookup, config=FrontendConfig(
                    max_batch=8, max_delay_us=100, queue_cap=256),
                replica_label=name)
            self.model = DenseModel(lambda f: f, flat.copy(), version=1,
                                    sink=lambda p: None)

        @property
        def healthy(self):
            return not self.frontend.stopped

        def stop(self):
            self.frontend.stop()

    wall0 = time.time()  # graftlint: ignore[time-time] — artifact wall timestamps
    cluster = ha.HACluster(num_shards=2, replication=2, sync=True,
                           job_id="gameday")
    members = []
    router = None
    stop_traffic = _sync.Event()
    traffic = {"pulls": 0, "errors": 0}
    schedule = []
    try:
        client = cluster.client()
        client.create_sparse_table(
            0, TableConfig(table_id=0, shard_num=8, accessor="ctr"))
        keys = np.arange(1, rows + 1, dtype=np.uint64)
        for lo in range(0, rows, 1 << 14):
            client.pull_sparse(0, keys[lo:lo + (1 << 14)])
        cluster.drain()
        seed_digest = crc32c(
            np.ascontiguousarray(client.pull_sparse(0, keys)).tobytes())

        flat1 = np.arange(dim, dtype=np.float32)
        flat2 = flat1 + 2.0
        members = [_Member(f"gd{i}", flat1) for i in range(4)]
        router = ServingRouter(RouterConfig(), rng=random.Random(0))
        for m in members:
            router.attach(m)
        rollout = RolloutManager(lambda: members, router,
                                 RolloutConfig(canary_members=1))
        v1 = rollout.register_baseline(flat1)
        for m in members:
            m.model.set(v1, flat1)
        versions = {2: flat2}

        ctrl = ReshardController(cluster)
        rec = Reconciler(cluster, ctrl, rollout=rollout,
                         model_source=lambda v: versions[v],
                         poll_s=0.05).start()
        rollout.set_proposer(rec)

        # -- background pull traffic (reads only: content must stay
        # bit-stable through every transition) -------------------------
        def _pull_loop():
            rng = np.random.default_rng(7)
            # share the seeding client (it holds the table catalog);
            # the main thread only touches it before the puller starts
            # and after it stops
            cli = client
            while not stop_traffic.is_set():
                batch = rng.choice(keys, size=64, replace=False)
                try:
                    cli.pull_sparse(0, np.sort(batch).astype(np.uint64))
                    traffic["pulls"] += 1
                except Exception:
                    traffic["errors"] += 1
                time.sleep(0.002)

        puller = _sync.Thread(target=_pull_loop, daemon=True,
                              name="gameday-puller")
        puller.start()

        def step(name, deadline_s=60.0, **info):
            t0 = time.time()  # graftlint: ignore[time-time] — artifact wall timestamps
            entry = {"step": name, "t_offset_s": round(t0 - wall0, 3),
                     **info}
            schedule.append(entry)
            return entry, t0

        # -- 1. grow-under-fire ----------------------------------------
        entry, t0 = step("grow_under_fire", shards=4, kill="shard0-primary")
        victim = cluster.primary(0)
        victim.server.arm_fault("kill-shard", cmd=rpc._SAVE_ALL, after=1)
        spec = rec.propose_shards(4, origin="gameday")
        entry["spec_version"] = spec.version
        assert rec.wait_converged(90.0), (
            f"grow 2->4 never converged (journal: {list(rec.events)})")
        assert cluster.num_shards == 4, cluster.num_shards
        entry["converged"] = True
        entry["elapsed_s"] = round(time.time() - t0, 3)  # graftlint: ignore[time-time] — artifact wall timestamps
        promotions = [e for e in rec.events if e["kind"] == "observed_repair"]
        entry["promotions"] = len(promotions)

        # -- 2. canary open via spec -----------------------------------
        entry, t0 = step("canary_open", version=2, fraction=0.25)
        spec = rec.propose_canary(2, 0.25, origin="gameday")
        entry["spec_version"] = spec.version
        assert rec.wait_converged(30.0), list(rec.events)
        assert rollout.canary_open() == 2
        # exact split: count request routing against the band arithmetic
        expect = sum(router.in_canary_band(b, 0.25) for b in range(blocks))
        for b in range(blocks):
            rr = router.submit(
                np.arange(b << 6, (b << 6) + 8, dtype=np.uint64),
                deadline_ms=5000)
            rr.result(10)
        counts = router.stats()["version_counts"]
        assert counts.get("2", 0) == expect, (counts, expect)
        assert counts.get("1", 0) == blocks - expect, (counts, expect)
        entry["converged"] = True
        entry["split"] = {"canary": expect, "stable": blocks - expect}
        entry["elapsed_s"] = round(time.time() - t0, 3)  # graftlint: ignore[time-time] — artifact wall timestamps

        # -- 3. canary rollback via spec -------------------------------
        entry, t0 = step("canary_rollback")
        spec = rec.propose_rollback(reason="gameday drill",
                                    origin="gameday")
        entry["spec_version"] = spec.version
        assert rec.wait_converged(30.0), list(rec.events)
        assert rollout.canary_open() is None
        assert all(v == v1 for v, _ in rollout.fleet_versions().values())
        entry["converged"] = True
        entry["elapsed_s"] = round(time.time() - t0, 3)  # graftlint: ignore[time-time] — artifact wall timestamps

        # -- 4. shrink back --------------------------------------------
        entry, t0 = step("shrink", shards=2)
        spec = rec.propose_shards(2, origin="gameday")
        entry["spec_version"] = spec.version
        assert rec.wait_converged(90.0), list(rec.events)
        # the ROUTED topology is back to 2; the retirees linger in
        # cluster.servers for the lame-duck window before stopping
        assert len(cluster.routing.read()[1]) == 2
        entry["converged"] = True
        entry["elapsed_s"] = round(time.time() - t0, 3)  # graftlint: ignore[time-time] — artifact wall timestamps

        # -- close the loop --------------------------------------------
        stop_traffic.set()
        puller.join(timeout=10)
        final_digest = crc32c(
            np.ascontiguousarray(client.pull_sparse(0, keys)).tobytes())
        digest_ok = bool(final_digest == seed_digest)
        assert digest_ok, (seed_digest, final_digest)
        assert traffic["errors"] == 0, traffic
        assert all(s.get("converged") for s in schedule), schedule
        journal = list(rec.events)
        transitions = [e for e in journal if e["kind"] == "transition"]
        rec.stop()

        out = {
            "metric": METRIC,
            "rows": rows,
            "schedule": schedule,
            "transitions": transitions,
            "journal": journal,
            "spec_log": rec.spec_store.log(),
            "promotions": len([e for e in journal
                               if e["kind"] == "observed_repair"]),
            "digest_ok": digest_ok,
            "seed_digest": int(seed_digest),
            "final_digest": int(final_digest),
            "traffic": dict(traffic),
            "shards_final": len(cluster.routing.read()[1]),
            "wall_s": round(time.time() - wall0, 2),  # graftlint: ignore[time-time] — artifact wall timestamps
        }
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(out, f, indent=1, sort_keys=True)
        return out
    finally:
        stop_traffic.set()
        for m in members:
            m.stop()
        if router is not None:
            router.stop()
        cluster.stop()


def main() -> int:
    out = os.environ.get("GAMEDAY_OUT", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "GAMEDAY.json"))
    try:
        rec = run(out)
        rec = {k: v for k, v in rec.items()
               if k not in ("transitions", "journal", "spec_log")}
    except Exception as e:  # one-JSON-line driver contract
        rec = {"metric": METRIC, "error": f"{type(e).__name__}: {e}"}
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
