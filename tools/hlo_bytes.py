"""hlo_bytes — per-collective element types and byte counts from compiled HLO.

The proof layer for comm compression: numeric tests cannot tell a wire
narrowing from a cast round-trip upstream of an fp32 psum (the
FP16AllReduce bug class this PR retires), but the compiled HLO can.
This walks an XLA module's text (``jit(f).lower(...).compile()
.as_text()``) and reports every collective with:

- ``op``            all-reduce | reduce-scatter | all-gather | all-to-all
                    | collective-permute (``-start`` async forms folded in)
- ``dtype``/``shape``/``result_bytes``  from the instruction's result
  (tuple results summed; for reduce-scatter the per-rank output)
- ``operand_bytes`` the payload entering the collective
- ``group_size``    parsed from ``replica_groups`` (explicit or iota form)
- ``wire_bytes``    ring-estimate of bytes a participant moves:
                    all-reduce 2(N-1)/N·payload, reduce-scatter /
                    all-to-all (N-1)/N·operand, all-gather
                    (N-1)/N·result, permute = operand
- ``computation``/``in_conditional``  whether the collective lives in
  (or is only reachable through) a conditional branch — how we prove
  GradientMerge's held steps skip the dp reduction entirely.

Library: ``report(hlo_text)``, ``report_compiled(compiled)``,
``grad_collectives(rep, min_bytes=1024)`` (drops scalar loss/flag
psums). CLI: ``python tools/hlo_bytes.py FILE [--min-bytes N]`` (or
``-`` for stdin) prints the JSON summary.
"""

from __future__ import annotations

import json
import re
import sys
from typing import Any, Dict, List, Optional

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1,
    "f8e5m2": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_COLLECTIVES = ("all-reduce", "reduce-scatter", "all-gather", "all-to-all",
                "collective-permute")

# one typed buffer: dtype[d0,d1,...]{layout} — layout/suffixes optional
_SHAPE_RE = re.compile(r"\b([a-z0-9]+)\[([0-9,]*)\]")
# an instruction line: %name = <result-type> opcode(...)
_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*?)\s+"
                       r"([a-z][\w\-]*)\(")
# computation header: [ENTRY] %name (params) -> ret {  (params may hold
# nested tuple parens, hence the greedy match anchored on the arrow)
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->")
_GROUPS_EXPLICIT_RE = re.compile(r"replica_groups=\{\{([^}]*)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_CALLED_RE = re.compile(
    r"(?:to_apply|branch_computations|true_computation|false_computation|"
    r"condition|body|calls|called_computations)="
    r"\{?%?([\w.\-]+(?:,\s*%?[\w.\-]+)*)\}?")
_COND_REFS_RE = re.compile(
    r"(?:branch_computations=\{([^}]*)\}|true_computation=%?([\w.\-]+)|"
    r"false_computation=%?([\w.\-]+))")


def _buffer_bytes(type_str: str) -> tuple:
    """(total bytes, first dtype, first shape) over every typed buffer in
    a result-type string (handles tuples)."""
    total, dtype, shape = 0, None, None
    for m in _SHAPE_RE.finditer(type_str):
        d, dims = m.group(1), m.group(2)
        if d not in _DTYPE_BYTES:
            continue
        n = 1
        if dims:
            for x in dims.split(","):
                n *= int(x)
        total += n * _DTYPE_BYTES[d]
        if dtype is None:
            dtype, shape = d, [int(x) for x in dims.split(",")] if dims else []
    return total, dtype, shape


def _group_size(line: str, default: int) -> int:
    m = _GROUPS_EXPLICIT_RE.search(line)
    if m:
        ids = [x for x in m.group(1).split(",") if x.strip() != ""]
        return max(len(ids), 1)
    m = _GROUPS_IOTA_RE.search(line)
    if m:  # [G,S]<=[N]: G groups of size S
        return max(int(m.group(2)), 1)
    return default


def _wire_bytes(op: str, operand: int, result: int, n: int) -> float:
    if n <= 1:
        return 0.0
    f = (n - 1) / n
    if op == "all-reduce":
        return 2.0 * f * result
    if op == "reduce-scatter":
        return f * operand
    if op == "all-gather":
        return f * result
    if op == "all-to-all":
        return f * operand
    return float(operand)   # collective-permute


def report(hlo_text: str, num_devices: Optional[int] = None) -> Dict[str, Any]:
    """Parse one HLO module's text into the collective report."""
    lines = hlo_text.splitlines()
    current = "entry"
    calls: Dict[str, set] = {}
    cond_roots: set = set()
    collectives: List[Dict[str, Any]] = []

    for line in lines:
        cm = _COMP_RE.match(line)
        if cm and line.rstrip().endswith("{"):
            current = cm.group(1)
            calls.setdefault(current, set())
        im = _INSTR_RE.match(line)
        if not im:
            continue
        result_type, opcode = im.group(2), im.group(3)
        for tm in _CALLED_RE.finditer(line):
            for name in tm.group(1).split(","):
                calls.setdefault(current, set()).add(name.strip().lstrip("%"))
        if opcode == "conditional":
            for gm in _COND_REFS_RE.finditer(line):
                blob = gm.group(1) or gm.group(2) or gm.group(3) or ""
                for name in blob.split(","):
                    name = name.strip().lstrip("%")
                    if name:
                        cond_roots.add(name)
        base = opcode[:-6] if opcode.endswith("-start") else opcode
        if base not in _COLLECTIVES or opcode.endswith("-done"):
            continue
        res_bytes, dtype, shape = _buffer_bytes(result_type)
        # operand buffers: typed buffers inside the (...) args
        args = line[im.end():]
        op_bytes, _, _ = _buffer_bytes(args.split(", channel_id")[0]
                                       .split(", replica_groups")[0])
        if base == "all-reduce" and op_bytes == 0:
            op_bytes = res_bytes
        n = _group_size(line, num_devices or 1)
        collectives.append({
            "op": base, "dtype": dtype, "shape": shape,
            "result_bytes": res_bytes, "operand_bytes": op_bytes or res_bytes,
            "group_size": n,
            "wire_bytes": _wire_bytes(base, op_bytes or res_bytes,
                                      res_bytes, n),
            "computation": current,
        })

    # a computation is "conditional" if it is a cond branch or reachable
    # only through one (transitive closure over the call graph)
    in_cond = set()
    frontier = set(cond_roots)
    while frontier:
        c = frontier.pop()
        if c in in_cond:
            continue
        in_cond.add(c)
        frontier |= calls.get(c, set())
    for c in collectives:
        c["in_conditional"] = c["computation"] in in_cond

    totals: Dict[str, float] = {}
    by_dtype: Dict[str, float] = {}
    for c in collectives:
        totals[c["op"]] = totals.get(c["op"], 0.0) + c["wire_bytes"]
        if c["dtype"]:
            by_dtype[c["dtype"]] = by_dtype.get(c["dtype"], 0.0) + c["wire_bytes"]
    return {
        "n_collectives": len(collectives),
        "collectives": collectives,
        "wire_bytes_total": sum(c["wire_bytes"] for c in collectives),
        "wire_bytes_by_op": totals,
        "wire_bytes_by_dtype": by_dtype,
    }


def report_compiled(compiled, num_devices: Optional[int] = None) -> Dict[str, Any]:
    """Report for a jax ``Compiled`` object (``jit(f).lower(...)
    .compile()``); concatenates every module's text."""
    try:
        text = compiled.as_text()
    except AttributeError:   # raw module list
        text = "\n".join(m.to_string() for m in compiled.hlo_modules())
    return report(text, num_devices=num_devices)


def grad_collectives(rep: Dict[str, Any], min_bytes: int = 1024
                     ) -> List[Dict[str, Any]]:
    """The data-plane collectives: big enough to be gradient/param
    traffic (drops the scalar loss pmean / AMP finite-flag psums)."""
    return [c for c in rep["collectives"]
            if c["op"] != "collective-permute"
            and max(c["result_bytes"], c["operand_bytes"]) >= min_bytes]


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="per-collective bytes from HLO")
    ap.add_argument("file", help="HLO text file, or - for stdin")
    ap.add_argument("--min-bytes", type=int, default=0,
                    help="only report collectives moving >= this many bytes")
    ap.add_argument("--devices", type=int, default=None,
                    help="device count fallback when replica_groups is absent")
    args = ap.parse_args(argv)
    text = sys.stdin.read() if args.file == "-" else open(args.file).read()
    rep = report(text, num_devices=args.devices)
    if args.min_bytes:
        rep["collectives"] = [c for c in rep["collectives"]
                              if max(c["result_bytes"], c["operand_bytes"])
                              >= args.min_bytes]
        rep["n_collectives"] = len(rep["collectives"])
    json.dump(rep, sys.stdout, indent=1)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
