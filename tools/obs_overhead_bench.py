"""Obs-plane overhead gate: the ALWAYS-ON layer (metrics handles +
ISSUE 10 sampler thread + SLO watchdog) vs metrics-compiled-out on the
DeepFM stream step (CI budget: ≤ 2 %), plus the tracing-off wire
contract (the RPC header carries EXACTLY the fixed 16-byte context
field, zeroed) and the job-wide snapshot acceptance (≥ 3 processes,
per-table wire bytes + observed density).

The ON arm now runs exactly what a production trainer runs
continuously: live registry handles AND a JobCollector sampling the
whole job (local snapshot + one kObsSnap per shard) every
OOB_SAMPLE_PERIOD seconds with the stock SLO rule set evaluated per
tick. The sampler's kObsSnap RPCs share the cluster with both arms'
training traffic — deliberately: that contention IS part of the
always-on cost the 2% budget must cover.

Methodology (the chaos_ps interleaved-A/B discipline): TWO identical
seeded DeepFM stream trainers (SYNC communicator — inline pull/push
per step, no background-thread scheduling jitter in the measurement)
against ONE shared real 2-shard RPC PS cluster — arm A's client built
with the registry live (FLAGS_obs_metrics default on), arm B's under
FLAGS_obs_metrics=0 so every pre-bound handle is the shared null (the
"compiled out" baseline; handles bind at client construction, so the
flag flip at build time is the whole story). Sharing the cluster
matters: separate per-arm clusters were observed to pick up DURABLE
±5% thread/memory-placement bias on a 2-core box, swamping the
effect; with one cluster the arms differ in exactly the thing being
measured — the Python-side metric handles.

Estimator, inside one measurement PASS: epochs interleave A/B for
``rounds`` rounds, alternating which arm runs first (no
first-in-round bias); the first rounds ride the process's settle
transient and are dropped; the rest pair up as per-round ratios
(on_i / off_i — the arms share the round's weather) aggregated by a
trimmed mean. Across passes: this box is a VM with noisy neighbors
(whole passes observed ±30% perturbed at zero local load), so the
reported value is the MIN estimate over up to OOB_PASSES passes with
early stop once a pass lands clearly inside the budget — the budget
bounds the quiet-weather overhead. Tracing stays OFF in both arms
(its own cost is the one module-bool check per span site; the gate's
wire assertion covers the header side).

Standalone: prints exactly ONE JSON line (driver contract). Env knobs:
OOB_BATCH, OOB_STEPS, OOB_ROUNDS, OOB_PASSES, OOB_SLOTS, OOB_NID,
OOB_SAMPLE_PERIOD.
"""

import json
import os
import sys
import time

METRIC = "obs_overhead_pct"


def _make_dataset(S, D, batch, steps, nid, seed=0):
    """Seeded synthetic CTR stream with the learnable-signal recipe
    (small id pool, `(ids % 5 == 0).sum() + dense[0] > 1` labels) —
    shared with tools/obs_trace_demo.py so the bench and the committed
    OBS_TRACE.json artifact can never desynchronize on data shape."""
    import numpy as np

    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc

    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(steps * batch):
        ids = rng.integers(0, nid, S)
        dense = rng.normal(size=D)
        label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
        lines.append(" ".join([f"1 {v}" for v in ids]
                              + [f"1 {v:.4f}" for v in dense]
                              + [f"1 {label}"]))
    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1)
              for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1)
                for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])
    ds = InMemoryDataset(slots, seed=0)
    ds.load_from_lines(lines)
    return ds


def run() -> dict:
    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.core.flags import get_flags, set_flags
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.obs import aggregate, registry, slo, timeseries, trace
    from paddle_tpu.ps import ha, rpc
    from paddle_tpu.ps.communicator import SyncCommunicator
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer
    from paddle_tpu.ps.table import TableConfig

    S = int(os.environ.get("OOB_SLOTS", 8))
    D = 4
    # the REAL DeepFM shape (CtrConfig defaults: 400x400x400 tower),
    # not a toy tower: representative of the step the 2% budget
    # protects, and heavy enough that scheduler noise on a 2-core box
    # stays small relative to the step
    batch = int(os.environ.get("OOB_BATCH", 512))
    steps = int(os.environ.get("OOB_STEPS", 6))
    rounds = int(os.environ.get("OOB_ROUNDS", 20))
    max_passes = int(os.environ.get("OOB_PASSES", 3))
    ds = _make_dataset(S, D, batch, steps,
                       nid=int(os.environ.get("OOB_NID", 1500)))

    registry.set_process_role("trainer")

    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    endpoints = [f"127.0.0.1:{s.port}" for s in servers]

    def build(metrics_on):
        was = get_flags(["obs_metrics"])["obs_metrics"]
        set_flags({"obs_metrics": bool(metrics_on)})
        try:
            client = rpc.RpcPsClient(endpoints)
            client.create_sparse_table(  # idempotent server-side
                0, TableConfig(table_id=0, shard_num=4, accessor="ctr"))
            comm = SyncCommunicator(client)
            comm.start()
            pt.seed(0)
            tr = CtrStreamTrainer(
                DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D,
                                 embedx_dim=8)),
                optimizer.Adam(1e-2), None, embedx_dim=8,
                sparse_slots=[f"s{i}" for i in range(S)],
                dense_slots=[f"d{i}" for i in range(D)],
                label_slot="label", communicator=comm, table_id=0)
        finally:
            set_flags({"obs_metrics": was})
        return client, comm, tr

    arms = {"on": build(True), "off": build(False)}
    # the ISSUE 10 always-on layer rides the ON arm for the WHOLE
    # measurement (warm-up included): job sampler + stock SLO rules.
    # Thresholds are production-shaped — nothing fires on a healthy
    # run, so the measured cost is evaluation, not alert handling.
    sampler = timeseries.JobCollector(
        client=arms["on"][0],
        period_s=float(os.environ.get("OOB_SAMPLE_PERIOD", 0.25)))
    watchdog = slo.SloWatchdog(sampler.ring, slo.default_rules())
    watchdog.attach(sampler)
    sampler.start()
    try:
        # warm-up: compile + row creation + the process's slow settle
        # (page cache / allocator arenas / predictors — measured ~45 →
        # 26 ms/step over the first half-dozen epochs on this box; one
        # warm epoch is NOT enough, and a transient straddling a round
        # poisons its pair)
        for _ in range(3):
            for name in ("on", "off"):
                _, comm, tr = arms[name]
                tr.train_from_dataset(ds, batch_size=batch)
                comm.barrier()

        import gc

        def measure_pass():
            """One interleaved A/B pass → (overhead %, min on ms, min
            off ms). PAIRED: each round yields on_i/off_i (the arms
            share the round's weather), order alternates per round
            (no first-in-round bias), the first rounds ride the settle
            transient → dropped, and the remaining ratios aggregate as
            a TRIMMED mean (top/bottom 2 discarded — scheduler
            outliers land in one arm of a round)."""
            gc.collect()
            gc.disable()  # GC pauses land in one arm's epoch, not both
            per_round = {"on": [], "off": []}
            try:
                for i in range(rounds):
                    order = ("on", "off") if i % 2 == 0 else ("off", "on")
                    for name in order:
                        _, comm, tr = arms[name]
                        t0 = time.perf_counter()
                        r = tr.train_from_dataset(ds, batch_size=batch)
                        comm.barrier()
                        dt = time.perf_counter() - t0
                        per_round[name].append(
                            dt / max(r["steps"], 1) * 1e3)
            finally:
                gc.enable()
            drop = min(rounds // 4, 4)
            ratios = sorted(a / b for a, b in
                            zip(per_round["on"][drop:],
                                per_round["off"][drop:]))
            trim = 2 if len(ratios) > 8 else 0
            kept = ratios[trim:len(ratios) - trim] if trim else ratios
            return ((sum(kept) / len(kept) - 1.0) * 100.0,
                    min(per_round["on"]), min(per_round["off"]))

        # this box is a VM with noisy neighbors: whole PASSES get
        # perturbed ±30% with zero local load, and no within-pass
        # statistic survives that. The budget bounds the QUIET-WEATHER
        # overhead, so take the MIN estimate over up to OOB_PASSES
        # passes, stopping early once a pass lands clearly inside it.
        overhead_pct, ms_on, ms_off = measure_pass()
        passes = 1
        while overhead_pct > 1.0 and passes < max_passes:
            est, on_ms, off_ms = measure_pass()
            passes += 1
            if est < overhead_pct:
                overhead_pct, ms_on, ms_off = est, on_ms, off_ms

        # -- wire contract: the header is fixed-size with tracing off ----
        hdr_bytes = ha._HDR.size
        ctx_bytes = trace.WIRE_CONTEXT_BYTES
        assert not trace.tracing_enabled()
        assert trace.wire_context() == (0, 0)  # off → zeroed fixed field

        # -- job-wide snapshot acceptance (arm A client) -----------------
        client_on, _, _ = arms["on"]
        job = aggregate.job_snapshot(client_on)
        wire = {f"{r['labels']['table']}/{r['labels']['dir']}": r["value"]
                for r in job["metrics"]["ps_server_wire_bytes"]["series"]}
        dens = {f"{r['labels']['table']}/{r['labels']['dir']}":
                round(r["ewma"], 4)
                for r in job["metrics"]["ps_client_density"]["series"]}
        return {
            "metric": METRIC,
            "value": round(overhead_pct, 3),
            "step_ms_metrics_on": round(ms_on, 3),
            "step_ms_metrics_off": round(ms_off, 3),
            "rounds": rounds,
            "passes": passes,
            "steps_per_round": steps,
            "sampler_ticks": sampler.ticks,
            "sampler_errors": sampler.errors,
            "watchdog_rules": len(watchdog.rules),
            "watchdog_evaluations": watchdog.evaluations,
            "alerts_fired": len(watchdog.alerts()),
            "wire_header_bytes": hdr_bytes,
            "trace_ctx_bytes": ctx_bytes,
            "tracing_off_extra_header_bytes": hdr_bytes - 28 - ctx_bytes,
            "job_processes": len(job["processes"]),
            "roles": [p.get("role") for p in job["processes"]],
            "server_wire_bytes": wire,
            "client_density": dens,
        }
    finally:
        sampler.stop()
        for client, comm, _ in arms.values():
            try:
                comm.stop()
            except Exception:
                pass
            client.close()
        for s in servers:
            s.stop()
            s.close()


def main() -> int:
    try:
        rec = run()
    except Exception as e:  # one-JSON-line driver contract
        rec = {"metric": METRIC, "error": f"{type(e).__name__}: {e}"}
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
