"""Merge per-worker profiler traces into one chrome://tracing view.

Reference counterpart: ``tools/timeline.py`` — it collects each
worker's profiler dump and emits a single chrome-trace JSON with one
process lane per worker. Workers write chrome-trace JSON directly
(``paddle_tpu.core.profiler.export_chrome_tracing`` or
``paddle_tpu.obs.trace.export_chrome_trace``); this tool merges them
into one timeline for chrome://tracing / the perfetto UI.

Two merge corrections (ISSUE 8 satellite):

- **Clock alignment.** Every exporter stamps its blob with
  ``clockSyncUs`` — the process's wall-clock anchor for its
  ``perf_counter`` timestamps. Raw per-host monotonic clocks have
  arbitrary origins, so merging on them interleaves lanes nonsensically
  (a worker booted 100 s later appears 100 s "ahead"). The merge
  shifts each file's events by its anchor relative to the EARLIEST
  anchor, putting every lane on one shared epoch while keeping the
  numbers small. Files without an anchor (pre-obs exports) merge
  unshifted with a warning.
- **Pid de-conflict.** A single input may legitimately carry SEVERAL
  pid lanes (the obs trace demo emits trainer + one lane per PS
  shard). Each DISTINCT (file, original pid) pair maps to a fresh
  output pid — lanes never collide across files and multi-lane files
  keep their internal structure (the old behavior flattened every
  event onto the file's index, silently merging a file's lanes).

SLO alerts (ISSUE 10 satellite): a blob may carry a top-level
``sloAlerts`` list (the ``obs.slo.SloWatchdog`` alert-log dicts, wall
seconds in ``t``). Each renders as a GLOBAL INSTANT event
(``ph: "i"``, ``s: "g"`` — the full-height line chrome://tracing draws)
named ``ALERT <rule>``, so a triage sees "the watchdog fired HERE"
against the span lanes; a ``cleared_t`` adds the matching
``CLEAR <rule>`` instant. Alert timestamps are already wall-anchored,
so they shift by the shared base only (not the blob's own anchor).

Usage:
    python tools/timeline.py worker0.json worker1.json -o merged.json
"""

import argparse
import json
import os
import sys


def merge_traces(paths, output):
    blobs = []
    for path in paths:
        with open(path) as f:
            blob = json.load(f)
        # both legal chrome-trace forms: {"traceEvents": [...]} or [...]
        evs = blob if isinstance(blob, list) else blob.get("traceEvents", [])
        sync = None if isinstance(blob, list) else blob.get("clockSyncUs")
        alerts = [] if isinstance(blob, list) else blob.get("sloAlerts", [])
        blobs.append((path, evs, sync, alerts))

    anchors = [s for _, _, s, _ in blobs if s is not None]
    base = min(anchors) if anchors else 0.0
    for path, _, sync, _ in blobs:
        if sync is None and anchors:
            print(f"warning: {path} has no clockSyncUs anchor — its lane "
                  "merges unshifted and may interleave on a raw "
                  "monotonic clock", file=sys.stderr)

    events = []
    pid_map = {}  # (file index, original pid) → output pid

    def out_pid(fi, orig):
        key = (fi, orig)
        if key not in pid_map:
            pid_map[key] = len(pid_map)
        return pid_map[key]

    for fi, (path, evs, sync, alerts) in enumerate(blobs):
        shift = (sync - base) if sync is not None else 0.0
        name = os.path.splitext(os.path.basename(path))[0]
        named_lanes = set()
        for ev in evs:
            ev = dict(ev)
            pid = out_pid(fi, ev.get("pid", 0))
            ev["pid"] = pid
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                named_lanes.add(pid)
            else:
                for k in ("ts",):
                    if k in ev:
                        ev[k] = ev[k] + shift
            events.append(ev)
        for a in alerts:
            # alert timestamps are wall seconds already — only the
            # shared base applies, never the blob's own anchor shift
            pid = out_pid(fi, 0)
            rule = a.get("rule", "?")
            events.append({"name": f"ALERT {rule}", "cat": "slo_alert",
                           "ph": "i", "s": "g",
                           "ts": float(a.get("t", 0.0)) * 1e6 - base,
                           "pid": pid, "tid": 0, "args": dict(a)})
            if a.get("cleared_t") is not None:
                events.append({"name": f"CLEAR {rule}", "cat": "slo_alert",
                               "ph": "i", "s": "g",
                               "ts": float(a["cleared_t"]) * 1e6 - base,
                               "pid": pid, "tid": 0,
                               "args": {"rule": rule}})
        # one metadata record names each unnamed lane (chrome convention)
        for (f2, orig), pid in list(pid_map.items()):
            if f2 == fi and pid not in named_lanes:
                lane = name if orig == 0 else f"{name}:{orig}"
                events.append({"name": "process_name", "ph": "M",
                               "pid": pid, "args": {"name": lane}})
                named_lanes.add(pid)
    # re-zero the merged axis (anchors can be wall-epoch-sized — the
    # lanes stay aligned, the viewer gets small numbers)
    t0 = min((ev["ts"] for ev in events if "ts" in ev), default=0.0)
    for ev in events:
        if "ts" in ev:
            ev["ts"] -= t0
    with open(output, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tools/timeline.py",
        description="Merge per-worker chrome-trace JSONs into one timeline")
    ap.add_argument("inputs", nargs="+", help="per-worker trace files")
    ap.add_argument("-o", "--output", default="timeline.json")
    args = ap.parse_args(argv)
    n = merge_traces(args.inputs, args.output)
    print(f"wrote {args.output}: {n} events from {len(args.inputs)} workers")
    return 0


if __name__ == "__main__":
    sys.exit(main())
