"""Merge per-worker profiler traces into one chrome://tracing view.

Reference counterpart: ``tools/timeline.py`` — it collects each
worker's profiler dump and emits a single chrome-trace JSON with one
process lane per worker. Here workers write chrome-trace JSON directly
(``paddle_tpu.core.profiler.export_chrome_tracing``); this tool merges
them, assigning each input file its own pid lane (named after the file)
so a multi-worker job reads as one timeline in chrome://tracing or the
perfetto UI.

Usage:
    python tools/timeline.py worker0.json worker1.json -o merged.json
"""

import argparse
import json
import os
import sys


def merge_traces(paths, output):
    events = []
    for pid, path in enumerate(paths):
        with open(path) as f:
            blob = json.load(f)
        # both legal chrome-trace forms: {"traceEvents": [...]} or [...]
        evs = blob if isinstance(blob, list) else blob.get("traceEvents", [])
        name = os.path.splitext(os.path.basename(path))[0]
        # one metadata record names the lane (chrome trace convention)
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": name}})
        for ev in evs:
            ev = dict(ev)
            ev["pid"] = pid
            events.append(ev)
    with open(output, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tools/timeline.py",
        description="Merge per-worker chrome-trace JSONs into one timeline")
    ap.add_argument("inputs", nargs="+", help="per-worker trace files")
    ap.add_argument("-o", "--output", default="timeline.json")
    args = ap.parse_args(argv)
    n = merge_traces(args.inputs, args.output)
    print(f"wrote {args.output}: {n} events from {len(args.inputs)} workers")
    return 0


if __name__ == "__main__":
    sys.exit(main())
