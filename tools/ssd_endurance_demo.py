"""Cold-tier endurance demonstration: a Zipf-skewed push/pull stream
whose key universe is 10-100x the hot budget, driven through the
admission-gated, fp16 block-compressed, background-compacted SSD tier
(csrc/ssd_table.cc) — the four cost attacks of the trillion-feature
cold-tier work measured together on one host:

* admission — at the default threshold the counting-Bloom pre-filter
  must admit at most 1/3 of the offered uniques (the singleton tail of
  the Zipf stream never earns a row);
* index — the open-addressing compact index must measure <=16 bytes per
  cold row (vs ~44.7 for the hash-map baseline it replaced);
* io-budget isolation — serve-path pull p99 while the background
  compactor churns must stay within a CI-gated multiple of the
  no-compaction baseline;
* durability — a checkpoint taken MID-compaction must restore
  digest-exact into a fresh table, and the digest must not move while
  the backlog drains.

Emits one JSON line (committed as SSD_ENDURANCE.json by the ci.sh
endurance gate, which asserts all four). Env knobs: SSD_END_UNIVERSE,
SSD_END_HOT, SSD_END_BATCHES, SSD_END_BATCH_KEYS, SSD_END_ADMIT,
SSD_END_PULL_BATCHES, SSD_END_IO_MBPS, SSD_END_DIR, SSD_END_OUT.
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _rss_bytes() -> int:
    """Host resident set: the gate that RSS tracks the HOT budget (plus
    the compact index + sketch), never the universe."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


class _ZipfMix:
    """Serve/train traffic model: ``head_frac`` of draws Zipf(s) over
    the first ``head`` ranks (the hot working set), the rest uniform
    over the whole universe (the singleton long tail the admission
    filter exists to reject)."""

    def __init__(self, np, rng, universe: int, head: int,
                 s: float = 1.1, head_frac: float = 0.3) -> None:
        self._np = np
        self._rng = rng
        self._universe = universe
        self._head_frac = head_frac
        w = 1.0 / self._np.arange(1, head + 1, dtype=self._np.float64) ** s
        self._cdf = self._np.cumsum(w / w.sum())

    def draw(self, n: int):
        np, rng = self._np, self._rng
        n_head = int(n * self._head_frac)
        head = np.searchsorted(self._cdf, rng.random(n_head)) + 1
        tail = rng.integers(1, self._universe + 1, size=n - n_head)
        return np.concatenate([head, tail]).astype(np.uint64)


def main() -> None:
    import numpy as np

    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig
    from paddle_tpu.ps.table import SsdSparseTable, TableConfig

    universe = int(os.environ.get("SSD_END_UNIVERSE", 1_000_000))
    hot_budget = int(os.environ.get("SSD_END_HOT", 20_000))
    n_batches = int(os.environ.get("SSD_END_BATCHES", 60))
    batch_keys = int(os.environ.get("SSD_END_BATCH_KEYS", 8192))
    admit = int(os.environ.get("SSD_END_ADMIT", 2))
    pull_batches = int(os.environ.get("SSD_END_PULL_BATCHES", 200))
    io_mbps = int(os.environ.get("SSD_END_IO_MBPS", 64))
    # per-shard counter budget: size for ~4x the expected uniques per
    # shard or collisions inflate min-of-two estimates into false
    # admissions (docs/OPERATIONS.md has the sizing rule)
    sketch_kb = int(os.environ.get("SSD_END_SKETCH_KB", 256))
    base = os.environ.get("SSD_END_DIR") or tempfile.mkdtemp(prefix="ssd_end_")
    cleanup = "SSD_END_DIR" not in os.environ

    rng = np.random.default_rng(0)
    dim = 8
    # delete_threshold=0: the lifecycle shrinks in the churn phase decay
    # scores and the sketch but must not evict the population mid-run
    acc = AccessorConfig(embedx_dim=dim, embedx_threshold=0.0,
                         delete_threshold=0.0,
                         sgd=SGDRuleConfig(initial_range=0.0))

    def _cfg():
        return TableConfig(
            shard_num=8, storage="ssd", accessor_config=acc,
            ssd_value_dtype="fp16", ssd_block_compress=True,
            ssd_admission_threshold=admit,
            ssd_admission_sketch_kb=sketch_kb, ssd_bg_compact=True,
            ssd_io_budget_mbps=io_mbps)

    rss_start = _rss_bytes()
    t_all = time.perf_counter()
    table = SsdSparseTable(os.path.join(base, "tbl"), _cfg())
    restored = None
    try:
        out = _run(table, base, _cfg, np, rng, universe, hot_budget,
                   n_batches, batch_keys, admit, pull_batches, io_mbps)
        out["rss_start_bytes"] = rss_start
        out["rss_final_bytes"] = _rss_bytes()
        out["rss_growth_bytes"] = out["rss_final_bytes"] - rss_start
        out["wall_s"] = round(time.perf_counter() - t_all, 2)
        line = json.dumps(out)
        if os.environ.get("SSD_END_OUT"):
            with open(os.environ["SSD_END_OUT"], "w") as f:
                f.write(json.dumps(out, indent=2, sort_keys=True) + "\n")
        print(line)
    finally:
        table.close()
        if cleanup:
            shutil.rmtree(base, ignore_errors=True)


def _serve_phase(table, np, mix, rng, hot_budget, pull_batches,
                 churn: bool):
    """Timed pull batches over the serve mixture; with ``churn`` the
    background compactor is kept busy (update pushes + lifecycle shrink
    + forced sweeps) while the pulls run.  Housekeeping (spill, churn
    kicks) happens BETWEEN timed batches — the p99 measures serve reads
    competing with background io, not the housekeeping itself."""
    samples = []
    for b in range(pull_batches):
        if b % 40 == 20:
            table.spill(hot_budget)  # keep promote-on-access bounded
        if churn and b % 50 == 0:
            keys = mix.draw(4096)
            keys, _ = np.unique(keys, return_index=True)
            push = np.zeros((len(keys), table.accessor.push_dim),
                            np.float32)
            push[:, 1] = 1.0
            push[:, 3:] = 0.01 * rng.standard_normal(
                (len(keys), push.shape[1] - 3)).astype(np.float32)
            table.push_sparse(keys, push)
            table.shrink()          # decay + cold rewrite -> garbage
            table.compact_async()   # forced background sweep
        keys = mix.draw(512)
        t0 = time.perf_counter()
        table.pull_sparse(keys, create=False)
        samples.append((time.perf_counter() - t0) * 1e3)
    return samples


def _run(table, base, make_cfg, np, rng, universe, hot_budget, n_batches,
         batch_keys, admit, pull_batches, io_mbps):
    from paddle_tpu.ps.table import SsdSparseTable

    mix = _ZipfMix(np, rng, universe, hot_budget)

    # -- admission phase: the training stream offers the whole universe,
    # the sketch only admits keys pushed >= threshold times ------------
    t0 = time.perf_counter()
    offered = []
    for _ in range(n_batches):
        keys = mix.draw(batch_keys)
        offered.append(keys)
        keys = np.unique(keys)  # client-side dedup-merge: 1 obs/batch
        push = np.zeros((len(keys), table.accessor.push_dim), np.float32)
        push[:, 0] = (keys % 8).astype(np.float32)
        push[:, 1] = 1.0
        push[:, 3:] = 0.01 * rng.standard_normal(
            (len(keys), push.shape[1] - 3)).astype(np.float32)
        table.push_sparse(keys, push)
    stream_s = time.perf_counter() - t0
    offered_uniques = int(np.unique(np.concatenate(offered)).size)
    del offered
    admitted = int(table.size())

    table.spill(hot_budget)
    table.flush()
    st = table.stats()
    index_bpr = round(float(st["index_bytes_per_row"]), 2)

    # -- serve p99: no-compaction baseline, then compaction churn ------
    base_ms = _serve_phase(table, np, mix, rng, hot_budget, pull_batches,
                           churn=False)
    churn_ms = _serve_phase(table, np, mix, rng, hot_budget, pull_batches,
                            churn=True)
    p99_base = float(np.percentile(base_ms, 99))
    p99_churn = float(np.percentile(churn_ms, 99))

    # -- checkpoint MID-compaction: force a sweep, save while the
    # backlog is live, drain, prove the digest never moved.  spill(0)
    # first: restore lands everything in the COLD tier, and cold is
    # fp16 — digest-exact is the all-cold contract (a still-hot fp32
    # row would quantize on restore)
    table.spill(0)
    table.compact_async()
    d_pre = table.digest()
    ckpt = os.path.join(base, "ckpt.raw")
    saved = table.save_file(ckpt, mode=0, fmt="raw")
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        st = table.stats()
        if st["bg_compactions"] > 0 and st["bg_backlog"] == 0:
            break
        time.sleep(0.05)
    digest_stable = table.digest() == d_pre

    restored = SsdSparseTable(os.path.join(base, "restore"), make_cfg())
    try:
        restored_rows = restored.load_file(ckpt, fmt="raw")
        digest_exact = restored.digest() == d_pre
    finally:
        restored.close()

    st = table.stats()
    return {
        "universe": universe,
        "hot_budget": hot_budget,
        "universe_over_hot": round(universe / hot_budget, 1),
        "admit_threshold": admit,
        "stream": {"batches": n_batches, "batch_keys": batch_keys,
                   "wall_s": round(stream_s, 2),
                   "keys_per_s": round(n_batches * batch_keys / stream_s)},
        "offered_uniques": offered_uniques,
        "admitted_rows": admitted,
        # THE admission acceptance: >=3x fewer rows than offered uniques
        "offered_over_admitted": round(offered_uniques / max(admitted, 1), 2),
        "admit_checks": st["admit_checks"],
        "admit_rejects": st["admit_rejects"],
        "sketch_bytes": st["sketch_bytes"],
        # THE index acceptance (<=16 B/row; hash-map baseline ~44.7)
        "index_bytes_per_row": index_bpr,
        "index_bytes_per_row_baseline": 44.7,
        "hot_rows": st["hot_rows"],
        "cold_rows": st["cold_rows"],
        "disk_bytes": st["disk_bytes"],
        "pull_p50_ms_baseline": round(float(np.percentile(base_ms, 50)), 3),
        "pull_p99_ms_baseline": round(p99_base, 3),
        "pull_p50_ms_churn": round(float(np.percentile(churn_ms, 50)), 3),
        "pull_p99_ms_churn": round(p99_churn, 3),
        # THE isolation acceptance (CI gates the multiple)
        "pull_p99_ratio": round(p99_churn / max(p99_base, 1e-3), 2),
        "io_budget_mbps": io_mbps,
        "io_serve_bytes": st["io_serve_bytes"],
        "io_bg_bytes": st["io_bg_bytes"],
        "io_bg_wait_ms": st["io_bg_wait_ms"],
        "bg_compactions": st["bg_compactions"],
        "bg_backlog_final": st["bg_backlog"],
        "saved_rows": int(saved),
        "restored_rows": int(restored_rows),
        # THE durability acceptance
        "digest_exact": bool(digest_exact),
        "digest_stable_under_churn": bool(digest_stable),
        # headline: admission leverage at the default threshold
        "value": round(offered_uniques / max(admitted, 1), 2),
    }


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — artifact must be one JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}))
        sys.exit(0)
