"""Multi-HOST routed-vs-gathered serving TIMING (VERDICT r3 #9 sparse;
r4 #8 dense + K sweep): two localhost jax.distributed processes × N
virtual CPU devices form one global "ps" mesh; both routing
formulations run the full pull+push serving step with the inter-host
hop crossing the process boundary — the DCN regime, where the routed
path's O(batch/K) wire volume matters most (HeterComm multi-node push,
heter_comm_inl.h:686).

test_multiprocess_sharded_cache pins CORRECTNESS of this exact setup;
this tool records the TIMING artifacts:
- ROUTED_MULTIHOST.json        (push_mode=sparse, K=8 — the r3 run)
- ROUTED_MULTIHOST_DENSE.json  (push_mode=dense — the TPU default —
  over K ∈ {2,4,8}; decides whether the dense path should ever route
  the push side over DCN)

Localhost loopback is NOT a real DCN — label every citation loopback.

Env: RM_BATCH (4096), RM_DIM (8), RM_CAP (262144), RM_STEPS (10),
RM_MODE (dense|sparse, default dense), RM_KS ("2,4,8"), RM_OUT.
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import textwrap
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent("""
    import json, os, sys, time
    import numpy as np

    rank = int(sys.argv[1]); world = int(sys.argv[2]); port = sys.argv[3]
    out_path = sys.argv[4]
    devs_per_proc = int(sys.argv[5])
    push_mode = sys.argv[6]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devs_per_proc}")
    os.environ["RANK"] = str(rank)
    os.environ["WORLD_SIZE"] = str(world)
    os.environ["PADDLE_MASTER"] = f"127.0.0.1:{port}"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.distributed import collective as C

    env = C.init_parallel_env()
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.ps.embedding_cache import CacheConfig
    from paddle_tpu.ps.sharded_cache import (routed_cache_pull,
                                             routed_cache_push, routed_dedup,
                                             sharded_cache_pull,
                                             sharded_cache_push)

    B = int(os.environ.get("RM_BATCH", 4096))
    dim = int(os.environ.get("RM_DIM", 8))
    Cap = int(os.environ.get("RM_CAP", 262144))
    steps = int(os.environ.get("RM_STEPS", 10))

    rng = np.random.default_rng(0)
    host = {
        "show": rng.uniform(0, 5, Cap).astype(np.float32),
        "click": rng.uniform(0, 2, Cap).astype(np.float32),
        "embed_w": rng.normal(size=(Cap, 1)).astype(np.float32),
        "embed_state": rng.uniform(0, 1, (Cap, 1)).astype(np.float32),
        "embedx_w": rng.normal(size=(Cap, dim)).astype(np.float32),
        "embedx_state": rng.uniform(0, 1, (Cap, 1)).astype(np.float32),
        "has_embedx": (rng.random(Cap) < 0.5).astype(np.float32),
    }
    rows = rng.integers(0, Cap, B).astype(np.int32)
    grads = rng.normal(size=(B, 1 + dim)).astype(np.float32)
    shows = np.ones(B, np.float32)
    clicks = (rng.random(B) < 0.4).astype(np.float32)
    cfg = CacheConfig(capacity=Cap, embedx_dim=dim, embedx_threshold=1.0,
                      push_mode=push_mode)

    mesh = Mesh(np.array(jax.devices()), ("ps",))

    def to_global(a):
        sh = NamedSharding(mesh, P(*(["ps"] + [None] * (a.ndim - 1))))
        return jax.make_array_from_callback(a.shape, sh, lambda i: a[i])

    rows_g, grads_g, shows_g, clicks_g = (to_global(x) for x in
                                          (rows, grads, shows, clicks))

    def routed_body(st, r, g, s, c):
        d = routed_dedup(r, Cap)
        vals, _ = routed_cache_pull(st, r, "ps", dedup=d)
        new, ov = routed_cache_push(st, r, g, s, c, cfg, "ps", dedup=d)
        return new, jnp.sum(vals), ov

    def gathered_body(st, r, g, s, c):
        vals = sharded_cache_pull(st, r, "ps")
        new = sharded_cache_push(st, r, g, s, c, cfg, "ps")
        return new, jnp.sum(vals), jnp.int32(0)

    result = {}
    for name, body in (("alltoall", routed_body), ("allgather", gathered_body)):
        state_g = {k: to_global(v) for k, v in host.items()}
        fn = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("ps"),) + (P("ps"),) * 4,
            out_specs=(P("ps"), P(), P()), check_vma=False),
            donate_argnums=(0,))
        st, val, ov = fn(state_g, rows_g, grads_g, shows_g, clicks_g)
        jax.block_until_ready(val)
        assert int(ov) == 0
        best = float("inf")
        for _ in range(3):  # min-of-3 (same estimator as routed_grid)
            t0 = time.perf_counter()
            for _ in range(steps):
                st, val, ov = fn(st, rows_g, grads_g, shows_g, clicks_g)
            jax.block_until_ready(val)
            best = min(best, (time.perf_counter() - t0) / steps)
        result[name] = round(best * 1e3, 3)

    if rank == 0:
        out = {
            "hosts": world, "devices": world * devs_per_proc, "batch": B,
            "dim": dim, "capacity": Cap, "steps": steps,
            "push_mode": push_mode, "ms_per_step": result,
            "routed_vs_gathered": round(
                result["alltoall"] / result["allgather"], 3),
        }
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps(out), flush=True)
    print("WORKER_OK", rank, flush=True)
""")


def _run_once(devs_per_proc: int, push_mode: str, tmp_out: str) -> dict:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "worker.py")
        with open(script, "w") as f:
            f.write(_WORKER)
        procs = []
        for r in range(2):
            env = dict(os.environ,
                       PYTHONPATH=_REPO + os.pathsep +
                       os.environ.get("PYTHONPATH", ""))
            env.pop("XLA_FLAGS", None)
            env.pop("JAX_PLATFORMS", None)
            procs.append(subprocess.Popen(
                [sys.executable, script, str(r), "2", str(port), tmp_out,
                 str(devs_per_proc), push_mode],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        try:
            for r, p in enumerate(procs):
                out, _ = p.communicate(timeout=600)
                assert p.returncode == 0, f"rank {r} failed:\n{out[-3000:]}"
                assert f"WORKER_OK {r}" in out, out[-2000:]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
    with open(tmp_out) as f:
        return json.load(f)


def main() -> None:
    mode = os.environ.get("RM_MODE", "dense")
    if mode == "sparse" and "RM_KS" not in os.environ:
        # the r3 artifact shape: one K=8 run, its own file
        out_path = os.environ.get("RM_OUT") or os.path.join(
            _REPO, "ROUTED_MULTIHOST.json")
        res = _run_once(4, "sparse", out_path)
        print(json.dumps(res))
        print("ok")
        return
    ks = [int(k) for k in os.environ.get("RM_KS", "2,4,8").split(",")]
    out_path = os.environ.get("RM_OUT") or os.path.join(
        _REPO, f"ROUTED_MULTIHOST_{mode.upper()}.json")
    runs = {}
    with tempfile.TemporaryDirectory() as td:
        for k in ks:
            assert k % 2 == 0, "K must split over the 2 host processes"
            tmp = os.path.join(td, f"k{k}.json")
            runs[str(k)] = _run_once(k // 2, mode, tmp)
    out = {
        "push_mode": mode,
        "transport": "loopback TCP (2 jax.distributed procs, one host) — "
                     "NOT a real DCN; ratios not absolute times are the "
                     "evidence",
        "runs_by_K": runs,
        "routed_vs_gathered_by_K": {
            k: v["routed_vs_gathered"] for k, v in runs.items()},
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    print("ok")


if __name__ == "__main__":
    main()
