"""Sparse push-wire ladder: fp32 vs fp16 vs int8 (ISSUE 14).

One seeded CTR push workload (merged-duplicate batches against a real
2-shard NativePsServer cluster) runs once per
``TableConfig.push_wire_dtype`` rung. Per rung the record carries:

- ``push_wire_bytes`` — the PR 8 per-table client byte counter's delta
  over the measured pushes (the counter measures the ENCODED payload,
  which is what the ≥3x CI gate asserts);
- ``bytes_per_row`` and ``samples_per_sec`` (host-loop push throughput
  — wall time on a shared CI box is indicative, the byte counts are
  exact);
- int8 additionally reports the residual rows drained at the end (the
  error-feedback store's quiesce contract).

Baseline-comparability note (the PR 12 lesson, MEASURED.md): every
ratio in this record is against THIS record's own fp32 rung — same
transport, same PR-2 overlapped client, same host. Ratios are not
comparable across records from different client eras; the committed
SPARSE_WIRE.json says which rpc baseline it measured.

Standalone: prints exactly ONE JSON line (driver contract).
Env knobs: SWB_ROWS, SWB_STEPS, SWB_EMBEDX, SWB_SHARDS.
"""

import json
import os
import sys
import time

METRIC = "sparse_push_wire_ratio_fp32_over_int8"


def _params():
    return {
        "rows": int(os.environ.get("SWB_ROWS", 4096)),
        "steps": int(os.environ.get("SWB_STEPS", 20)),
        "embedx": int(os.environ.get("SWB_EMBEDX", 64)),
        "shards": int(os.environ.get("SWB_SHARDS", 2)),
    }


def _push_bytes(table_id):
    from paddle_tpu.obs import registry as _reg

    snap = _reg.REGISTRY.snapshot()["metrics"]
    fam = snap.get("ps_client_wire_bytes", {"series": []})
    return sum(s["value"] for s in fam["series"]
               if s["labels"].get("dir") == "push"
               and s["labels"].get("table") == str(table_id))


def _run_rung(wire, p, tid):
    import numpy as np

    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.rpc import NativePsServer, RpcPsClient
    from paddle_tpu.ps.table import TableConfig

    srvs = [NativePsServer() for _ in range(p["shards"])]
    try:
        cli = RpcPsClient([f"127.0.0.1:{s.port}" for s in srvs])
        cli.create_sparse_table(tid, TableConfig(
            table_id=tid, push_wire_dtype=wire,
            accessor_config=AccessorConfig(embedx_dim=p["embedx"],
                                           embedx_threshold=0.0),
            seed=13))
        rng = np.random.default_rng(0)
        keys = rng.integers(1, 1 << 40, p["rows"]).astype(np.uint64)
        gd = 1 + p["embedx"]
        cli.pull_sparse(tid, keys)  # create rows outside the window
        before = _push_bytes(tid)
        t0 = time.perf_counter()
        for _ in range(p["steps"]):
            push = np.zeros((len(keys), 3 + gd), np.float32)
            push[:, 1] = 1.0
            push[:, 3:] = rng.normal(0, 0.1,
                                     (len(keys), gd)).astype(np.float32)
            cli.push_sparse(tid, keys, push)
        dt = time.perf_counter() - t0
        # steady-state wire FIRST; the error-feedback drain is a
        # checkpoint-boundary cost, not per-step wire — measured apart
        wire_bytes = _push_bytes(tid) - before
        drained = cli.drain_push_residuals(tid)
        drain_bytes = _push_bytes(tid) - before - wire_bytes
        n = p["rows"] * p["steps"]
        rec = {
            "wire": wire,
            "push_wire_bytes": int(wire_bytes),
            "bytes_per_row": round(wire_bytes / n, 2),
            "samples_per_sec": round(n / max(dt, 1e-9), 1),
            "residual_rows_drained": int(drained),
            "drain_bytes": int(drain_bytes),
        }
        cli.close()
        return rec
    finally:
        for s in srvs:
            s.stop()
            s.close()


def run():
    import jax

    p = _params()
    ladder = []
    for tid, wire in enumerate(("fp32", "fp16", "int8"), start=1):
        ladder.append(_run_rung(wire, p, tid))
    by = {r["wire"]: r for r in ladder}
    ratio = by["fp32"]["push_wire_bytes"] / max(
        by["int8"]["push_wire_bytes"], 1)
    return {
        "metric": METRIC,
        "value": round(ratio, 3),
        "ladder": ladder,
        "ratio_fp32_over_fp16": round(
            by["fp32"]["push_wire_bytes"]
            / max(by["fp16"]["push_wire_bytes"], 1), 3),
        # which baseline these ratios are against (the PR 12 lesson):
        # the SAME record's fp32 rung on the SAME PR-2 era client
        "baseline": "this-record fp32 rung (psc_callv scatter-gather "
                    "client, PR 2 era)",
        "rows": p["rows"], "steps": p["steps"], "embedx": p["embedx"],
        "shards": p["shards"],
        "platform": jax.devices()[0].platform,
    }


def main() -> None:
    try:
        rec = run()
    except Exception as e:  # noqa: BLE001 — one-JSON-line contract
        import traceback

        traceback.print_exc(file=sys.stderr)
        rec = {"metric": METRIC, "value": 0.0,
               "error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
