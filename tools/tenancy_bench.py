"""Multi-tenant interference bench (ISSUE 19 acceptance; TENANCY.json).

The workload zoo runs as CONCURRENT TENANTS of one shared HACluster —
each under its own wire-enforced namespace, admission budget and quota
(ps/tenancy.py + the csrc tenancy fence):

- **ctr** — streaming CTR: pulls of 64 keys with a push every 4th
  round (the Wide&Deep trainer's wire shape).
- **moe** — routed-MoE: small skewed pulls (16 keys, zipf-ish routing
  concentrated on hot experts).
- **gnn** — graph_table: neighbor sampling over a DistGraphClient on
  the tenant's namespaced graph table.
- **tdm** — TDM retrieval: a 3-level beam descent of small sequential
  pulls (8 keys per level) — latency-critical, dependency-chained.
- **abuse** — the deliberately abusive neighbor: fat 1024-key
  create-on-miss pulls as fast as the socket allows, row-creation
  churn against its quota, plus cross-tenant probes that must bounce.

Protocol, three phases: each well-behaved tenant runs SOLO for a
reference p99; all four run together WITHOUT the abuser (``shared`` —
the honest multi-tenancy baseline: on a small CI box the four zoo
loops already contend for cores); then the same four run WITH the
abusive flood (``abused``). The metric is the worst per-tenant
abused/shared p99 ratio — the abuser's MARGINAL damage, which is what
admission control owns (solo→shared movement is CPU scheduling, not
isolation). ci.sh's tenancy gate asserts abused p99 ≤ RATIO× shared +
SLACK ms per tenant; the committed TENANCY.json is a quiet-host run.
The bench also proves the negative: the abuser's meter shows
throttles (and quota refusals once its namespace fills), its rows
stay ≤ per-shard cap + one batch per shard, and every well-behaved
namespace is digest-identical across an abuse-only flood (zero
cross-tenant row writes).

Standalone: prints exactly ONE JSON line (driver contract).
Importable: ``run()`` returns the record. Env knobs: TB_SHARDS (2),
TB_SOLO_S (0.7 per tenant), TB_LOAD_S (1.5 per loaded phase),
TB_ABUSE_RATE (500 token cost units/s/shard), TB_ABUSE_BURST (1500 —
above one fat frame's cost, so the flood LANDS bursts before the
bucket clamps it), TB_ABUSE_ROWS (1000 rows/shard).
"""

import json
import os
import sys
import threading
import time

METRIC = "tenancy_p99_isolation_ratio"


def _pct(xs, q):
    import numpy as np

    return float(np.percentile(np.asarray(xs), q)) if len(xs) else 0.0


def run() -> dict:
    import jax
    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from paddle_tpu.core.enforce import (QuotaExceededError, ThrottledError,
                                         WrongTenantError)
    from paddle_tpu.ps import ha
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.graph_client import DistGraphClient
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig
    from paddle_tpu.ps.table import TableConfig
    from paddle_tpu.ps.tenancy import Tenant, TenantDirectory

    shards = int(os.environ.get("TB_SHARDS", 2))
    solo_s = float(os.environ.get("TB_SOLO_S", 0.7))
    load_s = float(os.environ.get("TB_LOAD_S", 1.5))
    abuse_rate = float(os.environ.get("TB_ABUSE_RATE", 500.0))
    abuse_burst = float(os.environ.get("TB_ABUSE_BURST", 1500.0))
    abuse_rows = int(os.environ.get("TB_ABUSE_ROWS", 1000))

    def cfg():
        return TableConfig(
            shard_num=4, accessor_config=AccessorConfig(
                sgd=SGDRuleConfig(initial_range=0.0)))

    with ha.HACluster(num_shards=shards, replication=1,
                      sync=True) as cluster:
        d = TenantDirectory(cluster)
        d.register(Tenant(name="ctr", tid=1, token=b"ctr"))
        d.register(Tenant(name="moe", tid=2, token=b"moe"))
        d.register(Tenant(name="gnn", tid=3, token=b"gnn"))
        d.register(Tenant(name="tdm", tid=4, token=b"tdm"))
        d.register(Tenant(name="abuse", tid=9, token=b"abuse", pclass=1,
                          rate=abuse_rate, burst=abuse_burst,
                          max_rows=abuse_rows))

        clis = {n: d.client(n) for n in
                ("ctr", "moe", "gnn", "tdm", "abuse")}
        tables = {n: d.get(n).table_id(0) for n in clis}

        # -- populate each tenant's namespace --------------------------
        def fill(name, n_keys):
            cli, t = clis[name], tables[name]
            cli.create_sparse_table(t, cfg())
            keys = np.arange(1, n_keys + 1, dtype=np.uint64)
            width = cli._dims(t)[1]
            push = np.zeros((len(keys), width), np.float32)
            push[:, 1] = 1.0
            cli.push_sparse(t, keys, push)

        fill("ctr", 4000)
        fill("moe", 4096)
        fill("tdm", 1024)
        clis["abuse"].create_sparse_table(tables["abuse"], cfg())
        # gnn: a namespaced graph table + a small power-lawish graph
        graph = DistGraphClient(clis["gnn"], table_id=d.get(
            "gnn").table_id(1), shard_num=8)
        rng = np.random.default_rng(0)
        nodes = np.arange(1, 2001, dtype=np.uint64)
        graph.add_graph_node(nodes)
        graph.add_edges(rng.choice(nodes, 8000), rng.choice(nodes, 8000))
        cluster.drain()

        # -- the zoo's per-tenant request shapes -----------------------
        def op_ctr(i, rng):
            keys = rng.integers(1, 4000, 64).astype(np.uint64)
            clis["ctr"].pull_sparse(tables["ctr"], keys)
            if i % 4 == 0:
                width = clis["ctr"]._dims(tables["ctr"])[1]
                push = np.zeros((len(keys), width), np.float32)
                push[:, 1] = 1.0
                clis["ctr"].push_sparse(tables["ctr"], keys, push)

        def op_moe(i, rng):
            # routing concentrates on hot experts (low ids)
            experts = np.minimum(
                rng.zipf(1.3, 16), 4095).astype(np.uint64) + 1
            clis["moe"].pull_sparse(tables["moe"], experts)

        def op_gnn(i, rng):
            seeds = rng.choice(nodes, 16)
            graph.sample_neighbors(seeds, 8)

        def op_tdm(i, rng):
            # beam descent: 3 dependency-chained levels of 8
            for _ in range(3):
                keys = rng.integers(1, 1024, 8).astype(np.uint64)
                clis["tdm"].pull_sparse(tables["tdm"], keys)

        ops = {"ctr": op_ctr, "moe": op_moe, "gnn": op_gnn,
               "tdm": op_tdm}
        wb = list(ops)

        def loop(name, stop, lat):
            rng = np.random.default_rng(abs(hash(name)) & 0xffff)
            i = 0
            while not stop.is_set():
                t0 = time.perf_counter()
                ops[name](i, rng)
                lat.append(time.perf_counter() - t0)
                i += 1

        def abuse_flood(stop, counters):
            cli, t = clis["abuse"], tables["abuse"]
            rng = np.random.default_rng(7)
            while not stop.is_set():
                keys = rng.integers(1, 1 << 40, 1024).astype(np.uint64)
                try:
                    cli.pull_sparse(t, keys, create=True)
                    counters["landed"] += 1
                except ThrottledError:
                    counters["throttled"] += 1
                except QuotaExceededError:
                    counters["quota"] += 1
                try:
                    cli.size(tables["ctr"])
                    counters["breach"] += 1       # must never happen
                except WrongTenantError:
                    counters["bounced"] += 1

        def measure(names, duration, with_abuse, counters):
            stop = threading.Event()
            lats = {n: [] for n in names}
            thr = [threading.Thread(target=loop, args=(n, stop, lats[n]),
                                    daemon=True, name=f"tenant-{n}")
                   for n in names]
            if with_abuse:
                thr.append(threading.Thread(target=abuse_flood,
                                            args=(stop, counters),
                                            daemon=True,
                                            name="tenant-abuse"))
            for th in thr:
                th.start()
            time.sleep(duration)
            stop.set()
            for th in thr:
                th.join(15)
            return lats

        def summarize(lats):
            return {n: {"p50_ms": round(_pct(v, 50) * 1e3, 3),
                        "p99_ms": round(_pct(v, 99) * 1e3, 3),
                        "ops": len(v)}
                    for n, v in lats.items()}

        # -- solo references (one tenant at a time, abuser idle) -------
        solo = {}
        for n in wb:
            solo.update(summarize(measure([n], solo_s, False, None)))

        # -- shared baseline: the whole zoo, abuser idle ---------------
        shared = summarize(measure(wb, load_s, False, None))

        digests = {n: clis[n].digest(tables[n]) for n in ("moe", "tdm")}
        rows_before = {n: d.usage(n)["rows"] for n in wb}

        # -- the whole zoo + the abusive flood -------------------------
        counters = {"landed": 0, "throttled": 0, "quota": 0,
                    "bounced": 0, "breach": 0}
        abused = summarize(measure(wb, load_s, True, counters))

        ratios = {n: round(abused[n]["p99_ms"]
                           / max(shared[n]["p99_ms"], 1e-3), 2)
                  for n in wb}
        worst = max(ratios.values())

        # -- digest proof: an abuse-only flood writes ZERO foreign rows
        stop = threading.Event()
        fl = threading.Thread(target=abuse_flood, args=(stop, counters),
                              daemon=True, name="tenant-abuse2")
        fl.start()
        time.sleep(0.5)
        stop.set()
        fl.join(15)
        digest_stable = all(clis[n].digest(tables[n]) == digests[n]
                            for n in ("moe", "tdm"))
        rows_after = {n: d.usage(n)["rows"] for n in wb}

        au = d.usage("abuse")
        usage = d.refresh_usage()

        return {
            "metric": METRIC,
            "value": worst,
            "unit": "x",
            "tenants": {n: {"solo": solo[n], "shared": shared[n],
                            "abused": abused[n],
                            "p99_ratio": ratios[n]} for n in wb},
            "abuse": {
                "flood": counters,
                "usage": au,
                "rows_cap_per_shard": abuse_rows,
                "rows_within_cap": au["rows"] <= shards * (abuse_rows
                                                           + 1024),
                "rate_units_per_s_per_shard": abuse_rate,
                "burst_units_per_shard": abuse_burst,
            },
            "isolation": {
                "cross_tenant_probes_bounced": counters["bounced"],
                "cross_tenant_breaches": counters["breach"],
                "digest_stable_under_abuse": bool(digest_stable),
                "wb_rows_unchanged": rows_after == rows_before,
            },
            "billing": {n: usage[n] for n in usage},
            "shards": shards,
            "solo_s": solo_s,
            "load_s": load_s,
            "platform": jax.devices()[0].platform,
            "host_cores": os.cpu_count(),
        }


def main() -> None:
    try:
        rec = run()
    except Exception as e:  # noqa: BLE001 — one-JSON-line contract
        import traceback

        traceback.print_exc(file=sys.stderr)
        rec = {"metric": METRIC, "value": 0.0,
               "error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
