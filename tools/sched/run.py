#!/usr/bin/env python3
"""graftsched gate — budgeted deterministic-schedule exploration of the
control-plane protocol harnesses (tools/sched/models.py), run from
``ci.sh sched``.

For each harness the gate runs a preemption-bounded EXHAUSTIVE sweep
(the whole ≤bound-preemption schedule space, or the run does not count
as exhausted) plus a seeded random-walk sweep on the full-task variant.
Every failure prints a replayable seed and a shrunk minimal schedule;
dynamic lock-order observations are cross-checked against the
``# LOCK ORDER:`` / ``# LOCK LEAF:`` declarations of the modules under
test (tools/lint/py_locks.py) — a mismatch fails the gate.

Usage:
  python tools/sched/run.py                       # full gate
  python tools/sched/run.py --harness three_way   # one harness
  python tools/sched/run.py --replay three_way --seed 123456
  python tools/sched/run.py --json out.json --budget-s 240
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Set, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(os.path.dirname(_HERE))
for p in (_ROOT, _HERE):
    if p not in sys.path:
        sys.path.insert(0, p)

from paddle_tpu.testing.sched import (  # noqa: E402
    Explorer, ScheduleFailure, load_lock_order)
import models  # noqa: E402

# (model factory, dfs bound, dfs task-trimming kwargs) per harness.  The
# exhaustive sweep wants a space small enough to actually EXHAUST inside
# the budget — the three-way harness drops the writer task for the pb-2
# sweep (the unpaused-read invariant alone catches the torn cut) and
# adds it back for the random walk; the checkpoint harness exhausts at
# bound 1 (bound 2 is ~75k schedules: random walk covers the tail).
HARNESSES: Dict[str, Dict[str, Any]] = {
    "three_way": {
        "dfs": lambda: models.three_way_model(with_writer=False),
        "full": lambda: models.three_way_model(),
        "bound": 2,
        "random_n": 5000,
    },
    "fleet": {
        "dfs": models.fleet_drain_tick_model,
        "full": models.fleet_drain_tick_model,
        "bound": 2,
        "random_n": 2000,
    },
    "ckpt": {
        "dfs": models.ckpt_writer_model,
        "full": models.ckpt_writer_model,
        "bound": 1,
        "random_n": 2000,
    },
    # the reconciler's single-actuator discipline: the pb sweep runs
    # the lean variant (one proposer) to exhaustion; the random walk
    # adds the trainer_np proposer back for spec-write interleavings
    "reconciler": {
        "dfs": lambda: models.reconciler_model(with_np_proposer=False),
        "full": models.reconciler_model,
        "bound": 2,
        "random_n": 2000,
    },
    # the cold-tier compactor drops the shrink sweep for the pb sweep
    # (the push/pull/save races alone cover the phase-B reconcile) and
    # adds it back for the random walk
    "ssd_compact": {
        "dfs": lambda: models.ssd_compact_model(with_shrink=False),
        "full": models.ssd_compact_model,
        "bound": 2,
        "random_n": 2000,
    },
}


def _decls() -> Tuple[Dict[str, Set[str]], Set[str]]:
    return load_lock_order(
        [os.path.join(_ROOT, f) for f in models.DECL_FILES])


def _closure(edges: Dict[str, Set[str]]) -> Dict[str, Set[str]]:
    out = {a: set(bs) for a, bs in edges.items()}
    changed = True
    while changed:
        changed = False
        for a in list(out):
            for b in list(out[a]):
                for c in out.get(b, ()):
                    if c not in out[a]:
                        out[a].add(c)
                        changed = True
    return out


def cross_check(observed: Set[Tuple[str, str]],
                decls: Tuple[Dict[str, Set[str]], Set[str]]
                ) -> List[str]:
    """Every dynamically observed held-A-acquire-B edge must agree with
    the static declarations: B acquired under a declared LEAF is a
    violation, and an observed edge whose REVERSE is in the declared
    order's transitive closure is an inversion.  (The scheduler already
    fails schedules on these live; this is the aggregated end-of-gate
    re-check across every schedule of every harness, so a declaration
    drifting from reality cannot slip through a non-failing run.)"""
    edges, leaves = decls
    closure = _closure(edges)
    bad = []
    for a, b in sorted(observed):
        if a in leaves:
            bad.append(f"observed {a} -> {b}, but {a} is declared LEAF")
        if b in closure and a in closure[b]:
            bad.append(f"observed {a} -> {b} inverts declared order "
                       f"{b} < {a}")
    return bad


def _fail_report(name: str, ex: Explorer, f: ScheduleFailure,
                 shrink: bool = True) -> ScheduleFailure:
    if shrink:
        try:
            f = ex.shrink(f)
        except Exception:  # noqa: BLE001 — report the unshrunk failure
            pass
    print(f"FAIL [{name}]\n{f.format()}", file=sys.stderr)
    if f.seed is not None:
        print(f"  replay: python tools/sched/run.py --replay {name} "
              f"--seed {f.seed}", file=sys.stderr)
    return f


def run_harness(name: str, spec: Dict[str, Any], seed: int,
                deadline: float, summary: Dict[str, Any]) -> bool:
    decls = _decls()
    entry: Dict[str, Any] = {}
    summary["harnesses"][name] = entry
    ok = True

    t0 = time.monotonic()
    ex = Explorer(spec["dfs"](), order_decls=decls)
    failure, exhausted = ex.explore_dfs(
        bound=spec["bound"], deadline=deadline)
    entry["dfs"] = {"bound": spec["bound"], "schedules": ex.schedules_run,
                    "exhausted": exhausted,
                    "wall_ms": int((time.monotonic() - t0) * 1000)}
    if failure is not None:
        f = _fail_report(name, ex, failure)
        entry["dfs"]["failure"] = {"kind": f.kind, "message": f.message,
                                   "choices": f.choices}
        ok = False
    elif not exhausted:
        print(f"FAIL [{name}] pb-{spec['bound']} sweep did NOT exhaust "
              f"inside budget ({ex.schedules_run} schedules) — the gate "
              "requires full coverage of the bounded space",
              file=sys.stderr)
        ok = False
    obs = set(ex.observed_edges)

    t0 = time.monotonic()
    ex2 = Explorer(spec["full"](), order_decls=decls)
    f2 = ex2.explore_random(spec["random_n"], base_seed=seed,
                            deadline=deadline)
    entry["random"] = {"n": spec["random_n"], "base_seed": seed,
                       "schedules": ex2.schedules_run,
                       "wall_ms": int((time.monotonic() - t0) * 1000)}
    if f2 is not None:
        f2 = _fail_report(name, ex2, f2, shrink=False)
        entry["random"]["failure"] = {"kind": f2.kind,
                                      "message": f2.message,
                                      "seed": f2.seed}
        ok = False
    obs |= ex2.observed_edges

    entry["observed_edges"] = sorted(list(e) for e in obs)
    violations = cross_check(obs, decls)
    if violations:
        entry["lock_order_violations"] = violations
        for v in violations:
            print(f"FAIL [{name}] lock-order cross-check: {v}",
                  file=sys.stderr)
        ok = False
    entry["ok"] = ok
    status = "ok" if ok else "FAIL"
    print(f"[{name}] {status}: pb-{spec['bound']} "
          f"{'exhausted' if exhausted else 'NOT exhausted'} "
          f"({entry['dfs']['schedules']} schedules, "
          f"{entry['dfs']['wall_ms']}ms) + "
          f"{entry['random']['schedules']} random walks "
          f"(base seed {seed}, {entry['random']['wall_ms']}ms)")
    return ok


def replay(name: str, seed: Optional[int],
           choices: Optional[List[str]]) -> int:
    spec = HARNESSES[name]
    ex = Explorer(spec["full"](), order_decls=_decls())
    if choices:
        sched = ex.replay_choices(choices)
    else:
        sched = ex.replay_seed(int(seed))
    if sched.failure is not None:
        if seed is not None and sched.failure.seed is None:
            sched.failure.seed = int(seed)
        print(sched.failure.format(max_trace=200))
        return 1
    print(f"[{name}] schedule ran clean "
          f"({len(sched.decision_log)} decisions)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--harness", choices=sorted(HARNESSES), default=None,
                    help="run one harness (default: all)")
    ap.add_argument("--seed", type=int, default=None,
                    help="base seed for random walks / seed to --replay")
    ap.add_argument("--budget-s", type=float, default=300.0,
                    help="wall budget for the whole gate")
    ap.add_argument("--json", default=None,
                    help="write the machine-readable summary here")
    ap.add_argument("--replay", choices=sorted(HARNESSES), default=None,
                    help="replay ONE schedule of this harness from "
                         "--seed (or --choices) and print its trace")
    ap.add_argument("--choices", default=None,
                    help="comma/space-separated choice list to --replay")
    args = ap.parse_args(argv)

    if args.replay:
        choices = None
        if args.choices:
            choices = args.choices.replace(",", " ").split()
        if args.seed is None and not choices:
            ap.error("--replay needs --seed or --choices")
        return replay(args.replay, args.seed, choices)

    base_seed = args.seed if args.seed is not None else (
        int(time.time()) & 0x7FFFFFFF)
    deadline = time.monotonic() + args.budget_s
    summary: Dict[str, Any] = {"base_seed": base_seed, "harnesses": {}}
    names = [args.harness] if args.harness else sorted(HARNESSES)
    print(f"graftsched: harnesses={names} base_seed={base_seed} "
          f"budget={args.budget_s:.0f}s")
    ok = True
    t0 = time.monotonic()
    for name in names:
        ok &= run_harness(name, HARNESSES[name], base_seed, deadline,
                          summary)
    summary["wall_ms"] = int((time.monotonic() - t0) * 1000)
    summary["total_schedules"] = sum(
        h["dfs"]["schedules"] + h["random"]["schedules"]
        for h in summary["harnesses"].values()
        if "dfs" in h and "random" in h)
    summary["ok"] = ok
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(summary, fh, indent=2, sort_keys=True)
        print(f"summary -> {args.json}")
    print(f"graftsched: {'OK' if ok else 'FAILED'} "
          f"({summary['total_schedules']} schedules, "
          f"{summary['wall_ms']}ms)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
