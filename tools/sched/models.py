"""Model harnesses for the control-plane protocols graftsched explores.

Three harnesses, matching the three protocols whose interlocks were
each added reactively (see docs/STATIC_ANALYSIS.md, explorer section):

* ``three_way_model`` — checkpoint-gate × reshard-cutover ×
  failover-``suspend()``: a faithful miniature of the ps/ha.py +
  ps/reshard.py protocol steps using the REAL lock names
  (``control_mu``, ``_step_mu``, ``_op_mu``, ``_pause_mu``,
  ``_susp_mu``), so the dynamic lock-order checker validates the same
  ``# LOCK ORDER:`` declarations the static pass reads.  Two knobs
  replay the protocol's history: ``gate_suspends=False`` reproduces
  the pre-fix CheckpointGate (no ``coordinator.suspend()`` — a
  mid-capture promotion routes the capture to an unpaused backup: the
  torn-cut bug this explorer surfaced), and ``depth_counted=False``
  reproduces the naive single-Event suspend (a reshard overlapping a
  gate clears the GATE's suspension from its ``finally`` — the
  second-order bug that makes the fix need nesting).  Defaults mirror
  the fixed production protocol and must explore clean.

* ``fleet_drain_tick_model`` — drives the REAL
  serving.fleet.ServingFleet (stub router/store/members) through
  ``drain()`` racing watcher ``tick()``s: the three seeded re-admit
  races fixed in its history must stay closed in EVERY interleaving.

* ``ckpt_writer_model`` — drives the REAL
  io.job_checkpoint.JobCheckpointManager (``_write`` stubbed) through
  two ``save()``s racing ``stop()``: every admitted snapshot must land
  ahead of the shutdown sentinel, and stop() must terminate.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from paddle_tpu.core import sync as _sync  # noqa: E402

#: sources whose `# LOCK ORDER:` / `# LOCK LEAF:` declarations the
#: dynamic checker loads (testing.sched.load_lock_order) — the models
#: use these exact lock names
DECL_FILES = (
    "paddle_tpu/ps/ha.py",
    "paddle_tpu/ps/rpc.py",
    "paddle_tpu/ps/reshard.py",
    "paddle_tpu/ps/reconcile.py",
    "paddle_tpu/ps/spec.py",
    "paddle_tpu/serving/fleet.py",
    "paddle_tpu/io/job_checkpoint.py",
    "paddle_tpu/csrc/ssd_table.cc",   # `//` grammar — load_lock_order
)                                     # dispatches on extension


# ---------------------------------------------------------------------------
# 1. checkpoint-gate × reshard-cutover × failover three-way
# ---------------------------------------------------------------------------

class _ModelServer:
    """One shard replica: pause depth + a data version (a write bumps
    it; the capture must see a frozen version)."""

    def __init__(self, ep: str) -> None:
        self.ep = ep
        self.pause_mu = _sync.Lock(name="_pause_mu")
        self.pause_depth = 0
        self.data = 0


class _ThreeWay:
    """ps/ha.py + ps/reshard.py control-plane protocol in miniature."""

    def __init__(self, sched, gate_suspends: bool,
                 depth_counted: bool) -> None:
        self.sched = sched
        self.gate_suspends = gate_suspends
        self.depth_counted = depth_counted
        # the real primitives, real names (HACluster / FailoverCoordinator
        # / ReshardController)
        self.control_mu = _sync.RLock(name="control_mu")
        self.step_mu = _sync.Lock(name="_step_mu")
        self.op_mu = _sync.Lock(name="_op_mu")
        self.susp_mu = _sync.Lock(name="_susp_mu")
        self.suspended = _sync.Event(name="suspended")
        self.susp_depth = 0
        self.servers = {"s0a": _ModelServer("s0a"),
                        "s0b": _ModelServer("s0b")}
        self.routing = {"epoch": 0,
                        "shards": [{"primary": "s0a", "backups": ["s0b"]}]}
        # the failure-detector's view: s0a's lease has expired (the
        # gate's drain delayed its heartbeats past the TTL) — the
        # coordinator WILL promote s0b if allowed to scan
        self.alive = {"s0b"}

    # routing store (read-modify-write; publish must be single-writer)
    def read_routing(self):
        self.sched.yield_point("routing.read")
        shards = [dict(sh, backups=list(sh["backups"]))
                  for sh in self.routing["shards"]]
        return self.routing["epoch"], shards

    def publish(self, epoch, shards):
        self.sched.yield_point("routing.publish")
        self.sched.check(
            epoch == self.routing["epoch"] + 1,
            f"routing clobbered: publish(epoch={epoch}) over live epoch "
            f"{self.routing['epoch']} — a stale read-modify-write won the "
            "race (suspend() exists to keep the routing table single-"
            "writer)")
        self.routing = {"epoch": epoch, "shards": shards}

    def pause(self, ep: str, on: bool) -> None:
        srv = self.servers[ep]
        with srv.pause_mu:
            srv.pause_depth += 1 if on else -1

    # FailoverCoordinator.suspend()/resume_scans()
    def suspend(self) -> None:
        if self.depth_counted:
            with self.susp_mu:
                self.susp_depth += 1
                self.suspended.set()
        else:
            self.suspended.set()
        with self.step_mu:
            pass            # barrier: in-flight scan finishes

    def resume_scans(self) -> None:
        if self.depth_counted:
            with self.susp_mu:
                self.susp_depth = max(0, self.susp_depth - 1)
                if self.susp_depth == 0:
                    self.suspended.clear()
        else:
            self.suspended.clear()

    # -- tasks ------------------------------------------------------------

    def failover_step(self) -> None:
        """FailoverCoordinator.step(): promote the backup of a
        lease-expired primary, fence, publish."""
        with self.step_mu:
            if self.suspended.is_set():
                return
            epoch, shards = self.read_routing()
            sh = shards[0]
            if sh["primary"] in self.alive:
                return
            cands = [b for b in sh["backups"] if b in self.alive]
            if not cands:
                return
            new_prim = cands[0]
            self.sched.yield_point("fence")     # epoch fence RPC
            sh["primary"] = new_prim
            sh["backups"] = [b for b in sh["backups"] if b != new_prim]
            self.publish(epoch + 1, shards)

    def gate_capture(self) -> None:
        """CheckpointGate + the capture loop of JobCheckpointManager.
        _capture: pause the routed primaries under control_mu, then
        stream each table off the (re-resolved) routed primary."""
        if self.gate_suspends:
            self.suspend()
        self.control_mu.acquire()
        targets = []
        try:
            _, shards = self.read_routing()
            targets = [sh["primary"] for sh in shards]
            for ep in targets:
                self.pause(ep, True)
            # two registered tables; each read re-resolves the topology
            # (RemoteSparseTable.refresh_routing under the gate)
            captured = []
            for tbl in range(2):
                _, now = self.read_routing()
                ep = now[0]["primary"]
                srv = self.servers[ep]
                with srv.pause_mu:
                    self.sched.check(
                        srv.pause_depth > 0,
                        f"torn cut: capture streamed table{tbl} from "
                        f"UNPAUSED {ep} — a mid-capture promotion routed "
                        "the capture (and the writers) to a backup the "
                        "gate never paused")
                    captured.append(srv.data)
            self.sched.check(
                captured[0] == captured[1],
                f"torn cut: tables captured at different data versions "
                f"{captured} — mutations landed between table streams")
        finally:
            for ep in reversed(targets):
                self.pause(ep, False)
            self.control_mu.release()
            if self.gate_suspends:
                self.resume_scans()

    def reshard_cutover(self) -> None:
        """ReshardController._cutover: suspend scans, flip the routing
        epoch under control_mu with sources paused."""
        with self.op_mu:
            self.suspend()
            prims = []
            try:
                self.control_mu.acquire()
                try:
                    epoch, shards = self.read_routing()
                    prims = [sh["primary"] for sh in shards]
                    for ep in prims:
                        self.pause(ep, True)
                    self.publish(epoch + 1, shards)   # the flip
                finally:
                    self.control_mu.release()
                # resume OUTSIDE control_mu (the real finally's order)
                for ep in reversed(prims):
                    self.pause(ep, False)
            finally:
                self.resume_scans()

    def writer(self) -> None:
        """A trainer push path: route, then mutate iff unpaused."""
        for _ in range(2):
            _, shards = self.read_routing()
            srv = self.servers[shards[0]["primary"]]
            with srv.pause_mu:
                if srv.pause_depth == 0:
                    srv.data += 1


def three_way_model(gate_suspends: bool = True, depth_counted: bool = True,
                    with_reshard: bool = True, with_writer: bool = True):
    """Model factory for Explorer: gate × failover × reshard (+writer).

    The writer widens the schedule space considerably; the systematic
    pb-2 sweep runs the pure three-way (``with_writer=False``, where
    the UNPAUSED-read check alone detects the torn cut) to exhaustion,
    and the random-walk sweep adds the writer back for data-version
    tears."""

    def model(sched):
        tw = _ThreeWay(sched, gate_suspends, depth_counted)
        sched.spawn(tw.gate_capture, name="gate")
        sched.spawn(tw.failover_step, name="failover")
        if with_reshard:
            sched.spawn(tw.reshard_cutover, name="reshard")
        if with_writer:
            sched.spawn(tw.writer, name="writer")

    return model


# ---------------------------------------------------------------------------
# 2. ServingFleet drain vs watcher tick (REAL class under the scheduler)
# ---------------------------------------------------------------------------

class _StubFrontend:
    def __init__(self):
        self.stopped = False

    def idle(self) -> bool:
        return True

    def stop(self) -> None:
        self.stopped = True


class _StubReplica:
    def close(self) -> None:
        pass

    def kill(self) -> None:
        pass


class _StubMember:
    """Duck-typed FleetMember: healthy, leased, no warm-handoff tier."""

    def __init__(self, ep: str) -> None:
        self.endpoint = ep
        self.frontend = _StubFrontend()
        self.replica = _StubReplica()
        self.lookup = None

    @property
    def healthy(self) -> bool:
        return True

    def stop(self) -> None:
        self.frontend.stop()

    def crash(self) -> None:
        self.frontend.stop()


class _StubRouter:
    def __init__(self):
        self._mu = _sync.Lock(name="router_mu")
        self._eps = []

    def attach(self, member) -> None:
        with self._mu:
            if member.endpoint not in self._eps:
                self._eps.append(member.endpoint)

    def eject(self, ep: str) -> None:
        with self._mu:
            if ep in self._eps:
                self._eps.remove(ep)

    def remove(self, ep: str) -> None:
        with self._mu:
            if ep in self._eps:
                self._eps.remove(ep)

    def endpoints(self):
        with self._mu:
            return list(self._eps)

    def inflight(self, ep: str) -> int:
        return 0


class _StubStore:
    """Both members hold live observer leases for the whole run."""

    def list_prefix(self, prefix: str):
        return [f"{prefix}m0", f"{prefix}m1"]


def fleet_drain_tick_model():
    """drain("m1") racing two watcher tick()s.  Starting state: m1 was
    ejected by the router on a transient error (the heal path's
    trigger), so every tick WANTS to re-admit it while the drain is
    taking it out on purpose.  Every interleaving must end with m1
    out of routing, out of membership, and stopped."""
    from paddle_tpu.serving.fleet import ServingFleet

    def model(sched):
        router = _StubRouter()
        fleet = ServingFleet(_StubStore(), "sched", lambda: None, router,
                             clock=lambda: 0.0, sleep=lambda s: None)
        m0, m1 = _StubMember("m0"), _StubMember("m1")
        fleet._members = {"m0": m0, "m1": m1}
        fleet._join_order = ["m0", "m1"]
        router._eps = ["m0"]           # m1 ejected on a transient error

        def drainer():
            fleet.drain("m1")

        def ticker():
            for _ in range(2):
                fleet.tick()

        sched.spawn(drainer, name="drain")
        sched.spawn(ticker, name="tick")

        def finish():
            assert "m1" not in router.endpoints(), \
                "drained member re-admitted to routing after drain()"
            assert "m1" not in fleet._members, \
                "drained member still in fleet membership"
            assert m1.frontend.stopped, "drained member never stopped"
            assert "m0" in router.endpoints(), \
                "healthy member m0 fell out of routing"
        sched.on_finish(finish)

    return model


# ---------------------------------------------------------------------------
# 3. JobCheckpointManager writer vs save()/stop() (REAL class)
# ---------------------------------------------------------------------------

def ssd_compact_model(two_phase: bool = True, with_shrink: bool = True):
    """Cold-tier background compactor (csrc/ssd_table.cc) in miniature:
    the two-phase compaction sweep racing a push-path rewrite, a
    promote-on-read, a save snapshot and (full variant) a lifecycle
    shrink, using the REAL lock names from the csrc declaration
    (``ssd_save_mu < mem_save_mu < shard_mu < disk_mu < bg_mu``, leaf
    ``io_mu``) so the dynamic checker validates the same ``// LOCK
    ORDER:`` grammar pass 2 reads statically.

    ``two_phase=False`` reproduces the naive single-phase publisher
    (install the phase-A snapshot verbatim instead of reconciling
    against the live index under ``disk_mu``): a rewrite landing during
    the unlocked copy is reverted to its stale version, and a key
    promoted to RAM during the copy is resurrected on disk — the save
    snapshot then sees it in BOTH tiers.  The default (the shipped
    phase-B reconcile) must explore clean."""

    def model(sched):
        sh = _SsdShardModel(sched, two_phase)
        sched.spawn(sh.writer, name="push")
        sched.spawn(sh.bg_worker, name="bg")
        sched.spawn(sh.reader, name="pull")
        sched.spawn(sh.saver, name="save")
        if with_shrink:
            sched.spawn(sh.shrinker, name="shrink")

        def finish():
            assert sh.index_val("k0") == 2, \
                f"push-path rewrite lost: k0 is {sh.index_val('k0')!r} " \
                "on disk, last write was 2 — a compaction published a " \
                "stale phase-A copy over it"
            assert "k1" in sh.hot and "k1" not in sh.index, \
                "promoted key resurrected on disk by compaction " \
                f"(hot={'k1' in sh.hot}, cold={'k1' in sh.index})"
            assert sh.index_val("k2") == 1, "bystander row k2 lost"
        sched.on_finish(finish)

    return model


class _SsdShardModel:
    """One cold shard: append-only ``log`` of (key, flag, value)
    records (ordinal = position, flag 0 = dead), ``index`` key ->
    ordinal, ``hot`` the RAM tier.  A key lives in at most ONE tier."""

    def __init__(self, sched, two_phase: bool) -> None:
        self.sched = sched
        self.two_phase = two_phase
        self.save_mu = _sync.Lock(name="ssd_save_mu")
        self.mem_save_mu = _sync.Lock(name="mem_save_mu")
        self.shard_mu = _sync.Lock(name="shard_mu")
        self.disk_mu = _sync.Lock(name="disk_mu")
        self.bg_mu = _sync.Lock(name="bg_mu")
        self.io_mu = _sync.Lock(name="io_mu")
        # k0 will be rewritten by the push path, k1 promoted by the
        # read path, k2 is the bystander; ord 3 is pre-existing garbage
        # (the policy debt that seeds bg_dirty)
        self.log = [("k0", 1, 1), ("k1", 1, 1), ("k2", 1, 1),
                    ("k0", 0, 0)]
        self.index = {"k0": 0, "k1": 1, "k2": 2}
        self.hot = {"h0": 1}
        self.bg_dirty = 1
        self.bg_busy = False

    def index_val(self, key):
        ord_ = self.index.get(key)
        return None if ord_ is None else self.log[ord_][2]

    def _request_bg(self, level: int) -> None:
        with self.bg_mu:          # nested under shard_mu+disk_mu
            if self.bg_dirty < level:
                self.bg_dirty = level

    def _check_index(self) -> None:
        for key, ord_ in self.index.items():
            rec = self.log[ord_] if 0 <= ord_ < len(self.log) else None
            self.sched.check(
                rec is not None and rec[0] == key and rec[1] == 1,
                f"index[{key}] = {ord_} points at a dead or mismatched "
                "record after publish")

    # -- tasks ------------------------------------------------------------

    def writer(self) -> None:
        """Push path: rewrite k0's cold row (append + repoint), then
        hand the garbage to the worker (maybe_compact)."""
        with self.shard_mu:
            with self.disk_mu:
                self.sched.yield_point("push.rewrite")
                self.log.append(("k0", 1, 2))
                self.index["k0"] = len(self.log) - 1
                self._request_bg(1)

    def reader(self) -> None:
        """Pull path: serve k1 from disk (io charge — leaf lock under
        disk_mu), then promote it: hot insert + INDEX-ONLY erase."""
        with self.shard_mu:
            with self.disk_mu:
                with self.io_mu:   # charge_serve: leaf, never blocks
                    pass
                self.sched.yield_point("pull.promote")
                ord_ = self.index.pop("k1", None)
                if ord_ is not None:
                    self.hot["k1"] = self.log[ord_][2]

    def saver(self) -> None:
        """sst_save_begin: both save locks, then both tier locks per
        shard — the snapshot must see every key in exactly one tier."""
        with self.save_mu:
            with self.mem_save_mu:
                with self.shard_mu:
                    with self.disk_mu:
                        self.sched.yield_point("save.snapshot")
                        both = set(self.hot) & set(self.index)
                        self.sched.check(
                            not both,
                            f"save snapshot sees {sorted(both)} in BOTH "
                            "tiers — a compaction resurrected a "
                            "promoted key on disk")

    def shrinker(self) -> None:
        """sst_shrink's disk sweep: rewrite every live cold row, then
        force-request compaction of the garbage it just made."""
        with self.shard_mu:
            with self.disk_mu:
                for key in sorted(self.index):
                    val = self.log[self.index[key]][2]
                    self.sched.yield_point("shrink.rewrite")
                    self.log.append((key, 1, val))
                    self.index[key] = len(self.log) - 1
                self._request_bg(2)

    def bg_worker(self) -> None:
        """bg_main in miniature: two dirty-flag sweeps, each running
        the two-phase compaction off the flag set under bg_mu."""
        for _ in range(2):
            with self.bg_mu:
                dirty = self.bg_dirty
                self.bg_dirty = 0
                self.bg_busy = dirty > 0
            if dirty:
                self._compact_bg()
                with self.bg_mu:
                    self.bg_busy = False

    def _compact_bg(self) -> None:
        # phase A: snapshot under disk_mu
        with self.disk_mu:
            snap_log = list(self.log)
            snap_ords = sorted(self.index.values())
        # unlocked budgeted copy (io_mu = acquire_bg's token bucket)
        self.sched.yield_point("compact.copy")
        with self.io_mu:
            pass
        new_log = []
        new_of = {}
        for ord_ in snap_ords:
            key, flag, val = snap_log[ord_]
            if not flag:
                continue
            new_of[ord_] = len(new_log)
            new_log.append((key, flag, val))
        # phase B: reconcile against the LIVE index + swap, under the
        # lock.  The naive publisher skips reconciliation and installs
        # the snapshot's view verbatim.
        with self.disk_mu:
            self.sched.yield_point("compact.publish")
            if self.two_phase:
                fresh = {}
                for key, ord_ in self.index.items():
                    if ord_ not in new_of:
                        # appended/rewritten during the copy: take the
                        # live record now, under the lock
                        new_of[ord_] = len(new_log)
                        new_log.append(self.log[ord_])
                    fresh[key] = new_of[ord_]
            else:
                fresh = {snap_log[o][0]: n for o, n in new_of.items()}
            self.log = new_log
            self.index = fresh
            self._check_index()


def ckpt_writer_model(root: str = None):
    """Two save()s racing stop() over a depth-1 queue: admission is
    atomic under _mu, the backpressured put is lock-free, and stop()'s
    sentinel must land BEHIND every admitted snapshot."""
    from paddle_tpu.io.job_checkpoint import JobCheckpointManager

    base = root or os.path.join(tempfile.gettempdir(), "graftsched-ckpt")

    def model(sched):
        shutil.rmtree(base, ignore_errors=True)
        os.makedirs(base, exist_ok=True)
        mgr = JobCheckpointManager(base, max_keep=4, queue_depth=1)
        written = []
        mgr._write = lambda snap: written.append(snap.ckpt_id)
        admitted = []

        def saver(step):
            try:
                admitted.append(mgr.save(step))
            except Exception:      # noqa: BLE001 — save-after-stop is a
                pass               # legal loser of the race

        def stopper():
            mgr.stop()

        sched.spawn(lambda: saver(1), name="saver1")
        sched.spawn(lambda: saver(2), name="saver2")
        sched.spawn(stopper, name="stop")

        def finish():
            assert set(admitted) <= set(written), \
                f"admitted snapshot lost: save() returned {admitted} but " \
                f"writer only wrote {written} — a snapshot landed behind " \
                "the shutdown sentinel"
            assert mgr._thread is None or not mgr._thread.is_alive(), \
                "writer thread survived stop()"
        sched.on_finish(finish)

    return model


# ---------------------------------------------------------------------------
# 4. declarative reconciler: proposers × serialized actuator × failover
# ---------------------------------------------------------------------------

class _ReconcilerModel:
    """ps/reconcile.py actuation protocol in miniature, REAL lock names
    (``_act_mu``, ``control_mu``, ``_step_mu``, ``_susp_mu``,
    ``_spec_mu``): proposers read-modify-write the versioned spec doc,
    actuator passes diff observed vs desired and sequence cutovers
    through ``begin_actuation`` (suspend scans, then ``control_mu``),
    and a lease-expiry failover scan races both.  ``serialized=False``
    reproduces the pre-reconciler world — two control loops each
    diffing and actuating directly, with no actuator mutex between
    diff and apply: both observe 2 shards with 4 desired and both
    admit the grow, so the second one actuates a STALE plan (the
    doubled-transition bug the single-actuator discipline removes).
    The default must explore clean."""

    def __init__(self, sched, serialized: bool) -> None:
        self.sched = sched
        self.serialized = serialized
        self.act_mu = _sync.Lock(name="_act_mu")
        self.control_mu = _sync.RLock(name="control_mu")
        self.step_mu = _sync.Lock(name="_step_mu")
        self.susp_mu = _sync.Lock(name="_susp_mu")
        self.spec_mu = _sync.Lock(name="_spec_mu")
        self.suspended = _sync.Event(name="suspended")
        self.susp_depth = 0
        self.spec = {"version": 0, "shards": 2, "trainer_np": 4}
        self.routing = {"epoch": 0, "shards": 2, "primary": "s0a"}
        # s0a's lease has expired; the scan WILL promote s0b if allowed
        self.alive = {"s0b"}

    # -- spec store (SpecStore.propose: rmw under _spec_mu) ---------------

    def read_spec(self) -> dict:
        self.sched.yield_point("spec.read")
        return dict(self.spec)

    def propose(self, field: str, value) -> None:
        with self.spec_mu:
            cur = dict(self.spec)
            self.sched.yield_point("spec.rmw")
            if cur[field] == value:
                return
            cur[field] = value
            cur["version"] = self.spec["version"] + 1
            self.spec = cur

    # -- routing + failover-suspend (HACluster/FailoverCoordinator) -------

    def publish(self, epoch: int, **delta) -> None:
        self.sched.yield_point("routing.publish")
        self.sched.check(
            epoch == self.routing["epoch"] + 1,
            f"routing clobbered: publish(epoch={epoch}) over live epoch "
            f"{self.routing['epoch']} — the routing table must stay "
            "single-writer (begin_actuation's suspend exists for this)")
        self.routing = dict(self.routing, epoch=epoch, **delta)

    def suspend(self) -> None:
        with self.susp_mu:
            self.susp_depth += 1
            self.suspended.set()
        with self.step_mu:
            pass            # barrier: in-flight scan finishes

    def resume_scans(self) -> None:
        with self.susp_mu:
            self.susp_depth = max(0, self.susp_depth - 1)
            if self.susp_depth == 0:
                self.suspended.clear()

    # -- tasks ------------------------------------------------------------

    def proposer_shards(self) -> None:
        """Autoscaler-as-proposer: desired shards 2 -> 4."""
        self.propose("shards", 4)

    def proposer_np(self) -> None:
        """Elastic-trainer proposer: desired trainer_np 4 -> 8."""
        self.propose("trainer_np", 8)

    def failover_step(self) -> None:
        """FailoverCoordinator.step(): promote the expired primary's
        backup unless actuation has the scans suspended."""
        with self.step_mu:
            if self.suspended.is_set():
                return
            self.sched.yield_point("scan.read")
            epoch = self.routing["epoch"]
            if self.routing["primary"] in self.alive:
                return
            self.sched.yield_point("scan.fence")
            self.publish(epoch + 1, primary="s0b")

    def actuator(self, who: str) -> None:
        """One reconcile pass: diff spec vs observed, actuate to
        convergence.  The real Reconciler holds ``_act_mu`` across the
        WHOLE diff-and-apply; the knob drops it."""
        if self.serialized:
            self.act_mu.acquire()
        try:
            self._reconcile_pass(who)
        finally:
            if self.serialized:
                self.act_mu.release()

    def _reconcile_pass(self, who: str) -> None:
        desired = self.read_spec()["shards"]
        self.sched.yield_point("reconcile.observe")
        observed = self.routing["shards"]
        while observed != desired:
            self.suspend()       # begin_actuation: scans first,
            try:                 # then the control mutex
                self.control_mu.acquire()
                try:
                    live = self.routing["shards"]
                    self.sched.check(
                        live == observed,
                        f"stale transition admitted by {who}: planned "
                        f"{'grow' if desired > observed else 'shrink'} "
                        f"from {observed} shards but the live topology "
                        f"has {live} — a second actuator applied the "
                        "step first (the actuator mutex + per-step "
                        "verification exist to refuse exactly this)")
                    new_n = live * 2 if desired > live else live // 2
                    self.publish(self.routing["epoch"] + 1, shards=new_n)
                finally:
                    self.control_mu.release()
            finally:
                self.resume_scans()
            self.sched.yield_point("reconcile.observe")
            observed = self.routing["shards"]


def reconciler_model(serialized: bool = True,
                     with_np_proposer: bool = True):
    """Model factory for Explorer: proposer(s) × two actuator passes ×
    lease-expiry failover.  The pb-2 sweep runs the lean variant (one
    proposer) to exhaustion; the random walk adds the trainer_np
    proposer back for spec-write interleavings."""

    def model(sched):
        rc = _ReconcilerModel(sched, serialized)
        sched.spawn(rc.proposer_shards, name="propose")
        if with_np_proposer:
            sched.spawn(rc.proposer_np, name="propose-np")
        sched.spawn(lambda: rc.actuator("act1"), name="act1")
        sched.spawn(lambda: rc.actuator("act2"), name="act2")
        sched.spawn(rc.failover_step, name="failover")

        def finish():
            assert rc.routing["shards"] in (2, 4), \
                f"topology overshot: {rc.routing['shards']} shards " \
                "(desired never exceeded 4) — doubled actuation"
            assert rc.spec["shards"] == 4, \
                f"shards proposal lost: spec says {rc.spec['shards']}"
            want_ver = 2 if with_np_proposer else 1
            assert rc.spec["version"] == want_ver, \
                f"spec version {rc.spec['version']} != {want_ver} — a " \
                "proposal was lost or double-counted under _spec_mu"
            if with_np_proposer:
                assert rc.spec["trainer_np"] == 8, \
                    "trainer_np proposal lost to a concurrent rmw"
        sched.on_finish(finish)

    return model
