"""Hot-tier vs RPC-only sparse-embedding bench (ROADMAP item 1 rung).

Identical seeded DeepFM streams train against a real 2-shard RPC PS
cluster (NativePsServer + RpcPsClient + HalfAsyncCommunicator — the
production transport, not a local table):

- **rpc_only** — every batch pulls/pushes over the RPC wire (the PR-2
  overlapped path);
- **hot_tier** — the persistent single-chip HBM tier (ps/hot_tier.py):
  after one admission epoch the working set is device-resident and the
  measured epoch's steps run entirely in-graph;
- **sharded** (the multi-host rung) — the banked multi-host tier on an
  8-device mesh (per-bank row blocks = per-shard HBM, ``all_to_all``
  id/vector exchange). Multi-device backends run it in-process; a
  1-device backend (the CPU CI rung) re-runs THIS script in a
  subprocess with 8 virtual CPU devices (the dense_comm_bench
  pattern). The sharded record also carries ``exchange_bytes``: the
  compiled step's collective wire bytes (tools/hlo_bytes.py) under the
  routed ``all_to_all`` formulation vs the gathered
  ``all_gather``+``reduce_scatter`` fallback — the proof that the
  routed exchange moves fewer bytes, independent of host timing noise.

All arms measure their SECOND epoch (compile warm, rows created — the
steady state the tier exists for) and report samples/sec, the per-step
PS RPC count (RpcPsClient.op_counts deltas — the hot-tier CI gate's
counter), and the tier's hit-rate/occupancy stats. The headline
``value`` is hot-tier samples/sec; ``speedup_vs_rpc_only`` and the
0-RPC claim ride the record for the CI full gate.

Standalone: prints exactly ONE JSON line (driver contract). Importable:
``run()`` returns the record — bench.py embeds it in its single
emission under ``sparse_hot``. Env knobs: SHB_BATCH, SHB_SAMPLES,
SHB_NID, SHB_CAPACITY, SHB_SLOTS, SHB_SHARDED (0 skips the rung),
SHB_KERNELS (hot-tier kernels knob: auto|pallas|jnp).
"""

import json
import os
import sys
import time

METRIC = "sparse_hot_samples_per_sec"
_CHILD_ENV = "SHB_ROLE"   # set to "sharded" in the 8-virtual-dev child


def _params():
    return {
        "S": int(os.environ.get("SHB_SLOTS", 8)),
        "D": 4,
        "batch": int(os.environ.get("SHB_BATCH", 256)),
        "n_samples": int(os.environ.get("SHB_SAMPLES", 4096)),
        "nid": int(os.environ.get("SHB_NID", 1500)),
        "capacity": int(os.environ.get("SHB_CAPACITY", 1 << 14)),
        "kernels": os.environ.get("SHB_KERNELS", "auto"),
    }


def _dataset(p):
    import numpy as np

    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc

    S, D = p["S"], p["D"]
    rng = np.random.default_rng(0)
    lines = []
    for _ in range(p["n_samples"]):
        ids = rng.integers(0, p["nid"], S)
        dense = rng.normal(size=D)
        label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
        lines.append(" ".join([f"1 {v}" for v in ids]
                              + [f"1 {v:.4f}" for v in dense]
                              + [f"1 {label}"]))
    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1) for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1) for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])
    ds = InMemoryDataset(slots, seed=0)
    ds.load_from_lines(lines)
    return ds


def _measure(p, ds, hot):
    """One arm: train two epochs against a real RPC PS cluster, time
    the second (warm) one. ``hot`` = HotTierConfig | None (rpc-only)."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps import rpc
    from paddle_tpu.ps.communicator import HalfAsyncCommunicator
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer
    from paddle_tpu.ps.table import TableConfig

    S, D, batch = p["S"], p["D"], p["batch"]
    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    client = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers])
    try:
        client.create_sparse_table(
            0, TableConfig(table_id=0, shard_num=4, accessor="ctr"))
        comm = HalfAsyncCommunicator(client)
        comm.start()
        pt.seed(0)
        tr = CtrStreamTrainer(
            DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D,
                             embedx_dim=8, dnn_hidden=(64, 64))),
            optimizer.Adam(1e-2), None, embedx_dim=8,
            sparse_slots=[f"s{i}" for i in range(S)],
            dense_slots=[f"d{i}" for i in range(D)],
            label_slot="label", communicator=comm, table_id=0,
            hot_tier=hot)
        tr.train_from_dataset(ds, batch_size=batch)  # warm-up epoch
        pre = tr.hot_tier.stats() if hot is not None else None
        client.reset_op_counts()
        t0 = time.perf_counter()
        out = tr.train_from_dataset(ds, batch_size=batch)
        wall = time.perf_counter() - t0
        counts = client.reset_op_counts()
        comm.stop()
        steps = max(out["steps"], 1.0)
        rec = {
            # wall-clock rate, not the result dict's (which excludes
            # the trailing barrier drain the RPC path relies on)
            "samples_per_sec": round(out["samples"] / wall, 1),
            "rpc_per_step": round(sum(counts.values()) / steps, 3),
            "rpc_ops": dict(counts),
            "steps": int(steps),
        }
        if hot is not None:
            st = out["hot_tier"]
            total = ((st["hits"] - pre["hits"])
                     + (st["misses"] - pre["misses"]))
            rec["hit_rate"] = round(
                (st["hits"] - pre["hits"]) / max(total, 1), 4)
            rec["occupancy"] = st["occupancy"]
            rec["evictions"] = st["evictions"]
            rec["shards"] = st["shards"]
            rec["banks"] = st["banks"]
            rec["kernels"] = st["kernels"]
        return rec
    finally:
        client.close()
        for s in servers:
            s.stop()


def _exchange_bytes(p, mesh, routing):
    """Compile (don't run) the sharded hot step under ``routing`` and
    report its collective wire bytes from the optimized HLO — the
    timing-independent half of the multi-host claim."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    here = os.path.dirname(os.path.abspath(__file__))
    if here not in sys.path:
        sys.path.insert(0, here)
    import hlo_bytes

    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps.hot_tier import HotEmbeddingTier, HotTierConfig
    from paddle_tpu.ps.table import MemorySparseTable, TableConfig
    from paddle_tpu.ps.hot_tier import make_sharded_hot_train_step

    S, D, batch = p["S"], p["D"], p["batch"]
    pt.seed(0)
    model = DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=8,
                             dnn_hidden=(64, 64)))
    opt = optimizer.Adam(1e-2)
    table = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    tier = HotEmbeddingTier(table, HotTierConfig(
        capacity=p["capacity"], mesh=mesh, axis="ps", routing=routing,
        kernels=p["kernels"]))
    step = make_sharded_hot_train_step(
        model, opt, tier.cache_config, mesh,
        slot_ids=np.arange(S), axis="ps", routing=routing, donate=False,
        probe_buckets=tier.device_map.probe_buckets,
        banks=tier.device_map.banks, kernels=p["kernels"])
    params = {"params": dict(model.named_parameters()), "buffers": {}}
    opt_state = opt.init(params)
    lo32 = jnp.zeros((batch, S), jnp.uint32)
    dense = jnp.zeros((batch, D), jnp.float32)
    labels = jnp.zeros((batch,), jnp.int32)
    compiled = step.lower(params, opt_state, tier.state,
                          tier.device_map.device_state(), lo32, dense,
                          labels).compile()
    rep = hlo_bytes.report_compiled(compiled, num_devices=len(jax.devices()))
    by_op = rep["wire_bytes_by_op"]
    # the sparse id/vector exchange: a2a under routed, ag+rs gathered
    return {
        "routing": routing,
        "wire_bytes_by_op": {k: int(v) for k, v in by_op.items()},
        "exchange_bytes": int(by_op.get("all-to-all", 0)
                              + by_op.get("all-gather", 0)
                              + by_op.get("reduce-scatter", 0)),
    }


def _run_sharded(p):
    """The multi-host rung (needs ≥ 8 devices): measured sharded
    samples/s + compile-time exchange-byte proof for both routings."""
    import jax

    from paddle_tpu.core import mesh as mesh_mod
    from paddle_tpu.ps.hot_tier import HotTierConfig

    mesh = mesh_mod.make_mesh({"ps": 8})
    ds = _dataset(p)
    rec = _measure(p, ds, HotTierConfig(capacity=p["capacity"], mesh=mesh,
                                        axis="ps", kernels=p["kernels"]))
    routed = _exchange_bytes(p, mesh, "alltoall")
    gathered = _exchange_bytes(p, mesh, "allgather")
    rec["exchange"] = {
        "alltoall": routed,
        "gathered": gathered,
        "alltoall_over_gathered": round(
            routed["exchange_bytes"] / max(gathered["exchange_bytes"], 1),
            4),
    }
    rec["devices"] = len(jax.devices())
    rec["platform"] = jax.devices()[0].platform
    return rec


def _sharded_rung(p):
    """In-process on a multi-device backend; otherwise a subprocess with
    8 virtual CPU devices (the bench.py dense_comm pattern)."""
    if os.environ.get("SHB_SHARDED", "1") != "1":
        return None
    try:
        import jax

        if len(jax.devices()) >= 8:
            return _run_sharded(p)
        import subprocess

        env = dict(os.environ)
        env.update({
            _CHILD_ENV: "sharded",
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (env.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"
                          ).strip(),
        })
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=900)
        lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
        if not lines:
            # no JSON = the child died before the one-line contract —
            # surface ITS diagnostics, not an IndexError
            return {"error": f"sharded child rc={out.returncode}: "
                             + out.stderr.strip()[-300:]}
        return json.loads(lines[-1])
    except Exception as e:  # noqa: BLE001 — optional rung, never fatal
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def run() -> dict:
    import jax

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from paddle_tpu.ps.hot_tier import HotTierConfig

    p = _params()
    ds = _dataset(p)
    rpc_only = _measure(p, ds, None)
    hot = _measure(p, ds, HotTierConfig(capacity=p["capacity"],
                                        kernels=p["kernels"]))
    sharded = _sharded_rung(p)

    out = {
        "metric": METRIC, "value": hot["samples_per_sec"],
        "unit": "samples/s", "hot_tier": hot, "rpc_only": rpc_only,
        "speedup_vs_rpc_only": round(
            hot["samples_per_sec"] / max(rpc_only["samples_per_sec"], 1e-9),
            3),
        "batch": p["batch"], "n_samples": p["n_samples"],
        "key_universe": p["nid"] * p["S"],
        "capacity": p["capacity"],
        "platform": jax.devices()[0].platform,
    }
    if sharded is not None:
        out["sharded"] = sharded
        if "samples_per_sec" in sharded:
            out["sharded_speedup_vs_rpc_only"] = round(
                sharded["samples_per_sec"]
                / max(rpc_only["samples_per_sec"], 1e-9), 3)
    return out


def main() -> None:
    try:
        if os.environ.get(_CHILD_ENV) == "sharded":
            repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            if repo not in sys.path:
                sys.path.insert(0, repo)
            rec = _run_sharded(_params())
        else:
            rec = run()
    except Exception as e:  # noqa: BLE001 — one-JSON-line contract
        import traceback

        traceback.print_exc(file=sys.stderr)
        rec = {"metric": METRIC, "value": 0.0,
               "error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
