"""Hot-tier vs RPC-only sparse-embedding bench (ROADMAP item 1 rung).

Two identical seeded DeepFM streams train against a real 2-shard RPC PS
cluster (NativePsServer + RpcPsClient + HalfAsyncCommunicator — the
production transport, not a local table):

- **rpc_only** — every batch pulls/pushes over the RPC wire (the PR-2
  overlapped path);
- **hot_tier** — the persistent HBM tier (ps/hot_tier.py): after one
  admission epoch the working set is device-resident and the measured
  epoch's steps run entirely in-graph.

Both measure their SECOND epoch (compile warm, rows created — the
steady state the tier exists for) and report samples/sec, the per-step
PS RPC count (RpcPsClient.op_counts deltas — the hot-tier CI gate's
counter), and the tier's hit-rate/occupancy stats. The headline
``value`` is hot-tier samples/sec; ``speedup_vs_rpc_only`` and the
0-RPC claim ride the record for the CI full gate.

Standalone: prints exactly ONE JSON line (driver contract). Importable:
``run()`` returns the record — bench.py embeds it in its single
emission under ``sparse_hot``. Env knobs: SHB_BATCH, SHB_SAMPLES,
SHB_NID, SHB_CAPACITY, SHB_SLOTS.
"""

import json
import os
import sys
import time

METRIC = "sparse_hot_samples_per_sec"


def run() -> dict:
    import jax
    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps import rpc
    from paddle_tpu.ps.communicator import HalfAsyncCommunicator
    from paddle_tpu.ps.hot_tier import HotTierConfig
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer
    from paddle_tpu.ps.table import TableConfig

    S = int(os.environ.get("SHB_SLOTS", 8))
    D = 4
    batch = int(os.environ.get("SHB_BATCH", 256))
    n_samples = int(os.environ.get("SHB_SAMPLES", 4096))
    nid = int(os.environ.get("SHB_NID", 1500))
    capacity = int(os.environ.get("SHB_CAPACITY", 1 << 14))

    rng = np.random.default_rng(0)
    lines = []
    for _ in range(n_samples):
        ids = rng.integers(0, nid, S)
        dense = rng.normal(size=D)
        label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
        lines.append(" ".join([f"1 {v}" for v in ids]
                              + [f"1 {v:.4f}" for v in dense]
                              + [f"1 {label}"]))
    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1) for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1) for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])
    ds = InMemoryDataset(slots, seed=0)
    ds.load_from_lines(lines)

    def measure(hot):
        servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
        client = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers])
        try:
            client.create_sparse_table(
                0, TableConfig(table_id=0, shard_num=4, accessor="ctr"))
            comm = HalfAsyncCommunicator(client)
            comm.start()
            pt.seed(0)
            tr = CtrStreamTrainer(
                DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D,
                                 embedx_dim=8, dnn_hidden=(64, 64))),
                optimizer.Adam(1e-2), None, embedx_dim=8,
                sparse_slots=[f"s{i}" for i in range(S)],
                dense_slots=[f"d{i}" for i in range(D)],
                label_slot="label", communicator=comm, table_id=0,
                hot_tier=hot)
            tr.train_from_dataset(ds, batch_size=batch)  # warm-up epoch
            pre = tr.hot_tier.stats() if hot is not None else None
            client.reset_op_counts()
            t0 = time.perf_counter()
            out = tr.train_from_dataset(ds, batch_size=batch)
            wall = time.perf_counter() - t0
            counts = client.reset_op_counts()
            comm.stop()
            steps = max(out["steps"], 1.0)
            rec = {
                # wall-clock rate, not the result dict's (which excludes
                # the trailing barrier drain the RPC path relies on)
                "samples_per_sec": round(out["samples"] / wall, 1),
                "rpc_per_step": round(sum(counts.values()) / steps, 3),
                "rpc_ops": dict(counts),
                "steps": int(steps),
            }
            if hot is not None:
                st = out["hot_tier"]
                total = ((st["hits"] - pre["hits"])
                         + (st["misses"] - pre["misses"]))
                rec["hit_rate"] = round(
                    (st["hits"] - pre["hits"]) / max(total, 1), 4)
                rec["occupancy"] = st["occupancy"]
                rec["evictions"] = st["evictions"]
            return rec
        finally:
            client.close()
            for s in servers:
                s.stop()

    rpc_only = measure(None)
    hot = measure(HotTierConfig(capacity=capacity))

    out = {
        "metric": METRIC, "value": hot["samples_per_sec"],
        "unit": "samples/s", "hot_tier": hot, "rpc_only": rpc_only,
        "speedup_vs_rpc_only": round(
            hot["samples_per_sec"] / max(rpc_only["samples_per_sec"], 1e-9),
            3),
        "batch": batch, "n_samples": n_samples, "key_universe": nid * S,
        "capacity": capacity,
        "platform": jax.devices()[0].platform,
    }
    return out


def main() -> None:
    try:
        rec = run()
    except Exception as e:  # noqa: BLE001 — one-JSON-line contract
        import traceback

        traceback.print_exc(file=sys.stderr)
        rec = {"metric": METRIC, "value": 0.0,
               "error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
