"""Billion-row distributed sparse-table composition (VERDICT r4 next #3).

Composes what the repo already ships — N ``NativePsServer`` SUBPROCESSES,
each owning an SSD-tiered shard (csrc/ssd_table.cc), a chunked
``load_cold`` bulk build over the TCP transport, ``RemoteSparseTable``
pass builds (BuildPull from remote shards, ps_gpu_wrapper.cc:299),
sustained training passes at a configurable hot fraction, a mode-0
server-side streaming save (gzip converter), and a full restart +
server-side reload with sampled value parity — at a population sized to
the reference's scale story (README.md:31-34: 1e11 features served by
N-server sharding, memory_sparse_table.h:53-56).

Population auto-sizes to the disk unless DIST_POP is set: the table's
log records plus the gzip'd checkpoint must BOTH fit, so
    pop = min(DIST_POP_CAP, free_bytes * 0.80 / (rec_bytes + save_bytes))
with save_bytes estimated from a measured small-scale save. Whatever is
chosen is recorded in the artifact ("largest that fits, stated").

Emits one JSON line (committed as DIST_SCALE.json). Knobs:
DIST_SERVERS (4), DIST_POP ("auto"), DIST_POP_CAP (1e9), DIST_DIM (4),
DIST_PASSES (3), DIST_PASS_KEYS (400k), DIST_HOT_FRACTION (0.02),
DIST_DIR (tmp), DIST_CHUNK (4M rows per load_cold wave),
DIST_CONVERTER (gzip | raw — the committed artifact used gzip; raw is
~6x faster at ~2x the bytes, see the save_local docstring).

Single-core host caveat (MEASURED.md): run ALONE in the foreground;
rates measured under concurrent load are garbage.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_SERVER = """
import sys
from paddle_tpu.ps.rpc import NativePsServer
import time
s = NativePsServer(port=0, n_trainers=1)
print("READY", s.port, flush=True)
while not s.stopped:
    time.sleep(0.2)
s.close()
"""


def _rss_bytes(pid="self") -> int:
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


def _du(path) -> int:
    total = 0
    for root, _, files in os.walk(path):
        for fn in files:
            try:
                total += os.path.getsize(os.path.join(root, fn))
            except OSError:
                pass
    return total


def spawn_servers(n):
    procs, ports = [], []
    for _ in range(n):
        p = subprocess.Popen([sys.executable, "-c", _SERVER],
                             stdout=subprocess.PIPE, text=True,
                             cwd=os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__))))
        line = p.stdout.readline().strip()
        assert line.startswith("READY"), line
        procs.append(p)
        ports.append(int(line.split()[1]))
    return procs, ports


def restore_only(ckpt: str) -> None:
    """Re-run ONLY the restore leg against an existing save_local
    checkpoint (DIST_RESTORE_ONLY=<ckpt_dir>): fresh server processes,
    fresh SSD directories, server-side load, parity against a sample
    PARSED FROM THE CHECKPOINT TEXT itself (ground truth travels in the
    artifact, so the original client's in-memory sample isn't needed).
    Exists because the first full run's restore leg hit the hash-order
    quadratic-probing bug — build/save/pass numbers from that run stand
    (they completed before the bug bit), and redoing 1.5 h of build to
    re-measure a 15-minute leg after the fix would say nothing new."""
    import gzip
    import json as _json

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu.ps.rpc as rpc
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig
    from paddle_tpu.ps.table import TableConfig, parse_shard_row

    n_servers = int(os.environ.get("DIST_SERVERS", 4))
    dim = int(os.environ.get("DIST_DIM", 4))
    base = os.environ.get("DIST_DIR") or tempfile.mkdtemp(prefix="dist_rest_")
    acc = AccessorConfig(embedx_dim=dim, embedx_threshold=0.0,
                         sgd=SGDRuleConfig(initial_range=0.0))
    with open(os.path.join(ckpt, "meta.json")) as f:
        meta = _json.load(f)
    assert meta["shard_num"] == n_servers, (meta, n_servers)

    # ground-truth sample: first K parseable lines of each shard file
    ed = 1  # adagrad embed state
    want = {}
    for s in range(n_servers):
        path = os.path.join(ckpt, f"part-{s:05d}.shard.gz")
        with gzip.open(path, "rt") as f:
            for _, line in zip(range(500), f):
                parts = line.split()
                if parts:
                    k, row = parse_shard_row(parts, ed, dim, 7 + ed + dim + 1)
                    want[int(k)] = row
    sample = np.asarray(sorted(want), np.uint64)

    out = {"mode": "restore_only", "ckpt": ckpt, "n_servers": n_servers,
           "host_cores": os.cpu_count()}
    procs, cli = [], None
    try:
        procs, ports = spawn_servers(n_servers)
        cli = rpc.RpcPsClient([f"127.0.0.1:{p}" for p in ports])
        cfg = TableConfig(shard_num=8, accessor_config=acc, storage="ssd",
                          ssd_path=os.path.join(base, "tiers_restore"))
        cli.create_sparse_table(0, cfg)
        t0 = time.perf_counter()
        restored = cli.load_local(0, ckpt)
        load_s = time.perf_counter() - t0
        got, found = cli.export_full(0, sample)
        expect = np.stack([want[int(k)] for k in sample])
        parity = bool(found.all()) and bool(
            np.allclose(got, expect, rtol=1e-6, atol=1e-9))
        out["restore"] = {"rows": int(restored), "seconds": round(load_s, 1),
                          "rows_per_s": round(restored / max(load_s, 1e-9)),
                          "sampled_parity": parity,
                          "sample_size": int(len(sample)),
                          "stats": cli.table_stats(0),
                          "server_rss": [_rss_bytes(p.pid) for p in procs]}
        out["ok"] = parity
    finally:
        try:
            if cli is not None:
                cli.stop_servers()
                cli.close()
        except Exception:
            pass
        for p in procs:
            if p.poll() is None:
                p.kill()
        shutil.rmtree(os.path.join(base, "tiers_restore"),
                      ignore_errors=True)
    print(json.dumps(out))


def main() -> None:
    if os.environ.get("DIST_RESTORE_ONLY"):
        restore_only(os.environ["DIST_RESTORE_ONLY"])
        return
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.ps.rpc as rpc
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig
    from paddle_tpu.ps.table import TableConfig

    n_servers = int(os.environ.get("DIST_SERVERS", 4))
    dim = int(os.environ.get("DIST_DIM", 4))
    n_passes = int(os.environ.get("DIST_PASSES", 3))
    pass_keys = int(os.environ.get("DIST_PASS_KEYS", 400_000))
    hot_fraction = float(os.environ.get("DIST_HOT_FRACTION", 0.02))
    chunk = int(os.environ.get("DIST_CHUNK", 4_000_000))
    pop_cap = int(float(os.environ.get("DIST_POP_CAP", 1_000_000_000)))
    base = os.environ.get("DIST_DIR") or tempfile.mkdtemp(prefix="dist_scale_")
    cleanup = "DIST_DIR" not in os.environ
    os.makedirs(base, exist_ok=True)

    pt.seed(0)
    rng = np.random.default_rng(0)
    acc = AccessorConfig(embedx_dim=dim, embedx_threshold=0.0,
                         sgd=SGDRuleConfig(initial_range=0.0))

    out = {"n_servers": n_servers, "embedx_dim": dim,
           "host_cores": os.cpu_count()}
    procs, cli = [], None
    try:
        procs, ports = spawn_servers(n_servers)
        cli = rpc.RpcPsClient([f"127.0.0.1:{p}" for p in ports])
        cfg = TableConfig(shard_num=8, accessor_config=acc, storage="ssd",
                          ssd_path=os.path.join(base, "tiers_a"))
        cli.create_sparse_table(0, cfg)
        full_dim = cli._dims(0)[2]
        rec_bytes = 12 + 4 * full_dim
        out["full_dim"] = full_dim
        out["rec_bytes"] = rec_bytes

        def make_vals(keys):
            n = len(keys)
            vals = np.zeros((n, full_dim), np.float32)
            vals[:, 0] = keys % 26            # slot
            vals[:, 3] = 1.0                  # show
            vals[:, 5] = 0.01 * rng.standard_normal(n).astype(np.float32)
            vals[:, 7] = 1.0                  # has_embedx (ed=1 adagrad)
            vals[:, 8:8 + dim] = 0.01 * rng.standard_normal(
                (n, dim)).astype(np.float32)
            return vals

        # -- size the population to the disk --------------------------------
        pop_env = os.environ.get("DIST_POP", "auto")
        probe_n = 2_000_000
        keys = np.arange(1, probe_n + 1, dtype=np.uint64)
        t0 = time.perf_counter()
        assert cli.load_cold(0, keys, make_vals(keys), chunk=chunk) == probe_n
        probe_rate = probe_n / (time.perf_counter() - t0)
        t0 = time.perf_counter()
        conv = os.environ.get("DIST_CONVERTER", "gzip")
        saved = cli.save_local(0, os.path.join(base, "probe_ckpt"), mode=0,
                               converter=conv)
        probe_save_rate = saved / (time.perf_counter() - t0)
        save_bytes_row = _du(os.path.join(base, "probe_ckpt")) / max(saved, 1)
        shutil.rmtree(os.path.join(base, "probe_ckpt"))
        if pop_env == "auto":
            free = shutil.disk_usage(base).free
            pop = int(free * 0.80 / (rec_bytes + save_bytes_row))
            pop = min(pop, pop_cap)
        else:
            pop = int(float(pop_env))
        pop = max(pop, probe_n)
        out["population"] = pop
        out["sizing"] = {
            "free_bytes_at_start": shutil.disk_usage(base).free,
            "probe_load_rows_per_s": round(probe_rate),
            "probe_save_rows_per_s": round(probe_save_rate),
            "est_save_bytes_per_row": round(save_bytes_row, 1),
            "auto": pop_env == "auto",
        }

        # -- bulk build: the remaining population ---------------------------
        t0 = time.perf_counter()
        chunk_rates = []
        for lo in range(probe_n, pop, chunk):
            n = min(chunk, pop - lo)
            keys = np.arange(lo + 1, lo + 1 + n, dtype=np.uint64)
            tc = time.perf_counter()
            got = cli.load_cold(0, keys, make_vals(keys), chunk=chunk)
            assert got == n, (got, n)
            chunk_rates.append(n / (time.perf_counter() - tc))
        build_s = time.perf_counter() - t0
        st = cli.table_stats(0)
        out["build"] = {
            "rows": pop,
            "seconds": round(build_s, 1),
            "rows_per_s": round((pop - probe_n) / max(build_s, 1e-9)),
            "rate_first_chunk": round(chunk_rates[0]) if chunk_rates else None,
            "rate_last_chunk": round(chunk_rates[-1]) if chunk_rates else None,
            "cold_rows": st["cold_rows"],
            "disk_bytes": st["disk_bytes"],
            "client_rss": _rss_bytes(),
            "server_rss": [_rss_bytes(p.pid) for p in procs],
        }

        # -- sustained passes over a hot working set ------------------------
        from paddle_tpu.ps.rpc import RemoteSparseTable

        remote = RemoteSparseTable(cli, 0, cfg)
        from paddle_tpu import optimizer
        from paddle_tpu.models.ctr import (CtrConfig, DeepFM,
                                           make_ctr_train_step)
        from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache

        hot_pool = max(int(pop * hot_fraction), pass_keys)
        cap = 1 << int(np.ceil(np.log2(max(pass_keys * 1.25, 1 << 18))))
        cache = HbmEmbeddingCache(remote, CacheConfig(
            capacity=cap, embedx_dim=dim, embedx_threshold=0.0))
        ccfg = CtrConfig(num_sparse_slots=8, num_dense=4, embedx_dim=dim,
                         dnn_hidden=(64, 64))
        model = DeepFM(ccfg)
        opt = optimizer.Adam(1e-3)
        params = {"params": dict(model.named_parameters()), "buffers": {}}
        ostate = opt.init(params)
        step = make_ctr_train_step(model, opt, cache.config)
        passes = []
        for pno in range(n_passes):
            # hot keys cluster at the front of the id space + a cold tail
            hot = rng.integers(1, hot_pool + 1,
                               size=int(pass_keys * 0.9)).astype(np.uint64)
            tail = rng.integers(1, pop + 1,
                                size=pass_keys - len(hot)).astype(np.uint64)
            pk = np.concatenate([hot, tail]).reshape(-1, 8)
            t0 = time.perf_counter()
            n_uniq = cache.begin_pass(pk.reshape(-1))
            build_pass_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            for _ in range(20):
                b = rng.integers(0, pk.shape[0], size=512)
                rows = cache.lookup(pk[b].reshape(-1)).reshape(512, 8)
                dense = rng.standard_normal((512, 4)).astype(np.float32)
                lab = (pk[b, 0] % 2).astype(np.int32)
                params, ostate, cache.state, loss = step(
                    params, ostate, cache.state, rows, dense, lab)
            jax.block_until_ready(loss)
            steps_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            cache.end_pass()
            flush_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            spilled = cli.spill(0, hot_budget=hot_pool)
            spill_s = time.perf_counter() - t0
            passes.append({"uniq": int(n_uniq),
                           "build_pull_s": round(build_pass_s, 2),
                           "steps_s": round(steps_s, 2),
                           "flush_s": round(flush_s, 2),
                           "spill_s": round(spill_s, 2),
                           "spilled": int(spilled)})
        out["passes"] = passes
        out["after_passes_stats"] = cli.table_stats(0)

        # sample BEFORE save for post-restore parity
        sample = rng.choice(np.arange(1, pop + 1, dtype=np.uint64), 2000,
                            replace=False)
        want, found = cli.export_full(0, sample)
        assert found.all()

        # -- mode-0 save (server-side streaming, gzip) ----------------------
        ckpt = os.path.join(base, "ckpt")
        t0 = time.perf_counter()
        saved = cli.save_local(0, ckpt, mode=0, converter=conv)
        save_s = time.perf_counter() - t0
        out["save"] = {"rows": int(saved), "seconds": round(save_s, 1),
                       "rows_per_s": round(saved / max(save_s, 1e-9)),
                       "bytes": _du(ckpt),
                       "bytes_per_row": round(_du(ckpt) / max(saved, 1), 1)}

        # -- restart: fresh servers + fresh dirs + server-side reload -------
        cli.stop_servers()
        cli.close()
        cli = None
        for p in procs:
            p.wait(timeout=60)
        procs = []
        shutil.rmtree(os.path.join(base, "tiers_a"))

        procs, ports = spawn_servers(n_servers)
        cli = rpc.RpcPsClient([f"127.0.0.1:{p}" for p in ports])
        cfg_b = TableConfig(shard_num=8, accessor_config=acc, storage="ssd",
                            ssd_path=os.path.join(base, "tiers_b"))
        cli.create_sparse_table(0, cfg_b)
        t0 = time.perf_counter()
        restored = cli.load_local(0, ckpt)
        load_s = time.perf_counter() - t0
        got, found = cli.export_full(0, sample)
        parity = bool(found.all()) and bool(
            np.allclose(got, want, rtol=1e-6, atol=1e-9))
        out["restore"] = {"rows": int(restored), "seconds": round(load_s, 1),
                          "rows_per_s": round(restored / max(load_s, 1e-9)),
                          "sampled_parity": parity,
                          "stats": cli.table_stats(0)}
        out["ok"] = bool(parity and restored == saved)
    finally:
        try:
            if cli is not None:
                cli.stop_servers()
                cli.close()
        except Exception:
            pass
        for p in procs:
            if p.poll() is None:
                p.kill()
        if cleanup:
            shutil.rmtree(base, ignore_errors=True)
    print(json.dumps(out))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — artifact must be one JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"ok": False,
                          "error": f"{type(e).__name__}: {e}"[:300]}))
        sys.exit(0)
