"""Job-checkpoint chaos demo: measured save/restore latency + pause cost.

Drives io/job_checkpoint.py end to end against a replicated HA cluster
under live traffic and emits one JSON line for the bench trajectory:

- **save latency** — N trials of a full blocking job snapshot (gate →
  capture sparse + dense + cursor → CRC32C → fsync → atomic publish)
  of a populated table; p50/p95 ms.
- **pause window** — the mutation-gate hold time per capture (the
  training stall a checkpoint costs — capture only, the bulk IO is
  gated OUT of this window); p50/p95 ms.
- **restore latency** — verify + load + import into a fresh table +
  digest check; p50/p95 ms.
- **fallback check** — the newest checkpoint is deliberately
  bit-flipped; the load must checksum-detect it and fall back
  (``fallback_ok``).

Env knobs: CHAOS_CKPT_TRIALS (default 5), CHAOS_CKPT_ROWS (default
20000), CHAOS_CKPT_OUT (also write JSON there), CHAOS_CKPT_CPU=0 to
keep the ambient jax platform. Exits 0 with an "error" field on
failure (one-JSON-line driver contract).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pct(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def main() -> None:
    out = {"bench": "chaos_ckpt"}
    path = os.environ.get("CHAOS_CKPT_OUT")
    try:
        import shutil
        import tempfile

        import jax

        if os.environ.get("CHAOS_CKPT_CPU", "1") == "1":
            jax.config.update("jax_platforms", "cpu")
        import numpy as np

        from paddle_tpu.io.job_checkpoint import JobCheckpointManager
        from paddle_tpu.ps import ha, rpc
        from paddle_tpu.ps.accessor import AccessorConfig
        from paddle_tpu.ps.sgd_rule import SGDRuleConfig
        from paddle_tpu.ps.table import MemorySparseTable, TableConfig

        out["platform"] = jax.devices()[0].platform
        trials = int(os.environ.get("CHAOS_CKPT_TRIALS", 5))
        rows = int(os.environ.get("CHAOS_CKPT_ROWS", 20000))
        out["trials"], out["rows"] = trials, rows

        cfg = TableConfig(shard_num=4, accessor_config=AccessorConfig(
            sgd=SGDRuleConfig(initial_range=0.0)))
        rng = np.random.default_rng(0)
        root = tempfile.mkdtemp(prefix="chaos_ckpt_")
        dense = {"state": {"w": rng.normal(size=4096).astype(np.float32)},
                 "opt": {"m": rng.normal(size=4096).astype(np.float32)}}
        save_ms, restore_ms = [], []
        with ha.HACluster(num_shards=2, replication=2, sync=True) as cluster:
            cli = cluster.client()
            cli.create_sparse_table(0, cfg)
            remote = rpc.RemoteSparseTable(cli, 0, cfg)
            keys = rng.integers(0, 1 << 40, rows).astype(np.uint64)
            cli.pull_sparse(0, keys, create=True)
            push = np.zeros((len(keys), 12), np.float32)
            push[:, 1] = 1.0
            push[:, 3:] = rng.normal(0, 0.1, (len(keys), 9)).astype(np.float32)
            cli.push_sparse(0, keys, push)
            mgr = JobCheckpointManager(root, max_keep=trials + 2,
                                       gate=cluster.checkpoint_gate())
            mgr.register_sparse("ctr", remote)
            for i in range(trials):
                t0 = time.perf_counter()
                mgr.save(step=i, cursor={"batch": i}, dense=dense,
                         blocking=True)
                save_ms.append((time.perf_counter() - t0) * 1000.0)
            for _ in range(trials):
                t0 = time.perf_counter()
                r = mgr.load_latest()
                fresh = MemorySparseTable(cfg)
                r.restore_sparse("ctr", fresh)
                restore_ms.append((time.perf_counter() - t0) * 1000.0)
            # corruption fallback: flip one byte in the newest artifact
            newest = mgr._ids()[-1]
            art = os.path.join(root, f"ckpt_{newest}", "sparse_ctr.npz")
            with open(art, "r+b") as f:
                f.seek(os.path.getsize(art) // 2)
                b = f.read(1)
                f.seek(-1, 1)
                f.write(bytes([b[0] ^ 0xFF]))
            r = mgr.load_latest()
            out["fallback_ok"] = bool(r.ckpt_id == newest - 1
                                      and len(mgr.fallbacks) == 1)
            pause = sorted(mgr.pause_ms)
            mgr.stop()
        shutil.rmtree(root, ignore_errors=True)
        save_ms.sort()
        restore_ms.sort()
        out["save_ms_p50"] = round(_pct(save_ms, 0.50), 1)
        out["save_ms_p95"] = round(_pct(save_ms, 0.95), 1)
        out["restore_ms_p50"] = round(_pct(restore_ms, 0.50), 1)
        out["restore_ms_p95"] = round(_pct(restore_ms, 0.95), 1)
        out["pause_ms_p50"] = round(_pct(pause, 0.50), 2)
        out["pause_ms_p95"] = round(_pct(pause, 0.95), 2)
    except Exception as e:  # noqa: BLE001 — one-JSON-line contract
        out["error"] = f"{type(e).__name__}: {e}"
    line = json.dumps(out)
    print(line)
    if path:
        with open(path, "w") as f:
            f.write(line + "\n")
    sys.exit(0)


if __name__ == "__main__":
    main()
