"""Shared graftlint infrastructure: diagnostics, allowlist, file walking.

A diagnostic is (path, line, rule, message) with ``path`` repo-relative
and '/'-separated. The allowlist (tools/lint/allow.txt) grandfathers
known sites one `path:line:rule` per entry; the gate is "no NEW
violations", so a diagnostic is only fatal if its exact key is absent.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

IGNORE_RE = re.compile(r"#\s*graftlint:\s*ignore\[([\w,\- ]+)\]")


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def line_ignores(source_lines: List[str], lineno: int) -> Set[str]:
    """Rules suppressed by a `# graftlint: ignore[...]` on this line."""
    if 1 <= lineno <= len(source_lines):
        m = IGNORE_RE.search(source_lines[lineno - 1])
        if m:
            return {r.strip() for r in m.group(1).split(",")}
    return set()


@dataclass(frozen=True)
class Diagnostic:
    path: str   # repo-relative, '/'-separated
    line: int
    rule: str
    message: str

    @property
    def key(self) -> str:
        return f"{self.path}:{self.line}:{self.rule}"

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def relpath(path: str, root: str = REPO_ROOT) -> str:
    return os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")


def walk_py(root: str, subdirs: Iterable[str], files: Iterable[str] = (),
            only: Optional[Set[str]] = None) -> List[str]:
    """All .py files under root/<subdir> for each subdir, plus explicit
    root-relative ``files``, absolute paths, sorted. ``only`` (a set of
    repo-relative paths — run.py's --changed view) restricts the result;
    every pass routes its file discovery through here so the filter
    cannot be forgotten in a new pass."""
    out = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    for f in files:
        p = os.path.join(root, f)
        if os.path.exists(p):
            out.append(p)
    if only is not None:
        out = [p for p in out if relpath(p, root) in only]
    return sorted(out)


@dataclass(frozen=True)
class AllowEntry:
    line: int   # line number inside allow.txt
    why: str    # the justification text (an optional `why:` prefix is
                # stripped) — surfaced in run.py's JSON summary


def load_allowlist(path: str) -> Dict[str, AllowEntry]:
    """Parse allow.txt → {key: AllowEntry}.

    Entry grammar (one per line): ``path:line:rule`` followed by a
    ``# justification`` comment (equivalently ``# why: justification``).
    Blank lines and full-line comments are skipped. A justification is
    REQUIRED on every entry (enforced here) so the file stays
    reviewable; run.py surfaces it per-violation in the JSON summary.
    """
    entries: Dict[str, AllowEntry] = {}
    if not os.path.exists(path):
        return entries
    with open(path, encoding="utf-8") as f:
        for i, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            entry, sep, comment = line.partition("#")
            entry = entry.strip()
            comment = comment.strip()
            if not sep or not comment:
                raise ValueError(
                    f"{path}:{i}: allowlist entry needs a '# justification' "
                    f"comment: {line!r}")
            if comment.lower().startswith("why:"):
                comment = comment[4:].strip()
            parts = entry.rsplit(":", 2)
            if len(parts) != 3 or not parts[1].isdigit():
                raise ValueError(
                    f"{path}:{i}: malformed entry {entry!r} "
                    "(want path:line:rule)")
            entries[entry] = AllowEntry(i, comment)
    return entries


def split_new_and_allowed(
    diags: List[Diagnostic], allow: Dict[str, "AllowEntry"]
) -> Tuple[List[Diagnostic], List[Diagnostic], List[str]]:
    """Partition into (new, allowlisted) and report stale allow entries."""
    new, allowed = [], []
    seen = set()
    for d in diags:
        seen.add(d.key)
        (allowed if d.key in allow else new).append(d)
    stale = sorted(k for k in allow if k not in seen)
    return new, allowed, stale
