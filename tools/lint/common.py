"""Shared graftlint infrastructure: diagnostics, allowlist, file walking.

A diagnostic is (path, line, rule, message) with ``path`` repo-relative
and '/'-separated. The allowlist (tools/lint/allow.txt) grandfathers
known sites one `path:line:rule` per entry; the gate is "no NEW
violations", so a diagnostic is only fatal if its exact key is absent.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

IGNORE_RE = re.compile(r"#\s*graftlint:\s*ignore\[([\w,\- ]+)\]")


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def line_ignores(source_lines: List[str], lineno: int) -> Set[str]:
    """Rules suppressed by a `# graftlint: ignore[...]` on this line."""
    if 1 <= lineno <= len(source_lines):
        m = IGNORE_RE.search(source_lines[lineno - 1])
        if m:
            return {r.strip() for r in m.group(1).split(",")}
    return set()


@dataclass(frozen=True)
class Diagnostic:
    path: str   # repo-relative, '/'-separated
    line: int
    rule: str
    message: str

    @property
    def key(self) -> str:
        return f"{self.path}:{self.line}:{self.rule}"

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def relpath(path: str, root: str = REPO_ROOT) -> str:
    return os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")


def walk_py(root: str, subdirs: Iterable[str], files: Iterable[str] = ()
            ) -> List[str]:
    """All .py files under root/<subdir> for each subdir, plus explicit
    root-relative ``files``, absolute paths, sorted."""
    out = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    for f in files:
        p = os.path.join(root, f)
        if os.path.exists(p):
            out.append(p)
    return sorted(out)


def load_allowlist(path: str) -> Dict[str, int]:
    """Parse allow.txt → {key: line_number_in_allowlist}.

    Entry grammar (one per line): ``path:line:rule`` followed by an
    optional ``# justification`` comment. Blank lines and full-line
    comments are skipped. A justification is REQUIRED on every entry
    (enforced here) so the file stays reviewable.
    """
    entries: Dict[str, int] = {}
    if not os.path.exists(path):
        return entries
    with open(path, encoding="utf-8") as f:
        for i, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            entry, sep, comment = line.partition("#")
            entry = entry.strip()
            if not sep or not comment.strip():
                raise ValueError(
                    f"{path}:{i}: allowlist entry needs a '# justification' "
                    f"comment: {line!r}")
            parts = entry.rsplit(":", 2)
            if len(parts) != 3 or not parts[1].isdigit():
                raise ValueError(
                    f"{path}:{i}: malformed entry {entry!r} "
                    "(want path:line:rule)")
            entries[entry] = i
    return entries


def split_new_and_allowed(
    diags: List[Diagnostic], allow: Dict[str, int]
) -> Tuple[List[Diagnostic], List[Diagnostic], List[str]]:
    """Partition into (new, allowlisted) and report stale allow entries."""
    new, allowed = [], []
    seen = set()
    for d in diags:
        seen.add(d.key)
        (allowed if d.key in allow else new).append(d)
    stale = sorted(k for k in allow if k not in seen)
    return new, allowed, stale
