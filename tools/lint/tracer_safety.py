"""graftlint pass 1: tracer safety — no host syncs inside traced code.

Walks every module under ``paddle_tpu/`` (plus ``bench.py``), resolves
the set of functions REACHABLE from tracing entry points (``jax.jit`` /
``pjit`` / ``shard_map`` decorators or call-site wraps, ``lax.scan`` /
``cond`` / ``while_loop`` bodies, ``grad`` / ``value_and_grad`` /
``vmap`` / ``pmap`` targets), and flags operations that force a device →
host sync or a trace-time side effect inside that set:

  host-sync-item        ``.item()`` / ``.tolist()`` on a value
  host-sync-block       ``.block_until_ready()``
  host-sync-device-get  ``jax.device_get(...)``
  host-sync-np          ``np.asarray`` / ``np.array`` / ``np.ceil`` … —
                        any call into the host numpy module
  host-float-cast       ``float(x)`` / ``int(x)`` / ``bool(x)`` where x
                        is (derived by local assignment from) a traced
                        -function parameter or a ``jnp``/``lax``
                        expression; ``.shape`` / ``.ndim`` / ``.dtype``
                        / ``len()`` chains are static and exempt, as are
                        results of opaque (non-jnp) helper calls
  tracer-branch         ``if``/``while`` on a ``jnp``/``lax`` expression
                        or an order/eq comparison of a param-derived
                        value (a concretization error or a silent host
                        sync); string-literal equality, ``is``/``in``
                        tests and bare param truthiness are treated as
                        static config dispatch and exempt
  global-mutation       ``global`` declaration inside traced code
  host-print            ``print()`` inside traced code (trace-time side
                        effect: fires once per compile, not per step)

Pass 1b (``run_hot_path``, registered separately in run.py) reuses the
same module index and call-graph closure for one more rule:

  hot-host-transfer     ``np.asarray`` / ``np.array`` / ``jax.device_get``
                        in a function reachable from a
                        ``# graftlint: hot-path`` root without crossing a
                        ``# graftlint: cold-path`` boundary — the
                        hot-embedding tier's zero-host-round-trip warm
                        step must not regrow per-step D2H syncs

Resolution is intentionally syntactic (same-module name lookup +
``from x import y`` aliases + ``self.method``); it is precise enough for
this tree and fails open (unresolvable callees are skipped, not
guessed). Suppression: a trailing ``# graftlint: ignore[rule]`` comment
skips that line; a ``# graftlint: traced`` comment on the line above a
``def`` marks an extra traced root (for hot paths invoked by drivers
the linter cannot see, e.g. registered bench step builders).
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from common import (Diagnostic, dotted, line_ignores,  # noqa: E402
                    relpath, walk_py)

# Callables whose function-valued arguments are traced by JAX.
TRACE_WRAPPERS = {
    "jit", "jax.jit", "pjit", "jax.pjit",
    "shard_map", "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "vmap", "jax.vmap", "pmap", "jax.pmap",
    "grad", "jax.grad", "value_and_grad", "jax.value_and_grad",
    "checkpoint", "jax.checkpoint", "remat", "jax.remat",
    "lax.scan", "jax.lax.scan", "lax.cond", "jax.lax.cond",
    "lax.while_loop", "jax.lax.while_loop",
    "lax.fori_loop", "jax.lax.fori_loop",
    "lax.switch", "jax.lax.switch", "lax.map", "jax.lax.map",
    "lax.associative_scan", "jax.lax.associative_scan",
}
PARTIAL_NAMES = {"partial", "functools.partial"}
NUMPY_MODULES = {"numpy"}
JNP_PREFIXES = ("jnp.", "jax.numpy.", "lax.", "jax.lax.")
STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}

_TRACED_RE = re.compile(r"#\s*graftlint:\s*traced\b")


@dataclass
class FuncDef:
    module: str                       # dotted module name
    path: str                         # repo-relative file path
    name: str                         # bare name
    node: ast.AST                     # FunctionDef / AsyncFunctionDef
    params: Set[str] = field(default_factory=set)


@dataclass
class ModuleInfo:
    path: str                               # repo-relative
    modname: str
    tree: ast.Module
    source_lines: List[str]
    # local alias -> fully qualified 'module' or 'module.name' target
    imports: Dict[str, str] = field(default_factory=dict)
    # bare function name -> defs (module-level, methods, nested)
    funcs: Dict[str, List[FuncDef]] = field(default_factory=dict)
    np_aliases: Set[str] = field(default_factory=set)   # e.g. {'np'}
    jnp_aliases: Set[str] = field(default_factory=set)  # e.g. {'jnp','lax'}


def _modname_for(path: str, root: str) -> str:
    rel = relpath(path, root)
    mod = rel[:-3].replace("/", ".")
    return mod[:-9] if mod.endswith(".__init__") else mod


def _collect_module(path: str, root: str) -> Optional[ModuleInfo]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None
    mi = ModuleInfo(path=relpath(path, root), modname=_modname_for(path, root),
                    tree=tree, source_lines=src.splitlines())
    # base package for level-1 relative imports: a package __init__ is
    # its own base (`from . import x` in paddle_tpu/__init__.py means
    # paddle_tpu.x), while for a plain module it is the parent package
    is_pkg = os.path.basename(path) == "__init__.py"
    pkg_parts = mi.modname.split(".") if is_pkg \
        else mi.modname.split(".")[:-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                alias = a.asname or a.name.split(".")[0]
                target = a.name if a.asname else a.name.split(".")[0]
                mi.imports[alias] = target
                if a.name in NUMPY_MODULES:
                    mi.np_aliases.add(alias)
                if a.name in ("jax.numpy", "jax.lax"):
                    mi.jnp_aliases.add(alias)
        elif isinstance(node, ast.ImportFrom):
            if node.module is None and node.level == 0:
                continue
            if node.level:  # relative import → absolute
                base = pkg_parts[:len(pkg_parts) - node.level + 1]
                modname = ".".join(base + ([node.module] if node.module else []))
            else:
                modname = node.module
            for a in node.names:
                alias = a.asname or a.name
                mi.imports[alias] = f"{modname}.{a.name}"
                if modname in ("jax.numpy", "jax.lax", "jax") and \
                        a.name in ("numpy", "lax"):
                    mi.jnp_aliases.add(alias)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            params = {a.arg for a in
                      args.posonlyargs + args.args + args.kwonlyargs}
            if args.vararg:
                params.add(args.vararg.arg)
            if args.kwarg:
                params.add(args.kwarg.arg)
            params.discard("self")
            params.discard("cls")
            fd = FuncDef(module=mi.modname, path=mi.path, name=node.name,
                         node=node, params=params)
            mi.funcs.setdefault(node.name, []).append(fd)
    return mi


class _Index:
    def __init__(self, modules: List[ModuleInfo]):
        self.by_name: Dict[str, ModuleInfo] = {m.modname: m for m in modules}
        self.modules = modules

    def resolve_callable(self, mi: ModuleInfo, name: str) -> List[FuncDef]:
        """Resolve a bare or dotted callable name used in ``mi``."""
        # bare name defined in this module (any nesting level)
        if name in mi.funcs:
            return mi.funcs[name]
        # imported symbol: alias -> module.symbol
        target = mi.imports.get(name.split(".")[0])
        if target is None:
            return []
        if "." in name:  # mod_alias.func
            rest = name.split(".")[1:]
            target = ".".join([target] + rest[:-1])
            sym = rest[-1]
        else:            # from mod import func [as alias]
            target, _, sym = target.rpartition(".")
            if not target:
                return []
        other = self.by_name.get(target)
        if other is None:
            return []
        return other.funcs.get(sym, [])


def _is_trace_wrapper(mi: ModuleInfo, call: ast.Call) -> bool:
    name = dotted(call.func)
    if name is None:
        return False
    if name in TRACE_WRAPPERS:
        return True
    # partial(jax.jit, ...) used as decorator or wrapper
    if name in PARTIAL_NAMES and call.args:
        inner = dotted(call.args[0])
        return inner in TRACE_WRAPPERS
    # alias resolution: `from jax import jit as j` etc.
    target = mi.imports.get(name.split(".")[0])
    if target:
        full = ".".join([target] + name.split(".")[1:])
        return full in TRACE_WRAPPERS
    return False


def _traced_roots(mi: ModuleInfo, index: _Index) -> List[FuncDef]:
    roots: List[FuncDef] = []
    # decorator-marked and comment-marked defs
    for defs in mi.funcs.values():
        for fd in defs:
            node = fd.node
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and _is_trace_wrapper(mi, dec):
                    roots.append(fd)
                elif dotted(dec) in TRACE_WRAPPERS:
                    roots.append(fd)
            ln = node.lineno - 2  # line above `def` (0-based)
            for probe in (ln, ln - len(node.decorator_list)):
                if 0 <= probe < len(mi.source_lines) and \
                        _TRACED_RE.search(mi.source_lines[probe]):
                    roots.append(fd)
    # call-site wraps: jax.jit(f), shard_map(f, ...), lax.scan(f, ...)
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Call) and _is_trace_wrapper(mi, node):
            cands = node.args
            if dotted(node.func) in PARTIAL_NAMES:
                cands = node.args[1:]
            for arg in cands:
                name = dotted(arg)
                if name:
                    roots.extend(index.resolve_callable(mi, name))
    return roots


def _callees(mi: ModuleInfo, fd: FuncDef, index: _Index) -> List[FuncDef]:
    out: List[FuncDef] = []
    for node in ast.walk(fd.node):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name is None:
            continue
        if name.startswith("self."):
            name = name[len("self."):]
        out.extend(index.resolve_callable(mi, name))
        # function-valued args of tracing combinators inside traced code
        if _is_trace_wrapper(mi, node):
            for arg in node.args:
                an = dotted(arg)
                if an:
                    out.extend(index.resolve_callable(mi, an))
    return out


def _expr_is_static(node: ast.AST) -> bool:
    """True for `.shape`/`.ndim`/`.dtype` chains and len() — static at
    trace time, so casting/branching on them is fine."""
    if isinstance(node, ast.Subscript):
        return _expr_is_static(node.value)
    if isinstance(node, ast.Attribute):
        return node.attr in STATIC_ATTRS
    if isinstance(node, ast.Call):
        return dotted(node.func) == "len"
    return False


def _contains_jnp_call(mi: ModuleInfo, node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = dotted(sub.func)
            if not name:
                continue
            head = name.split(".")[0]
            if name.startswith(JNP_PREFIXES) or head in mi.jnp_aliases:
                return True
    return False


def _has_tainted_name(node: ast.AST, tainted: Set[str]) -> bool:
    """A name from ``tainted`` appears outside a static
    `.shape`/`.ndim`/`.dtype`/`len()` chain (those are trace-time
    constants even on tracers, so they don't propagate taint)."""
    if _expr_is_static(node):
        return False
    if isinstance(node, ast.Name):
        return node.id in tainted
    return any(_has_tainted_name(c, tainted)
               for c in ast.iter_child_nodes(node))


def _tainted_names(fd: FuncDef) -> Set[str]:
    """Parameters plus local names (transitively) assigned from them —
    a syntactic over-approximation of "may hold a tracer"."""
    tainted = set(fd.params)
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fd.node):
            if not isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                continue
            value = node.value
            if value is None or not _has_tainted_name(value, tainted):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                names = [t] if isinstance(t, ast.Name) else [
                    e for e in ast.walk(t) if isinstance(e, ast.Name)]
                for n in names:
                    if n.id not in tainted:
                        tainted.add(n.id)
                        changed = True
    return tainted


def _scan_traced_function(mi: ModuleInfo, fd: FuncDef) -> List[Diagnostic]:
    diags: List[Diagnostic] = []

    def emit(node: ast.AST, rule: str, msg: str) -> None:
        line = getattr(node, "lineno", fd.node.lineno)
        if rule not in line_ignores(mi.source_lines, line):
            diags.append(Diagnostic(mi.path, line, rule,
                                    f"{msg} (in traced `{fd.name}`)"))

    own_nested = {n for n in ast.walk(fd.node)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and n is not fd.node}
    tainted = _tainted_names(fd)

    def _is_tainted_expr(node: ast.AST) -> bool:
        """Param-derived without an intervening opaque (non-jnp) call —
        casting/branching on a helper's return is usually static
        trace-time math, so don't guess there."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and not _expr_is_static(sub):
                name = dotted(sub.func)
                head = (name or "").split(".")[0]
                if not (name and (name.startswith(JNP_PREFIXES)
                                  or head in mi.jnp_aliases)):
                    return False  # opaque call: don't guess
        return _has_tainted_name(node, tainted)

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            if node in own_nested:
                return  # nested defs are scanned as their own units
            self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node: ast.Call):
            name = dotted(node.func)
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr in ("item", "tolist"):
                    emit(node, "host-sync-item",
                         f"`.{attr}()` forces a device→host sync")
                elif attr == "block_until_ready":
                    emit(node, "host-sync-block",
                         "`.block_until_ready()` blocks inside traced code")
            if name:
                head = name.split(".")[0]
                if name in ("jax.device_get", "device_get"):
                    emit(node, "host-sync-device-get",
                         "`jax.device_get` pulls values to host")
                elif head in mi.np_aliases:
                    emit(node, "host-sync-np",
                         f"host numpy call `{name}` in traced code "
                         "(use jnp, or hoist to trace-time constants)")
                elif name in ("float", "int", "bool") and len(node.args) == 1:
                    arg = node.args[0]
                    if not _expr_is_static(arg) and (
                            _contains_jnp_call(mi, arg)
                            or _is_tainted_expr(arg)):
                        emit(node, "host-float-cast",
                             f"`{name}()` on a traced value concretizes "
                             "(host sync)")
                elif name == "print":
                    emit(node, "host-print",
                         "print() in traced code fires at trace time only")
            self.generic_visit(node)

        def _branch(self, node, kind):
            test = node.test
            if _expr_is_static(test):
                self.generic_visit(node)
                return
            # jnp/lax expression in the test, OR an ORDER/EQ comparison
            # involving a param-derived value (`if x > 0:` — the
            # canonical TracerBoolConversionError). NOT flagged: bare
            # truthiness of a param (`if pre_dedup:`), comparisons
            # against string literals (`if mode == "sum"`), and
            # is/in tests — those are static config dispatch, which is
            # everywhere in traced builders and fine at trace time.
            cmp_tainted = (
                isinstance(test, ast.Compare)
                and not any(isinstance(op, (ast.Is, ast.IsNot, ast.In,
                                            ast.NotIn))
                            for op in test.ops)
                and not any(isinstance(c, ast.Constant)
                            and isinstance(c.value, str)
                            for c in [test.left] + test.comparators)
                and _is_tainted_expr(test))
            if _contains_jnp_call(mi, test) or cmp_tainted:
                emit(node, "tracer-branch",
                     f"`{kind}` on a traced expression — concretization "
                     "error or silent host sync (use lax.cond/jnp.where)")
            self.generic_visit(node)

        def visit_If(self, node):
            self._branch(node, "if")

        def visit_While(self, node):
            self._branch(node, "while")

        def visit_Global(self, node: ast.Global):
            emit(node, "global-mutation",
                 "`global` mutation inside traced code is a trace-time "
                 "side effect")

    V().visit(fd.node)
    return diags


# ---------------------------------------------------------------------------
# pass 1b: hot-path host transfers — the persistent hot-embedding tier
# (ps/hot_tier.py) exists so a warm step performs ZERO host round-trips;
# an `np.asarray`/`jax.device_get` on a device array anywhere in the
# per-batch step path silently reintroduces a device→host sync per step
# with no functional symptom (bit-parity holds, throughput quietly
# dies). Roots are marked `# graftlint: hot-path` above the def; the
# same syntactic call-graph closure as pass 1 follows callees, EXCEPT
# into functions marked `# graftlint: cold-path` (the miss/eviction/
# writeback handlers — those are RPC-bound by design and own their
# transfers). Within the hot set, every np.ndarray-returning conversion
# (`np.asarray` / `np.array`, any numpy alias) and `jax.device_get` is
# flagged; `# graftlint: ignore[hot-host-transfer]` suppresses a line
# whose argument is provably host data (e.g. python lists).
# ---------------------------------------------------------------------------

_HOT_RE = re.compile(r"#\s*graftlint:\s*hot-path\b")
_COLD_RE = re.compile(r"#\s*graftlint:\s*cold-path\b")


def _marked(mi: ModuleInfo, fd: FuncDef, regex: re.Pattern) -> bool:
    """Marker comment on the line above ``def`` (or above the decorator
    stack) — same probing as `# graftlint: traced`."""
    node = fd.node
    ln = node.lineno - 2  # line above `def` (0-based)
    for probe in (ln, ln - len(node.decorator_list)):
        if 0 <= probe < len(mi.source_lines) and \
                regex.search(mi.source_lines[probe]):
            return True
    return False


def _scan_hot_function(mi: ModuleInfo, fd: FuncDef) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    own_nested = {n for n in ast.walk(fd.node)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and n is not fd.node}

    def emit(node: ast.AST, msg: str) -> None:
        line = getattr(node, "lineno", fd.node.lineno)
        if "hot-host-transfer" not in line_ignores(mi.source_lines, line):
            diags.append(Diagnostic(
                mi.path, line, "hot-host-transfer",
                f"{msg} (reachable from hot-tier step path via "
                f"`{fd.name}`)"))

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            if node in own_nested:
                return  # nested defs scan as their own units (if reached)
            self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node: ast.Call):
            name = dotted(node.func)
            if name:
                head, sym = name.split(".")[0], name.split(".")[-1]
                if name in ("jax.device_get", "device_get"):
                    emit(node, "`jax.device_get` is a per-step device→host "
                               "transfer on the warm path")
                elif head in mi.np_aliases and sym in ("asarray", "array"):
                    emit(node, f"`{name}` materializes an np.ndarray — a "
                               "host transfer when handed a device array; "
                               "keep warm-path data in jnp or mark the "
                               "function `# graftlint: cold-path`")
            self.generic_visit(node)

    V().visit(fd.node)
    return diags


def run_hot_path(root: str, subdirs=("paddle_tpu",), files=("bench.py",),
                 only=None) -> List[Diagnostic]:
    modules = [m for m in (_collect_module(p, root)
                           for p in walk_py(root, subdirs, files, only=only))
               if m is not None]
    index = _Index(modules)

    reachable: Dict[int, Tuple[ModuleInfo, FuncDef]] = {}
    work: List[Tuple[ModuleInfo, FuncDef]] = []
    for mi in modules:
        for defs in mi.funcs.values():
            for fd in defs:
                if _marked(mi, fd, _HOT_RE) and id(fd.node) not in reachable:
                    reachable[id(fd.node)] = (mi, fd)
                    work.append((mi, fd))
    while work:
        mi, fd = work.pop()
        for callee in _callees(mi, fd, index):
            if id(callee.node) in reachable:
                continue
            cmi = index.by_name[callee.module]
            if _marked(cmi, callee, _COLD_RE):
                continue  # declared cold: owns its transfers
            reachable[id(callee.node)] = (cmi, callee)
            work.append((cmi, callee))

    diags: List[Diagnostic] = []
    for mi, fd in reachable.values():
        diags.extend(_scan_hot_function(mi, fd))
    return sorted(diags, key=lambda d: (d.path, d.line, d.rule))


def run(root: str, subdirs=("paddle_tpu",), files=("bench.py",),
        only=None) -> List[Diagnostic]:
    modules = [m for m in (_collect_module(p, root)
                           for p in walk_py(root, subdirs, files, only=only))
               if m is not None]
    index = _Index(modules)

    # seed with roots, then close over the call graph
    reachable: Dict[int, Tuple[ModuleInfo, FuncDef]] = {}
    work: List[Tuple[ModuleInfo, FuncDef]] = []
    for mi in modules:
        for fd in _traced_roots(mi, index):
            if id(fd.node) not in reachable:
                reachable[id(fd.node)] = (mi, fd)
                work.append((mi, fd))
    while work:
        mi, fd = work.pop()
        for callee in _callees(mi, fd, index):
            if id(callee.node) not in reachable:
                cmi = index.by_name[callee.module]
                reachable[id(callee.node)] = (cmi, callee)
                work.append((cmi, callee))

    diags: List[Diagnostic] = []
    for mi, fd in reachable.values():
        diags.extend(_scan_traced_function(mi, fd))
    return sorted(diags, key=lambda d: (d.path, d.line, d.rule))


if __name__ == "__main__":
    from common import REPO_ROOT
    for d in run(REPO_ROOT):
        print(d)
