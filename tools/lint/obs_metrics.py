"""graftlint pass: ``metric-in-hot-path`` — registry handles bind at
module/constructor scope, never per call.

The obs metrics registry (paddle_tpu/obs/registry.py) splits its API
asymmetrically on purpose: handle CREATION
(``registry.counter("fam", table="0")``, ``gauge``, ``histogram``,
``CounterGroup(...)``) takes the registry lock, canonicalizes labels
and walks the cardinality bound — a cold-path cost; handle USE
(``.inc``/``.add``/``.set``/``.observe``) is the lock-cheap hot-path
call. Creating a handle per request/step silently turns every
increment into a registry transaction AND invites unbounded label
churn — the exact failure the bounded-cardinality design exists to
contain. This pass flags handle *creation* (never increments):

- inside any ``for``/``while`` body (comprehensions at constructor
  scope are the sanctioned bulk-bind idiom and are exempt), anywhere
  in the tree;
- anywhere in a function reachable from a ``# graftlint: hot-path``
  root without crossing ``# graftlint: cold-path`` (the same
  call-graph closure as the hot-host-transfer pass).

A creation call is recognized syntactically: a call whose final
attribute is ``counter``/``gauge``/``histogram`` (or the bare/dotted
``CounterGroup`` constructor) with a STRING LITERAL first argument —
the family name. Variable-named families (the registry's own
internals, generic re-export shims) are not creations at the call
site and pass. Suppression: trailing
``# graftlint: ignore[metric-in-hot-path]``; known-bounded sites go in
tools/lint/allow.txt with a justification.

Second rule in this pass: ``unbounded-label`` — a label value drawn
from an unbounded domain needs an EXPLICIT ``max_series=`` bound at
the creation site. The registry clamps every family to
``FLAGS_obs_max_series`` (64) as a last resort, but a site that feeds
a per-key/per-user/per-request identifier into a label is designing
for overflow: the series it actually wants get collapsed into the
``overflow="true"`` bucket and the operator loses exactly the
per-tenant/per-id breakdown the label was added for. The rule is
syntactic: a creation call (same definition as above) where a label
kwarg's VALUE expression references an identifier matching the
unbounded-id pattern (``key``/``keys``/``user``/``uid``/``request``/
``req``/``trace``/``span``/``endpoint``/``item``/``url``/``addr``/
``id``/``ids`` as a whole ``_``-separated token — so ``uid``,
``user_id``, ``request_id``, ``trace_id`` match; ``table``, ``tier``,
``shard`` don't), or a ``**labels`` splat, with NO ``max_series=``
kwarg on the call. Passing ``max_series=`` — ANY value — is the fix:
it proves the author sized the family's cardinality on purpose.
Suppression: ``# graftlint: ignore[unbounded-label]``.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from common import (Diagnostic, dotted, line_ignores,  # noqa: E402
                    relpath, walk_py)
from tracer_safety import (FuncDef, ModuleInfo, _callees,  # noqa: E402
                           _collect_module, _COLD_RE, _HOT_RE, _Index,
                           _marked)

RULE = "metric-in-hot-path"
RULE_LABEL = "unbounded-label"
_CREATORS = {"counter", "gauge", "histogram"}
_CTOR = "CounterGroup"

#: identifiers (as whole ``_``-separated tokens anywhere in the dotted
#: name) whose domain is unbounded by construction: feature keys, user
#: / request / trace identities, endpoints. ``id`` is the deliberate
#: wide net — ``job_id``/``trace_id``/``span_id`` label values churn
#: forever; a genuinely bounded id label states its bound via
#: ``max_series=`` and the rule stands down.
_UNBOUNDED_ID = re.compile(
    r"(?:^|_)(?:key|keys|user|uid|request|req|trace|span|endpoint|"
    r"item|url|addr|id|ids)(?:_|$)")
#: kwargs on a creation call that are NOT labels
_NONLABEL_KW = {"max_series", "buckets"}


def _is_creation(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted(node.func)
    if name is None:
        return False
    sym = name.split(".")[-1]
    if sym != _CTOR and sym not in _CREATORS:
        return False
    return bool(node.args) and isinstance(node.args[0], ast.Constant) \
        and isinstance(node.args[0].value, str)


def _emit(mi: ModuleInfo, node: ast.AST, msg: str,
          out: List[Diagnostic], rule: str = RULE) -> None:
    line = getattr(node, "lineno", 1)
    if rule not in line_ignores(mi.source_lines, line):
        out.append(Diagnostic(mi.path, line, rule, msg))


def _unbounded_labels(node: ast.Call) -> List[str]:
    """Offending label kwargs on a creation call: value expression
    references an unbounded-domain identifier (or is a ``**labels``
    splat) and the call carries no explicit ``max_series=``."""
    if any(kw.arg == "max_series" for kw in node.keywords):
        return []
    hits: List[str] = []
    for kw in node.keywords:
        if kw.arg in _NONLABEL_KW:
            continue
        if kw.arg is None:  # **labels: caller-controlled, unbounded
            hits.append("**" + (dotted(kw.value) or "labels"))
            continue
        for sub in ast.walk(kw.value):
            ident = (sub.id if isinstance(sub, ast.Name)
                     else sub.attr if isinstance(sub, ast.Attribute)
                     else None)
            if ident is not None and _UNBOUNDED_ID.search(ident):
                hits.append(f"{kw.arg}={ident}")
                break
    return hits


def _scan_labels(mi: ModuleInfo) -> List[Diagnostic]:
    """unbounded-label: every creation call in the module, any scope —
    an unbounded label value is wrong at constructor scope too (the
    overflow happens across calls, not within a loop)."""
    diags: List[Diagnostic] = []
    for node in ast.walk(mi.tree):
        if not _is_creation(node):
            continue
        for hit in _unbounded_labels(node):
            _emit(mi, node,
                  f"label `{hit}` draws from an unbounded domain with no "
                  f"explicit max_series= on the creation — the family "
                  "will collapse into the overflow series exactly when "
                  "the breakdown matters; size the cardinality "
                  "(max_series=N) or drop the label",
                  diags, rule=RULE_LABEL)
    return diags


def _scan_loops(mi: ModuleInfo) -> List[Diagnostic]:
    """Creation calls lexically inside for/while bodies (module scope
    and function bodies alike — a loop is a loop)."""
    diags: List[Diagnostic] = []

    class V(ast.NodeVisitor):
        def __init__(self) -> None:
            self.loop_depth = 0

        def _loop(self, node) -> None:
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_For = visit_While = visit_AsyncFor = _loop

        def visit_Call(self, node: ast.Call) -> None:
            if self.loop_depth > 0 and _is_creation(node):
                _emit(mi, node,
                      "metric handle created inside a loop — bind the "
                      "handle once at module/constructor scope (a dict "
                      "comprehension or obs.registry.CounterGroup) and "
                      "increment it here", diags)
            self.generic_visit(node)

        def visit_FunctionDef(self, node) -> None:
            # a nested def's body does not execute per loop iteration
            depth, self.loop_depth = self.loop_depth, 0
            self.generic_visit(node)
            self.loop_depth = depth

        visit_AsyncFunctionDef = visit_FunctionDef

    V().visit(mi.tree)
    return diags


def _scan_hot(mi: ModuleInfo, fd: FuncDef) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    own_nested = {n for n in ast.walk(fd.node)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and n is not fd.node}

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node) -> None:
            if node in own_nested:
                return  # nested defs scan as their own units (if reached)
            self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node: ast.Call) -> None:
            if _is_creation(node):
                _emit(mi, node,
                      f"metric handle created on the hot path (reachable "
                      f"from a `# graftlint: hot-path` root via "
                      f"`{fd.name}`) — pre-bind it at constructor scope "
                      "and increment here", diags)
            self.generic_visit(node)

    V().visit(fd.node)
    return diags


def run(root: str, subdirs=("paddle_tpu",), files=("bench.py",),
        only=None) -> List[Diagnostic]:
    modules = [m for m in (_collect_module(p, root)
                           for p in walk_py(root, subdirs, files, only=only))
               if m is not None]
    index = _Index(modules)

    diags: List[Diagnostic] = []
    for mi in modules:
        diags.extend(_scan_loops(mi))
        diags.extend(_scan_labels(mi))

    # the same hot-path closure as tracer_safety.run_hot_path: roots
    # marked `# graftlint: hot-path`, stopping at `# graftlint: cold-path`
    reachable: Dict[int, Tuple[ModuleInfo, FuncDef]] = {}
    work: List[Tuple[ModuleInfo, FuncDef]] = []
    for mi in modules:
        for defs in mi.funcs.values():
            for fd in defs:
                if _marked(mi, fd, _HOT_RE) and id(fd.node) not in reachable:
                    reachable[id(fd.node)] = (mi, fd)
                    work.append((mi, fd))
    while work:
        mi, fd = work.pop()
        for callee in _callees(mi, fd, index):
            if id(callee.node) in reachable:
                continue
            cmi = index.by_name[callee.module]
            if _marked(cmi, callee, _COLD_RE):
                continue  # declared cold: may bind handles
            reachable[id(callee.node)] = (cmi, callee)
            work.append((cmi, callee))
    seen = {(d.path, d.line) for d in diags}
    for mi, fd in reachable.values():
        for d in _scan_hot(mi, fd):
            if (d.path, d.line) not in seen:  # loop hit already covers it
                seen.add((d.path, d.line))
                diags.append(d)
    return sorted(diags, key=lambda d: (d.path, d.line, d.rule))


if __name__ == "__main__":
    from common import REPO_ROOT
    for d in run(REPO_ROOT):
        print(d)
