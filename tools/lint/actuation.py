"""graftlint pass 10: one actuator — control loops must not actuate.

  direct-actuation  a class that runs its own CONTROL LOOP (constructs
                   a ``threading.Thread`` whose ``target`` is one of
                   its own methods) calls a cluster-mutating primitive
                   — ``grow``/``shrink`` (reshard cutover),
                   ``begin_canary``/``promote``/``rollback`` (model
                   rollout), ``suspend``/``resume_scans`` (failover
                   scan gate) — on some OTHER object from code
                   reachable from that loop. Under the declarative
                   control plane there is exactly ONE actuator
                   (``ps/reconcile.py``): every other loop observes,
                   decides, and PROPOSES a spec change; the reconciler
                   serializes the actuation. A second loop that
                   actuates directly reintroduces the
                   concurrent-cutover races the reconciler exists to
                   remove (two writers interleaving routing flips,
                   promotion during an unfenced cutover). Route the
                   decision through ``Reconciler.propose_*`` instead.

The loop-body scan is the TRANSITIVE closure of ``self._method()``
calls reachable from the thread target — an actuation buried two
helpers deep is still actuation on the loop's thread. Calls on bare
``self`` (``self.promote()``) are the class mutating ITSELF and are
fine; the rule fires when the receiver is another object
(``self.controller.grow(...)``, ``coordinator.suspend()``).

Scope: ``paddle_tpu/`` except ``paddle_tpu/ps/reconcile.py`` (the one
sanctioned actuator). Suppression, in preference order:

  # graftlint: actuate-ok <reason>    on the CALL line — the reason
                   (>= 3 chars) is mandatory; an escape hatch without
                   a why is itself flagged. For loops that genuinely
                   own actuation (standalone mode, no reconciler
                   wired).
  # graftlint: ignore[direct-actuation]   blanket per-line ignore, or
                   an allow.txt entry with justification.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from common import (Diagnostic, line_ignores,  # noqa: E402
                    relpath, walk_py)
from control_loops import (_method_map,  # noqa: E402
                           _self_thread_targets)

RULE = "direct-actuation"

#: the cluster-mutating primitives the reconciler sequences. Attribute
#: names, not dotted paths: `self.controller.grow`, `ctrl.grow`, and
#: `cluster.coordinator.suspend` all resolve to their final attr.
_ACTUATION_ATTRS = {"grow", "shrink", "begin_canary", "promote",
                    "rollback", "suspend", "resume_scans"}

#: the one module allowed to actuate
_ACTUATOR_MODULES = {"paddle_tpu/ps/reconcile.py"}

_ACTUATE_OK_RE = re.compile(r"#\s*graftlint:\s*actuate-ok\b[ \t]*(.*)$")


def _closure(targets: Dict[str, ast.Call],
             methods: Dict[str, ast.FunctionDef]) -> List[ast.FunctionDef]:
    """All of the class's own methods transitively reachable from its
    thread targets via ``self._helper()`` calls (any depth — unlike the
    clock rule's one-level scan, an actuation buried in a helper chain
    still runs on the loop's thread)."""
    seen: Set[str] = set()
    work = [m for m in targets if m in methods]
    while work:
        name = work.pop()
        if name in seen:
            continue
        seen.add(name)
        for node in ast.walk(methods[name]):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self" \
                    and node.func.attr in methods:
                work.append(node.func.attr)
    return [methods[n] for n in sorted(seen)]


def _actuation_call(node: ast.Call) -> bool:
    """A call to an actuation primitive on a receiver other than bare
    ``self``."""
    if not isinstance(node.func, ast.Attribute):
        return False
    if node.func.attr not in _ACTUATION_ATTRS:
        return False
    recv = node.func.value
    if isinstance(recv, ast.Name) and recv.id == "self":
        return False  # the class mutating itself, not another subsystem
    return True


def check_file(path: str, root: str) -> List[Diagnostic]:
    rel = relpath(path, root)
    if rel in _ACTUATOR_MODULES:
        return []
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    lines = src.splitlines()
    diags: List[Diagnostic] = []

    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        targets = _self_thread_targets(cls)
        if not targets:
            continue
        methods = _method_map(cls)
        for m in _closure(targets, methods):
            for node in ast.walk(m):
                if not (isinstance(node, ast.Call)
                        and _actuation_call(node)):
                    continue
                if RULE in line_ignores(lines, node.lineno):
                    continue
                line_src = lines[node.lineno - 1] \
                    if node.lineno - 1 < len(lines) else ""
                ok = _ACTUATE_OK_RE.search(line_src)
                if ok is not None:
                    reason = ok.group(1).strip()
                    if len(reason) >= 3:
                        continue
                    diags.append(Diagnostic(
                        rel, node.lineno, RULE,
                        f"`{cls.name}.{m.name}` carries a bare "
                        "`# graftlint: actuate-ok` — the escape hatch "
                        "requires a reason (>= 3 chars) saying WHY this "
                        "loop may actuate directly"))
                    continue
                target = ast.unparse(node.func) \
                    if hasattr(ast, "unparse") else node.func.attr
                diags.append(Diagnostic(
                    rel, node.lineno, RULE,
                    f"`{cls.name}` runs a thread control loop and "
                    f"`{m.name}` calls the actuation primitive "
                    f"`{target}(...)` directly — under the declarative "
                    "control plane only the reconciler actuates "
                    "(ps/reconcile.py); propose the change via "
                    "`Reconciler.propose_*` instead, or justify with "
                    "`# graftlint: actuate-ok <reason>`"))
    return diags


def run(root: str, only=None) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for p in walk_py(root, ("paddle_tpu",), only=only):
        diags.extend(check_file(p, root))
    return sorted(diags, key=lambda d: (d.path, d.line, d.rule))


if __name__ == "__main__":
    from common import REPO_ROOT
    for d in run(REPO_ROOT):
        print(d)
