#!/usr/bin/env python
"""graftlint driver: run all three passes, apply the allowlist, report.

Usage:
  python tools/lint/run.py              # gate: exit 1 on NEW violations
  python tools/lint/run.py --json F    # also write machine-readable summary
  python tools/lint/run.py --all       # show allowlisted hits too (for
                                       # regenerating/pruning allow.txt)

Diagnostics print as `path:line: [rule] message`. The allowlist
(tools/lint/allow.txt) grandfathers existing sites; stale entries (no
longer firing) are reported as warnings so the file shrinks over time —
they do not fail the gate (line drift would otherwise make every
refactor red).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import control_loops  # noqa: E402
import conventions  # noqa: E402
import lock_order  # noqa: E402
import obs_metrics  # noqa: E402
import tracer_safety  # noqa: E402
from common import (REPO_ROOT, load_allowlist,  # noqa: E402
                    split_new_and_allowed)

ALLOW_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "allow.txt")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="graftlint driver")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write a machine-readable JSON summary")
    ap.add_argument("--all", action="store_true",
                    help="also print allowlisted diagnostics")
    ap.add_argument("--root", default=REPO_ROOT, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    passes = {
        "tracer_safety": tracer_safety.run,
        "hot_path": tracer_safety.run_hot_path,
        "lock_order": lock_order.run,
        "conventions": conventions.run,
        "obs_metrics": obs_metrics.run,
        "control_loops": control_loops.run,
    }
    diags = []
    per_pass = {}
    for name, fn in passes.items():
        got = fn(args.root)
        per_pass[name] = len(got)
        diags.extend(got)

    allow = load_allowlist(ALLOW_PATH)
    new, allowed, stale = split_new_and_allowed(diags, allow)

    for d in new:
        print(d)
    if args.all:
        for d in allowed:
            print(f"{d}  [allowlisted]")
    for key in stale:
        print(f"warning: stale allowlist entry (no longer fires): {key}",
              file=sys.stderr)

    summary = {
        "total": len(diags),
        "new": len(new),
        "allowlisted": len(allowed),
        "stale_allowlist_entries": stale,
        "per_pass": per_pass,
        "violations": [
            {"path": d.path, "line": d.line, "rule": d.rule,
             "message": d.message, "allowlisted": d.key in allow}
            for d in diags
        ],
    }
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(summary, f, indent=2)

    if new:
        print(f"\ngraftlint: {len(new)} new violation(s) "
              f"({len(allowed)} allowlisted). Fix them, or — for cold/debug "
              "paths only — add `path:line:rule  # justification` to "
              "tools/lint/allow.txt (see docs/STATIC_ANALYSIS.md).",
              file=sys.stderr)
        return 1
    print(f"graftlint OK: 0 new violations "
          f"({len(allowed)} allowlisted, {len(stale)} stale entries)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
