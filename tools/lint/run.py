#!/usr/bin/env python
"""graftlint driver: run all ten passes, apply the allowlist, report.

Usage:
  python tools/lint/run.py              # gate: exit 1 on NEW violations
                                        # or stale allowlist entries
  python tools/lint/run.py --json F    # also write machine-readable summary
  python tools/lint/run.py --all       # show allowlisted hits too (for
                                       # regenerating/pruning allow.txt)
  python tools/lint/run.py --changed   # lint only files changed vs
                                       # merge-base(HEAD, origin/main) —
                                       # the sub-second pre-commit loop

Diagnostics print as `path:line: [rule] message`. The allowlist
(tools/lint/allow.txt) grandfathers existing sites; a STALE entry (no
longer firing) FAILS the full gate — delete it, or re-justify the moved
site at its new line. `--changed` (a deliberately partial view) skips
the staleness check entirely: most entries legitimately reference
unchanged files there, and the call-graph passes lose cross-module
reachability on a subset — the full gate owns allowlist hygiene.
Cross-file passes (_CROSS_FILE_PASSES) are the exception to the
partial view: when a changed file is in their domain they re-run over
the whole tree, because their findings are RELATIONS between files —
a partial input doesn't just miss findings, it fabricates them.

The JSON summary carries per-pass wall time + finding counts (ci.sh
archives it) and each allowlisted violation's `why` justification; a
soft budget warning fires when the whole run exceeds 10 s so a newly
slow or noisy pass is visible in the CI log before it hurts.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import actuation  # noqa: E402
import control_loops  # noqa: E402
import conventions  # noqa: E402
import lock_order  # noqa: E402
import obs_metrics  # noqa: E402
import py_locks  # noqa: E402
import sync_shim  # noqa: E402
import tracer_safety  # noqa: E402
import wire_contract  # noqa: E402
from common import (REPO_ROOT, load_allowlist,  # noqa: E402
                    split_new_and_allowed)

ALLOW_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "allow.txt")

#: soft wall-time budget for the whole lint run (seconds). Exceeding it
#: never fails the gate — it flags the trend in the log.
TIME_BUDGET_S = 10.0

_LINT_EXTS = (".py", ".cc", ".h")

#: passes whose findings depend on MORE than the file being linted:
#: wire_contract cross-checks the Python wire tables against the csrc
#: enums (a partial view sees "missing counterpart" everywhere — or,
#: worse, nothing), and the lock passes merge `LOCK ORDER` decls that
#: neighbours contribute. Under --changed these run on the WHOLE tree
#: whenever any changed file is in their extension domain; the other
#: passes are strictly per-file and keep the fast partial view.
_CROSS_FILE_PASSES = {
    "lock_order": (".cc", ".h"),
    "py_locks": (".py",),
    "wire_contract": (".py", ".cc", ".h"),
}


def changed_files(root: str) -> set:
    """Repo-relative lintable files changed vs merge-base(HEAD,
    origin/main), plus staged/unstaged/untracked work — the pre-commit
    view. Falls back to HEAD when origin/main doesn't exist (local-only
    clones)."""
    def git(*args):
        return subprocess.run(["git", "-C", root, *args],
                              capture_output=True, text=True)

    base = "HEAD"
    mb = git("merge-base", "HEAD", "origin/main")
    if mb.returncode == 0 and mb.stdout.strip():
        base = mb.stdout.strip()
    out = set()
    # NUL-separated so paths with spaces (or core.quotePath escapes)
    # survive — a fragmented path silently drops the file from the run
    diff = git("diff", "--name-only", "-z", base, "--")
    if diff.returncode == 0:
        out.update(f for f in diff.stdout.split("\0") if f)
    untracked = git("ls-files", "--others", "--exclude-standard", "-z")
    if untracked.returncode == 0:
        out.update(f for f in untracked.stdout.split("\0") if f)
    return {f for f in out if f.endswith(_LINT_EXTS)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="graftlint driver")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write a machine-readable JSON summary")
    ap.add_argument("--all", action="store_true",
                    help="also print allowlisted diagnostics")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files changed vs merge-base(HEAD, "
                         "origin/main) — fast pre-commit loop; the "
                         "allowlist staleness check is skipped")
    ap.add_argument("--root", default=REPO_ROOT, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    only = None
    if args.changed:
        only = changed_files(args.root)
        if not only:
            print("graftlint OK: no lintable files changed")
            if args.json:
                with open(args.json, "w", encoding="utf-8") as f:
                    json.dump({"total": 0, "new": 0, "allowlisted": 0,
                               "changed_mode": True, "changed_files": [],
                               "per_pass": {}, "violations": [],
                               "stale_allowlist_entries": []}, f, indent=2)
            return 0

    passes = {
        "tracer_safety": tracer_safety.run,
        "hot_path": tracer_safety.run_hot_path,
        "lock_order": lock_order.run,
        "py_locks": py_locks.run,
        "wire_contract": wire_contract.run,
        "conventions": conventions.run,
        "obs_metrics": obs_metrics.run,
        "control_loops": control_loops.run,
        "sync_shim": sync_shim.run,
        "actuation": actuation.run,
    }
    diags = []
    per_pass = {}
    t_total0 = time.perf_counter()
    for name, fn in passes.items():
        pass_only = only
        if only is not None and name in _CROSS_FILE_PASSES:
            exts = _CROSS_FILE_PASSES[name]
            if any(f.endswith(exts) for f in only):
                # a cross-file pass on a PARTIAL file set silently loses
                # findings (wire_contract diffs the py/cc surfaces
                # against each other; lock_order/py_locks merge decls
                # across a module's neighbors): one changed file in the
                # pass's domain re-runs the WHOLE pass
                pass_only = None
        t0 = time.perf_counter()
        got = fn(args.root, only=pass_only)
        per_pass[name] = {
            "violations": len(got),
            "wall_ms": round((time.perf_counter() - t0) * 1000.0, 1),
        }
        diags.extend(got)
    total_s = time.perf_counter() - t_total0

    allow = load_allowlist(ALLOW_PATH)
    new, allowed, stale = split_new_and_allowed(diags, allow)
    # staleness is only meaningful against the FULL diagnostic set: a
    # --changed run sees a sliver of the tree (and the call-graph passes
    # lose cross-module reachability on it), so unmatched entries prove
    # nothing there — the full gate owns allowlist hygiene
    if args.changed:
        stale = []
    stale_fatal = bool(stale)

    for d in new:
        print(d)
    if args.all:
        for d in allowed:
            print(f"{d}  [allowlisted: {allow[d.key].why}]")
    for key in stale:
        print(f"ERROR: stale allowlist entry (no longer fires): {key} "
              f"[allow.txt:{allow[key].line}]", file=sys.stderr)

    summary = {
        "total": len(diags),
        "new": len(new),
        "allowlisted": len(allowed),
        "stale_allowlist_entries": stale,
        "changed_mode": args.changed,
        "wall_s": round(total_s, 3),
        "per_pass": per_pass,
        "violations": [
            {"path": d.path, "line": d.line, "rule": d.rule,
             "message": d.message, "allowlisted": d.key in allow,
             "why": allow[d.key].why if d.key in allow else None}
            for d in diags
        ],
    }
    if args.changed:
        summary["changed_files"] = sorted(only)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(summary, f, indent=2)

    if total_s > TIME_BUDGET_S:
        slowest = max(per_pass, key=lambda k: per_pass[k]["wall_ms"])
        print(f"warning: graftlint took {total_s:.1f}s (soft budget "
              f"{TIME_BUDGET_S:.0f}s); slowest pass: {slowest} "
              f"({per_pass[slowest]['wall_ms']:.0f} ms)", file=sys.stderr)

    if new or stale_fatal:
        if new:
            print(f"\ngraftlint: {len(new)} new violation(s) "
                  f"({len(allowed)} allowlisted). Fix them, or — for "
                  "cold/debug paths only — add `path:line:rule  # why: "
                  "justification` to tools/lint/allow.txt "
                  "(see docs/STATIC_ANALYSIS.md).", file=sys.stderr)
        if stale_fatal:
            print(f"\ngraftlint: {len(stale)} stale allowlist entr"
                  f"{'y' if len(stale) == 1 else 'ies'} — the grandfathered "
                  "site moved or was fixed. Delete the entry, or re-review "
                  "and re-add it at the new line (docs/STATIC_ANALYSIS.md).",
                  file=sys.stderr)
        return 1
    print(f"graftlint OK: 0 new violations ({len(allowed)} allowlisted) "
          f"in {total_s:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
