"""graftlint pass 3: project conventions for the Python layer.

  time-time        ``time.time()`` — wall clock steps on NTP slew and is
                   the wrong tool for measuring latency; use
                   ``time.perf_counter()`` for durations/deadlines.
                   Genuine wall-clock timestamps (heartbeat payloads,
                   checkpoint metadata) go in the allowlist with a
                   justification.
  bare-except      ``except:`` swallows KeyboardInterrupt/SystemExit;
                   name the exception (or ``except Exception``).
  mutable-default  mutable default argument (list/dict/set literal or
                   constructor) — shared across calls.
  env-read         ``os.environ`` / ``os.getenv`` read outside the
                   config modules (core/flags.py, ps/config.py,
                   distributed bootstrap). Env reads scattered through
                   library code make runs irreproducible; route them
                   through flags.
  cast-roundtrip   a value narrowed with ``.astype(...)`` is immediately
                   widened back with no intervening collective/op —
                   either a direct ``x.astype(a).astype(b)`` chain or
                   the tree_map pair form
                   (``h = tmap(lambda g: g.astype(d), grads)`` followed
                   by ``tmap(lambda h, g: h.astype(g.dtype), half, …)``
                   with no use of ``h`` in between). Numerically it
                   simulates wire precision while moving zero fewer
                   bytes — the FP16AllReduceOptimizer bug class; route
                   the dtype to the collective (comm_fusion) instead.
                   Intentional precision simulation gets an ignore with
                   a justification.
  sleep-no-backoff a RETRY loop (a loop whose body contains an except
                   handler) that sleeps a bare CONSTANT between
                   attempts. Fixed-interval retries hammer a struggling
                   server in lockstep across every client — the thundering
                   herd that turns one slow shard into a dead one; back
                   off exponentially instead (``base * 2 ** attempt``,
                   the pattern ``ps/rpc.py`` _ServerConn.call follows).
                   Plain polling loops (no except) are fine, as is any
                   sleep whose duration is computed from a variable.
  unbounded-queue  ``queue.Queue()`` / ``queue.LifoQueue()`` /
                   ``collections.deque()`` constructed WITHOUT a bound
                   (no ``maxsize``/``maxlen``, or ``maxsize<=0``) in a
                   module that imports ``threading`` — threaded
                   producer/consumer code. A producer that outruns its
                   consumer grows memory and tail latency without limit
                   (the class PR 5 had to retrofit bounded deques for,
                   and the failure mode serving admission control
                   exists to prevent): bound the queue and make the
                   producer block or shed at the bound. Flow-controlled
                   cases (credit protocols) get an ignore/allowlist
                   entry with the justification.
  anonymous-thread ``threading.Thread(...)`` created without ``name=``.
                   Thread names are the lane labels in chrome traces,
                   flight-recorder bundles, py-spy dumps and TSAN
                   reports — an anonymous ``Thread-7`` makes every one
                   of those unattributable. Name the thread after its
                   role (``name="obs-sampler"``,
                   ``name=f"ps-repl:{shard}"``).
  atomic-publish   an ``os.replace``/``os.rename`` publish in a scope
                   that never fsyncs: the rename can land while the
                   renamed content is still dirty page cache, so a crash
                   publishes empty/partial files — the torn-checkpoint
                   bug class ``io/job_checkpoint.py`` exists to prevent.
                   fsync the written files and the parent directory
                   first (``io.fs.fsync_file``/``fsync_dir``, or
                   ``publish_atomic`` which does the whole dance);
                   any call whose name mentions fsync counts as
                   evidence. Non-durable renames (tmp scratch, caches)
                   get an ignore with a justification.

Scope: ``paddle_tpu/`` and ``bench.py`` for all rules; ``tools/`` for
time-time and anonymous-thread only (demo drivers legitimately read
their own env knobs, but their threads show up in the same traces).
Suppression: trailing ``# graftlint: ignore[rule]``.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Set

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from common import (Diagnostic, dotted, line_ignores,  # noqa: E402
                    relpath, walk_py)

# modules whose job is reading process-level configuration
ENV_READ_OK = {
    "paddle_tpu/core/flags.py",       # the flags registry itself
    "paddle_tpu/ps/config.py",        # PS table config
    "paddle_tpu/distributed/role_maker.py",   # PADDLE_* bootstrap env
    "paddle_tpu/distributed/launch.py",       # launcher materializes env
    "bench.py",                               # driver owns its BENCH_* knobs
}

_MUTABLE_CTORS = {"list", "dict", "set"}

_TREE_MAP_BASES = {"tree_map", "_tmap", "tmap", "tree_multimap"}


def _is_tree_map(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted(node.func)
    if not name:
        return False
    return (name.rsplit(".", 1)[-1] in _TREE_MAP_BASES
            or name == "jax.tree.map")


def _astype_call(node: ast.AST):
    """The Attribute node of a direct ``<expr>.astype(...)`` call."""
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"):
        return node.func
    return None


def _lambda_body_astype(call: ast.Call):
    """For a tree-map call whose first arg is a lambda whose body is a
    direct ``.astype(...)``, return that lambda; else None."""
    if not call.args or not isinstance(call.args[0], ast.Lambda):
        return None
    lam = call.args[0]
    return lam if _astype_call(lam.body) is not None else None


def _names_in(node: ast.AST):
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _roundtrip_in_block(stmts, emit) -> None:
    """Scan one statement list for the narrow-then-immediately-widen
    pair: ``h = <cast-producing stmt>`` whose NEXT use is itself a
    direct ``.astype`` of ``h`` (plain or tree_map form). An intervening
    statement that touches ``h`` (a collective, a reducer call, any op)
    clears the pending match — that is the "no intervening op" test."""
    pending = {}   # var name -> ("direct"|"tmap", assign lineno)
    for st in stmts:
        used = _names_in(st)
        if isinstance(st, ast.Assign) and len(st.targets) == 1 and \
                isinstance(st.targets[0], ast.Name):
            tgt = st.targets[0].id
            val = st.value
            # does this statement WIDEN a pending narrow?
            hit = None
            if isinstance(val, ast.Call):
                att = _astype_call(val)
                if att is not None:
                    base = dotted(att.value)
                    if base in pending and pending[base][0] == "direct":
                        hit = base
                elif _is_tree_map(val) and _lambda_body_astype(val) is not None:
                    lam = val.args[0]
                    att2 = _astype_call(lam.body)
                    if isinstance(att2.value, ast.Name) and \
                            att2.value.id in {a.arg for a in lam.args.args}:
                        for a in val.args[1:]:
                            if isinstance(a, ast.Name) and a.id in pending \
                                    and pending[a.id][0] == "tmap":
                                hit = a.id
                                break
            if hit is not None:
                emit(st, "cast-roundtrip",
                     f"`{hit}` was narrowed with .astype() and is widened "
                     "right back with no intervening collective/op — a "
                     "wire-width no-op (FP16AllReduce bug class); route "
                     "the dtype to the collective (comm_fusion) or add an "
                     "ignore with justification")
                pending.pop(hit, None)
            # any other use of a pending name clears it (intervening op)
            for name in list(pending):
                if name in used and name != hit:
                    pending.pop(name)
            # does this statement NARROW (start a pending match)?
            if isinstance(val, ast.Call):
                if _astype_call(val) is not None:
                    pending[tgt] = ("direct", st.lineno)
                elif _is_tree_map(val) and _lambda_body_astype(val) is not None:
                    pending[tgt] = ("tmap", st.lineno)
                elif tgt in pending:
                    pending.pop(tgt)
            elif tgt in pending:
                pending.pop(tgt)
        else:
            for name in list(pending):
                if name in used:
                    pending.pop(name)


_PUBLISH_ATTRS = {"replace", "rename"}

_QUEUE_ATTRS = {"Queue", "LifoQueue"}


def _queue_bound_arg(call: ast.Call, kind: str):
    """The bounding argument node of a Queue/deque constructor call:
    Queue(maxsize)/LifoQueue(maxsize) take it as arg 0 or ``maxsize=``;
    deque(iterable, maxlen) as arg 1 or ``maxlen=``. None = absent."""
    kw_name = "maxsize" if kind == "queue" else "maxlen"
    pos = 0 if kind == "queue" else 1
    if len(call.args) > pos:
        return call.args[pos]
    for kw in call.keywords:
        if kw.arg == kw_name:
            return kw.value
    return None


def _queue_is_unbounded(call: ast.Call, kind: str) -> bool:
    arg = _queue_bound_arg(call, kind)
    if arg is None:
        return True
    if isinstance(arg, ast.Constant):
        if arg.value is None:
            return True  # deque(it, maxlen=None)
        if kind == "queue" and isinstance(arg.value, (int, float)) \
                and arg.value <= 0:
            return True  # Queue(maxsize=0) means INFINITE
    return False


def _check_atomic_publish(tree: ast.AST, emit, os_aliases: Set[str],
                          pub_bare: Set[str]) -> None:
    """Flag os.replace/os.rename calls whose enclosing scope (nearest
    function, else the module) shows no fsync evidence — any call whose
    name mentions fsync, or publish_atomic (which fsyncs internally)."""

    def is_publish(call: ast.Call) -> bool:
        name = dotted(call.func)
        if name in pub_bare:
            return True
        if name and "." in name:
            mod, _, attr = name.rpartition(".")
            return mod in os_aliases and attr in _PUBLISH_ATTRS
        return False

    def has_fsync(scope: ast.AST) -> bool:
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Call):
                nm = dotted(sub.func) or ""
                last = nm.rsplit(".", 1)[-1]
                if "fsync" in last or last == "publish_atomic":
                    return True
        return False

    msg = ("os.replace/os.rename publishes files that were never fsynced "
           "— a crash can publish empty/partial content (the torn-"
           "checkpoint class); fsync the written files and the parent "
           "directory first (io.fs.fsync_file/fsync_dir/publish_atomic) "
           "or justify with an ignore")
    # nearest enclosing function owns each publish (ast.walk is
    # breadth-first: outer functions come before nested ones, so the
    # innermost assignment wins)
    owner = {}
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call) and is_publish(sub):
                owner[id(sub)] = (sub, fn)
    scope_ok: dict = {}
    for sub, fn in owner.values():
        ok = scope_ok.get(id(fn))
        if ok is None:
            ok = scope_ok[id(fn)] = has_fsync(fn)
        if not ok:
            emit(sub, "atomic-publish", msg)
    in_fn: set = set()
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        in_fn.update(map(id, ast.walk(fn)))
    module_pubs = [sub for sub in ast.walk(tree)
                   if isinstance(sub, ast.Call) and is_publish(sub)
                   and id(sub) not in owner]
    if module_pubs:
        # module-scope evidence must itself be at module scope: an
        # fsync buried in some (possibly never-called) function body is
        # not evidence that the import-time publish was fsynced
        module_fsync = any(
            isinstance(sub, ast.Call) and id(sub) not in in_fn
            and ("fsync" in (dotted(sub.func) or "").rsplit(".", 1)[-1]
                 or (dotted(sub.func) or "").rsplit(".", 1)[-1]
                 == "publish_atomic")
            for sub in ast.walk(tree))
        if not module_fsync:
            for sub in module_pubs:
                emit(sub, "atomic-publish", msg)


def _iter_blocks(fn: ast.AST):
    """Every statement list inside a function (body + nested blocks)."""
    for node in ast.walk(fn):
        for field in ("body", "orelse", "finalbody"):
            block = getattr(node, field, None)
            if isinstance(block, list) and block and \
                    isinstance(block[0], ast.stmt):
                yield block


def check_file(path: str, root: str, rules: Set[str]) -> List[Diagnostic]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    rel = relpath(path, root)
    lines = src.splitlines()
    diags: List[Diagnostic] = []

    def emit(node: ast.AST, rule: str, msg: str) -> None:
        if rule in rules and rule not in line_ignores(lines, node.lineno):
            diags.append(Diagnostic(rel, node.lineno, rule, msg))

    # names that call the wall clock: `time.time` via any module alias
    # (`import time as _time`), plus bare aliases of
    # `from time import time [as now]`; sleep aliases tracked the same
    # way for the retry-backoff rule
    time_mod_aliases = {"time"}
    time_func_aliases: Set[str] = set()
    sleep_func_aliases: Set[str] = set()
    os_mod_aliases = {"os"}
    publish_bare: Set[str] = set()  # from os import replace/rename [as x]
    queue_mod_aliases: Set[str] = set()   # import queue [as q]
    coll_mod_aliases: Set[str] = set()    # import collections [as c]
    queue_bare: Set[str] = set()   # from queue import Queue/LifoQueue [as x]
    deque_bare: Set[str] = set()   # from collections import deque [as x]
    threaded = False               # module imports threading
    threading_mod_aliases: Set[str] = set()  # import threading [as t]
    thread_bare: Set[str] = set()  # from threading import Thread [as T]
    # the core.sync shim (imported RELATIVELY: `from ..core import sync
    # as _sync`, any level) wraps the same constructors — its Queue is
    # an unbounded queue, its Thread an anonymous thread, and a module
    # that imports it runs threads by definition
    sync_mod_aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    time_mod_aliases.add(a.asname or "time")
                elif a.name == "os":
                    os_mod_aliases.add(a.asname or "os")
                elif a.name == "queue":
                    queue_mod_aliases.add(a.asname or "queue")
                elif a.name == "collections":
                    coll_mod_aliases.add(a.asname or "collections")
                elif a.name == "threading":
                    threaded = True
                    threading_mod_aliases.add(a.asname or "threading")
                elif a.name.endswith("core.sync"):
                    threaded = True
                    sync_mod_aliases.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "time" and not node.level:
                for a in node.names:
                    if a.name == "time":
                        time_func_aliases.add(a.asname or "time")
                    elif a.name == "sleep":
                        sleep_func_aliases.add(a.asname or "sleep")
            elif node.module == "os" and not node.level:
                for a in node.names:
                    if a.name in _PUBLISH_ATTRS:
                        publish_bare.add(a.asname or a.name)
            elif node.module == "queue" and not node.level:
                for a in node.names:
                    if a.name in _QUEUE_ATTRS:
                        queue_bare.add(a.asname or a.name)
            elif node.module == "collections" and not node.level:
                for a in node.names:
                    if a.name == "deque":
                        deque_bare.add(a.asname or a.name)
            elif node.module == "threading" and not node.level:
                threaded = True
                for a in node.names:
                    if a.name == "Thread":
                        thread_bare.add(a.asname or "Thread")
            if (node.module or "").split(".")[-1] == "core":
                for a in node.names:
                    if a.name == "sync":
                        threaded = True
                        sync_mod_aliases.add(a.asname or a.name)

    def _queue_kind(call: ast.Call):
        name = dotted(call.func)
        if name in queue_bare:
            return "queue"
        if name in deque_bare:
            return "deque"
        if name and "." in name:
            mod, _, attr = name.rpartition(".")
            if mod in queue_mod_aliases and attr in _QUEUE_ATTRS:
                return "queue"
            if mod in sync_mod_aliases and attr == "Queue":
                return "queue"
            if mod in coll_mod_aliases and attr == "deque":
                return "deque"
        return None

    _check_atomic_publish(tree, emit, os_mod_aliases, publish_bare)

    def _is_sleep(call: ast.Call) -> bool:
        name = dotted(call.func)
        if name in sleep_func_aliases:
            return True
        if name and "." in name:
            mod, _, attr = name.rpartition(".")
            return mod in time_mod_aliases and attr == "sleep"
        return False

    # sleep-no-backoff: a loop that both catches exceptions (a retry
    # loop) and sleeps a literal constant between attempts. Innermost
    # enclosing loop decides, so a constant-sleep POLLING loop nested
    # inside a retrying outer loop is not flagged.
    loops = [n for n in ast.walk(tree)
             if isinstance(n, (ast.While, ast.For, ast.AsyncFor))]
    inner_loops = {id(sub) for lp in loops for sub in ast.walk(lp)
                   if sub is not lp
                   and isinstance(sub, (ast.While, ast.For, ast.AsyncFor))}
    for lp in loops:
        nested = [sub for sub in ast.walk(lp)
                  if sub is not lp
                  and isinstance(sub, (ast.While, ast.For, ast.AsyncFor))]
        in_nested = {id(x) for n2 in nested for x in ast.walk(n2)}
        own = [sub for sub in ast.walk(lp) if id(sub) not in in_nested]

        def _retries(handler: ast.ExceptHandler) -> bool:
            # a handler that unconditionally leaves the loop (return /
            # raise / break at its top level) is an exit path, not a
            # retry — only handlers that fall back into the loop count
            return not any(isinstance(st, (ast.Return, ast.Raise, ast.Break))
                           for st in handler.body)

        if not any(isinstance(s, ast.ExceptHandler) and _retries(s)
                   for s in own):
            continue
        for s in own:
            if isinstance(s, ast.Call) and _is_sleep(s) and s.args and \
                    isinstance(s.args[0], ast.Constant) and \
                    isinstance(s.args[0].value, (int, float)):
                emit(s, "sleep-no-backoff",
                     "retry loop sleeps a constant between attempts — "
                     "fixed-interval retries from every client hammer a "
                     "struggling server in lockstep; back off "
                     "exponentially (base * 2 ** attempt, the ps/rpc.py "
                     "pattern) or justify with an ignore")

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            is_wall_clock = name in time_func_aliases
            if name and "." in name:
                mod, _, attr = name.rpartition(".")
                is_wall_clock |= mod in time_mod_aliases and attr == "time"
            if is_wall_clock:
                emit(node, "time-time",
                     "time.time() measures wall clock — use "
                     "time.perf_counter() for durations/deadlines "
                     "(allowlist genuine timestamps)")
            att = _astype_call(node)
            if att is not None and _astype_call(att.value) is not None:
                emit(node, "cast-roundtrip",
                     "chained `.astype(a).astype(b)` narrows and widens in "
                     "place — a wire-width no-op (FP16AllReduce bug class); "
                     "route the dtype to the collective (comm_fusion) or "
                     "add an ignore with justification")
            if threaded:
                kind = _queue_kind(node)
                if kind is not None and _queue_is_unbounded(node, kind):
                    emit(node, "unbounded-queue",
                         "unbounded queue.Queue()/deque() in a module "
                         "that runs threads — a producer that outruns "
                         "its consumer grows memory and tail latency "
                         "without limit; bound it (maxsize=/maxlen=) "
                         "and block or shed at the bound (the serving "
                         "admission-control pattern), or justify a "
                         "flow-controlled case with an ignore")
            is_thread_ctor = name in thread_bare
            if name and "." in name:
                mod, _, attr = name.rpartition(".")
                is_thread_ctor |= (attr == "Thread"
                                   and (mod in threading_mod_aliases
                                        or mod in sync_mod_aliases))
            if is_thread_ctor and not any(kw.arg == "name"
                                          for kw in node.keywords):
                emit(node, "anonymous-thread",
                     "threading.Thread() without name= — anonymous "
                     "Thread-N lanes make traces, flight-recorder "
                     "bundles and sanitizer reports unattributable; "
                     "name the thread after its role")
            if name in ("os.environ.get", "os.getenv") and \
                    rel not in ENV_READ_OK:
                emit(node, "env-read",
                     f"`{name}` outside config modules — route through "
                     "core.flags / ps.config")
        elif isinstance(node, ast.Subscript):
            if dotted(node.value) == "os.environ" and \
                    isinstance(node.ctx, ast.Load) and rel not in ENV_READ_OK:
                emit(node, "env-read",
                     "`os.environ[...]` read outside config modules — "
                     "route through core.flags / ps.config")
        elif isinstance(node, ast.ExceptHandler):
            if node.type is None:
                emit(node, "bare-except",
                     "bare `except:` catches KeyboardInterrupt/SystemExit "
                     "— use `except Exception` or narrower")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in (node.args.defaults + node.args.kw_defaults):
                if default is None:
                    continue
                bad = isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(default, ast.Call)
                    and dotted(default.func) in _MUTABLE_CTORS
                    and not default.args and not default.keywords)
                if bad:
                    emit(default, "mutable-default",
                         f"mutable default argument in `{node.name}()` is "
                         "shared across calls — default to None")

    for block in _iter_blocks(tree):
        _roundtrip_in_block(block, emit)
    return diags


def run(root: str, only=None) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    all_rules = {"time-time", "bare-except", "mutable-default", "env-read",
                 "cast-roundtrip", "sleep-no-backoff", "atomic-publish",
                 "unbounded-queue", "anonymous-thread"}
    for p in walk_py(root, ("paddle_tpu",), ("bench.py",), only=only):
        diags.extend(check_file(p, root, all_rules))
    tools_dir = os.path.join(root, "tools")
    tool_files = sorted(os.listdir(tools_dir)) if os.path.isdir(tools_dir) \
        else []
    for p in walk_py(root, (), tuple(
            f"tools/{f}" for f in tool_files if f.endswith(".py")),
            only=only):
        diags.extend(check_file(p, root, {"time-time", "anonymous-thread"}))
    return sorted(diags, key=lambda d: (d.path, d.line, d.rule))


if __name__ == "__main__":
    from common import REPO_ROOT
    for d in run(REPO_ROOT):
        print(d)
