"""graftlint pass 3: project conventions for the Python layer.

  time-time        ``time.time()`` — wall clock steps on NTP slew and is
                   the wrong tool for measuring latency; use
                   ``time.perf_counter()`` for durations/deadlines.
                   Genuine wall-clock timestamps (heartbeat payloads,
                   checkpoint metadata) go in the allowlist with a
                   justification.
  bare-except      ``except:`` swallows KeyboardInterrupt/SystemExit;
                   name the exception (or ``except Exception``).
  mutable-default  mutable default argument (list/dict/set literal or
                   constructor) — shared across calls.
  env-read         ``os.environ`` / ``os.getenv`` read outside the
                   config modules (core/flags.py, ps/config.py,
                   distributed bootstrap). Env reads scattered through
                   library code make runs irreproducible; route them
                   through flags.

Scope: ``paddle_tpu/`` and ``bench.py`` for all rules; ``tools/`` for
time-time only (demo drivers legitimately read their own env knobs).
Suppression: trailing ``# graftlint: ignore[rule]``.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Set

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from common import (Diagnostic, dotted, line_ignores,  # noqa: E402
                    relpath, walk_py)

# modules whose job is reading process-level configuration
ENV_READ_OK = {
    "paddle_tpu/core/flags.py",       # the flags registry itself
    "paddle_tpu/ps/config.py",        # PS table config
    "paddle_tpu/distributed/role_maker.py",   # PADDLE_* bootstrap env
    "paddle_tpu/distributed/launch.py",       # launcher materializes env
    "bench.py",                               # driver owns its BENCH_* knobs
}

_MUTABLE_CTORS = {"list", "dict", "set"}


def check_file(path: str, root: str, rules: Set[str]) -> List[Diagnostic]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    rel = relpath(path, root)
    lines = src.splitlines()
    diags: List[Diagnostic] = []

    def emit(node: ast.AST, rule: str, msg: str) -> None:
        if rule in rules and rule not in line_ignores(lines, node.lineno):
            diags.append(Diagnostic(rel, node.lineno, rule, msg))

    # names that call the wall clock: `time.time` via any module alias
    # (`import time as _time`), plus bare aliases of
    # `from time import time [as now]`
    time_mod_aliases = {"time"}
    time_func_aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    time_mod_aliases.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "time" and not node.level:
                for a in node.names:
                    if a.name == "time":
                        time_func_aliases.add(a.asname or "time")

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            is_wall_clock = name in time_func_aliases
            if name and "." in name:
                mod, _, attr = name.rpartition(".")
                is_wall_clock |= mod in time_mod_aliases and attr == "time"
            if is_wall_clock:
                emit(node, "time-time",
                     "time.time() measures wall clock — use "
                     "time.perf_counter() for durations/deadlines "
                     "(allowlist genuine timestamps)")
            if name in ("os.environ.get", "os.getenv") and \
                    rel not in ENV_READ_OK:
                emit(node, "env-read",
                     f"`{name}` outside config modules — route through "
                     "core.flags / ps.config")
        elif isinstance(node, ast.Subscript):
            if dotted(node.value) == "os.environ" and \
                    isinstance(node.ctx, ast.Load) and rel not in ENV_READ_OK:
                emit(node, "env-read",
                     "`os.environ[...]` read outside config modules — "
                     "route through core.flags / ps.config")
        elif isinstance(node, ast.ExceptHandler):
            if node.type is None:
                emit(node, "bare-except",
                     "bare `except:` catches KeyboardInterrupt/SystemExit "
                     "— use `except Exception` or narrower")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in (node.args.defaults + node.args.kw_defaults):
                if default is None:
                    continue
                bad = isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(default, ast.Call)
                    and dotted(default.func) in _MUTABLE_CTORS
                    and not default.args and not default.keywords)
                if bad:
                    emit(default, "mutable-default",
                         f"mutable default argument in `{node.name}()` is "
                         "shared across calls — default to None")
    return diags


def run(root: str) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    all_rules = {"time-time", "bare-except", "mutable-default", "env-read"}
    for p in walk_py(root, ("paddle_tpu",), ("bench.py",)):
        diags.extend(check_file(p, root, all_rules))
    tools_dir = os.path.join(root, "tools")
    tool_files = sorted(os.listdir(tools_dir)) if os.path.isdir(tools_dir) \
        else []
    for p in walk_py(root, (), tuple(
            f"tools/{f}" for f in tool_files if f.endswith(".py"))):
        diags.extend(check_file(p, root, {"time-time"}))
    return sorted(diags, key=lambda d: (d.path, d.line, d.rule))


if __name__ == "__main__":
    from common import REPO_ROOT
    for d in run(REPO_ROOT):
        print(d)
