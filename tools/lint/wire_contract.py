"""graftlint pass 8: cross-language wire-contract drift checker.

The PS wire protocol lives in TWO languages: csrc/ps_service.cc owns
the Cmd/Err enums, the packed ReqHeader, and the per-cmd classification
predicates (tapped-for-replication, pause-gate/read-only plane,
key-ownership fence); ps/rpc.py, ps/ha.py, ps/graph_client.py and
obs/trace.py hand-mirror the values Python needs (`_PULL_SPARSE = 3`,
`_HDR = struct.Struct("<QIIqiQQ")`, `_rpc_err_stale_epoch = -5`, …).
Until this pass, one comment and one pinned test defended that mirror;
everything else was convention. This is the static complement of the
PR 4 digest machinery: digests catch divergence at RUNTIME, this pass
catches it at commit time.

Three sources are cross-validated:

1. a csrc extractor (line-based, clang-free, like lock_order.py):
   Cmd/Err enum values, ReqHeader/ObsSpan packed field layouts, and
   the four classification switches (`is_mutating_cmd` = the oplog
   tap, `is_training_plane_cmd` = the read-only/pause gate,
   `is_keyed_data_cmd` = the ownership fence scan, `is_create_cmd`);
2. a Python extractor: module-level int constants in rpc/graph_client,
   ha's `_rpc_err_*` + `_HDR`, trace's `WIRE_CONTEXT_BYTES` +
   `SERVER_SPAN_STRUCT`, and the `status → exception` mapping inside
   `_ServerConn.check` (AST);
3. CONTRACT below — the reviewed table every cmd must appear in. A new
   csrc cmd fails the gate until it is classified here, which is where
   "mutating but deliberately NOT replicated" must be said out loud
   (`local_only=True`: operator save/load flows with server-local
   paths, the epoch/seq fencing plane, the unreplicated graph service).

Rules (all fatal; none are allowlisted in practice — drift is a bug):

  wire-cmd-drift        csrc Cmd enum vs CONTRACT (value/missing/extra)
  wire-cmd-mirror       Python cmd constant missing or value drift
  wire-err-drift        csrc Err enum vs CONTRACT
  wire-err-mirror       Python error mirror (const or raised exception)
                        missing or value drift
  wire-flag-drift       csrc PushWireFlag enum (quantized push-payload
                        aux bits + block shift) vs FLAG_CONTRACT
  wire-flag-mirror      Python _PUSH_WIRE_* constant missing or drifted
  wire-header-drift     ReqHeader fields vs ha._HDR format vs
                        rpc._REQ_HEADER_BYTES vs trace.WIRE_CONTEXT_BYTES;
                        ObsSpan vs trace.SERVER_SPAN_STRUCT
  wire-class-drift      tap/gate/keyed/create classification in csrc
                        disagrees with CONTRACT
  wire-untapped-mutation a cmd the gate treats as a mutation is neither
                        tapped for replication nor declared local_only

tests/test_wire_contract.py reuses :func:`extract_csrc` and
:func:`extract_python` as a library so the same pins also fail plain
pytest (tier-1), not just the lint gate.
"""

from __future__ import annotations

import ast
import os
import re
import struct
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from common import Diagnostic, dotted, relpath  # noqa: E402

# ---------------------------------------------------------------------------
# the reviewed contract: every wire command, classified
# ---------------------------------------------------------------------------
# fields: id; py = Python mirror constant (module key, name) or None;
# tap  = is_mutating_cmd     (oplog tap)          yes/no/cond
# gate = is_training_plane_cmd (read-only refuse + obs gate class)
# keyed = is_keyed_data_cmd   (payload leads with [u64 keys × n])
# local_only = mutates server state but is DELIBERATELY untapped
#              (operator flows with local paths; fencing plane; the
#              unreplicated graph service)


@dataclass(frozen=True)
class CmdSpec:
    id: int
    py: Optional[Tuple[str, str]]
    tap: str = "no"
    gate: str = "no"
    keyed: bool = False
    create: bool = False
    local_only: bool = False


CONTRACT: Dict[str, CmdSpec] = {
    "kCreateSparse": CmdSpec(1, ("rpc", "_CREATE_SPARSE"), tap="yes",
                             create=True),
    "kCreateDense": CmdSpec(2, ("rpc", "_CREATE_DENSE"), tap="yes",
                            create=True),
    "kPullSparse": CmdSpec(3, ("rpc", "_PULL_SPARSE"), tap="cond",
                           keyed=True),
    "kPushSparse": CmdSpec(4, ("rpc", "_PUSH_SPARSE"), tap="yes",
                           gate="yes", keyed=True),
    "kPullDense": CmdSpec(5, ("rpc", "_PULL_DENSE")),
    "kPushDense": CmdSpec(6, ("rpc", "_PUSH_DENSE"), tap="yes", gate="yes"),
    "kSetDense": CmdSpec(7, ("rpc", "_SET_DENSE"), tap="yes", gate="yes"),
    "kSize": CmdSpec(8, ("rpc", "_SIZE")),
    "kShrink": CmdSpec(9, ("rpc", "_SHRINK"), tap="yes", gate="yes"),
    "kSaveBegin": CmdSpec(10, ("rpc", "_SAVE_BEGIN")),
    "kSaveFetch": CmdSpec(11, ("rpc", "_SAVE_FETCH")),
    "kInsertFull": CmdSpec(12, ("rpc", "_INSERT_FULL"), tap="yes",
                           keyed=True),
    "kExport": CmdSpec(13, ("rpc", "_EXPORT"), tap="cond", gate="cond",
                       keyed=True),
    "kBarrier": CmdSpec(14, ("rpc", "_BARRIER")),
    "kStop": CmdSpec(15, ("rpc", "_STOP"), local_only=True),
    "kPing": CmdSpec(16, ("rpc", "_PING")),
    "kGlobalStep": CmdSpec(17, ("rpc", "_GLOBAL_STEP"), tap="cond"),
    "kCreateGeo": CmdSpec(18, ("rpc", "_CREATE_GEO"), tap="yes",
                          create=True),
    "kPushGeo": CmdSpec(19, ("rpc", "_PUSH_GEO"), tap="yes", gate="yes",
                        keyed=True),
    "kPullGeo": CmdSpec(20, ("rpc", "_PULL_GEO"), tap="yes", gate="yes"),
    "kSaveAll": CmdSpec(21, ("rpc", "_SAVE_ALL")),
    "kSpill": CmdSpec(22, ("rpc", "_SPILL"), local_only=True),
    "kStats": CmdSpec(23, ("rpc", "_STATS")),
    "kCompact": CmdSpec(24, ("rpc", "_COMPACT"), local_only=True),
    # graph service: mutates the graph table but the graph plane is NOT
    # replicated (no oplog tap by design) — hence local_only
    "kCreateGraph": CmdSpec(25, ("graph", "_CREATE_GRAPH"),
                            local_only=True),
    "kGraphAddNodes": CmdSpec(26, ("graph", "_ADD_NODES"), local_only=True),
    "kGraphAddEdges": CmdSpec(27, ("graph", "_ADD_EDGES"), local_only=True),
    "kGraphSampleNeighbors": CmdSpec(28, ("graph", "_SAMPLE_NEIGHBORS")),
    "kGraphDegree": CmdSpec(29, ("graph", "_DEGREE")),
    "kGraphNodeFeat": CmdSpec(30, ("graph", "_NODE_FEAT")),
    "kGraphSetNodeFeat": CmdSpec(31, ("graph", "_SET_NODE_FEAT"),
                                 local_only=True),
    "kGraphSampleNodes": CmdSpec(32, ("graph", "_SAMPLE_NODES")),
    "kGraphStats": CmdSpec(33, ("graph", "_GRAPH_STATS")),
    # operator bulk save/load: server-local paths, deliberately
    # unreplicated (ha.py documents the restriction)
    "kLoadCold": CmdSpec(34, ("rpc", "_LOAD_COLD"), tap="yes", gate="yes",
                         keyed=True),
    "kSaveFile": CmdSpec(35, ("rpc", "_SAVE_FILE"), local_only=True),
    "kLoadFile": CmdSpec(36, ("rpc", "_LOAD_FILE"), local_only=True),
    # HA / replication control plane: the fence itself must never
    # replicate (a demoted primary's stream is what it fences out)
    "kReplicate": CmdSpec(37, ("rpc", "_REPLICATE"), local_only=True),
    "kEpoch": CmdSpec(38, ("rpc", "_EPOCH"), local_only=True),
    "kReplState": CmdSpec(39, ("rpc", "_REPL_STATE"), local_only=True),
    "kDigest": CmdSpec(40, ("rpc", "_DIGEST")),
    "kDenseSnap": CmdSpec(41, ("rpc", "_DENSE_SNAP")),
    "kDenseRestore": CmdSpec(42, ("rpc", "_DENSE_RESTORE"), tap="yes"),
    "kObsSnap": CmdSpec(43, ("rpc", "_OBS_SNAP"), local_only=True),
    "kRetain": CmdSpec(44, ("rpc", "_RETAIN"), tap="cond", gate="cond"),
    # multi-tenancy (ps/tenancy.py): hello binds a connection to its
    # tenant; config is the operator-plane registry/usage-meter. Both
    # are pure control plane — never tapped, never gated, and config is
    # local_only (the tenant registry is per-server state an operator
    # installs on every shard; it must not ride the oplog to backups
    # that may serve a different tenant set).
    "kTenantHello": CmdSpec(45, ("rpc", "_TENANT_HELLO")),
    "kTenantConfig": CmdSpec(46, ("rpc", "_TENANT_CONFIG"),
                             local_only=True),
}

# quantized-payload wire flags (csrc PushWireFlag — kPushSparse aux
# bits + the int8 block-size shift). A new encoding flag must appear
# here AND in both languages, or the gate fails: the aux word is part
# of the frame the oplog taps, so a drifted flag silently corrupts
# every replaying backup.
FLAG_CONTRACT: Dict[str, Tuple[int, Tuple[str, str]]] = {
    "kPushWireF16": (1, ("rpc", "_PUSH_WIRE_F16")),
    "kPushWireI8": (2, ("rpc", "_PUSH_WIRE_I8")),
    "kPushWireBlockShift": (8, ("rpc", "_PUSH_WIRE_BLOCK_SHIFT")),
}

# error codes: py mirror is either a module-level constant in ha.py or
# the exception _ServerConn.check raises for that status (or None)
ERR_CONTRACT: Dict[str, Tuple[int, Optional[Tuple[str, str]]]] = {
    "kErrBadCmd": (-1, None),
    "kErrNoTable": (-2, ("raise", "NotFoundError")),
    "kErrBadSize": (-3, None),
    "kErrInternal": (-4, None),
    "kErrStaleEpoch": (-5, ("ha", "_rpc_err_stale_epoch")),
    "kErrSeqGap": (-6, ("ha", "_rpc_err_seq_gap")),
    "kErrReadOnly": (-7, ("raise", "PreconditionNotMetError")),
    "kErrWrongShard": (-8, ("raise", "WrongShardError")),
    "kErrWrongTenant": (-9, ("raise", "WrongTenantError")),
    "kErrQuota": (-10, ("raise", "QuotaExceededError")),
    "kErrThrottled": (-11, ("raise", "ThrottledError")),
}

_CTYPE_FMT = {"uint64_t": "Q", "int64_t": "q", "uint32_t": "I",
              "int32_t": "i", "uint16_t": "H", "int16_t": "h",
              "uint8_t": "B", "int8_t": "b", "double": "d", "float": "f"}

_CSRC = "paddle_tpu/csrc/ps_service.cc"
_PY_FILES = {"rpc": "paddle_tpu/ps/rpc.py",
             "graph": "paddle_tpu/ps/graph_client.py",
             "ha": "paddle_tpu/ps/ha.py",
             "trace": "paddle_tpu/obs/trace.py"}

# ---------------------------------------------------------------------------
# SSD cold-tier ABI contract (csrc/ssd_table.cc ↔ ps/native.py)
# ---------------------------------------------------------------------------
# The sst_* surface is an in-process ctypes ABI, not an RPC wire — but
# it drifts the same way: ssd_table.cc owns the entry points, the
# SstStatField enum and the block-record format; native.py hand-mirrors
# the symbol bindings, SST_STAT_FIELDS and the SST_BLOCK_*/SST_FLAG_*
# constants. Every extern "C" sst_* definition must be listed here and
# referenced from native.py; the stat enum and format constants must
# agree in both languages, value for value.

_SST_CSRC = "paddle_tpu/csrc/ssd_table.cc"
_SST_PY = "paddle_tpu/ps/native.py"

#: every extern "C" sst_* entry point, reviewed. A new one fails the
#: gate until it is added here AND bound in native.py.
SST_ENTRY_CONTRACT = (
    "sst_create", "sst_create2", "sst_destroy",
    "sst_pull_dim", "sst_push_dim", "sst_full_dim",
    "sst_stats", "sst_stats2", "sst_shard_sizes", "sst_size",
    "sst_digest",
    "sst_pull", "sst_push", "sst_export", "sst_insert_full",
    "sst_load_cold", "sst_spill", "sst_shrink", "sst_compact",
    "sst_admission_config", "sst_io_budget",
    "sst_bg_start", "sst_bg_stop", "sst_bg_step", "sst_compact_async",
    "sst_save_begin", "sst_save_fetch", "sst_flush",
    "sst_save_file", "sst_load_file",
)

#: SstStatField enum (csrc) ↔ SST_STAT_FIELDS dict (native.py):
#: csrc name → (python key, index)
SST_STAT_CONTRACT: Dict[str, Tuple[str, int]] = {
    "kSstHotRows": ("hot_rows", 0),
    "kSstColdRows": ("cold_rows", 1),
    "kSstDiskBytes": ("disk_bytes", 2),
    "kSstIndexBytes": ("index_bytes", 3),
    "kSstSketchBytes": ("sketch_bytes", 4),
    "kSstAdmitChecks": ("admit_checks", 5),
    "kSstAdmitRejects": ("admit_rejects", 6),
    "kSstAdmitAdmitted": ("admit_admitted", 7),
    "kSstBgCompactions": ("bg_compactions", 8),
    "kSstBgBacklog": ("bg_backlog", 9),
    "kSstIoServeBytes": ("io_serve_bytes", 10),
    "kSstIoBgBytes": ("io_bg_bytes", 11),
    "kSstIoBgWaitMs": ("io_bg_wait_ms", 12),
    "kSstOpenBlockBytes": ("open_block_bytes", 13),
}
SST_STAT_COUNT = 14

#: block-record format + create-flag bits: csrc constexpr name →
#: (python constant in native.py, reviewed value). The python flag
#: constants have no named csrc twin (sst_create2 reads the bits
#: directly) — csrc_name None pins the python side to the contract.
SST_FORMAT_CONTRACT: Dict[str, Tuple[Optional[str], int]] = {
    "SST_BLOCK_MAGIC": ("kSstBlkMagic", 0x4B4C4253),
    "SST_BLOCK_RECS": ("kSstBlockRecs", 128),
    "SST_BLOCK_HDR_BYTES": ("kSstBlockHdrBytes", 16),
    "SST_FLAG_VALUE_F16": (None, 1),
    "SST_FLAG_BLOCK_COMPRESS": (None, 2),
    "SST_STAT_COUNT": ("kSstStatCount", SST_STAT_COUNT),
}

# the pass's own file is relevant too: a CONTRACT edit must re-run the
# cross-validation in --changed mode
RELEVANT_FILES = (_CSRC, *_PY_FILES.values(), _SST_CSRC, _SST_PY,
                  "tools/lint/wire_contract.py")


# ---------------------------------------------------------------------------
# csrc extractor (line-based; no clang)
# ---------------------------------------------------------------------------

@dataclass
class CsrcContract:
    cmds: Dict[str, Tuple[int, int]] = field(default_factory=dict)  # val,line
    errs: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    flags: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    structs: Dict[str, List[Tuple[str, str, int]]] = \
        field(default_factory=dict)            # name -> [(ctype, field, line)]
    classify: Dict[str, Dict[str, str]] = \
        field(default_factory=dict)            # fn -> {cmd: yes|no|cond}


_ENUM_START_RE = re.compile(r"enum\s+(\w+)\s*(?::\s*\w+)?\s*\{")
_ENUM_ENTRY_RE = re.compile(r"^\s*(k\w+)\s*=\s*(-?\d+)\s*,?")
_STRUCT_START_RE = re.compile(r"struct\s+(\w+)\s*\{")
_FIELD_RE = re.compile(r"^\s*(\w+)\s+(\w+(?:\s*,\s*\w+)*)\s*(?:=[^;]*)?;")
_FN_START_RE = re.compile(r"inline\s+bool\s+(is_\w+)\s*\(")
_CASE_RE = re.compile(r"^\s*case\s+(k\w+)\s*:")
_RETURN_RE = re.compile(r"^\s*return\s+([^;]+);")


def extract_csrc(path: str) -> CsrcContract:
    out = CsrcContract()
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()

    mode = None          # ("enum", name) | ("struct", name) | ("fn", name)
    pending_cases: List[str] = []
    default_seen = False
    for i, raw in enumerate(lines, 1):
        line = raw.split("//")[0]
        if mode is None:
            m = _ENUM_START_RE.search(line)
            if m and m.group(1) in ("Cmd", "Err", "PushWireFlag"):
                mode = ("enum", m.group(1))
                continue
            m = _STRUCT_START_RE.search(line)
            if m and m.group(1) in ("ReqHeader", "ObsSpan"):
                mode = ("struct", m.group(1))
                out.structs[m.group(1)] = []
                continue
            m = _FN_START_RE.search(line)
            if m:
                mode = ("fn", m.group(1))
                out.classify[m.group(1)] = {}
                pending_cases, default_seen = [], False
            continue
        kind, name = mode
        if kind == "enum":
            m = _ENUM_ENTRY_RE.match(line)
            if m:
                tgt = {"Cmd": out.cmds, "Err": out.errs,
                       "PushWireFlag": out.flags}[name]
                tgt[m.group(1)] = (int(m.group(2)), i)
            if "}" in line:
                mode = None
        elif kind == "struct":
            m = _FIELD_RE.match(line)
            if m and m.group(1) in _CTYPE_FMT:
                for fname in m.group(2).split(","):
                    out.structs[name].append((m.group(1), fname.strip(), i))
            if "}" in line:
                mode = None
        elif kind == "fn":
            m = _CASE_RE.match(line)
            if m:
                pending_cases.append(m.group(1))
            if re.match(r"^\s*default\s*:", line):
                default_seen = True
            m = _RETURN_RE.match(line)
            if m:
                expr = m.group(1).strip()
                verdict = {"true": "yes", "false": "no"}.get(expr, "cond")
                if default_seen:
                    # `default: return X;` ends the switch for us
                    mode = None
                    continue
                if not pending_cases and "==" in expr:
                    # the `return cmd == kA || cmd == kB;` one-liner form
                    for c in re.findall(r"k\w+", expr):
                        out.classify[name][c] = "yes"
                    mode = None
                    continue
                for c in pending_cases:
                    out.classify[name][c] = verdict
                pending_cases = []
            if re.match(r"^\}", raw):
                mode = None
    return out


def struct_format(fields: List[Tuple[str, str, int]]) -> str:
    return "<" + "".join(_CTYPE_FMT[t] for t, _, _ in fields)


# ---------------------------------------------------------------------------
# SSD cold-tier extractors
# ---------------------------------------------------------------------------

@dataclass
class SstCsrcContract:
    entries: Dict[str, int] = field(default_factory=dict)   # name -> line
    stats: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    consts: Dict[str, Tuple[int, int]] = field(default_factory=dict)


_SST_ENTRY_RE = re.compile(
    r"^[A-Za-z_][A-Za-z0-9_]*\s*\*?\s+\*?(sst_\w+)\s*\(")
_SST_CONST_RE = re.compile(
    r"^constexpr\s+\w+\s+(k\w+)\s*=\s*(0[xX][0-9a-fA-F]+|\d+)[uU]?\s*;")


def extract_sst_csrc(path: str) -> SstCsrcContract:
    out = SstCsrcContract()
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    in_enum = False
    for i, raw in enumerate(lines, 1):
        line = raw.split("//")[0]
        if in_enum:
            m = _ENUM_ENTRY_RE.match(line)
            if m:
                out.stats[m.group(1)] = (int(m.group(2)), i)
            if "}" in line:
                in_enum = False
            continue
        m = _ENUM_START_RE.search(line)
        if m and m.group(1) == "SstStatField":
            in_enum = True
            continue
        m = _SST_CONST_RE.match(line)
        if m:
            out.consts[m.group(1)] = (int(m.group(2), 0), i)
            continue
        m = _SST_ENTRY_RE.match(line)
        if m:
            out.entries[m.group(1)] = i
    return out


@dataclass
class SstPyContract:
    refs: Dict[str, int] = field(default_factory=dict)    # sst_* attr -> line
    consts: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    stat_fields: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    stat_fields_line: int = 0


def extract_sst_python(path: str) -> SstPyContract:
    out = SstPyContract()
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    out.consts = _int_consts(tree)
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "SST_STAT_FIELDS" and \
                isinstance(node.value, ast.Dict):
            out.stat_fields_line = node.lineno
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and \
                        isinstance(v, ast.Constant) and \
                        isinstance(v.value, int):
                    out.stat_fields[str(k.value)] = (v.value, k.lineno)
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and \
                node.attr.startswith("sst_"):
            out.refs.setdefault(node.attr, node.lineno)
        elif isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                node.value.startswith("sst_"):
            # hasattr(lib, "sst_digest") / getattr-by-name bindings
            out.refs.setdefault(node.value, node.lineno)
    return out


def check_sst(root: str) -> List[Diagnostic]:
    csrc_path = os.path.join(root, _SST_CSRC)
    py_path = os.path.join(root, _SST_PY)
    if not (os.path.exists(csrc_path) and os.path.exists(py_path)):
        return []   # scratch trees: fail open like the rpc section
    cs = extract_sst_csrc(csrc_path)
    py = extract_sst_python(py_path)
    diags: List[Diagnostic] = []

    def d(path: str, line: int, rule: str, msg: str) -> None:
        diags.append(Diagnostic(path, line, rule, msg))

    # -- entry points --------------------------------------------------------
    for name in SST_ENTRY_CONTRACT:
        if name not in cs.entries:
            d(_SST_CSRC, 1, "sst-entry-drift",
              f"contract entry point `{name}` has no extern \"C\" "
              "definition in ssd_table.cc")
        if name not in py.refs:
            d(_SST_PY, 1, "sst-entry-mirror",
              f"`{name}` (contract ABI entry) is never bound or "
              "referenced in ps/native.py")
    for name, line in cs.entries.items():
        if name not in SST_ENTRY_CONTRACT:
            d(_SST_CSRC, line, "sst-entry-drift",
              f"extern \"C\" `{name}` is not in SST_ENTRY_CONTRACT — "
              "add it there AND bind it in ps/native.py "
              "(tools/lint/wire_contract.py)")

    # -- stat enum -----------------------------------------------------------
    for cname, (pykey, idx) in SST_STAT_CONTRACT.items():
        got = cs.stats.get(cname)
        if got is None:
            d(_SST_CSRC, 1, "sst-stat-drift",
              f"contract stat `{cname}` (= {idx}) missing from the csrc "
              "SstStatField enum")
        elif got[0] != idx:
            d(_SST_CSRC, got[1], "sst-stat-drift",
              f"`{cname}` = {got[0]} in csrc but {idx} in the contract")
        got_py = py.stat_fields.get(pykey)
        if got_py is None:
            d(_SST_PY, py.stat_fields_line or 1, "sst-stat-mirror",
              f"SST_STAT_FIELDS lacks `{pykey}` (mirror of csrc "
              f"{cname} = {idx})")
        elif got_py[0] != idx:
            d(_SST_PY, got_py[1], "sst-stat-mirror",
              f"SST_STAT_FIELDS[{pykey!r}] = {got_py[0]} but csrc "
              f"{cname} = {idx}")
    known_idx = {i for _, i in SST_STAT_CONTRACT.values()}
    for cname, (val, line) in cs.stats.items():
        if cname == "kSstStatCount":
            continue
        if cname not in SST_STAT_CONTRACT:
            d(_SST_CSRC, line, "sst-stat-drift",
              f"csrc stat `{cname}` = {val} is not in SST_STAT_CONTRACT")
    for pykey, (val, line) in py.stat_fields.items():
        if val not in known_idx:
            d(_SST_PY, line, "sst-stat-mirror",
              f"SST_STAT_FIELDS[{pykey!r}] = {val} has no contract twin")

    # -- record format + flag bits -------------------------------------------
    for pyname, (cname, want) in SST_FORMAT_CONTRACT.items():
        if cname is not None:
            got = cs.consts.get(cname) or cs.stats.get(cname)
            if got is None:
                d(_SST_CSRC, 1, "sst-format-drift",
                  f"csrc constant `{cname}` (contract value {want}) not "
                  "found in ssd_table.cc")
            elif got[0] != want:
                d(_SST_CSRC, got[1], "sst-format-drift",
                  f"`{cname}` = {got[0]} in csrc but {want} in the "
                  "contract")
        got_py = py.consts.get(pyname)
        if got_py is None:
            d(_SST_PY, 1, "sst-format-mirror",
              f"`{pyname}` (contract value {want}) missing from "
              "ps/native.py")
        elif got_py[0] != want:
            d(_SST_PY, got_py[1], "sst-format-mirror",
              f"`{pyname}` = {got_py[0]} but the contract says {want}")
    return diags


# ---------------------------------------------------------------------------
# Python extractor
# ---------------------------------------------------------------------------

@dataclass
class PyContract:
    consts: Dict[str, Dict[str, Tuple[int, int]]] = \
        field(default_factory=dict)   # module key -> {NAME: (value, line)}
    raises: Dict[int, Tuple[str, int]] = \
        field(default_factory=dict)   # status -> (exception name, line)
    hdr_format: Optional[str] = None
    hdr_line: int = 0
    span_format: Optional[str] = None
    span_line: int = 0
    req_header_bytes: Optional[int] = None
    req_header_line: int = 0
    wire_context_bytes: Optional[int] = None


def _int_consts(tree: ast.Module) -> Dict[str, Tuple[int, int]]:
    out: Dict[str, Tuple[int, int]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            v = node.value
            neg = isinstance(v, ast.UnaryOp) and isinstance(v.op, ast.USub)
            if neg:
                v = v.operand
            if isinstance(v, ast.Constant) and isinstance(v.value, int) \
                    and not isinstance(v.value, bool):
                out[node.targets[0].id] = (-v.value if neg else v.value,
                                           node.lineno)
    return out


def _struct_literal(tree: ast.Module, name: str) -> Tuple[Optional[str], int]:
    """`NAME = struct.Struct("<fmt>")` → (fmt, line)."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == name and \
                isinstance(node.value, ast.Call) and \
                dotted(node.value.func) in ("struct.Struct", "Struct") and \
                node.value.args and \
                isinstance(node.value.args[0], ast.Constant):
            return str(node.value.args[0].value), node.lineno
    return None, 0


def extract_python(root: str) -> PyContract:
    out = PyContract()
    trees: Dict[str, ast.Module] = {}
    for key, rel in _PY_FILES.items():
        p = os.path.join(root, rel)
        if not os.path.exists(p):
            continue
        with open(p, encoding="utf-8") as f:
            trees[key] = ast.parse(f.read())
        out.consts[key] = _int_consts(trees[key])

    if "trace" in out.consts:
        got = out.consts["trace"].get("WIRE_CONTEXT_BYTES")
        out.wire_context_bytes = got[0] if got else None
    if "trace" in trees:
        out.span_format, out.span_line = _struct_literal(
            trees["trace"], "SERVER_SPAN_STRUCT")
    if "ha" in trees:
        out.hdr_format, out.hdr_line = _struct_literal(trees["ha"], "_HDR")

    rpc_tree = trees.get("rpc")
    if rpc_tree is not None:
        # _REQ_HEADER_BYTES = 28 + _trace.WIRE_CONTEXT_BYTES
        for node in rpc_tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id == "_REQ_HEADER_BYTES":
                out.req_header_line = node.lineno
                v = node.value
                if isinstance(v, ast.Constant):
                    out.req_header_bytes = int(v.value)
                elif isinstance(v, ast.BinOp) and \
                        isinstance(v.op, ast.Add) and \
                        isinstance(v.left, ast.Constant) and \
                        (dotted(v.right) or "").endswith(
                            "WIRE_CONTEXT_BYTES") and \
                        out.wire_context_bytes is not None:
                    out.req_header_bytes = (int(v.left.value)
                                            + out.wire_context_bytes)
        # `if status == -N: raise Exc(...)` inside any `check` function
        for node in ast.walk(rpc_tree):
            if not (isinstance(node, ast.FunctionDef) and
                    node.name == "check"):
                continue
            for st in ast.walk(node):
                if not (isinstance(st, ast.If) and
                        isinstance(st.test, ast.Compare) and
                        len(st.test.ops) == 1 and
                        isinstance(st.test.ops[0], ast.Eq)):
                    continue
                rhs = st.test.comparators[0]
                neg = isinstance(rhs, ast.UnaryOp) and \
                    isinstance(rhs.op, ast.USub)
                lit = rhs.operand if neg else rhs
                if not (isinstance(lit, ast.Constant) and
                        isinstance(lit.value, int)):
                    continue
                status = -lit.value if neg else lit.value
                for b in st.body:
                    if isinstance(b, ast.Raise) and b.exc is not None:
                        exc = b.exc.func if isinstance(b.exc, ast.Call) \
                            else b.exc
                        nm = dotted(exc)
                        if nm:
                            out.raises[status] = (nm.rsplit(".", 1)[-1],
                                                  st.lineno)
    return out


# ---------------------------------------------------------------------------
# cross-validation
# ---------------------------------------------------------------------------

def check(root: str) -> List[Diagnostic]:
    csrc_path = os.path.join(root, _CSRC)
    if not os.path.exists(csrc_path):
        return []   # scratch trees / partial checkouts: fail open
    rel_csrc = _CSRC
    cs = extract_csrc(csrc_path)
    py = extract_python(root)
    diags: List[Diagnostic] = []

    def d(path: str, line: int, rule: str, msg: str) -> None:
        diags.append(Diagnostic(path, line, rule, msg))

    # -- cmd enum vs contract ------------------------------------------------
    for name, spec in CONTRACT.items():
        got = cs.cmds.get(name)
        if got is None:
            d(rel_csrc, 1, "wire-cmd-drift",
              f"contract cmd `{name}` (= {spec.id}) is missing from the "
              "csrc Cmd enum")
        elif got[0] != spec.id:
            d(rel_csrc, got[1], "wire-cmd-drift",
              f"`{name}` = {got[0]} in csrc but {spec.id} in the contract "
              "(tools/lint/wire_contract.py CONTRACT)")
    for name, (val, line) in cs.cmds.items():
        if name not in CONTRACT:
            d(rel_csrc, line, "wire-cmd-drift",
              f"csrc cmd `{name}` = {val} is not classified in the "
              "contract — add a CmdSpec (tap/gate/keyed/local_only) to "
              "tools/lint/wire_contract.py")

    # -- python cmd mirrors --------------------------------------------------
    for name, spec in CONTRACT.items():
        if spec.py is None:
            continue
        mod, const = spec.py
        rel_py = _PY_FILES[mod]
        got = py.consts.get(mod, {}).get(const)
        if got is None:
            d(rel_py, 1, "wire-cmd-mirror",
              f"`{const}` (mirror of csrc {name} = {spec.id}) is missing")
        elif got[0] != spec.id:
            d(rel_py, got[1], "wire-cmd-mirror",
              f"`{const}` = {got[0]} but csrc {name} = {spec.id}")

    # -- err enum + mirrors --------------------------------------------------
    for name, (val, mirror) in ERR_CONTRACT.items():
        got = cs.errs.get(name)
        if got is None:
            d(rel_csrc, 1, "wire-err-drift",
              f"contract error `{name}` (= {val}) missing from the csrc "
              "Err enum")
        elif got[0] != val:
            d(rel_csrc, got[1], "wire-err-drift",
              f"`{name}` = {got[0]} in csrc but {val} in the contract")
        if mirror is None:
            continue
        kind, nm = mirror
        if kind == "ha":
            got_py = py.consts.get("ha", {}).get(nm)
            if got_py is None:
                d(_PY_FILES["ha"], 1, "wire-err-mirror",
                  f"`{nm}` (mirror of csrc {name} = {val}) is missing")
            elif got_py[0] != val:
                d(_PY_FILES["ha"], got_py[1], "wire-err-mirror",
                  f"`{nm}` = {got_py[0]} but csrc {name} = {val}")
        elif kind == "raise":
            got_r = py.raises.get(val)
            if got_r is None:
                d(_PY_FILES["rpc"], 1, "wire-err-mirror",
                  f"_ServerConn.check does not map status {val} "
                  f"(csrc {name}) to `{nm}`")
            elif got_r[0] != nm:
                d(_PY_FILES["rpc"], got_r[1], "wire-err-mirror",
                  f"_ServerConn.check raises `{got_r[0]}` for status "
                  f"{val} but the contract says `{nm}` (csrc {name})")
    for val, (exc, line) in py.raises.items():
        if not any(v == val for v, _ in ERR_CONTRACT.values()):
            d(_PY_FILES["rpc"], line, "wire-err-mirror",
              f"_ServerConn.check maps status {val} (`{exc}`) but no csrc "
              "error code has that value")

    # -- quantized-payload wire flags (PushWireFlag) -------------------------
    for name, (val, (mod, const)) in FLAG_CONTRACT.items():
        got = cs.flags.get(name)
        if got is None:
            d(rel_csrc, 1, "wire-flag-drift",
              f"contract wire flag `{name}` (= {val}) missing from the "
              "csrc PushWireFlag enum")
        elif got[0] != val:
            d(rel_csrc, got[1], "wire-flag-drift",
              f"`{name}` = {got[0]} in csrc but {val} in the contract")
        rel_py = _PY_FILES[mod]
        got_py = py.consts.get(mod, {}).get(const)
        if got_py is None:
            d(rel_py, 1, "wire-flag-mirror",
              f"`{const}` (mirror of csrc {name} = {val}) is missing")
        elif got_py[0] != val:
            d(rel_py, got_py[1], "wire-flag-mirror",
              f"`{const}` = {got_py[0]} but csrc {name} = {val}")
    for name, (val, line) in cs.flags.items():
        if name not in FLAG_CONTRACT:
            d(rel_csrc, line, "wire-flag-drift",
              f"csrc wire flag `{name}` = {val} is not in FLAG_CONTRACT "
              "— classify it (tools/lint/wire_contract.py)")

    # -- header layouts ------------------------------------------------------
    req = cs.structs.get("ReqHeader")
    if not req:
        d(rel_csrc, 1, "wire-header-drift",
          "could not extract `struct ReqHeader` field layout")
    else:
        fmt = struct_format(req)
        size = struct.calcsize(fmt)
        if py.hdr_format is not None:
            py_fmt = py.hdr_format.replace(" ", "")
            if py_fmt != fmt:
                d(_PY_FILES["ha"], py.hdr_line, "wire-header-drift",
                  f"ha._HDR format {py.hdr_format!r} != csrc ReqHeader "
                  f"layout {fmt!r} "
                  f"({', '.join(f'{t} {n}' for t, n, _ in req)})")
            elif struct.calcsize(py_fmt) != size:
                d(_PY_FILES["ha"], py.hdr_line, "wire-header-drift",
                  f"ha._HDR size {struct.calcsize(py_fmt)} != csrc "
                  f"ReqHeader packed size {size}")
        if py.req_header_bytes is not None and py.req_header_bytes != size:
            d(_PY_FILES["rpc"], py.req_header_line, "wire-header-drift",
              f"rpc._REQ_HEADER_BYTES = {py.req_header_bytes} != csrc "
              f"ReqHeader packed size {size}")
        if py.wire_context_bytes is not None:
            trace_fields = [n for _, n, _ in req
                            if n in ("trace_id", "span_id")]
            tb = sum(struct.calcsize(_CTYPE_FMT[t])
                     for t, n, _ in req if n in ("trace_id", "span_id"))
            if len(trace_fields) != 2 or tb != py.wire_context_bytes:
                d(rel_csrc, req[0][2], "wire-header-drift",
                  f"ReqHeader trace-context fields ({tb} bytes across "
                  f"{len(trace_fields)} fields) != "
                  f"trace.WIRE_CONTEXT_BYTES = {py.wire_context_bytes}")
    span = cs.structs.get("ObsSpan")
    if span and py.span_format is not None:
        fmt = struct_format(span)
        if py.span_format.replace(" ", "") != fmt:
            d(_PY_FILES["trace"], py.span_line, "wire-header-drift",
              f"trace.SERVER_SPAN_STRUCT {py.span_format!r} != csrc "
              f"ObsSpan layout {fmt!r}")

    # -- classification ------------------------------------------------------
    fn_field = {"is_mutating_cmd": "tap", "is_training_plane_cmd": "gate",
                "is_keyed_data_cmd": "keyed", "is_create_cmd": "create"}
    for fn, fld in fn_field.items():
        table = cs.classify.get(fn)
        if table is None:
            d(rel_csrc, 1, "wire-class-drift",
              f"could not extract the `{fn}` switch")
            continue
        for name, spec in CONTRACT.items():
            want = getattr(spec, fld)
            if isinstance(want, bool):
                want = "yes" if want else "no"
            got = table.get(name, "no")
            if got != want:
                line = cs.cmds.get(name, (0, 1))[1]
                d(rel_csrc, line, "wire-class-drift",
                  f"`{name}`: csrc {fn} says {got!r} but the contract "
                  f"says {want!r} — if the behavior changed, update BOTH "
                  "the contract and every consumer of this class "
                  "(replication tap / read-only gate / ownership fence)")
        for name in table:
            if name not in CONTRACT:
                d(rel_csrc, 1, "wire-class-drift",
                  f"`{fn}` classifies unknown cmd `{name}`")

    # -- every gated mutation must be tapped or declared local-only ----------
    for name, spec in CONTRACT.items():
        if spec.gate != "no" and spec.tap == "no" and not spec.local_only:
            line = cs.cmds.get(name, (0, 1))[1]
            d(rel_csrc, line, "wire-untapped-mutation",
              f"`{name}` is gate-checked as a mutation but neither "
              "tapped for replication (is_mutating_cmd) nor declared "
              "local_only in the contract — a backup would silently "
              "miss it")
    return diags


def run(root: str, only=None) -> List[Diagnostic]:
    if only is not None and not any(f in only for f in RELEVANT_FILES):
        return []
    return sorted(check(root) + check_sst(root),
                  key=lambda d: (d.path, d.line, d.rule))


if __name__ == "__main__":
    from common import REPO_ROOT
    for diag in run(REPO_ROOT):
        print(diag)
