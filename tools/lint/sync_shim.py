"""graftlint pass 9: sync-shim discipline for schedulable modules.

A module that imports the ``paddle_tpu.core.sync`` shim has opted into
deterministic-schedule testing (paddle_tpu/testing/sched.py): every
lock, condition, event, semaphore, queue and thread it constructs must
go through the shim's factories so the explorer can interpose. ONE raw
``threading.Lock()`` in such a module is an invisible hole — the
explorer never sees its acquire/release, schedules stop being
serializable, and a "verified" protocol quietly regains real
nondeterminism. This pass makes the migration a ratchet: once a module
is shim-migrated, raw construction there is a violation.

Scope: a module is *shim-migrated* iff it imports ``sync`` out of a
``core`` package (``from ..core import sync as _sync``, any relative
level or alias, or ``import paddle_tpu.core.sync``). Non-migrated
modules are untouched — adopting the shim is deliberate, not ambient.
The shim's own implementation (``paddle_tpu/core/sync.py``) and the
test-only explorer (``paddle_tpu/testing/``) construct raw primitives
by design and are skipped.

Rules:

  raw-sync         constructing ``threading.Lock/RLock/Condition/
                   Event/Semaphore/BoundedSemaphore/Thread`` or
                   ``queue.Queue/LifoQueue/PriorityQueue`` in a
                   shim-migrated module — use the ``_sync.*`` factory
  raw-sync-syntax  a ``# graftlint: raw-sync`` escape without a reason

Escape: ``# graftlint: raw-sync <reason>`` trailing the construction
line keeps a deliberate raw primitive (e.g. the scheduler must never
interpose on a watchdog that OUTLIVES a test run); the reason is
required. ``# graftlint: ignore[raw-sync]`` also works.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import List, Optional, Set

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from common import Diagnostic, dotted, line_ignores, relpath, walk_py  # noqa: E402
from py_locks import _Aliases  # noqa: E402

_RAW_SYNC_RE = re.compile(r"#\s*graftlint:\s*raw-sync\b[:\s]*(.*)$")

#: raw constructors the shim wraps — resolved through import aliases
_RAW_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Event", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Thread",
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
}

#: files that construct raw primitives BY DESIGN
_SKIP_SUFFIXES = (
    os.path.join("paddle_tpu", "core", "sync.py"),
)
_SKIP_DIRS = (os.path.join("paddle_tpu", "testing") + os.sep,)


def _shim_alias_names(tree: ast.Module) -> Set[str]:
    """Local names bound to the core.sync shim module."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith("core.sync"):
                    names.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "sync" and \
                        (node.module or "").split(".")[-1] == "core":
                    names.add(a.asname or a.name)
    return names


def _escape(lines: List[str], line: int, end_line: int,
            rel: str, diags: List[Diagnostic]) -> bool:
    """raw-sync escape / ignore on any of the statement's lines."""
    for ln in range(line, min(end_line, line + 8) + 1):
        if "raw-sync" in line_ignores(lines, ln):
            return True
        if 1 <= ln <= len(lines):
            m = _RAW_SYNC_RE.search(lines[ln - 1])
            if m:
                if m.group(1).strip():
                    return True
                diags.append(Diagnostic(
                    rel, ln, "raw-sync-syntax",
                    "`# graftlint: raw-sync` needs a reason (`# "
                    "graftlint: raw-sync <why this primitive must "
                    "stay raw>`)"))
                return True  # malformed escape reported; don't double up
    return False


def check_file(path: str, root: str) -> List[Diagnostic]:
    rel = relpath(path, root)
    if rel.replace("/", os.sep).endswith(_SKIP_SUFFIXES) or \
            any(rel.replace("/", os.sep).startswith(d)
                for d in _SKIP_DIRS):
        return []
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []  # py_locks already reports unparsable files
    if not _shim_alias_names(tree):
        return []  # not shim-migrated: raw construction is fine
    lines = src.splitlines()
    aliases = _Aliases(tree)
    diags: List[Diagnostic] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = aliases.resolve(dotted(node.func))
        if callee not in _RAW_CTORS:
            continue
        end = getattr(node, "end_lineno", None) or node.lineno
        if _escape(lines, node.lineno, end, rel, diags):
            continue
        factory = callee.rsplit(".", 1)[-1]
        diags.append(Diagnostic(
            rel, node.lineno, "raw-sync",
            f"raw `{callee}()` in a shim-migrated module — construct "
            f"through the sync shim (`_sync.{factory}(...)`) so the "
            "schedule explorer can interpose, or justify with "
            "`# graftlint: raw-sync <reason>`"))
    return diags


def run(root: str, subdirs=("paddle_tpu",), files=(),
        only: Optional[Set[str]] = None) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for p in walk_py(root, subdirs, files, only=only):
        diags.extend(check_file(p, root))
    return sorted(diags, key=lambda d: (d.path, d.line, d.rule))


if __name__ == "__main__":
    from common import REPO_ROOT
    for d in run(REPO_ROOT):
        print(d)
