"""graftlint pass 6: control-loop timing + randomness injectability.

  uninjectable-clock  a class that runs its own CONTROL LOOP — it
                   constructs a ``threading.Thread`` whose ``target``
                   is one of its own methods — and whose loop reads
                   time (``time.sleep``/``time.monotonic``/
                   ``time.perf_counter``/``<event>.wait(...)``) while
                   its ``__init__`` exposes NO timing injection point.
                   Such a class can only be tested by real sleeping:
                   the test either races the loop (flaky under load —
                   the class of bug every "bump the sleep and rerun"
                   commit is apologizing for) or pays wall-clock per
                   case. Make the timing constructor-injectable —
                   either the cadence itself (``period_s=``,
                   ``poll_s=``, ``hb_interval=`` …) or the clock/sleep
                   callables (``clock=time.monotonic``,
                   ``sleep=time.sleep``) — the way Sampler(period_s),
                   Lease(interval), CircuitBreaker(clock) and
                   ReshardController(clock, sleep) already do.

  uninjectable-rng  the same control-loop shape drawing from the
                   PROCESS-GLOBAL rng (``random.random()``/
                   ``random.choice``/… or ``np.random.*``) with no
                   rng/seed injection point in ``__init__``. A routing
                   or retry decision made from global randomness on a
                   background thread cannot be replayed: the test
                   cannot seed it without seeding the whole process
                   (racing every other draw), so "which member did the
                   router pick" becomes unassertable — the serving
                   router's P2C/hedge choices are the motivating case.
                   Take ``rng=random.Random()`` (or a ``seed=``) in the
                   constructor and draw from it, the way
                   HARouter(jitter_seed) and ServingRouter(rng) do.
                   Module-level draws outside a thread loop (bench
                   setup, one-shot jitter at construction) are fine —
                   the rule fires only where a loop's DECISIONS hide
                   behind global state.

An ``__init__`` parameter counts as a timing injection point when its
name is one of the CLOCK names (clock, sleep, sleep_fn, now, now_fn,
timer, tick) or contains one of the CADENCE fragments (interval,
period, poll, timeout, ttl, cooldown, grace, idle, lag, duck, hold,
delay, backoff, every, _s / _ms suffixes are NOT required — the
fragment match is substring, case-insensitive).

The loop-body scan covers the thread-target method plus one level of
``self._helper()`` calls (a ``_loop`` that delegates its waiting to
``_poll_once`` is still a control loop).

Scope: ``paddle_tpu/`` (library control loops; tools/ demo drivers die
with their process). Suppression: trailing
``# graftlint: ignore[uninjectable-clock]`` on the ``class`` line, or
an allow.txt entry with justification.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Set

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from common import (Diagnostic, dotted, line_ignores,  # noqa: E402
                    relpath, walk_py)

RULE = "uninjectable-clock"
RULE_RNG = "uninjectable-rng"

_CLOCK_PARAM_NAMES = {"clock", "sleep", "sleep_fn", "sleep_s", "now",
                      "now_fn", "timer", "tick"}
_CADENCE_FRAGMENTS = ("interval", "period", "poll", "timeout", "ttl",
                      "cooldown", "grace", "idle", "lag", "duck", "hold",
                      "delay", "backoff", "every")

_TIME_FUNCS = {"sleep", "monotonic", "perf_counter", "time"}

_RNG_PARAM_NAMES = {"rng", "seed", "random", "rand", "generator"}
_RNG_FRAGMENTS = ("rng", "seed")

#: stdlib `random` module draws (global-state; `random.Random(...)`
#: CONSTRUCTION is not a draw and is excluded below)
_RANDOM_FUNCS = {"random", "randint", "randrange", "choice", "choices",
                 "shuffle", "sample", "uniform", "gauss", "normalvariate",
                 "expovariate", "betavariate", "triangular", "getrandbits",
                 "randbytes"}
#: numpy legacy global-state draws (np.random.<f>); default_rng(...) is
#: a constructor, not a draw
_NP_RANDOM_FUNCS = {"rand", "randn", "randint", "random", "random_sample",
                    "choice", "shuffle", "permutation", "uniform", "normal",
                    "standard_normal", "exponential", "beta", "binomial",
                    "poisson"}


def _init_params(init: ast.FunctionDef):
    return list(init.args.posonlyargs) + list(init.args.args) + \
        list(init.args.kwonlyargs)


def _init_injects_timing(init: ast.FunctionDef) -> bool:
    for a in _init_params(init):
        name = a.arg.lower()
        if name in _CLOCK_PARAM_NAMES:
            return True
        if any(frag in name for frag in _CADENCE_FRAGMENTS):
            return True
    return False


def _init_injects_rng(init: ast.FunctionDef) -> bool:
    for a in _init_params(init):
        name = a.arg.lower()
        if name in _RNG_PARAM_NAMES:
            return True
        if any(frag in name for frag in _RNG_FRAGMENTS):
            return True
    return False


def _self_thread_targets(cls: ast.ClassDef) -> Dict[str, ast.Call]:
    """Method names used as ``target=self.<m>`` in a Thread
    construction anywhere in the class (module-alias and from-import
    Thread forms are the caller's concern — we match on the keyword
    shape: any Call with a ``target=self.X`` keyword and a name ending
    in 'Thread')."""
    out: Dict[str, ast.Call] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        if not name.rsplit(".", 1)[-1].endswith("Thread"):
            continue
        for kw in node.keywords:
            if kw.arg == "target" and isinstance(kw.value, ast.Attribute) \
                    and isinstance(kw.value.value, ast.Name) \
                    and kw.value.value.id == "self":
                out[kw.value.attr] = node
    return out


def _timing_call(node: ast.Call, time_aliases: Set[str],
                 bare_time_funcs: Set[str]) -> bool:
    name = dotted(node.func)
    if name in bare_time_funcs:
        return True
    if name and "." in name:
        mod, _, attr = name.rpartition(".")
        if mod in time_aliases and attr in _TIME_FUNCS:
            return True
        # <event>.wait(x) — threading.Event/Condition waits ARE the
        # loop cadence; a bare .wait() (no deadline) is a pure signal
        if attr == "wait" and node.args:
            return True
    return False


def _rng_call(node: ast.Call, random_aliases: Set[str],
              numpy_aliases: Set[str], npr_aliases: Set[str],
              bare_random_funcs: Set[str]) -> bool:
    name = dotted(node.func)
    if name in bare_random_funcs:
        return True
    if name and "." in name:
        mod, _, attr = name.rpartition(".")
        if mod in random_aliases and attr in _RANDOM_FUNCS:
            return True
        if attr in _NP_RANDOM_FUNCS:
            if mod in npr_aliases:
                return True
            parts = mod.split(".")
            if len(parts) == 2 and parts[0] in numpy_aliases \
                    and parts[1] == "random":
                return True
    return False


def _method_map(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _loop_first_call(target: ast.FunctionDef,
                     methods: Dict[str, ast.FunctionDef],
                     pred) -> Optional[ast.Call]:
    """The first call matching ``pred`` in the thread target or one
    level of its ``self._helper()`` callees."""
    scopes = [target]
    for node in ast.walk(target):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self" \
                and node.func.attr in methods:
            scopes.append(methods[node.func.attr])
    for scope in scopes:
        for node in ast.walk(scope):
            if isinstance(node, ast.Call) and pred(node):
                return node
    return None


def check_file(path: str, root: str) -> List[Diagnostic]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    rel = relpath(path, root)
    lines = src.splitlines()
    diags: List[Diagnostic] = []

    time_aliases = {"time"}
    bare_time_funcs: Set[str] = set()
    random_aliases: Set[str] = set()
    numpy_aliases: Set[str] = set()
    npr_aliases: Set[str] = set()
    bare_random_funcs: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    time_aliases.add(a.asname or "time")
                elif a.name == "random":
                    random_aliases.add(a.asname or "random")
                elif a.name == "numpy":
                    numpy_aliases.add(a.asname or "numpy")
                elif a.name == "numpy.random":
                    npr_aliases.add(a.asname or "numpy.random")
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                continue
            if node.module == "time":
                for a in node.names:
                    if a.name in _TIME_FUNCS:
                        bare_time_funcs.add(a.asname or a.name)
            elif node.module == "random":
                for a in node.names:
                    if a.name in _RANDOM_FUNCS:
                        bare_random_funcs.add(a.asname or a.name)
            elif node.module == "numpy":
                for a in node.names:
                    if a.name == "random":
                        npr_aliases.add(a.asname or "random")

    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        targets = _self_thread_targets(cls)
        if not targets:
            continue
        methods = _method_map(cls)
        init = methods.get("__init__")
        checks = []
        if init is None or not _init_injects_timing(init):
            checks.append((
                RULE,
                lambda m: _loop_first_call(
                    m, methods,
                    lambda c: _timing_call(c, time_aliases,
                                           bare_time_funcs)),
                "sleeps/reads the clock",
                "take the cadence (period_s=/poll_s=/…) or the "
                "clock/sleep callables as constructor parameters "
                "(the Sampler/Lease/CircuitBreaker pattern)"))
        if init is None or not _init_injects_rng(init):
            checks.append((
                RULE_RNG,
                lambda m: _loop_first_call(
                    m, methods,
                    lambda c: _rng_call(c, random_aliases, numpy_aliases,
                                        npr_aliases, bare_random_funcs)),
                "draws from the process-global rng",
                "take rng=random.Random()/a seed= as a constructor "
                "parameter and draw from it (the HARouter(jitter_seed)/"
                "ServingRouter(rng) pattern)"))
        for rule, finder, what, fix in checks:
            for mname in sorted(targets):
                m = methods.get(mname)
                if m is None:
                    continue
                hit = finder(m)
                if hit is None:
                    continue
                if rule in line_ignores(lines, cls.lineno):
                    break
                diags.append(Diagnostic(
                    rel, cls.lineno, rule,
                    f"`{cls.name}` runs a thread control loop "
                    f"(`{mname}` {what} at line {hit.lineno}) but "
                    f"__init__ exposes no injection point — "
                    f"deterministic tests are impossible; {fix}, or "
                    "justify with an ignore/allowlist entry"))
                break  # one diagnostic per class per rule
    return diags


def run(root: str, only=None) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for p in walk_py(root, ("paddle_tpu",), only=only):
        diags.extend(check_file(p, root))
    return sorted(diags, key=lambda d: (d.path, d.line, d.rule))


if __name__ == "__main__":
    from common import REPO_ROOT
    for d in run(REPO_ROOT):
        print(d)
