"""graftlint pass 6: control-loop timing injectability.

  uninjectable-clock  a class that runs its own CONTROL LOOP — it
                   constructs a ``threading.Thread`` whose ``target``
                   is one of its own methods — and whose loop reads
                   time (``time.sleep``/``time.monotonic``/
                   ``time.perf_counter``/``<event>.wait(...)``) while
                   its ``__init__`` exposes NO timing injection point.
                   Such a class can only be tested by real sleeping:
                   the test either races the loop (flaky under load —
                   the class of bug every "bump the sleep and rerun"
                   commit is apologizing for) or pays wall-clock per
                   case. Make the timing constructor-injectable —
                   either the cadence itself (``period_s=``,
                   ``poll_s=``, ``hb_interval=`` …) or the clock/sleep
                   callables (``clock=time.monotonic``,
                   ``sleep=time.sleep``) — the way Sampler(period_s),
                   Lease(interval), CircuitBreaker(clock) and
                   ReshardController(clock, sleep) already do.

An ``__init__`` parameter counts as a timing injection point when its
name is one of the CLOCK names (clock, sleep, sleep_fn, now, now_fn,
timer, tick) or contains one of the CADENCE fragments (interval,
period, poll, timeout, ttl, cooldown, grace, idle, lag, duck, hold,
delay, backoff, every, _s / _ms suffixes are NOT required — the
fragment match is substring, case-insensitive).

The loop-body scan covers the thread-target method plus one level of
``self._helper()`` calls (a ``_loop`` that delegates its waiting to
``_poll_once`` is still a control loop).

Scope: ``paddle_tpu/`` (library control loops; tools/ demo drivers die
with their process). Suppression: trailing
``# graftlint: ignore[uninjectable-clock]`` on the ``class`` line, or
an allow.txt entry with justification.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Set

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from common import (Diagnostic, dotted, line_ignores,  # noqa: E402
                    relpath, walk_py)

RULE = "uninjectable-clock"

_CLOCK_PARAM_NAMES = {"clock", "sleep", "sleep_fn", "sleep_s", "now",
                      "now_fn", "timer", "tick"}
_CADENCE_FRAGMENTS = ("interval", "period", "poll", "timeout", "ttl",
                      "cooldown", "grace", "idle", "lag", "duck", "hold",
                      "delay", "backoff", "every")

_TIME_FUNCS = {"sleep", "monotonic", "perf_counter", "time"}


def _init_injects_timing(init: ast.FunctionDef) -> bool:
    args = list(init.args.posonlyargs) + list(init.args.args) + \
        list(init.args.kwonlyargs)
    for a in args:
        name = a.arg.lower()
        if name in _CLOCK_PARAM_NAMES:
            return True
        if any(frag in name for frag in _CADENCE_FRAGMENTS):
            return True
    return False


def _self_thread_targets(cls: ast.ClassDef) -> Dict[str, ast.Call]:
    """Method names used as ``target=self.<m>`` in a Thread
    construction anywhere in the class (module-alias and from-import
    Thread forms are the caller's concern — we match on the keyword
    shape: any Call with a ``target=self.X`` keyword and a name ending
    in 'Thread')."""
    out: Dict[str, ast.Call] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        if not name.rsplit(".", 1)[-1].endswith("Thread"):
            continue
        for kw in node.keywords:
            if kw.arg == "target" and isinstance(kw.value, ast.Attribute) \
                    and isinstance(kw.value.value, ast.Name) \
                    and kw.value.value.id == "self":
                out[kw.value.attr] = node
    return out


def _timing_call(node: ast.Call, time_aliases: Set[str],
                 bare_time_funcs: Set[str]) -> bool:
    name = dotted(node.func)
    if name in bare_time_funcs:
        return True
    if name and "." in name:
        mod, _, attr = name.rpartition(".")
        if mod in time_aliases and attr in _TIME_FUNCS:
            return True
        # <event>.wait(x) — threading.Event/Condition waits ARE the
        # loop cadence; a bare .wait() (no deadline) is a pure signal
        if attr == "wait" and node.args:
            return True
    return False


def _method_map(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _loop_reads_time(target: ast.FunctionDef,
                     methods: Dict[str, ast.FunctionDef],
                     time_aliases: Set[str],
                     bare_time_funcs: Set[str]) -> Optional[ast.Call]:
    """The first timing call in the thread target or one level of its
    ``self._helper()`` callees."""
    scopes = [target]
    for node in ast.walk(target):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self" \
                and node.func.attr in methods:
            scopes.append(methods[node.func.attr])
    for scope in scopes:
        for node in ast.walk(scope):
            if isinstance(node, ast.Call) and _timing_call(
                    node, time_aliases, bare_time_funcs):
                return node
    return None


def check_file(path: str, root: str) -> List[Diagnostic]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    rel = relpath(path, root)
    lines = src.splitlines()
    diags: List[Diagnostic] = []

    time_aliases = {"time"}
    bare_time_funcs: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    time_aliases.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "time" and not node.level:
                for a in node.names:
                    if a.name in _TIME_FUNCS:
                        bare_time_funcs.add(a.asname or a.name)

    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        targets = _self_thread_targets(cls)
        if not targets:
            continue
        methods = _method_map(cls)
        init = methods.get("__init__")
        if init is not None and _init_injects_timing(init):
            continue
        for mname in sorted(targets):
            m = methods.get(mname)
            if m is None:
                continue
            hit = _loop_reads_time(m, methods, time_aliases,
                                   bare_time_funcs)
            if hit is None:
                continue
            if RULE in line_ignores(lines, cls.lineno):
                continue
            diags.append(Diagnostic(
                rel, cls.lineno, RULE,
                f"`{cls.name}` runs a thread control loop "
                f"(`{mname}` sleeps/reads the clock at line "
                f"{hit.lineno}) but __init__ exposes no timing "
                "injection point — deterministic tests are impossible; "
                "take the cadence (period_s=/poll_s=/…) or the "
                "clock/sleep callables as constructor parameters "
                "(the Sampler/Lease/CircuitBreaker pattern), or "
                "justify with an ignore/allowlist entry"))
            break  # one diagnostic per class
    return diags


def run(root: str, only=None) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for p in walk_py(root, ("paddle_tpu",), only=only):
        diags.extend(check_file(p, root))
    return sorted(diags, key=lambda d: (d.path, d.line, d.rule))


if __name__ == "__main__":
    from common import REPO_ROOT
    for d in run(REPO_ROOT):
        print(d)
