"""graftlint pass 2: static lock-order checking for csrc/*.cc.

Grammar (see docs/STATIC_ANALYSIS.md):

  // LOCK ORDER: a < b < c     declares a partial order over lock names
                               (anywhere in the file; decls merge)
  // LOCK LEAF: a b c          declares leaf locks: while one is held,
                               NO other lock may be acquired (decls
                               merge; a file may have several)
  // LOCK: name                trailing comment on an acquisition line,
                               naming the lock being acquired

Acquisitions are RAII guards (``std::lock_guard`` / ``unique_lock`` /
``shared_lock`` / ``scoped_lock``). A guard's scope is tracked by brace
depth: it is held until its enclosing block closes. When a guard is
acquired while another is held, that is NESTED locking and both locks
must be (a) named — via ``// LOCK:`` tag or an unambiguous default (the
final member segment of the mutex expression, ``t->save_mu`` →
``save_mu``) — and (b) ordered outer < inner by the declared partial
order. Rules:

  lock-order-cycle   the declared order itself has a cycle
  lock-unannotated   nested acquisition whose lock name is not in the
                     declared order (add a LOCK ORDER decl / LOCK tag)
  lock-order         nested acquisition that contradicts the declared
                     order (inner not reachable from outer)
  lock-leaf          acquisition while a declared LEAF lock is held —
                     leaf locks must be innermost by contract (this is
                     what lets hot paths skip hierarchy reasoning)

This is a textual single-translation-unit analysis: it sees lexical
nesting inside one function body, not inter-procedural chains — the
annotations plus the TSAN sweep cover the rest.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from common import Diagnostic, relpath  # noqa: E402

_ORDER_RE = re.compile(r"//\s*LOCK ORDER:\s*(.+)$")
_LEAF_RE = re.compile(r"//\s*LOCK LEAF:\s*(.+)$")
_TAG_RE = re.compile(r"//\s*LOCK:\s*(\w+)")
_GUARD_RE = re.compile(
    r"std::(lock_guard|unique_lock|shared_lock|scoped_lock)\s*"
    r"(?:<[^>]*>)?\s+\w+\s*[({]([^;{}]*)[)}]\s*;")


def _default_name(expr: str) -> str:
    """`t->shards[s]->mu` → `mu`; `*save_mu` → `save_mu`."""
    expr = expr.split(",")[0].strip().lstrip("*&")
    expr = re.sub(r"\[[^\]]*\]", "", expr)
    expr = re.sub(r"\([^)]*\)", "", expr)
    for sep in ("->", "."):
        expr = expr.split(sep)[-1] if sep in expr else expr
    return expr.strip()


def _parse_order(lines: List[str], path: str) -> Tuple[
        Dict[str, Set[str]], Set[str], List[Diagnostic]]:
    """(declared edges {a: {b,...}} meaning a < b, declared leaf locks,
    syntax diagnostics)."""
    edges: Dict[str, Set[str]] = {}
    leaves: Set[str] = set()
    diags: List[Diagnostic] = []
    for i, line in enumerate(lines, 1):
        lm = _LEAF_RE.search(line)
        if lm:
            names = lm.group(1).split()
            if not names or not all(re.fullmatch(r"\w+", n) for n in names):
                diags.append(Diagnostic(path, i, "lock-order-syntax",
                                        f"malformed LOCK LEAF decl: "
                                        f"{lm.group(1).strip()!r} "
                                        "(want `a [b ...]`)"))
                continue
            leaves.update(names)
            continue
        m = _ORDER_RE.search(line)
        if not m:
            continue
        names = [n.strip() for n in m.group(1).split("<")]
        if len(names) < 2 or not all(re.fullmatch(r"\w+", n) for n in names):
            diags.append(Diagnostic(path, i, "lock-order-syntax",
                                    f"malformed LOCK ORDER decl: "
                                    f"{m.group(1).strip()!r} "
                                    "(want `a < b [< c ...]`)"))
            continue
        for a, b in zip(names, names[1:]):
            edges.setdefault(a, set()).add(b)
            edges.setdefault(b, set())
    return edges, leaves, diags


def _find_cycle(edges: Dict[str, Set[str]]) -> List[str]:
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in edges}
    stack: List[str] = []

    def dfs(n: str):
        color[n] = GRAY
        stack.append(n)
        for m in sorted(edges.get(n, ())):
            if color[m] == GRAY:
                return stack[stack.index(m):] + [m]
            if color[m] == WHITE:
                cyc = dfs(m)
                if cyc:
                    return cyc
        color[n] = BLACK
        stack.pop()
        return None

    for n in sorted(edges):
        if color[n] == WHITE:
            cyc = dfs(n)
            if cyc:
                return cyc
    return []


def _reachable(edges: Dict[str, Set[str]], a: str, b: str) -> bool:
    seen, work = set(), [a]
    while work:
        n = work.pop()
        if n == b:
            return True
        if n in seen:
            continue
        seen.add(n)
        work.extend(edges.get(n, ()))
    return False


def _strip_comments_keep_lines(src: str) -> str:
    """Remove /*...*/ and //... and string/char literals, preserving
    newlines so line numbers survive."""
    out = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c == "/" and i + 1 < n and src[i + 1] == "/":
            j = src.find("\n", i)
            i = n if j < 0 else j
        elif c == "/" and i + 1 < n and src[i + 1] == "*":
            j = src.find("*/", i + 2)
            seg = src[i:(n if j < 0 else j + 2)]
            out.append("\n" * seg.count("\n"))
            i = n if j < 0 else j + 2
        elif c in "\"'":
            q, j = c, i + 1
            while j < n and src[j] != q:
                j += 2 if src[j] == "\\" else 1
            out.append(" ")
            i = min(j + 1, n)
        else:
            out.append(c)
            i += 1
    return "".join(out)


def check_file(path: str, root: str) -> List[Diagnostic]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    rel = relpath(path, root)
    raw_lines = src.splitlines()
    edges, leaves, diags = _parse_order(raw_lines, rel)

    for leaf in sorted(leaves):
        if edges.get(leaf):
            diags.append(Diagnostic(
                rel, 1, "lock-order-syntax",
                f"`{leaf}` declared LOCK LEAF but has successors in a "
                f"LOCK ORDER decl ({', '.join(sorted(edges[leaf]))}) — "
                "a leaf lock is innermost by definition"))

    cyc = _find_cycle(edges)
    if cyc:
        diags.append(Diagnostic(rel, 1, "lock-order-cycle",
                                "declared LOCK ORDER has a cycle: "
                                + " < ".join(cyc)))
        return diags

    code = _strip_comments_keep_lines(src)
    # events (offset-ordered): every guard acquisition and every brace,
    # so guard scopes follow real lexical block structure
    acquisitions = []  # (offset, lineno, kind, mutex_exprs, tag_name)
    line_starts = [0]
    for i, c in enumerate(code):
        if c == "\n":
            line_starts.append(i + 1)

    def line_of(off: int) -> int:
        lo, hi = 0, len(line_starts) - 1
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if line_starts[mid] <= off:
                lo = mid
            else:
                hi = mid - 1
        return lo + 1

    for m in _GUARD_RE.finditer(code):
        lineno = line_of(m.start())
        raw = raw_lines[lineno - 1] if lineno <= len(raw_lines) else ""
        tag = _TAG_RE.search(raw)
        acquisitions.append((m.start(), lineno, m.group(1),
                             m.group(2).split(","),
                             tag.group(1) if tag else None))

    depth = 0
    held: List[Tuple[str, int, int]] = []  # (name, depth_at_acq, line)
    ai = 0
    for off, c in enumerate(code):
        while ai < len(acquisitions) and acquisitions[ai][0] == off:
            _, lineno, kind, exprs, tag_name = acquisitions[ai]
            ai += 1
            # scoped_lock(a, b, ...) locks all deadlock-free; others take
            # the mutex as first arg (later args are lock-policy tags)
            mutexes = exprs if kind == "scoped_lock" else exprs[:1]
            for k, me in enumerate(mutexes):
                me = me.strip()
                if not me or me in ("std::defer_lock", "std::adopt_lock",
                                    "std::try_to_lock"):
                    continue
                name = tag_name if (tag_name and k == 0) else _default_name(me)
                atomic_peer = kind == "scoped_lock" and k > 0
                for hname, _, hline in held:
                    if atomic_peer:
                        continue
                    if hname in leaves:
                        diags.append(Diagnostic(
                            rel, lineno, "lock-leaf",
                            f"acquires `{name}` while leaf lock "
                            f"`{hname}` is held (line {hline}) — LOCK "
                            f"LEAF locks must be innermost"))
                    elif name in leaves:
                        # a leaf nests under ANY outer lock by contract;
                        # no ORDER decl is required for it
                        continue
                    elif hname not in edges or name not in edges:
                        missing = name if name not in edges else hname
                        diags.append(Diagnostic(
                            rel, lineno, "lock-unannotated",
                            f"nested acquisition of `{name}` while "
                            f"`{hname}` held (line {hline}) but "
                            f"`{missing}` is not in any LOCK ORDER decl"))
                    elif not _reachable(edges, hname, name):
                        diags.append(Diagnostic(
                            rel, lineno, "lock-order",
                            f"acquires `{name}` while holding `{hname}` "
                            f"(line {hline}) — declared order does not "
                            f"allow {hname} < {name}"))
                held.append((name, depth, lineno))
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            held = [h for h in held if h[1] <= depth]
    return diags


def run(root: str, subdir: str = "paddle_tpu/csrc",
        only=None) -> List[Diagnostic]:
    base = os.path.join(root, subdir)
    diags: List[Diagnostic] = []
    if not os.path.isdir(base):
        return diags
    for fn in sorted(os.listdir(base)):
        if fn.endswith((".cc", ".h")):
            p = os.path.join(base, fn)
            if only is not None and relpath(p, root) not in only:
                continue
            diags.extend(check_file(p, root))
    return sorted(diags, key=lambda d: (d.path, d.line, d.rule))


if __name__ == "__main__":
    from common import REPO_ROOT
    for d in run(REPO_ROOT):
        print(d)
