"""graftlint pass 7: Python lock discipline for the threading modules.

The csrc side has had a static lock checker since PR 1 (lock_order.py);
the Python side of the same system — ha/rpc/reshard/autoscale/
communicator/hot_tier, job_checkpoint, slo/flightrec/timeseries,
serving/frontend, elastic — grew the SAME bug classes PR after PR and
relied on human review to catch them: callbacks invoked under a lock,
blocking RPC/socket/queue ops under a hot-path mutex, and lock-order
inversions between sibling mutexes. This pass ports the csrc grammar to
Python comments and adds the two Python-specific rules.

Grammar (docs/STATIC_ANALYSIS.md):

  # LOCK ORDER: a < b < c    partial order over lock names (anywhere in
                             the file; decls merge)
  # LOCK LEAF: a b           leaf locks: while one is held NO other
                             lock may be acquired (and nothing may
                             block under them by convention — the
                             blocking rules apply everywhere)
  # LOCK: name               trailing comment on an acquisition line,
                             naming the lock (default: the final
                             attribute segment, ``self._mu`` → ``_mu``)
  # graftlint: lock-ok <reason>
                             trailing escape for callback-under-lock /
                             blocking-under-lock on that line; the
                             reason is REQUIRED (empty → lock-ok-syntax)

Lock-scope regions come from the AST: ``with self._mu:`` bodies (for
attributes assigned ``threading.Lock/RLock/Condition`` anywhere in the
class, module-level lock variables, or any ``with`` target whose final
segment LOOKS like a lock: ``*_mu``/``*_lock``/``*_cv``/…), plus
``x.acquire()`` … ``x.release()`` pairs tracked in statement order.
Nested ``def``/``lambda`` bodies do not execute under the lock and are
skipped.

Rules:

  lock-order-cycle     the declared order itself has a cycle
  lock-order-syntax    malformed decl / leaf with declared successors
  lock-unannotated     nested acquisition whose lock name is not in the
                       declared order
  lock-order           nested acquisition contradicting the order
  lock-leaf            acquiring anything while a declared LEAF lock is
                       held
  callback-under-lock  calling a caller-supplied or subscribed callable
                       inside a lock region: a function parameter, an
                       ``on_*``/``notify*``/``*callback*``/``*_cb``/
                       ``*hook*`` name, or a variable bound by
                       iterating a subscriber-ish collection
                       (``for fn in self._on_fire: fn(...)``). The
                       callee can take arbitrary locks or block — the
                       CircuitBreaker/SloWatchdog contract is notify
                       AFTER release. ``cond.notify{,_all}()`` on a
                       tracked lock/condition is exempt (that is the
                       condition-variable protocol, not a callback).
  cond-wait-no-predicate
                       `cv.wait()` on a Condition outside a `while`
                       loop: a condition wake is a HINT, not a
                       guarantee — spurious wakeups, stolen wakeups
                       (another waiter consumed the state first) and
                       timeouts all return with the predicate false,
                       so the wait must live in
                       `while not pred: cv.wait()`. The scheduler
                       explorer (tools/sched) detects the RESULTING
                       lost wakeups dynamically; this rule catches the
                       shape statically.
  blocking-under-lock  a blocking operation inside a lock region:
                       ``time.sleep``, socket IO, thread/queue
                       ``join``, ``<q>.put`` on a BOUNDED queue /
                       ``<q>.get`` (the nowait forms are fine),
                       ``<event>.wait``, future ``.result``, and the
                       PS RPC surface (``conn.call/check``,
                       ``make_conn``, ``send_replicate``, client
                       pull/push ops). ``cv.wait()`` under its OWN
                       region is the condition protocol and exempt.

Like the csrc pass this is lexical and per-function: it sees nesting
and calls inside one body, not interprocedural chains — the annotations
plus the TSAN sweep cover the rest.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from common import Diagnostic, dotted, line_ignores, relpath, walk_py  # noqa: E402
from lock_order import _find_cycle, _reachable  # noqa: E402

_ORDER_RE = re.compile(r"#\s*LOCK ORDER:\s*(.+)$")
_LEAF_RE = re.compile(r"#\s*LOCK LEAF:\s*(.+)$")
_TAG_RE = re.compile(r"#\s*LOCK:\s*(\w+)")
_LOCK_OK_RE = re.compile(r"#\s*graftlint:\s*lock-ok\b[:\s]*(.*)$")

# a `with X:` whose final segment matches this is a lock region even
# when the assignment site is in another class/module (cross-object
# locks like `self.cluster.control_mu`)
_LOCKISH_NAME_RE = re.compile(r"(^|_)(mu|mutex|lock|cv|cond)$")

# callee names that denote caller-supplied / subscribed callables
_CALLBACK_NAME_RE = re.compile(
    r"^_?(on_[a-z0-9_]+|notify(_[a-z0-9_]+)?|[a-z0-9_]*callback[a-z0-9_]*"
    r"|[a-z0-9_]+_cb|[a-z0-9_]*hook[a-z0-9_]*)$")

# attribute names that hold subscriber/listener collections: calling a
# loop variable bound from one of these is a callback invocation
_SUBSCRIBER_ATTR_RE = re.compile(
    r"^_?(subs|subscribers|listeners|callbacks|watchers|observers|hooks"
    r"|on_[a-z0-9_]+)$")

# blocking method names on arbitrary receivers (socket IO + the PS RPC
# client surface — `conn.call(...)` / `c.check(...)` IS a TCP roundtrip)
_BLOCKING_METHODS = {
    "recv": "socket recv", "recv_into": "socket recv",
    "sendall": "socket send", "connect": "socket connect",
    "accept": "socket accept", "readline": "socket read",
    "failover": "routing-store poll",
    "call": "PS RPC", "check": "PS RPC",
    "send_replicate": "replication RPC",
    "drain_remote": "replication RPC",
    "pull_sparse": "PS RPC", "push_sparse": "PS RPC",
    "pull_dense": "PS RPC", "push_dense": "PS RPC",
    "insert_full": "PS RPC", "export_full": "PS RPC",
    "snapshot_items": "PS RPC", "global_step": "PS RPC",
    "barrier": "PS barrier",
    "result": "future result",
}

# module-level blocking callables (resolved through import aliases)
_BLOCKING_FUNCS = {
    "time.sleep": "sleep",
    "socket.create_connection": "socket connect",
    "socket.getaddrinfo": "DNS resolution",
}
_LOCAL_BLOCKING_FUNCS = {"make_conn": "TCP connect",
                         "_ServerConn": "TCP connect"}

# the core.sync shim factories construct the same objects (or their
# schedulable doubles under tools/sched) — lock regions and queue
# boundedness carry over verbatim
_THREADING_LOCKS = {"threading.Lock", "threading.RLock",
                    "threading.Condition",
                    "core.sync.Lock", "core.sync.RLock",
                    "core.sync.Condition"}
_QUEUE_CLASSES = {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
                  "core.sync.Queue"}


def _parse_decls(lines: List[str], path: str) -> Tuple[
        Dict[str, Set[str]], Set[str], List[Diagnostic]]:
    """Same semantics as lock_order._parse_order, '#' comment grammar."""
    edges: Dict[str, Set[str]] = {}
    leaves: Set[str] = set()
    diags: List[Diagnostic] = []
    for i, line in enumerate(lines, 1):
        lm = _LEAF_RE.search(line)
        if lm:
            names = lm.group(1).split()
            if not names or not all(re.fullmatch(r"\w+", n) for n in names):
                diags.append(Diagnostic(path, i, "lock-order-syntax",
                                        f"malformed LOCK LEAF decl: "
                                        f"{lm.group(1).strip()!r} "
                                        "(want `a [b ...]`)"))
                continue
            leaves.update(names)
            continue
        m = _ORDER_RE.search(line)
        if not m:
            continue
        names = [n.strip() for n in m.group(1).split("<")]
        if len(names) < 2 or not all(re.fullmatch(r"\w+", n) for n in names):
            diags.append(Diagnostic(path, i, "lock-order-syntax",
                                    f"malformed LOCK ORDER decl: "
                                    f"{m.group(1).strip()!r} "
                                    "(want `a < b [< c ...]`)"))
            continue
        for a, b in zip(names, names[1:]):
            edges.setdefault(a, set()).add(b)
            edges.setdefault(b, set())
    return edges, leaves, diags


class _Aliases:
    """Resolve dotted callee names through the module's imports:
    `th.Lock` → `threading.Lock`, `sleep` (from time import sleep) →
    `time.sleep`."""

    def __init__(self, tree: ast.Module) -> None:
        self.mod: Dict[str, str] = {}    # local name -> module path
        self.sym: Dict[str, str] = {}    # local name -> module.symbol
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.mod[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                if node.module and node.level == 0:
                    for a in node.names:
                        self.sym[a.asname or a.name] = \
                            f"{node.module}.{a.name}"
                # the sync shim is imported RELATIVELY in production
                # modules (`from ..core import sync as _sync`) — level-N
                # ImportFrom of `sync` out of a `core` package resolves
                # to the canonical `core.sync` module name so its
                # factories classify like the stdlib constructors
                for a in node.names:
                    if a.name == "sync" and \
                            (node.module or "").split(".")[-1] == "core":
                        self.mod[a.asname or a.name] = "core.sync"

    def resolve(self, name: Optional[str]) -> Optional[str]:
        if not name:
            return None
        head, _, rest = name.partition(".")
        if rest and head in self.mod:
            return f"{self.mod[head]}.{rest}"
        if not rest and name in self.sym:
            return self.sym[name]
        return name


@dataclass
class _Held:
    name: str
    line: int
    obj: Optional[str]  # final attr segment of the lock expr, for exemptions


@dataclass
class _FileCtx:
    rel: str
    lines: List[str]
    aliases: _Aliases
    edges: Dict[str, Set[str]]
    leaves: Set[str]
    locks_mod: Set[str] = field(default_factory=set)       # module-level names
    locks_attr: Set[str] = field(default_factory=set)      # self.X across file
    cond_bound: Dict[str, str] = field(default_factory=dict)  # cv -> its lock
    queues_bounded: Set[str] = field(default_factory=set)  # attr/var names
    queues_all: Set[str] = field(default_factory=set)
    diags: List[Diagnostic] = field(default_factory=list)


def _final_segment(node: ast.AST) -> Optional[str]:
    d = dotted(node)
    return d.rsplit(".", 1)[-1] if d else None


def _collect_locks(tree: ast.Module, ctx: _FileCtx) -> None:
    """Find lock/queue objects by their construction sites."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        if not isinstance(node.value, ast.Call):
            continue
        callee = ctx.aliases.resolve(dotted(node.value.func))
        for tgt in targets:
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self":
                name, is_attr = tgt.attr, True
            elif isinstance(tgt, ast.Name):
                name, is_attr = tgt.id, False
            else:
                continue
            if callee in _THREADING_LOCKS:
                (ctx.locks_attr if is_attr else ctx.locks_mod).add(name)
                if callee.endswith(".Condition"):
                    # Condition(lock) waits/notifies on THAT lock; a
                    # bare Condition() owns its own
                    bound = (_final_segment(node.value.args[0])
                             if node.value.args else None)
                    ctx.cond_bound[name] = bound or name
            elif callee in _QUEUE_CLASSES:
                ctx.queues_all.add(name)
                if _queue_is_bounded(node.value):
                    ctx.queues_bounded.add(name)


def _queue_is_bounded(call: ast.Call) -> bool:
    """Queue(maxsize=N): bounded unless maxsize is literally <= 0 or
    absent. A non-literal maxsize is assumed bounded (that is the point
    of passing one)."""
    arg = None
    if call.args:
        arg = call.args[0]
    for kw in call.keywords:
        if kw.arg == "maxsize":
            arg = kw.value
    if arg is None:
        return False
    if isinstance(arg, ast.Constant) and isinstance(arg.value, (int, float)):
        return arg.value > 0
    return True


def _lock_name_of_with_item(item: ast.withitem, ctx: _FileCtx
                            ) -> Optional[Tuple[str, str]]:
    """(lock name, final attr segment) when the context expr is a lock."""
    expr = item.context_expr
    seg = _final_segment(expr)
    if seg is None:
        return None
    if isinstance(expr, ast.Name):
        if seg in ctx.locks_mod or _LOCKISH_NAME_RE.search(seg):
            return seg, seg
        return None
    if isinstance(expr, ast.Attribute):
        if seg in ctx.locks_attr or seg in ctx.locks_mod or \
                _LOCKISH_NAME_RE.search(seg):
            return seg, seg
    return None


#: the ONLY rules `# graftlint: lock-ok` may waive — ordering/leaf
#: violations have no justified form and need the audited allowlist
_LOCK_OK_RULES = {"callback-under-lock", "blocking-under-lock"}


def _suppressed(ctx: _FileCtx, line: int, rule: str, end_line: int) -> bool:
    """An ignore[] / lock-ok escape anywhere on the statement's lines
    (a call can span several) suppresses the diagnostic; lock-ok only
    waives the callback/blocking rules."""
    for ln in range(line, min(end_line, line + 8) + 1):
        if rule in line_ignores(ctx.lines, ln):
            return True
        if rule not in _LOCK_OK_RULES:
            continue
        if 1 <= ln <= len(ctx.lines):
            m = _LOCK_OK_RE.search(ctx.lines[ln - 1])
            if m:
                if m.group(1).strip():
                    return True
                ctx.diags.append(Diagnostic(
                    ctx.rel, ln, "lock-ok-syntax",
                    "`# graftlint: lock-ok` needs a reason (`# graftlint: "
                    "lock-ok <why this cannot block/deadlock>`)"))
                return True  # malformed escape reported; don't double up
    return False


def _emit(ctx: _FileCtx, line: int, rule: str, msg: str,
          end_line: Optional[int] = None) -> None:
    if not _suppressed(ctx, line, rule, end_line or line):
        ctx.diags.append(Diagnostic(ctx.rel, line, rule, msg))


class _FunctionScan:
    """One function body: track held locks in statement order, check
    nesting against the declared order, and classify calls made while
    any lock is held."""

    def __init__(self, func: ast.AST, ctx: _FileCtx) -> None:
        self.ctx = ctx
        self.func = func
        self.params: Set[str] = set()
        args = getattr(func, "args", None)
        if args is not None:
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                if a.arg != "self":
                    self.params.add(a.arg)
        self.subscriber_vars: Set[str] = set()
        self.held: List[_Held] = []

    # -- region bookkeeping -------------------------------------------------

    def _tag_or(self, line: int, default: str) -> str:
        if 1 <= line <= len(self.ctx.lines):
            m = _TAG_RE.search(self.ctx.lines[line - 1])
            if m:
                return m.group(1)
        return default

    def _push(self, name: str, line: int, obj: Optional[str]) -> None:
        ctx = self.ctx
        for h in self.held:
            if h.name == name:      # RLock reentry / same lock: not nesting
                continue
            if h.name in ctx.leaves:
                _emit(ctx, line, "lock-leaf",
                      f"acquires `{name}` while leaf lock `{h.name}` is "
                      f"held (line {h.line}) — LOCK LEAF locks must be "
                      "innermost")
            elif name in ctx.leaves:
                continue            # a leaf nests under anything by contract
            elif h.name not in ctx.edges or name not in ctx.edges:
                missing = name if name not in ctx.edges else h.name
                _emit(ctx, line, "lock-unannotated",
                      f"nested acquisition of `{name}` while `{h.name}` "
                      f"held (line {h.line}) but `{missing}` is not in any "
                      "LOCK ORDER decl")
            elif not _reachable(ctx.edges, h.name, name):
                _emit(ctx, line, "lock-order",
                      f"acquires `{name}` while holding `{h.name}` (line "
                      f"{h.line}) — declared order does not allow "
                      f"{h.name} < {name}")
        self.held.append(_Held(name, line, obj))

    def _pop(self, name: str) -> None:
        for i in range(len(self.held) - 1, -1, -1):
            if self.held[i].name == name:
                del self.held[i]
                return

    # -- statement walk -----------------------------------------------------

    def scan(self) -> None:
        self._scan_body(list(getattr(self.func, "body", [])))

    def _scan_body(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._scan_stmt(stmt)

    def _acquire_release(self, stmt: ast.stmt) -> Optional[Tuple[str, str,
                                                                 ast.Call]]:
        """('acquire'|'release', lock name, call) for `x.acquire()` /
        `x.release()` expression statements."""
        if not (isinstance(stmt, ast.Expr) and
                isinstance(stmt.value, ast.Call) and
                isinstance(stmt.value.func, ast.Attribute)):
            return None
        meth = stmt.value.func.attr
        if meth not in ("acquire", "release"):
            return None
        obj = _final_segment(stmt.value.func.value)
        if obj is None:
            return None
        return meth, obj, stmt.value

    def _scan_stmt(self, stmt: ast.stmt) -> None:
        ctx = self.ctx
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # a nested def does not run under the lock
        ar = self._acquire_release(stmt)
        if ar is not None:
            meth, obj, call = ar
            name = self._tag_or(stmt.lineno, obj)
            if meth == "acquire":
                if self.held:
                    self._check_calls_outside_regions(stmt)
                self._push(name, stmt.lineno, obj)
            else:
                self._pop(name)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = []
            for item in stmt.items:
                got = _lock_name_of_with_item(item, ctx)
                if got is None:
                    if self.held:
                        self._check_expr(item.context_expr)
                    continue
                seg, obj = got
                name = self._tag_or(stmt.lineno, seg)
                self._push(name, stmt.lineno, obj)
                pushed.append(name)
            self._scan_body(stmt.body)
            for name in reversed(pushed):
                self._pop(name)
            return
        if isinstance(stmt, ast.For):
            self._note_subscriber_iter(stmt)
            if self.held:
                self._check_expr(stmt.iter)
            self._scan_body(stmt.body)
            self._scan_body(stmt.orelse)
            return
        if isinstance(stmt, ast.While):
            if self.held:
                self._check_expr(stmt.test)
            self._scan_body(stmt.body)
            self._scan_body(stmt.orelse)
            return
        if isinstance(stmt, ast.If):
            if self.held:
                self._check_expr(stmt.test)
            self._scan_body(stmt.body)
            self._scan_body(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self._scan_body(stmt.body)
            for h in stmt.handlers:
                self._scan_body(h.body)
            self._scan_body(stmt.orelse)
            self._scan_body(stmt.finalbody)
            return
        # leaf statement: check every call in it when a lock is held
        self._note_subscriber_assign(stmt)
        if self.held:
            self._check_calls_outside_regions(stmt)

    def _check_calls_outside_regions(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            self._check_expr(child)

    # -- subscriber-variable tracking ---------------------------------------

    def _unwrap_iterable(self, node: ast.AST) -> Optional[str]:
        """Final attr segment of the underlying collection:
        `list(self._subs)`, `self._subs.copy()`, `self._subs[:]` →
        `_subs`."""
        while True:
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name) and f.id in ("list", "tuple",
                                                        "sorted", "reversed",
                                                        "iter", "enumerate") \
                        and node.args:
                    node = node.args[0]
                    continue
                if isinstance(f, ast.Attribute) and f.attr in ("copy",
                                                               "values",
                                                               "items"):
                    node = f.value
                    continue
                return None
            if isinstance(node, ast.Subscript):
                node = node.value
                continue
            return _final_segment(node)

    def _note_subscriber_iter(self, stmt: ast.For) -> None:
        seg = self._unwrap_iterable(stmt.iter)
        if seg and _SUBSCRIBER_ATTR_RE.match(seg):
            for t in ast.walk(stmt.target):
                if isinstance(t, ast.Name):
                    self.subscriber_vars.add(t.id)

    def _note_subscriber_assign(self, stmt: ast.stmt) -> None:
        if not isinstance(stmt, ast.Assign):
            return
        seg = self._unwrap_iterable(stmt.value)
        if seg and _SUBSCRIBER_ATTR_RE.match(seg):
            for tgt in stmt.targets:
                for t in ast.walk(tgt):
                    if isinstance(t, ast.Name):
                        self.subscriber_vars.add(t.id)

    # -- call classification --------------------------------------------------

    def _check_expr(self, node: ast.AST) -> None:
        # manual walk so deferred bodies (lambda / nested def) are
        # truly skipped — ast.walk would descend into their children
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                self._check_call(n)
            stack.extend(ast.iter_child_nodes(n))

    def _innermost(self) -> _Held:
        return self.held[-1]

    def _cv_protocol_ok(self, recv: ast.AST) -> bool:
        """True when `recv.wait()/notify*()` is the condition-variable
        protocol on the lock currently held: the receiver — or the lock
        its Condition was constructed over — is the INNERMOST held
        lock. Waiting on a condition bound to some OTHER mutex does not
        release the held one; it parks it for the whole wait."""
        seg = _final_segment(recv)
        if seg is None or not self.held:
            return False
        h = self.held[-1]
        names = {h.name, h.obj}
        if seg in names:
            return True
        bound = self.ctx.cond_bound.get(seg)
        return bound is not None and bound in names

    def _check_call(self, call: ast.Call) -> None:
        ctx = self.ctx
        line = call.lineno
        end = getattr(call, "end_lineno", None) or line

        def emit(rule: str, msg: str) -> None:
            _emit(ctx, line, rule, msg, end)

        lock = self._innermost().name
        f = call.func

        # callback-under-lock ------------------------------------------------
        if isinstance(f, ast.Name):
            if f.id in self.params:
                emit("callback-under-lock",
                      f"calls caller-supplied callable `{f.id}` while "
                      f"holding `{lock}` — invoke callbacks after release "
                      "(the subscriber can take arbitrary locks or block)")
                return
            if f.id in self.subscriber_vars:
                emit("callback-under-lock",
                      f"invokes subscribed callable `{f.id}` while holding "
                      f"`{lock}` — snapshot the subscriber list under the "
                      "lock, notify after release")
                return
        seg = _final_segment(f) if isinstance(f, (ast.Name, ast.Attribute)) \
            else None
        if seg and _CALLBACK_NAME_RE.match(seg):
            recv = f.value if isinstance(f, ast.Attribute) else None
            if not (recv is not None and
                    self._cv_protocol_ok(recv)):
                emit("callback-under-lock",
                      f"calls `{seg}` while holding `{lock}` — "
                      "notify/callback invocations must happen outside "
                      "lock regions (flight-recorder/SLO-subscriber "
                      "contract)")
                return

        # blocking-under-lock ------------------------------------------------
        resolved = ctx.aliases.resolve(dotted(f))
        if resolved in _BLOCKING_FUNCS:
            emit("blocking-under-lock",
                  f"{_BLOCKING_FUNCS[resolved]} (`{resolved}`) while "
                  f"holding `{lock}` — every waiter on the lock now waits "
                  "on the IO too")
            return
        if isinstance(f, ast.Name) and f.id in _LOCAL_BLOCKING_FUNCS:
            emit("blocking-under-lock",
                  f"{_LOCAL_BLOCKING_FUNCS[f.id]} (`{f.id}`) while holding "
                  f"`{lock}` — build connections outside the lock, swap "
                  "the reference under it")
            return
        if not isinstance(f, ast.Attribute):
            return
        meth = f.attr
        recv_seg = _final_segment(f.value)
        if meth == "wait":
            if not self._cv_protocol_ok(f.value):
                emit("blocking-under-lock",
                      f"`.wait()` on `{recv_seg or '?'}` while holding "
                      f"`{lock}` — only a Condition may wait under its own "
                      "lock (it releases it); anything else parks the lock")
            return
        if meth == "join":
            if self._join_is_blocking(call, recv_seg):
                emit("blocking-under-lock",
                      f"`.join()` on `{recv_seg or '?'}` while holding "
                      f"`{lock}` — joining a thread/queue under a lock the "
                      "joined work may need is the canonical deadlock")
            return
        if meth in ("put", "get"):
            if recv_seg in ctx.queues_all:
                nowait = any(kw.arg == "block" and
                             isinstance(kw.value, ast.Constant) and
                             kw.value.value is False
                             for kw in call.keywords)
                bounded = recv_seg in ctx.queues_bounded
                if not nowait and (meth == "get" or bounded):
                    emit("blocking-under-lock",
                          f"blocking `.{meth}()` on "
                          f"{'bounded ' if bounded else ''}queue "
                          f"`{recv_seg}` while holding `{lock}` — a full/"
                          "empty queue parks every thread that needs the "
                          "lock (use the _nowait form, or move the "
                          "blocking op outside the region)")
            return
        if meth in _BLOCKING_METHODS:
            emit("blocking-under-lock",
                  f"{_BLOCKING_METHODS[meth]} (`.{meth}()`) while holding "
                  f"`{lock}` — blocking IO under a mutex serializes the "
                  "whole plane behind one wire round-trip")

    @staticmethod
    def _join_is_blocking(call: ast.Call, recv_seg: Optional[str]) -> bool:
        # `" ".join(parts)` / `os.path.join(a, b)` are string/path joins
        if recv_seg == "path":
            return False
        if isinstance(call.func, ast.Attribute) and \
                isinstance(call.func.value, ast.Constant):
            return False
        if len(call.args) > 1:
            return False
        if call.args and not (isinstance(call.args[0], ast.Constant) and
                              isinstance(call.args[0].value, (int, float))):
            return False
        return True


def _check_cond_waits(tree: ast.Module, ctx: _FileCtx) -> None:
    """cond-wait-no-predicate: every `.wait()` on a tracked Condition
    must be lexically inside a `while` (test or body) — the re-checked
    predicate is what makes the CV protocol correct under spurious and
    stolen wakeups. A nested def resets the loop context: its body does
    not inherit the enclosing loop's guard."""

    def walk(node: ast.AST, in_while: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                walk(child, False)
                continue
            if isinstance(child, ast.Call) and \
                    isinstance(child.func, ast.Attribute) and \
                    child.func.attr == "wait" and not in_while:
                seg = _final_segment(child.func.value)
                if seg in ctx.cond_bound:
                    _emit(ctx, child.lineno, "cond-wait-no-predicate",
                          f"`{seg}.wait()` outside a while-predicate "
                          "loop — a Condition wake is a hint, not a "
                          "guarantee (spurious/stolen wakeups, "
                          "timeouts): use `while not pred: "
                          f"{seg}.wait()`",
                          getattr(child, "end_lineno", None))
            walk(child, in_while or isinstance(child, ast.While))

    walk(tree, False)


def check_file(path: str, root: str) -> List[Diagnostic]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    rel = relpath(path, root)
    lines = src.splitlines()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Diagnostic(rel, e.lineno or 1, "lock-order-syntax",
                           f"unparsable: {e.msg}")]
    edges, leaves, diags = _parse_decls(lines, rel)
    for leaf in sorted(leaves):
        if edges.get(leaf):
            diags.append(Diagnostic(
                rel, 1, "lock-order-syntax",
                f"`{leaf}` declared LOCK LEAF but has successors in a "
                f"LOCK ORDER decl ({', '.join(sorted(edges[leaf]))}) — "
                "a leaf lock is innermost by definition"))
    cyc = _find_cycle(edges)
    if cyc:
        diags.append(Diagnostic(rel, 1, "lock-order-cycle",
                                "declared LOCK ORDER has a cycle: "
                                + " < ".join(cyc)))
        return diags

    ctx = _FileCtx(rel=rel, lines=lines, aliases=_Aliases(tree),
                   edges=edges, leaves=leaves, diags=diags)
    _collect_locks(tree, ctx)
    if not (ctx.locks_attr or ctx.locks_mod or
            "# LOCK" in src or ".acquire()" in src):
        return diags
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _FunctionScan(node, ctx).scan()
    _check_cond_waits(tree, ctx)
    return diags


def run(root: str, subdirs=("paddle_tpu",), files=(),
        only: Optional[Set[str]] = None) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for p in walk_py(root, subdirs, files, only=only):
        diags.extend(check_file(p, root))
    return sorted(diags, key=lambda d: (d.path, d.line, d.rule))


if __name__ == "__main__":
    from common import REPO_ROOT
    for d in run(REPO_ROOT):
        print(d)
