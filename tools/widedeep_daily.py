"""Config-ladder rung 4 (BASELINE.md): Wide&Deep CTR over the tiered
sparse stack, run as the production DAILY loop — cold SSD population,
per-day pass training with overlapped next-day builds, evaluation,
base/delta saves, shrink, spill. Emits one JSON line (WIDEDEEP.json).

Env knobs: WD_POP (cold population), WD_DAYS, WD_RECORDS (per day),
WD_HOT (spill budget), WD_DIR.
"""

import json
import os
import shutil
import sys
import tempfile
import time


def main() -> None:
    import jax

    if os.environ.get("WD_CPU", "1") == "1":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.table import SsdSparseTable, TableConfig

    pop = int(os.environ.get("WD_POP", 5_000_000))
    n_days = int(os.environ.get("WD_DAYS", 3))
    n_records = int(os.environ.get("WD_RECORDS", 50_000))
    hot_budget = int(os.environ.get("WD_HOT", 500_000))
    base = os.environ.get("WD_DIR") or tempfile.mkdtemp(prefix="wd_daily_")
    cleanup = "WD_DIR" not in os.environ

    S, D, dim = 8, 4, 8
    pt.seed(0)
    acc = AccessorConfig(embedx_dim=dim, embedx_threshold=0.0)
    table = SsdSparseTable(os.path.join(base, "tbl"),
                           TableConfig(shard_num=16, accessor_config=acc))
    try:
        out = _run(table, pop, n_days, n_records, hot_budget, base,
                   S, D, dim)
        print(json.dumps(out))
    finally:
        table.close()
        if cleanup:
            shutil.rmtree(base, ignore_errors=True)


def _day_lines(rng, n, S, D, hot_pool):
    """Learnable synthetic CTR day: ids drawn from a hot pool (repeats)
    with clicky-id + dense signal."""
    lines = []
    ids = rng.choice(hot_pool, size=(n, S))
    dense = rng.normal(size=(n, D))
    label = ((ids % 7 == 0).sum(axis=1) + dense[:, 0]
             + rng.normal(scale=0.5, size=n) > 1.0).astype(int)
    for i in range(n):
        parts = [f"1 {v}" for v in ids[i]]
        parts += [f"1 {v:.4f}" for v in dense[i]]
        parts.append(f"1 {label[i]}")
        lines.append(" ".join(parts))
    return lines


def _run(table, pop, n_days, n_records, hot_budget, base, S, D, dim):
    import numpy as np

    from paddle_tpu import optimizer
    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc
    from paddle_tpu.models.ctr import CtrConfig, WideDeep
    from paddle_tpu.ps.embedding_cache import CacheConfig
    from paddle_tpu.ps.ps_trainer import CtrPassTrainer

    # cold population on disk (bulk load at scale)
    t0 = time.perf_counter()
    chunk = 1_000_000
    fd = table.full_dim
    for lo in range(0, pop, chunk):
        n = min(chunk, pop - lo)
        keys = np.arange(lo + 1, lo + 1 + n, dtype=np.uint64)
        vals = np.zeros((n, fd), np.float32)
        # previously-seen features: show high enough that the daily
        # shrink's decay doesn't immediately cross delete_threshold
        vals[:, 3] = 10.0
        table.load_cold(keys, vals)
    load_s = time.perf_counter() - t0

    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1) for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1) for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])
    # the hot pool of ids days draw from — a slice of the population
    hot_pool = np.arange(1, 20_000, dtype=np.uint64)

    def make_day(day):
        day_rng = np.random.default_rng(1000 + day)
        ds = InMemoryDataset(slots, seed=day)
        ds.load_from_lines(_day_lines(day_rng, n_records, S, D, hot_pool))
        ds.local_shuffle()
        return ds

    trainer = CtrPassTrainer(
        WideDeep(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=dim,
                           dnn_hidden=(128, 128))),
        optimizer.Adam(1e-3), table,
        CacheConfig(capacity=1 << 18, embedx_dim=dim, embedx_threshold=0.0),
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label",
        slab=int(os.environ.get("WD_SLAB", "1")),
        amp=os.environ.get("WD_AMP", "0") == "1")

    days = [make_day(d) for d in range(n_days)]
    t0 = time.perf_counter()
    # overlapped pass builds (pre_build_thread pattern)
    results = trainer.train_passes(days, batch_size=512, drop_last=False)
    train_s = time.perf_counter() - t0

    # NB: evaluation runs AFTER all passes — the auc field scores the
    # FINAL model on each day's data (per-day progression is visible in
    # the per-pass losses, which are measured during that day's pass)
    day_stats = []
    for d, r in enumerate(results):
        ev = trainer.evaluate(days[d], batch_size=512)
        day_stats.append({"loss": round(r["loss"], 4),
                          "samples_per_sec": round(r["samples_per_sec"], 1),
                          "final_model_auc": round(ev["auc"], 4)})

    # daily ops: base save, shrink, spill back to budget
    n_base = table.save(os.path.join(base, "ckpt_base"), mode=2)
    erased = table.shrink()
    spilled = table.spill(hot_budget)
    st = table.stats()
    return {
        "task": "widedeep_daily_ssd",
        "population": pop,
        "cold_load_s": round(load_s, 2),
        "days": day_stats,
        "total_train_s": round(train_s, 2),
        "base_save_rows": int(n_base),
        "shrink_erased": int(erased),
        "spilled": int(spilled),
        "final_tiers": {"hot_rows": st["hot_rows"],
                        "cold_rows": st["cold_rows"],
                        "disk_bytes": st["disk_bytes"]},
    }


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — artifact must be one JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"error": f"{type(e).__name__}: {e}"[:300]}))
        sys.exit(0)
