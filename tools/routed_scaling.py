"""Measure the routed-serving scaling claim (VERDICT r2 #2) with
numbers: per-step wall time of sharded cache pull+push under the
key-routed all-to-all vs the dense all_gather fallback, across shard
counts, on the virtual CPU mesh.

The architectural claim: gathered serving does O(batch·K) work per
shard (every shard processes the whole global batch), routed serving
O(batch/K·cap_factor) — so as K grows, gathered per-step time grows
while routed stays ~flat. CPU devices share one host, so absolute
numbers are not TPU numbers, but the per-shard WORK ratio — the thing
the architecture changes — shows directly in the step time.

Writes ROUTED_SCALING.json. Env: RS_BATCH (512), RS_SLOTS (26),
RS_DIM (8), RS_STEPS (20), RS_SHARDS ("2,4,8").
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass  # older jax: module top already set the XLA device-count flag
    import paddle_tpu  # noqa: F401  (installs jax compat shims)
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.ps.embedding_cache import CacheConfig
    from paddle_tpu.ps.sharded_cache import (routed_cache_pull,
                                             routed_cache_push,
                                             routed_dedup,
                                             sharded_cache_pull,
                                             sharded_cache_push)

    B = int(os.environ.get("RS_BATCH", 512))
    S = int(os.environ.get("RS_SLOTS", 26))
    dim = int(os.environ.get("RS_DIM", 8))
    steps = int(os.environ.get("RS_STEPS", 20))
    shard_counts = [int(k) for k in
                    os.environ.get("RS_SHARDS", "2,4,8").split(",")]
    capacity = 1 << 18
    # RS_PUSH_MODE: "sparse" (default — the merge_grad shape, the
    # original artifact) or "dense" (the TPU hot path: per-shard
    # O(C/K) streaming — its cost FALLS as K grows)
    push_mode = os.environ.get("RS_PUSH_MODE", "sparse")
    cfg = CacheConfig(capacity=capacity, embedx_dim=dim,
                      embedx_threshold=0.0, push_mode=push_mode)
    rng = np.random.default_rng(0)
    devices = jax.devices()

    def fresh(cap_local, key):
        r = np.random.default_rng(key)
        return {
            "show": jnp.asarray(r.uniform(0, 5, cap_local).astype(np.float32)),
            "click": jnp.asarray(r.uniform(0, 2, cap_local).astype(np.float32)),
            "embed_w": jnp.asarray(r.normal(size=(cap_local, 1)).astype(np.float32)),
            "embed_state": jnp.asarray(r.uniform(0, 1, (cap_local, 1)).astype(np.float32)),
            "embedx_w": jnp.asarray(r.normal(size=(cap_local, dim)).astype(np.float32)),
            "embedx_state": jnp.asarray(r.uniform(0, 1, (cap_local, 1)).astype(np.float32)),
            "has_embedx": jnp.asarray((r.random(cap_local) < 0.5).astype(np.float32)),
        }

    out = {"batch": B, "slots": S, "dim": dim, "steps": steps,
           "capacity": capacity, "push_mode": push_mode, "modes": {}}
    m_global = B * S  # rows per step, total (each of K devices holds m/K)

    for routing in ("alltoall", "allgather"):
        res = {}
        for K in shard_counts:
            mesh = Mesh(np.array(devices[:K]), ("ps",))
            state = fresh(capacity, 0)
            shard = NamedSharding(mesh, P("ps"))
            ss = {k: jax.device_put(v, shard) for k, v in state.items()}

            if routing == "alltoall":
                def body(st, r, g, s, c):
                    # shared local merge, as the production step does
                    d = routed_dedup(r, capacity)
                    vals, _ = routed_cache_pull(st, r, "ps", dedup=d)
                    new, ov = routed_cache_push(st, r, g, s, c, cfg, "ps",
                                                dedup=d)
                    return new, jnp.sum(vals), ov
            else:
                def body(st, r, g, s, c):
                    vals = sharded_cache_pull(st, r, "ps")
                    new = sharded_cache_push(st, r, g, s, c, cfg, "ps")
                    return new, jnp.sum(vals), jnp.int32(0)

            fn = jax.jit(shard_map(
                body, mesh=mesh,
                in_specs=(P("ps"),) + (P("ps"),) * 4,
                out_specs=(P("ps"), P(), P()), check_vma=False),
                donate_argnums=(0,))

            rows = jnp.asarray(rng.integers(0, capacity, m_global), jnp.int32)
            grads = jnp.asarray(rng.normal(size=(m_global, 1 + dim)).astype(np.float32))
            shows = jnp.ones((m_global,), jnp.float32)
            clicks = jnp.asarray((rng.random(m_global) < 0.4).astype(np.float32))

            ss, val, ov = fn(ss, rows, grads, shows, clicks)  # compile
            jax.block_until_ready(val)
            assert int(ov) == 0
            t0 = time.perf_counter()
            for _ in range(steps):
                ss, val, ov = fn(ss, rows, grads, shows, clicks)
            jax.block_until_ready(val)
            dt = (time.perf_counter() - t0) / steps
            res[str(K)] = round(dt * 1e3, 3)  # ms/step
        out["modes"][routing] = res

    # scaling ratio: gathered cost grows with K, routed stays ~flat —
    # the K=max vs K=min cost ratio per mode
    lo, hi = str(min(shard_counts)), str(max(shard_counts))
    out["growth"] = {
        m: round(out["modes"][m][hi] / out["modes"][m][lo], 2)
        for m in out["modes"]
    }
    path = os.environ.get("RS_OUT") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ROUTED_SCALING.json" if push_mode == "sparse"
        else "ROUTED_SCALING_DENSE.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
