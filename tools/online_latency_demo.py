"""Online-learning latency: record-arrival → servable, two ways
(VERDICT r4 next #7; ISSUE 7 change-feed column).

The reference's banner claim includes REAL-TIME update of huge sparse
models (README.md:31-34): records stream in, trainers push through the
async communicator (the_one_ps a_sync mode), and the serving side keeps
serving fresh parameters. This artifact measures that loop end to end
on the repo's own pieces, as TWO columns of the same JSON:

- **export loop** (the legacy baseline): stream batch arrives
  (MultiSlot text) → CtrStreamTrainer (pull → jitted step → push via
  AsyncCommunicator) → queues drained → serving refresh (fresh
  HbmEmbeddingCache begin_pass over the serving keys — read-only: no
  end_pass flush) → export_ctr_inference writes the new serving
  program+tables. Freshness = a new export on disk.
- **change feed** (paddle_tpu/serving): the same stream trains against
  an HA cluster whose oplog a read-only ServingReplica subscribes to;
  freshness = the round's last push APPLIED on the replica (a marker
  push ordered behind the round in the oplog ring becomes visible
  through the serve read path). No refresh pass, no export, no
  re-serialize — the feed carries each mutation as it happens.

Per round each column records component times and the total
arrival→servable latency; the artifact reports p50/p95 plus a
freshness check (served embed_w for streamed keys really moved each
round). Emits one JSON line (committed as ONLINE.json). Knobs:
ONLINE_POP (export-loop preloaded population, default 2e6),
ONLINE_ROUNDS (20), ONLINE_BATCH (512), ONLINE_SERVE_KEYS (50k),
ONLINE_FEED_POP (change-feed preload, default 200k — per-op feed
latency is table-size independent, unlike the export loop),
ONLINE_FULL_EXPORT=1 adds the full-export-every-round column.
Single-core host: run ALONE.
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

S, D = 8, 4  # sparse/dense slots


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.data.dataset import QueueDataset, SlotDesc
    from paddle_tpu.models.ctr import CtrConfig, DeepFM, export_ctr_inference
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.client import LocalPsClient, PsServerHandle
    from paddle_tpu.ps.communicator import AsyncCommunicator
    from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig
    from paddle_tpu.ps.table import TableConfig

    pop = int(float(os.environ.get("ONLINE_POP", 2_000_000)))
    rounds = int(os.environ.get("ONLINE_ROUNDS", 20))
    batch = int(os.environ.get("ONLINE_BATCH", 512))
    n_serve = int(float(os.environ.get("ONLINE_SERVE_KEYS", 50_000)))
    dim = 8
    vocab = max(pop // S, 1000)   # ids per slot; keys are slot<<32 | id
    base = tempfile.mkdtemp(prefix="online_")

    pt.seed(0)
    rng = np.random.default_rng(0)
    acc = AccessorConfig(embedx_dim=dim, embedx_threshold=0.0,
                         sgd=SGDRuleConfig(initial_range=0.01))
    server = PsServerHandle()
    table = server.create_sparse_table(0, TableConfig(
        shard_num=8, accessor_config=acc))

    # preload the population (the live model the stream updates):
    # slot-tagged keys, the trainers' shared key layout
    t0 = time.perf_counter()
    fd = table.full_dim
    ed = table.accessor.embed_rule.state_dim
    chunk = 1_000_000
    for si in range(S):
        for lo in range(0, vocab, chunk):
            n = min(chunk, vocab - lo)
            ids = np.arange(lo, lo + n, dtype=np.uint64)
            keys = (np.uint64(si) << np.uint64(32)) + ids
            vals = np.zeros((n, fd), np.float32)
            vals[:, 0] = si
            vals[:, 3] = 1.0
            vals[:, 5] = 0.01 * rng.standard_normal(n).astype(np.float32)
            vals[:, 6 + ed] = 1.0  # has_embedx
            table.import_full(keys, vals)
    preload_s = time.perf_counter() - t0

    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=dim,
                    dnn_hidden=(64, 64))
    model = DeepFM(cfg)
    comm = AsyncCommunicator(LocalPsClient(server))
    comm.start()
    trainer = CtrStreamTrainer(model, optimizer.Adam(1e-3), table,
                               sparse_slots=[f"s{i}" for i in range(S)],
                               dense_slots=[f"d{i}" for i in range(D)],
                               label_slot="label",
                               communicator=comm, table_id=0)

    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1)
              for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1)
                for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])

    # serving key set: hot streamed ids + a random sample, slot-tagged
    hot_ids = rng.choice(vocab, 2000, replace=False).astype(np.uint64)
    sample_ids = rng.choice(vocab, max(n_serve // S - len(hot_ids), 1),
                            replace=False).astype(np.uint64)
    serve_ids = np.unique(np.concatenate([hot_ids, sample_ids]))
    serve_keys = np.concatenate([
        (np.uint64(si) << np.uint64(32)) + serve_ids for si in range(S)])
    slot_hi = np.arange(S, dtype=np.uint32)
    cap = 1 << int(np.ceil(np.log2(max(len(serve_keys) * 1.5, 1 << 14))))

    def make_batch_lines():
        lines = []
        for _ in range(batch):
            ids = rng.choice(hot_ids, S)
            dense = rng.normal(size=D)
            label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
            parts = [f"1 {v}" for v in ids]
            parts += [f"1 {v:.4f}" for v in dense]
            parts.append(f"1 {label}")
            lines.append(" ".join(parts))
        return lines

    def percentiles(rows):
        totals = sorted(x["total_s"] for x in rows)
        return {
            "latency_p50_s": totals[len(totals) // 2],
            "latency_p95_s": totals[min(int(len(totals) * 0.95),
                                        len(totals) - 1)],
            "latency_max_s": totals[-1],
            "components_last": rows[-1],
        }

    def export_loop_rounds(export_dir, refresh_after_first):
        rows, fresh_fail, prev_embed = [], 0, None
        for r in range(rounds):
            with open(stream_path, "w") as f:
                f.write("\n".join(make_batch_lines()))
            ds = QueueDataset(slots)
            ds.set_filelist([stream_path])
            t_arrive = time.perf_counter()
            trainer.train_from_dataset(ds, batch_size=batch,
                                       drop_last=False)
            t_trained = time.perf_counter()   # incl. async queue drain

            cache = HbmEmbeddingCache(
                table,
                CacheConfig(capacity=cap, embedx_dim=dim,
                            embedx_threshold=0.0),
                device_map=True)
            cache.begin_pass(serve_keys)      # read-only: no end_pass
            t_refreshed = time.perf_counter()
            # refresh_after_first: round 0 exports the full program,
            # later rounds overwrite only the serving values
            # (refresh_inference_params) — the shapes are identical
            # between refreshes by construction
            export_ctr_inference(export_dir, model, cache, slot_hi, D,
                                 params=trainer.params["params"],
                                 refresh_only=refresh_after_first
                                 and r > 0)
            t_exported = time.perf_counter()

            embed = np.asarray(cache.state["embed_w"])
            if prev_embed is not None and np.allclose(embed, prev_embed):
                fresh_fail += 1  # export did not move despite training
            prev_embed = embed
            rows.append({
                "train_s": round(t_trained - t_arrive, 4),
                "refresh_s": round(t_refreshed - t_trained, 4),
                "export_s": round(t_exported - t_refreshed, 4),
                "total_s": round(t_exported - t_arrive, 4),
            })
        return rows, fresh_fail

    stream_path = os.path.join(base, "stream.txt")
    try:
        rows, fresh_fail = export_loop_rounds(
            os.path.join(base, "serve"), refresh_after_first=True)
        full_export = None
        if os.environ.get("ONLINE_FULL_EXPORT", "0") == "1":
            f_rows, _ = export_loop_rounds(
                os.path.join(base, "serve_full"),
                refresh_after_first=False)
            full_export = percentiles(f_rows)
        feed = _change_feed_rounds(base, rounds, batch, make_batch_lines,
                                   slots, acc, dim, hot_ids)
    finally:
        comm.stop()
        shutil.rmtree(base, ignore_errors=True)

    out = {
        "population": int(vocab) * S,
        "serve_keys": int(len(serve_keys)),
        "batch": batch,
        "rounds": rounds,
        "preload_s": round(preload_s, 2),
        **percentiles(rows),
        "freshness_failures": fresh_fail,
        "ok": fresh_fail == 0 and feed.get("freshness_failures") == 0,
        "host_cores": os.cpu_count(),
        "note": ("arrival→updated-serving-export (baseline column) vs "
                 "arrival→applied-on-replica over the replication "
                 "change feed (change_feed column, paddle_tpu/serving);"
                 " async communicator drained per round; single CPU "
                 "core — chip-hosted serving would overlap "
                 "train/export"),
        "change_feed": feed,
    }
    if full_export is not None:
        out["full_export_every_round_run"] = full_export
    print(json.dumps(out))


def _change_feed_rounds(base, rounds, batch, make_batch_lines, slots,
                        acc, dim, hot_ids):
    """The change-feed column: the same stream shape trains against an
    HA cluster (RpcPsClient + HalfAsyncCommunicator over NativePsServer
    primaries) with a read-only ServingReplica subscribed to the oplog.
    Per round, a marker push issued AFTER the round's training pushes
    is ordered behind them in the (single-shard, FIFO) oplog ring — the
    moment it is visible through the serve read path, every push of the
    round is servable. total_s = arrival → servable, no export."""
    import time

    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.data.dataset import QueueDataset
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps import ha
    from paddle_tpu.ps.communicator import HalfAsyncCommunicator
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer
    from paddle_tpu.ps.table import TableConfig
    from paddle_tpu.serving import ReplicaLookup, ServingReplica

    feed_pop = int(float(os.environ.get("ONLINE_FEED_POP", 200_000)))
    S, D = 8, 4
    rng = np.random.default_rng(7)
    stream_path = os.path.join(base, "feed_stream.txt")

    with ha.HACluster(num_shards=1, replication=1, sync=False) as cluster:
        cli = cluster.client()
        cli.create_sparse_table(0, TableConfig(
            shard_num=8, accessor_config=acc))
        # preload: the live population the stream updates (the feed's
        # per-op latency is table-size independent — recorded, not
        # matched to the export column's ANCHOR-scale table)
        t0 = time.perf_counter()
        width = None
        for lo in range(0, feed_pop, 1 << 15):
            n = min(1 << 15, feed_pop - lo)
            ids = np.arange(lo, lo + n, dtype=np.uint64)
            keys = (np.uint64(lo % S) << np.uint64(32)) + ids
            cli.pull_sparse(0, keys)
            if width is None:
                width = cli._dims(0)[1]
            push = np.zeros((n, width), np.float32)
            push[:, 1] = 1.0
            push[:, 3:] = 0.01 * rng.standard_normal(
                (n, width - 3)).astype(np.float32)
            cli.push_sparse(0, keys, push)
        preload_s = time.perf_counter() - t0

        comm = HalfAsyncCommunicator(cli)
        comm.start()
        pt.seed(0)
        trainer = CtrStreamTrainer(
            DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D,
                             embedx_dim=dim, dnn_hidden=(64, 64))),
            optimizer.Adam(1e-3), None, embedx_dim=dim,
            sparse_slots=[f"s{i}" for i in range(S)],
            dense_slots=[f"d{i}" for i in range(D)],
            label_slot="label", communicator=comm, table_id=0)

        rep = ServingReplica(cluster.store, cluster.job_id, shard=0)
        try:
            serve = rep.client()
            serve.create_sparse_table(0, TableConfig(
                shard_num=8, accessor_config=acc))
            lookup = ReplicaLookup(serve, 0)
            # wait for the subscription snapshot to land
            prim = cluster.primary(0)
            deadline = time.monotonic() + 120
            while cluster.digests(0, 0).get(prim.endpoint) != \
                    serve.digest(0)[0]:
                if time.monotonic() > deadline:
                    raise TimeoutError("replica never caught up")
                time.sleep(0.05)

            marker_key = np.asarray([np.uint64(1) << np.uint64(41)],
                                    np.uint64)
            cli.pull_sparse(0, marker_key)
            # probe slot-0 keys from the streamed hot-id set: a round
            # trains a few hundred of them, so "none of 128 probes
            # moved" means the feed really went stale
            probe_keys = rng.choice(hot_ids, 128,
                                    replace=False).astype(np.uint64)
            rows, fresh_fail, marker, prev = [], 0, 0.0, None
            for r in range(rounds):
                with open(stream_path, "w") as f:
                    f.write("\n".join(make_batch_lines()))
                ds = QueueDataset(slots)
                ds.set_filelist([stream_path])
                t_arrive = time.perf_counter()
                trainer.train_from_dataset(ds, batch_size=batch,
                                           drop_last=False)
                t_trained = time.perf_counter()  # pushes acked on the PS
                marker += 1.0
                mp = np.zeros((1, width), np.float32)
                mp[0, 2] = marker  # click stat: additive, pull col 1
                cli.push_sparse(0, marker_key, mp)
                while lookup.lookup(marker_key)[0, 1] < marker:
                    time.sleep(0.0002)
                t_servable = time.perf_counter()
                served = lookup.lookup(probe_keys)
                if prev is not None and np.allclose(served, prev):
                    fresh_fail += 1  # served state did not move
                prev = served
                rows.append({
                    "train_s": round(t_trained - t_arrive, 4),
                    "feed_s": round(t_servable - t_trained, 4),
                    "total_s": round(t_servable - t_arrive, 4),
                })
            totals = sorted(x["total_s"] for x in rows)
            feeds = sorted(x["feed_s"] for x in rows)
            return {
                "population": feed_pop,
                "preload_s": round(preload_s, 2),
                "latency_p50_s": totals[len(totals) // 2],
                "latency_p95_s": totals[min(int(len(totals) * 0.95),
                                            len(totals) - 1)],
                "push_to_servable_p50_s": feeds[len(feeds) // 2],
                "push_to_servable_p95_s": feeds[
                    min(int(len(feeds) * 0.95), len(feeds) - 1)],
                "components_last": rows[-1],
                "freshness_failures": fresh_fail,
                "replica": rep.status(),
            }
        finally:
            comm.stop()
            rep.close()


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — artifact must be one JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"ok": False,
                          "error": f"{type(e).__name__}: {e}"[:300]}))
        sys.exit(0)
